// Native batch-assembly engine for the input pipeline.
//
// Reference parity (SURVEY.md §2b N7): torch's DataLoader escapes the GIL by
// forking worker *processes* and paying pickle/shared-memory costs per batch.
// This engine keeps one process and escapes the GIL the native way: batch
// assembly (index gather + augmentation + normalization) runs on C++ threads
// over memory-resident datasets, writing directly into caller-owned output
// buffers (which Python hands to jax.device_put — the host->HBM copy then
// overlaps compute via async dispatch).
//
// Three dataset modes:
//   - image mode: uint8 [N,H,W,C] source; per-sample ops are reflect-pad-4 +
//     random crop + horizontal flip (CIFAR recipe) and mean/std normalize to
//     float32 NHWC.
//   - gather mode: raw row gather of fixed-size samples (token sequences,
//     pre-processed float images) with no transform.
//   - jpeg mode (HAVE_LIBJPEG): ImageNet-style file decode. Per sample:
//     read JPEG from disk, RandomResizedCrop (train) or resize-short/center
//     crop (eval) computed in original coords, DCT-space scaled decode
//     (libjpeg scale_num/8 chosen so the crop decodes at >= out_size),
//     bilinear crop+resize to [S,S,3], optional hflip, mean/std normalize to
//     float32. Same pipeline as data/datasets.py:FolderDataset, GIL-free.
//
// Build: make (links -ljpeg when /usr/include/jpeglib.h exists).
// Driven from Python via ctypes (data/native_loader.py). Plain C ABI.

#include <atomic>
#include <chrono>
#include <cmath>
#include <condition_variable>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <deque>
#include <mutex>
#include <string>
#include <thread>
#include <vector>

#ifdef HAVE_LIBJPEG
#include <csetjmp>
#include <jpeglib.h>
#endif

namespace {

struct Job {
  int64_t batch_id;
  std::vector<int64_t> indices;
  void* out;            // caller-owned output buffer
  uint64_t seed;        // per-batch RNG seed (epoch-stable determinism)
};

// splitmix64: tiny deterministic per-sample RNG
static inline uint64_t mix(uint64_t x) {
  x += 0x9e3779b97f4a7c15ULL;
  x = (x ^ (x >> 30)) * 0xbf58476d1ce4e5b9ULL;
  x = (x ^ (x >> 27)) * 0x94d049bb133111ebULL;
  return x ^ (x >> 31);
}

// mix-based uniform double in [0, 1); advances the state.
static inline double next_uniform(uint64_t& state) {
  state = mix(state);
  return static_cast<double>(state >> 11) * (1.0 / 9007199254740992.0);
}

// Per-sample RNG stream, keyed by the DATASET index (not chunk position):
// augmentation is invariant to batch size / worker count / job chunking.
static inline uint64_t sample_rng(uint64_t seed, int64_t dataset_index) {
  return mix(seed ^ (0x517cc1b7ULL * static_cast<uint64_t>(dataset_index + 1)));
}

struct Engine {
  // dataset description
  const uint8_t* u8_data = nullptr;    // image mode
  const uint8_t* raw_data = nullptr;   // gather mode
  int64_t n = 0, height = 0, width = 0, channels = 0;
  int64_t sample_bytes = 0;            // gather mode row size
  int64_t stride_bytes = 0;            // gather row stride (overlapping LM windows)
  float mean[8] = {0}, stdinv[8] = {1, 1, 1, 1, 1, 1, 1, 1};
  bool augment = false;
  int pad = 4;

  // jpeg mode
  bool jpeg_mode = false;
  std::vector<std::string> paths;
  int64_t out_size = 0;
  std::atomic<int64_t> decode_errors{0};

  // worker pool
  std::vector<std::thread> workers;
  std::deque<Job> queue;
  std::mutex mu;
  std::condition_variable cv;
  std::atomic<bool> stop{false};
  std::mutex done_mu;
  std::condition_variable done_cv;
  std::vector<int64_t> done_ids;

  void worker_loop() {
    for (;;) {
      Job job;
      {
        std::unique_lock<std::mutex> lk(mu);
        cv.wait(lk, [&] { return stop.load() || !queue.empty(); });
        if (stop.load() && queue.empty()) return;
        job = std::move(queue.front());
        queue.pop_front();
      }
      run(job);
      {
        std::lock_guard<std::mutex> lk(done_mu);
        done_ids.push_back(job.batch_id);
      }
      done_cv.notify_all();
    }
  }

  void run(const Job& job) {
    if (jpeg_mode) run_jpeg(job);
    else if (u8_data) run_image(job);
    else run_gather(job);
  }

  void run_jpeg(const Job& job) {
#ifdef HAVE_LIBJPEG
    float* out = static_cast<float*>(job.out);
    const int64_t sample = out_size * out_size * 3;
    for (size_t i = 0; i < job.indices.size(); ++i) {
      uint64_t rng = sample_rng(job.seed, job.indices[i]);
      if (!decode_jpeg(paths[job.indices[i]], out + i * sample, rng)) {
        // Failed decode: emit the dataset mean (zeros after normalize) so the
        // batch shape stays valid; count it for the caller to inspect.
        std::memset(out + i * sample, 0, sample * sizeof(float));
        decode_errors.fetch_add(1);
      }
    }
#else
    (void)job;
#endif
  }

#ifdef HAVE_LIBJPEG
  struct JpegErr {
    jpeg_error_mgr mgr;
    std::jmp_buf env;
  };

  static void jpeg_err_exit(j_common_ptr cinfo) {
    std::longjmp(reinterpret_cast<JpegErr*>(cinfo->err)->env, 1);
  }

  // Crop box (x, y, w, h, flip) in ORIGINAL pixel coords; mirrors
  // datasets.py random_resized_crop_params / center_crop_box (the RNG stream
  // differs by design, as in image mode).
  void crop_box(uint64_t& rng, int W, int H, double* bx, double* by,
                double* bw, double* bh, bool* flip) const {
    if (augment) {
      const double area = static_cast<double>(W) * H;
      const double log_lo = std::log(3.0 / 4.0), log_hi = std::log(4.0 / 3.0);
      for (int t = 0; t < 10; ++t) {
        double target = area * (0.08 + 0.92 * next_uniform(rng));
        double aspect = std::exp(log_lo + (log_hi - log_lo) * next_uniform(rng));
        int w = static_cast<int>(std::lround(std::sqrt(target * aspect)));
        int h = static_cast<int>(std::lround(std::sqrt(target / aspect)));
        if (w > 0 && w <= W && h > 0 && h <= H) {
          *bx = static_cast<int>(next_uniform(rng) * (W - w + 1));
          *by = static_cast<int>(next_uniform(rng) * (H - h + 1));
          *bw = w;
          *bh = h;
          *flip = next_uniform(rng) < 0.5;
          return;
        }
      }
      double in_ratio = static_cast<double>(W) / H;
      int w = W, h = H;
      if (in_ratio < 3.0 / 4.0) h = static_cast<int>(std::lround(W / (3.0 / 4.0)));
      else if (in_ratio > 4.0 / 3.0) w = static_cast<int>(std::lround(H * (4.0 / 3.0)));
      *bx = (W - w) / 2;
      *by = (H - h) / 2;
      *bw = w;
      *bh = h;
      *flip = next_uniform(rng) < 0.5;
    } else {
      const int resize_short = static_cast<int>(out_size) * 256 / 224;
      int short_side = W < H ? W : H;
      int side = static_cast<int>(std::lround(
          static_cast<double>(short_side) * out_size / resize_short));
      if (side < 1) side = 1;
      *bx = (W - side) / 2;
      *by = (H - side) / 2;
      *bw = side;
      *bh = side;
      *flip = false;
    }
  }

  bool decode_jpeg(const std::string& path, float* dst, uint64_t rng) const {
    // Read the file into memory (JPEGs are small; avoids stdio src locking).
    FILE* f = std::fopen(path.c_str(), "rb");
    if (!f) return false;
    std::fseek(f, 0, SEEK_END);
    long fsize = std::ftell(f);
    std::fseek(f, 0, SEEK_SET);
    if (fsize <= 0) {
      std::fclose(f);
      return false;
    }
    std::vector<uint8_t> buf(static_cast<size_t>(fsize));
    size_t got = std::fread(buf.data(), 1, buf.size(), f);
    std::fclose(f);
    if (got != buf.size()) return false;

    jpeg_decompress_struct cinfo;
    JpegErr jerr;
    cinfo.err = jpeg_std_error(&jerr.mgr);
    jerr.mgr.error_exit = jpeg_err_exit;
    std::vector<uint8_t> pixels;
    if (setjmp(jerr.env)) {
      jpeg_destroy_decompress(&cinfo);
      return false;
    }
    jpeg_create_decompress(&cinfo);
    jpeg_mem_src(&cinfo, buf.data(), buf.size());
    jpeg_read_header(&cinfo, TRUE);
    const int W0 = cinfo.image_width, H0 = cinfo.image_height;
    if (W0 < 1 || H0 < 1) {
      jpeg_destroy_decompress(&cinfo);
      return false;
    }

    double bx, by, bw, bh;
    bool flip;
    crop_box(rng, W0, H0, &bx, &by, &bw, &bh, &flip);

    // DCT-space downscale m/8: smallest m with crop decoding >= out_size.
    double crop_min = bw < bh ? bw : bh;
    int m = static_cast<int>(std::ceil(8.0 * out_size / crop_min));
    if (m < 1) m = 1;
    if (m > 8) m = 8;
    cinfo.scale_num = m;
    cinfo.scale_denom = 8;
    if (cinfo.jpeg_color_space != JCS_CMYK &&
        cinfo.jpeg_color_space != JCS_YCCK) {
      cinfo.out_color_space = JCS_RGB;  // YCbCr/grayscale -> RGB in-library
    }
    jpeg_start_decompress(&cinfo);
    const int Wd = cinfo.output_width, Hd = cinfo.output_height;
    const int comp = cinfo.output_components;
    const bool cmyk_inverted = cinfo.saw_Adobe_marker != 0;
    pixels.resize(static_cast<size_t>(Wd) * Hd * comp);
    while (cinfo.output_scanline < cinfo.output_height) {
      JSAMPROW row = pixels.data() + static_cast<size_t>(cinfo.output_scanline) * Wd * comp;
      jpeg_read_scanlines(&cinfo, &row, 1);
    }
    jpeg_finish_decompress(&cinfo);
    jpeg_destroy_decompress(&cinfo);

    // Bilinear sample the crop box (scaled to decoded coords) to SxS.
    const double sx = static_cast<double>(Wd) / W0;
    const double sy = static_cast<double>(Hd) / H0;
    const double x0 = bx * sx, y0 = by * sy;
    const double step_x = bw * sx / out_size, step_y = bh * sy / out_size;
    const int S = static_cast<int>(out_size);
    for (int oy = 0; oy < S; ++oy) {
      double fy = y0 + (oy + 0.5) * step_y - 0.5;
      int iy = static_cast<int>(std::floor(fy));
      double wy = fy - iy;
      int y1c = iy < 0 ? 0 : (iy >= Hd ? Hd - 1 : iy);
      int y2c = iy + 1 < 0 ? 0 : (iy + 1 >= Hd ? Hd - 1 : iy + 1);
      for (int ox = 0; ox < S; ++ox) {
        double fx = x0 + (ox + 0.5) * step_x - 0.5;
        int ix = static_cast<int>(std::floor(fx));
        double wx = fx - ix;
        int x1c = ix < 0 ? 0 : (ix >= Wd ? Wd - 1 : ix);
        int x2c = ix + 1 < 0 ? 0 : (ix + 1 >= Wd ? Wd - 1 : ix + 1);
        const uint8_t* p11 = &pixels[(static_cast<size_t>(y1c) * Wd + x1c) * comp];
        const uint8_t* p12 = &pixels[(static_cast<size_t>(y1c) * Wd + x2c) * comp];
        const uint8_t* p21 = &pixels[(static_cast<size_t>(y2c) * Wd + x1c) * comp];
        const uint8_t* p22 = &pixels[(static_cast<size_t>(y2c) * Wd + x2c) * comp];
        float rgb[3];
        for (int c = 0; c < 3; ++c) {
          int cc = comp >= 3 ? c : 0;
          double v = (1 - wy) * ((1 - wx) * p11[cc] + wx * p12[cc]) +
                     wy * ((1 - wx) * p21[cc] + wx * p22[cc]);
          if (comp == 4) {
            // CMYK -> RGB: R = (255-C)*(255-K)/255. Adobe JPEGs store the
            // planes pre-inverted, in which case R = C*K/255 directly.
            double k = (1 - wy) * ((1 - wx) * p11[3] + wx * p12[3]) +
                       wy * ((1 - wx) * p21[3] + wx * p22[3]);
            v = cmyk_inverted ? v * k / 255.0
                              : (255.0 - v) * (255.0 - k) / 255.0;
          }
          rgb[c] = static_cast<float>(v);
        }
        int tx = flip ? S - 1 - ox : ox;
        float* q = dst + (static_cast<size_t>(oy) * S + tx) * 3;
        for (int c = 0; c < 3; ++c) {
          q[c] = (rgb[c] * (1.0f / 255.0f) - mean[c]) * stdinv[c];
        }
      }
    }
    return true;
  }
#endif  // HAVE_LIBJPEG

  void run_gather(const Job& job) {
    uint8_t* out = static_cast<uint8_t*>(job.out);
    for (size_t i = 0; i < job.indices.size(); ++i) {
      std::memcpy(out + i * sample_bytes,
                  raw_data + job.indices[i] * stride_bytes,
                  static_cast<size_t>(sample_bytes));
    }
  }

  void run_image(const Job& job) {
    const int64_t H = height, W = width, C = channels;
    float* out = static_cast<float*>(job.out);
    const int64_t hw = H * W * C;
    for (size_t i = 0; i < job.indices.size(); ++i) {
      const uint8_t* src = u8_data + job.indices[i] * hw;
      float* dst = out + i * hw;
      int dy = 0, dx = 0;
      bool flip = false;
      if (augment) {
        uint64_t r = sample_rng(job.seed, job.indices[i]);
        dy = static_cast<int>(r % (2 * pad + 1)) - pad;
        dx = static_cast<int>((r >> 16) % (2 * pad + 1)) - pad;
        flip = ((r >> 32) & 1) != 0;
      }
      for (int64_t y = 0; y < H; ++y) {
        // reflect-pad source row index
        int64_t sy = y + dy;
        if (sy < 0) sy = -sy;
        if (sy >= H) sy = 2 * H - 2 - sy;
        for (int64_t x = 0; x < W; ++x) {
          int64_t sx = x + dx;
          if (sx < 0) sx = -sx;
          if (sx >= W) sx = 2 * W - 2 - sx;
          if (flip) sx = W - 1 - sx;
          const uint8_t* px = src + (sy * W + sx) * C;
          float* q = dst + (y * W + x) * C;
          for (int64_t c = 0; c < C; ++c) {
            q[c] = (static_cast<float>(px[c]) * (1.0f / 255.0f) - mean[c]) *
                   stdinv[c];
          }
        }
      }
    }
  }
};

}  // namespace

extern "C" {

// Bumped on any C-ABI change; the Python bindings refuse mismatches (the
// library is untracked, so stale binaries can survive checkouts).
int64_t be_abi_version() { return 2; }

void* be_create_image(const uint8_t* data, int64_t n, int64_t h, int64_t w,
                      int64_t c, const float* mean, const float* std_,
                      int augment, int num_threads) {
  Engine* e = new Engine();
  e->u8_data = data;
  e->n = n;
  e->height = h;
  e->width = w;
  e->channels = c;
  for (int64_t i = 0; i < c && i < 8; ++i) {
    e->mean[i] = mean[i];
    e->stdinv[i] = 1.0f / std_[i];
  }
  e->augment = augment != 0;
  if (num_threads < 1) num_threads = 1;
  for (int i = 0; i < num_threads; ++i)
    e->workers.emplace_back([e] { e->worker_loop(); });
  return e;
}

// `stride_bytes` is the byte distance between consecutive samples; 0 means
// densely packed (= sample_bytes). A smaller stride than sample size gives
// the overlapping windows LM datasets use (sample i = tokens[i*L : i*L+L+1]).
void* be_create_gather(const uint8_t* data, int64_t n, int64_t sample_bytes,
                       int num_threads, int64_t stride_bytes) {
  Engine* e = new Engine();
  e->raw_data = data;
  e->n = n;
  e->sample_bytes = sample_bytes;
  e->stride_bytes = stride_bytes > 0 ? stride_bytes : sample_bytes;
  if (num_threads < 1) num_threads = 1;
  for (int i = 0; i < num_threads; ++i)
    e->workers.emplace_back([e] { e->worker_loop(); });
  return e;
}

// JPEG-file mode: `paths_blob` is n concatenated utf-8 paths delimited by
// `offsets` (n+1 entries). Returns nullptr when built without libjpeg.
void* be_create_jpeg(const char* paths_blob, const int64_t* offsets, int64_t n,
                     int64_t out_size, const float* mean, const float* std_,
                     int augment, int num_threads) {
#ifdef HAVE_LIBJPEG
  Engine* e = new Engine();
  e->jpeg_mode = true;
  e->n = n;
  e->out_size = out_size;
  e->paths.reserve(n);
  for (int64_t i = 0; i < n; ++i) {
    e->paths.emplace_back(paths_blob + offsets[i],
                          static_cast<size_t>(offsets[i + 1] - offsets[i]));
  }
  for (int i = 0; i < 3; ++i) {
    e->mean[i] = mean[i];
    e->stdinv[i] = 1.0f / std_[i];
  }
  e->augment = augment != 0;
  if (num_threads < 1) num_threads = 1;
  for (int i = 0; i < num_threads; ++i)
    e->workers.emplace_back([e] { e->worker_loop(); });
  return e;
#else
  (void)paths_blob; (void)offsets; (void)n; (void)out_size;
  (void)mean; (void)std_; (void)augment; (void)num_threads;
  return nullptr;
#endif
}

// Decode failures since creation (jpeg mode); failed samples are zero-filled.
int64_t be_decode_errors(void* handle) {
  return static_cast<Engine*>(handle)->decode_errors.load();
}

// Submit one batch: gather `count` samples by `indices` into `out`.
void be_submit(void* handle, int64_t batch_id, const int64_t* indices,
               int64_t count, void* out, uint64_t seed) {
  Engine* e = static_cast<Engine*>(handle);
  Job job;
  job.batch_id = batch_id;
  job.indices.assign(indices, indices + count);
  job.out = out;
  job.seed = seed;
  {
    std::lock_guard<std::mutex> lk(e->mu);
    e->queue.push_back(std::move(job));
  }
  e->cv.notify_one();
}

// Block until `batch_id` has been produced, then retire the id (so ids may
// be reused and done_ids stays bounded). Returns 0 on success, 1 on timeout.
int be_wait(void* handle, int64_t batch_id, int64_t timeout_ms) {
  Engine* e = static_cast<Engine*>(handle);
  auto find = [&] {
    for (size_t i = 0; i < e->done_ids.size(); ++i)
      if (e->done_ids[i] == batch_id) return static_cast<int64_t>(i);
    return static_cast<int64_t>(-1);
  };
  std::unique_lock<std::mutex> lk(e->done_mu);
  bool ok = e->done_cv.wait_for(lk, std::chrono::milliseconds(timeout_ms),
                                [&] { return find() >= 0; });
  if (!ok) return 1;
  e->done_ids.erase(e->done_ids.begin() + find());
  return 0;
}

void be_destroy(void* handle) {
  Engine* e = static_cast<Engine*>(handle);
  e->stop.store(true);
  e->cv.notify_all();
  for (auto& t : e->workers) t.join();
  delete e;
}

}  // extern "C"
