// Native batch-assembly engine for the input pipeline.
//
// Reference parity (SURVEY.md §2b N7): torch's DataLoader escapes the GIL by
// forking worker *processes* and paying pickle/shared-memory costs per batch.
// This engine keeps one process and escapes the GIL the native way: batch
// assembly (index gather + augmentation + normalization) runs on C++ threads
// over memory-resident datasets, writing directly into caller-owned output
// buffers (which Python hands to jax.device_put — the host->HBM copy then
// overlaps compute via async dispatch).
//
// Two dataset modes:
//   - image mode: uint8 [N,H,W,C] source; per-sample ops are reflect-pad-4 +
//     random crop + horizontal flip (CIFAR recipe) and mean/std normalize to
//     float32 NHWC.
//   - gather mode: raw row gather of fixed-size samples (token sequences,
//     pre-processed float images) with no transform.
//
// Build: g++ -O3 -march=native -shared -fPIC -o libbatch_engine.so batch_engine.cc -lpthread
// Driven from Python via ctypes (data/native_loader.py). Plain C ABI.

#include <atomic>
#include <chrono>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <deque>
#include <mutex>
#include <thread>
#include <vector>

namespace {

struct Job {
  int64_t batch_id;
  std::vector<int64_t> indices;
  void* out;            // caller-owned output buffer
  uint64_t seed;        // per-batch RNG seed (epoch-stable determinism)
};

// splitmix64: tiny deterministic per-sample RNG
static inline uint64_t mix(uint64_t x) {
  x += 0x9e3779b97f4a7c15ULL;
  x = (x ^ (x >> 30)) * 0xbf58476d1ce4e5b9ULL;
  x = (x ^ (x >> 27)) * 0x94d049bb133111ebULL;
  return x ^ (x >> 31);
}

struct Engine {
  // dataset description
  const uint8_t* u8_data = nullptr;    // image mode
  const uint8_t* raw_data = nullptr;   // gather mode
  int64_t n = 0, height = 0, width = 0, channels = 0;
  int64_t sample_bytes = 0;            // gather mode row size
  float mean[8] = {0}, stdinv[8] = {1, 1, 1, 1, 1, 1, 1, 1};
  bool augment = false;
  int pad = 4;

  // worker pool
  std::vector<std::thread> workers;
  std::deque<Job> queue;
  std::mutex mu;
  std::condition_variable cv;
  std::atomic<bool> stop{false};
  std::mutex done_mu;
  std::condition_variable done_cv;
  std::vector<int64_t> done_ids;

  void worker_loop() {
    for (;;) {
      Job job;
      {
        std::unique_lock<std::mutex> lk(mu);
        cv.wait(lk, [&] { return stop.load() || !queue.empty(); });
        if (stop.load() && queue.empty()) return;
        job = std::move(queue.front());
        queue.pop_front();
      }
      run(job);
      {
        std::lock_guard<std::mutex> lk(done_mu);
        done_ids.push_back(job.batch_id);
      }
      done_cv.notify_all();
    }
  }

  void run(const Job& job) {
    if (u8_data) run_image(job);
    else run_gather(job);
  }

  void run_gather(const Job& job) {
    uint8_t* out = static_cast<uint8_t*>(job.out);
    for (size_t i = 0; i < job.indices.size(); ++i) {
      std::memcpy(out + i * sample_bytes,
                  raw_data + job.indices[i] * sample_bytes,
                  static_cast<size_t>(sample_bytes));
    }
  }

  void run_image(const Job& job) {
    const int64_t H = height, W = width, C = channels;
    float* out = static_cast<float*>(job.out);
    const int64_t hw = H * W * C;
    for (size_t i = 0; i < job.indices.size(); ++i) {
      const uint8_t* src = u8_data + job.indices[i] * hw;
      float* dst = out + i * hw;
      int dy = 0, dx = 0;
      bool flip = false;
      if (augment) {
        uint64_t r = mix(job.seed ^ (0x517cc1b7ULL * (i + 1)));
        dy = static_cast<int>(r % (2 * pad + 1)) - pad;
        dx = static_cast<int>((r >> 16) % (2 * pad + 1)) - pad;
        flip = ((r >> 32) & 1) != 0;
      }
      for (int64_t y = 0; y < H; ++y) {
        // reflect-pad source row index
        int64_t sy = y + dy;
        if (sy < 0) sy = -sy;
        if (sy >= H) sy = 2 * H - 2 - sy;
        for (int64_t x = 0; x < W; ++x) {
          int64_t sx = x + dx;
          if (sx < 0) sx = -sx;
          if (sx >= W) sx = 2 * W - 2 - sx;
          if (flip) sx = W - 1 - sx;
          const uint8_t* px = src + (sy * W + sx) * C;
          float* q = dst + (y * W + x) * C;
          for (int64_t c = 0; c < C; ++c) {
            q[c] = (static_cast<float>(px[c]) * (1.0f / 255.0f) - mean[c]) *
                   stdinv[c];
          }
        }
      }
    }
  }
};

}  // namespace

extern "C" {

void* be_create_image(const uint8_t* data, int64_t n, int64_t h, int64_t w,
                      int64_t c, const float* mean, const float* std_,
                      int augment, int num_threads) {
  Engine* e = new Engine();
  e->u8_data = data;
  e->n = n;
  e->height = h;
  e->width = w;
  e->channels = c;
  for (int64_t i = 0; i < c && i < 8; ++i) {
    e->mean[i] = mean[i];
    e->stdinv[i] = 1.0f / std_[i];
  }
  e->augment = augment != 0;
  if (num_threads < 1) num_threads = 1;
  for (int i = 0; i < num_threads; ++i)
    e->workers.emplace_back([e] { e->worker_loop(); });
  return e;
}

void* be_create_gather(const uint8_t* data, int64_t n, int64_t sample_bytes,
                       int num_threads) {
  Engine* e = new Engine();
  e->raw_data = data;
  e->n = n;
  e->sample_bytes = sample_bytes;
  if (num_threads < 1) num_threads = 1;
  for (int i = 0; i < num_threads; ++i)
    e->workers.emplace_back([e] { e->worker_loop(); });
  return e;
}

// Submit one batch: gather `count` samples by `indices` into `out`.
void be_submit(void* handle, int64_t batch_id, const int64_t* indices,
               int64_t count, void* out, uint64_t seed) {
  Engine* e = static_cast<Engine*>(handle);
  Job job;
  job.batch_id = batch_id;
  job.indices.assign(indices, indices + count);
  job.out = out;
  job.seed = seed;
  {
    std::lock_guard<std::mutex> lk(e->mu);
    e->queue.push_back(std::move(job));
  }
  e->cv.notify_one();
}

// Block until `batch_id` has been produced, then retire the id (so ids may
// be reused and done_ids stays bounded). Returns 0 on success, 1 on timeout.
int be_wait(void* handle, int64_t batch_id, int64_t timeout_ms) {
  Engine* e = static_cast<Engine*>(handle);
  auto find = [&] {
    for (size_t i = 0; i < e->done_ids.size(); ++i)
      if (e->done_ids[i] == batch_id) return static_cast<int64_t>(i);
    return static_cast<int64_t>(-1);
  };
  std::unique_lock<std::mutex> lk(e->done_mu);
  bool ok = e->done_cv.wait_for(lk, std::chrono::milliseconds(timeout_ms),
                                [&] { return find() >= 0; });
  if (!ok) return 1;
  e->done_ids.erase(e->done_ids.begin() + find());
  return 0;
}

void be_destroy(void* handle) {
  Engine* e = static_cast<Engine*>(handle);
  e->stop.store(true);
  e->cv.notify_all();
  for (auto& t : e->workers) t.join();
  delete e;
}

}  // extern "C"
