"""TPU-native distributed training framework.

A brand-new JAX/XLA/pjit/Pallas framework providing the capabilities of the
reference ``ownzonefeng/pytorch-distributed-training-example`` (see SURVEY.md;
the reference mount was empty, so parity targets come from BASELINE.json's
``north_star`` contract):

- ``main.py --distributed`` entrypoint            -> unchanged CLI surface
- ``torch.distributed.init_process_group('nccl')``-> :func:`core.distributed.init_process_group`
  (wraps ``jax.distributed.initialize`` over ICI/DCN)
- ``DistributedDataParallel`` + bucketed NCCL all-reduce
                                                  -> gradient ``psum`` fused inside ONE
                                                     compiled XLA step over a named mesh
- ``DistributedSampler``/``DataLoader``           -> :mod:`data` (per-host sharding + HBM prefetch)
- ``torch.cuda.amp`` + ``GradScaler``             -> :mod:`core.precision` (native bf16 policy;
                                                     dynamic scaler kept for fp16 parity)

Parallelism is data, not code: a strategy is a table of sharding rules over the
named mesh axes ``('data','fsdp','stage','expert','context','model')``.
"""

__version__ = "0.1.0"

from pytorch_distributed_training_example_tpu.core import mesh, precision  # noqa: F401
