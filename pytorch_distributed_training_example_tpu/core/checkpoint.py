"""Sharded checkpoint save/restore with commit markers — the ``torch.save`` /
``--resume`` equivalent (SURVEY.md §3.4, §5).

Reference parity: rank-0 ``torch.save({'model', 'opt', 'epoch'})`` + map_location
restore. TPU-native design (Orbax-style, self-contained implementation):

- every *host* writes only the param shards it addresses (no gather through
  one host — required for FSDP where no host could hold the full model);
- a JSON manifest records each leaf's global shape/dtype and which file holds
  which index-region, so restore works under a *different* sharding/topology
  than save (regions are assembled, then re-placed by ``device_put`` with the
  target NamedSharding);
- a ``COMMIT`` marker is written last (after every host's files are on disk),
  so a crashed half-written checkpoint is never eligible for ``--resume auto``
  (partial-write recovery, SURVEY.md §7 hard part (b));
- file writes run on a background thread (device->host copy is taken
  synchronously first, since the train loop donates state buffers). The
  cross-host commit rendezvous is FILESYSTEM-based (process 0 waits for every
  host's per-host file list to appear) rather than a device collective, so
  multi-host saves stay async too: a device-collective barrier on a
  background thread could interleave with train-step collectives and
  deadlock, and the shared-filesystem assumption is already baked into
  restore's manifest union;
- restore assembles each leaf PER ADDRESSABLE SHARD of the target sharding
  (index-intersecting saved regions with the shard's index) and builds the
  array via ``jax.make_array_from_single_device_arrays`` — peak host memory
  is the host's shard bytes, not the full model (required for FSDP restore
  of models no single host can hold).
"""

from __future__ import annotations

import json
import logging
import os
import re
import shutil
import threading
import time
import zlib
from typing import Any

import jax
import numpy as np

from pytorch_distributed_training_example_tpu.core import distributed
from pytorch_distributed_training_example_tpu.parallel.sharding import param_path
from pytorch_distributed_training_example_tpu.utils import resilience

log = logging.getLogger("pdtx")

COMMIT_FILE = "COMMIT"
MANIFEST_FILE = "manifest.json"
SAVING_SUFFIX = ".saving"  # in-progress attempt dirs (never resume-eligible)
OLD_SUFFIX = ".old"  # prior committed dir set aside during a re-save swap
_STEP_RE = re.compile(r"^step_(\d+)$")


class CheckpointWriteError(RuntimeError):
    """A checkpoint save failed (surfaced by :meth:`Checkpointer.wait`)."""


class CheckpointCorruptError(RuntimeError):
    """A committed checkpoint failed integrity verification on restore."""


def _read_json(path: str):
    """JSON file read, designed to be invoked via ``retriable_io``."""
    with open(path) as fh:
        return json.load(fh)


def _file_crc32(path: str) -> int:
    """Streaming CRC32 of a file's bytes (1 MB chunks).

    File-level (includes the npy header), streamed so integrity verification
    never materializes a full leaf — restore's peak-host-memory contract is
    one SHARD (see ``_assemble_sharded``), and checksumming must not be the
    thing that breaks it.
    """
    crc = 0
    with open(path, "rb") as fh:
        while True:
            chunk = fh.read(1 << 20)
            if not chunk:
                return crc & 0xFFFFFFFF
            crc = zlib.crc32(chunk, crc)


def _is_array_leaf(x) -> bool:
    return isinstance(x, (jax.Array, np.ndarray))


def _flatten(state) -> dict[str, Any]:
    flat = {}

    def visit(path, x):
        if _is_array_leaf(x):
            flat[param_path(path)] = x
        return x

    jax.tree_util.tree_map_with_path(visit, state)
    return flat


class Checkpointer:
    def __init__(self, directory: str, keep: int = 3):
        self.directory = directory
        self.keep = keep
        self._thread: threading.Thread | None = None
        self._error: tuple[int, BaseException] | None = None
        #: Step actually restored by the last ``restore()`` call — the caller
        #: asked for "latest usable", this is which one survived verification.
        self.last_restored_step: int | None = None
        if distributed.is_main_process():
            resilience.retriable_io(os.makedirs, directory, exist_ok=True,
                                    _what="ckpt_mkdir")
            self._recover_interrupted_replace()
        if jax.process_count() > 1:
            # Non-main hosts must not race latest_checkpoint() against the
            # heal above: a step_X.old-only directory would look empty to
            # them and desynchronize --resume across hosts. __init__ runs on
            # the main thread (same thread as train-step collectives).
            distributed.barrier("ckpt_init_recover")
            self._validate_shared_filesystem()

    def _validate_shared_filesystem(self):
        """Fail fast if the checkpoint directory is not shared across hosts.

        The multi-host commit rendezvous is filesystem-based (module
        docstring): process 0 polls for every host's ``files.p*.json``
        sentinel before writing COMMIT. On disjoint local disks that
        protocol can never succeed — every save would time out after 600 s
        and no checkpoint would ever commit, silently. Probe at init
        instead: process 0 writes a nonce file, and every host must observe
        it (with a short poll to ride out NFS attribute-cache latency).
        Runs on the main thread; uses host-level collectives only.
        """
        from jax.experimental import multihost_utils

        probe = os.path.join(self.directory, ".fs_probe")
        nonce = np.int32(np.random.randint(1 << 30)
                         if distributed.is_main_process() else 0)
        nonce = int(multihost_utils.broadcast_one_to_all(nonce))
        if distributed.is_main_process():
            with open(probe + ".tmp", "w") as fh:
                fh.write(str(nonce))
            os.replace(probe + ".tmp", probe)
        distributed.barrier("ckpt_fs_probe_written")
        deadline = time.monotonic() + 15.0
        seen = False
        while time.monotonic() < deadline:
            try:
                with open(probe) as fh:
                    seen = fh.read().strip() == str(nonce)
            except OSError:
                seen = False
            if seen:
                break
            time.sleep(0.25)
        all_seen = multihost_utils.process_allgather(
            np.asarray(seen, np.bool_))
        if distributed.is_main_process():
            try:
                os.remove(probe)
            except OSError:
                pass
        if not np.all(all_seen):
            missing = [i for i, ok in enumerate(np.atleast_1d(all_seen))
                       if not ok]
            raise RuntimeError(
                f"checkpoint directory {self.directory!r} is not visible "
                f"from host process(es) {missing}: the multi-host commit "
                f"rendezvous requires a SHARED filesystem (NFS/GCS fuse). "
                f"Point --checkpoint-dir at storage all hosts can read, or "
                f"run single-host.")

    def _recover_interrupted_replace(self):
        """Heal a crash inside save()'s re-save swap: a ``step_X.old`` dir
        without its ``step_X`` means the crash hit between the two renames —
        the set-aside copy is the committed checkpoint; restore its name."""
        for name in os.listdir(self.directory):
            if not name.endswith(OLD_SUFFIX):
                continue
            old = os.path.join(self.directory, name)
            base = os.path.join(self.directory, name[: -len(OLD_SUFFIX)])
            if os.path.isdir(base):
                shutil.rmtree(old, ignore_errors=True)  # swap had completed
            else:
                os.rename(old, base)

    # -- save ---------------------------------------------------------------

    def save(self, state, step: int, extra: dict | None = None, block: bool = False):
        """Snapshot device->host now; write files in the background."""
        self.wait()  # at most one in-flight save
        flat = _flatten(state)
        # Snapshot synchronously: the caller will donate these buffers to the
        # next step. Each host only materializes its addressable shards.
        # np.array (not np.asarray): asarray of a shard is a zero-copy
        # memoryview of the device buffer, and once the caller donates the
        # state XLA recycles that memory for activations — the background
        # thread would then serialize garbage (with a valid CRC, since the
        # checksum is computed over whatever bytes hit disk).
        shards: dict[str, list[tuple[list[list[int]], np.ndarray]]] = {}
        manifest_leaves: dict[str, Any] = {}
        for path, arr in flat.items():
            if isinstance(arr, np.ndarray):
                regions = [([[0, s] for s in arr.shape], np.array(arr))]
            else:
                regions = []
                for sh in arr.addressable_shards:
                    if sh.replica_id != 0:
                        continue  # one copy per replicated region
                    idx = [
                        [s.start or 0, s.stop if s.stop is not None else dim]
                        for s, dim in zip(sh.index, arr.shape)
                    ] or [[0, 0]]
                    regions.append((idx, np.array(sh.data)))
            shards[path] = regions
            manifest_leaves[path] = {
                "shape": list(np.shape(arr)),
                "dtype": str(regions[0][1].dtype) if regions else str(arr.dtype),
            }

        # Source-topology record (elastic resume): which geometry wrote this
        # checkpoint. Restore warns loudly on mismatch instead of silently
        # reassembling across topologies; the elastic trainer reads it via
        # peek_manifest() to plan the batch rescale before building anything.
        geometry: dict[str, Any] = {
            "process_count": jax.process_count(),
            "device_count": jax.device_count(),
        }
        for arr in flat.values():
            mesh = getattr(getattr(arr, "sharding", None), "mesh", None)
            if mesh is not None and hasattr(mesh, "shape"):
                geometry["mesh_shape"] = {
                    str(k): int(v) for k, v in dict(mesh.shape).items()}
                break

        step_dir = os.path.join(self.directory, f"step_{step:08d}")
        attempt_dir = step_dir + SAVING_SUFFIX
        multihost = jax.process_count() > 1
        nproc = jax.process_count()

        # All hosts write into an ATTEMPT dir that is renamed over the final
        # dir only when complete — so a committed checkpoint for this step
        # (e.g. from a run being re-done after --resume to an older step) is
        # never destroyed before its replacement is fully on disk. A crashed
        # earlier attempt may have left stale files.p*.json sentinels in the
        # attempt dir that would satisfy process 0's commit wait early; clear
        # it behind a MAIN-THREAD barrier (same thread as train-step
        # collectives, so no cross-thread collective interleaving).
        if distributed.is_main_process() and os.path.isdir(attempt_dir):
            shutil.rmtree(attempt_dir, ignore_errors=True)
        if multihost:
            distributed.barrier(f"ckpt_clear_{step}")

        def write():
            arrays_dir = os.path.join(attempt_dir, "arrays")
            resilience.retriable_io(os.makedirs, arrays_dir, exist_ok=True,
                                    _what="ckpt_write")
            written: dict[str, list] = {}
            for path, regions in shards.items():
                safe = path.replace("/", ".")
                for i, (idx, data) in enumerate(regions):
                    fname = f"{safe}.p{jax.process_index()}.{i}.npy"
                    fpath = os.path.join(arrays_dir, fname)
                    resilience.retriable_io(np.save, fpath, data,
                                            _what="ckpt_write")
                    # Checksum recorded in the manifest, verified by restore.
                    # Computed right after the write (page-cache hot), over
                    # the file bytes — so restore verifies exactly what the
                    # filesystem durably holds, npy header included.
                    written.setdefault(path, []).append({
                        "file": fname, "index": idx,
                        "crc32": _file_crc32(fpath)})
            if multihost:
                # Per-host file list doubles as the "this host is done"
                # sentinel: written ATOMICALLY (tmp+rename) after the arrays
                # so process 0 commits only once every host's data is on the
                # shared filesystem. No device collective -> async-safe.
                flist = os.path.join(attempt_dir,
                                     f"files.p{jax.process_index()}.json")

                def write_flist():
                    with open(flist + ".tmp", "w") as fh:
                        json.dump({p: f for p, f in written.items()}, fh)
                    os.replace(flist + ".tmp", flist)

                resilience.retriable_io(write_flist, _what="ckpt_write")
            if distributed.is_main_process():
                if multihost and not self._await_hosts(attempt_dir, nproc):
                    # A host died or stalled mid-save: leave uncommitted,
                    # but NEVER silently — the operator must know --resume
                    # will fall back to an older step.
                    log.error(
                        "checkpoint step %d NOT committed: not every host "
                        "finished writing within the timeout (attempt left "
                        "at %s)", step, attempt_dir)
                    return
                manifest = {
                    "step": step,
                    "extra": extra or {},
                    "geometry": geometry,
                    "leaves": {
                        p: {**manifest_leaves[p], "files": written.get(p, [])}
                        for p in shards
                    },
                }
                # NOTE: multi-host file listings are per-host in files.p*.json;
                # restore unions them with the manifest's own list.
                def write_json(path, obj):
                    with open(path, "w") as fh:
                        json.dump(obj, fh)

                resilience.retriable_io(
                    write_json, os.path.join(attempt_dir, MANIFEST_FILE),
                    manifest, _what="ckpt_write")
                # COMMIT is written INSIDE the attempt dir (whose .saving
                # suffix keeps it resume-ineligible), so the rename below
                # publishes a fully-committed dir in one atomic syscall.
                # An existing committed dir for this step is renamed ASIDE,
                # never rmtree'd before its replacement exists: a crash at
                # any point leaves either the old or the new copy intact
                # (the one-syscall gap between the two renames is healed by
                # _recover_interrupted_replace at next startup).
                def write_commit():
                    with open(os.path.join(attempt_dir, COMMIT_FILE),
                              "w") as fh:
                        fh.write(str(step))

                resilience.retriable_io(write_commit, _what="ckpt_commit")
                old_dir = step_dir + OLD_SUFFIX
                if os.path.isdir(step_dir):
                    if os.path.isdir(old_dir):
                        shutil.rmtree(old_dir, ignore_errors=True)
                    os.rename(step_dir, old_dir)
                os.rename(attempt_dir, step_dir)
                shutil.rmtree(old_dir, ignore_errors=True)
                self._prune()

        # attempt dir + rename + COMMIT marker is the atomicity boundary
        if block:
            try:
                write()
            except Exception as e:
                raise CheckpointWriteError(
                    f"checkpoint save for step {step} failed: "
                    f"{type(e).__name__}: {e}") from e
        else:
            def guarded():
                try:
                    write()
                except BaseException as e:  # noqa: BLE001 — surfaced by wait()
                    # A failed background save must NOT die silently with the
                    # daemon thread: the trainer would believe the step is
                    # durable. Stash it; wait() re-raises on the main thread.
                    self._error = (step, e)
                    log.error("background checkpoint write for step %d "
                              "failed: %s: %s", step, type(e).__name__, e)

            self._thread = threading.Thread(target=guarded, daemon=True)
            self._thread.start()

    def _await_hosts(self, step_dir: str, nproc: int,
                     timeout_s: float = 600.0) -> bool:
        """Wait for every host's files.p*.json sentinel; False on timeout."""
        import time

        deadline = time.monotonic() + timeout_s
        want = {f"files.p{i}.json" for i in range(nproc)}
        while time.monotonic() < deadline:
            if want <= set(os.listdir(step_dir)):
                return True
            time.sleep(0.05)
        return False

    def wait(self):
        """Join the in-flight background save, RE-RAISING its failure.

        Before this, a failed background write vanished with its daemon
        thread and the trainer believed the step was durable. Raises
        :class:`CheckpointWriteError` (chained to the original) so callers
        can log-and-retry; the stashed error is cleared once raised.
        """
        if self._thread is not None:
            self._thread.join()
            self._thread = None
        if self._error is not None:
            (step, err), self._error = self._error, None
            raise CheckpointWriteError(
                f"background checkpoint write for step {step} failed: "
                f"{type(err).__name__}: {err}") from err

    def quarantine(self, step: int, reason: str = "poisoned") -> None:
        """Set a committed checkpoint aside, permanently resume-ineligible.

        Renamed (not deleted) so the bad state stays inspectable; the suffix
        makes the name fail ``_STEP_RE``, so every discovery path ignores it.
        Used by anomaly rollback when a checkpoint saved after a poisoned
        batch itself contains non-finite params — left in place it would be
        exactly what a later ``--resume auto`` restores.
        """
        src = os.path.join(self.directory, f"step_{step:08d}")
        dst = f"{src}.{reason}"
        if os.path.isdir(src):
            shutil.rmtree(dst, ignore_errors=True)
            os.rename(src, dst)
            log.warning("checkpoint step %d quarantined -> %s", step, dst)

    def _prune(self):
        steps = sorted(all_checkpoints(self.directory))
        for s in steps[: -self.keep] if self.keep else []:
            shutil.rmtree(os.path.join(self.directory, f"step_{s:08d}"),
                          ignore_errors=True)
        # Orphaned attempts from crashed runs. No live attempt can exist
        # here: _prune runs at the end of process 0's write thread, and every
        # host's next save() is gated behind a main-thread barrier that
        # process 0 only reaches after joining this thread.
        for name in resilience.retriable_io(os.listdir, self.directory,
                                            _what="ckpt_prune"):
            if name.endswith(SAVING_SUFFIX):
                shutil.rmtree(os.path.join(self.directory, name),
                              ignore_errors=True)

    # -- restore ------------------------------------------------------------

    def restore(self, state_template, step: int | None = None,
                allow_partial: bool = False):
        """Restore into the shardings of ``state_template`` (a real or abstract
        TrainState whose leaves carry ``.sharding``). Returns (state, extra).

        By default every model parameter must be present in the checkpoint
        with a matching shape — resuming is all-or-nothing, because training
        or evaluating a half-initialized model is silent garbage.
        ``allow_partial=True`` downgrades mismatches to a warning (surgical
        transfer-learning loads).

        With ``step=None`` ("latest usable"): committed steps are tried
        newest-first, and one whose manifest is missing/unparseable or whose
        files fail CRC verification is SKIPPED with a loud warning — a
        corrupted latest checkpoint costs the steps since the previous save,
        not the whole run. An explicit ``step`` is restored exactly or raises.
        ``self.last_restored_step`` records which step actually loaded.
        """
        if step is not None:
            out = self._restore_step(state_template, step, allow_partial)
            self.last_restored_step = step
            return out
        candidates = sorted(all_checkpoints(self.directory), reverse=True)
        if not candidates:
            raise FileNotFoundError(
                f"no committed checkpoint in {self.directory}")
        last_err: BaseException | None = None
        for cand in candidates:
            try:
                out = self._restore_step(state_template, cand, allow_partial)
            except (CheckpointCorruptError, OSError,
                    json.JSONDecodeError, KeyError) as e:
                log.error(
                    "checkpoint step %d is unusable (%s: %s) — falling back "
                    "to the previous committed step", cand,
                    type(e).__name__, e)
                last_err = e
                continue
            if cand != candidates[0]:
                log.warning(
                    "restored step %d instead of latest committed step %d "
                    "(newer checkpoint(s) failed integrity checks)",
                    cand, candidates[0])
            self.last_restored_step = cand
            return out
        raise CheckpointCorruptError(
            f"every committed checkpoint in {self.directory} "
            f"({candidates}) failed to restore") from last_err

    def restore_params(self, params_template, step: int | None = None):
        """Params-only restore for inference/serving. Returns (params, extra).

        ``params_template`` is the model's params pytree (real arrays or
        ``jax.ShapeDtypeStruct``-like leaves; leaves with ``.sharding``
        re-shard exactly as in ``restore``). Only the checkpoint files
        backing model parameters are CRC-verified and read — optimizer
        state, which dominates checkpoint bytes, is never touched, so a
        serving host pays a fraction of the resume-time I/O. The match is
        all-or-nothing like a full restore: serving a half-initialized
        model is the same silent garbage as training one.
        """
        # The manifest namespaces model parameters under "params/..."
        # (TrainState field name); wrapping reproduces that namespace so
        # the integrity pre-pass and assembly skip every other leaf.
        wrapped, extra = self.restore({"params": params_template}, step=step)
        return wrapped["params"], extra

    def _restore_step(self, state_template, step: int,
                      allow_partial: bool = False):
        step_dir = os.path.join(self.directory, f"step_{step:08d}")

        def read_manifest():
            with open(os.path.join(step_dir, MANIFEST_FILE)) as fh:
                return json.load(fh)

        manifest = resilience.retriable_io(read_manifest, _what="ckpt_read")
        _warn_geometry_mismatch(step, manifest)
        # Union per-host file lists when present (multi-host shared fs).
        leaves = manifest["leaves"]
        for fn in resilience.retriable_io(os.listdir, step_dir,
                                          _what="ckpt_read"):
            if fn.startswith("files.p") and fn.endswith(".json"):
                extra_files = resilience.retriable_io(
                    _read_json, os.path.join(step_dir, fn), _what="ckpt_read")
                for p, files in extra_files.items():
                    known = {e["file"] for e in leaves[p]["files"]}
                    leaves[p]["files"] += [e for e in files if e["file"] not in known]

        arrays_dir = os.path.join(step_dir, "arrays")
        flat_template = _flatten(state_template)

        # Integrity pre-pass: verify the recorded CRC32 of every file this
        # restore will read, BEFORE any assembly — a bitflip or truncation
        # must surface as CheckpointCorruptError (fallback-eligible), never
        # as silent garbage weights or an np.load crash mid-assembly.
        # Entries without a checksum (pre-integrity checkpoints) are skipped.
        checked: set[str] = set()
        for path, meta in leaves.items():
            if path not in flat_template:
                continue
            for entry in meta["files"]:
                fname = entry["file"]
                if "crc32" not in entry or fname in checked:
                    continue
                checked.add(fname)
                fpath = os.path.join(arrays_dir, fname)
                got = resilience.retriable_io(_file_crc32, fpath,
                                              _what="ckpt_read")
                if got != entry["crc32"]:
                    raise CheckpointCorruptError(
                        f"CRC mismatch in {fpath!r}: manifest says "
                        f"{entry['crc32']:#010x}, file has {got:#010x} "
                        f"(size {os.path.getsize(fpath)} bytes)")

        restored: dict[str, Any] = {}
        shape_mismatch: list[str] = []
        for path, meta in leaves.items():
            target = flat_template.get(path)
            if target is None:
                continue
            if tuple(meta["shape"]) != tuple(np.shape(target)):
                # Same layer name, different architecture (e.g. resnet18
                # checkpoint into resnet_micro): loading it would blow up
                # later inside flax with a much less useful error.
                shape_mismatch.append(path)
                continue
            if hasattr(target, "sharding"):
                restored[path] = _assemble_sharded(
                    arrays_dir, meta, target.sharding)
            else:
                restored[path] = _assemble_full(arrays_dir, meta)

        want_params = [p for p in flat_template if p.startswith("params")]
        missing = [p for p in want_params if p not in restored]
        if missing:
            detail = (f"{len(missing)}/{len(want_params)} model parameters "
                      f"missing or shape-mismatched (e.g. {missing[:3]}; "
                      f"{len(shape_mismatch)} shape mismatches)")
            if not allow_partial:
                raise ValueError(
                    f"checkpoint at {step_dir!r} does not match this model: "
                    f"{detail} — wrong --model for this --resume path? "
                    f"(allow_partial=True to force a partial load)")
            import logging

            logging.getLogger(__name__).warning(
                "partial restore from %s: %s; unmatched leaves keep their "
                "initialization", step_dir, detail)

        def rebuild(path, x):
            key = param_path(path)
            if _is_array_leaf(x) or hasattr(x, "shape"):
                if key in restored:
                    return restored[key]
            return x

        state = jax.tree_util.tree_map_with_path(rebuild, state_template)
        return state, manifest.get("extra", {})


def _assemble_full(arrays_dir: str, meta: dict) -> np.ndarray:
    """Materialize a whole leaf (host-local numpy targets only)."""
    full = np.empty(meta["shape"], dtype=np.dtype(meta["dtype"]))
    for entry in meta["files"]:
        region = resilience.retriable_io(
            np.load, os.path.join(arrays_dir, entry["file"]),
            _what="ckpt_read")
        if full.ndim == 0:
            full = region.reshape(())
        else:
            full[tuple(slice(a, b) for a, b in entry["index"])] = region
    return full


def _assemble_sharded(arrays_dir: str, meta: dict, sharding) -> jax.Array:
    """Build a jax.Array leaf shard-by-shard under the target ``sharding``.

    For every addressable shard of the target, copy in just the overlapping
    parts of the saved regions (mmap-opened, so only the overlap is read).
    Peak host memory is one shard, not the leaf — FSDP-restore requirement
    (SURVEY.md §3.4/§7(b)); also how a checkpoint saved under one topology
    re-shards onto another.
    """
    shape = tuple(meta["shape"])
    index_map = sharding.addressable_devices_indices_map(shape)
    opened: dict[str, np.ndarray] = {}

    def region(fname):
        if fname not in opened:
            opened[fname] = resilience.retriable_io(
                np.load, os.path.join(arrays_dir, fname), mmap_mode="r",
                _what="ckpt_read")
        return opened[fname]

    def assemble(bounds):
        block = np.empty([b - a for a, b in bounds],
                         dtype=np.dtype(meta["dtype"]))
        for entry in meta["files"]:
            src = entry["index"] if shape else []
            inter = [(max(a, c), min(b, d))
                     for (a, b), (c, d) in zip(bounds, src)]
            if any(a >= b for a, b in inter):
                continue
            dst_sl = tuple(slice(a - o[0], b - o[0])
                           for (a, b), o in zip(inter, bounds))
            src_sl = tuple(slice(a - o[0], b - o[0])
                           for (a, b), o in zip(inter, src))
            if block.ndim == 0:
                block = np.asarray(region(entry["file"])).reshape(())
            else:
                block[dst_sl] = region(entry["file"])[src_sl]
        return block

    # Group devices by shard region: replicated leaves (DP) assemble each
    # region ONCE for all devices holding it, and each host block is freed
    # right after placement so peak host memory stays one shard.
    by_bounds: dict[tuple, list] = {}
    for device, idx in index_map.items():
        bounds = tuple(
            (s.start or 0, s.stop if s.stop is not None else dim)
            for s, dim in zip(idx, shape)
        )
        by_bounds.setdefault(bounds, []).append(device)
    placed = {}
    for bounds, devs in by_bounds.items():
        block = assemble(bounds)
        for device in devs:
            placed[device] = jax.device_put(block, device)
        del block
    pieces = [placed[device] for device in index_map]
    arr = jax.make_array_from_single_device_arrays(shape, sharding, pieces)
    # ``device_put(host_block, device)`` zero-copies aligned numpy memory on
    # the CPU PJRT client (jax 0.4.x), and the train loop DONATES the state:
    # donating a buffer XLA merely borrows frees host memory it does not own
    # — a hard segfault on the first post-resume step (reproduced by
    # tests/test_distributed.py::test_mid_epoch_kill_resume_is_sample_exact).
    # A jitted copy forces fresh XLA-owned buffers; applied per leaf, so peak
    # memory stays one leaf above the state being assembled.
    import jax.numpy as jnp

    return jax.jit(jnp.copy)(arr)


def split_resume_path(path: str) -> tuple[str, int | None]:
    """Parse a ``--resume`` value into (checkpoint root, explicit step|None).

    ``.../ck`` -> ("/.../ck", None); ``.../ck/step_00000007`` ->
    ("/.../ck", 7). Single shared parser for every resume entry point.
    """
    target = path.rstrip("/")
    m = _STEP_RE.match(os.path.basename(target))
    if m:
        return os.path.dirname(target) or ".", int(m.group(1))
    return target, None


def all_checkpoints(directory: str) -> list[int]:
    if not os.path.isdir(directory):
        return []
    out = []
    for name in os.listdir(directory):
        m = _STEP_RE.match(name)
        if m and os.path.exists(os.path.join(directory, name, COMMIT_FILE)):
            out.append(int(m.group(1)))
    return sorted(out)


def _manifest_ok(directory: str, step: int) -> bool:
    """True when the committed step's manifest exists and parses."""
    try:
        with open(os.path.join(directory, f"step_{step:08d}",
                               MANIFEST_FILE)) as fh:
            json.load(fh)
        return True
    except (OSError, json.JSONDecodeError):
        return False


def latest_checkpoint(directory: str) -> int | None:
    """Newest committed step whose manifest is present and parseable.

    A COMMIT marker over a missing/garbled manifest (torn write, partial
    sync) previously made ``--resume auto`` crash with a raw JSONDecodeError;
    such a dir is treated as uncommitted and skipped with a warning.
    """
    steps = all_checkpoints(directory)
    for s in reversed(steps):
        if _manifest_ok(directory, s):
            return s
        log.warning(
            "checkpoint step %d in %s has a missing/unparseable manifest — "
            "treating as uncommitted and falling back", s, directory)
    return None


def peek_manifest(directory: str, step: int | None = None) -> dict | None:
    """JSON-only read of a committed step's manifest (no array I/O).

    The elastic resume path calls this *before* the mesh/model/optimizer are
    built, to learn the geometry (``manifest["geometry"]``, ``extra``'s
    ``global_batch_size``/``grad_accum``/``mesh_shape``) the checkpoint was
    written under and plan the batch rescale. ``step=None`` peeks the newest
    usable committed step. Returns None when nothing committed/parseable —
    advisory only, never raises for a missing checkpoint.
    """
    steps = ([step] if step is not None
             else list(reversed(all_checkpoints(directory))))
    for s in steps:
        try:
            with open(os.path.join(directory, f"step_{s:08d}",
                                   MANIFEST_FILE)) as fh:
                return json.load(fh)
        except (OSError, json.JSONDecodeError):
            continue
    return None


def _warn_geometry_mismatch(step: int, manifest: dict) -> None:
    """Loud (non-fatal) warning when a checkpoint written under one topology
    is restored under another — previously a changed world size restored
    silently. Cross-topology restore is *supported* (shard-wise reassembly);
    the warning exists so an unintended geometry change can't go unnoticed."""
    geom = manifest.get("geometry") or {}
    if not geom:
        return  # pre-geometry checkpoint: nothing recorded to compare
    mismatches = []
    for key, current in (("process_count", jax.process_count()),
                         ("device_count", jax.device_count())):
        recorded = geom.get(key)
        if recorded is not None and int(recorded) != current:
            mismatches.append(f"{key} {recorded} -> {current}")
    if mismatches:
        log.warning(
            "checkpoint step %d was written under a DIFFERENT topology "
            "(%s; source mesh %s) — restoring cross-topology via shard-wise "
            "reassembly. If this is not an intended elastic/topology change, "
            "stop and check the checkpoint path.", step,
            ", ".join(mismatches), geom.get("mesh_shape"))
