"""Multi-host bootstrap — the ``init_process_group('nccl')`` equivalent.

Reference parity (SURVEY.md §3.1): the reference launches one process per GPU
under ``torchrun``, which sets ``RANK``/``WORLD_SIZE``/``LOCAL_RANK`` and
rendezvouses through a TCP store before constructing ``ProcessGroupNCCL``.
On TPU the unit is one process per *host* (each host drives its local chips),
and the rendezvous is ``jax.distributed.initialize(coordinator_address)``;
afterwards every process sees the global device list and all collectives are
compiled into the step over ICI/DCN — there is no runtime process-group
object to pass around.

Environment contract (compatible with torchrun-style launchers and with our
``launch.py``):

    COORDINATOR_ADDRESS | MASTER_ADDR:MASTER_PORT  — rendezvous endpoint
    NUM_PROCESSES       | WORLD_SIZE               — number of host processes
    PROCESS_ID          | RANK                     — this host's index
"""

from __future__ import annotations

import logging
import os

import jax

log = logging.getLogger(__name__)

_initialized = False


def init_process_group(
    coordinator_address: str | None = None,
    num_processes: int | None = None,
    process_id: int | None = None,
) -> None:
    """Initialize the multi-host runtime (idempotent).

    Single-host (the common dev case, and under a gang-scheduled TPU runtime
    that pre-wires the cluster) requires no arguments: if no coordinator can
    be determined and no cluster env is present this is a no-op — matching
    the reference's non-``--distributed`` path running without a process
    group.
    """
    global _initialized
    if _initialized:
        return

    env = os.environ
    if coordinator_address is None:
        coordinator_address = env.get("COORDINATOR_ADDRESS")
        if coordinator_address is None and "MASTER_ADDR" in env:
            coordinator_address = f"{env['MASTER_ADDR']}:{env.get('MASTER_PORT', '12355')}"
    if num_processes is None:
        raw = env.get("NUM_PROCESSES", env.get("WORLD_SIZE"))
        num_processes = int(raw) if raw is not None else None
    if process_id is None:
        raw = env.get("PROCESS_ID", env.get("RANK"))
        process_id = int(raw) if raw is not None else None

    if coordinator_address is None and num_processes in (None, 1):
        # Single-process mode; nothing to rendezvous.
        _initialized = True
        return

    if (os.environ.get("JAX_PLATFORMS", "").startswith("cpu")
            or os.environ.get("JAX_PLATFORMS_OVERRIDE") == "cpu"):
        # Local CPU pods (launch.py --cpu-devices): XLA:CPU refuses any
        # computation spanning processes ("Multiprocess computations aren't
        # implemented on the CPU backend") unless a CPU collectives backend
        # is selected before the backend initializes. Gloo ships in jaxlib;
        # older jaxlibs without the flag fall through to the old behavior.
        try:
            jax.config.update("jax_cpu_collectives_implementation", "gloo")
        except Exception:  # pragma: no cover - jaxlib without gloo
            log.warning("no CPU collectives backend available — "
                        "multi-process CPU computations will fail")

    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )
    _initialized = True
    log.info(
        "distributed initialized: process %d/%d, %d local / %d global devices",
        jax.process_index(), jax.process_count(),
        jax.local_device_count(), jax.device_count(),
    )


def rank() -> int:
    """Host-process index (the reference's RANK; chips are below this level)."""
    return jax.process_index()


def world_size() -> int:
    return jax.process_count()


def is_main_process() -> bool:
    """The 'rank 0' predicate used for logging/checkpoint gating."""
    return jax.process_index() == 0


def barrier(name: str = "barrier") -> None:
    """Cross-host sync point (reference: ``dist.barrier()``)."""
    from jax.experimental import multihost_utils

    multihost_utils.sync_global_devices(name)
