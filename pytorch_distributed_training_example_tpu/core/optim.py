"""Optimizer + LR schedule construction (optax chains).

Reference parity: SGD-momentum with step/cosine decay for the vision configs,
AdamW for ViT/GPT/Llama; warmup + cosine is the modern default for all five
presets. Gradient clipping folds into the optax chain (the reference would
call ``clip_grad_norm_`` between unscale and step).
"""

from __future__ import annotations

import optax

from pytorch_distributed_training_example_tpu.utils.config import Config


def build_schedule(cfg: Config, steps_per_epoch: int) -> optax.Schedule:
    total_steps = max(int(cfg.epochs * steps_per_epoch), 1)
    warmup_steps = min(int(cfg.warmup_epochs * steps_per_epoch), total_steps - 1)
    if cfg.lr_schedule == "step":
        # The reference ImageNet recipe (StepLR): lr * gamma^(epoch //
        # step_epochs), evaluated on the GLOBAL step grid — decay epochs
        # must not shift with warmup. join_schedules hands the post-warmup
        # schedule (step - boundary), so shift it back by warmup_steps.
        stair = optax.exponential_decay(
            cfg.lr, transition_steps=max(cfg.lr_step_epochs, 1)
            * steps_per_epoch, decay_rate=cfg.lr_gamma, staircase=True)
        main = ((lambda step: stair(step + warmup_steps))
                if warmup_steps > 0 else stair)
    elif cfg.lr_schedule == "constant":
        main = optax.constant_schedule(cfg.lr)
    elif cfg.lr_schedule == "cosine":
        main = optax.cosine_decay_schedule(
            cfg.lr, decay_steps=max(total_steps - warmup_steps, 1))
    else:
        raise ValueError(f"unknown lr_schedule {cfg.lr_schedule!r} "
                         "(cosine | step | constant)")
    if warmup_steps > 0:
        return optax.join_schedules(
            [optax.linear_schedule(0.0, cfg.lr, warmup_steps), main],
            boundaries=[warmup_steps])
    return main


def build_optimizer(cfg: Config, steps_per_epoch: int):
    """Returns ``(tx, schedule)``; schedule is also used for logging lr."""
    schedule = build_schedule(cfg, steps_per_epoch)
    parts = []
    if cfg.grad_clip and cfg.grad_clip > 0:
        parts.append(optax.clip_by_global_norm(cfg.grad_clip))
    if cfg.optimizer == "sgd":
        parts += [
            optax.sgd(schedule, momentum=cfg.momentum, nesterov=True),
        ]
        if cfg.weight_decay:
            # Decoupled WD on >=2D params only (skip BN/bias), torch-style.
            parts.insert(-1, optax.add_decayed_weights(
                cfg.weight_decay, mask=_wd_mask))
    elif cfg.optimizer == "adamw":
        parts.append(optax.adamw(
            schedule, b1=0.9, b2=0.95 if "llama" in cfg.model or "gpt" in cfg.model else 0.999,
            weight_decay=cfg.weight_decay, mask=_wd_mask,
        ))
    else:
        raise ValueError(f"unknown optimizer {cfg.optimizer!r}")
    return optax.chain(*parts), schedule


def _wd_mask(params):
    import jax

    return jax.tree.map(lambda p: p.ndim >= 2, params)
