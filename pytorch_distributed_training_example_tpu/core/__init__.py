"""Core runtime: mesh construction, precision policy, train state/loop, distributed bootstrap."""
