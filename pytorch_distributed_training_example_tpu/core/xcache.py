"""Persistent executable cache — compile once per topology, restart warm.

The restart tax of an elastic relaunch is dominated by two costs the
checkpoint machinery never touched: re-tracing + re-compiling the train
step, and re-assembling the checkpoint layout (core/reshard.py owns the
second). This module removes the first: the exact
``jit(...).lower(...).compile()`` front-end the trainer, ``profile_step.py
--aot`` and the serve warmup all share is keyed on a **fingerprint** of
everything that can change the lowered program — jax version, backend,
topology (process/device counts), mesh shape, the config knobs that reach
tracing, and the abstract avals+shardings of every input — and the
compiled executable is serialized under ``<ckpt-dir>/xcache/`` with the
same CRC discipline checkpoints use. A relaunched attempt at a previously
seen topology deserializes instead of compiling; any mismatch falls back
to a cold compile with a loud log line, never a stale executable.

Entry layout (one directory per fingerprint)::

    <ckpt-dir>/xcache/<key>/
        executable.bin   pickle of (payload, in_tree, out_tree) from
                         jax.experimental.serialize_executable.serialize
        meta.json        fingerprint fields + crc32 of executable.bin

Corruption handling mirrors ``core/checkpoint.py``: a CRC or unpickle
failure quarantines the entry (rename to ``<key>.corrupt``) and recompiles.
Serialization is backend-dependent; where ``serialize`` is unsupported the
cache degrades to the jax persistent compilation cache (``main.py`` points
``jax_compilation_cache_dir`` into ``<ckpt-dir>/xcache/jaxcache`` when
``--xcache`` is on), which ``Lowered.compile()`` consults transparently.
"""

from __future__ import annotations

import hashlib
import json
import os
import pickle
import shutil
import zlib

import jax

from pytorch_distributed_training_example_tpu.utils.resilience import (
    retriable_io)

import logging

log = logging.getLogger("pdtx")

XCACHE_DIRNAME = "xcache"
EXECUTABLE_FILE = "executable.bin"
META_FILE = "meta.json"
SCHEMA_VERSION = 1

#: Config fields that reach tracing/lowering of the train step. Anything
#: here changing MUST miss the cache (a stale executable is silent wrong
#: math); anything not here must not spuriously invalidate it.
TRACED_KNOBS = (
    "model", "dataset", "num_classes", "image_size", "seq_len",
    "global_batch_size", "grad_accum_steps", "precision", "remat",
    "remat_policy", "strategy", "attn_impl", "dropout", "label_smoothing",
    "grad_clip", "optimizer", "weight_decay", "momentum", "telemetry",
    "moe_top_k", "moe_capacity_factor", "moe_dispatch_impl",
    "moe_combine_dtype", "moe_router_dtype", "moe_router_impl",
    "moe_ep_dispatch", "moe_ep_overlap_chunks", "pp_microbatches",
)


def _abstract_sig(tree) -> list[str]:
    """Stable string per leaf: shape/dtype/sharding spec of the aval."""
    sig = []
    for path, leaf in jax.tree_util.tree_flatten_with_path(tree)[0]:
        shard = getattr(leaf, "sharding", None)
        spec = getattr(shard, "spec", None)
        sig.append(f"{jax.tree_util.keystr(path)}:"
                   f"{tuple(getattr(leaf, 'shape', ()))}:"
                   f"{getattr(getattr(leaf, 'dtype', None), 'name', '?')}:"
                   f"{spec}")
    return sig


def fingerprint(*, mesh, config=None, example_args=(), extra=None) -> dict:
    """Everything that can change the lowered step, as a flat JSON dict."""
    fields = {
        "schema_version": SCHEMA_VERSION,
        "jax_version": jax.__version__,
        "backend": jax.default_backend(),
        "process_count": jax.process_count(),
        "device_count": jax.device_count(),
        "device_kind": jax.devices()[0].device_kind,
        "mesh_shape": {str(k): int(v) for k, v in dict(mesh.shape).items()},
        "abstract": [s for a in example_args for s in _abstract_sig(a)],
    }
    if config is not None:
        fields["knobs"] = {k: getattr(config, k) for k in TRACED_KNOBS
                           if hasattr(config, k)}
    if extra:
        fields["extra"] = dict(extra)
    return fields


def cache_key(fields: dict) -> str:
    blob = json.dumps(fields, sort_keys=True, default=str)
    return hashlib.sha256(blob.encode()).hexdigest()[:24]


def _crc32(path: str) -> int:
    crc = 0
    with open(path, "rb") as fh:
        for chunk in iter(lambda: fh.read(1 << 20), b""):
            crc = zlib.crc32(chunk, crc)
    return crc & 0xFFFFFFFF


def _read_json(path: str) -> dict:
    with open(path) as fh:
        return json.load(fh)


def _read_bytes(path: str) -> bytes:
    with open(path, "rb") as fh:
        return fh.read()


def cache_dir(root: str) -> str:
    return os.path.join(root, XCACHE_DIRNAME)


def _skeleton(tree):
    """JSON-able container skeleton of a plain pytree (leaves become 0.0).

    Only standard containers (dict/list/tuple) are representable — enough
    for the metrics side of the train step's output. Raises TypeError on
    anything fancier, which the caller treats as "trees not
    reconstructible".
    """
    if isinstance(tree, dict):
        if not all(isinstance(k, str) for k in tree):
            raise TypeError("non-string dict key in metrics tree")
        return {"d": {k: _skeleton(v) for k, v in tree.items()}}
    if isinstance(tree, tuple):
        return {"t": [_skeleton(v) for v in tree]}
    if isinstance(tree, list):
        return {"l": [_skeleton(v) for v in tree]}
    return {"x": 0}


def _unskeleton(skel):
    if "d" in skel:
        return {k: _unskeleton(v) for k, v in skel["d"].items()}
    if "t" in skel:
        return tuple(_unskeleton(v) for v in skel["t"])
    if "l" in skel:
        return [_unskeleton(v) for v in skel["l"]]
    return 0.0


def _quarantine(entry: str, reason: str) -> None:
    dst = f"{entry}.{reason}"
    retriable_io(os.replace, entry, dst, _what="xcache quarantine")
    log.warning("xcache: entry %s quarantined -> %s", entry, dst)


def load(root: str, fields: dict, example=None):
    """Deserialize the cached executable for ``fields``, or None (cold).

    Every miss/fallback is loud: the log line names WHY the run compiles
    cold (no entry, fingerprint mismatch, CRC mismatch, deserialize
    failure), because a silent cold path would hide an invalidation bug
    behind a slow restart. A corrupted entry is quarantined like a
    corrupted checkpoint so the recompile can re-save under the same key.

    ``example`` is the live ``(state, batch)`` pair for entries saved in
    ``reconstruct`` tree mode (see :func:`save`): their in/out treedefs
    are rebuilt from the live objects instead of unpickled, because the
    train state's static fields (optax closures) don't pickle.
    """
    entry = os.path.join(cache_dir(root), cache_key(fields))
    meta_path = os.path.join(entry, META_FILE)
    exe_path = os.path.join(entry, EXECUTABLE_FILE)
    if not os.path.isdir(entry):
        log.warning("xcache: MISS — no entry for fingerprint %s (first run "
                    "at this topology, or a knob/topology change "
                    "invalidated the key) — cold compile",
                    os.path.basename(entry))
        return None
    try:
        meta = retriable_io(_read_json, meta_path, _what="xcache meta read")
    except (OSError, ValueError) as e:
        log.warning("xcache: unreadable meta for %s (%s) — quarantining, "
                    "cold compile", entry, e)
        _quarantine(entry, "corrupt")
        return None
    if meta.get("fields") != json.loads(
            json.dumps(fields, sort_keys=True, default=str)):
        # A sha collision would be the only way here; treat as a mismatch.
        log.warning("xcache: fingerprint mismatch under key %s — refusing "
                    "the stale executable, cold compile",
                    os.path.basename(entry))
        return None
    try:
        if retriable_io(_crc32, exe_path, _what="xcache crc") != int(
                meta["crc32"]):
            log.warning("xcache: CRC mismatch for %s — quarantining, cold "
                        "compile", exe_path)
            _quarantine(entry, "corrupt")
            return None
        blob = retriable_io(_read_bytes, exe_path, _what="xcache read")
        if meta.get("tree_mode") == "reconstruct":
            if example is None:
                log.warning("xcache: entry %s needs live example trees and "
                            "none were passed — cold compile", entry)
                return None
            payload = blob
            in_tree = jax.tree_util.tree_structure((tuple(example), {}))
            out_tree = jax.tree_util.tree_structure(
                (example[0], _unskeleton(meta["metrics_skeleton"])))
        else:
            payload, in_tree, out_tree = pickle.loads(blob)
        from jax.experimental.serialize_executable import (
            deserialize_and_load)

        compiled = deserialize_and_load(payload, in_tree, out_tree)
    except Exception as e:  # noqa: BLE001 — any failure means cold compile
        log.warning("xcache: deserialize failed for %s (%s: %s) — "
                    "quarantining, cold compile", entry,
                    type(e).__name__, e)
        try:
            _quarantine(entry, "corrupt")
        except OSError:
            pass
        return None
    log.warning("xcache: HIT — restored compiled executable %s "
                "(jax %s, %d devices), compile skipped",
                os.path.basename(entry), meta["fields"].get("jax_version"),
                meta["fields"].get("device_count"))
    return compiled


def save(root: str, fields: dict, compiled, *, example=None,
         metrics=None) -> bool:
    """Serialize ``compiled`` under the fingerprint key (best-effort).

    Tree handling: the executable payload always serializes, but the
    in/out *treedefs* only pickle when every custom pytree node's static
    data does — the train state's optax closures don't. When ``example``
    (the live ``(state, batch)``) and ``metrics`` (the first step's
    metrics pytree) are passed and their treedefs match the serialized
    ones exactly, the entry is written in ``reconstruct`` mode: raw
    payload plus a JSON skeleton of the metrics tree, and :func:`load`
    rebuilds the treedefs from the caller's live objects.

    Returns False — with a loud line naming the fallback — when neither
    mode works; the jax persistent compilation cache then carries the
    warm restart instead.
    """
    try:
        from jax.experimental.serialize_executable import serialize

        payload, in_tree, out_tree = serialize(compiled)
    except Exception as e:  # noqa: BLE001 — backend-dependent support
        log.warning("xcache: executable serialization unsupported here "
                    "(%s: %s) — relying on the jax persistent compilation "
                    "cache for warm restarts", type(e).__name__, e)
        return False
    tree_mode = None
    skel = None
    try:
        blob = pickle.dumps((payload, in_tree, out_tree))
        tree_mode = "pickle"
    except Exception:  # noqa: BLE001 — unpicklable static treedef data
        if example is not None and metrics is not None:
            try:
                skel = _skeleton(metrics)
                ok = (jax.tree_util.tree_structure((tuple(example), {}))
                      == in_tree
                      and jax.tree_util.tree_structure(
                          (example[0], _unskeleton(skel))) == out_tree)
            except TypeError:
                ok = False
            if ok:
                blob = payload
                tree_mode = "reconstruct"
    if tree_mode is None:
        log.warning("xcache: executable treedefs neither pickle nor "
                    "reconstruct from the train-step contract — relying on "
                    "the jax persistent compilation cache for warm restarts")
        return False
    entry = os.path.join(cache_dir(root), cache_key(fields))
    tmp = f"{entry}.saving.{os.getpid()}"
    retriable_io(os.makedirs, tmp, exist_ok=True, _what="xcache entry dir")
    exe_tmp = os.path.join(tmp, EXECUTABLE_FILE)

    def _write_blob():
        with open(exe_tmp, "wb") as fh:
            fh.write(blob)

    def _write_meta():
        meta = {"schema_version": SCHEMA_VERSION,
                "crc32": _crc32(exe_tmp),
                "tree_mode": tree_mode,
                "fields": json.loads(json.dumps(
                    fields, sort_keys=True, default=str))}
        if skel is not None:
            meta["metrics_skeleton"] = skel
        with open(os.path.join(tmp, META_FILE), "w") as fh:
            json.dump(meta, fh, indent=1, default=str)

    try:
        retriable_io(_write_blob, _what="xcache executable write")
        retriable_io(_write_meta, _what="xcache meta write")
        # Last writer wins: a concurrent attempt racing the same key swaps
        # in an equivalent entry (same fingerprint -> same program).
        shutil.rmtree(entry, ignore_errors=True)
        retriable_io(os.replace, tmp, entry, _what="xcache entry commit")
    except OSError as e:
        log.warning("xcache: save failed (%s) — next restart compiles cold",
                    e)
        shutil.rmtree(tmp, ignore_errors=True)
        return False
    log.info("xcache: saved compiled executable -> %s (%d bytes)",
             entry, len(blob))
    return True


def compile_cached(lowered, root: str | None, fields: dict):
    """The shared front-end: deserialize on hit, else compile and save.

    Returns ``(compiled, mode)`` where mode is ``"warm"`` (cache hit) or
    ``"cold"``. With ``root=None`` this is exactly ``lowered.compile()``.
    """
    if root:
        compiled = load(root, fields)
        if compiled is not None:
            return compiled, "warm"
    compiled = lowered.compile()
    if root:
        save(root, fields, compiled)
    return compiled, "cold"
