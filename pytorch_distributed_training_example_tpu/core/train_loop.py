"""The compiled train/eval step and state construction.

This is where the reference's whole hot loop (SURVEY.md §3.2) — forward under
autocast, scaled backward, bucketed all-reduce overlapped with backward,
optimizer step — collapses into ONE ``jax.jit``-compiled XLA program:

- forward/backward: ``jax.value_and_grad`` traced at compute dtype (bf16);
- the DDP all-reduce: *implicit* — the loss is a mean over the globally
  sharded batch, so GSPMD emits the gradient ``psum`` and XLA's latency-
  hiding scheduler overlaps it with the backward, which is exactly what
  DDP's C++ reducer does by hand with buckets (SURVEY.md §2b N2);
- optimizer update: fused into the same program; the state is donated so
  updates happen in-place in HBM.

Strategy (DP/FSDP/TP/...) enters only through the shardings of the state and
batch — the step function is strategy-agnostic.
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Any, Callable, Sequence

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from pytorch_distributed_training_example_tpu.core import mesh as mesh_lib
from pytorch_distributed_training_example_tpu.core import precision as precision_lib
from pytorch_distributed_training_example_tpu.core.train_state import TrainState
from pytorch_distributed_training_example_tpu.parallel import sharding as sharding_lib
from pytorch_distributed_training_example_tpu.utils import metrics as metrics_lib


# ---------------------------------------------------------------------------
# Tasks: how a batch turns into (loss, metrics) given model outputs.
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class ClassificationTask:
    label_smoothing: float = 0.0

    inputs = ("image",)

    def loss(self, logits, batch):
        return metrics_lib.cross_entropy(logits, batch["label"], self.label_smoothing)

    def metrics(self, logits, batch):
        """LINEAR per-batch metrics only (averaged across grad-accum
        microbatches); nonlinear ones go in :meth:`metrics_from_loss`."""
        counts = metrics_lib.topk_correct(logits, batch["label"])
        n = jnp.asarray(batch["label"].shape[0], jnp.float32)
        return {f"acc_{k}": v / n for k, v in counts.items()}

    def metrics_from_loss(self, loss):
        return {}

    def eval_stats(self, logits, batch):
        """Exact global sums (mask-aware for padded final eval batches)."""
        mask = batch.get("mask")
        if mask is None:
            mask = jnp.ones(batch["label"].shape[0], jnp.float32)
        logits32 = logits.astype(jnp.float32)
        per_ex = metrics_lib.per_example_cross_entropy(logits32, batch["label"])
        counts = metrics_lib.topk_correct(logits32, batch["label"], mask=mask)
        return {
            "count": jnp.sum(mask),
            "loss_sum": jnp.sum(per_ex * mask),
            **{f"acc_{k}_sum": v for k, v in counts.items()},
        }


@dataclasses.dataclass(frozen=True)
class LanguageModelingTask:
    inputs = ("tokens",)

    def loss(self, logits, batch):
        return metrics_lib.cross_entropy(logits, batch["targets"])

    def metrics(self, logits, batch):
        return {}

    def metrics_from_loss(self, loss):
        # Derived AFTER loss averaging: mean(exp(l_i)) over microbatches
        # would be Jensen-biased upward vs exp(mean(l_i)).
        return {"perplexity": jnp.exp(loss)}

    def eval_stats(self, logits, batch):
        mask = batch.get("mask")
        seq_weight = jnp.ones(batch["targets"].shape, jnp.float32)
        if mask is not None:
            seq_weight = seq_weight * mask[:, None]
        per_tok = metrics_lib.per_example_cross_entropy(
            logits.astype(jnp.float32), batch["targets"])
        return {
            "count": jnp.sum(seq_weight),
            "loss_sum": jnp.sum(per_tok * seq_weight),
        }


def get_task(kind: str, label_smoothing: float = 0.0):
    if kind == "classification":
        return ClassificationTask(label_smoothing)
    if kind == "lm":
        return LanguageModelingTask()
    raise ValueError(f"unknown task {kind!r}")


# ---------------------------------------------------------------------------
# State construction (sharded init — params are born sharded, never
# materialized replicated; the FSDP-at-init requirement).
# ---------------------------------------------------------------------------


def state_shardings(state_shape, mesh: Mesh, rules: Sequence = ()):
    """Infer a NamedSharding for every leaf of a TrainState shape tree."""
    specs = sharding_lib.infer_specs(state_shape, rules, mesh)
    return jax.tree.map(lambda s: NamedSharding(mesh, s), specs,
                        is_leaf=lambda x: isinstance(x, P))


def create_train_state(
    model,
    tx,
    input_template: tuple,
    mesh: Mesh,
    rules: Sequence = (),
    seed: int = 0,
    scaler=None,
) -> TrainState:
    """Init model params directly into their target shardings (jit + out_shardings)."""
    root = jax.random.PRNGKey(seed)
    init_rng, state_rng = jax.random.split(root)

    def init_fn(rng):
        variables = model.init(
            {"params": rng, "dropout": jax.random.fold_in(rng, 1)},
            *input_template, train=False,
        )
        params = variables["params"]
        batch_stats = variables.get("batch_stats")
        return TrainState.create(
            apply_fn=model.apply, params=params, tx=tx, rng=state_rng,
            batch_stats=batch_stats, scaler=scaler,
        )

    state_shape = jax.eval_shape(init_fn, init_rng)
    shardings = state_shardings(state_shape, mesh, rules)
    with mesh_lib.use_mesh(mesh):
        return jax.jit(init_fn, out_shardings=shardings)(init_rng)


# ---------------------------------------------------------------------------
# Step builders
# ---------------------------------------------------------------------------


def make_train_step(task, grad_accum: int = 1, health: bool = False) -> Callable:
    """Build the pure ``(state, batch) -> (state, metrics)`` function.

    Callers wrap it in ``jax.jit(..., donate_argnums=0)`` under the mesh:
    sharding propagates from the state/batch, so one builder serves every
    strategy. Precision is carried by the model's dtypes and, for fp16, by
    ``state.scaler`` (presence enables GradScaler semantics at trace time).

    ``grad_accum > 1`` splits the batch into that many microbatches inside
    the compiled step (``lax.scan``), averaging gradients before ONE
    optimizer update — same numbers as the large batch (equivalence-tested)
    at 1/G the activation memory. BatchNorm running stats chain through the
    microbatches sequentially.

    ``health=True`` adds the telemetry health pack to the metrics dict:
    update/param norms, finite flags (utils/telemetry.health_pack) and any
    scalars the model sows under the ``"telemetry"`` collection (MoE
    router-load entropy / drop fraction). All on-device; the scalars ride
    the same device_get the loss already takes, so there is no extra host
    sync — only the small fused reductions inside the step. Downstream the
    fetched row feeds the anomaly guard AND the fleet layer: the
    flight-recorder ring merges it into the matching step record and the
    per-rank step rows behind the straggler detector ride the same cadence
    (utils/fleetobs.py) — so fleet observability inherits the same
    zero-extra-syncs contract.
    """
    from pytorch_distributed_training_example_tpu.utils import (
        telemetry as telemetry_lib)

    def compute_grads(state: TrainState, batch: dict, step_rng, batch_stats):
        def loss_fn(params):
            variables = {"params": params}
            # "losses" collects model-internal auxiliary terms (MoE load
            # balancing); "batch_stats" is BatchNorm's running stats;
            # "telemetry" (health runs only) collects model diagnostics —
            # sow() is a no-op when the collection isn't mutable.
            mutable = ["losses"]
            if health:
                mutable.append("telemetry")
            if batch_stats is not None:
                variables["batch_stats"] = batch_stats
                mutable.append("batch_stats")
            inputs = [batch[k] for k in task.inputs]
            logits, new_vars = state.apply_fn(
                variables, *inputs, train=True,
                rngs={"dropout": step_rng}, mutable=mutable)
            loss = task.loss(logits, batch)
            for aux in jax.tree.leaves(new_vars.get("losses", {})):
                loss = loss + aux
            tele = (telemetry_lib.collect_sowed(new_vars["telemetry"])
                    if health and "telemetry" in new_vars else {})
            scaled = state.scaler.scale_loss(loss) if state.scaler is not None else loss
            return scaled, (loss, logits, new_vars.get("batch_stats"), tele)

        return jax.grad(loss_fn, has_aux=True)(state.params)

    def train_step(state: TrainState, batch: dict):
        step_rng = (jax.random.fold_in(state.rng, state.step)
                    if state.rng is not None else jax.random.PRNGKey(0))

        if grad_accum <= 1:
            grads, (loss, logits, new_batch_stats, tele) = compute_grads(
                state, batch, step_rng, state.batch_stats)
            task_metrics = task.metrics(logits, batch)
        else:
            G = grad_accum
            bad = {k: v.shape[0] for k, v in batch.items()
                   if hasattr(v, "shape") and v.ndim and v.shape[0] % G}
            if bad:
                raise ValueError(
                    f"grad_accum={G} does not divide the batch dimension of "
                    f"{bad} — after an elastic rescale the global batch must "
                    f"remain a multiple of grad_accum x data-parallel degree "
                    f"(utils/elastic.py guarantees this for its plans)")
            micro = jax.tree.map(
                lambda x: mesh_lib.constrain(
                    x.reshape(G, x.shape[0] // G, *x.shape[1:]),
                    P(None, mesh_lib.BATCH_AXES)), batch)

            def body(carry, xs):
                g_acc, l_acc, m_acc, t_acc, bs, i = carry
                mb, = xs
                g, (l, logits, new_bs, t) = compute_grads(
                    state, mb, jax.random.fold_in(step_rng, i), bs)
                g_acc = jax.tree.map(jnp.add, g_acc, g)
                m_acc = jax.tree.map(jnp.add, m_acc, task.metrics(logits, mb))
                t_acc = jax.tree.map(jnp.add, t_acc, t)
                bs = new_bs if new_bs is not None else bs
                return (g_acc, l_acc + l, m_acc, t_acc, bs, i + 1), None

            # Zero-seeded carry (shapes via eval_shape, so the traced program
            # contains ONE copy of forward+backward, not an unrolled first
            # microbatch plus the scan body).
            mb0 = jax.tree.map(lambda x: x[0], micro)
            m_shape = jax.eval_shape(
                lambda: task.metrics(
                    state.apply_fn(
                        {"params": state.params, **(
                            {"batch_stats": state.batch_stats}
                            if state.batch_stats is not None else {})},
                        *[mb0[k] for k in task.inputs], train=False), mb0))
            t_shape = jax.eval_shape(compute_grads, state, mb0, step_rng,
                                     state.batch_stats)[1][3]
            zeros = lambda s: jnp.zeros(s.shape, s.dtype)
            carry0 = (
                jax.tree.map(jnp.zeros_like, state.params),
                jnp.zeros((), jnp.float32),
                jax.tree.map(zeros, m_shape),
                jax.tree.map(zeros, t_shape),
                state.batch_stats,
                jnp.int32(0),
            )
            (grads, loss, task_metrics, tele, new_batch_stats, _), _ = \
                jax.lax.scan(body, carry0, (micro,))
            inv = 1.0 / G
            grads = jax.tree.map(lambda g: g * inv, grads)
            loss = loss * inv
            task_metrics = jax.tree.map(lambda m: m * inv, task_metrics)
            tele = jax.tree.map(lambda t: t * inv, tele)

        bn_update = ({"batch_stats": new_batch_stats}
                     if new_batch_stats is not None else {})
        if state.scaler is not None:
            grads = state.scaler.unscale(grads)
            finite = precision_lib.all_finite(grads)
            new_scaler = state.scaler.update(finite)
            candidate = state.apply_gradients(grads, scaler=new_scaler, **bn_update)
            # GradScaler.step parity: on overflow skip the optimizer update
            # entirely (params AND optimizer state hold) but still advance
            # step/scaler so the schedule and backoff progress.
            pick = lambda n, o: jnp.where(finite, n, o)
            new_state = candidate.replace(
                params=jax.tree.map(pick, candidate.params, state.params),
                opt_state=jax.tree.map(pick, candidate.opt_state, state.opt_state),
            )
        else:
            new_state = state.apply_gradients(grads, **bn_update)

        metrics = {"loss": loss, **task_metrics,
                   **task.metrics_from_loss(loss),
                   "grad_norm": global_norm(grads)}
        if health:
            metrics.update(tele)
            metrics.update(telemetry_lib.health_pack(
                loss, grads, state.params, new_state.params))
        if state.scaler is not None:
            metrics["loss_scale"] = new_scaler.scale
            metrics["grads_finite"] = finite.astype(jnp.float32)
        return new_state, metrics

    return train_step


def make_eval_step(task) -> Callable:
    """Eval step returns exact SUMS + count; the host loop divides at the end
    (reference: all_reduce of metric sums then rank-0 division, SURVEY.md §3.3)."""

    def eval_step(state: TrainState, batch: dict):
        variables = {"params": state.params}
        if state.batch_stats is not None:
            variables["batch_stats"] = state.batch_stats
        inputs = [batch[k] for k in task.inputs]
        logits = state.apply_fn(variables, *inputs, train=False)
        return task.eval_stats(logits, batch)

    return eval_step


def global_norm(tree) -> jax.Array:
    import optax

    return optax.global_norm(jax.tree.map(lambda x: x.astype(jnp.float32), tree))


def jit_train_step(train_step, mesh: Mesh):
    """jit with state donation under the mesh (in-place HBM update)."""
    return jax.jit(train_step, donate_argnums=0)


def jit_eval_step(eval_step, mesh: Mesh):
    return jax.jit(eval_step)
