"""Mixed-precision policy — the TPU-native replacement for AMP + GradScaler.

Reference parity (SURVEY.md §2a #6, §2b N6): the reference wraps its forward
pass in ``torch.cuda.amp.autocast`` and scales the loss with ``GradScaler``
because fp16 has a narrow exponent range. TPUs compute natively in bfloat16,
whose exponent range equals fp32, so the idiomatic policy is:

    params fp32  /  compute bf16  /  no loss scaling

expressed here as a :class:`Policy` that models consult for their ``dtype`` /
``param_dtype``. A :class:`DynamicGradScaler` is still provided for exact API
parity (``scale -> unscale -> check-finite -> step -> update``) and for fp16
experiments; with the default bf16 policy it is simply never enabled.
"""

from __future__ import annotations

import dataclasses
from typing import Any

import jax
import jax.numpy as jnp
from flax import struct


@dataclasses.dataclass(frozen=True)
class Policy:
    """What dtype each class of tensor uses inside the compiled step."""

    param_dtype: Any = jnp.float32   # master copy held in the train state
    compute_dtype: Any = jnp.bfloat16  # matmul/conv inputs (MXU-native)
    output_dtype: Any = jnp.float32  # loss accumulation
    #: dtype LM logits are *stored* in between the vocab matmul and the loss.
    #: The loss always accumulates in fp32 (metrics.cross_entropy upcasts
    #: per-element inside its fusions); bf16 storage only re-rounds values the
    #: bf16 vocab matmul already rounded, while halving-to-quartering the
    #: largest activation tensor's HBM traffic ([B,S,50257] for GPT-2 —
    #: measured 18.5% of the v5e step, see LM_SWEEP.json/PROFILE notes).
    logits_dtype: Any = jnp.float32

    def cast_to_compute(self, tree):
        return _cast_floating(tree, self.compute_dtype)

    def cast_to_param(self, tree):
        return _cast_floating(tree, self.param_dtype)

    def cast_to_output(self, tree):
        return _cast_floating(tree, self.output_dtype)


def _cast_floating(tree, dtype):
    def cast(x):
        if hasattr(x, "dtype") and jnp.issubdtype(x.dtype, jnp.floating):
            return x.astype(dtype)
        return x

    return jax.tree.map(cast, tree)


#: Named presets selectable from the CLI (``--precision``).
POLICIES: dict[str, Policy] = {
    # Reference's fp32 baseline path (no autocast).
    "fp32": Policy(jnp.float32, jnp.float32, jnp.float32),
    # The TPU-native AMP equivalent: fp32 master params, bf16 compute.
    "bf16": Policy(jnp.float32, jnp.bfloat16, jnp.float32, jnp.bfloat16),
    # Fully bf16 (params too) — halves HBM for params; fine for inference
    # and large-model training with care.
    "pure_bf16": Policy(jnp.bfloat16, jnp.bfloat16, jnp.float32, jnp.bfloat16),
    # fp16 with dynamic loss scaling — GPU-style AMP parity path (logits
    # stay fp32: fp16's narrow exponent near softmax is exactly what the
    # scaler exists to protect against).
    "fp16": Policy(jnp.float32, jnp.float16, jnp.float32),
}


def get_policy(name: str | Policy) -> Policy:
    if isinstance(name, Policy):
        return name
    try:
        return POLICIES[name]
    except KeyError:
        raise ValueError(f"unknown precision policy {name!r}; have {sorted(POLICIES)}")


def needs_loss_scaling(policy: Policy) -> bool:
    return policy.compute_dtype == jnp.float16


class ScalerState(struct.PyTreeNode):
    """Functional ``GradScaler`` state (lives inside the jitted step).

    Mirrors torch.cuda.amp.GradScaler semantics: multiply the loss by
    ``scale`` before differentiation; if any grad is non-finite skip the
    update and halve the scale; after ``growth_interval`` consecutive finite
    steps double it.
    """

    scale: jax.Array
    growth_tracker: jax.Array
    growth_interval: int = struct.field(pytree_node=False, default=2000)
    growth_factor: float = struct.field(pytree_node=False, default=2.0)
    backoff_factor: float = struct.field(pytree_node=False, default=0.5)

    @classmethod
    def create(cls, init_scale: float = 2.0**15, **kw) -> "ScalerState":
        return cls(
            scale=jnp.asarray(init_scale, jnp.float32),
            growth_tracker=jnp.asarray(0, jnp.int32),
            **kw,
        )

    def scale_loss(self, loss):
        return loss * self.scale.astype(loss.dtype)

    def unscale(self, grads):
        inv = 1.0 / self.scale
        return jax.tree.map(lambda g: g * inv.astype(g.dtype), grads)

    def update(self, grads_finite: jax.Array) -> "ScalerState":
        tracker = jnp.where(grads_finite, self.growth_tracker + 1, 0)
        grow = tracker >= self.growth_interval
        new_scale = jnp.where(
            grads_finite,
            jnp.where(grow, self.scale * self.growth_factor, self.scale),
            self.scale * self.backoff_factor,
        )
        return self.replace(
            scale=jnp.clip(new_scale, 1.0, 2.0**24),
            growth_tracker=jnp.where(grow, 0, tracker),
        )


def all_finite(tree) -> jax.Array:
    leaves = [x for x in jax.tree.leaves(tree) if jnp.issubdtype(x.dtype, jnp.floating)]
    if not leaves:
        return jnp.asarray(True)
    return jnp.all(jnp.stack([jnp.all(jnp.isfinite(x)) for x in leaves]))
