"""Epoch/step orchestration — the reference's ``train()``/``validate()`` loop.

Reference call stack parity (SURVEY.md §3.2/§3.3): per-epoch
``sampler.set_epoch`` -> per-step forward/backward/update -> periodic eval
with cross-replica metric reduction -> rank-0 logging -> checkpoint. The
host-side loop here never blocks on step results (async dispatch); metrics
are fetched every ``log_every`` steps.
"""

from __future__ import annotations

import contextlib
import json
import os
import tempfile
import time

import jax
import jax.numpy as jnp
import numpy as np

from pytorch_distributed_training_example_tpu.core import (
    checkpoint as checkpoint_lib,
    distributed,
    mesh as mesh_lib,
    optim,
    precision as precision_lib,
    train_loop,
    xcache as xcache_lib,
)
from pytorch_distributed_training_example_tpu.data import (
    datasets as datasets_lib,
    loader as loader_lib,
    prefetch,
    sampler as sampler_lib,
)
from pytorch_distributed_training_example_tpu.models import registry
from pytorch_distributed_training_example_tpu.parallel import sharding as sharding_lib
from pytorch_distributed_training_example_tpu.utils import chaos as chaos_lib
from pytorch_distributed_training_example_tpu.utils import elastic as elastic_lib
from pytorch_distributed_training_example_tpu.utils import fleetobs
from pytorch_distributed_training_example_tpu.utils import metrics as metrics_lib
from pytorch_distributed_training_example_tpu.utils import resilience
from pytorch_distributed_training_example_tpu.utils import telemetry as telemetry_lib
from pytorch_distributed_training_example_tpu.utils import watchdog as watchdog_lib
from pytorch_distributed_training_example_tpu.utils.config import Config
from pytorch_distributed_training_example_tpu.utils.logging import (
    AverageMeter, MetricLogger, Throughput, log, setup_logging,
)


class Trainer:
    def __init__(self, cfg: Config, mesh=None):
        self.cfg = cfg
        self.metric_logger = setup_logging(
            jsonl_path=os.path.join(cfg.checkpoint_dir, "metrics.jsonl")
            if cfg.checkpoint_dir else None,
            tensorboard_dir=cfg.tensorboard_dir)

        # Telemetry layer (utils/telemetry.py): span recorder + anomaly
        # guard; also flips the compiled step's on-device health pack on.
        # Created FIRST so the init/compile/restore phases are on the
        # timeline too.
        self.telemetry = None
        self._watchdog: watchdog_lib.Watchdog | None = None
        self._compiled = False
        # "warm" when the first step ran an xcache-deserialized executable,
        # else "cold" — lands in goodput.json as ttfs_mode (core/xcache.py).
        self._xcache_mode = "cold"
        if cfg.telemetry:
            tdir = cfg.checkpoint_dir or os.path.join(
                tempfile.gettempdir(), "pdtx_telemetry")
            self.telemetry = telemetry_lib.Telemetry(
                tdir, run_id=self.metric_logger.run_id,
                anomaly_action=cfg.anomaly_action, config=cfg,
                allow_scaler_skips=(cfg.precision == "fp16"),
                resume=bool(cfg.resume),
                straggler_threshold=cfg.straggler_threshold,
                flightrec_steps=cfg.flightrec_steps)
            log.info("telemetry on: health pack in metrics, spans/goodput/"
                     "anomaly bundles -> %s", tdir)

        # Live metrics surface (utils/fleetobs.py): Prometheus endpoint on
        # rank 0 plus an atomically-replaced progress.json in the checkpoint
        # dir — both fed at the log cadence, so they cost nothing extra.
        self._metrics_server: fleetobs.MetricsServer | None = None
        self._progress_dir = cfg.checkpoint_dir or (
            self.telemetry.directory if self.telemetry is not None else None)
        self._progress: dict = {}
        if cfg.metrics_port is not None and distributed.is_main_process():
            try:
                self._metrics_server = fleetobs.MetricsServer(
                    cfg.metrics_port).start()
            except OSError as e:
                log.warning("metrics endpoint disabled (%s)", e)

        # Chaos harness (utils/chaos.py): armed BEFORE the workload builds so
        # the loader batch hook is installed before any batch is yielded.
        self._chaos: chaos_lib.ChaosEngine | None = None
        if cfg.chaos:
            self._chaos = chaos_lib.ChaosEngine(
                cfg.chaos,
                seed=(cfg.chaos_seed if cfg.chaos_seed is not None
                      else cfg.seed),
                log_dir=cfg.checkpoint_dir, rank=jax.process_index())
            loader_lib.set_batch_hook(self._chaos.batch_hook)
            log.warning("chaos harness armed: %s (seed %d)", cfg.chaos,
                        self._chaos.seed)
        self._rollbacks = 0

        init_span = self._span("init")
        init_span.__enter__()
        try:
            self._init_workload(cfg, mesh)
        finally:
            init_span.__exit__(None, None, None)

    def _span(self, name: str):
        return (self.telemetry.span(name) if self.telemetry is not None
                else contextlib.nullcontext())

    def _init_workload(self, cfg: Config, mesh=None):
        self.mesh = mesh if mesh is not None else mesh_lib.build_mesh(
            cfg.mesh_config(), elastic=cfg.elastic)
        # Elastic resume: BEFORE anything batch-dependent is built, peek the
        # newest committed manifest for the geometry that wrote it; if the
        # world size changed, rescale this run's batch geometry under the
        # configured policy (utils/elastic.py) so the restore continues
        # sample-exact at the surviving device count.
        self._elastic_plan = None
        if cfg.elastic and cfg.resume:
            cfg = self._plan_elastic(cfg)
            self.cfg = cfg
        self.policy = precision_lib.get_policy(cfg.precision)

        self.bundle = registry.create_model(
            cfg.model, num_classes=cfg.num_classes, image_size=cfg.image_size,
            seq_len=cfg.seq_len, dtype=self.policy.compute_dtype,
            param_dtype=self.policy.param_dtype, remat=cfg.remat,
            remat_policy=cfg.remat_policy,
            sp=cfg.strategy.endswith("_sp"), attn_impl=cfg.attn_impl,
            dropout=cfg.dropout,
            moe_capacity_factor=cfg.moe_capacity_factor,
            moe_top_k=cfg.moe_top_k,
            moe_dispatch_impl=cfg.moe_dispatch_impl,
            moe_combine_dtype=cfg.moe_combine_dtype,
            moe_router_dtype=cfg.moe_router_dtype,
            moe_router_impl=cfg.moe_router_impl,
            moe_ep_dispatch=cfg.moe_ep_dispatch,
            moe_ep_overlap_chunks=cfg.moe_ep_overlap_chunks,
            logits_dtype=self.policy.logits_dtype)

        # data ------------------------------------------------------------
        vocab = getattr(self.bundle.module, "vocab_size", 50257)
        data_kw = dict(image_size=cfg.image_size, seq_len=cfg.seq_len,
                       seed=cfg.seed, vocab_size=vocab)
        self.train_data = datasets_lib.build_dataset(
            cfg.dataset, cfg.data_path, train=True, **data_kw)
        self.eval_data = datasets_lib.build_dataset(
            cfg.dataset, cfg.data_path, train=False,
            require_split=cfg.evaluate, **data_kw)
        if isinstance(self.train_data, datasets_lib.TokenFileDataset):
            # Out-of-vocab ids don't crash an embedding gather — they clamp
            # and train to NaN. Fail loudly on a wrong model/data pairing.
            head = np.asarray(self.train_data.tokens[:1_000_000])
            if head.size and int(head.max()) >= vocab:
                raise ValueError(
                    f"token file {cfg.data_path!r} contains id "
                    f"{int(head.max())} >= model vocab {vocab} — wrong "
                    f"--model / --data-path pairing?")
        nproc = jax.process_count()
        dp = mesh_lib.dp_size(self.mesh)
        if cfg.global_batch_size % dp:
            raise ValueError(
                f"--batch-size {cfg.global_batch_size} must be divisible by the "
                f"data-parallel degree {dp} (mesh data x fsdp); e.g. use "
                f"{(cfg.global_batch_size // dp + 1) * dp}")
        if nproc <= dp and cfg.global_batch_size % max(nproc, 1):
            raise ValueError(
                "global batch size must divide evenly across hosts")
        # Shard the sample stream by the process's data-parallel COORDINATE
        # (loader.dp_shard): with seq/pp/ep/tp axes in the mesh, processes
        # sharing a dp coordinate must feed identical rows — otherwise each
        # host feeds its own rows into a "replicated" array and devices
        # silently compute on inconsistent copies.
        loader_shards, loader_rank = loader_lib.dp_shard(
            nproc, dp, jax.process_index())
        if cfg.grad_accum_steps > 1 and cfg.global_batch_size % (
                dp * cfg.grad_accum_steps):
            raise ValueError(
                f"--batch-size {cfg.global_batch_size} must be divisible by "
                f"data-parallel degree ({dp}) x --grad-accum "
                f"({cfg.grad_accum_steps})")
        self.local_batch = cfg.global_batch_size // loader_shards
        train_sampler = sampler_lib.ShardedSampler(
            len(self.train_data), loader_shards, loader_rank, shuffle=True,
            seed=cfg.seed, drop_last=True)
        self.train_loader = self._make_train_loader(train_sampler)
        self.eval_loader = loader_lib.DataLoader(
            self.eval_data, self.local_batch,
            sampler_lib.ShardedSampler(len(self.eval_data), loader_shards,
                                       loader_rank, shuffle=False),
            num_workers=cfg.workers, drop_last=False)

        self.steps_per_epoch = len(self.train_loader)
        if cfg.steps_per_epoch:
            self.steps_per_epoch = min(self.steps_per_epoch, cfg.steps_per_epoch)
        # epoch-keyed eval rows land on the global-step TensorBoard axis
        self.metric_logger.steps_per_epoch = self.steps_per_epoch
        if self._chaos is not None:
            # Batch-site chaos events key on the same global index as the
            # step-site ones: epoch * steps_per_epoch + batch.
            self._chaos.steps_per_epoch = self.steps_per_epoch

        # optimizer / state ------------------------------------------------
        self.tx, self.schedule = optim.build_optimizer(cfg, self.steps_per_epoch)
        # Warm the schedule's op-by-op dispatch here, inside the init span:
        # the first eager evaluation costs ~0.2s of tracing that would
        # otherwise land UNATTRIBUTED between the first step's spans and
        # drag goodput coverage below its gate.
        float(self.schedule(0))
        scaler = (precision_lib.ScalerState.create()
                  if precision_lib.needs_loss_scaling(self.policy) else None)
        model = self.bundle.module
        if cfg.strategy == "pp":
            from pytorch_distributed_training_example_tpu.parallel import pp_lm

            if not hasattr(model, "scan_layers"):
                raise ValueError("strategy 'pp' currently supports the Llama "
                                 "family (scan-stacked blocks)")
            model = pp_lm.PipelinedLlama(model, self.mesh,
                                         cfg.pp_microbatches)
            rules = pp_lm.PP_RULES
        else:
            rules = sharding_lib.strategy_rules(cfg.strategy, self.bundle.rules)
        self.state = train_loop.create_train_state(
            model, self.tx, self.bundle.input_template,
            self.mesh, rules, seed=cfg.seed, scaler=scaler)

        task = train_loop.get_task(self.bundle.task, cfg.label_smoothing)
        self.train_step = jax.jit(
            train_loop.make_train_step(task, cfg.grad_accum_steps,
                                       health=cfg.telemetry),
            donate_argnums=0)
        self.eval_step = jax.jit(train_loop.make_eval_step(task))
        self.batch_sharding = mesh_lib.batch_sharding(self.mesh)

        # checkpointing ----------------------------------------------------
        self.checkpointer = (checkpoint_lib.Checkpointer(cfg.checkpoint_dir)
                             if cfg.checkpoint_dir else None)
        self.start_epoch = 0
        self.start_step_offset = 0
        self._last_saved_step = -1
        self.resumed = False
        if cfg.resume and self.checkpointer is None:
            # --resume <path> without --checkpoint-dir: restore from (and
            # keep saving into) that path instead of silently ignoring it.
            if cfg.resume == "auto":
                raise ValueError("--resume auto needs --checkpoint-dir (or "
                                 "pass an explicit checkpoint path)")
            root, _ = checkpoint_lib.split_resume_path(cfg.resume)
            if not os.path.isdir(root):
                # Validate BEFORE Checkpointer() mkdirs it: a typo'd path
                # must not become a fresh empty checkpoint dir.
                raise FileNotFoundError(f"--resume path not found: {cfg.resume}")
            self.checkpointer = checkpoint_lib.Checkpointer(root)
        # After the resume path may have provided a save directory: the
        # step cadence needs SOMEWHERE to write (mid-epoch resume + keep
        # saving into the resume path is a supported combination).
        if cfg.checkpoint_every_steps and self.checkpointer is None:
            raise ValueError("--checkpoint-every-steps needs --checkpoint-dir "
                             "or --resume <path> (step-granular saves were "
                             "requested but there is nowhere to write them)")
        if cfg.resume and self.checkpointer:
            self._resume()

        self.profile_range = None
        if cfg.profile_steps:
            a, b = cfg.profile_steps.split(":")
            self.profile_range = (int(a), int(b))

        self.fault_inject = None
        if cfg.fault_inject:  # "rank:step" — SURVEY.md §5 fault injector
            try:
                r, s = cfg.fault_inject.split(":")
                self.fault_inject = (int(r), int(s))
            except ValueError:
                raise ValueError(
                    f"--fault-inject expects 'rank:step' (two integers "
                    f"separated by a colon), got {cfg.fault_inject!r}") from None

        n_params = sum(int(np.prod(p.shape)) for p in jax.tree.leaves(self.state.params))
        log.info("model=%s params=%.2fM devices=%d mesh=%s strategy=%s precision=%s",
                 cfg.model, n_params / 1e6, jax.device_count(),
                 dict(self.mesh.shape), cfg.strategy, cfg.precision)

    def _make_train_loader(self, sampler):
        """Prefer the C++ batch engine: in-memory uint8 arrays (CIFAR) and
        JPEG directory trees (ImageNet) both have native fast paths."""
        cfg = self.cfg
        ldr = loader_lib.build_image_loader(
            self.train_data, sampler, self.local_batch, workers=cfg.workers,
            native=cfg.native_loader)
        from pytorch_distributed_training_example_tpu.data import native_loader

        if isinstance(ldr, native_loader.NativeDataLoader):
            log.info("using native C++ batch engine for the input pipeline")
        return ldr

    # -- checkpoint glue ---------------------------------------------------

    def _plan_elastic(self, cfg: Config) -> Config:
        """Rescale the batch geometry when resuming at a changed world size.

        Reads the newest committed manifest (JSON only — no array I/O, runs
        before the model exists) and compares the recorded data-parallel
        degree against this run's mesh. All the policy math lives in
        ``utils/elastic.py``; this method just threads it into the config.
        The relaunch command always carries the ORIGINAL launch geometry
        (same argv + ``--resume auto``), so caps like ``--steps-per-epoch``
        are remapped from the launched batch size, while the plan itself
        starts from the RECORDED geometry so repeated shrinks compose.
        """
        root = cfg.checkpoint_dir
        if cfg.resume not in ("auto", None):
            root, _ = checkpoint_lib.split_resume_path(cfg.resume)
        manifest = checkpoint_lib.peek_manifest(root) if root else None
        if not manifest:
            return cfg
        recorded = dict(manifest.get("extra") or {})
        geom = manifest.get("geometry") or {}
        if "mesh_shape" not in recorded and geom.get("mesh_shape"):
            recorded["mesh_shape"] = geom["mesh_shape"]
        new_dp = mesh_lib.dp_size(self.mesh)
        if elastic_lib.recorded_world(recorded) is None:
            log.warning(
                "elastic resume: checkpoint records no source geometry "
                "(pre-elastic save) — resuming without batch rescale")
            return cfg
        plan = elastic_lib.plan_from_record(
            recorded, policy=cfg.elastic_policy, new_world=new_dp,
            fallback_global_batch=cfg.global_batch_size,
            fallback_grad_accum=cfg.grad_accum_steps)
        if plan is None:
            return cfg  # world size unchanged
        updates = {"global_batch_size": plan.global_batch_size,
                   "grad_accum_steps": plan.grad_accum_steps,
                   "lr": float(recorded.get("lr", cfg.lr)) * plan.lr_scale}
        if cfg.steps_per_epoch and plan.global_batch_size != cfg.global_batch_size:
            updates["steps_per_epoch"] = elastic_lib.remap_step_count(
                cfg.steps_per_epoch, cfg.global_batch_size,
                plan.global_batch_size)
        self._elastic_plan = plan
        log.warning("%s", plan.describe())
        return cfg.replace(**updates)

    def _resume(self):
        """``--resume`` accepts 'auto', a checkpoint root, or a step_NNN dir."""
        step = None
        directory = self.checkpointer.directory
        if self.cfg.resume not in ("auto", None):
            directory, step = checkpoint_lib.split_resume_path(self.cfg.resume)
            if step is None and not os.path.isdir(directory):
                raise FileNotFoundError(
                    f"--resume path not found: {self.cfg.resume}")
            if directory != self.checkpointer.directory:
                self.checkpointer = checkpoint_lib.Checkpointer(directory)
        if step is None and not checkpoint_lib.all_checkpoints(directory):
            log.info("resume requested but no committed checkpoint in %s", directory)
            return
        # step=None lets restore() pick the newest USABLE step: a corrupted
        # or manifest-less latest checkpoint falls back to the previous
        # committed one (with a loud warning) instead of crashing the resume.
        with self._span("checkpoint_restore"):
            self.state, extra = self.checkpointer.restore(self.state, step)
        step = self.checkpointer.last_restored_step
        epoch = int(extra.get("epoch", -1))
        # Epoch-boundary checkpoints carry no step_offset (the epoch is
        # complete); mid-epoch ones record how many steps of `epoch` were
        # already applied, and the sampler — a pure function of
        # (seed, epoch) — regenerates the identical permutation, so
        # fast-forwarding the index stream is sample-exact.
        raw_offset = extra.get("step_offset")
        offset = (self.steps_per_epoch if raw_offset is None
                  else int(raw_offset))
        if raw_offset is not None and self._elastic_plan is not None:
            # Elastic resume: the recorded offset counts optimizer steps of
            # the SAVING geometry. Convert it through the sample position
            # (offset * old_gb must be a whole number of new batches —
            # remap_step_offset raises otherwise), so the loader continues
            # at the exact next unconsumed sample.
            rec_gb = int(extra.get("global_batch_size",
                                   self.cfg.global_batch_size))
            if rec_gb != self.cfg.global_batch_size:
                remapped = elastic_lib.remap_step_offset(
                    offset, rec_gb, self.cfg.global_batch_size)
                log.warning(
                    "elastic resume: mid-epoch offset %d (gb %d) -> %d "
                    "(gb %d); sample position %d preserved", offset, rec_gb,
                    remapped, self.cfg.global_batch_size, offset * rec_gb)
                offset = remapped
            rec_spe = extra.get("steps_per_epoch")
            if rec_spe is not None and (
                    int(rec_spe) * rec_gb !=
                    self.steps_per_epoch * self.cfg.global_batch_size):
                log.warning(
                    "elastic resume: epoch sample count changed (%d -> %d "
                    "samples/epoch) — epoch boundaries shift at the dataset "
                    "tail", int(rec_spe) * rec_gb,
                    self.steps_per_epoch * self.cfg.global_batch_size)
        if offset < self.steps_per_epoch:
            if self._elastic_plan is None:
                # Mid-epoch restore: the offset counts optimizer steps of the
                # SAVING run's batch geometry. Resuming with a different
                # --batch-size (or a loader that slices the epoch differently)
                # would fast-forward to the wrong sample silently — refuse
                # (pass --elastic to convert the offset instead).
                for key, current in (("global_batch_size",
                                      self.cfg.global_batch_size),
                                     ("steps_per_epoch", self.steps_per_epoch)):
                    recorded = extra.get(key)
                    if recorded is None:
                        log.warning(
                            "checkpoint predates %s recording; cannot verify "
                            "the mid-epoch offset matches this run's batch "
                            "geometry", key)
                    elif int(recorded) != current:
                        raise ValueError(
                            f"mid-epoch resume with mismatched {key}: checkpoint "
                            f"was saved with {int(recorded)}, this run uses "
                            f"{current}. The step offset {offset} would land on "
                            "the wrong sample; resume with the original batch "
                            "geometry, restart from an epoch boundary, or pass "
                            "--elastic to rescale under a batch policy.")
            self.start_epoch = epoch
            self.start_step_offset = offset
            log.info("resumed from step %d (epoch %d, step offset %d)",
                     step, epoch, offset)
        else:
            self.start_epoch = epoch + 1
            self.start_step_offset = 0
            log.info("resumed from step %d (epoch %d)", step, self.start_epoch)
        self.resumed = True

    def _save(self, epoch: int, step_offset: int | None = None,
              block: bool = False):
        if self.checkpointer is None:
            return
        step = int(jax.device_get(self.state.step))
        if step == self._last_saved_step:
            return  # the step cadence already wrote this exact state
        # Batch geometry travels with the checkpoint: a mid-epoch resume
        # fast-forwards the sampler by step_offset * global_batch samples,
        # which is only sample-exact if the restore run slices the epoch
        # the same way (_resume validates).
        extra = {"epoch": epoch,
                 "global_batch_size": self.cfg.global_batch_size,
                 "steps_per_epoch": self.steps_per_epoch,
                 # Elastic-resume provenance (utils/elastic.py): the geometry
                 # that produced this state, so a different-world relaunch can
                 # rescale from what was actually running — repeated shrinks
                 # compose, and scaled LR carries forward.
                 "mesh_shape": {str(k): int(v)
                                for k, v in dict(self.mesh.shape).items()},
                 "grad_accum": self.cfg.grad_accum_steps,
                 "lr": self.cfg.lr}
        if step_offset is not None:
            extra["step_offset"] = step_offset
        # One retry: save() first joins the previous background write, so a
        # CheckpointWriteError here may be THAT save's failure surfacing —
        # either way the right response is to try writing the current state
        # once more, then let a persistent failure propagate.
        for attempt in (1, 2):
            try:
                with self._span("checkpoint_save"):
                    if self._chaos is not None:
                        self._chaos.before_save()
                    self.checkpointer.save(self.state, step, extra=extra,
                                           block=block)
                    if self._chaos is not None:
                        self._chaos.after_save(self.checkpointer)
                break
            except checkpoint_lib.CheckpointWriteError as e:
                if attempt == 2:
                    raise
                log.error("checkpoint save for step %d failed (%s) — "
                          "retrying once", step, e)
        self._last_saved_step = step
        if self.telemetry is not None:
            # Flush the goodput/timeline files alongside every durable save:
            # an ABRUPT host loss (chaos kill_host, real hardware) writes no
            # shutdown summary, so the restart-tax merge in the next attempt
            # measures its gap from the last flush here.
            self.telemetry.write_artifacts()

    # -- resilience --------------------------------------------------------

    def _graceful_shutdown(self, epoch: int, step_offset: int):
        """Act on a preemption signal at a step/epoch boundary: make the
        current state durable, then exit with the distinct preemption code.

        Raises :class:`resilience.PreemptedExit` (a SystemExit), so
        ``train()``'s finally still emits the telemetry goodput summary and
        closes the metric logger on the way out; a supervisor
        (``launch.py --restart-policy``) relaunches ``--resume auto`` on
        :data:`resilience.PREEMPTED_EXIT_CODE`.
        """
        log.warning(
            "preemption (signal %s): emergency checkpoint at epoch %d step "
            "offset %d, then exit %d", resilience.preempt_signal(), epoch,
            step_offset, resilience.PREEMPTED_EXIT_CODE)
        if self.checkpointer is not None:
            try:
                self.checkpointer.wait()  # join any in-flight background save
            except checkpoint_lib.CheckpointWriteError as e:
                # That save never committed — its step id must not dedupe
                # the emergency save below.
                log.error("in-flight save failed during shutdown (%s)", e)
                self._last_saved_step = -1
            self._save(epoch, step_offset=step_offset, block=True)
            log.warning("emergency checkpoint committed — exiting")
        if self.telemetry is not None:
            # Post-mortems of preempted runs start from the flight recorder,
            # not an empty log: dump the last-N step records before exiting.
            self.telemetry.flight_dump("preempt", epoch=int(epoch),
                                       step_offset=int(step_offset))
        raise resilience.PreemptedExit()

    def _anomaly_rollback(self, epoch: int, i: int) -> int:
        """``anomaly_action="rollback"``: restore the last committed
        checkpoint and return the batch index to continue from.

        The poisoned batch was consumed exactly once (its update is being
        discarded with the restore), so continuing at ``i + 1`` keeps the
        run's yielded-index log identical to an uninterrupted run's.
        Escalates to :class:`AnomalyError` once ``rollback_budget`` is
        exhausted or when there is nothing to restore — a model that keeps
        going non-finite after restores has a real problem, not a blip.
        """
        cfg = self.cfg
        self._rollbacks += 1
        if self._rollbacks > cfg.rollback_budget:
            raise telemetry_lib.AnomalyError(
                f"anomaly rollback budget exhausted "
                f"({cfg.rollback_budget}): still hitting non-finite health "
                f"scalars after {cfg.rollback_budget} restore(s) — aborting")
        if self.checkpointer is None:
            raise telemetry_lib.AnomalyError(
                "anomaly_action=rollback needs --checkpoint-dir (nothing "
                "to restore from)")
        try:
            self.checkpointer.wait()  # don't race an in-flight save
        except checkpoint_lib.CheckpointWriteError as e:
            log.error("in-flight save failed before rollback (%s)", e)
            self._last_saved_step = -1
        # Newest-first over committed steps, VALIDATING each restored state:
        # a step-cadence save that landed at/after the poisoned batch is
        # committed and CRC-clean yet contains non-finite params — restoring
        # it would just re-trip the guard until the budget aborts. Such a
        # checkpoint is quarantined so a later --resume cannot pick it either.
        restored_step = None
        for cand in sorted(checkpoint_lib.all_checkpoints(
                self.checkpointer.directory), reverse=True):
            try:
                with self._span("checkpoint_restore"):
                    state, _ = self.checkpointer.restore(self.state, cand)
            except (checkpoint_lib.CheckpointCorruptError, OSError,
                    json.JSONDecodeError, KeyError) as e:
                log.error("rollback: checkpoint step %d unusable (%s: %s) — "
                          "trying an older one", cand, type(e).__name__, e)
                continue
            if all(bool(jnp.isfinite(x).all())
                   for x in jax.tree.leaves(state.params)):
                self.state = state
                restored_step = cand
                break
            log.warning(
                "rollback: checkpoint step %d itself has non-finite params "
                "(saved after the poisoned batch) — quarantining and trying "
                "an older one", cand)
            if distributed.is_main_process():
                self.checkpointer.quarantine(cand)
        if restored_step is None:
            raise telemetry_lib.AnomalyError(
                "anomaly_action=rollback: no committed checkpoint with "
                "finite params to restore")
        log.warning(
            "anomaly rollback %d/%d: restored step %d, continuing at epoch "
            "%d batch %d", self._rollbacks, cfg.rollback_budget,
            restored_step, epoch, i + 1)
        # The restored optimizer step count will re-pass ids the cadence
        # already saved; clear the dedupe so those saves are not skipped.
        self._last_saved_step = -1
        return i + 1

    # -- loops -------------------------------------------------------------

    def train(self):
        cfg = self.cfg
        # Preemption-safe shutdown: SIGTERM/SIGINT only set a flag here; the
        # step loop polls it at step boundaries and runs _graceful_shutdown
        # (finish in-flight step -> blocking emergency checkpoint -> goodput
        # emit via the finally below -> exit PREEMPTED_EXIT_CODE). No-op off
        # the main thread (install() warns and returns False).
        resilience.install()
        # One run-level watchdog spanning train AND eval (both loops beat it,
        # so a long eval never false-triggers); its timeout dump carries the
        # telemetry snapshot — last step, last health row, goodput — when on.
        self._watchdog = watchdog_lib.Watchdog(
            timeout_s=cfg.watchdog_timeout,
            context_fn=(self.telemetry.snapshot
                        if self.telemetry is not None else None)).start()
        try:
            for epoch in range(self.start_epoch, cfg.epochs):
                self.train_epoch(epoch)
                if resilience.preempted():
                    # Tripped during the epoch's tail or between loops (e.g.
                    # mid-eval next iteration): the epoch is complete, so the
                    # emergency save is an epoch-boundary one.
                    self._graceful_shutdown(epoch, self.steps_per_epoch)
                if (epoch + 1) % cfg.eval_every_epochs == 0:
                    self.evaluate(epoch)
                if (epoch + 1) % cfg.checkpoint_every_epochs == 0:
                    self._save(epoch)
                if self.telemetry is not None:
                    g = self.telemetry.emit(f"epoch {epoch}")
                    self.metric_logger.write(
                        kind="goodput", epoch=epoch, wall_s=g["wall_s"],
                        goodput_fraction=g["goodput_fraction"],
                        badput_fraction=g["badput_fraction"],
                        coverage=g["coverage"],
                        **{f"frac_{k}": v for k, v in g["fractions"].items()})
            if self.checkpointer:
                self.checkpointer.wait()
        finally:
            self._watchdog.stop()
            self._watchdog = None
            if self.telemetry is not None:
                # Shutdown emit runs even on an anomaly abort, so the
                # timeline + goodput files always reflect the full run.
                self.telemetry.emit("shutdown")
            if distributed.is_main_process() and self._progress_dir:
                try:
                    fleetobs.write_progress(
                        self._progress_dir,
                        {**self._progress, "status": "shutdown"})
                except OSError:
                    pass
            if self._metrics_server is not None:
                self._metrics_server.stop()
                self._metrics_server = None
            self.metric_logger.close()
        return self.state

    def train_epoch(self, epoch: int):
        cfg = self.cfg
        self.train_loader.set_epoch(epoch)
        # Resumed mid-epoch: skip the already-trained prefix of this epoch's
        # (deterministic) index stream; every later epoch starts at 0.
        self.train_loader.start_batch = (
            self.start_step_offset if epoch == self.start_epoch else 0)
        loss_m = AverageMeter("loss")
        tput = Throughput()
        t_step = time.perf_counter()
        # train() owns the run-level watchdog; a direct train_epoch() call
        # (tests, notebooks) gets a per-epoch one with the same context hook.
        watchdog = self._watchdog
        own_watchdog = watchdog is None
        if own_watchdog:
            watchdog = watchdog_lib.Watchdog(
                timeout_s=cfg.watchdog_timeout,
                context_fn=(self.telemetry.snapshot
                            if self.telemetry is not None else None)).start()
        try:
            self._train_epoch_inner(epoch, loss_m, tput, t_step, watchdog)
        finally:
            if own_watchdog:
                watchdog.stop()
            errs = getattr(getattr(self.train_loader, "engine", None),
                           "decode_errors", None)
            if errs is not None and errs() > 0:
                log.warning("native loader: %d image(s) failed to decode "
                            "(zero-filled)", errs())

    def _make_step_iter(self, epoch, start):
        """(Re)build the prefetched batch iterator from batch ``start``.

        Separate from the epoch loop so the anomaly-rollback path can tear
        the pipeline down and rebuild it past the poisoned batch window —
        the loader's index stream is a pure function of (seed, epoch, start),
        so this is sample-exact.
        """
        self.train_loader.start_batch = start
        return prefetch.device_prefetch(self.train_loader, self.batch_sharding)

    def _train_epoch_inner(self, epoch, loss_m, tput, t_step, watchdog):
        cfg = self.cfg
        tele = self.telemetry
        it = self._make_step_iter(epoch, self.train_loader.start_batch)
        with mesh_lib.use_mesh(self.mesh):
            i = self.train_loader.start_batch
            # Per-step host timings for the fleet layer (straggler detection,
            # flight recorder): pure perf_counter deltas around phases the
            # loop already runs — no extra device syncs at any cadence.
            t_iter = time.perf_counter()
            while i < self.steps_per_epoch:
                t_wait = time.perf_counter()
                # Host wait on the input pipeline is its own badput bucket —
                # with the prefetcher keeping up this span is ~0.
                with self._span("input_wait"):
                    try:
                        batch = next(it)
                    except StopIteration:
                        break
                input_wait_s = time.perf_counter() - t_wait
                watchdog.beat()
                gstep = epoch * self.steps_per_epoch + i
                if (self.fault_inject
                        and jax.process_index() == self.fault_inject[0]
                        and gstep == self.fault_inject[1]):
                    # Simulated host failure: no cleanup, no flushes — the
                    # hardest crash shape recovery must handle.
                    log.error("fault injection: killing process %d at step %d",
                              *self.fault_inject)
                    os._exit(57)
                if self.profile_range and gstep == self.profile_range[0]:
                    jax.profiler.start_trace(cfg.profile_dir)
                if not self._compiled:
                    # First dispatch ever traces + compiles; block so the
                    # "compile" span covers it (dispatch is async — without
                    # the block the cost would leak into later step spans).
                    with self._span("compile"):
                        metrics = self._first_dispatch(batch)
                        jax.tree.map(lambda x: x.block_until_ready(), metrics)
                    self._compiled = True
                    if tele is not None:
                        # Time-to-first-step: wall from process start to the
                        # first completed optimizer step, cold vs warm.
                        tele.mark_first_step(self._xcache_mode)
                else:
                    with self._span("step"):
                        self.state, metrics = self.train_step(self.state, batch)
                if self.profile_range and gstep + 1 == self.profile_range[1]:
                    jax.tree.map(lambda x: x.block_until_ready(), metrics)
                    jax.profiler.stop_trace()
                    log.info("profile written to %s", cfg.profile_dir)
                tput.update(cfg.global_batch_size)
                is_log = ((i + 1) % cfg.log_every == 0
                          or i + 1 == self.steps_per_epoch)
                is_health = (tele is not None and cfg.health_every > 0
                             and (i + 1) % cfg.health_every == 0)
                if is_log or is_health:
                    # The fetch drains the async step queue: that wait IS
                    # device step time, so it stays in the "step" bucket.
                    with self._span("step"):
                        m = {k: float(v)
                             for k, v in jax.device_get(metrics).items()}
                    if tele is not None:
                        # May raise AnomalyError (anomaly_action="abort")
                        # after writing the diagnostic bundle.
                        tripped = tele.observe(gstep, {"epoch": epoch, **m})
                        if tripped and cfg.anomaly_action == "rollback":
                            it.close()
                            i = self._anomaly_rollback(epoch, i)
                            it = self._make_step_iter(epoch, i)
                            t_step = t_iter = time.perf_counter()
                            continue
                    if not is_log:
                        self.metric_logger.write(kind="health", epoch=epoch,
                                                 step=gstep, **m)
                checkpoint_s = 0.0
                if (cfg.checkpoint_every_steps
                        and (gstep + 1) % cfg.checkpoint_every_steps == 0):
                    # Step-cadence save: records (epoch, steps applied) so
                    # resume fast-forwards to the exact next sample. Runs
                    # even at the epoch boundary — eval may take a long
                    # time, and the boundary state must be durable before
                    # it; the per-epoch save then dedupes on step id.
                    # AFTER the health fetch above: a state the anomaly
                    # guard just flagged (rollback `continue`d, abort
                    # raised) must never be the checkpoint a restart
                    # resumes into.
                    t_save = time.perf_counter()
                    self._save(epoch, step_offset=i + 1)
                    checkpoint_s = time.perf_counter() - t_save
                if is_log:
                    loss_m.update(m["loss"])
                    lr = float(self.schedule(gstep))
                    dt = (time.perf_counter() - t_step) / cfg.log_every
                    t_step = time.perf_counter()
                    rate = tput.rate
                    per_chip = rate / max(jax.device_count(), 1)
                    mfu = metrics_lib.mfu(per_chip, self.bundle.fwd_flops_per_example)
                    log.info(
                        "epoch %d step %d/%d loss %.4f lr %.2e %s/s %.1f "
                        "(%.1f/chip) mfu %.1f%% %s",
                        epoch, i + 1, self.steps_per_epoch, m["loss"], lr,
                        self.bundle.examples_unit, rate, per_chip, 100 * mfu,
                        " ".join(f"{k} {v:.4f}" for k, v in m.items()
                                 if k not in ("loss",)),
                    )
                    self.metric_logger.write(kind="train", epoch=epoch, step=gstep,
                                             lr=lr, rate=rate, mfu=mfu, **m)
                    self._publish(gstep, epoch, m, dt)
                now = time.perf_counter()
                if tele is not None:
                    # Feed the fleet layer every step: flight-recorder ring,
                    # buffered step rows, live straggler monitor (warn-only).
                    tele.observe_timing(gstep, total_s=now - t_iter,
                                        input_wait_s=input_wait_s,
                                        checkpoint_s=checkpoint_s,
                                        epoch=epoch)
                t_iter = now
                if self._chaos is not None:
                    self._chaos.step_boundary(gstep)
                # Preemption poll — the ONLY place the SIGTERM flag is acted
                # on, so the in-flight step always completes first and the
                # emergency checkpoint is taken at a clean step boundary.
                if resilience.preempted():
                    self._graceful_shutdown(epoch, i + 1)
                i += 1

    def _first_dispatch(self, batch):
        """Run the first step, consulting the persistent executable cache.

        With ``--xcache`` + a checkpoint dir, the ``lower().compile()``
        front-end is keyed on a topology/knob/aval fingerprint
        (core/xcache.py): a hit deserializes the compiled executable and
        skips XLA entirely; a miss compiles AOT and serializes the result
        for the next attempt. Either way the compiled executable replaces
        ``self.train_step`` for the rest of the run — an AOT call never
        populates jit's dispatch cache, so leaving the jit wrapper in
        place would re-trace on step 2.
        """
        cfg = self.cfg
        root = (self.checkpointer.directory
                if cfg.xcache and self.checkpointer is not None else None)
        if root is None:
            self.state, metrics = self.train_step(self.state, batch)
            return metrics
        fields = xcache_lib.fingerprint(mesh=self.mesh, config=cfg,
                                        example_args=(self.state, batch))
        compiled = xcache_lib.load(root, fields, example=(self.state, batch))
        if compiled is not None:
            try:
                self.state, metrics = compiled(self.state, batch)
                self.train_step = compiled
                self._xcache_mode = "warm"
                return metrics
            except Exception as e:  # noqa: BLE001 — never a stale executable
                # The fingerprint should make this unreachable; if the
                # deserialized executable still rejects our inputs, refuse
                # it loudly and compile cold rather than trust it.
                log.error("xcache: cached executable rejected our inputs "
                          "(%s: %s) — falling back to cold compile",
                          type(e).__name__, e)
        lowered = self.train_step.lower(self.state, batch)
        compiled = lowered.compile()
        self.state, metrics = compiled(self.state, batch)
        # Save AFTER the first execution: the metrics pytree is part of the
        # entry (reconstruct tree mode) and only exists once the step ran.
        xcache_lib.save(root, fields, compiled,
                        example=(self.state, batch), metrics=metrics)
        self.train_step = compiled
        return metrics

    def _publish(self, gstep: int, epoch: int, m: dict, dt: float):
        """Refresh the live metrics surface (rank 0, log cadence): the
        Prometheus gauges and the atomically-replaced progress.json."""
        if not distributed.is_main_process() or self._progress_dir is None:
            return
        row = {"step": int(gstep), "epoch": int(epoch),
               "loss": float(m.get("loss", 0.0)), "step_time_s": float(dt)}
        if self.telemetry is not None:
            g = self.telemetry.recorder.goodput()
            row.update(
                run_id=self.telemetry.run_id,
                goodput_fraction=g["goodput_fraction"],
                goodput_coverage=g["coverage"],
                attempt=g["attempts"],
                straggler_warnings=self.telemetry.guard.warnings,
                anomaly_count=self.telemetry.guard.trips)
            if g.get("time_to_first_step_s") is not None:
                # Renders as the pdtx_ttfs_seconds gauge on /metrics.
                row["ttfs_seconds"] = g["time_to_first_step_s"]
        self._progress = row
        if self._metrics_server is not None:
            self._metrics_server.update(**row)
        try:
            fleetobs.write_progress(self._progress_dir,
                                    {**row, "status": "training"})
        except OSError as e:
            log.warning("progress.json write failed (%s)", e)

    def evaluate(self, epoch: int):
        sums: dict[str, float] = {}
        n_batches = 0
        padded = (prefetch.pad_batch(b, self.local_batch) for b in self.eval_loader)
        with self._span("eval"), mesh_lib.use_mesh(self.mesh):
            for batch in prefetch.device_prefetch(padded, self.batch_sharding):
                if self._watchdog is not None:
                    self._watchdog.beat()
                stats = self.eval_step(self.state, batch)
                m = {k: float(v) for k, v in jax.device_get(stats).items()}
                for k, v in m.items():
                    sums[k] = sums.get(k, 0.0) + v
                n_batches += 1
                if self.cfg.steps_per_epoch and n_batches >= self.cfg.steps_per_epoch:
                    break
        if n_batches:
            count = max(sums.get("count", 0.0), 1.0)
            avg = metrics_lib.finalize_eval_sums(sums)
            log.info("eval epoch %d %s (n=%d)", epoch,
                     " ".join(f"{k} {v:.4f}" for k, v in avg.items()), int(count))
            self.metric_logger.write(kind="eval", epoch=epoch, count=count, **avg)
            return avg
        return {}
