"""Background checkpoint re-shard — restore-ready layout before the restart.

The second half of the restart tax (``core/xcache.py`` docstring owns the
first): an elastic relaunch restores a checkpoint written under the OLD
topology, so ``_assemble_sharded`` re-slices N region files per leaf through
mmap intersection while the new attempt's devices sit idle. But the elastic
supervisor (``launch.py``) knows the surviving world the moment it reads
``dead_hosts.jsonl`` — *before* the restart backoff ends — so this module
runs in a background subprocess during that window and rewrites the newest
committed checkpoint into a **consolidated layout**: one contiguous
full-leaf file per array, exactly what a fresh restore at any topology
assembles fastest (every target shard is one contiguous read from one file
instead of an intersection over the old world's regions).

Integrity discipline mirrors ``core/checkpoint.py`` end to end:

- every source region file is CRC-verified against the manifest BEFORE any
  output is written; a mismatch quarantines the source step
  (``step_X.corrupt``, resume-ineligible) and exits loudly — a torn source
  must never launder into a fresh-looking consolidated copy;
- output is written into a ``step_X.saving.reshard`` attempt dir with
  per-file CRCs in a rewritten manifest, ``COMMIT`` written inside, then
  swapped over the source via the ``.old`` set-aside rename pair (healed by
  ``Checkpointer._recover_interrupted_replace`` if interrupted), so at every
  instant a committed copy of the step exists;
- the manifest's ``extra`` dict is preserved VERBATIM — it records the
  *saving* geometry (global batch, mesh shape) that elastic batch-rescale
  planning starts from; only ``geometry`` (which layout is on disk) is
  updated, plus a ``resharded`` marker recording the target world and the
  source geometry for provenance.

Deliberately jax-free (numpy + ``retriable_io``): the supervisor spawns it
as ``python -m ...core.reshard --checkpoint-dir D --world N`` during the
restart backoff, and it must come up in milliseconds, not pay a jax import.

Exit codes: 0 = re-sharded (or already consolidated), 3 = nothing
committed to re-shard, 4 = source corrupt (quarantined), 1 = other error.
"""

from __future__ import annotations

import argparse
import json
import logging
import os
import re
import shutil
import sys
import zlib

import numpy as np

from pytorch_distributed_training_example_tpu.utils.resilience import (
    retriable_io)

log = logging.getLogger("pdtx")

# Mirrors core/checkpoint.py (not imported: that module imports jax).
COMMIT_FILE = "COMMIT"
MANIFEST_FILE = "manifest.json"
OLD_SUFFIX = ".old"
RESHARD_SUFFIX = ".saving.reshard"
_STEP_RE = re.compile(r"^step_(\d+)$")


def _file_crc32(path: str) -> int:
    crc = 0
    with open(path, "rb") as fh:
        for chunk in iter(lambda: fh.read(1 << 20), b""):
            crc = zlib.crc32(chunk, crc)
    return crc & 0xFFFFFFFF


def _read_json(path: str):
    with open(path) as fh:
        return json.load(fh)


def committed_steps(directory: str) -> list[int]:
    """Committed steps with a parseable manifest, ascending."""
    out = []
    try:
        names = retriable_io(os.listdir, directory, _what="reshard read")
    except OSError:
        return []
    for name in names:
        m = _STEP_RE.match(name)
        if not m:
            continue
        step_dir = os.path.join(directory, name)
        if not os.path.exists(os.path.join(step_dir, COMMIT_FILE)):
            continue
        try:
            _read_json(os.path.join(step_dir, MANIFEST_FILE))
        except (OSError, ValueError):
            continue
        out.append(int(m.group(1)))
    return sorted(out)


def _union_file_lists(step_dir: str, manifest: dict) -> dict:
    """Merge per-host ``files.p*.json`` sentinels into the manifest's lists
    (same union restore performs)."""
    leaves = manifest["leaves"]
    for fn in retriable_io(os.listdir, step_dir, _what="reshard read"):
        if fn.startswith("files.p") and fn.endswith(".json"):
            extra_files = retriable_io(
                _read_json, os.path.join(step_dir, fn), _what="reshard read")
            for p, files in extra_files.items():
                known = {e["file"] for e in leaves[p]["files"]}
                leaves[p]["files"] += [e for e in files
                                       if e["file"] not in known]
    return leaves


def _verify_sources(step_dir: str, leaves: dict) -> str | None:
    """CRC every source region file; returns an error string on mismatch."""
    arrays_dir = os.path.join(step_dir, "arrays")
    checked: set[str] = set()
    for path, meta in leaves.items():
        for entry in meta["files"]:
            fname = entry["file"]
            if "crc32" not in entry or fname in checked:
                continue
            checked.add(fname)
            fpath = os.path.join(arrays_dir, fname)
            try:
                got = retriable_io(_file_crc32, fpath, _what="reshard crc")
            except OSError as e:
                return f"{fname}: unreadable ({e})"
            if got != int(entry["crc32"]):
                return (f"{fname}: CRC mismatch (manifest "
                        f"{int(entry['crc32']):#010x}, file {got:#010x})")
    return None


def _assemble_full(arrays_dir: str, meta: dict) -> np.ndarray:
    """Materialize one whole leaf from its region files (mmap reads, so
    only each region's bytes are touched; peak memory is one full leaf —
    this runs host-side in the supervisor's background process, never on a
    training host's budget)."""
    full = np.empty(meta["shape"], dtype=np.dtype(meta["dtype"]))
    for entry in meta["files"]:
        region = retriable_io(
            np.load, os.path.join(arrays_dir, entry["file"]), mmap_mode="r",
            _what="reshard read")
        if full.ndim == 0:
            full = np.array(region).reshape(())
        else:
            full[tuple(slice(a, b) for a, b in entry["index"])] = region
    return full


def reshard_step(directory: str, step: int, world: int) -> bool:
    """Consolidate ``step_<step>`` to one file per leaf; True on success.

    Raises ``ValueError`` after quarantining the source when a region file
    fails CRC verification.
    """
    step_dir = os.path.join(directory, f"step_{step:08d}")
    manifest = retriable_io(
        _read_json, os.path.join(step_dir, MANIFEST_FILE),
        _what="reshard read")
    leaves = _union_file_lists(step_dir, manifest)
    if manifest.get("resharded") and all(
            len(m["files"]) <= 1 for m in leaves.values()):
        log.info("reshard: step %d already consolidated — nothing to do",
                 step)
        return True

    err = _verify_sources(step_dir, leaves)
    if err is not None:
        quarantined = f"{step_dir}.corrupt"
        retriable_io(os.rename, step_dir, quarantined,
                     _what="reshard quarantine")
        log.error("reshard: source checkpoint step %d FAILED verification "
                  "(%s) — quarantined -> %s; the next restore falls back to "
                  "an older committed step", step, err, quarantined)
        raise ValueError(f"source step {step} corrupt: {err}")

    tmp = step_dir + RESHARD_SUFFIX
    shutil.rmtree(tmp, ignore_errors=True)
    arrays_src = os.path.join(step_dir, "arrays")
    arrays_out = os.path.join(tmp, "arrays")
    retriable_io(os.makedirs, arrays_out, exist_ok=True,
                 _what="reshard write")
    new_leaves: dict = {}
    for path, meta in leaves.items():
        full = _assemble_full(arrays_src, meta)
        safe = path.replace("/", ".")
        fname = f"{safe}.p0.0.npy"
        fpath = os.path.join(arrays_out, fname)
        retriable_io(np.save, fpath, full, _what="reshard write")
        index = ([[0, s] for s in full.shape] if full.ndim else [[0, 0]])
        new_leaves[path] = {
            "shape": list(meta["shape"]), "dtype": str(meta["dtype"]),
            "files": [{"file": fname, "index": index,
                       "crc32": retriable_io(_file_crc32, fpath,
                                             _what="reshard crc")}]}
        del full

    src_geometry = manifest.get("geometry") or {}
    new_manifest = {
        "step": manifest.get("step", step),
        # Preserved verbatim: the SAVING geometry elastic rescale plans from.
        "extra": manifest.get("extra", {}),
        # What is on disk now: one host wrote one full-leaf region per array.
        "geometry": {"process_count": 1, "device_count": int(world)},
        "resharded": {"world": int(world),
                      "source_geometry": src_geometry},
        "leaves": new_leaves,
    }

    def _write_json(path, obj):
        with open(path, "w") as fh:
            json.dump(obj, fh)

    retriable_io(_write_json, os.path.join(tmp, MANIFEST_FILE), new_manifest,
                 _what="reshard write")

    def _write_commit():
        with open(os.path.join(tmp, COMMIT_FILE), "w") as fh:
            fh.write(str(step))

    retriable_io(_write_commit, _what="reshard commit")
    # The .old set-aside swap (core/checkpoint.py save discipline): a crash
    # between the renames is healed at next startup, and a committed copy of
    # the step exists at every instant.
    old_dir = step_dir + OLD_SUFFIX
    shutil.rmtree(old_dir, ignore_errors=True)
    retriable_io(os.rename, step_dir, old_dir, _what="reshard swap")
    retriable_io(os.rename, tmp, step_dir, _what="reshard swap")
    shutil.rmtree(old_dir, ignore_errors=True)
    n_files = sum(len(m["files"]) for m in leaves.values())
    log.warning("reshard: step %d consolidated for world %d — %d region "
                "files -> %d full-leaf files", step, world, n_files,
                len(new_leaves))
    return True


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        description="Background checkpoint re-shard (launch.py elastic)")
    p.add_argument("--checkpoint-dir", required=True)
    p.add_argument("--world", type=int, required=True,
                   help="surviving world size the relaunch targets")
    p.add_argument("--step", type=int, default=None,
                   help="explicit step (default: newest committed)")
    args = p.parse_args(argv)
    logging.basicConfig(level=logging.INFO,
                        format="%(levelname).1s reshard: %(message)s",
                        stream=sys.stderr)
    steps = committed_steps(args.checkpoint_dir)
    step = args.step if args.step is not None else (steps[-1] if steps else None)
    if step is None or (args.step is not None and args.step not in steps):
        log.warning("reshard: no committed checkpoint in %s — nothing to "
                    "re-shard", args.checkpoint_dir)
        return 3
    try:
        reshard_step(args.checkpoint_dir, step, args.world)
    except ValueError:
        return 4
    except OSError as e:
        log.error("reshard: failed (%s) — the relaunch restores the "
                  "original layout instead", e)
        shutil.rmtree(
            os.path.join(args.checkpoint_dir,
                         f"step_{step:08d}{RESHARD_SUFFIX}"),
            ignore_errors=True)
        return 1
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
