"""Train state pytree: params + optimizer state + step, as one shardable value.

Reference parity: the reference's mutable trio (``model`` module, ``optimizer``,
``scaler``) becomes one immutable pytree threaded through a pure, jitted
``train_step``. Sharding the state *is* the parallelism strategy; donating it
to the step makes updates in-place in HBM.
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import optax
from flax import struct


class TrainState(struct.PyTreeNode):
    step: jax.Array
    params: Any
    opt_state: Any
    tx: optax.GradientTransformation = struct.field(pytree_node=False)
    apply_fn: Callable = struct.field(pytree_node=False)
    rng: Any = None          # base PRNG key; per-step keys are fold_in(rng, step)
    batch_stats: Any = None  # BatchNorm running stats (ResNet family); None otherwise
    scaler: Any = None       # precision.ScalerState when fp16 loss-scaling is on

    @classmethod
    def create(cls, *, apply_fn, params, tx, rng=None, batch_stats=None, scaler=None):
        import jax.numpy as jnp

        return cls(
            step=jnp.zeros((), jnp.int32),
            params=params,
            opt_state=tx.init(params),
            rng=rng,
            batch_stats=batch_stats,
            scaler=scaler,
            tx=tx,
            apply_fn=apply_fn,
        )

    def apply_gradients(self, grads, **updates) -> "TrainState":
        upd, new_opt_state = self.tx.update(grads, self.opt_state, self.params)
        new_params = optax.apply_updates(self.params, upd)
        return self.replace(
            step=self.step + 1,
            params=new_params,
            opt_state=new_opt_state,
            **updates,
        )
