"""Named device-mesh construction — the TPU-native replacement for process groups.

Reference parity (SURVEY.md §2d): the reference's communication substrate is a
c10d ``ProcessGroup`` over NCCL, created by ``init_process_group('nccl')``.
On TPU the substrate is the XLA partitioner over a :class:`jax.sharding.Mesh`:
you never hand-write transport code — you declare *named axes* and shardings
and XLA emits ICI/DCN collectives inside the compiled step.

Axis convention (DCN-major ordering — the outermost axis crosses the slowest
interconnect, so pure data-parallel gradient reduction is what rides DCN in
multislice, while TP/CP collectives stay on ICI):

    data    — pure data parallelism (gradient psum; replicated params)
    fsdp    — data parallelism with parameter/optimizer sharding (ZeRO-3)
    stage   — pipeline-parallel stage axis
    expert  — MoE expert parallelism
    context — sequence/context parallelism (ring attention / Ulysses)
    model   — tensor (Megatron-style) model parallelism

A batch is sharded over ``('data','fsdp')`` jointly; any axis of size 1 is
free (GSPMD ignores it), so one 6-axis mesh serves every strategy.
"""

from __future__ import annotations

import contextlib
import dataclasses
import logging
import math
import threading
from typing import Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

log = logging.getLogger("pdtx")

AXES: tuple[str, ...] = ("data", "fsdp", "stage", "expert", "context", "model")

#: Axes over which the batch dimension is sharded (both are "data parallel"
#: axes from the input pipeline's point of view).
BATCH_AXES: tuple[str, ...] = ("data", "fsdp")

#: Spelling aliases accepted in mesh-spec dicts (CLI ``--mesh seq=4``,
#: SNIPPETS.md [3]'s rules vocabulary). The canonical axis names stay AXES —
#: aliases are normalized before MeshConfig is built so every downstream
#: consumer (rule tables, shard_map axis names, the AOT census) sees one
#: spelling.
AXIS_ALIASES: dict[str, str] = {"seq": "context", "cp": "context",
                                "tp": "model", "ep": "expert",
                                "pp": "stage"}


def normalize_axes(spec: dict) -> dict:
    """Map aliased axis names in a mesh-spec dict onto the canonical AXES.

    Raises when an alias and its canonical name are both given (ambiguous
    intent beats a silent override).
    """
    out: dict = {}
    for key, val in spec.items():
        canon = AXIS_ALIASES.get(key, key)
        if canon in out:
            raise ValueError(
                f"mesh spec names axis {canon!r} twice (via {key!r}); "
                f"aliases: {AXIS_ALIASES}")
        out[canon] = val
    return out


@dataclasses.dataclass(frozen=True)
class MeshConfig:
    """Logical mesh shape. ``data=-1`` absorbs all remaining devices.

    The product of all axis sizes must equal the device count (after ``-1``
    expansion). This mirrors how the reference picks ``world_size`` from the
    launcher (SURVEY.md §3.1) — here the "world" is the device mesh.
    """

    data: int = -1
    fsdp: int = 1
    stage: int = 1
    expert: int = 1
    context: int = 1
    model: int = 1

    def sizes(self) -> tuple[int, ...]:
        return (self.data, self.fsdp, self.stage, self.expert, self.context, self.model)

    def resolve(self, num_devices: int) -> tuple[int, ...]:
        sizes = list(self.sizes())
        fixed = math.prod(s for s in sizes if s != -1)
        n_wild = sum(1 for s in sizes if s == -1)
        if n_wild > 1:
            raise ValueError("at most one mesh axis may be -1")
        if n_wild == 1:
            if num_devices % fixed != 0:
                raise ValueError(
                    f"{num_devices} devices not divisible by fixed axes product {fixed}"
                )
            sizes[sizes.index(-1)] = num_devices // fixed
        if math.prod(sizes) != num_devices:
            raise ValueError(
                f"mesh {dict(zip(AXES, sizes))} needs {math.prod(sizes)} devices, "
                f"have {num_devices}"
            )
        return tuple(sizes)

    def elastic_resolve(self, num_devices: int) -> tuple[int, ...]:
        """:meth:`resolve`, but degrade pinned axes when the device set shrank.

        Elastic resume relaunches with fewer (or more) devices than the mesh
        was configured for. A wildcard axis absorbs the change for free; when
        the *fixed* axes no longer fit, shrink each — innermost (``model``)
        first, since inner axes carry the latency-sensitive collectives that
        a degraded topology can least afford — to its largest divisor that
        still fits, and let ``data`` (or the wildcard) absorb the remainder.
        Changes are logged loudly; the result always multiplies out to
        ``num_devices``.
        """
        try:
            return self.resolve(num_devices)
        except ValueError:
            pass
        sizes = list(self.sizes())
        wild = sizes.index(-1) if -1 in sizes else 0
        if sizes[wild] == -1:
            sizes[wild] = 1
        # Shrink fixed axes innermost-first until the rest fits.
        for i in reversed(range(len(sizes))):
            if i == wild:
                continue
            others = math.prod(s for j, s in enumerate(sizes)
                               if j != i and j != wild)
            cap = max(1, num_devices // others)
            sizes[i] = math.gcd(sizes[i], cap)
        others = math.prod(s for j, s in enumerate(sizes) if j != wild)
        if num_devices % others:
            raise ValueError(
                f"elastic resolve failed: fixed axes "
                f"{dict(zip(AXES, sizes))} do not divide {num_devices} devices")
        sizes[wild] = num_devices // others
        resolved = tuple(sizes)
        changed = {a: (old, new) for a, old, new
                   in zip(AXES, self.sizes(), resolved)
                   if old not in (-1, new)}
        if changed:
            log.warning(
                "elastic mesh: %d devices cannot satisfy the configured mesh "
                "— degraded axes %s (full shape %s)", num_devices,
                {a: f"{o}->{n}" for a, (o, n) in changed.items()},
                dict(zip(AXES, resolved)))
        return resolved


def dcn_split(shape: Sequence[int], num_slices: int) -> tuple[tuple, tuple]:
    """Split a logical mesh shape into (per-slice ICI shape, DCN shape).

    Multislice rule (SURVEY.md §2d): the slice dimension — the only traffic
    that crosses DCN — must land on the OUTERMOST data-parallel axis whose
    size it divides (``data`` first, then ``fsdp``), so gradient psum is
    what rides DCN while TP/CP/EP collectives stay on intra-slice ICI.
    """
    dcn = [1] * len(shape)
    for i in (0, 1):  # data, fsdp
        if shape[i] % num_slices == 0:
            dcn[i] = num_slices
            break
    else:
        raise ValueError(
            f"multislice with {num_slices} slices needs a data or fsdp axis "
            f"divisible by it; mesh is {dict(zip(AXES, shape))}")
    ici = tuple(s // d for s, d in zip(shape, dcn))
    return ici, tuple(dcn)


def build_mesh(
    config: MeshConfig | dict | None = None,
    *,
    devices: Sequence[jax.Device] | None = None,
    elastic: bool = False,
) -> Mesh:
    """Build the named device mesh.

    Uses ``mesh_utils.create_device_mesh`` so the logical mesh is laid out
    along the physical ICI torus (nearest-neighbor axes get the fastest
    links); multislice device sets (distinct ``slice_index``) go through
    ``create_hybrid_device_mesh`` with the slice dimension on the outermost
    data axis (DCN-major). Falls back to a plain reshape for CPU/fake
    devices.
    """
    if config is None:
        config = MeshConfig()
    elif isinstance(config, dict):
        config = MeshConfig(**normalize_axes(config))
    if devices is None:
        devices = jax.devices()
    devices = list(devices)
    shape = (config.elastic_resolve(len(devices)) if elastic
             else config.resolve(len(devices)))
    slices = {getattr(d, "slice_index", 0) for d in devices}
    if len(slices) > 1:
        ici, dcn = dcn_split(shape, len(slices))  # config errors surface
        from jax.experimental import mesh_utils

        dev_array = mesh_utils.create_hybrid_device_mesh(
            ici, dcn, devices=devices)
    else:
        try:
            from jax.experimental import mesh_utils

            dev_array = mesh_utils.create_device_mesh(shape, devices=devices)
        except Exception:
            dev_array = np.asarray(devices).reshape(shape)
    return Mesh(dev_array, AXES)


def single_device_mesh(device: jax.Device | None = None) -> Mesh:
    """1-device mesh (the reference's non-``--distributed`` path, SURVEY.md §3.5)."""
    if device is None:
        device = jax.devices()[0]
    return Mesh(np.asarray([device]).reshape((1,) * len(AXES)), AXES)


# ---------------------------------------------------------------------------
# Current-mesh context: lets model code apply sharding constraints without
# threading the mesh through every call signature.
# ---------------------------------------------------------------------------

_local = threading.local()


def current_mesh() -> Mesh | None:
    return getattr(_local, "mesh", None)


@contextlib.contextmanager
def use_mesh(mesh: Mesh):
    """Make ``mesh`` the ambient mesh for :func:`constrain` and friends."""
    prev = current_mesh()
    _local.mesh = mesh
    try:
        # jax's own set_mesh/use_mesh contextmanager (when present) lets bare
        # PartitionSpecs be used inside jit bodies.
        ctx = getattr(jax.sharding, "use_mesh", None)
        if ctx is not None:
            with ctx(mesh):
                yield mesh
        else:
            yield mesh
    finally:
        _local.mesh = prev


@contextlib.contextmanager
def no_constrain():
    """Disable :func:`constrain` in this trace region.

    Used when model code runs inside ``shard_map`` (pipeline stages), where
    values are per-device and global sharding constraints don't apply.
    """
    prev = getattr(_local, "constrain_disabled", False)
    _local.constrain_disabled = True
    try:
        yield
    finally:
        _local.constrain_disabled = prev


def constrain(x, spec: P):
    """``with_sharding_constraint`` against the ambient mesh (no-op without one).

    Drops axis names that the ambient mesh does not have at size > 1, so model
    code can always annotate the "full" spec (e.g. activations sharded over
    ``('data','fsdp')`` and ``'model'``) and run unmodified on any mesh shape.
    """
    mesh = current_mesh()
    if mesh is None or getattr(_local, "constrain_disabled", False):
        return x
    spec = _prune_spec(spec, mesh)
    return jax.lax.with_sharding_constraint(x, NamedSharding(mesh, spec))


def _prune_spec(spec: P, mesh: Mesh) -> P:
    def keep(axis):
        return mesh.shape.get(axis, 1) > 1

    pruned = []
    for entry in spec:
        if entry is None:
            pruned.append(None)
        elif isinstance(entry, (tuple, list)):
            kept = tuple(a for a in entry if keep(a))
            pruned.append(kept if kept else None)
        else:
            pruned.append(entry if keep(entry) else None)
    return P(*pruned)


# ---------------------------------------------------------------------------
# Batch sharding (the DistributedSampler/DataLoader device-side contract)
# ---------------------------------------------------------------------------


def batch_pspec(ndim: int = 1) -> P:
    """PartitionSpec sharding axis 0 (batch) over the data-parallel axes."""
    return P(BATCH_AXES, *([None] * (ndim - 1)))


def batch_sharding(mesh: Mesh, ndim: int = 1) -> NamedSharding:
    return NamedSharding(mesh, batch_pspec(ndim))


def dp_size(mesh: Mesh) -> int:
    """Total data-parallel degree (replicas of the model across the batch)."""
    return mesh.shape["data"] * mesh.shape["fsdp"]


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())
