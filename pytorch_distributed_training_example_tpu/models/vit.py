"""ViT-B/16 — the reference's "ViT-B/16 / ImageNet-1k" config (BASELINE.json
configs[2]: DDP -> pjit data-parallel).

Standard ViT: 16x16 conv patch embedding, class token, learned position
embeddings, pre-LN encoder blocks (MSA + GELU MLP), LN + linear head.
Dropout is plumbed for the classic recipe; attention is the shared
ops.attention dispatcher so flash/ring engage by shape/mesh exactly as for
the LMs (bidirectional here — ``causal=False``).
"""

from __future__ import annotations

from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from pytorch_distributed_training_example_tpu.core import mesh as mesh_lib
from pytorch_distributed_training_example_tpu.ops import attention as attn_lib

BATCH = mesh_lib.BATCH_AXES


class EncoderBlock(nn.Module):
    num_heads: int
    mlp_dim: int
    dtype: Any
    param_dtype: Any
    dropout: float = 0.0
    attn_impl: str = "auto"

    @nn.compact
    def __call__(self, x, train: bool):
        d = x.shape[-1]
        head_dim = d // self.num_heads
        ln = lambda name: nn.LayerNorm(epsilon=1e-6, dtype=self.dtype,
                                       param_dtype=self.param_dtype, name=name)
        h = ln("ln_1")(x)
        dg = lambda name: nn.DenseGeneral((self.num_heads, head_dim), axis=-1,
                                          dtype=self.dtype,
                                          param_dtype=self.param_dtype, name=name)
        q, k, v = dg("attn_query")(h), dg("attn_key")(h), dg("attn_value")(h)
        q = mesh_lib.constrain(q, P(BATCH, None, "model", None))
        k = mesh_lib.constrain(k, P(BATCH, None, "model", None))
        v = mesh_lib.constrain(v, P(BATCH, None, "model", None))
        h = attn_lib.attention(q, k, v, causal=False, impl=self.attn_impl)
        h = nn.DenseGeneral(d, axis=(-2, -1), dtype=self.dtype,
                            param_dtype=self.param_dtype, name="attn_out")(h)
        if self.dropout > 0:
            h = nn.Dropout(self.dropout, deterministic=not train)(h)
        x = x + h

        h = ln("ln_2")(x)
        h = nn.Dense(self.mlp_dim, dtype=self.dtype,
                     param_dtype=self.param_dtype, name="mlp_up")(h)
        h = mesh_lib.constrain(h, P(BATCH, None, "model"))
        h = nn.gelu(h)
        h = nn.Dense(d, dtype=self.dtype, param_dtype=self.param_dtype,
                     name="mlp_down")(h)
        if self.dropout > 0:
            h = nn.Dropout(self.dropout, deterministic=not train)(h)
        x = x + h
        return mesh_lib.constrain(x, P(BATCH, None, None))


class ViT(nn.Module):
    num_classes: int = 1000
    patch_size: int = 16
    num_layers: int = 12
    num_heads: int = 12
    d_model: int = 768
    mlp_dim: int = 3072
    dropout: float = 0.0
    dtype: Any = jnp.float32
    param_dtype: Any = jnp.float32
    remat: bool = False
    attn_impl: str = "auto"

    @nn.compact
    def __call__(self, images, train: bool = True):
        p = self.patch_size
        x = nn.Conv(self.d_model, (p, p), strides=(p, p), padding="VALID",
                    dtype=self.dtype, param_dtype=self.param_dtype,
                    name="patch_embed")(images.astype(self.dtype))
        B, gh, gw, d = x.shape
        x = x.reshape(B, gh * gw, d)
        cls = self.param("cls", nn.initializers.zeros, (1, 1, d), self.param_dtype)
        x = jnp.concatenate([jnp.broadcast_to(cls.astype(self.dtype), (B, 1, d)), x],
                            axis=1)
        pos = self.param("pos_embed", nn.initializers.normal(0.02),
                         (1, gh * gw + 1, d), self.param_dtype)
        x = x + pos.astype(self.dtype)
        if self.dropout > 0:
            x = nn.Dropout(self.dropout, deterministic=not train)(x)
        x = mesh_lib.constrain(x, P(BATCH, None, None))

        block_cls = EncoderBlock
        if self.remat:
            block_cls = nn.remat(
                EncoderBlock, prevent_cse=False,
                policy=jax.checkpoint_policies.nothing_saveable,
                static_argnums=(1,))
        for i in range(self.num_layers):
            x = block_cls(self.num_heads, self.mlp_dim, self.dtype,
                          self.param_dtype, self.dropout, self.attn_impl,
                          name=f"block_{i}")(x, train)
        x = nn.LayerNorm(epsilon=1e-6, dtype=self.dtype,
                         param_dtype=self.param_dtype, name="ln_f")(x)
        cls_repr = x[:, 0]
        logits = nn.Dense(self.num_classes, dtype=self.dtype,
                          param_dtype=self.param_dtype, name="head")(cls_repr)
        return logits.astype(jnp.float32)


TP_RULES = (
    (r"attn_(query|key|value)/kernel", P(None, "model", None)),
    (r"attn_(query|key|value)/bias", P("model", None)),
    (r"attn_out/kernel", P("model", None, None)),
    (r"mlp_up/kernel", P(None, "model")),
    (r"mlp_up/bias", P("model")),
    (r"mlp_down/kernel", P("model", None)),
)


def vit_b16(**kw) -> ViT:
    return ViT(**kw)


def vit_l16(**kw) -> ViT:
    """ViT-L/16 (torchvision vit_l_16 architecture: 24 layers, d=1024)."""
    kw.setdefault("num_layers", 24)
    kw.setdefault("num_heads", 16)
    kw.setdefault("d_model", 1024)
    kw.setdefault("mlp_dim", 4096)
    return ViT(**kw)


def vit_tiny(**kw) -> ViT:
    kw.setdefault("num_layers", 2)
    kw.setdefault("num_heads", 4)
    kw.setdefault("d_model", 64)
    kw.setdefault("mlp_dim", 128)
    kw.setdefault("patch_size", 4)
    return ViT(**kw)


def flops_per_image(image_size: int = 224, patch: int = 16, L: int = 12,
                    d: int = 768, mlp: int = 3072) -> float:
    """Forward FLOPs (ViT-B/16 @224 ~= 17.6 GFLOP)."""
    S = (image_size // patch) ** 2 + 1
    per_block = 2 * S * (4 * d * d + 2 * d * mlp) + 2 * 2 * S * S * d
    return L * per_block + 2 * S * 3 * d * patch * patch
