"""ResNet-18/50 — the reference's vision workloads (BASELINE.json configs 1-2).

TPU-first choices (vs the reference's ``torchvision.models.resnet``):

- NHWC layout: XLA:TPU's native conv layout (torchvision is NCHW).
- BatchNorm over a GSPMD-sharded batch axis reduces over the *global* batch
  (SyncBN semantics for free — inside the single compiled step, no extra
  collective pass like GPU SyncBN needs).
- dtype/param_dtype plumbed from the precision Policy (AMP equivalent).
- ``strides=2`` conv layers padded SAME to keep shapes powers-of-two-ish for
  MXU tiling.

The classic architecture: stem (7x7/2 conv + 3x3/2 maxpool), 4 stages of
residual blocks ([2,2,2,2] BasicBlock for -18; [3,4,6,3] Bottleneck for -50),
global average pool, linear head.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable, Sequence

import flax.linen as nn
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from pytorch_distributed_training_example_tpu.core import mesh as mesh_lib

ModuleDef = Any


class BasicBlock(nn.Module):
    filters: int
    strides: int
    conv: ModuleDef
    norm: ModuleDef
    act: Callable

    @nn.compact
    def __call__(self, x):
        residual = x
        y = self.conv(self.filters, (3, 3), (self.strides, self.strides))(x)
        y = self.norm()(y)
        y = self.act(y)
        y = self.conv(self.filters, (3, 3))(y)
        y = self.norm(scale_init=nn.initializers.zeros)(y)  # zero-init last BN gamma
        if residual.shape != y.shape:
            residual = self.conv(self.filters, (1, 1), (self.strides, self.strides),
                                 name="downsample_conv")(residual)
            residual = self.norm(name="downsample_norm")(residual)
        return self.act(residual + y)


class Bottleneck(nn.Module):
    filters: int
    strides: int
    conv: ModuleDef
    norm: ModuleDef
    act: Callable

    @nn.compact
    def __call__(self, x):
        residual = x
        y = self.conv(self.filters, (1, 1))(x)
        y = self.norm()(y)
        y = self.act(y)
        y = self.conv(self.filters, (3, 3), (self.strides, self.strides))(y)
        y = self.norm()(y)
        y = self.act(y)
        y = self.conv(self.filters * 4, (1, 1))(y)
        y = self.norm(scale_init=nn.initializers.zeros)(y)
        if residual.shape != y.shape:
            residual = self.conv(self.filters * 4, (1, 1), (self.strides, self.strides),
                                 name="downsample_conv")(residual)
            residual = self.norm(name="downsample_norm")(residual)
        return self.act(residual + y)


class ResNet(nn.Module):
    stage_sizes: Sequence[int]
    block_cls: ModuleDef
    num_classes: int = 1000
    num_filters: int = 64
    dtype: Any = jnp.float32        # compute dtype (Policy.compute_dtype)
    param_dtype: Any = jnp.float32
    small_images: bool = False      # CIFAR stem: 3x3/1 conv, no maxpool

    @nn.compact
    def __call__(self, x, train: bool = True):
        conv = partial(nn.Conv, use_bias=False, padding="SAME",
                       dtype=self.dtype, param_dtype=self.param_dtype,
                       kernel_init=nn.initializers.he_normal())
        norm = partial(nn.BatchNorm, use_running_average=not train,
                       momentum=0.9, epsilon=1e-5,
                       dtype=self.dtype, param_dtype=self.param_dtype)
        act = nn.relu

        x = x.astype(self.dtype)
        if self.small_images:
            x = conv(self.num_filters, (3, 3), name="conv_init")(x)
        else:
            x = conv(self.num_filters, (7, 7), (2, 2), name="conv_init")(x)
        x = norm(name="bn_init")(x)
        x = act(x)
        if not self.small_images:
            x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="SAME")

        for i, block_count in enumerate(self.stage_sizes):
            for j in range(block_count):
                strides = 2 if i > 0 and j == 0 else 1
                x = self.block_cls(self.num_filters * 2**i, strides, conv, norm, act)(x)
            x = mesh_lib.constrain(x, P(mesh_lib.BATCH_AXES, None, None, None))

        x = jnp.mean(x, axis=(1, 2))
        x = nn.Dense(self.num_classes, dtype=self.dtype, param_dtype=self.param_dtype,
                     name="head")(x)
        return x.astype(jnp.float32)


def resnet18(num_classes: int = 1000, **kw) -> ResNet:
    return ResNet(stage_sizes=[2, 2, 2, 2], block_cls=BasicBlock,
                  num_classes=num_classes, **kw)


def resnet_micro(num_classes: int = 10, **kw) -> ResNet:
    """Two-block ResNet: the test-suite oracle model.

    Exercises every code path the big models do (BN batch_stats sync over
    the sharded batch, stride-2 downsample projection, AUTO_FSDP conv/dense
    sharding, activation constraints) at a fraction of the compile time.
    32 base filters keeps the stage-2 convs (3x3x64x64 = 36.9k elements)
    above parallel/sharding.py's MIN_SHARD_ELEMENTS so FSDP really shards.
    """
    kw.setdefault("small_images", True)
    return ResNet(stage_sizes=[1, 1], block_cls=BasicBlock, num_filters=32,
                  num_classes=num_classes, **kw)


def resnet34(num_classes: int = 1000, **kw) -> ResNet:
    return ResNet(stage_sizes=[3, 4, 6, 3], block_cls=BasicBlock,
                  num_classes=num_classes, **kw)


def resnet50(num_classes: int = 1000, **kw) -> ResNet:
    return ResNet(stage_sizes=[3, 4, 6, 3], block_cls=Bottleneck,
                  num_classes=num_classes, **kw)


def resnet101(num_classes: int = 1000, **kw) -> ResNet:
    return ResNet(stage_sizes=[3, 4, 23, 3], block_cls=Bottleneck,
                  num_classes=num_classes, **kw)


def resnet152(num_classes: int = 1000, **kw) -> ResNet:
    return ResNet(stage_sizes=[3, 8, 36, 3], block_cls=Bottleneck,
                  num_classes=num_classes, **kw)


def flops_per_image(name: str, image_size: int = 224) -> float:
    """Approximate forward FLOPs per image (for MFU accounting).

    Standard published figures: ResNet-50 @224 ~= 4.09 GFLOP (multiply-adds
    x2), ResNet-18 @224 ~= 1.81 GFLOP; scaled quadratically for other sizes.
    """
    base = {"resnet18": 1.81e9, "resnet34": 3.66e9, "resnet50": 4.09e9,
            "resnet101": 7.80e9, "resnet152": 11.51e9,
            "resnet_micro": 1.2e7}[name]
    return base * (image_size / 224.0) ** 2
