"""Import HuggingFace (PyTorch) checkpoints into this framework's models.

Interop with the reference's ecosystem: a user coming from the PyTorch
example can bring torch-trained GPT-2 / Llama weights straight into the
TPU-native models (and these converters double as numerical parity tests —
``tests/test_hf_parity.py`` checks our logits against the torch
implementations to ~1e-4 on random weights).

Conventions converted:
- HF GPT-2 uses Conv1D ([in, out]) and a fused qkv projection; we split and
  reshape to [d_model, heads, head_dim] DenseGeneral kernels.
- HF Llama Linear weights are [out, in]; ours are [in, out] (transposed),
  attention kernels reshaped to [d, heads, head_dim] / [heads, head_dim, d].
- Both use rotate-half RoPE and pre-norm, matching our implementations.
"""

from __future__ import annotations

import numpy as np


def _np(t):  # torch tensor -> numpy (no grad, cpu)
    return t.detach().cpu().numpy()


def import_gpt2(hf_model) -> dict:
    """HF ``GPT2LMHeadModel`` -> params for :class:`models.gpt2.GPT2`."""
    sd = {k: _np(v) for k, v in hf_model.state_dict().items()}
    cfg = hf_model.config
    d, H = cfg.n_embd, cfg.n_head
    Dh = d // H
    params: dict = {
        "wte": {"embedding": sd["transformer.wte.weight"]},
        "wpe": sd["transformer.wpe.weight"],
        "ln_f": {"scale": sd["transformer.ln_f.weight"],
                 "bias": sd["transformer.ln_f.bias"]},
    }
    for i in range(cfg.n_layer):
        p = f"transformer.h.{i}."
        qkv_w = sd[p + "attn.c_attn.weight"]          # [d, 3d] (Conv1D)
        qkv_b = sd[p + "attn.c_attn.bias"]            # [3d]
        qw, kw, vw = np.split(qkv_w, 3, axis=1)
        qb, kb, vb = np.split(qkv_b, 3)
        block = {
            "ln_1": {"scale": sd[p + "ln_1.weight"], "bias": sd[p + "ln_1.bias"]},
            "ln_2": {"scale": sd[p + "ln_2.weight"], "bias": sd[p + "ln_2.bias"]},
            "attn": {
                "query": {"kernel": qw.reshape(d, H, Dh), "bias": qb.reshape(H, Dh)},
                "key": {"kernel": kw.reshape(d, H, Dh), "bias": kb.reshape(H, Dh)},
                "value": {"kernel": vw.reshape(d, H, Dh), "bias": vb.reshape(H, Dh)},
                "out": {"kernel": sd[p + "attn.c_proj.weight"].reshape(H, Dh, d),
                        "bias": sd[p + "attn.c_proj.bias"]},
            },
            "mlp_up": {"kernel": sd[p + "mlp.c_fc.weight"],
                       "bias": sd[p + "mlp.c_fc.bias"]},
            "mlp_down": {"kernel": sd[p + "mlp.c_proj.weight"],
                         "bias": sd[p + "mlp.c_proj.bias"]},
        }
        params[f"block_{i}"] = block
    return params


def import_llama(hf_model) -> dict:
    """HF ``LlamaForCausalLM`` -> params for :class:`models.llama.Llama`."""
    sd = {k: _np(v) for k, v in hf_model.state_dict().items()}
    cfg = hf_model.config
    d = cfg.hidden_size
    H = cfg.num_attention_heads
    Hkv = cfg.num_key_value_heads
    Dh = d // H
    params: dict = {
        "embed": {"embedding": sd["model.embed_tokens.weight"]},
        "final_norm": {"scale": sd["model.norm.weight"]},
        "lm_head": {"kernel": sd["lm_head.weight"].T},
    }
    for i in range(cfg.num_hidden_layers):
        p = f"model.layers.{i}."
        block = {
            "attn_norm": {"scale": sd[p + "input_layernorm.weight"]},
            "mlp_norm": {"scale": sd[p + "post_attention_layernorm.weight"]},
            "attn": {
                "query": {"kernel": sd[p + "self_attn.q_proj.weight"].T
                          .reshape(d, H, Dh)},
                "key": {"kernel": sd[p + "self_attn.k_proj.weight"].T
                        .reshape(d, Hkv, Dh)},
                "value": {"kernel": sd[p + "self_attn.v_proj.weight"].T
                          .reshape(d, Hkv, Dh)},
                "out": {"kernel": sd[p + "self_attn.o_proj.weight"].T
                        .reshape(H, Dh, d)},
            },
            "gate": {"kernel": sd[p + "mlp.gate_proj.weight"].T},
            "up": {"kernel": sd[p + "mlp.up_proj.weight"].T},
            "down": {"kernel": sd[p + "mlp.down_proj.weight"].T},
        }
        params[f"block_{i}"] = block
    return params


def to_jax(params, dtype=None):
    import jax.numpy as jnp

    def conv(x):
        arr = jnp.asarray(x)
        return arr.astype(dtype) if dtype is not None else arr

    import jax

    return jax.tree.map(conv, params)
