"""Model zoo covering the reference's config matrix (BASELINE.json ``configs``):

ResNet-18/50, ViT-B/16, GPT-2 124M, Llama-3 8B — built TPU-first (NHWC convs,
bf16-friendly, static shapes, sharding-annotated activations).
"""

from pytorch_distributed_training_example_tpu.models.registry import create_model, list_models  # noqa: F401
