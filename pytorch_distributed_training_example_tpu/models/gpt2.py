"""GPT-2 decoder LM — the reference's "GPT-2 124M LM" config (BASELINE.json
configs[3]: FSDP -> GSPMD param-shard).

Architecture (standard GPT-2): learned token+position embeddings, pre-LN
blocks, GELU MLP at 4x width, biased projections, weight-tied LM head.

TPU-first details:
- QKV projections are ``DenseGeneral`` with kernels shaped [d_model, heads,
  head_dim] so tensor-parallel rules shard the *head* dimension (Megatron
  column-split) purely via PartitionSpec — no parallel linear classes.
- Activations carry sharding constraints (batch over data axes, sequence
  over 'context') so CP/ring-attention engages by mesh shape alone.
- ``remat`` wraps each block in ``jax.checkpoint`` (the reference matrix's
  gradient-checkpointing capability).
"""

from __future__ import annotations

from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from pytorch_distributed_training_example_tpu.core import mesh as mesh_lib
from pytorch_distributed_training_example_tpu.ops import attention as attn_lib
from pytorch_distributed_training_example_tpu.parallel import sharding

BATCH = mesh_lib.BATCH_AXES


def _seq_rule(name: str, sp: bool = False):
    """Sequence/context activation spec from the shared rule table
    (parallel/sharding.seq_rules): with Megatron-style SP on, the residual
    stream's sequence dim also shards over the TP axis between matmul
    regions (GSPMD inserts the gather/scatter Megatron's SP does by hand)."""
    return sharding.seq_rules(sp)[name]


class SelfAttention(nn.Module):
    num_heads: int
    dtype: Any
    param_dtype: Any
    dropout: float = 0.0
    attn_impl: str = "auto"

    @nn.compact
    def __call__(self, x, train: bool):
        d = x.shape[-1]
        head_dim = d // self.num_heads
        dg = lambda name: nn.DenseGeneral(
            (self.num_heads, head_dim), axis=-1, dtype=self.dtype,
            param_dtype=self.param_dtype, name=name)
        q, k, v = dg("query")(x), dg("key")(x), dg("value")(x)
        q = mesh_lib.constrain(q, _seq_rule("qkv"))
        k = mesh_lib.constrain(k, _seq_rule("qkv"))
        v = mesh_lib.constrain(v, _seq_rule("qkv"))
        out = attn_lib.attention(q, k, v, causal=True, impl=self.attn_impl)
        out = nn.DenseGeneral(d, axis=(-2, -1), dtype=self.dtype,
                              param_dtype=self.param_dtype, name="out")(out)
        if self.dropout > 0:
            out = nn.Dropout(self.dropout, deterministic=not train)(out)
        return out


class Block(nn.Module):
    num_heads: int
    mlp_ratio: int
    dtype: Any
    param_dtype: Any
    dropout: float = 0.0
    attn_impl: str = "auto"
    sp: bool = False

    @nn.compact
    def __call__(self, x, train: bool):
        ln = lambda name: nn.LayerNorm(epsilon=1e-5, dtype=self.dtype,
                                       param_dtype=self.param_dtype, name=name)
        x = x + SelfAttention(self.num_heads, self.dtype, self.param_dtype,
                              self.dropout, self.attn_impl,
                              name="attn")(ln("ln_1")(x), train)
        x = mesh_lib.constrain(x, _seq_rule("residual", self.sp))
        h = ln("ln_2")(x)
        d = x.shape[-1]
        h = nn.Dense(self.mlp_ratio * d, dtype=self.dtype,
                     param_dtype=self.param_dtype, name="mlp_up")(h)
        h = mesh_lib.constrain(h, _seq_rule("ffn_hidden"))
        h = nn.gelu(h, approximate=True)
        h = nn.Dense(d, dtype=self.dtype, param_dtype=self.param_dtype,
                     name="mlp_down")(h)
        if self.dropout > 0:
            h = nn.Dropout(self.dropout, deterministic=not train)(h)
        x = x + h
        return mesh_lib.constrain(x, _seq_rule("residual", self.sp))


class GPT2(nn.Module):
    vocab_size: int = 50257
    num_layers: int = 12
    num_heads: int = 12
    d_model: int = 768
    max_seq_len: int = 1024
    mlp_ratio: int = 4
    dropout: float = 0.0
    dtype: Any = jnp.float32
    param_dtype: Any = jnp.float32
    remat: bool = False
    attn_impl: str = "auto"
    sp: bool = False
    logits_dtype: Any = jnp.float32  # storage dtype; loss upcasts per-element

    @nn.compact
    def __call__(self, tokens, train: bool = True):
        B, S = tokens.shape
        emb = nn.Embed(self.vocab_size, self.d_model, dtype=self.dtype,
                       param_dtype=self.param_dtype, name="wte")
        pos_emb = self.param("wpe", nn.initializers.normal(0.01),
                             (self.max_seq_len, self.d_model), self.param_dtype)
        x = emb(tokens) + pos_emb[None, :S].astype(self.dtype)
        x = mesh_lib.constrain(x, _seq_rule("residual", self.sp))
        if self.dropout > 0:
            x = nn.Dropout(self.dropout, deterministic=not train)(x)

        block_cls = Block
        if self.remat:
            block_cls = nn.remat(
                Block, prevent_cse=False,
                policy=jax.checkpoint_policies.nothing_saveable,
                static_argnums=(1,))
        for i in range(self.num_layers):
            x = block_cls(self.num_heads, self.mlp_ratio, self.dtype,
                          self.param_dtype, self.dropout, self.attn_impl,
                          self.sp, name=f"block_{i}")(x, train)
        x = nn.LayerNorm(epsilon=1e-5, dtype=self.dtype,
                         param_dtype=self.param_dtype, name="ln_f")(x)
        # Weight-tied LM head (GPT-2 convention). flax's attend promotes both
        # operands to the module dtype (bf16 under the bf16 policy), so the
        # matmul output is already bf16-rounded; logits_dtype only decides
        # what lands in HBM (metrics.cross_entropy upcasts fp32 per-element).
        logits = emb.attend(x.astype(self.param_dtype))
        logits = mesh_lib.constrain(logits, _seq_rule("logits", self.sp))
        return logits.astype(self.logits_dtype)


#: Tensor-parallel rule table (path regex -> PartitionSpec). AUTO_FSDP
#: composition happens in parallel.sharding when the mesh has an fsdp axis.
TP_RULES = (
    (r"attn/(query|key|value)/kernel", P(None, "model", None)),
    # The one sequence-dim parameter in the repo: learned position embeddings
    # shard over 'context' so each seq shard holds only its own positions
    # (pruned to replicated when the mesh has no context axis).
    (r"wpe", P("context", None)),
    (r"attn/(query|key|value)/bias", P("model", None)),
    (r"attn/out/kernel", P("model", None, None)),
    (r"mlp_up/kernel", P(None, "model")),
    (r"mlp_up/bias", P("model")),
    (r"mlp_down/kernel", P("model", None)),
    (r"wte/embedding", P(None, "model")),
)


def gpt2_124m(**kw) -> GPT2:
    return GPT2(**kw)


def gpt2_tiny(**kw) -> GPT2:
    """4-layer toy for tests/dry-runs."""
    kw.setdefault("vocab_size", 512)
    kw.setdefault("num_layers", 4)
    kw.setdefault("num_heads", 4)
    kw.setdefault("d_model", 128)
    kw.setdefault("max_seq_len", 256)
    return GPT2(**kw)


def num_params(cfg: GPT2) -> int:
    d, L, V = cfg.d_model, cfg.num_layers, cfg.vocab_size
    per_block = 4 * d * d + 4 * d + 2 * cfg.mlp_ratio * d * d \
        + (cfg.mlp_ratio + 1) * d + 4 * d
    return V * d + cfg.max_seq_len * d + L * per_block + 2 * d
