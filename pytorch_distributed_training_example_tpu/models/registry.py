"""Model registry: name -> (module, task kind, input template, FLOPs, TP rules).

The torchvision-factory equivalent (reference builds models via
``torchvision.models.resnet50()`` etc., SURVEY.md §2a #4) plus the metadata
the framework needs: which task head to use, an input template for sharded
init, a forward-FLOPs estimate for MFU accounting, and per-family tensor-
parallel rule tables.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable

import jax.numpy as jnp


@dataclasses.dataclass
class ModelBundle:
    module: Any                      # flax module (constructed, not initialized)
    task: str                        # "classification" | "lm"
    input_template: tuple            # abstract sample inputs for init
    fwd_flops_per_example: float     # forward FLOPs for one example (MFU accounting)
    rules: dict[str, tuple]          # strategy name -> partition-rule table
    examples_unit: str = "images"    # "images" | "sequences" (throughput label)


_REGISTRY: dict[str, Callable[..., ModelBundle]] = {}


def register(name):
    def deco(fn):
        _REGISTRY[name] = fn
        return fn
    return deco


def list_models() -> list[str]:
    return sorted(_REGISTRY)


def create_model(name: str, *, num_classes: int = 1000, image_size: int = 224,
                 seq_len: int = 1024, dtype=jnp.bfloat16, param_dtype=jnp.float32,
                 remat: bool = False, remat_policy: str = "nothing",
                 sp: bool = False,
                 attn_impl: str = "auto", dropout: float = 0.0,
                 moe_capacity_factor: float = 1.25,
                 moe_top_k: int = 2,
                 moe_dispatch_impl: str = "gather",
                 moe_combine_dtype: str = "fp32",
                 moe_router_dtype: str = "fp32",
                 moe_router_impl: str = "reference",
                 moe_ep_dispatch: str = "replicated",
                 moe_ep_overlap_chunks: int = 2,
                 logits_dtype=jnp.float32) -> ModelBundle:
    if name not in _REGISTRY:
        raise ValueError(f"unknown model {name!r}; have {list_models()}")
    builder = _REGISTRY[name]
    if dropout != 0.0:
        import inspect

        if "dropout" not in inspect.signature(builder).parameters:
            raise ValueError(
                f"model {name!r} does not implement dropout; --dropout "
                f"{dropout} would be silently ignored (the Llama and ResNet "
                "families have no dropout knob, matching the reference "
                "factories)")
    if remat_policy != "nothing":
        import inspect

        if "remat_policy" not in inspect.signature(builder).parameters:
            raise ValueError(
                f"model {name!r} does not implement remat_policy; "
                f"--remat-policy {remat_policy} would be silently ignored "
                "(only the Llama family exposes checkpoint-policy tuning)")
    return builder(
        num_classes=num_classes, image_size=image_size, seq_len=seq_len,
        dtype=dtype, param_dtype=param_dtype, remat=remat,
        remat_policy=remat_policy, sp=sp,
        attn_impl=attn_impl, dropout=dropout,
        moe_capacity_factor=moe_capacity_factor,
        moe_top_k=moe_top_k, moe_dispatch_impl=moe_dispatch_impl,
        moe_combine_dtype=moe_combine_dtype,
        moe_router_dtype=moe_router_dtype, moe_router_impl=moe_router_impl,
        moe_ep_dispatch=moe_ep_dispatch,
        moe_ep_overlap_chunks=moe_ep_overlap_chunks,
        logits_dtype=logits_dtype,
    )


# --moe-combine flag values -> MoEBlock.combine_dtype (None = fp32, exact);
# --moe-router-dtype uses the same spelling for MoEBlock.router_dtype.
_MOE_COMBINE_DTYPES = {"fp32": None, "bf16": jnp.bfloat16}
_MOE_ROUTER_IMPLS = ("reference", "fused")
_MOE_DISPATCH_IMPLS = ("sort", "gather", "einsum", "dropless")
_MOE_EP_DISPATCH = ("replicated", "a2a", "a2a_overlap")


def _moe_kwargs(moe_capacity_factor, moe_top_k, moe_dispatch_impl,
                moe_combine_dtype, moe_router_dtype="fp32",
                moe_router_impl="reference", moe_ep_dispatch="replicated",
                moe_ep_overlap_chunks=2):
    if moe_dispatch_impl not in _MOE_DISPATCH_IMPLS:
        raise ValueError(
            f"unknown moe_dispatch_impl {moe_dispatch_impl!r}; "
            f"have {list(_MOE_DISPATCH_IMPLS)}")
    if moe_ep_dispatch not in _MOE_EP_DISPATCH:
        raise ValueError(
            f"unknown moe_ep_dispatch {moe_ep_dispatch!r}; "
            f"have {list(_MOE_EP_DISPATCH)}")
    if moe_ep_dispatch != "replicated" and moe_dispatch_impl != "dropless":
        raise ValueError(
            f"moe_ep_dispatch={moe_ep_dispatch!r} requires "
            f"moe_dispatch_impl='dropless' (got {moe_dispatch_impl!r}); the "
            "capacity-dropped impls shard through GSPMD alone")
    if int(moe_ep_overlap_chunks) < 1:
        raise ValueError(
            f"moe_ep_overlap_chunks must be >= 1 "
            f"(got {moe_ep_overlap_chunks})")
    if moe_combine_dtype not in _MOE_COMBINE_DTYPES:
        raise ValueError(
            f"unknown moe_combine_dtype {moe_combine_dtype!r}; "
            f"have {sorted(_MOE_COMBINE_DTYPES)}")
    if moe_router_dtype not in _MOE_COMBINE_DTYPES:
        raise ValueError(
            f"unknown moe_router_dtype {moe_router_dtype!r}; "
            f"have {sorted(_MOE_COMBINE_DTYPES)}")
    if moe_router_impl not in _MOE_ROUTER_IMPLS:
        raise ValueError(
            f"unknown moe_router_impl {moe_router_impl!r}; "
            f"have {list(_MOE_ROUTER_IMPLS)}")
    return dict(moe_capacity_factor=moe_capacity_factor,
                moe_top_k=moe_top_k,
                moe_dispatch_impl=moe_dispatch_impl,
                moe_combine_dtype=_MOE_COMBINE_DTYPES[moe_combine_dtype],
                moe_router_dtype=_MOE_COMBINE_DTYPES[moe_router_dtype],
                moe_router_impl=moe_router_impl,
                moe_ep_dispatch=moe_ep_dispatch,
                moe_ep_overlap_chunks=int(moe_ep_overlap_chunks))


@register("vit_b16")
def _vit_b16(*, num_classes, image_size, dtype, param_dtype, remat,
             attn_impl="auto", dropout=0.0, **_):
    from pytorch_distributed_training_example_tpu.models import vit

    # dropout defaults to 0.0 for parity with the reference model factory
    # (torchvision vit_b_16: dropout=0.0, attention_dropout=0.0). r4 profile
    # found dropout=0.1 was costing ~25% of the ViT step: the threefry mask
    # bits get rematerialized inside the weight-grad matmul fusions
    # (PROFILE_VIT.md).
    module = vit.vit_b16(num_classes=num_classes, dtype=dtype,
                         param_dtype=param_dtype, remat=remat, dropout=dropout,
                         attn_impl=attn_impl)
    return ModelBundle(
        module=module, task="classification",
        input_template=(jnp.zeros((2, image_size, image_size, 3), jnp.float32),),
        fwd_flops_per_example=vit.flops_per_image(image_size),
        rules={"fsdp_tp": vit.TP_RULES, "tp": vit.TP_RULES},
    )


@register("vit_tiny")
def _vit_tiny(*, num_classes, image_size, dtype, param_dtype, remat,
              attn_impl="auto", dropout=0.0, **_):
    from pytorch_distributed_training_example_tpu.models import vit

    module = vit.vit_tiny(num_classes=num_classes, dtype=dtype,
                          param_dtype=param_dtype, remat=remat,
                          dropout=dropout, attn_impl=attn_impl)
    return ModelBundle(
        module=module, task="classification",
        input_template=(jnp.zeros((2, image_size, image_size, 3), jnp.float32),),
        fwd_flops_per_example=vit.flops_per_image(image_size, 4, 2, 64, 128),
        rules={"fsdp_tp": vit.TP_RULES, "tp": vit.TP_RULES},
    )


def _lm_bundle(module, tp_rules, seq_len, n_params_fn):
    from pytorch_distributed_training_example_tpu.utils import metrics as metrics_lib

    flops_tok = metrics_lib.transformer_flops_per_token(
        n_params_fn(module), seq_len, module.num_layers, module.d_model)
    return ModelBundle(
        module=module, task="lm",
        input_template=(jnp.zeros((2, seq_len), jnp.int32),),
        fwd_flops_per_example=flops_tok * seq_len,
        rules={"fsdp_tp": tp_rules, "tp": tp_rules},
        examples_unit="sequences",
    )


@register("gpt2")
def _gpt2(*, seq_len, dtype, param_dtype, remat, sp=False, attn_impl="auto",
          dropout=0.0, logits_dtype, **_):
    from pytorch_distributed_training_example_tpu.models import gpt2

    # GPT-2 carries the reference family's dropout (HF gpt2: resid/embd/attn
    # pdrop 0.1, but 0.0 default here for bench parity with the other rows)
    module = gpt2.gpt2_124m(dtype=dtype, param_dtype=param_dtype, remat=remat,
                            max_seq_len=max(seq_len, 1024), sp=sp,
                            dropout=dropout,
                            attn_impl=attn_impl, logits_dtype=logits_dtype)
    return _lm_bundle(module, gpt2.TP_RULES, seq_len, gpt2.num_params)


@register("gpt2_tiny")
def _gpt2_tiny(*, seq_len, dtype, param_dtype, remat, sp=False, attn_impl="auto",
               dropout=0.0, logits_dtype, **_):
    from pytorch_distributed_training_example_tpu.models import gpt2

    module = gpt2.gpt2_tiny(dtype=dtype, param_dtype=param_dtype, remat=remat,
                            max_seq_len=max(seq_len, 256), sp=sp,
                            dropout=dropout,
                            attn_impl=attn_impl, logits_dtype=logits_dtype)
    return _lm_bundle(module, gpt2.TP_RULES, seq_len, gpt2.num_params)


@register("llama3_8b")
def _llama3_8b(*, seq_len, dtype, param_dtype, remat, remat_policy="nothing",
               sp=False, attn_impl="auto", logits_dtype, **_):
    from pytorch_distributed_training_example_tpu.models import llama

    module = llama.llama3_8b(dtype=dtype, param_dtype=param_dtype, remat=remat,
                             remat_policy=remat_policy,
                             max_seq_len=max(seq_len, 8192), sp=sp,
                             attn_impl=attn_impl, logits_dtype=logits_dtype)
    return _lm_bundle(module, llama.TP_RULES, seq_len, llama.num_params)


@register("llama_400m")
def _llama_400m(*, seq_len, dtype, param_dtype, remat, remat_policy="nothing",
                sp=False, attn_impl="auto", logits_dtype, **_):
    from pytorch_distributed_training_example_tpu.models import llama

    module = llama.llama_400m(dtype=dtype, param_dtype=param_dtype,
                              remat=remat, remat_policy=remat_policy,
                              max_seq_len=max(seq_len, 2048),
                              sp=sp, attn_impl=attn_impl,
                              logits_dtype=logits_dtype)
    return _lm_bundle(module, llama.TP_RULES, seq_len, llama.num_params)


@register("llama_tiny")
def _llama_tiny(*, seq_len, dtype, param_dtype, remat, remat_policy="nothing",
                sp=False, attn_impl="auto", logits_dtype, **_):
    from pytorch_distributed_training_example_tpu.models import llama

    module = llama.llama_tiny(dtype=dtype, param_dtype=param_dtype, remat=remat,
                              remat_policy=remat_policy,
                              max_seq_len=max(seq_len, 256), sp=sp,
                              attn_impl=attn_impl, logits_dtype=logits_dtype)
    return _lm_bundle(module, llama.TP_RULES, seq_len, llama.num_params)


@register("llama_moe_tiny")
def _llama_moe_tiny(*, seq_len, dtype, param_dtype, remat,
                    remat_policy="nothing", sp=False,
                    attn_impl="auto", moe_capacity_factor=1.25, moe_top_k=2,
                    moe_dispatch_impl="gather", moe_combine_dtype="fp32",
                    moe_router_dtype="fp32", moe_router_impl="reference",
                    moe_ep_dispatch="replicated", moe_ep_overlap_chunks=2,
                    logits_dtype, **_):
    from pytorch_distributed_training_example_tpu.models import llama

    module = llama.llama_moe_tiny(dtype=dtype, param_dtype=param_dtype,
                                  remat=remat, remat_policy=remat_policy,
                                  max_seq_len=max(seq_len, 256),
                                  sp=sp, attn_impl=attn_impl,
                                  logits_dtype=logits_dtype,
                                  **_moe_kwargs(moe_capacity_factor, moe_top_k,
                                                moe_dispatch_impl,
                                                moe_combine_dtype,
                                                moe_router_dtype,
                                                moe_router_impl,
                                                moe_ep_dispatch,
                                                moe_ep_overlap_chunks))
    # MFU basis = ACTIVE params (top-2 experts), not the full expert stack
    return _lm_bundle(module, llama.TP_RULES, seq_len,
                      llama.num_params_active)


@register("llama_moe")
def _llama_moe(*, seq_len, dtype, param_dtype, remat, remat_policy="nothing",
               sp=False,
               attn_impl="auto", moe_capacity_factor=1.25, moe_top_k=2,
               moe_dispatch_impl="gather", moe_combine_dtype="fp32",
               moe_router_dtype="fp32", moe_router_impl="reference",
               moe_ep_dispatch="replicated", moe_ep_overlap_chunks=2,
               logits_dtype, **_):
    """Bench-scale MoE (llama trunk, 8 experts top-2, ~520M total): the
    e2e EP perf row on the real chip (BENCH_MOE.json e2e, BASELINE.md)."""
    from pytorch_distributed_training_example_tpu.models import llama

    module = llama.llama_moe_520m(dtype=dtype, param_dtype=param_dtype,
                                  remat=remat, remat_policy=remat_policy,
                                  max_seq_len=max(seq_len, 2048),
                                  sp=sp, attn_impl=attn_impl,
                                  logits_dtype=logits_dtype,
                                  **_moe_kwargs(moe_capacity_factor, moe_top_k,
                                                moe_dispatch_impl,
                                                moe_combine_dtype,
                                                moe_router_dtype,
                                                moe_router_impl,
                                                moe_ep_dispatch,
                                                moe_ep_overlap_chunks))
    return _lm_bundle(module, llama.TP_RULES, seq_len,
                      llama.num_params_active)


@register("resnet_micro")
def _resnet_micro(*, num_classes, image_size, dtype, param_dtype, **_):
    from pytorch_distributed_training_example_tpu.models import resnet

    module = resnet.resnet_micro(num_classes=num_classes, dtype=dtype,
                                 param_dtype=param_dtype)
    return ModelBundle(
        module=module, task="classification",
        input_template=(jnp.zeros((2, image_size, image_size, 3), jnp.float32),),
        fwd_flops_per_example=resnet.flops_per_image("resnet_micro", image_size),
        rules={},
    )


def _resnet_bundle(name):
    """Torchvision-style ResNet family entries (reference model zoo:
    ``torchvision.models.resnet{18,34,50,101,152}()``)."""
    def build(*, num_classes, image_size, dtype, param_dtype, **_):
        from pytorch_distributed_training_example_tpu.models import resnet

        module = getattr(resnet, name)(num_classes=num_classes, dtype=dtype,
                                       param_dtype=param_dtype,
                                       small_images=image_size <= 64)
        return ModelBundle(
            module=module, task="classification",
            input_template=(jnp.zeros((2, image_size, image_size, 3),
                                      jnp.float32),),
            fwd_flops_per_example=resnet.flops_per_image(name, image_size),
            rules={},
        )
    return build


for _name in ("resnet18", "resnet34", "resnet50", "resnet101", "resnet152"):
    _REGISTRY[_name] = _resnet_bundle(_name)


@register("vit_l16")
def _vit_l16(*, num_classes, image_size, dtype, param_dtype, remat,
             attn_impl="auto", dropout=0.0, **_):
    from pytorch_distributed_training_example_tpu.models import vit

    module = vit.vit_l16(num_classes=num_classes, dtype=dtype,
                         param_dtype=param_dtype, remat=remat,
                         dropout=dropout, attn_impl=attn_impl)
    return ModelBundle(
        module=module, task="classification",
        input_template=(jnp.zeros((2, image_size, image_size, 3), jnp.float32),),
        fwd_flops_per_example=vit.flops_per_image(image_size, 16, 24, 1024,
                                                  4096),
        rules={"fsdp_tp": vit.TP_RULES, "tp": vit.TP_RULES},
    )
