"""Llama-3 family — the reference's large-model config (BASELINE.json
configs[4]: Llama-3 8B, FSDP + gradient checkpointing on v5p-32).

Standard Llama-3 architecture: RMSNorm (pre-norm), rotary position
embeddings (theta 500k), grouped-query attention (8 KV heads), SwiGLU MLP,
no biases, untied output head.

TPU-first: same sharding-by-annotation scheme as gpt2.py (heads sharded on
'model', sequence on 'context', GQA KV heads replicated across TP when
num_kv_heads < tp); ``remat`` per block for the grad-checkpoint config;
``scan_layers`` trades python-loop unrolling for an ``nn.scan`` over a
stacked block (constant compile time at depth 32+, params gain a leading
layer dim handled by the partition rules).
"""

from __future__ import annotations

import contextlib
from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax import ad_checkpoint
from jax.sharding import PartitionSpec as P

from pytorch_distributed_training_example_tpu.core import mesh as mesh_lib
from pytorch_distributed_training_example_tpu.ops import attention as attn_lib
from pytorch_distributed_training_example_tpu.parallel import sharding

BATCH = mesh_lib.BATCH_AXES


def _seq_rule(name: str, sp: bool = False):
    """Sequence/context activation spec from the shared rule table
    (parallel/sharding.seq_rules): Megatron SP additionally shards the
    residual stream's sequence dim over the TP axis between matmul regions
    (GSPMD reshards)."""
    return sharding.seq_rules(sp)[name]


class RMSNorm(nn.Module):
    epsilon: float = 1e-5
    dtype: Any = jnp.float32
    param_dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x):
        scale = self.param("scale", nn.initializers.ones, (x.shape[-1],),
                           self.param_dtype)
        x32 = x.astype(jnp.float32)
        norm = x32 * jax.lax.rsqrt(
            jnp.mean(jnp.square(x32), axis=-1, keepdims=True) + self.epsilon)
        return (norm * scale.astype(jnp.float32)).astype(self.dtype)


def rope(x: jax.Array, positions: jax.Array, theta: float) -> jax.Array:
    """Rotary embeddings on [B, S, H, D] (rotate half, fp32 trig)."""
    d_half = x.shape[-1] // 2
    freqs = theta ** (-jnp.arange(0, d_half, dtype=jnp.float32) / d_half)
    angles = positions[..., None].astype(jnp.float32) * freqs  # [B?,S,d/2]
    cos, sin = jnp.cos(angles), jnp.sin(angles)
    cos = cos[:, :, None, :]  # broadcast over heads
    sin = sin[:, :, None, :]
    x1, x2 = jnp.split(x.astype(jnp.float32), 2, axis=-1)
    out = jnp.concatenate([x1 * cos - x2 * sin, x1 * sin + x2 * cos], axis=-1)
    return out.astype(x.dtype)


class LlamaAttention(nn.Module):
    num_heads: int
    num_kv_heads: int
    head_dim: int
    rope_theta: float
    dtype: Any
    param_dtype: Any
    attn_impl: str = "auto"

    @nn.compact
    def __call__(self, x, train: bool, decode_ctx: dict | None = None):
        B, S, d = x.shape
        dg = lambda heads, name: nn.DenseGeneral(
            (heads, self.head_dim), axis=-1, use_bias=False, dtype=self.dtype,
            param_dtype=self.param_dtype, name=name)
        q = dg(self.num_heads, "query")(x)
        k = dg(self.num_kv_heads, "key")(x)
        v = dg(self.num_kv_heads, "value")(x)
        if decode_ctx is not None:
            return self._decode(q, k, v, d, decode_ctx)
        positions = jnp.broadcast_to(jnp.arange(S)[None], (B, S))
        q = rope(q, positions, self.rope_theta)
        k = rope(k, positions, self.rope_theta)
        q = mesh_lib.constrain(q, _seq_rule("qkv"))
        k = mesh_lib.constrain(k, _seq_rule("qkv"))
        v = mesh_lib.constrain(v, _seq_rule("qkv"))
        out = attn_lib.attention(q, k, v, causal=True, impl=self.attn_impl)
        # Named for the "attn_out" remat policy (save attention outputs,
        # recompute everything else): a no-op unless that policy is active.
        out = ad_checkpoint.checkpoint_name(out, "attn_out")
        return nn.DenseGeneral(d, axis=(-2, -1), use_bias=False,
                               dtype=self.dtype, param_dtype=self.param_dtype,
                               name="out")(out)

    def _decode(self, q, k, v, d, decode_ctx):
        """Serving path (serve/): RoPE at explicit per-request positions,
        K/V appended through the page table into this layer's pools (the
        flax ``cache`` collection — the engine threads it through each step
        via ``mutable=["cache"]`` and donates the buffers), then attention
        reads the cache. S == 1 is a decode step (paged flash-decode
        kernel); S > 1 is prefill. A fresh prefill starts at position 0,
        where causal self-attention over the chunk IS the full answer, so
        it reuses the training dispatcher for exact parity. A window with
        HISTORY (suffix prefill after a prefix-cache splice, or a later
        chunk of a chunked prefill — ``decode_ctx["history"]``, static so
        each flavor is its own compiled program) must also attend to the
        cached positions before it, so it reads back through the page
        table instead."""
        from pytorch_distributed_training_example_tpu.ops import (
            flash_attention as flash_lib)
        from pytorch_distributed_training_example_tpu.serve import kv_cache

        B, S = q.shape[0], q.shape[1]
        positions = decode_ctx["positions"]             # [B, S] int32
        page_table = decode_ctx["page_table"]           # [B, max_pages]
        num_pages, page_size = decode_ctx["cache_spec"]
        q = rope(q, positions, self.rope_theta)
        k = rope(k, positions, self.rope_theta)
        init = lambda: jnp.zeros(
            (num_pages, page_size, self.num_kv_heads, self.head_dim),
            self.dtype)
        k_pages = self.variable("cache", "k_pages", init)
        v_pages = self.variable("cache", "v_pages", init)
        with jax.named_scope("serve_cache"):
            k_pages.value = kv_cache.append_pages(k_pages.value, k,
                                                  page_table, positions)
            v_pages.value = kv_cache.append_pages(v_pages.value, v,
                                                  page_table, positions)
        with jax.named_scope("serve_attn"):
            if S == 1:
                out = flash_lib.paged_decode_attention(
                    q[:, 0], k_pages.value, v_pages.value, page_table,
                    positions[:, 0],
                    impl=decode_ctx.get("attn_impl", "auto"))[:, None]
            elif decode_ctx.get("history"):
                out = flash_lib.paged_prefill_attention(
                    q, k_pages.value, v_pages.value, page_table, positions)
            else:
                out = attn_lib.attention(q, k, v, causal=True,
                                         impl=self.attn_impl)
        return nn.DenseGeneral(d, axis=(-2, -1), use_bias=False,
                               dtype=self.dtype, param_dtype=self.param_dtype,
                               name="out")(out)


class LlamaBlock(nn.Module):
    num_heads: int
    num_kv_heads: int
    head_dim: int
    ffn_dim: int
    rope_theta: float
    dtype: Any
    param_dtype: Any
    attn_impl: str = "auto"
    num_experts: int = 0     # >0 replaces the SwiGLU MLP with an MoE block (EP)
    moe_top_k: int = 2
    moe_capacity_factor: float = 1.25
    moe_dispatch_impl: str = "gather"  # sort|gather|einsum|dropless (parallel/moe.py)
    moe_combine_dtype: Any = None      # None -> fp32 combine (exact)
    moe_router_dtype: Any = None       # None -> fp32 logits matmul (exact)
    moe_router_impl: str = "reference"  # reference | fused (ops/fused_router)
    moe_ep_dispatch: str = "replicated"  # replicated|a2a|a2a_overlap (dropless)
    moe_ep_overlap_chunks: int = 2      # a2a_overlap double-buffer windows
    sp: bool = False

    @nn.compact
    def __call__(self, x, train: bool = True, decode_ctx: dict | None = None):
        rn = lambda name: RMSNorm(dtype=self.dtype, param_dtype=self.param_dtype,
                                  name=name)
        x = x + LlamaAttention(self.num_heads, self.num_kv_heads, self.head_dim,
                               self.rope_theta, self.dtype, self.param_dtype,
                               self.attn_impl, name="attn")(rn("attn_norm")(x), train,
                                                            decode_ctx)
        x = mesh_lib.constrain(x, _seq_rule("residual", self.sp))
        h = rn("mlp_norm")(x)
        d = x.shape[-1]
        if self.num_experts > 0:
            from pytorch_distributed_training_example_tpu.parallel.moe import MoEBlock

            # Serving decode reuses the training MoE block at batch-decode
            # shapes (T = B*S tokens). ``decode=True`` forces the dropless
            # route: capacity-dropped dispatch is non-causal (a token's k>1
            # choice competes for capacity with LATER tokens' k=0 choices),
            # so only per-token-independent dropless routing has an exact
            # incremental equivalent. Params are identical across dispatch
            # impls, so any trained checkpoint serves through this path.
            scope = (jax.named_scope("serve_moe") if decode_ctx is not None
                     else contextlib.nullcontext())
            with scope:
                h = MoEBlock(self.num_experts, self.ffn_dim,
                             top_k=self.moe_top_k,
                             capacity_factor=self.moe_capacity_factor,
                             dispatch_impl=self.moe_dispatch_impl,
                             combine_dtype=self.moe_combine_dtype,
                             router_dtype=self.moe_router_dtype,
                             router_impl=self.moe_router_impl,
                             ep_dispatch=self.moe_ep_dispatch,
                             ep_overlap_chunks=self.moe_ep_overlap_chunks,
                             dtype=self.dtype,
                             param_dtype=self.param_dtype,
                             name="moe")(h, train,
                                         decode=decode_ctx is not None)
        else:
            scope = (jax.named_scope("serve_mlp") if decode_ctx is not None
                     else contextlib.nullcontext())
            dense = lambda feat, name: nn.Dense(
                feat, use_bias=False, dtype=self.dtype,
                param_dtype=self.param_dtype, name=name)
            with scope:
                gate = dense(self.ffn_dim, "gate")(h)
                up = dense(self.ffn_dim, "up")(h)
                gate = mesh_lib.constrain(gate, _seq_rule("ffn_hidden"))
                up = mesh_lib.constrain(up, _seq_rule("ffn_hidden"))
                h = dense(d, "down")(nn.silu(gate) * up)
        x = x + h
        return mesh_lib.constrain(x, _seq_rule("residual", self.sp))


#: Remat policies for the grad-checkpoint config (selected by name so the
#: flag threads through Config/argparse). "nothing" is the measured default
#: (BENCH_LLAMA.json: rate-neutral at S=8192 b=1 vs no-remat, and the only
#: policy that admits b=2). The alternatives trade activation memory for
#: recompute FLOPs — A/B them with bench.py --remat-policy (see
#: PROFILE_LLAMA.md lever 4):
#:   nothing       recompute the whole block (minimum memory)
#:   dots          save every matmul output (maximum saveable under remat)
#:   dots_no_batch save matmul outputs with no batch dims (XLA's classic
#:                 "save weights-only matmuls" heuristic)
#:   attn_out      save only the attention outputs (tagged below): skips
#:                 recomputing the S^2 attention in the backward at the cost
#:                 of one [B,S,H,D] residual per layer
REMAT_POLICIES = {
    "nothing": jax.checkpoint_policies.nothing_saveable,
    "dots": jax.checkpoint_policies.dots_saveable,
    "dots_no_batch": jax.checkpoint_policies.dots_with_no_batch_dims_saveable,
    "attn_out": jax.checkpoint_policies.save_only_these_names("attn_out"),
}


class Llama(nn.Module):
    vocab_size: int = 128256
    num_layers: int = 32
    num_heads: int = 32
    num_kv_heads: int = 8
    d_model: int = 4096
    ffn_dim: int = 14336
    max_seq_len: int = 8192
    rope_theta: float = 500000.0
    dtype: Any = jnp.float32
    param_dtype: Any = jnp.float32
    remat: bool = False
    remat_policy: str = "nothing"  # key into REMAT_POLICIES
    scan_layers: bool = False
    attn_impl: str = "auto"
    num_experts: int = 0
    moe_top_k: int = 2
    moe_capacity_factor: float = 1.25
    moe_dispatch_impl: str = "gather"
    moe_combine_dtype: Any = None
    moe_router_dtype: Any = None
    moe_router_impl: str = "reference"
    moe_ep_dispatch: str = "replicated"
    moe_ep_overlap_chunks: int = 2
    sp: bool = False
    logits_dtype: Any = jnp.float32  # storage dtype; loss upcasts per-element

    @property
    def head_dim(self):
        return self.d_model // self.num_heads

    @nn.compact
    def __call__(self, tokens, train: bool = True,
                 decode_ctx: dict | None = None):
        """``decode_ctx`` switches to the serving forward (serve/engine.py):
        a dict with ``positions`` [B,S], ``page_table`` [B,max_pages],
        ``cache_spec`` (num_pages, page_size), ``last_index`` [B] and
        optionally ``attn_impl`` / ``history`` / ``all_logits``. K/V live
        in the flax ``cache`` collection (paged pools); the return value is
        next-token logits [B, vocab] taken at ``last_index`` — or the full
        [B, S, vocab] when ``all_logits`` is set (the speculative-decode
        verify step scores every draft position in one forward)."""
        x = nn.Embed(self.vocab_size, self.d_model, dtype=self.dtype,
                     param_dtype=self.param_dtype, name="embed")(tokens)
        x = mesh_lib.constrain(x, _seq_rule("residual", self.sp))

        block_cls = LlamaBlock
        if self.remat:
            if self.remat_policy not in REMAT_POLICIES:
                raise ValueError(
                    f"unknown remat_policy {self.remat_policy!r}; "
                    f"have {sorted(REMAT_POLICIES)}")
            block_cls = nn.remat(
                LlamaBlock, prevent_cse=False,
                policy=REMAT_POLICIES[self.remat_policy])
        block_args = dict(
            num_heads=self.num_heads, num_kv_heads=self.num_kv_heads,
            head_dim=self.head_dim, ffn_dim=self.ffn_dim,
            rope_theta=self.rope_theta, dtype=self.dtype,
            param_dtype=self.param_dtype, attn_impl=self.attn_impl,
            num_experts=self.num_experts, moe_top_k=self.moe_top_k,
            moe_capacity_factor=self.moe_capacity_factor,
            moe_dispatch_impl=self.moe_dispatch_impl,
            moe_combine_dtype=self.moe_combine_dtype,
            moe_router_dtype=self.moe_router_dtype,
            moe_router_impl=self.moe_router_impl,
            moe_ep_dispatch=self.moe_ep_dispatch,
            moe_ep_overlap_chunks=self.moe_ep_overlap_chunks, sp=self.sp)
        if self.scan_layers:
            # One stacked block scanned over a leading 'layers' dim: constant
            # trace/compile cost regardless of depth. The body wrapper adapts
            # LlamaBlock's single-array return to scan's (carry, ys) contract.
            # Under ``decode_ctx`` the per-block paged K/V pools become a
            # STACKED carry too: scanning the ``cache`` collection on axis 0
            # gives [L, P, page_size, Hkv, D] pools, so scanned checkpoints
            # serve without a retrain (serve/kv_cache.py rank-dispatches its
            # page ops on the extra leading dim).
            inner = block_cls

            class _ScanBody(nn.Module):
                @nn.compact
                def __call__(self, carry, _):
                    return inner(name="block", **block_args)(
                        carry, train, decode_ctx), None

            variable_axes = {"params": 0}
            if decode_ctx is not None:
                variable_axes["cache"] = 0
            ScanBlocks = nn.scan(
                _ScanBody, variable_axes=variable_axes,
                split_rngs={"params": True, "dropout": True},
                length=self.num_layers)
            x, _ = ScanBlocks(name="blocks")(x, None)
        else:
            for i in range(self.num_layers):
                x = block_cls(name=f"block_{i}", **block_args)(x, train,
                                                               decode_ctx)
        if decode_ctx is not None:
            # Serving: only the last real position's logits matter (the
            # next-token distribution). Gather the hidden row BEFORE the
            # [d, vocab] head matmul — at decode S == 1 this is free, at
            # prefill it turns a [B,S,V] matmul into [B,V]. The speculative
            # verify step instead needs EVERY position's next-token
            # distribution (one score per draft token plus the bonus), so
            # ``decode_ctx["all_logits"]`` (static — its own compiled
            # program) skips the gather and returns [B, S, vocab].
            with jax.named_scope("serve_head"):
                norm = RMSNorm(dtype=self.dtype, param_dtype=self.param_dtype,
                               name="final_norm")
                head = nn.Dense(self.vocab_size, use_bias=False,
                                dtype=self.dtype,
                                param_dtype=self.param_dtype, name="lm_head")
                if decode_ctx.get("all_logits"):
                    # Score every draft position through the SAME [B, d]
                    # head matmul shape the decode program uses (unrolled
                    # over the small verify width) rather than one
                    # [B, S, vocab] matmul: XLA lowers the rank-3 head
                    # differently (bf16 materialization vs fused fp32
                    # accumulation), and that sub-bf16 numerical skew can
                    # flip near-tie argmaxes — which would break the
                    # bit-identity contract between speculative verify and
                    # plain decode.
                    # The fp32 cast must land INSIDE the stack: XLA fuses
                    # convert(dot) into an fp32-accumulated matmul, and the
                    # decode program gets that fusion — a stack between dot
                    # and convert would materialize bf16 logits instead and
                    # reintroduce grid ties.
                    logits = jnp.stack(
                        [head(norm(x[:, m])).astype(self.logits_dtype)
                         for m in range(x.shape[1])], axis=1)
                else:
                    idx = decode_ctx["last_index"].astype(jnp.int32)  # [B]
                    x = jnp.take_along_axis(
                        x, idx[:, None, None].astype(jnp.int32), axis=1)[:, 0]
                    logits = head(norm(x))
            return logits.astype(self.logits_dtype)
        x = RMSNorm(dtype=self.dtype, param_dtype=self.param_dtype,
                    name="final_norm")(x)
        x = mesh_lib.constrain(x, _seq_rule("residual", self.sp))
        logits = nn.Dense(self.vocab_size, use_bias=False, dtype=self.dtype,
                          param_dtype=self.param_dtype, name="lm_head")(x)
        logits = mesh_lib.constrain(logits, _seq_rule("logits", self.sp))
        return logits.astype(self.logits_dtype)


TP_RULES = (
    (r"attn/(query|key|value)/kernel", P(None, "model", None)),
    (r"attn/out/kernel", P("model", None, None)),
    (r"(gate|up)/kernel", P(None, "model")),
    (r"down/kernel", P("model", None)),
    (r"embed/embedding", P(None, "model")),
    (r"lm_head/kernel", P(None, "model")),
    # MoE variant: experts sharded on the expert axis (EP), router replicated.
    (r"moe/experts/w_(up|down)", P("expert", None, "model")),
    (r"moe/router/kernel", P()),
)


def llama3_8b(**kw) -> Llama:
    return Llama(**kw)


def llama_400m(**kw) -> Llama:
    """One-chip bench scale: full Llama architecture (GQA 4:1, RoPE,
    SwiGLU, RMSNorm) at ~400M params so the family has a measured
    single-v5e perf row (BENCH_LLAMA.json) alongside the 8B feasibility
    artifact. Llama-2-sized vocab keeps embeddings from dominating."""
    kw.setdefault("vocab_size", 32000)
    kw.setdefault("num_layers", 16)
    kw.setdefault("num_heads", 16)
    kw.setdefault("num_kv_heads", 4)
    kw.setdefault("d_model", 1024)
    kw.setdefault("ffn_dim", 4096)
    kw.setdefault("max_seq_len", 2048)
    return Llama(**kw)


def llama_tiny(**kw) -> Llama:
    """Test-scale Llama (same architecture, toy dims)."""
    kw.setdefault("vocab_size", 512)
    kw.setdefault("num_layers", 2)
    kw.setdefault("num_heads", 4)
    kw.setdefault("num_kv_heads", 2)
    kw.setdefault("d_model", 128)
    kw.setdefault("ffn_dim", 256)
    kw.setdefault("max_seq_len", 256)
    return Llama(**kw)


def llama_moe_tiny(**kw) -> Llama:
    """Test-scale MoE Llama (8 experts, top-2 routing)."""
    kw.setdefault("num_experts", 8)
    return llama_tiny(**kw)


def llama_moe_520m(**kw) -> Llama:
    """Bench-scale MoE Llama for the measured e2e EP row (BENCH_MOE.json):
    the llama_400m trunk (d=1024, GQA 4:1, RoPE) at 12 layers with
    8-expert top-2 MoE FFNs of ffn_dim 2048 — ~520M total / ~220M active
    params. Sized so AdamW optimizer state (12 B/param f32) + bf16
    compute copies + activations fit ONE v5e's 16 GB HBM: the 400m
    backbone with 8 experts (1.18 B total) measured RESOURCE_EXHAUSTED
    at any batch, with or without remat — expert stacks multiply FFN
    params 8x, and optimizer memory, not activations, is the binding
    constraint on a single chip (EP sharding divides it on real pods)."""
    kw.setdefault("num_experts", 8)
    kw.setdefault("num_layers", 12)
    kw.setdefault("ffn_dim", 2048)
    return llama_400m(**kw)


def num_params(cfg: Llama) -> int:
    d, L, V = cfg.d_model, cfg.num_layers, cfg.vocab_size
    hd = cfg.head_dim
    attn = d * cfg.num_heads * hd + 2 * d * cfg.num_kv_heads * hd \
        + cfg.num_heads * hd * d
    if cfg.num_experts:
        # MoE block: E stacked (w_up, w_down) expert FFNs + fp32 router
        mlp = cfg.num_experts * 2 * d * cfg.ffn_dim + d * cfg.num_experts
    else:
        mlp = 3 * d * cfg.ffn_dim
    return V * d + L * (attn + mlp + 2 * d) + d + d * V


def num_params_active(cfg: Llama, top_k: int | None = None) -> int:
    """Parameters touched per token — the honest FLOPs basis for MoE MFU
    (6*N_active, PaLM-style): only the routed experts' FFN weights count,
    everything else as in the dense model. ``top_k`` defaults to the
    routing the model actually executes (``cfg.moe_top_k``) so the MFU
    basis can't drift from the config (ADVICE r5)."""
    if not cfg.num_experts:
        return num_params(cfg)
    if top_k is None:
        top_k = cfg.moe_top_k
    top_k = min(top_k, cfg.num_experts)
    total = num_params(cfg)
    per_expert = 2 * cfg.d_model * cfg.ffn_dim
    inactive = (cfg.num_experts - top_k) * per_expert * cfg.num_layers
    return total - inactive
