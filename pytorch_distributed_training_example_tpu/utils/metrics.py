"""In-step metrics (loss/accuracy) and MFU accounting.

Metric reduction happens *inside* the compiled step over the sharded batch
(reference: ``dist.all_reduce(metric_sum)`` after the fact, SURVEY.md §3.3) —
with GSPMD, ``jnp.sum`` over a batch-sharded array already is the global
reduction.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import optax


def _integer_ce(logits, labels):
    """Per-element integer-label CE that never materializes fp32 logits.

    The optax formulation upcasts + max-shifts the whole logits tensor
    first; with two consumers (gather and exp-sum) XLA materializes the
    shifted ``f32[B,S,V]`` in HBM — measured 3.3 GB/step and ~9 ms of the
    GPT-2 vocab slice (xplane: ``%fusion.3236`` writing f32[16,1024,50257]).
    Here every large elementwise op has exactly one reduction consumer, so
    each fuses into its reduce and only the bf16 model logits are ever
    resident: the label term uses an iota==label mask (whose gradient is
    elementwise, not a scatter), the lse shift uses a stop-gradient max,
    and fp32 happens per-element inside the fusions.
    """
    f32 = jnp.float32
    m = jax.lax.stop_gradient(jnp.max(logits, axis=-1).astype(f32))
    iota = jax.lax.broadcasted_iota(jnp.int32, logits.shape, logits.ndim - 1)
    onehot_mask = iota == labels[..., None]
    label_logit = jnp.sum(
        jnp.where(onehot_mask, logits.astype(f32), 0.0), axis=-1)
    sumexp = jnp.sum(
        jnp.exp(logits.astype(f32) - m[..., None]), axis=-1)
    return jnp.log(sumexp) + m - label_logit


def cross_entropy(logits, labels, label_smoothing: float = 0.0):
    """Mean softmax CE over the (possibly sharded) batch, fp32 accumulation."""
    if label_smoothing > 0.0:
        logits = logits.astype(jnp.float32)
        onehot = optax.smooth_labels(
            jax.nn.one_hot(labels, logits.shape[-1]), label_smoothing
        )
        losses = optax.softmax_cross_entropy(logits, onehot)
    else:
        losses = _integer_ce(logits, labels)
    return losses.mean()


def per_example_cross_entropy(logits, labels):
    """Unreduced CE per example/token (fp32)."""
    return _integer_ce(logits, labels)


def topk_correct(logits, labels, ks=(1, 5), mask=None):
    """Count of top-k correct predictions (summed over the global batch).

    ``mask`` (float [batch]) zeroes out padded examples in the final eval
    batch (the DistributedSampler wrap-around analog).
    """
    out = {}
    maxk = max(ks)
    maxk = min(maxk, logits.shape[-1])
    _, pred = jax.lax.top_k(logits, maxk)
    hit = pred == labels[..., None]
    for k in ks:
        correct = hit[..., : min(k, maxk)].any(-1)
        if mask is not None:
            out[f"top{k}"] = jnp.sum(correct.astype(jnp.float32) * mask)
        else:
            out[f"top{k}"] = jnp.sum(correct)
    return out


# ---------------------------------------------------------------------------
# MFU — the driver metric (BASELINE.json): achieved FLOP/s vs peak.
# ---------------------------------------------------------------------------

#: Peak dense bf16 FLOP/s per chip by device kind (public spec sheets).
PEAK_FLOPS = {
    "tpu v4": 275e12,
    "tpu v5 lite": 197e12,  # v5e
    "tpu v5": 459e12,       # v5p
    "tpu v5p": 459e12,
    "tpu v6 lite": 918e12,  # trillium
    "cpu": 1e12,            # nominal; CPU MFU is not meaningful
}

#: HBM bandwidth per chip (GB/s) — the other roofline axis.
PEAK_HBM_GBPS = {
    "tpu v4": 1228.0,
    "tpu v5 lite": 819.0,   # v5e
    "tpu v5": 2765.0,       # v5p
    "tpu v5p": 2765.0,
    "tpu v6 lite": 1640.0,  # trillium
    "cpu": 100.0,
}


def finalize_eval_sums(sums: dict) -> dict:
    """Normalize accumulated eval-step outputs to per-example averages.

    ``eval_step`` emits mask-weighted ``*_sum`` metrics plus a ``count``;
    callers accumulate them across batches and call this once. Shared by
    the trainer's evaluate loop and the convergence harness's
    seen-samples probe so the key convention lives in one place.
    """
    count = max(sums.pop("count", 0.0), 1.0)
    return {k.removesuffix("_sum"): v / count for k, v in sums.items()}


def peak_hbm_gbps(device=None) -> float:
    if device is None:
        device = jax.devices()[0]
    kind = device.device_kind.lower()
    for key, val in PEAK_HBM_GBPS.items():
        if key in kind:
            return val
    return PEAK_HBM_GBPS["cpu"]


def peak_flops_per_chip(device=None) -> float:
    if device is None:
        device = jax.devices()[0]
    kind = device.device_kind.lower()
    for key, val in PEAK_FLOPS.items():
        if key in kind:
            return val
    return PEAK_FLOPS["cpu"]


def training_flops_per_example(fwd_flops: float) -> float:
    """fwd + bwd ~= 3x forward (bwd is 2x: grads wrt activations and params)."""
    return 3.0 * fwd_flops


def mfu(examples_per_sec_per_chip: float, fwd_flops_per_example: float,
        device=None) -> float:
    achieved = examples_per_sec_per_chip * training_flops_per_example(fwd_flops_per_example)
    return achieved / peak_flops_per_chip(device)


def transformer_flops_per_token(n_params: int, seq_len: int, n_layers: int,
                                d_model: int) -> float:
    """Forward FLOPs/token: 2*N plus attention's 2*2*L*s*d (PaLM appendix-B style)."""
    return 2.0 * n_params + 4.0 * n_layers * seq_len * d_model
