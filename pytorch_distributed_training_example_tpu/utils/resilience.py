"""Preemption-safe shutdown + retriable filesystem I/O.

The TPU recovery model this repo targets (``utils/watchdog.py`` docstring,
SURVEY.md §7(b)) is "gang-scheduled slices get preempted and restart from the
latest checkpoint". Cloud TPU preemption is delivered as SIGTERM with a grace
window — before this module, a SIGTERM mid-epoch simply killed the process and
every optimizer step since the last checkpoint cadence was lost.

Two pieces:

1. **Preemption handler**: :func:`install` registers a SIGTERM/SIGINT handler
   that only sets a flag. The train loop polls :func:`preempted` at step
   boundaries (``core/trainer.py``); on trip it finishes the in-flight step,
   takes a *blocking* emergency checkpoint, emits the telemetry goodput
   summary (the trainer's shutdown path), and exits with
   :data:`PREEMPTED_EXIT_CODE` — distinct from crash codes so a supervisor
   (``launch.py --restart-policy``) can relaunch ``--resume auto`` only for
   preemptions. A second signal while the flag is already set restores the
   previous handlers, so a third delivery force-kills a stuck shutdown.

2. **Retriable I/O**: :func:`retriable_io` runs one filesystem operation with
   bounded exponential backoff on ``OSError`` — transient NFS/GCS-fuse
   hiccups must not lose a checkpoint. A process-wide fault hook
   (:func:`set_fault_hook`) lets the chaos harness (``utils/chaos.py``)
   inject deterministic failures through the exact same code path real
   errors take.
"""

from __future__ import annotations

import logging
import signal
import threading
import time
from typing import Callable

log = logging.getLogger("pdtx")

#: Exit code of a graceful preemption shutdown. 75 is EX_TEMPFAIL ("temporary
#: failure, try again later") — the supervisor's restart predicate, and
#: distinct from the fault injector's hard-kill (57) and ordinary crashes.
#: Both diagnostic exits (75 and 76) dump the flight-recorder ring
#: (``utils/fleetobs.py`` -> ``flightrec*.jsonl``) on the way out, so a
#: post-mortem never starts from an empty log.
PREEMPTED_EXIT_CODE = 75

#: Exit code of an *abrupt* simulated host loss (chaos ``kill_host``): the
#: process dies without an emergency checkpoint, exactly like real hardware
#: (the one exception: two tiny bounded appends — the dead-host record and
#: the flight-recorder dump). An elastic supervisor (``launch.py --elastic``)
#: treats it as restartable — at a smaller world size, per the dead-host
#: records (``utils/elastic``); a fixed-gang supervisor only restarts it
#: under ``on-failure``.
HOST_LOST_EXIT_CODE = 76

_flag = threading.Event()
_signum: int | None = None
_prev_handlers: dict[int, object] = {}


class PreemptedExit(SystemExit):
    """Raised by the trainer after the emergency checkpoint is committed."""

    def __init__(self):
        super().__init__(PREEMPTED_EXIT_CODE)


def _handle(signum, frame):
    global _signum
    if _flag.is_set():
        # Second delivery: the operator (or platform) is insisting. Restore
        # the previous handlers so one more signal terminates immediately
        # instead of being swallowed by a wedged graceful shutdown.
        uninstall()
    _signum = signum
    _flag.set()


def install(signals=(signal.SIGTERM, signal.SIGINT)) -> bool:
    """Register the graceful-shutdown handler. Idempotent; main thread only.

    Returns False (and leaves handlers untouched) when called off the main
    thread — e.g. a Trainer driven from a worker thread in tests — where
    ``signal.signal`` would raise.
    """
    if _prev_handlers:
        return True
    try:
        for s in signals:
            _prev_handlers[s] = signal.signal(s, _handle)
    except ValueError:  # not the main thread
        _prev_handlers.clear()
        log.warning("resilience: cannot install signal handlers off the main "
                    "thread — preemption-safe shutdown disabled")
        return False
    return True


def uninstall() -> None:
    """Restore the pre-:func:`install` handlers (tests; second-signal path)."""
    for s, h in list(_prev_handlers.items()):
        try:
            signal.signal(s, h)
        except (ValueError, TypeError):
            pass
    _prev_handlers.clear()


def preempted() -> bool:
    """True once a shutdown signal arrived; polled at step boundaries."""
    return _flag.is_set()


def preempt_signal() -> int | None:
    """The signal number that tripped the flag (None if untripped)."""
    return _signum


def reset() -> None:
    """Clear the flag (tests only — a real preemption is never un-asked)."""
    global _signum
    _flag.clear()
    _signum = None


def trip() -> None:
    """Set the flag programmatically (tests / cooperative shutdown)."""
    _flag.set()


# ---------------------------------------------------------------------------
# Retriable filesystem I/O.
# ---------------------------------------------------------------------------

#: When set, called as ``hook(what)`` before every retriable operation; the
#: chaos harness raises OSError from it to exercise the retry path without
#: touching real files.
_fault_hook: Callable[[str], None] | None = None


def set_fault_hook(fn: Callable[[str], None] | None) -> None:
    global _fault_hook
    _fault_hook = fn


def retriable_io(fn, *args, _what: str = "io", _attempts: int = 4,
                 _base_delay_s: float = 0.05, **kwargs):
    """Run ``fn(*args, **kwargs)`` retrying OSError with exponential backoff.

    Bounded: ``_attempts`` tries total, delays ``_base_delay_s * 2**k``
    between them; the final failure re-raises the original error. Transient
    shared-filesystem errors (ESTALE, EIO on NFS attribute revalidation,
    GCS-fuse 5xx surfaced as EIO) resolve well inside this window; real
    persistent failures still surface — loudly, after the warnings.
    """
    delay = _base_delay_s
    for attempt in range(_attempts):
        try:
            if _fault_hook is not None:
                _fault_hook(_what)
            return fn(*args, **kwargs)
        except OSError as e:
            if attempt == _attempts - 1:
                raise
            log.warning(
                "retriable io [%s] failed (%s: %s) — retry %d/%d in %.2fs",
                _what, type(e).__name__, e, attempt + 1, _attempts - 1, delay)
            time.sleep(delay)
            delay *= 2
