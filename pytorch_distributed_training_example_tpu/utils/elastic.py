"""Elastic resume: batch-rescale policies + index-stream remap (pure functions).

When the supervisor (``launch.py --elastic``) relaunches a shrunk/grown gang,
the training geometry changes: the data-parallel world size W goes from
``old_world`` to ``new_world`` while the checkpoint on disk was written under
the old geometry. Everything needed to continue *sample-exact* reduces to two
pure decisions, both implemented here with no jax dependency so they are
unit-testable and usable from the (jax-free) supervisor:

1. **Batch policy** (:func:`rescale`) — what happens to the global batch:

   ========================  ==============  ===================  ==========
   policy                    global batch    grad accumulation    learning
                                                                  rate
   ========================  ==============  ===================  ==========
   ``keep_global_batch``     unchanged       scaled by W_old/W_new unchanged
   ``scale_lr``              scaled by        unchanged            scaled by
                             W_new/W_old                          W_new/W_old
   ========================  ==============  ===================  ==========

   ``keep_global_batch`` preserves the optimization trajectory exactly: the
   same samples enter the same optimizer updates in the same order (the
   per-device microbatch stays constant; a shrink just replays more
   microbatches through ``lax.scan``), so the loss curve matches a
   fixed-topology run step-for-step and the LR schedule needs no adjustment.
   ``scale_lr`` is classic linear scaling (Goyal et al.): smaller world →
   smaller global batch → proportionally smaller LR. The *flat sample stream*
   is still exactly the uninterrupted one (see invariance note below), but
   optimizer-update boundaries move, so the loss curve is only statistically
   — not bitwise — comparable, and the schedule continues on the
   optimizer-step axis.

2. **Index-stream remap** (:func:`remap_step_offset`) — where to continue in
   the epoch's sample stream. A mid-epoch checkpoint records ``step_offset``
   in *old* steps; the sample position is ``step_offset * old_global_batch``
   and the resumed loader starts at batch ``samples // new_global_batch``.

**Why the sampler is world-size invariant** (the property that makes all of
this sample-exact): :class:`~...data.sampler.ShardedSampler` deals rank ``r``
of ``W`` the strided slice ``perm[r::W]`` of one seed-deterministic global
permutation, with ``drop_last`` truncating to a multiple of W. Global batch
``b`` — the union over ranks of each rank's batch ``b`` — is therefore the
*contiguous* slice ``perm[b*G : (b+1)*G]`` as a set, for any W dividing the
global batch G. Steps per epoch are identical too: ``floor(floor(N/W) /
(G/W)) == floor(N/G)`` for every W | G (if some multiple ``q*G`` landed in
``(N - N%W, N]`` then ``N = q*G + s`` with ``s < N%W < W``, but ``N%W == s``
— contradiction). So no sample is dropped or double-consumed across a world-
size change; :func:`~...data.sampler.global_sample_stream` materializes the
stream for tests and drills.

The dead-host protocol (``dead_hosts.jsonl``) is how an abrupt host loss
(chaos ``kill_host``, or a real hard failure detected by a health probe)
tells the supervisor to shrink: the dying attempt appends one JSON line into
the checkpoint dir; the supervisor reads the unique host ids and relaunches
with that many fewer hosts.
"""

from __future__ import annotations

import dataclasses
import json
import os

KEEP_GLOBAL_BATCH = "keep_global_batch"
SCALE_LR = "scale_lr"
POLICIES = (KEEP_GLOBAL_BATCH, SCALE_LR)

#: One JSON line per lost host, appended into the checkpoint/log dir by the
#: dying attempt and read by the supervisor before relaunch.
DEAD_HOSTS_FILE = "dead_hosts.jsonl"

#: The grow-side mirror: one JSON line per host COMING BACK (repaired, or a
#: preemption ending), appended by whoever notices — a node manager, a health
#: probe, the returning host itself. The supervisor reads both files and
#: relaunches at ``base_world - |currently dead|``, so a return grows the
#: world back (bounded by ``--elastic MIN[:MAX]``'s MAX and the base size).
RETURNED_HOSTS_FILE = "returned_hosts.jsonl"


@dataclasses.dataclass(frozen=True)
class BatchPlan:
    """Result of :func:`rescale` — the new geometry, plus provenance."""

    policy: str
    old_world: int
    new_world: int
    global_batch_size: int
    grad_accum_steps: int
    lr_scale: float
    note: str

    def describe(self) -> str:
        return (f"elastic [{self.policy}]: world {self.old_world} -> "
                f"{self.new_world}, global_batch={self.global_batch_size}, "
                f"grad_accum={self.grad_accum_steps}, "
                f"lr_scale={self.lr_scale:g} ({self.note})")


def rescale(policy: str, *, old_world: int, new_world: int,
            global_batch: int, grad_accum: int = 1) -> BatchPlan:
    """Pure batch-geometry policy: old world -> new world.

    ``old_world``/``new_world`` are data-parallel degrees (``mesh data*fsdp``
    in this repo). ``global_batch``/``grad_accum`` are the values *recorded at
    save time* — rescaling always starts from the geometry that produced the
    checkpoint, so repeated shrinks compose correctly.
    """
    if policy not in POLICIES:
        raise ValueError(f"unknown elastic policy {policy!r}; one of {POLICIES}")
    if old_world < 1 or new_world < 1:
        raise ValueError(f"world sizes must be >= 1, got {old_world} -> {new_world}")
    if grad_accum < 1:
        raise ValueError(f"grad_accum must be >= 1, got {grad_accum}")
    if global_batch % (old_world * grad_accum):
        raise ValueError(
            f"global_batch {global_batch} not divisible by old world "
            f"{old_world} x grad_accum {grad_accum}")

    if policy == KEEP_GLOBAL_BATCH:
        # Keep the per-device microbatch constant: the total microbatch count
        # per update is grad_accum * old_world; redistribute it over the new
        # world. When the redistribution isn't integral (e.g. 3 -> 2 hosts),
        # round accumulation UP to the next value that divides the per-host
        # batch — a slightly smaller microbatch, never a larger one.
        scaled = grad_accum * old_world
        accum, rem = divmod(scaled, new_world)
        if rem:
            accum += 1
        while global_batch % (new_world * accum):
            accum += 1
        note = ("per-device microbatch preserved" if not rem else
                "accumulation rounded up (non-integral world ratio)")
        return BatchPlan(policy, old_world, new_world, global_batch,
                         max(1, accum), 1.0, note)

    # SCALE_LR: linear scaling rule.
    scaled_gb, rem = divmod(global_batch * new_world, old_world)
    if rem or scaled_gb % (new_world * grad_accum):
        raise ValueError(
            f"scale_lr cannot produce an integral global batch: "
            f"{global_batch} * {new_world}/{old_world} with grad_accum "
            f"{grad_accum}")
    return BatchPlan(policy, old_world, new_world, scaled_gb, grad_accum,
                     new_world / old_world,
                     "linear LR scaling, per-device batch preserved")


def remap_step_offset(step_offset: int, old_global_batch: int,
                      new_global_batch: int) -> int:
    """Convert a mid-epoch step offset across a global-batch change.

    The invariant is the *sample* position: ``step_offset`` old-geometry
    steps consumed ``step_offset * old_global_batch`` samples of the epoch's
    flat stream; the resumed run continues at the batch covering the next
    sample. Non-divisible positions are rejected rather than silently
    replaying or skipping a partial batch — with both policies' integral
    constraints this cannot happen for offsets the trainer actually records.
    """
    samples = step_offset * old_global_batch
    offset, rem = divmod(samples, new_global_batch)
    if rem:
        raise ValueError(
            f"sample position {samples} (offset {step_offset} x gb "
            f"{old_global_batch}) is not a whole number of new batches "
            f"(gb {new_global_batch}) — cannot resume sample-exact")
    return offset


def remap_step_count(steps: int, old_global_batch: int,
                     new_global_batch: int) -> int:
    """Same sample-position math for step *counts* (``--steps-per-epoch``
    caps, cumulative step budgets)."""
    return remap_step_offset(steps, old_global_batch, new_global_batch)


def plan_from_record(recorded: dict, *, policy: str, new_world: int,
                     fallback_global_batch: int,
                     fallback_grad_accum: int = 1) -> BatchPlan | None:
    """Build a :class:`BatchPlan` from a checkpoint's recorded geometry.

    ``recorded`` is the manifest ``extra`` dict. Returns None when the
    checkpoint predates geometry recording (nothing to rescale against) or
    when the world size is unchanged.
    """
    old_world = recorded_world(recorded)
    if old_world is None or old_world == new_world:
        return None
    return rescale(
        policy, old_world=old_world, new_world=new_world,
        global_batch=int(recorded.get("global_batch_size",
                                      fallback_global_batch)),
        grad_accum=int(recorded.get("grad_accum", fallback_grad_accum)))


def recorded_world(recorded: dict) -> int | None:
    """Data-parallel degree recorded at save time (``mesh_shape`` data*fsdp,
    falling back to an explicit ``world`` field)."""
    mesh_shape = recorded.get("mesh_shape")
    if isinstance(mesh_shape, dict) and mesh_shape:
        return int(mesh_shape.get("data", 1)) * int(mesh_shape.get("fsdp", 1))
    world = recorded.get("world")
    return int(world) if world is not None else None


# ---------------------------------------------------------------------------
# Dead-host protocol (jax-free; shared by chaos harness and supervisor).
# ---------------------------------------------------------------------------


def _record_host_event(directory: str, filename: str, host: int, *,
                       world: int | None, step: int | None,
                       reason: str) -> str:
    path = os.path.join(directory, filename)
    row = {"host": int(host), "world": world, "step": step, "reason": reason}
    with open(path, "a") as fh:
        fh.write(json.dumps(row) + "\n")
    return path


def _read_host_counts(directory: str, filename: str) -> dict[int, int]:
    """host id -> number of recorded events (empty if no file). Unparseable
    lines (a host died mid-``write`` despite line-atomicity, filesystem
    truncation) are skipped — a lost record degrades to a same-size
    relaunch, never a crash. Shared by the dead-host AND returned-host
    readers, so both sides of the shrink/grow ledger get identical
    torn-tail tolerance; any OSError (not just a missing file — ESTALE on
    NFS, EIO mid-read) likewise degrades to "no records seen"."""
    path = os.path.join(directory, filename)
    counts: dict[int, int] = {}
    try:
        with open(path) as fh:
            for line in fh:
                line = line.strip()
                if not line:
                    continue
                try:
                    host = int(json.loads(line)["host"])
                except (ValueError, KeyError, TypeError):
                    continue
                counts[host] = counts.get(host, 0) + 1
    except OSError:
        pass
    return counts


def record_dead_host(directory: str, host: int, *, world: int | None = None,
                     step: int | None = None, reason: str = "") -> str:
    """Append one dead-host record; returns the file path. Append-only and
    line-atomic (one ``write`` call) so a dying process can't corrupt it."""
    return _record_host_event(directory, DEAD_HOSTS_FILE, host, world=world,
                              step=step, reason=reason)


def record_host_return(directory: str, host: int, *, world: int | None = None,
                       step: int | None = None, reason: str = "") -> str:
    """Append one host-return record (the grow-side mirror of
    :func:`record_dead_host`); returns the file path."""
    return _record_host_event(directory, RETURNED_HOSTS_FILE, host,
                              world=world, step=step, reason=reason)


def read_dead_hosts(directory: str) -> set[int]:
    """Unique host ids EVER recorded dead under ``directory``."""
    return set(_read_host_counts(directory, DEAD_HOSTS_FILE))


def read_returned_hosts(directory: str) -> set[int]:
    """Unique host ids ever recorded as returned under ``directory``."""
    return set(_read_host_counts(directory, RETURNED_HOSTS_FILE))


def effective_dead_hosts(directory: str) -> set[int]:
    """Hosts dead RIGHT NOW: recorded dead strictly more times than
    returned. Count-based (not set difference) so a host that dies, returns
    and dies again is correctly dead — both files are append-only logs."""
    dead = _read_host_counts(directory, DEAD_HOSTS_FILE)
    ret = _read_host_counts(directory, RETURNED_HOSTS_FILE)
    return {h for h, c in dead.items() if c > ret.get(h, 0)}
