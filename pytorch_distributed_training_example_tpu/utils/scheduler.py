"""Multi-tenant fleet scheduler: priorities, preemption, backfill (pure).

``launch.py --fleet jobs.json`` promotes the single-gang supervisor into a
control plane for N jobs sharing one device pool. All *decisions* live here,
jax-free and stdlib-only like :mod:`utils.elastic`, so they are unit-testable
without spawning anything: the launcher executes what :meth:`FleetScheduler.
plan` returns (spawn / SIGTERM) and reports exits back through
:meth:`FleetScheduler.on_exit`.

Model
-----
- **Pool**: ``pool`` interchangeable devices. A job holds ``world`` of them
  from launch until its process exits (a job being preempted still holds its
  devices — they free only when the emergency checkpoint is written and the
  process is gone).
- **Jobs** have a priority and a device range ``MIN[:MAX]`` (same grammar as
  ``--elastic``). Placement is priority-tiered: higher tiers get their
  minimums first AND grow toward their caps before a lower tier sees a
  single device. Within one tier, surplus devices are apportioned by the
  D'Hondt highest-averages method weighted by each job's last recorded
  goodput fraction (a job that turns devices into steps outbids one that
  burns them on restarts), quantized to damp run-to-run jitter.
- **Preemption** reuses the single-job machinery end to end: the launcher
  SIGTERMs the victim, the trainer's resilience path takes its emergency
  checkpoint and exits ``PREEMPTED_EXIT_CODE``; the scheduler re-queues the
  victim with *no restart-budget burn* (being evicted is the scheduler's
  doing, not the job's) and the relaunch appends ``--resume auto`` — resume
  is already sample-exact across world-size changes (utils/elastic.py).
  Victims are chosen lowest-priority-first and only ever from strictly
  lower tiers; a job can never preempt its own tier.
- **Backfill / shrink**: a job's allocatable ceiling is
  ``min(MAX, pool) - |effective_dead_hosts(ckdir)|`` — the same append-only
  ``dead_hosts.jsonl`` / ``returned_hosts.jsonl`` protocol the elastic
  supervisor reads. A ``kill_host`` that shrinks one job's gang returns the
  idled device to the pool, where the next plan hands it to whoever is
  waiting (the backfill path). A host-return record grows the ceiling back.
- **Backoff**: failures (including abrupt host loss) burn the per-job
  restart budget with doubling backoff. A job waiting out its backoff keeps
  a *claim* on its minimum so lower-priority jobs cannot squat on devices it
  is about to take back — claims bind only tiers below the claimant.
- **Straggler eviction** (``evict_after``): train jobs opt in to having the
  scheduler act on the straggler detector's verdicts. When a job's
  ``straggler.jsonl`` (written by the jax-side detector, read through the
  jax-free :mod:`utils.fleetobs` helpers) shows the SAME host flagged in
  ``evict_after`` consecutive windows, the scheduler records that host dead
  (the elastic dead-host protocol — the job's ceiling shrinks by one) and
  preempts the job through the normal SIGTERM path, so the exit is the
  graceful code and burns *no* restart budget; the relaunch backfills one
  host smaller. Suspicion **decays**: after ``evict_decay`` further
  scheduling decisions the host-return record is appended and the ceiling
  grows back (a transient slow host — thermal throttle, a noisy neighbour —
  is not branded forever). A job is never evicted below ``min_world``, and
  stale evidence never re-evicts: only flag rows appended since the last
  eviction count.

Determinism contract (the robustness gate diffs placement logs byte-for-
byte across same-seed chaos drills): no RNG, no wall-clock anywhere in a
decision. Time enters only as the caller-supplied monotonic ``now_s`` used
to expire backoff timers, and ``placement.jsonl`` rows carry a sequence
number, never a timestamp. Ties break on job name.
"""

from __future__ import annotations

import dataclasses
import json
import os
import re

from pytorch_distributed_training_example_tpu.utils import elastic
from pytorch_distributed_training_example_tpu.utils import fleetobs
from pytorch_distributed_training_example_tpu.utils import resilience

#: Decision log, one JSON row per scheduling action, in the fleet log dir.
PLACEMENT_FILE = "placement.jsonl"

#: Merged cluster-wide goodput summary written by the fleet launcher.
CLUSTER_GOODPUT_FILE = "cluster_goodput.json"

# Job lifecycle.
PENDING = "pending"        # waiting for devices (or a dependency)
RUNNING = "running"        # process alive, holds ``world`` devices
PREEMPTING = "preempting"  # SIGTERM sent; holds devices until exit
BACKOFF = "backoff"        # failed; eligible again at next_eligible_s
DONE = "done"              # exit 0
FAILED = "failed"          # restart budget exhausted / starved
TERMINAL = (DONE, FAILED)

_STEP_DIR_RE = re.compile(r"^step_\d+$")
_UNBOUNDED = 1 << 30


def parse_world(spec: str) -> tuple[int, int]:
    """``MIN`` or ``MIN:MAX`` -> (min_world, max_world); MAX defaults open
    (capped by the pool at plan time) — the ``--elastic`` grammar."""
    lo, _, hi = str(spec).partition(":")
    min_world = int(lo)
    max_world = int(hi) if hi else _UNBOUNDED
    if min_world < 1 or max_world < min_world:
        raise ValueError(f"world expects MIN[:MAX] with 1 <= MIN <= MAX, "
                         f"got {spec!r}")
    return min_world, max_world


# GL002: every filesystem touch in this module goes through one of these
# helpers under resilience.retriable_io — a transient NFS error must never
# crash the control plane that is supposed to survive everything else.
def _read_text(path: str) -> str:
    with open(path) as fh:
        return fh.read()


def _append_line(path: str, line: str) -> None:
    # One write call: line-atomic like the dead-host protocol.
    with open(path, "a") as fh:
        fh.write(line)


@dataclasses.dataclass(frozen=True)
class JobSpec:
    """One entry of ``jobs.json`` (immutable; runtime state lives in
    :class:`JobState`)."""

    name: str
    cmd: tuple[str, ...]
    priority: int = 0
    min_world: int = 1
    max_world: int = _UNBOUNDED
    max_restarts: int = 3
    backoff_s: float = 1.0
    after: str | None = None          # submit once this job has...
    after_event: str = "start"        # ..."start"-ed or written a "checkpoint"
    env: tuple[tuple[str, str], ...] = ()  # extra child env (sorted pairs)
    # "train" (default) or "serve". Serving jobs drain on SIGTERM instead of
    # checkpoint-and-yield (serve/run.py), so the fleet surfaces them
    # separately and the launcher exports PDTX_JOB_KIND to the child.
    kind: str = "train"
    # Straggler-fed eviction (train jobs): preempt + mark one host dead when
    # straggler.jsonl flags it this many CONSECUTIVE windows. 0 = disabled.
    evict_after: int = 0
    # Scheduling decisions after which an evicted host's suspicion decays
    # (host-return record appended; the ceiling grows back). Decision-count
    # based, not wall-clock — placement logs stay byte-reproducible.
    evict_decay: int = 8

    @property
    def checkpoint_dir(self) -> str | None:
        """The job's ``--checkpoint-dir`` (last wins) — where its dead-host
        records, chaos log, and goodput.json live."""
        value = None
        for i, tok in enumerate(self.cmd[:-1]):
            if tok == "--checkpoint-dir":
                value = self.cmd[i + 1]
        return value


@dataclasses.dataclass
class JobState:
    spec: JobSpec
    status: str = PENDING
    world: int = 0                 # devices held right now
    restarts: int = 0              # budget burned (preemption is free)
    attempts: int = 0              # launches so far (drives --resume auto)
    started: bool = False
    next_eligible_s: float = 0.0   # backoff deadline (monotonic clock)
    last_exit: int | None = None
    weight: float = 1.0            # quantized goodput fraction
    #: Quantized sliding-window SLO attainment (serve jobs only; 1.0 until
    #: the job's slo.jsonl reports otherwise). Multiplies ``weight`` in the
    #: D'Hondt quotient — a replica missing its latency targets bids for
    #: surplus devices at a discount, it is never starved below MIN.
    slo_attainment: float = 1.0
    #: Straggler-eviction bookkeeping: how many straggler.jsonl rows were
    #: consumed by the last eviction (stale evidence never re-evicts), and
    #: the evicted hosts still under suspicion as (host, seq-at-eviction)
    #: pairs — suspicion decays after ``evict_decay`` further decisions.
    straggler_rows_seen: int = 0
    suspects: list[tuple[int, int]] = dataclasses.field(default_factory=list)

    @property
    def name(self) -> str:
        return self.spec.name


def load_jobs(path: str) -> tuple[int, list[JobSpec]]:
    """Parse ``jobs.json``: ``{"pool": N, "jobs": [{...}]}``.

    Each job: ``name``, ``cmd`` (argv list, like the launcher's ``--``
    remainder), optional ``priority`` (int, higher wins), ``world``
    (``"MIN[:MAX]"``), ``max_restarts``, ``backoff_s``, ``after`` (+
    ``after_event``: ``start`` | ``checkpoint``) and ``env`` (dict).
    Validation is eager — a fleet that can never place a job fails at load,
    not an hour in.
    """
    doc = json.loads(resilience.retriable_io(
        _read_text, path, _what="jobs.json read"))
    pool = int(doc.get("pool", 0))
    if pool < 1:
        raise ValueError(f"jobs.json needs a positive device pool, "
                         f"got {doc.get('pool')!r}")
    specs: list[JobSpec] = []
    names: set[str] = set()
    for row in doc.get("jobs", []):
        name = str(row["name"])
        if name in names:
            raise ValueError(f"duplicate job name {name!r}")
        names.add(name)
        cmd = tuple(str(t) for t in row["cmd"])
        if not cmd:
            raise ValueError(f"job {name!r} has an empty cmd")
        min_world, max_world = parse_world(row.get("world", "1"))
        if min_world > pool:
            raise ValueError(f"job {name!r} needs at least {min_world} "
                             f"devices but the pool is {pool}")
        after_event = str(row.get("after_event", "start"))
        if after_event not in ("start", "checkpoint"):
            raise ValueError(f"job {name!r}: after_event must be 'start' or "
                             f"'checkpoint', got {after_event!r}")
        kind = str(row.get("kind", "train"))
        if kind not in ("train", "serve"):
            raise ValueError(f"job {name!r}: kind must be 'train' or "
                             f"'serve', got {kind!r}")
        evict_after = int(row.get("evict_after", 0))
        if evict_after < 0:
            raise ValueError(f"job {name!r}: evict_after must be >= 0 "
                             f"(0 disables), got {evict_after}")
        if evict_after and kind != "train":
            raise ValueError(f"job {name!r}: evict_after applies to train "
                             f"jobs only (kind={kind!r})")
        evict_decay = int(row.get("evict_decay", 8))
        if evict_decay < 1:
            raise ValueError(f"job {name!r}: evict_decay must be >= 1, "
                             f"got {evict_decay}")
        specs.append(JobSpec(
            name=name, cmd=cmd, priority=int(row.get("priority", 0)),
            min_world=min_world, max_world=max_world,
            max_restarts=int(row.get("max_restarts", 3)),
            backoff_s=float(row.get("backoff_s", 1.0)),
            after=row.get("after"), after_event=after_event,
            env=tuple(sorted((str(k), str(v))
                             for k, v in (row.get("env") or {}).items())),
            kind=kind, evict_after=evict_after, evict_decay=evict_decay))
    if not specs:
        raise ValueError("jobs.json has no jobs")
    for s in specs:
        if s.after is not None and s.after not in names:
            raise ValueError(f"job {s.name!r}: after={s.after!r} names no "
                             "job in this fleet")
        if s.after == s.name:
            raise ValueError(f"job {s.name!r} depends on itself")
    return pool, specs


def quantize_weight(goodput_fraction: float) -> float:
    """Goodput fraction -> placement weight, quantized to 0.1 steps with a
    floor so a catastrophically bad attempt still gets a hearing. Coarse on
    purpose: run-to-run goodput jitter must not flip placement decisions."""
    return max(0.1, round(float(goodput_fraction), 1))


class FleetScheduler:
    """Deterministic placement over one shared device pool.

    Drive it as an event loop::

        sched = FleetScheduler(pool, specs, log_dir=...)
        while not sched.finished():
            for d in sched.plan(now):   # applies transitions, logs rows
                ...spawn / SIGTERM per d["action"]...
            ...poll children; sched.on_exit(name, code, now) as they die...
    """

    def __init__(self, pool: int, specs: list[JobSpec],
                 log_dir: str | None = None):
        if pool < 1:
            raise ValueError(f"pool must be >= 1, got {pool}")
        self.pool = pool
        self.jobs: dict[str, JobState] = {}
        for s in specs:
            if s.name in self.jobs:
                raise ValueError(f"duplicate job name {s.name!r}")
            self.jobs[s.name] = JobState(spec=s)
        self._seq = 0
        self._placement_path = (os.path.join(log_dir, PLACEMENT_FILE)
                                if log_dir else None)

    # ------------------------------------------------------------- queries

    def state(self, name: str) -> JobState:
        return self.jobs[name]

    def held(self) -> int:
        """Devices held by live processes (running or still dying)."""
        return sum(st.world for st in self.jobs.values()
                   if st.status in (RUNNING, PREEMPTING))

    def free(self) -> int:
        return self.pool - self.held()

    def finished(self) -> bool:
        return all(st.status in TERMINAL for st in self.jobs.values())

    def next_deadline_s(self) -> float | None:
        """Earliest backoff expiry among waiting jobs, or None."""
        deadlines = [st.next_eligible_s for st in self.jobs.values()
                     if st.status == BACKOFF]
        return min(deadlines) if deadlines else None

    def live_jobs(self) -> list[str]:
        return sorted(n for n, st in self.jobs.items()
                      if st.status in (RUNNING, PREEMPTING))

    def gauges(self) -> dict[str, float]:
        """Cluster + per-job gauges for the fleet ``/metrics`` endpoint
        (exported under the ``pdtx_`` prefix by fleetobs.MetricsServer)."""
        by_status: dict[str, int] = {}
        for st in self.jobs.values():
            by_status[st.status] = by_status.get(st.status, 0) + 1
        out: dict[str, float] = {
            "fleet_pool_devices": self.pool,
            "fleet_devices_held": self.held(),
            "fleet_devices_free": self.free(),
            "fleet_jobs_total": len(self.jobs),
            "fleet_decisions_total": self._seq,
        }
        for status in (PENDING, RUNNING, PREEMPTING, BACKOFF, DONE, FAILED):
            out[f"fleet_jobs_{status}"] = by_status.get(status, 0)
        out["fleet_jobs_serve"] = sum(
            1 for st in self.jobs.values() if st.spec.kind == "serve")
        for name in sorted(self.jobs):
            st = self.jobs[name]
            out[f"fleet_job_world_{name}"] = st.world
            out[f"fleet_job_restarts_{name}"] = st.restarts
            if st.spec.kind == "serve":
                out[f"fleet_job_slo_attainment_{name}"] = st.slo_attainment
        return out

    # ------------------------------------------------------------ internals

    def _cap(self, st: JobState) -> int:
        """Allocatable ceiling right now: the spec's MAX clamped to the pool,
        minus the job's currently-dead hosts (count-based, so a host return
        restores the ceiling — same accounting as the elastic supervisor)."""
        cap = min(st.spec.max_world, self.pool)
        ckdir = st.spec.checkpoint_dir
        if ckdir and os.path.isdir(ckdir):
            cap -= len(elastic.effective_dead_hosts(ckdir))
        return max(cap, 0)

    def _dep_ready(self, st: JobState) -> bool:
        if st.spec.after is None:
            return True
        dep = self.jobs[st.spec.after]
        if st.spec.after_event == "checkpoint":
            ckdir = dep.spec.checkpoint_dir
            if not ckdir or not os.path.isdir(ckdir):
                return False
            try:
                names = resilience.retriable_io(
                    os.listdir, ckdir, _what="fleet dep probe")
            except OSError:
                return False
            return any(_STEP_DIR_RE.match(n) for n in names)
        return dep.started

    def _eligible(self, now_s: float) -> list[JobState]:
        out = []
        for st in self.jobs.values():
            if st.status == PENDING and self._dep_ready(st):
                out.append(st)
            elif st.status == BACKOFF and now_s >= st.next_eligible_s:
                out.append(st)
        out.sort(key=lambda s: (-s.spec.priority, s.name))
        return out

    def _claims_above(self, priority: int, now_s: float) -> int:
        """Devices reserved for higher-priority jobs waiting out a backoff:
        they will be back, and a lower tier must not squat on their minimum."""
        return sum(min(st.spec.min_world, self._cap(st))
                   for st in self.jobs.values()
                   if st.status == BACKOFF and now_s < st.next_eligible_s
                   and st.spec.priority > priority)

    def _log(self, action: str, st: JobState, world: int, reason: str):
        self._seq += 1
        row = {"seq": self._seq, "action": action, "job": st.name,
               "world": world, "free": self.free(), "reason": reason}
        if self._placement_path is not None:
            resilience.retriable_io(
                _append_line, self._placement_path, json.dumps(row) + "\n",
                _what="placement.jsonl append")
        return row

    # -------------------------------------------------------------- events

    def plan(self, now_s: float) -> list[dict]:
        """One scheduling pass. Applies transitions (PENDING/BACKOFF ->
        RUNNING, RUNNING -> PREEMPTING) and returns the decision rows the
        launcher must execute: ``launch`` (spawn at ``world``) and
        ``preempt`` (SIGTERM). Deterministic given job states and ``now_s``.
        """
        decisions: list[dict] = []
        # Serving SLO feedback (ROADMAP item 6): refresh each serve job's
        # sliding-window attainment from its atomically-replaced slo.jsonl
        # before weighing the surplus. Quantized like goodput, so the
        # placement log stays byte-reproducible for a given set of files.
        for st in self.jobs.values():
            if st.spec.kind == "serve":
                self._refresh_slo(st)
        # Straggler feedback (name order — deterministic): decay first so a
        # rehabilitated host's ceiling is back before this pass places
        # anything, then evict chronic stragglers; their devices count as
        # arriving supply (PREEMPTING) for the placement below.
        for name in sorted(self.jobs):
            self._decay_suspects(self.jobs[name], decisions)
        for name in sorted(self.jobs):
            self._evict_straggler(self.jobs[name], decisions)
        eligible = self._eligible(now_s)
        incoming = sum(st.world for st in self.jobs.values()
                       if st.status == PREEMPTING)
        # Priority-tiered: a tier gets its minimums AND grows toward its
        # caps before any lower tier sees a device.
        tiers: dict[int, list[JobState]] = {}
        for st in eligible:
            tiers.setdefault(st.spec.priority, []).append(st)
        for priority in sorted(tiers, reverse=True):
            tier = tiers[priority]  # name-sorted within the tier already
            avail = self.free() - self._claims_above(priority, now_s)
            launched: list[JobState] = []
            for st in tier:
                cap = self._cap(st)
                need = st.spec.min_world
                if cap < need:
                    continue  # dead hosts ate the range; wait for a return
                if avail >= need:
                    st.status = RUNNING
                    st.world = need
                    st.started = True
                    st.attempts += 1
                    avail -= need
                    launched.append(st)
                    continue
                # Not placeable: preempt strictly-lower tiers, cheapest
                # victims first (ascending priority, then name), but only
                # while the shortfall is real — devices already freeing
                # from in-flight preemptions count as arriving supply.
                victims = sorted(
                    (v for v in self.jobs.values()
                     if v.status == RUNNING and v.spec.priority < priority),
                    key=lambda v: (v.spec.priority, v.name))
                chosen: list[JobState] = []
                freed = 0
                for v in victims:
                    if avail + incoming + freed >= need:
                        break
                    chosen.append(v)
                    freed += v.world
                if avail + incoming + freed < need:
                    continue  # not satisfiable even by preempting everyone
                for v in chosen:
                    v.status = PREEMPTING
                    incoming += v.world
                    decisions.append(self._log(
                        "preempt", v, v.world,
                        f"preempted for {st.name} (priority "
                        f"{priority} > {v.spec.priority})"))
                # The candidate launches on a later pass, once the victims'
                # emergency checkpoints are written and their devices free.
            # Surplus within the tier: D'Hondt highest averages, weighted
            # by quantized goodput times quantized SLO attainment (serve
            # jobs; 1.0 for trainers), capped per job.
            while avail > 0:
                best = None
                best_score = (-1.0, "")
                for st in launched:
                    if st.world >= self._cap(st):
                        continue
                    score = (st.weight * st.slo_attainment / (st.world + 1),
                             st.name)
                    # Higher quotient wins; name ascending breaks ties.
                    if best is None or score[0] > best_score[0] or (
                            score[0] == best_score[0]
                            and score[1] < best_score[1]):
                        best, best_score = st, score
                if best is None:
                    break
                best.world += 1
                avail -= 1
            for st in launched:
                decisions.append(self._log(
                    "launch", st, st.world,
                    f"attempt {st.attempts}, range "
                    f"{st.spec.min_world}:{min(st.spec.max_world, self.pool)}"
                    f", cap {self._cap(st)}"))
        return decisions

    def _evict_straggler(self, st: JobState, decisions: list[dict]) -> None:
        """Preempt ``st`` and record its chronic straggler dead, if the
        evidence says so.

        Reads the job's ``straggler.jsonl`` through the jax-free fleetobs
        reader; acts only on flag rows appended SINCE the last eviction
        (the cursor), never evicts below ``min_world``, and quotes only
        configuration in the log row (the threshold, not the observed
        streak) so same-seed placement logs stay byte-identical.
        """
        sp = st.spec
        if sp.kind != "train" or sp.evict_after < 1 or st.status != RUNNING:
            return
        ckdir = sp.checkpoint_dir
        if not ckdir or not os.path.isdir(ckdir):
            return
        chronic = fleetobs.read_chronic_straggler(
            os.path.join(ckdir, fleetobs.STRAGGLER_FILE), sp.evict_after)
        if chronic is None or chronic["rows"] <= st.straggler_rows_seen:
            return  # no verdict, or no new evidence since the last eviction
        host = int(chronic["rank"])
        # Prospective ceiling check: evicting must leave the job placeable
        # (set-union, not +1 — re-evicting an already-dead rank id does not
        # shrink the ceiling further).
        dead_after = len(elastic.effective_dead_hosts(ckdir) | {host})
        if min(sp.max_world, self.pool) - dead_after < sp.min_world:
            return  # never shrink a job below its minimum
        st.straggler_rows_seen = int(chronic["rows"])
        elastic.record_dead_host(ckdir, host, world=st.world,
                                 reason="scheduler straggler eviction")
        st.status = PREEMPTING
        row = self._log(
            "preempt", st, st.world,
            f"straggler: host {host} flagged {sp.evict_after} consecutive "
            f"windows -> evict (suspicion decays after {sp.evict_decay} "
            f"decisions)")
        st.suspects.append((host, row["seq"]))
        decisions.append(row)

    def _decay_suspects(self, st: JobState, decisions: list[dict]) -> None:
        """Readmit evicted hosts whose suspicion has aged out: append the
        host-return record (the ceiling grows back; the next relaunch may
        use the host again) after ``evict_decay`` scheduling decisions —
        decision-sequence based, never wall-clock."""
        if not st.suspects:
            return
        keep: list[tuple[int, int]] = []
        for host, seq_at in st.suspects:
            if self._seq - seq_at < st.spec.evict_decay:
                keep.append((host, seq_at))
                continue
            ckdir = st.spec.checkpoint_dir
            if ckdir:
                elastic.record_host_return(
                    ckdir, host, reason="straggler suspicion decayed")
            decisions.append(self._log(
                "readmit", st, st.world,
                f"host {host}: straggler suspicion decayed after "
                f"{st.spec.evict_decay} decisions — ceiling restored"))
        st.suspects = keep

    def on_exit(self, name: str, code: int, now_s: float) -> dict:
        """Record a child exit and transition the job. Returns the logged
        row. Scheduler-initiated preemption (status PREEMPTING + the
        graceful exit code) re-queues without burning the restart budget;
        everything else non-zero burns one restart with doubling backoff
        until the budget is gone."""
        st = self.jobs[name]
        was = st.status
        held = st.world
        st.world = 0
        st.last_exit = code
        self._refresh_weight(st)
        if code == 0:
            st.status = DONE
            reason = "exit 0"
        elif was == PREEMPTING and code == resilience.PREEMPTED_EXIT_CODE:
            st.status = PENDING
            reason = (f"exit {code} (scheduler preemption) -> requeued, "
                      "no budget burned")
        else:
            st.restarts += 1
            if st.restarts > st.spec.max_restarts:
                st.status = FAILED
                reason = (f"exit {code}; restart budget exhausted "
                          f"({st.spec.max_restarts})")
            else:
                st.status = BACKOFF
                delay = st.spec.backoff_s * 2 ** (st.restarts - 1)
                st.next_eligible_s = now_s + delay
                kind = ("host loss"
                        if code == resilience.HOST_LOST_EXIT_CODE else
                        "preemption" if code ==
                        resilience.PREEMPTED_EXIT_CODE else "failure")
                reason = (f"exit {code} ({kind}) -> backoff "
                          f"{delay:g}s, restart "
                          f"{st.restarts}/{st.spec.max_restarts}")
        action = {DONE: "done", FAILED: "giveup"}.get(st.status, "exit")
        return self._log(action, st, held, reason)

    def mark_starved(self) -> list[dict]:
        """Terminal sweep for the launcher: jobs that can never run (their
        dependency died checkpoint-less, or dead hosts pinned their ceiling
        below MIN with nothing left alive to change that) become FAILED so
        the fleet can report and exit instead of hanging."""
        rows = []
        for name in sorted(self.jobs):
            st = self.jobs[name]
            if st.status not in TERMINAL:
                st.status = FAILED
                rows.append(self._log(
                    "giveup", st, st.world,
                    "starved: unplaceable with no live jobs left"))
        return rows

    def _refresh_weight(self, st: JobState) -> None:
        ckdir = st.spec.checkpoint_dir
        if not ckdir:
            return
        path = os.path.join(ckdir, "goodput.json")
        if not os.path.exists(path):
            return
        try:
            doc = json.loads(resilience.retriable_io(
                _read_text, path, _what="fleet goodput read"))
            st.weight = quantize_weight(doc["goodput_fraction"])
        except (OSError, ValueError, KeyError, TypeError):
            pass  # a torn goodput file must not stall scheduling

    def _refresh_slo(self, st: JobState) -> None:
        """Serve jobs: quantized attainment from the job's slo.jsonl.

        The file is atomically replaced by the serving loop (never torn)
        and ``read_slo_attainment`` is tolerant of anything else; absence
        (job not started, SLO tracking off) leaves the neutral 1.0."""
        ckdir = st.spec.checkpoint_dir
        if not ckdir:
            return
        att = fleetobs.read_slo_attainment(
            os.path.join(ckdir, fleetobs.SLO_FILE))
        if att is not None:
            st.slo_attainment = quantize_weight(att)
