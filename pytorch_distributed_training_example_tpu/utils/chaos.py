"""Deterministic chaos harness: seeded fault injection at named sites.

Every recovery path in the resilience layer (``utils/resilience.py``,
``core/checkpoint.py`` integrity + fallback, the trainer's anomaly rollback)
is exercised end-to-end by injecting faults from INSIDE a real training run,
rather than trusted on inspection. ``--chaos`` takes a comma-separated spec:

    sigterm@step=7         deliver SIGTERM to this process at the end of
                           global step 7 (the preemption drill)
    sigint@step=7          same, with SIGINT
    nan_grad@step=5        poison the batch consumed at global step 5 (float
                           inputs overwritten with NaN -> non-finite health
                           scalars -> anomaly guard)
    loader_stall@batch=3   sleep ``STALL_S`` before yielding global batch 3
                           (shows up in the input_wait badput bucket)
    ckpt_io_error@save=2   inject OSError into the first ``IO_FAILURES``
                           filesystem ops of the 2nd checkpoint save (1-based)
                           — exercises the retriable-io backoff path
    truncate_ckpt[@save=1] after the K-th save commits, truncate one array
                           file of the newest committed checkpoint (the CRC
                           fallback-restore drill; file choice is seeded)
    kill_host@step=9       ABRUPT simulated host loss at the end of global
                           step 9: record the victim host in
                           ``dead_hosts.jsonl`` (utils/elastic.py), then
                           ``os._exit(HOST_LOST_EXIT_CODE)`` — no emergency
                           checkpoint, exactly like real hardware. An elastic
                           supervisor relaunches one host smaller.
    slow_host@step=4:rank=1  CHRONIC straggler: from global batch 4 onward,
                           rank 1 sleeps ``SLOW_S`` before yielding EVERY
                           batch (a failing NIC / thermal throttle, not a
                           one-off hiccup like loader_stall). Unlike every
                           other event it keeps firing for the life of the
                           process — that is the point: the straggler
                           detector must flag the same host in consecutive
                           windows so the fleet scheduler's ``evict_after``
                           verdict trips. Logged to chaos.jsonl once, on
                           first fire.

Counters are GLOBAL (step/batch indices are ``epoch * steps_per_epoch + i``;
save counts every ``Checkpointer.save`` call this process makes), and every
event fires at most once per process — a run resumed past the trip point
does not re-trip, which is what lets the supervisor restart converge.
``kill_host`` additionally never re-fires once its victim is recorded dead
(a dead host cannot die twice): a resumed attempt that re-runs the trip step
— e.g. because the abrupt kill lost an uncommitted cadence save — skips it.

Determinism: the spec + seed fully determine what fires where; the only
randomness (truncation target choice) draws from a ``RandomState(seed)``.
Each injection appends one JSON line to ``<log_dir>/chaos.jsonl`` so two runs
with the same spec and seed can be diffed.
"""

from __future__ import annotations

import dataclasses
import json
import logging
import os
import signal
import time

import numpy as np

from pytorch_distributed_training_example_tpu.utils import (
    elastic, fleetobs, resilience)

log = logging.getLogger("pdtx")

CHAOS_LOG = "chaos.jsonl"

#: Sites and the counter key each one fires on (None = optional, default 1).
_SITES = {
    "sigterm": "step",
    "sigint": "step",
    "nan_grad": "step",
    "loader_stall": "batch",
    "ckpt_io_error": "save",
    "truncate_ckpt": "save",
    "kill_host": "step",
    "slow_host": "step",
}


@dataclasses.dataclass
class _Event:
    name: str
    key: str
    value: int
    fired: bool = False
    #: None = fire on every process; N = fire only on process/rank N (the
    #: ``:rank=N`` spec qualifier — e.g. stall ONE rank's loader so the
    #: fleet-level straggler detector has a definite culprit).
    rank: int | None = None


def parse_spec(spec: str) -> list[_Event]:
    """Parse ``name@key=value[:rank=R],...`` into events; raises ValueError
    on junk."""
    events = []
    for raw in spec.split(","):
        raw = raw.strip()
        if not raw:
            continue
        name, _, cond = raw.partition("@")
        if name not in _SITES:
            raise ValueError(
                f"unknown chaos event {name!r} in {spec!r}; "
                f"have {sorted(_SITES)}")
        want_key = _SITES[name]
        rank: int | None = None
        if cond:
            head, *quals = cond.split(":")
            key, _, val = head.partition("=")
            if key != want_key or not val.lstrip("-").isdigit():
                raise ValueError(
                    f"chaos event {raw!r}: expected "
                    f"{name}@{want_key}=<int>[:rank=<int>]")
            value = int(val)
            for qual in quals:
                qkey, _, qval = qual.partition("=")
                if qkey != "rank" or not qval.isdigit():
                    raise ValueError(
                        f"chaos event {raw!r}: unknown qualifier {qual!r} "
                        f"(only :rank=<int>)")
                rank = int(qval)
        elif name == "truncate_ckpt":
            value = 1  # default: corrupt the first committed save
        else:
            raise ValueError(
                f"chaos event {raw!r} needs @{want_key}=<int>")
        events.append(_Event(name, want_key, value, rank=rank))
    if not events:
        raise ValueError(f"empty chaos spec {spec!r}")
    return events


class ChaosEngine:
    """Holds the parsed spec and fires events at the named sites.

    The trainer wires the sites: ``step_boundary`` after each optimizer step,
    ``batch_hook`` installed as the loader's yield-time hook
    (``data/loader.py``), ``before_save``/``after_save`` around every
    ``Checkpointer.save``.
    """

    IO_FAILURES = 2   # < retriable_io's default 4 attempts: retry succeeds
    STALL_S = 1.0
    SLOW_S = 0.25     # per-batch chronic drag: well past the straggler
                      # detector's absolute floor, small enough to keep
                      # same-seed drill runtimes sane

    def __init__(self, spec: str, seed: int = 0, log_dir: str | None = None,
                 rank: int | None = None):
        self.events = parse_spec(spec)
        self.seed = seed
        self.rng = np.random.RandomState(seed)
        self.log_dir = log_dir
        self.rank = rank
        self.log_path = (os.path.join(log_dir, CHAOS_LOG)
                         if log_dir else None)
        # Set by the trainer so batch-site events can map (epoch, batch) to
        # a global index consistent with the step numbering.
        self.steps_per_epoch: int | None = None
        self._saves = 0
        self._io_faults_left = 0
        # A host already recorded dead cannot die twice: pre-fire kill_host
        # events whose drill already ran (the resumed attempt may re-run the
        # trip step when the abrupt kill lost an uncommitted cadence save).
        if log_dir:
            dead = elastic.read_dead_hosts(log_dir)
            kills = sorted((ev for ev in self.events
                            if ev.name == "kill_host"), key=lambda e: e.value)
            for ev in kills[:len(dead)]:  # one recorded death per past fire
                ev.fired = True
                log.info(
                    "chaos: kill_host@step=%d disarmed — host(s) %s already "
                    "recorded dead in %s", ev.value, sorted(dead), log_dir)

    # -- bookkeeping --------------------------------------------------------

    def _proc_rank(self) -> int:
        """This process's rank, resolved lazily: the trainer passes it in;
        otherwise the launcher env (``PROCESS_ID``), then jax, then 0."""
        if self.rank is None:
            pid = os.environ.get("PROCESS_ID", "")
            if pid.isdigit():
                self.rank = int(pid)
            else:
                try:
                    import jax

                    self.rank = jax.process_index()
                except Exception:  # no jax / uninitialized: single process
                    self.rank = 0
        return self.rank

    def _take(self, name: str, value: int) -> _Event | None:
        for ev in self.events:
            if ev.name == name and ev.value == value and not ev.fired:
                if ev.rank is not None and ev.rank != self._proc_rank():
                    continue
                ev.fired = True
                return ev
        return None

    def _record(self, ev: _Event, **detail) -> None:
        row = {"event": ev.name, ev.key: ev.value, "seed": self.seed, **detail}
        log.warning("chaos: injecting %s", row)
        if self.log_path:
            os.makedirs(os.path.dirname(self.log_path), exist_ok=True)
            with open(self.log_path, "a") as fh:
                fh.write(json.dumps(row) + "\n")

    # -- sites --------------------------------------------------------------

    def step_boundary(self, gstep: int) -> None:
        """End of global step ``gstep`` (trainer loop, after the dispatch)."""
        for name, sig in (("sigterm", signal.SIGTERM),
                          ("sigint", signal.SIGINT)):
            ev = self._take(name, gstep)
            if ev is not None:
                self._record(ev, pid=os.getpid())
                # A REAL signal through the real delivery path — the
                # resilience handler, not a shortcut to its flag.
                os.kill(os.getpid(), sig)
        ev = self._take("kill_host", gstep)
        if ev is not None:
            self._kill_host(ev, gstep)

    def _kill_host(self, ev: _Event, gstep: int) -> None:
        """Abrupt simulated host loss: no emergency checkpoint, no cleanup —
        the process is gone mid-whatever, exactly like real hardware. The
        victim (deterministically the highest-index host) is recorded in the
        dead-hosts file first, so the elastic supervisor knows to relaunch
        one host smaller, and the chaos row is on disk for same-seed diffing.
        """
        host, world = 0, 1
        try:  # lazy: the harness stays importable (and testable) without jax
            import jax

            world = (jax.process_count() if jax.process_count() > 1
                     else jax.local_device_count())
            host = world - 1
        except Exception:  # pragma: no cover - no jax / uninitialized
            pass
        # Last words: the flight recorder ring is the ONLY diagnostic record
        # an abrupt loss leaves (no flushes by design — a tiny bounded append
        # is the one exception, same spirit as the dead-host record below).
        fleetobs.dump_active("host_loss", step=gstep)
        if self.log_dir:
            elastic.record_dead_host(self.log_dir, host, world=world,
                                     step=gstep, reason="chaos kill_host")
        else:
            log.warning("chaos: kill_host has no log_dir — the supervisor "
                        "cannot learn the dead host; relaunch will be "
                        "same-size")
        self._record(ev, host=host, world=world,
                     exit=resilience.HOST_LOST_EXIT_CODE)
        os._exit(resilience.HOST_LOST_EXIT_CODE)

    def batch_hook(self, epoch: int, batch_idx: int, batch: dict) -> dict:
        """Loader yield-time hook (``data/loader.py`` ``set_batch_hook``)."""
        g = batch_idx
        if self.steps_per_epoch:
            g = epoch * self.steps_per_epoch + batch_idx
        ev = self._take("loader_stall", g)
        if ev is not None:
            self._record(ev, stall_s=self.STALL_S)
            time.sleep(self.STALL_S)
        # slow_host is CHRONIC: from its trip batch onward it drags every
        # yield on the targeted rank — ``fired`` only gates the one-time
        # chaos.jsonl row (keeping same-seed logs byte-diffable), never the
        # effect itself.
        for ev in self.events:
            if (ev.name == "slow_host" and g >= ev.value
                    and (ev.rank is None or ev.rank == self._proc_rank())):
                if not ev.fired:
                    ev.fired = True
                    self._record(ev, slow_s=self.SLOW_S, chronic=True)
                time.sleep(self.SLOW_S)
        ev = self._take("nan_grad", g)
        if ev is not None:
            float_keys = [k for k, v in batch.items()
                          if np.issubdtype(np.asarray(v).dtype, np.floating)]
            if not float_keys:
                raise ValueError(
                    "nan_grad chaos needs a float input array to poison; "
                    f"batch has only {sorted(batch)} "
                    "(integer token batches cannot carry NaN)")
            self._record(ev, poisoned=sorted(float_keys))
            batch = dict(batch)
            for k in float_keys:
                batch[k] = np.full_like(np.asarray(batch[k]), np.nan)
        return batch

    def before_save(self) -> None:
        """Called before every ``Checkpointer.save`` this process issues."""
        self._saves += 1
        ev = self._take("ckpt_io_error", self._saves)
        if ev is not None:
            self._record(ev, io_failures=self.IO_FAILURES)
            self._io_faults_left = self.IO_FAILURES
            resilience.set_fault_hook(self._io_fault)

    def _io_fault(self, what: str) -> None:
        if self._io_faults_left > 0:
            self._io_faults_left -= 1
            if self._io_faults_left == 0:
                resilience.set_fault_hook(None)
            raise OSError(f"chaos: injected checkpoint io error [{what}]")

    def after_save(self, checkpointer) -> None:
        """Called after every save; corrupts the newest committed checkpoint
        when a ``truncate_ckpt`` event targets this save index."""
        from pytorch_distributed_training_example_tpu.core import (
            checkpoint as checkpoint_lib)

        ev = self._take("truncate_ckpt", self._saves)
        if ev is None:
            return
        checkpointer.wait()  # the targeted save may still be in flight
        step = checkpoint_lib.latest_checkpoint(checkpointer.directory)
        if step is None:
            log.warning("chaos: truncate_ckpt armed but no committed "
                        "checkpoint exists — nothing to corrupt")
            return
        arrays_dir = os.path.join(checkpointer.directory,
                                  f"step_{step:08d}", "arrays")
        files = sorted(os.listdir(arrays_dir))
        target = files[int(self.rng.randint(len(files)))]
        path = os.path.join(arrays_dir, target)
        size = os.path.getsize(path)
        with open(path, "r+b") as fh:
            fh.truncate(max(size // 2, 1))
        self._record(ev, step=step, file=target, orig_bytes=size)
