"""Unified telemetry: on-device health pack, span timeline, goodput, anomaly guard.

Three pieces, one module (ROADMAP items 1/3/5 all need this to be
interpretable):

1. **Health pack** (device side): ``health_pack`` computes global grad/update/
   param norms and finite flags INSIDE the compiled train step, and
   ``collect_sowed`` folds model-internal diagnostics (MoE router-load
   entropy, drop fraction — sowed under the ``"telemetry"`` collection) into
   the same metrics dict. Everything rides the existing ``log_every``
   device_get: zero extra host syncs at the default cadence.

2. **Span recorder** (host side): ``SpanRecorder.span("input_wait")`` times
   named phases, mirrors them onto the device timeline via
   ``jax.profiler.TraceAnnotation`` (so they line up with xplane traces), and
   emits a Perfetto-loadable ``trace_events.json`` plus a goodput summary —
   fraction of wall-clock in productive steps vs. each badput category
   (PaLM-style goodput accounting, PAPERS.md).

3. **Anomaly guard**: on a non-finite health scalar, dump a diagnostic
   bundle (step, config, last-K metric rows, trigger row, goodput snapshot)
   and either raise :class:`AnomalyError` or skip-and-continue, per the
   ``--anomaly-action`` knob.

The :class:`Telemetry` facade bundles all three for ``core/trainer.py``.
"""

from __future__ import annotations

import atexit
import collections
import contextlib
import dataclasses
import json
import logging
import os
import time
from typing import Any, Callable

import jax
import jax.numpy as jnp

from pytorch_distributed_training_example_tpu.utils import fleetobs

log = logging.getLogger("pdtx")

#: Span names treated as productive time in the goodput summary. "step" is
#: the training step AND the serving decode step; "prefill" is the serving
#: engine's prompt-ingestion forward (serve/engine.py) — tokens leave the
#: model in both, so both count toward goodput. Trainers never emit
#: "prefill", so training goodput is unchanged.
PRODUCTIVE_SPANS = ("step", "prefill")

#: Badput categories the trainer emits (order is the report order).
#: "restart" is synthesized, not timed by a span: the wall-clock gap between
#: a previous supervisor attempt's last goodput write and this attempt's
#: start (the restart tax of an elastic/preemption relaunch).
BADPUT_SPANS = ("init", "compile", "input_wait", "checkpoint_save",
                "checkpoint_restore", "eval", "anomaly_dump", "restart")


class AnomalyError(RuntimeError):
    """Raised by the anomaly guard when ``anomaly_action='abort'``."""


# ---------------------------------------------------------------------------
# Device side: the health pack. Pure functions traced into the train step.
# ---------------------------------------------------------------------------


def _global_norm(tree) -> jax.Array:
    import optax

    return optax.global_norm(jax.tree.map(
        lambda x: x.astype(jnp.float32), tree))


def health_pack(loss, grads, old_params, new_params) -> dict[str, jax.Array]:
    """Training-health scalars, computed where the tensors already live.

    ``update_norm`` is the norm of the applied delta (new - old), so it is
    exact under every update rule including the fp16 scaler's skip branch
    (where it is 0: params held). All reductions fuse into the step program;
    the result is a handful of f32 scalars in the metrics dict.
    """
    with jax.named_scope("telemetry_health"):
        update = jax.tree.map(
            lambda n, o: n.astype(jnp.float32) - o.astype(jnp.float32),
            new_params, old_params)
        finite = jnp.all(jnp.stack(
            [jnp.all(jnp.isfinite(g.astype(jnp.float32)))
             for g in jax.tree.leaves(grads)]))
        return {
            "update_norm": _global_norm(update),
            "param_norm": _global_norm(new_params),
            "loss_finite": jnp.isfinite(loss).astype(jnp.float32),
            "grads_finite_all": finite.astype(jnp.float32),
        }


def collect_sowed(tele_vars) -> dict[str, jax.Array]:
    """Fold a flax ``"telemetry"`` sow collection into named mean scalars.

    Sow appends one entry per call site per layer (tuples; a leading scan
    dim when layers are scanned) — group leaves by their final name and
    average, so ``router_load_entropy`` is the mean over all MoE layers.

    Since the r8 router round both MoE sows (``moe_drop_fraction``,
    ``router_load_entropy``) derive from the SAME compact [E] routing
    counts the dispatch uses (``parallel/moe.py routing_stats``) — they
    are exact token counts, not a second mask-based estimate, and cost no
    extra [T, E] materialization in the step.
    """
    out: dict[str, list] = {}
    flat = jax.tree_util.tree_flatten_with_path(tele_vars)[0]
    for path, leaf in flat:
        name = None
        for part in reversed(path):
            key = getattr(part, "key", getattr(part, "name", None))
            if isinstance(key, str) and not key.isdigit():
                name = key
                break
        if name is None:
            name = "telemetry"
        out.setdefault(name, []).append(jnp.mean(jnp.asarray(leaf)))
    return {k: jnp.mean(jnp.stack(v)).astype(jnp.float32)
            for k, v in out.items()}


# ---------------------------------------------------------------------------
# Host side: span recorder + goodput accounting.
# ---------------------------------------------------------------------------


class SpanRecorder:
    """Times named host-side phases and renders them two ways.

    ``trace_events()`` is Chrome/Perfetto trace-event JSON (complete "X"
    events, microsecond timestamps); ``goodput()`` is the wall-clock
    decomposition. Only OUTERMOST spans accrue to the goodput totals —
    nested spans (e.g. a checkpoint restore inside init) still appear on
    the timeline but never double-count wall time. Each span also enters a
    ``jax.profiler.TraceAnnotation`` so the phase shows up on xplane traces
    captured by ``--profile-steps``.
    """

    def __init__(self, run_id: str = "", carry: dict | None = None,
                 meta: dict | None = None):
        self.run_id = run_id
        # Monotonic<->wall anchor, captured at the same instant: ``ts``
        # values in the trace are microseconds after ``_start`` on THIS
        # host's monotonic clock; ``_wall_origin`` places that origin on the
        # shared wall clock so the merge CLI can align ranks whose monotonic
        # clocks have arbitrary offsets.
        self._start = time.perf_counter()
        self._wall_origin = time.time()
        self.meta = dict(meta or {})
        self._run_ids: list[str] = []
        self._attempt_ids: list[str] = []
        self._events: list[dict] = []
        self._totals: collections.defaultdict = collections.defaultdict(float)
        self._counts: collections.defaultdict = collections.defaultdict(int)
        self._depth = 0
        self._pid = jax.process_index()
        # Cross-attempt carryover (elastic/preemption relaunch): ``carry`` is
        # a previous attempt's goodput.json dict. Its categories/counts/wall
        # seed the cumulative totals, and the gap between its ``ended_at``
        # and now becomes one "restart" badput interval — so the merged
        # goodput.json decomposes the FULL job wall-clock, restart tax
        # included, not just the current attempt.
        self._base_totals: dict[str, float] = {}
        self._base_counts: dict[str, int] = {}
        self._base_wall = 0.0
        self.attempts = 1
        # Time-to-first-step (r21 instant restart): wall from construction
        # to the first completed optimizer step, tagged cold/warm by the
        # executable-cache outcome. History carries across attempts so the
        # warm-vs-cold comparison lives in ONE goodput.json.
        self._ttfs: float | None = None
        self._ttfs_mode: str | None = None
        self._ttfs_history: list[dict] = []
        if carry:
            self._ttfs_history = [dict(h) for h in
                                  (carry.get("ttfs_history") or [])]
            self._base_totals = {k: float(v) for k, v in
                                 (carry.get("categories_s") or {}).items()}
            self._base_counts = {k: int(v) for k, v in
                                 (carry.get("counts") or {}).items()}
            self._base_wall = float(carry.get("wall_s") or 0.0)
            self.attempts = int(carry.get("attempts") or 1) + 1
            # Provenance across attempts: which run/attempt ids this
            # cumulative summary merged (mixed-run detection downstream).
            for rid in (carry.get("run_ids")
                        or ([carry["run_id"]] if carry.get("run_id") else [])):
                if rid and rid not in self._run_ids:
                    self._run_ids.append(rid)
            for aid in (carry.get("attempt_ids")
                        or ([carry["attempt_id"]]
                            if carry.get("attempt_id") else [])):
                if aid and aid not in self._attempt_ids:
                    self._attempt_ids.append(aid)
            ended = carry.get("ended_at")
            if ended is not None:
                gap = max(0.0, time.time() - float(ended))
                self._base_totals["restart"] = (
                    self._base_totals.get("restart", 0.0) + gap)
                self._base_counts["restart"] = (
                    self._base_counts.get("restart", 0) + 1)
                self._base_wall += gap
                # Timeline marker: the gap sits BEFORE this attempt's origin.
                self._events.append({
                    "name": "restart", "ph": "X", "cat": "telemetry",
                    "ts": -int(gap * 1e6), "dur": int(gap * 1e6),
                    "pid": self._pid, "tid": 0})
        if run_id and run_id not in self._run_ids:
            self._run_ids.append(run_id)
        aid = self.meta.get("attempt_id")
        if aid and aid not in self._attempt_ids:
            self._attempt_ids.append(aid)
        self.meta.setdefault("attempt", self.attempts)

    @contextlib.contextmanager
    def span(self, name: str):
        ann = jax.profiler.TraceAnnotation(f"telemetry/{name}")
        ann.__enter__()
        t0 = time.perf_counter()
        self._depth += 1
        try:
            yield
        finally:
            dt = time.perf_counter() - t0
            self._depth -= 1
            ann.__exit__(None, None, None)
            self._events.append({
                "name": name, "ph": "X", "cat": "telemetry",
                "ts": int((t0 - self._start) * 1e6),
                "dur": int(dt * 1e6),
                "pid": self._pid, "tid": self._depth,
            })
            if self._depth == 0:
                self._totals[name] += dt
                self._counts[name] += 1

    @property
    def wall_s(self) -> float:
        return time.perf_counter() - self._start

    def mark_first_step(self, mode: str) -> None:
        """Record time-to-first-step once, tagged ``cold``/``warm``."""
        if self._ttfs is not None:
            return
        self._ttfs = self.wall_s
        self._ttfs_mode = str(mode)
        self._ttfs_history.append({"attempt": self.attempts,
                                   "ttfs_s": round(self._ttfs, 4),
                                   "mode": self._ttfs_mode})

    def trace_events(self) -> dict:
        # ``fleetobs.trace_doc`` puts otherData FIRST (torn-write salvage
        # contract) and is shared with the serving-side RequestTrace so both
        # kinds of file merge under one clock-alignment rule.
        return fleetobs.trace_doc(
            run_id=self.run_id, anchor_wall=self._wall_origin,
            anchor_mono=self._start, events=self._events, meta=self.meta)

    def goodput(self) -> dict:
        """Wall-clock decomposition since construction (plus carried attempts).

        ``goodput_fraction`` is the productive ("step") share; ``coverage``
        is the fraction of wall-clock any top-level span accounts for —
        the acceptance bar asks for >= 0.95, the rest is loop bookkeeping.
        Fractions sum to ``coverage`` <= 1 by construction (top-level spans
        cannot overlap on one thread). With carried attempts the totals and
        wall are CUMULATIVE over every attempt plus the restart gaps;
        ``attempts``/``ended_at`` let the next attempt keep merging.
        """
        wall = max(self._base_wall + self.wall_s, 1e-9)
        totals = dict(self._base_totals)
        for k, v in self._totals.items():
            totals[k] = totals.get(k, 0.0) + v
        counts = dict(self._base_counts)
        for k, v in self._counts.items():
            counts[k] = counts.get(k, 0) + v
        cats = {k: round(v, 4) for k, v in sorted(totals.items())}
        fracs = {k: v / wall for k, v in totals.items()}
        good = sum(fracs.get(k, 0.0) for k in PRODUCTIVE_SPANS)
        out = {
            "schema_version": fleetobs.SCHEMA_VERSION,
            "run_id": self.run_id,
            "run_ids": list(self._run_ids),
            "wall_s": round(wall, 4),
            "categories_s": cats,
            "counts": counts,
            "fractions": {k: round(v, 4) for k, v in sorted(fracs.items())},
            "goodput_fraction": round(good, 4),
            "badput_fraction": round(sum(fracs.values()) - good, 4),
            "coverage": round(sum(fracs.values()), 4),
            "attempts": self.attempts,
            "ended_at": round(time.time(), 3),
        }
        if self._ttfs is not None:
            out["time_to_first_step_s"] = round(self._ttfs, 4)
            out["ttfs_mode"] = self._ttfs_mode
        if self._ttfs_history:
            out["ttfs_history"] = [dict(h) for h in self._ttfs_history]
        if "restart" in totals:
            # The restart tax decomposed: the supervisor gap between
            # attempts plus THIS job's cumulative compile/restore spans —
            # the three costs the executable cache + background re-shard
            # exist to shrink.
            out["restart_breakdown"] = {
                "gap_s": round(totals.get("restart", 0.0), 4),
                "compile_s": round(totals.get("compile", 0.0), 4),
                "restore_s": round(totals.get("checkpoint_restore", 0.0), 4),
            }
        if self.meta:
            out["meta"] = dict(self.meta)
        if self.meta.get("attempt_id"):
            out["attempt_id"] = self.meta["attempt_id"]
            out["attempt_ids"] = list(self._attempt_ids)
        return out

    def write(self, directory: str) -> None:
        """The rank-0 (single-process-compatible) artifact pair."""
        os.makedirs(directory, exist_ok=True)
        with open(os.path.join(directory, "trace_events.json"), "w") as fh:
            json.dump(self.trace_events(), fh)
        fleetobs.write_json_atomic(os.path.join(directory, "goodput.json"),
                                   self.goodput())

    def write_rank(self, directory: str, rank: int, attempt: int) -> None:
        """Per-rank, per-attempt artifact pair — every rank writes its own
        (the plain names above are rank 0's; before this, N ranks clobbered
        one shared file and the merge had nothing to merge)."""
        os.makedirs(directory, exist_ok=True)
        suffix = f"r{rank}.a{attempt}"
        path = os.path.join(directory, f"trace_events.{suffix}.json")
        with open(path, "w") as fh:
            json.dump(self.trace_events(), fh)
        fleetobs.write_json_atomic(
            os.path.join(directory, f"goodput.{suffix}.json"), self.goodput())


def load_goodput(directory: str, rank: int = 0) -> dict | None:
    """Previous attempt's cumulative goodput for ``rank`` (None if absent).

    Rank 0 reads the plain ``goodput.json``; other ranks read their
    highest-attempt suffixed file, falling back to the plain file (resume
    from a run that predates per-rank artifacts)."""
    import re as _re

    if rank:
        best: tuple[int, str] | None = None
        try:
            for name in os.listdir(directory):
                m = _re.fullmatch(rf"goodput\.r{rank}\.a(\d+)\.json", name)
                if m and (best is None or int(m.group(1)) > best[0]):
                    best = (int(m.group(1)), name)
        except OSError:
            best = None
        if best is not None:
            try:
                with open(os.path.join(directory, best[1])) as fh:
                    return json.load(fh)
            except (OSError, ValueError):
                return None
    try:
        with open(os.path.join(directory, "goodput.json")) as fh:
            return json.load(fh)
    except (OSError, ValueError):
        return None


# ---------------------------------------------------------------------------
# Anomaly guard.
# ---------------------------------------------------------------------------


def _nonfinite_keys(row: dict) -> list[str]:
    import math

    bad = []
    for k, v in row.items():
        if isinstance(v, bool) or not isinstance(v, (int, float)):
            continue
        if not math.isfinite(v):
            bad.append(k)
    return bad


class AnomalyGuard:
    """Watches fetched metric rows for non-finite training-health scalars.

    ``record`` keeps the last-K rows; ``check`` dumps a diagnostic bundle
    (step, config, trigger row, history, goodput snapshot) into
    ``directory`` on the first non-finite scalar and then either raises
    :class:`AnomalyError` (action="abort") or logs and returns True
    (action="continue"). With an fp16 GradScaler in play, rows whose
    ``grads_finite`` flag is 0 are the scaler's *handled* overflow-skip
    branch — set ``allow_scaler_skips`` so they don't false-trigger.
    """

    def __init__(self, directory: str, action: str = "abort", keep: int = 32,
                 config: Any = None, run_id: str = "",
                 goodput_fn: Callable[[], dict] | None = None,
                 allow_scaler_skips: bool = False):
        if action not in ("abort", "continue", "rollback"):
            raise ValueError(
                f"anomaly_action must be 'abort', 'continue' or 'rollback', "
                f"got {action!r}")
        self.directory = directory
        self.action = action
        self.config = config
        self.run_id = run_id
        self.goodput_fn = goodput_fn
        self.allow_scaler_skips = allow_scaler_skips
        self.history: collections.deque = collections.deque(maxlen=keep)
        self.tripped = False
        self.trips = 0
        self.warnings = 0
        # Optional hook called as ``fn(reason, step=...)`` after a bundle is
        # written — the Telemetry facade points it at the flight recorder.
        # Dumped once per anomaly EPISODE (a run of anomalous checks with no
        # clean row in between), not per anomalous step: under
        # anomaly_action=continue a NaN that sticks in the params would
        # otherwise append a near-identical ring dump every step.
        self.flight_dump_fn: Callable[..., Any] | None = None
        self._in_anomaly_episode = False

    def record(self, step: int, row: dict) -> None:
        self.history.append({"step": int(step), **row})

    def check(self, step: int, row: dict) -> bool:
        """Record the row, then trip on any non-finite scalar in it."""
        self.record(step, row)
        if (self.allow_scaler_skips
                and float(row.get("grads_finite", 1.0)) == 0.0):
            return False  # fp16 overflow-skip: params held, not an anomaly
        bad = _nonfinite_keys(row)
        if not bad:
            self._in_anomaly_episode = False
            return False
        self.tripped = True
        self.trips += 1
        path = self.dump(step, row, bad)
        msg = (f"non-finite health scalar(s) {bad} at step {step}; "
               f"diagnostic bundle: {path}")
        if self.action == "abort":
            raise AnomalyError(msg)
        # "continue" and "rollback" both return True after the dump; for
        # rollback, acting on the trip (restore + iterator re-seed + budget)
        # is the TRAINER's job — the guard only detects and documents.
        log.error("anomaly guard: %s — anomaly_action=%s", msg, self.action)
        return True

    def warn(self, step: int, reason: str) -> None:
        """Warn-only trigger (straggler/skew detection): counted and kept in
        the history ring so the next bundle shows it, but never dumps or
        aborts on its own — a slow host is an operator page, not a rollback.
        """
        self.warnings += 1
        self.history.append({"step": int(step), "warn": reason})
        log.warning("anomaly guard [warn-only] step %d: %s", int(step), reason)

    def dump(self, step: int, row: dict, bad_keys: list[str]) -> str:
        cfg = self.config
        if dataclasses.is_dataclass(cfg) and not isinstance(cfg, type):
            cfg = dataclasses.asdict(cfg)
        bundle = {
            "schema_version": fleetobs.SCHEMA_VERSION,
            "run_id": self.run_id,
            "step": int(step),
            "trigger_keys": bad_keys,
            "trigger_row": row,
            "config": cfg,
            "history": list(self.history),
            "goodput": self.goodput_fn() if self.goodput_fn else None,
            "time": time.time(),
        }
        os.makedirs(self.directory, exist_ok=True)
        path = os.path.join(self.directory, f"anomaly_step{int(step):08d}.json")
        with open(path, "w") as fh:
            json.dump(bundle, fh, indent=1, default=float)
        if self.flight_dump_fn is not None and not self._in_anomaly_episode:
            try:
                self.flight_dump_fn("anomaly", step=int(step))
            except Exception as e:  # diagnostics never mask the anomaly
                log.warning("flight dump on anomaly failed: %s", e)
        self._in_anomaly_episode = True
        return path


# ---------------------------------------------------------------------------
# Facade: what the trainer holds.
# ---------------------------------------------------------------------------


class Telemetry:
    """Span recorder + anomaly guard + last-seen state, as one object.

    ``directory`` receives ``trace_events.json`` / ``goodput.json`` (epoch
    end and shutdown) and anomaly bundles. ``snapshot()`` is the watchdog's
    context hook: last global step, last health row, goodput decomposition.
    """

    def __init__(self, directory: str, run_id: str = "",
                 anomaly_action: str = "abort", config: Any = None,
                 history_keep: int = 32, allow_scaler_skips: bool = False,
                 resume: bool = False, straggler_threshold: float = 2.0,
                 flightrec_steps: int = 256):
        self.directory = directory
        self.rank = jax.process_index()
        self.host = fleetobs.host_identity()
        # ``run_id`` (the MetricLogger per-process uuid) is really the
        # ATTEMPT id; the fleet-stable run id lives in <dir>/run_id.json so
        # every rank and every elastic attempt stamps the same one.
        self.attempt_id = run_id
        self.run_id = fleetobs.ensure_run_id(
            directory, run_id, fresh=not resume, rank=self.rank)
        # ``resume=True`` (a --resume run, e.g. a supervisor relaunch) merges
        # a previous attempt's goodput.json into this one: cumulative
        # categories plus a "restart" badput interval for the gap. The file
        # in ``directory`` then always decomposes the whole job so far.
        carry = load_goodput(directory, rank=self.rank) if resume else None
        if carry and (carry.get("attempt_id") == self.attempt_id
                      or carry.get("run_id") == run_id):
            carry = None  # same attempt rewriting its own file: nothing to merge
        elif (carry and carry.get("schema_version")
              and carry.get("run_id") != self.run_id):
            # Stamped artifact from a DIFFERENT run in the same directory —
            # summing unrelated attempts would fabricate goodput. Refuse.
            log.warning(
                "telemetry: refusing to carry goodput from foreign run %s "
                "into run %s (stale artifacts in %s?)",
                carry.get("run_id"), self.run_id, directory)
            carry = None
        meta = {"host": self.host, "rank": self.rank,
                "attempt_id": self.attempt_id}
        self.recorder = SpanRecorder(run_id=self.run_id, carry=carry,
                                     meta=meta)
        if carry:
            log.info(
                "telemetry: merging goodput across supervisor attempts — "
                "attempt %d, %.1fs of prior wall-clock carried",
                self.recorder.attempts, carry.get("wall_s", 0.0))
        self.guard = AnomalyGuard(
            directory, action=anomaly_action, keep=history_keep,
            config=config, run_id=self.run_id,
            goodput_fn=self.recorder.goodput,
            allow_scaler_skips=allow_scaler_skips)
        self.guard.flight_dump_fn = self.flight_dump
        self.flight = fleetobs.FlightRecorder(flightrec_steps)
        self.monitor = fleetobs.StragglerMonitor(threshold=straggler_threshold)
        self._steprows = (fleetobs.StepRowWriter(
            directory, self.rank, self.recorder.attempts,
            meta={"run_id": self.run_id, "attempt_id": self.attempt_id})
            if directory else None)
        fleetobs.set_active(
            self.flight, directory, self.rank,
            meta={"run_id": self.run_id, "attempt_id": self.attempt_id,
                  "attempt": self.recorder.attempts})
        self.last_step: int | None = None
        self.last_health: dict | None = None
        # Satellite fix (host-loss flush gap): a surviving rank torn down by
        # the launcher after a peer's abrupt death may never reach the
        # trainer's finally — flush the tail spans at interpreter exit so
        # only the genuinely-killed host loses data.
        self._atexit_armed = True
        atexit.register(self._atexit_flush)

    def span(self, name: str):
        return self.recorder.span(name)

    def mark_first_step(self, mode: str) -> None:
        """Time-to-first-step landed (cold/warm) — forwarded to goodput."""
        self.recorder.mark_first_step(mode)

    def observe(self, step: int, row: dict) -> bool:
        """Feed one fetched metrics row; returns True if the guard tripped."""
        self.last_step = int(step)
        self.last_health = dict(row)
        # Into the flight recorder FIRST: if the guard trips on this row its
        # bundle-adjacent flightrec dump must already contain the trigger.
        self.flight.record_health(step, row)
        return self.guard.check(step, row)

    def observe_timing(self, step: int, *, total_s: float,
                       input_wait_s: float = 0.0, checkpoint_s: float = 0.0,
                       epoch: int | None = None) -> str | None:
        """Feed one step's host-side phase timings (every step — pure
        ``perf_counter`` deltas, no device syncs). Returns the warn reason
        when the live straggler monitor flags the step."""
        compute = max(0.0, total_s - input_wait_s - checkpoint_s)
        row = {"step": int(step), "t": round(time.time(), 3),
               "total_s": round(total_s, 6),
               "input_wait_s": round(input_wait_s, 6),
               "compute_s": round(compute, 6),
               "checkpoint_s": round(checkpoint_s, 6)}
        if epoch is not None:
            row["epoch"] = int(epoch)
        self.flight.record_timing(step, **{k: v for k, v in row.items()
                                           if k != "step"})
        if self._steprows is not None:
            self._steprows.add(row)
        reason = self.monitor.observe(step, total_s=total_s,
                                      input_wait_s=input_wait_s)
        if reason:
            self.guard.warn(step, reason)
            if self.directory:
                # Live feed for the fleet scheduler's eviction reader
                # (fleetobs.read_chronic_straggler): the offline
                # detect_stragglers merge only lands after the attempt
                # exits. Same row shape as the merged attribution rows.
                fleetobs.append_straggler_flag(self.directory, {
                    "step": int(step), "slowest_rank": self.rank,
                    "delta_s": round(input_wait_s, 6),
                    "cause": "input_wait_s", "flagged": True,
                    "source": "live", "attempt": self.recorder.attempts})
        return reason

    def flight_dump(self, reason: str, **extra) -> str | None:
        """Dump the flight-recorder ring (anomaly / preempt / shutdown)."""
        return self.flight.dump(
            self.directory, reason=reason, rank=self.rank,
            meta={"run_id": self.run_id, "attempt_id": self.attempt_id,
                  "attempt": self.recorder.attempts, **extra})

    def write_artifacts(self) -> None:
        """Flush every on-disk artifact this rank owns: the per-rank trace/
        goodput pair (all ranks), the legacy plain pair (rank 0 only — N
        ranks used to clobber one shared file), and buffered step rows."""
        self.recorder.write_rank(self.directory, self.rank,
                                 self.recorder.attempts)
        if self.rank == 0:
            self.recorder.write(self.directory)
        if self._steprows is not None:
            self._steprows.flush()

    def _atexit_flush(self) -> None:
        if not self._atexit_armed:
            return
        self._atexit_armed = False
        try:
            self.write_artifacts()
        except Exception:  # interpreter teardown: never raise
            pass

    def snapshot(self) -> dict:
        return {"last_step": self.last_step,
                "last_health": self.last_health,
                "straggler_warnings": self.guard.warnings,
                "goodput": self.recorder.goodput()}

    def emit(self, where: str = "") -> dict:
        """Write the timeline + goodput files and log the one-line summary."""
        self.write_artifacts()
        if where == "shutdown":
            self._atexit_armed = False
        g = self.recorder.goodput()
        log.info(
            "goodput%s: %.1f%% productive over %.1fs (coverage %.1f%%) — %s",
            f" [{where}]" if where else "", 100 * g["goodput_fraction"],
            g["wall_s"], 100 * g["coverage"],
            " ".join(f"{k} {100 * v:.1f}%"
                     for k, v in g["fractions"].items() if k != "step"))
        return g
