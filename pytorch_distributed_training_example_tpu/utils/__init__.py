"""Config, logging/metrics, profiling, and guard-rail utilities."""
