"""Fleet observability primitives: stragglers, flight recorder, live metrics.

Everything here is jax-free and stdlib-only (numpy excepted nowhere) so the
same code runs inside a training rank, inside the (jax-free) supervisor, and
inside the offline merge CLI (``benchmarks/trace_merge.py``). Four pieces:

1. **Straggler/skew detection** — :func:`detect_stragglers` consumes per-rank
   step rows (written by the trainer at the existing ``log_every`` cadence;
   the timings are host-side ``perf_counter`` deltas so they cost zero extra
   device syncs) and attributes each step's skew to ``input_wait`` vs
   ``compute`` vs ``checkpoint``. The subtlety: in a gang, collectives
   equalize *total* step time across ranks — the rank stalled in its host
   input pipeline and the rank waiting for it in the collective show the same
   wall time. Attribution therefore keys on the HOST-LOCAL components
   (input_wait, checkpoint): the rank whose local component is elevated is
   the cause; elevated compute with flat local components means genuine
   device skew. :class:`StragglerMonitor` is the live, rank-local version
   wired into the AnomalyGuard as a warn-only trigger.

2. **Flight recorder** — :class:`FlightRecorder`, a bounded ring of the last
   N step records (span timings + health-pack norms + router stats). Every
   diagnostic exit dumps it as ``flightrec*.jsonl``: AnomalyGuard bundles,
   preemption exit-75, and — via the module-level :func:`dump_active`
   registry, callable from ``utils/chaos.py`` without holding a Telemetry
   reference — the abrupt host-loss exit-76.

3. **Live metrics surface** — :class:`MetricsServer`, a stdlib
   ``http.server`` endpoint serving Prometheus text format, plus
   :func:`write_progress`, an atomically-replaced ``progress.json`` for
   scrapers without network access to the pod.

4. **Artifact identity** — :func:`ensure_run_id` persists ONE stable run id
   in the checkpoint dir (``O_CREAT|O_EXCL``: first writer wins, everyone
   else reads it back), so every rank and every elastic attempt stamps the
   same ``run_id`` while keeping its per-attempt ``attempt_id``; the merge
   CLI and ``check_regression.py --goodput`` refuse to sum artifacts whose
   run ids differ.
"""

from __future__ import annotations

import collections
import json
import logging
import math
import os
import re
import socket
import statistics
import threading
import time

log = logging.getLogger("pdtx")

#: Version stamped into every telemetry artifact (trace, goodput, step rows,
#: flight-recorder dumps, progress.json). Bump on breaking layout changes.
SCHEMA_VERSION = 1

RUN_ID_FILE = "run_id.json"
PROGRESS_FILE = "progress.json"
STRAGGLER_FILE = "straggler.jsonl"
#: Sliding-window serving SLO summary (serve/slo.py), written atomically
#: into the serve job's checkpoint dir; the fleet scheduler folds its
#: attainment into placement weights.
SLO_FILE = "slo.jsonl"

#: Step-row components attributed by the straggler detector. ``input_wait``
#: and ``checkpoint`` are host-local causes; ``compute`` is the residual
#: (dispatch + device wait at the metrics fetch).
STEP_COMPONENTS = ("input_wait_s", "compute_s", "checkpoint_s")


def host_identity() -> str:
    """Short hostname for artifact stamps and merge track groups."""
    try:
        return socket.gethostname().split(".")[0] or "host"
    except Exception:  # pragma: no cover - exotic resolver failures
        return "host"


# ---------------------------------------------------------------------------
# Artifact identity: one stable run id per checkpoint dir.
# ---------------------------------------------------------------------------


def ensure_run_id(directory: str, fallback: str, *, fresh: bool = False,
                  rank: int = 0, timeout_s: float = 10.0) -> str:
    """Return the directory's stable run id, creating it on rank 0.

    Rank 0 owns the file: on a fresh (non-resume) run it replaces any stale
    id from a previous experiment, then creates atomically
    (``O_CREAT|O_EXCL`` + a pre-write temp name would be overkill: the
    payload is one ``write``). Other ranks only ever READ, polling briefly
    for rank 0 to get there first — ``jax.distributed.initialize`` has
    already barriered the gang, so the skew is milliseconds. This ordering
    (never rank>0-creates) is what makes the fresh-run replacement race-free.

    ``fallback`` (the per-process attempt uuid) is returned when there is no
    directory, or when the file never appears (single-process tests, a
    supervisor-less rank>0 with a dead rank 0) — artifacts are then stamped
    per-process only.
    """
    if not directory:
        return fallback
    path = os.path.join(directory, RUN_ID_FILE)
    os.makedirs(directory, exist_ok=True)
    if rank == 0:
        if fresh:
            try:
                os.unlink(path)
            except OSError:
                pass
        for reclaim in (False, True):
            try:
                fd = os.open(path, os.O_WRONLY | os.O_CREAT | os.O_EXCL, 0o644)
                with os.fdopen(fd, "w") as fh:
                    fh.write(json.dumps({
                        "schema_version": SCHEMA_VERSION, "run_id": fallback,
                        "host": host_identity(), "time": time.time()}))
                return fallback
            except FileExistsError:
                # Resume: a previous attempt's id survives — read it below.
                # BUT an attempt killed mid-write leaves a TORN file, and
                # without this check rank 0 would poll-read its own torn
                # file to the deadline on EVERY relaunch (the supervisor
                # never clears it). Validate and reclaim loudly instead.
                try:
                    with open(path) as fh:
                        str(json.load(fh)["run_id"])
                    break  # healthy survivor — the read loop returns it
                except (OSError, ValueError, KeyError) as e:
                    if reclaim:
                        break  # second torn file in a row — give up loudly
                    log.error(
                        "fleetobs: %s is torn (%s: %s) — an earlier attempt "
                        "died mid-write; reclaiming run identity", path,
                        type(e).__name__, e)
                    try:
                        os.unlink(path)
                    except OSError:
                        break
                    continue  # retry the exclusive create once
            except OSError as e:
                log.warning("fleetobs: cannot create %s (%s) — per-process "
                            "run id %s", path, e, fallback)
                return fallback
    deadline = time.monotonic() + (timeout_s if rank else 1.0)
    while True:
        try:
            with open(path) as fh:
                return str(json.load(fh)["run_id"])
        except (OSError, ValueError, KeyError):
            if time.monotonic() >= deadline:
                break
            time.sleep(0.05)
    if os.path.exists(path):
        log.error("fleetobs: %s exists but stayed unreadable past the "
                  "%.1fs deadline (torn write from a killed attempt?) — "
                  "falling back to per-process run id %s; artifacts from "
                  "this rank will not merge under the shared identity",
                  path, timeout_s if rank else 1.0, fallback)
    else:
        log.warning("fleetobs: no readable %s — falling back to per-process "
                    "run id %s", path, fallback)
    return fallback


# ---------------------------------------------------------------------------
# Torn-tolerant JSONL + atomic JSON helpers.
# ---------------------------------------------------------------------------


def read_jsonl_tolerant(path: str) -> list[dict]:
    """Parse a JSONL file, skipping unparseable lines (torn tails from a
    killed host) exactly like ``utils/elastic.read_dead_hosts``."""
    rows: list[dict] = []
    try:
        with open(path) as fh:
            for line in fh:
                line = line.strip()
                if not line:
                    continue
                try:
                    row = json.loads(line)
                except ValueError:
                    continue
                if isinstance(row, dict):
                    rows.append(row)
    except OSError:
        pass
    return rows


def write_json_atomic(path: str, payload: dict) -> None:
    """Write via temp file + ``os.replace`` so readers never see a torn file."""
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as fh:
        json.dump(payload, fh, indent=1, default=float)
    os.replace(tmp, path)


def read_slo_attainment(path: str) -> float | None:
    """Last ``slo_summary`` attainment from a serve job's ``slo.jsonl``
    (written atomically by ``serve.slo.SLOTracker.flush``), or None.

    Lives here — not in the serve package — so the jax-free fleet
    scheduler and launcher can fold SLO attainment into placement without
    importing serving code. Tolerant of torn tails like every fleet reader.
    """
    att = None
    for row in read_jsonl_tolerant(path):
        if row.get("kind") == "slo_summary":
            try:
                a = float(row["attainment"])
            except (KeyError, TypeError, ValueError):
                continue
            if math.isfinite(a):
                att = min(max(a, 0.0), 1.0)
    return att


def trace_doc(*, run_id: str, anchor_wall: float, anchor_mono: float,
              events: list, meta: dict | None = None) -> dict:
    """Assemble a Perfetto/Chrome trace document with the repo's salvage
    contract: ``otherData`` (identity stamps + clock anchor) deliberately
    comes FIRST — json.dump preserves insertion order, so a file torn
    mid-write by a killed host loses trailing *events*, never the header
    the merge CLI needs to salvage the prefix. Shared by the training-side
    ``telemetry.SpanRecorder`` and the serving-side ``serve.slo
    .RequestTrace`` so both merge under one clock-alignment rule."""
    return {"otherData": {
                "schema_version": SCHEMA_VERSION,
                "run_id": run_id,
                **(meta or {}),
                "clock_anchor": {"wall": anchor_wall,
                                 "monotonic": anchor_mono}},
            "displayTimeUnit": "ms",
            "traceEvents": list(events)}


def write_progress(directory: str, payload: dict) -> str:
    """Atomically replace ``progress.json`` (rank 0, log cadence)."""
    path = os.path.join(directory, PROGRESS_FILE)
    os.makedirs(directory, exist_ok=True)
    row = {"schema_version": SCHEMA_VERSION, "time": time.time(), **payload}
    write_json_atomic(path, row)
    return path


# ---------------------------------------------------------------------------
# Straggler / skew detection.
# ---------------------------------------------------------------------------


def _component(row: dict, key: str) -> float:
    try:
        return max(0.0, float(row.get(key, 0.0) or 0.0))
    except (TypeError, ValueError):
        return 0.0


def detect_stragglers(rows_by_rank: dict[int, list[dict]],
                      threshold: float = 2.0,
                      abs_floor_s: float = 0.05) -> list[dict]:
    """Offline per-step skew attribution across ranks.

    For every step present on >= 2 ranks, the rank with the largest
    host-local excess (input_wait + checkpoint above the per-component
    cross-rank minimum) is the candidate straggler; when no local component
    is elevated the candidate is the rank with the slowest total (genuine
    device/compute skew). A step is ``flagged`` when the candidate's delta
    exceeds both ``abs_floor_s`` and ``(threshold - 1) x`` the fleet-typical
    step time (median of ALL rank-step totals — robust to the handful of
    stalled steps being diagnosed).

    Returns one row per multi-rank step, sorted by step::

        {"step", "slowest_rank", "delta_s", "typical_s", "cause",
         "flagged", "attribution": {"input_wait_s": ..., "compute_s": ...,
         "checkpoint_s": ...}, "ranks": N}
    """
    by_step: dict[int, dict[int, dict]] = {}
    totals_all: list[float] = []
    for rank, rows in rows_by_rank.items():
        for row in rows:
            step = row.get("step")
            if step is None:
                continue
            by_step.setdefault(int(step), {})[int(rank)] = row
            totals_all.append(_component(row, "total_s"))
    if not totals_all:
        return []
    typical = statistics.median(totals_all)

    out: list[dict] = []
    for step in sorted(by_step):
        ranks = by_step[step]
        if len(ranks) < 2:
            continue
        mins = {c: min(_component(r, c) for r in ranks.values())
                for c in STEP_COMPONENTS}
        local_excess = {
            rank: (_component(row, "input_wait_s") - mins["input_wait_s"])
            + (_component(row, "checkpoint_s") - mins["checkpoint_s"])
            for rank, row in ranks.items()}
        slow_local = max(local_excess, key=local_excess.get)
        totals = {rank: _component(row, "total_s")
                  for rank, row in ranks.items()}
        slow_total = max(totals, key=totals.get)
        total_skew = totals[slow_total] - min(totals.values())

        if local_excess[slow_local] >= max(abs_floor_s, 0.5 * total_skew):
            slowest, delta = slow_local, local_excess[slow_local]
        else:
            # No host-local cause: collectives hide who is slow locally, so
            # fall back to the total-time spread (device skew, unsynced run).
            slowest, delta = slow_total, total_skew
        row = ranks[slowest]
        attribution = {c: round(_component(row, c) - mins[c], 6)
                       for c in STEP_COMPONENTS}
        cause = max(attribution, key=attribution.get)
        flagged = (delta > abs_floor_s
                   and delta > max(0.0, threshold - 1.0) * typical)
        out.append({
            "step": step,
            "slowest_rank": slowest,
            "delta_s": round(delta, 6),
            "typical_s": round(typical, 6),
            "cause": cause,
            "flagged": bool(flagged),
            "attribution": attribution,
            "ranks": len(ranks),
        })
    return out


def write_stragglers(directory: str, rows: list[dict]) -> str:
    path = os.path.join(directory, STRAGGLER_FILE)
    with open(path, "w") as fh:
        for row in rows:
            fh.write(json.dumps(row, default=float) + "\n")
    return path


def straggler_gauges(rows: list[dict], prefix: str = "fleet_straggler"
                     ) -> dict[str, float]:
    """Fold ``straggler.jsonl`` rows into live Prometheus gauges.

    r12 detection has been write-only since it landed; this makes it
    scrapeable at runtime (``launch.py --fleet`` pushes the result onto the
    fleet MetricsServer every poll cadence). Per-rank flag counts stand in
    for per-host counts — in this fleet each child process IS a host, and
    ``slowest_rank`` is the only locator the rows carry.
    """
    out: dict[str, float] = {f"{prefix}_steps": float(len(rows)),
                             f"{prefix}_flagged_total": 0.0}
    worst = 0.0
    for row in rows:
        if not row.get("flagged"):
            continue
        out[f"{prefix}_flagged_total"] += 1
        rank = row.get("slowest_rank")
        if rank is not None:
            key = f"{prefix}_flagged_rank{int(rank)}"
            out[key] = out.get(key, 0.0) + 1
        cause = str(row.get("cause") or "unknown")
        key = f"{prefix}_cause_{_METRIC_RE.sub('_', cause)}"
        out[key] = out.get(key, 0.0) + 1
        try:
            worst = max(worst, float(row.get("delta_s") or 0.0))
        except (TypeError, ValueError):
            pass
    if out[f"{prefix}_flagged_total"]:
        out[f"{prefix}_worst_delta_s"] = round(worst, 4)
    return out


def append_straggler_flag(directory: str, row: dict) -> None:
    """Append one LIVE flagged row to ``straggler.jsonl`` (single ``write``,
    so a killed host tears at most the final line).

    The in-run straggler monitor feeds the scheduler's eviction reader
    *while the job runs* — the offline ``detect_stragglers`` merge only
    lands after an attempt exits, far too late to evict a chronically slow
    host. The post-run ``write_stragglers`` rewrite replaces these rows
    with the fleet-level attribution of the same events.
    """
    try:
        with open(os.path.join(directory, STRAGGLER_FILE), "a") as fh:
            fh.write(json.dumps(row, default=float) + "\n")
    except OSError as e:
        log.warning("fleetobs: straggler append failed (%s)", e)


def read_chronic_straggler(path: str, consecutive: int) -> dict | None:
    """Trailing run of flagged rows blaming one rank — the eviction signal.

    Jax-free (the ``read_slo_attainment`` pattern) so the fleet scheduler
    and launcher consume ``straggler.jsonl`` without importing jax. Scans
    rows in file order and measures the TRAILING streak of ``flagged``
    rows that name one consistent ``slowest_rank``; an unflagged row or a
    different culprit resets it. Returns ``{"rank", "streak", "rows"}``
    when the streak reaches ``consecutive`` (``rows`` = total straggler
    rows seen, the scheduler's evidence-freshness cursor), else None.
    Missing/torn files are no evidence, never an error.
    """
    streak, rank, nrows = 0, None, 0
    for row in read_jsonl_tolerant(path):
        if "flagged" not in row and "slowest_rank" not in row:
            continue  # meta/header rows
        nrows += 1
        r = row.get("slowest_rank")
        if not row.get("flagged") or r is None:
            streak, rank = 0, None
            continue
        r = int(r)
        streak = streak + 1 if r == rank else 1
        rank = r
    if rank is not None and streak >= max(int(consecutive), 1):
        return {"rank": rank, "streak": streak, "rows": nrows}
    return None


class StragglerMonitor:
    """Live, rank-local input-stall detector (warn-only AnomalyGuard trigger).

    A single rank cannot see the fleet, but it CAN see its own host-local
    input_wait spike against its own recent step times — the signature of a
    stalled data pipeline (the fleet-level attribution of the same event is
    the offline :func:`detect_stragglers`). Checkpoint time is excluded:
    cadence saves are legitimate local work, not a straggle.
    """

    def __init__(self, threshold: float = 2.0, window: int = 32,
                 min_window: int = 3, abs_floor_s: float = 0.05):
        self.threshold = float(threshold)
        self.abs_floor_s = abs_floor_s
        self.min_window = min_window
        self._totals: collections.deque = collections.deque(maxlen=window)
        self.warnings = 0

    def observe(self, step: int, *, total_s: float,
                input_wait_s: float) -> str | None:
        """Feed one step; returns a warn reason when the step straggled."""
        reason = None
        if len(self._totals) >= self.min_window:
            typical = statistics.median(self._totals)
            bar = max(self.abs_floor_s,
                      max(0.0, self.threshold - 1.0) * typical)
            if input_wait_s > bar:
                self.warnings += 1
                reason = (f"input_wait {input_wait_s:.3f}s at step {step} "
                          f"exceeds {bar:.3f}s "
                          f"(threshold {self.threshold:g}x median "
                          f"{typical:.3f}s)")
        # Record AFTER the check so a stall doesn't poison its own baseline;
        # record the total regardless so the window keeps moving.
        self._totals.append(max(0.0, float(total_s)))
        return reason


class StepRowWriter:
    """Buffered appender for per-rank step rows (``steprows.r<R>.a<A>.jsonl``).

    Rows are buffered in memory and appended in batches (log cadence /
    shutdown / atexit) — one ``write`` per flush, so a killed host tears at
    most the final line, which :func:`read_jsonl_tolerant` skips.
    """

    def __init__(self, directory: str, rank: int, attempt: int,
                 meta: dict | None = None, flush_every: int = 32):
        self.path = os.path.join(directory,
                                 f"steprows.r{rank}.a{attempt}.jsonl")
        self.flush_every = max(1, int(flush_every))
        self._pending: list[dict] = [
            {"schema_version": SCHEMA_VERSION, "rank": rank,
             "attempt": attempt, "host": host_identity(), **(meta or {})}]

    def add(self, row: dict) -> None:
        self._pending.append(row)
        if len(self._pending) >= self.flush_every:
            self.flush()

    def flush(self) -> None:
        if not self._pending:
            return
        rows, self._pending = self._pending, []
        try:
            os.makedirs(os.path.dirname(self.path) or ".", exist_ok=True)
            with open(self.path, "a") as fh:
                fh.write("".join(json.dumps(r, default=float) + "\n"
                                 for r in rows))
        except OSError as e:  # diagnostics never take down training
            log.warning("steprow flush failed (%s)", e)


def steprow_files(directory: str) -> dict[int, list[str]]:
    """Per-rank steprow files under ``directory``, attempt-sorted."""
    found: dict[int, list[tuple[int, str]]] = {}
    try:
        names = os.listdir(directory)
    except OSError:
        return {}
    for name in names:
        m = re.fullmatch(r"steprows\.r(\d+)\.a(\d+)\.jsonl", name)
        if m:
            found.setdefault(int(m.group(1)), []).append(
                (int(m.group(2)), os.path.join(directory, name)))
    return {rank: [p for _, p in sorted(pairs)]
            for rank, pairs in sorted(found.items())}


def load_steprows(directory: str) -> dict[int, list[dict]]:
    """All ranks' step rows, later attempts overriding replayed steps."""
    out: dict[int, list[dict]] = {}
    for rank, paths in steprow_files(directory).items():
        by_step: dict[int, dict] = {}
        for path in paths:  # attempt order: later attempts win on replay
            for row in read_jsonl_tolerant(path):
                if "step" in row:
                    by_step[int(row["step"])] = row
        out[rank] = [by_step[s] for s in sorted(by_step)]
    return out


# ---------------------------------------------------------------------------
# Flight recorder.
# ---------------------------------------------------------------------------


class FlightRecorder:
    """Bounded ring of the last N step records, dumped on diagnostic exits.

    ``record_timing`` adds one row per step (host span timings);
    ``record_health`` merges the health-pack fetch (loss, norms, router
    stats) into the matching step's row — the two arrive from different
    call sites in the trainer loop. ``dump`` appends a header + the rows to
    ``flightrec.jsonl`` (rank 0) / ``flightrec.r<rank>.jsonl``, append-mode
    so an anomaly dump followed by a preemption dump keeps both.
    """

    def __init__(self, capacity: int = 256):
        self.capacity = max(1, int(capacity))
        self._ring: collections.deque = collections.deque(maxlen=self.capacity)
        self._lock = threading.Lock()

    def __len__(self) -> int:
        return len(self._ring)

    def record_timing(self, step: int, **fields) -> None:
        with self._lock:
            self._ring.append({"step": int(step), **fields})

    def record_health(self, step: int, row: dict) -> None:
        clean = {k: v for k, v in row.items()
                 if isinstance(v, (int, float, str, bool)) or v is None}
        with self._lock:
            for rec in reversed(self._ring):
                if rec.get("step") == int(step):
                    rec.update(clean)
                    return
            self._ring.append({"step": int(step), **clean})

    def rows(self) -> list[dict]:
        with self._lock:
            return [dict(r) for r in self._ring]

    def dump(self, directory: str, *, reason: str, rank: int = 0,
             meta: dict | None = None) -> str | None:
        """Append the ring to the per-rank flightrec file; best-effort (the
        host-loss path calls this from ``os._exit`` territory)."""
        rows = self.rows()
        name = ("flightrec.jsonl" if rank == 0
                else f"flightrec.r{rank}.jsonl")
        path = os.path.join(directory, name)
        try:
            os.makedirs(directory, exist_ok=True)
            with open(path, "a") as fh:
                header = {"flightrec": reason, "schema_version": SCHEMA_VERSION,
                          "rank": rank, "host": host_identity(),
                          "records": len(rows), "time": time.time(),
                          **(meta or {})}
                fh.write(json.dumps(header, default=float) + "\n")
                for row in rows:
                    fh.write(json.dumps(row, default=float) + "\n")
            return path
        except Exception as e:  # never let diagnostics kill the exit path
            log.warning("flight recorder dump failed (%s: %s)",
                        type(e).__name__, e)
            return None


#: Active recorder registry: (recorder, directory, rank, meta). Lets code
#: with no Telemetry reference — chaos ``kill_host`` just before
#: ``os._exit(76)`` — dump the ring of whatever run is live in this process.
_active: tuple[FlightRecorder, str, int, dict] | None = None


def set_active(recorder: FlightRecorder | None, directory: str = "",
               rank: int = 0, meta: dict | None = None) -> None:
    global _active
    _active = ((recorder, directory, rank, dict(meta or {}))
               if recorder is not None and directory else None)


def dump_active(reason: str, **extra) -> str | None:
    """Dump the registered recorder (no-op when none is live)."""
    if _active is None:
        return None
    recorder, directory, rank, meta = _active
    return recorder.dump(directory, reason=reason, rank=rank,
                         meta={**meta, **extra})


# ---------------------------------------------------------------------------
# Live metrics surface.
# ---------------------------------------------------------------------------

_METRIC_RE = re.compile(r"[^a-zA-Z0-9_]")


class MetricsServer:
    """Stdlib-only Prometheus endpoint on rank 0 (``--metrics-port``).

    ``GET /metrics`` renders the current gauges in Prometheus text format
    (all ``pdtx_``-prefixed); ``GET /progress`` returns them as JSON. Gauges
    are updated from the trainer at the log cadence — the server thread
    never touches jax state, just a dict under a lock. ``port=0`` binds an
    ephemeral port (tests); the bound port is in ``.port`` after
    :meth:`start`.
    """

    def __init__(self, port: int = 0, addr: str = "0.0.0.0"):
        self.requested_port = int(port)
        self.addr = addr
        self.port: int | None = None
        self._gauges: dict[str, float] = {}
        self._info: dict[str, str] = {}
        self._hists: dict[str, dict] = {}
        self._lock = threading.Lock()
        self._httpd = None
        self._thread: threading.Thread | None = None

    def update(self, **gauges) -> None:
        with self._lock:
            for key, val in gauges.items():
                if isinstance(val, bool) or val is None:
                    continue
                if isinstance(val, (int, float)):
                    self._gauges[_METRIC_RE.sub("_", str(key))] = float(val)
                else:
                    self._info[_METRIC_RE.sub("_", str(key))] = str(val)

    def update_histograms(self, **hists) -> None:
        """Cumulative Prometheus histograms (serving SLO latencies). Each
        value is ``{"buckets": [(le, cum_count), ..., ("+Inf", n)],
        "sum": float, "count": int}`` — the shape
        ``serve.slo.SLOTracker.histograms`` emits."""
        with self._lock:
            for key, val in hists.items():
                if val:
                    self._hists[_METRIC_RE.sub("_", str(key))] = val

    def render(self) -> str:
        with self._lock:
            gauges = dict(self._gauges)
            info = dict(self._info)
            hists = dict(self._hists)
        lines = []
        if info:
            labels = ",".join(f'{k}="{v}"' for k, v in sorted(info.items()))
            lines += ["# TYPE pdtx_run_info gauge",
                      f"pdtx_run_info{{{labels}}} 1"]
        for key in sorted(gauges):
            val = gauges[key]
            if val != val:  # Prometheus spells non-finite values its own way
                text = "NaN"
            elif val in (float("inf"), float("-inf")):
                text = "+Inf" if val > 0 else "-Inf"
            else:
                text = repr(val)
            lines += [f"# TYPE pdtx_{key} gauge", f"pdtx_{key} {text}"]
        for key in sorted(hists):
            h = hists[key]
            lines.append(f"# TYPE pdtx_{key} histogram")
            for le, cum in h.get("buckets", ()):
                le_s = le if isinstance(le, str) else repr(float(le))
                lines.append(f'pdtx_{key}_bucket{{le="{le_s}"}} {int(cum)}')
            lines += [f"pdtx_{key}_sum {float(h.get('sum', 0.0))!r}",
                      f"pdtx_{key}_count {int(h.get('count', 0))}"]
        return "\n".join(lines) + "\n"

    def snapshot(self) -> dict:
        with self._lock:
            return {**self._info, **self._gauges}

    def start(self) -> "MetricsServer":
        import http.server

        server = self

        class Handler(http.server.BaseHTTPRequestHandler):
            def do_GET(self):  # noqa: N802 (stdlib naming)
                if self.path.rstrip("/") in ("", "/metrics"):
                    body = server.render().encode()
                    ctype = "text/plain; version=0.0.4"
                elif self.path.rstrip("/") == "/progress":
                    body = json.dumps(server.snapshot(),
                                      default=float).encode()
                    ctype = "application/json"
                else:
                    self.send_error(404)
                    return
                self.send_response(200)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *args):  # quiet: scrapes are not log lines
                pass

        self._httpd = http.server.ThreadingHTTPServer(
            (self.addr, self.requested_port), Handler)
        self._httpd.daemon_threads = True
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, kwargs={"poll_interval": 0.5},
            name="pdtx-metrics", daemon=True)
        self._thread.start()
        log.info("metrics endpoint: http://%s:%d/metrics",
                 self.addr, self.port)
        return self

    def stop(self) -> None:
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None


# ---------------------------------------------------------------------------
# Fleet goodput aggregation (used by the merge CLI; pure + unit-testable).
# ---------------------------------------------------------------------------


def aggregate_goodput(per_rank: dict[int, dict]) -> dict:
    """Fold per-rank cumulative goodput summaries into one fleet summary.

    Each input is the FINAL (highest-attempt) goodput dict of one rank, so
    category seconds are averaged (every rank spans the same wall-clock; the
    mean is the fleet's per-host decomposition), fractions are recomputed
    from the averaged decomposition, and attempts is the max seen.
    """
    ranks = sorted(per_rank)
    if not ranks:
        return {}
    n = len(ranks)
    wall = sum(float(per_rank[r].get("wall_s") or 0.0) for r in ranks) / n
    cats: dict[str, float] = {}
    counts: dict[str, int] = {}
    run_ids: list[str] = []
    attempts = 1
    for r in ranks:
        g = per_rank[r]
        for k, v in (g.get("categories_s") or {}).items():
            cats[k] = cats.get(k, 0.0) + float(v) / n
        for k, v in (g.get("counts") or {}).items():
            counts[k] = max(counts.get(k, 0), int(v))
        rid = g.get("run_id")
        if rid and rid not in run_ids:
            run_ids.append(rid)
        attempts = max(attempts, int(g.get("attempts") or 1))
    wall = max(wall, 1e-9)
    fracs = {k: v / wall for k, v in cats.items()}
    good = sum(fracs.get(k, 0.0) for k in ("step",))
    return {
        "schema_version": SCHEMA_VERSION,
        "run_id": run_ids[0] if len(run_ids) == 1 else None,
        "run_ids": run_ids,
        "ranks": ranks,
        "wall_s": round(wall, 4),
        "categories_s": {k: round(v, 4) for k, v in sorted(cats.items())},
        "counts": counts,
        "fractions": {k: round(v, 4) for k, v in sorted(fracs.items())},
        "goodput_fraction": round(good, 4),
        "badput_fraction": round(sum(fracs.values()) - good, 4),
        "coverage": round(sum(fracs.values()), 4),
        "attempts": attempts,
        "per_rank": {str(r): {
            "goodput_fraction": per_rank[r].get("goodput_fraction"),
            "coverage": per_rank[r].get("coverage"),
            "wall_s": per_rank[r].get("wall_s"),
            "host": (per_rank[r].get("meta") or {}).get("host"),
        } for r in ranks},
    }


def aggregate_cluster_goodput(per_job: dict[str, dict]) -> dict:
    """Fold per-JOB goodput summaries into one cluster-level summary.

    The unit of aggregation is different from :func:`aggregate_goodput`:
    there the inputs are ranks of ONE run spanning the same wall clock (so
    category seconds average), here they are independent jobs of a shared
    device pool — separate runs with *distinct run_ids* and disjoint wall
    spans. Wall and category seconds therefore SUM (the device-time view a
    cluster is billed in), coverage and goodput come out wall-weighted, and
    carrying several run_ids is the expected shape, not the stale-artifact
    smell it is for a single run (``check_regression.py --goodput
    --cluster`` relaxes the mixed-run refusal for exactly this file).
    """
    names = sorted(per_job)
    if not names:
        return {}
    wall = 0.0
    cats: dict[str, float] = {}
    counts: dict[str, int] = {}
    run_ids: list[str] = []
    attempts = 0
    for name in names:
        g = per_job[name]
        wall += float(g.get("wall_s") or 0.0)
        for k, v in (g.get("categories_s") or {}).items():
            cats[k] = cats.get(k, 0.0) + float(v)
        for k, v in (g.get("counts") or {}).items():
            counts[k] = counts.get(k, 0) + int(v)
        rid = g.get("run_id")
        if rid and rid not in run_ids:
            run_ids.append(rid)
        attempts += int(g.get("attempts") or 1)
    wall = max(wall, 1e-9)
    fracs = {k: v / wall for k, v in cats.items()}
    good = sum(fracs.get(k, 0.0) for k in ("step", "prefill"))
    return {
        "schema_version": SCHEMA_VERSION,
        "cluster": True,
        "jobs": names,
        "run_ids": run_ids,
        "wall_s": round(wall, 4),
        "categories_s": {k: round(v, 4) for k, v in sorted(cats.items())},
        "counts": counts,
        "fractions": {k: round(v, 4) for k, v in sorted(fracs.items())},
        "goodput_fraction": round(good, 4),
        "badput_fraction": round(sum(fracs.values()) - good, 4),
        "coverage": round(sum(fracs.values()), 4),
        "attempts": attempts,
        "per_job": {name: {
            "run_id": per_job[name].get("run_id"),
            "goodput_fraction": per_job[name].get("goodput_fraction"),
            "coverage": per_job[name].get("coverage"),
            "wall_s": per_job[name].get("wall_s"),
            "attempts": per_job[name].get("attempts"),
        } for name in names},
    }
