"""Guard rails: hang watchdog, NaN debugging, donation-safe blocking.

SURVEY.md §5 "race detection / sanitizers": JAX's functional model removes
data races by construction; what remains are (a) collective deadlocks — one
host stops feeding steps and the rest block inside a collective forever
(the reference relies on the NCCL watchdog for this), (b) NaN propagation,
(c) host-side input races. This module covers (a) and (b); (c) is handled
by the loader's deterministic per-slot queues.
"""

from __future__ import annotations

import faulthandler
import json
import logging
import sys
import threading
import time
from typing import Callable

import jax

log = logging.getLogger("pdtx")


class Watchdog:
    """Dead-man's switch for the train loop (NCCL-watchdog equivalent).

    ``beat()`` every step (the trainer beats from BOTH the train and eval
    loops, so a long eval never false-triggers); if no beat arrives within
    ``timeout_s`` the watchdog dumps all Python thread stacks to stderr (so
    a hung collective is diagnosable post-mortem), logs ``context_fn()``
    when provided (the trainer passes the telemetry snapshot: last global
    step, last health-pack row, goodput decomposition — so the dump says
    WHERE training was, not just which frames are parked), and, with
    ``fatal=True``, aborts the process so a supervisor can restart from the
    latest checkpoint — the TPU recovery model (gang-scheduled slices
    restart; no elastic shrink).
    """

    def __init__(self, timeout_s: float = 600.0, fatal: bool = False,
                 context_fn: Callable[[], dict] | None = None):
        self.timeout_s = timeout_s
        self.fatal = fatal
        self.context_fn = context_fn
        self._last = time.monotonic()
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    def beat(self):
        self._last = time.monotonic()

    def start(self):
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()
        return self

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)  # no late fires after stop()

    def _run(self):
        while not self._stop.wait(min(self.timeout_s / 4, 30.0)):
            idle = time.monotonic() - self._last
            if idle > self.timeout_s:
                # Re-check the stop flag before acting: stop() may have been
                # called while this thread was computing `idle` (the wait()
                # above returned False BEFORE the event was set). Without
                # this, a clean shutdown that raced the final wait window
                # could still dump stacks — or, with fatal=True, abort a
                # process that was exiting normally.
                if self._stop.is_set():
                    return
                log.error(
                    "watchdog: no step progress for %.0fs (timeout %.0fs) — "
                    "likely a hung collective; dumping stacks", idle, self.timeout_s)
                if self.context_fn is not None:
                    try:
                        log.error("watchdog context: %s",
                                  json.dumps(self.context_fn(), default=str))
                    except Exception as e:  # never let context kill the dump
                        log.error("watchdog context unavailable (%s)", e)
                if self._stop.is_set():
                    return
                faulthandler.dump_traceback(file=sys.stderr)
                if self.fatal and not self._stop.is_set():
                    import os

                    os.abort()
                self._last = time.monotonic()  # don't spam


def block_until_ready_with_timeout(tree, timeout_s: float = 600.0,
                                   poll_s: float = 0.02):
    """block_until_ready that raises instead of hanging forever.

    Implemented by POLLING ``jax.Array.is_ready()`` against a deadline —
    no helper thread. The previous version parked a daemon thread inside
    ``block_until_ready``; on timeout that thread could never be joined and
    leaked (pinned to the hung dispatch) for the life of the process, one
    per timed-out call. Leaves without ``is_ready`` (host numpy, python
    scalars) are ready by definition. Once everything is ready, a real
    ``block_until_ready`` surfaces any deferred computation error.
    """
    leaves = [x for x in jax.tree.leaves(tree) if hasattr(x, "is_ready")]
    deadline = time.monotonic() + timeout_s
    pending = leaves
    while pending:
        pending = [x for x in pending if not x.is_ready()]
        if not pending:
            break
        if time.monotonic() > deadline:
            faulthandler.dump_traceback(file=sys.stderr)
            raise TimeoutError(
                f"device results not ready after {timeout_s}s "
                f"({len(pending)}/{len(leaves)} arrays pending) — "
                f"hung collective?")
        time.sleep(poll_s)
    for x in leaves:
        x.block_until_ready()  # raises the computation's error, if any


def enable_nan_checks():
    """Trap NaNs at the op that produced them (debug runs; slows compile)."""
    jax.config.update("jax_debug_nans", True)


def check_donation_safety(fn):
    """Wrap a donated-arg jitted fn to give a clear error on reuse-after-donate."""
    def wrapper(state, *a, **kw):
        try:
            return fn(state, *a, **kw)
        except RuntimeError as e:
            if "donated" in str(e) or "deleted" in str(e):
                raise RuntimeError(
                    "train state was reused after being donated to the step; "
                    "always rebind: `state, metrics = train_step(state, batch)`"
                ) from e
            raise
    return wrapper
