"""Process-0 structured logging (console + JSONL) and the AverageMeter.

Reference parity (SURVEY.md §5 metrics): the reference prints loss/acc/
images-per-sec from rank 0 using the classic ``AverageMeter`` pattern. Same
surface here, plus machine-readable JSONL for the bench harness.
"""

from __future__ import annotations

import json
import logging
import os
import sys
import time
import uuid

import jax

log = logging.getLogger("pdtx")


def setup_logging(level: int = logging.INFO, jsonl_path: str | None = None,
                  tensorboard_dir: str | None = None) -> "MetricLogger":
    """Configure stdout logging on process 0 (other processes stay quiet)."""
    is_main = jax.process_index() == 0
    handler = logging.StreamHandler(sys.stdout)
    handler.setFormatter(logging.Formatter("%(asctime)s %(levelname).1s %(message)s",
                                           datefmt="%H:%M:%S"))
    log.handlers[:] = [handler]
    log.setLevel(level if is_main else logging.ERROR)
    log.propagate = False
    return MetricLogger(jsonl_path if is_main else None,
                        tensorboard_dir if is_main else None)


class MetricLogger:
    """JSONL sink plus optional TensorBoard scalars (SURVEY.md §5 metrics:
    "optional TensorBoard scalars"). TB is lazy and best-effort — if no
    SummaryWriter implementation is importable the logger degrades to
    JSONL-only with one warning."""

    def __init__(self, jsonl_path: str | None = None,
                 tensorboard_dir: str | None = None,
                 run_id: str | None = None):
        self._fh = None
        self._tb = None
        # Every row is stamped with a per-process run id: --resume appends
        # to the same metrics.jsonl, so without it reruns of one experiment
        # are indistinguishable in the file.
        self.run_id = run_id or uuid.uuid4().hex[:12]
        self._steps: dict[str, int] = {}  # per-kind last x-value (ADVICE r4)
        # When the trainer sets this, epoch-keyed rows (eval) are converted
        # to the global-step axis so train and eval scalars are comparable.
        self.steps_per_epoch: int | None = None
        if jsonl_path:
            os.makedirs(os.path.dirname(jsonl_path) or ".", exist_ok=True)
            self._fh = open(jsonl_path, "a")
        if tensorboard_dir:
            try:
                from torch.utils.tensorboard import SummaryWriter

                self._tb = SummaryWriter(tensorboard_dir)
            except Exception as e:  # no TB in this environment
                log.warning("TensorBoard export disabled (%s)", e)

    def write(self, **metrics):
        if self._fh is not None:
            metrics.setdefault("time", time.time())
            metrics.setdefault("run_id", self.run_id)
            self._fh.write(json.dumps(metrics, default=float) + "\n")
            self._fh.flush()
        if self._tb is not None:
            kind = metrics.get("kind", "train")
            prev = self._steps.get(kind, -1)
            if "step" in metrics:
                step = int(metrics["step"])
            elif "epoch" in metrics and self.steps_per_epoch:
                # end-of-epoch row -> last global step of that epoch
                step = (int(metrics["epoch"]) + 1) * self.steps_per_epoch - 1
            elif "epoch" in metrics:
                step = int(metrics["epoch"])
            else:
                step = prev + 1
            # Clamp to the per-kind high-water mark so a resume that replays
            # an earlier step (or an epoch row computed from a shorter
            # steps_per_epoch) cannot emit a backwards x-value — TensorBoard
            # renders non-monotonic series as a sawtooth.
            step = max(prev, step)
            self._steps[kind] = step
            for key, val in metrics.items():
                if key in ("kind", "step", "time"):
                    continue
                if isinstance(val, bool) or not isinstance(val, (int, float)):
                    continue
                self._tb.add_scalar(f"{kind}/{key}", float(val), step)

    def close(self):
        if self._fh is not None:
            self._fh.close()
            self._fh = None
        if self._tb is not None:
            self._tb.close()
            self._tb = None


class AverageMeter:
    """Running average of a scalar (the reference's logging idiom)."""

    def __init__(self, name: str = "", fmt: str = ":.4f"):
        self.name, self.fmt = name, fmt
        self.reset()

    def reset(self):
        self.val = self.sum = self.count = self.avg = 0.0

    def update(self, val: float, n: int = 1):
        self.val = float(val)
        self.sum += float(val) * n
        self.count += n
        self.avg = self.sum / max(self.count, 1)

    def __str__(self):
        # fmt may be given with or without the format-spec colon (":.4f" or
        # ".4f"); the old [1:] slice silently mangled the latter into "4f".
        spec = self.fmt[1:] if self.fmt.startswith(":") else self.fmt
        return f"{self.name} {format(self.val, spec)} ({format(self.avg, spec)})"


class Throughput:
    """Images|tokens-per-second meter with warmup skip."""

    def __init__(self, warmup_steps: int = 2):
        self.warmup_steps = warmup_steps
        self._n = 0
        self._items = 0
        self._t0 = None

    def update(self, items: int):
        self._n += 1
        if self._n == self.warmup_steps:
            self._t0 = time.perf_counter()
            self._items = 0
        elif self._n > self.warmup_steps:
            self._items += items

    @property
    def rate(self) -> float:
        if self._t0 is None or self._items == 0:
            return 0.0
        return self._items / (time.perf_counter() - self._t0)
