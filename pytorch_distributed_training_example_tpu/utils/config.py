"""Flat dataclass config + the five workload presets from BASELINE.json.

Reference parity (SURVEY.md §5 config): the reference's config system is
argparse flags on ``main.py``. We keep that CLI surface (main.py builds one
of these dataclasses from flags) backed by named presets matching the
reference's config matrix exactly.
"""

from __future__ import annotations

import dataclasses
from typing import Any


@dataclasses.dataclass
class Config:
    # workload
    model: str = "resnet18"
    dataset: str = "cifar10"
    num_classes: int = 10
    image_size: int = 32
    seq_len: int = 1024
    # optimization
    epochs: int = 10
    global_batch_size: int = 256
    lr: float = 0.1
    warmup_epochs: float = 1.0
    # cosine (default) | step (the reference ImageNet recipe:
    # lr * gamma^(epoch // step_epochs)) | constant
    lr_schedule: str = "cosine"
    lr_step_epochs: int = 30
    lr_gamma: float = 0.1
    weight_decay: float = 1e-4
    momentum: float = 0.9
    optimizer: str = "sgd"  # sgd | adamw
    label_smoothing: float = 0.0
    grad_clip: float = 0.0
    # attention kernel: auto | xla | flash (Pallas) | ring (CP) | ulysses
    attn_impl: str = "auto"
    # model regularization (0.0 matches torchvision factory defaults; the
    # registry forwards it to families that support it, e.g. ViT)
    dropout: float = 0.0
    # precision / memory
    precision: str = "bf16"
    remat: bool = False  # gradient checkpointing (reference configs[4])
    # checkpoint policy under remat (Llama family): nothing | dots |
    # dots_no_batch | attn_out — see models.llama.REMAT_POLICIES
    remat_policy: str = "nothing"
    grad_accum_steps: int = 1  # microbatches per optimizer step (in-step scan)
    # MoE routing/dispatch (llama_moe family; parallel/moe.py)
    moe_top_k: int = 2
    moe_capacity_factor: float = 1.25
    moe_dispatch_impl: str = "gather"  # sort | gather | einsum | dropless
    moe_combine_dtype: str = "fp32"  # fp32 (exact) | bf16 (combine-BW A/B)
    moe_router_dtype: str = "fp32"  # fp32 (ST-MoE exact) | bf16 (matmul A/B)
    moe_router_impl: str = "reference"  # reference | fused (Pallas kernel)
    # dropless EP transport: replicated weights | sharded a2a | a2a+gmm overlap
    moe_ep_dispatch: str = "replicated"  # replicated | a2a | a2a_overlap
    moe_ep_overlap_chunks: int = 2  # a2a_overlap double-buffer windows
    pp_microbatches: int = 8  # GPipe microbatches (strategy "pp")
    # parallelism (mesh axis sizes; -1 absorbs remaining devices)
    strategy: str = "dp"  # dp | fsdp | fsdp_tp (model-provided tables)
    mesh_data: int = -1
    mesh_fsdp: int = 1
    mesh_stage: int = 1
    mesh_expert: int = 1
    mesh_context: int = 1
    mesh_model: int = 1
    # io
    data_path: str | None = None
    workers: int = 4
    native_loader: bool = True  # C++ batch engine when dataset supports it
    log_every: int = 50
    eval_every_epochs: int = 1
    checkpoint_dir: str | None = None
    # TensorBoard scalar export dir (optional; JSONL is always written
    # when checkpoint_dir is set)
    tensorboard_dir: str | None = None
    checkpoint_every_epochs: int = 1
    # 0 = epoch-boundary only. N > 0 also saves every N optimizer steps with
    # the within-epoch offset recorded, so --resume restarts mid-epoch at the
    # exact next unseen sample (the 8B-class configs cannot afford losing a
    # days-long epoch to a failure; BASELINE.json configs[4]).
    checkpoint_every_steps: int = 0
    resume: str | None = None  # path | "auto"
    # elastic resume (utils/elastic.py): when resuming under a different
    # world size, rebuild the mesh at the surviving size (degraded axes
    # allowed) and rescale the batch geometry under elastic_policy instead
    # of failing the mid-epoch geometry guard.
    elastic: bool = False
    elastic_policy: str = "keep_global_batch"  # | "scale_lr"
    evaluate: bool = False  # eval-only mode (main.py --evaluate)
    seed: int = 0
    # telemetry (utils/telemetry.py): on-device health pack in the metrics
    # dict + host span timeline / goodput accounting + anomaly guard
    telemetry: bool = False
    # 0 = health rows ride the log_every fetch only (zero extra host syncs);
    # N > 0 also fetches/checks the health pack every N steps (kind="health"
    # JSONL rows between the train rows)
    health_every: int = 0
    # on a non-finite health scalar: dump a diagnostic bundle then
    # "abort" (raise) | "continue" (log and keep training) | "rollback"
    # (restore the last committed checkpoint and continue past the poisoned
    # batch window — Switch-Transformer-style instability recovery)
    anomaly_action: str = "abort"
    # rollback restores allowed per run before escalating to abort (a model
    # that keeps diverging after N restores has a real problem, not a blip)
    rollback_budget: int = 3
    # watchdog: seconds without step progress before dumping stacks/aborting
    # (utils/watchdog.py; was hardcoded at 1800)
    watchdog_timeout: float = 1800.0
    # fleet observability (utils/fleetobs.py) — straggler warn threshold:
    # a step whose host-local wait exceeds (threshold - 1) x the median step
    # time trips the AnomalyGuard's warn-only trigger and is flagged by the
    # offline merge (benchmarks/trace_merge.py)
    straggler_threshold: float = 2.0
    # flight recorder: step records kept in the ring dumped on anomaly /
    # preemption / host-loss exits (flightrec*.jsonl)
    flightrec_steps: int = 256
    # rank-0 Prometheus endpoint (fleetobs.MetricsServer): None disables,
    # 0 binds an ephemeral port (logged), N binds :N
    metrics_port: int | None = None
    # deterministic fault injection (utils/chaos.py): comma-separated spec,
    # e.g. "sigterm@step=7,ckpt_io_error@save=2" — None disables
    chaos: str | None = None
    chaos_seed: int | None = None  # defaults to `seed` when unset
    # r21 instant restart (core/xcache.py): persist the train step's
    # compiled executable under <checkpoint_dir>/xcache keyed by a
    # topology/knob/aval fingerprint, so a supervisor relaunch at a
    # previously seen topology deserializes instead of compiling. The jax
    # persistent compilation cache is pointed at the same directory as the
    # fallback where executable serialization is unsupported.
    xcache: bool = False
    # profiling
    profile_steps: str | None = None  # "start:stop" step range
    profile_dir: str = "/tmp/pdtx_profile"
    # fault injection (SURVEY.md §5 failure detection): "rank:step" hard-kills
    # that host process before the given global step — for recovery testing.
    fault_inject: str | None = None
    # loop control (bench/smoke)
    steps_per_epoch: int | None = None  # cap steps (synthetic/bench runs)
    # serving (serve/): main.py --serve runs the continuous-batching decode
    # engine over a paged KV cache instead of training. Restores params only
    # (Checkpointer.restore_params) when --resume is set. Bucket lists are
    # comma-separated ints; max_model_len 0 means the model/cache cap.
    serve: bool = False
    serve_page_size: int = 16
    serve_num_pages: int = 128
    serve_max_model_len: int = 0
    serve_decode_buckets: str = "1,2,4,8"
    serve_prompt_buckets: str = "16,32"
    serve_requests: int = 16
    serve_rate: float = 0.0  # open-loop req/s; 0 = all at t=0 (saturation)
    # SIGTERM drain budget: in-flight sequences get this many seconds to
    # finish decoding before the session exits PREEMPTED_EXIT_CODE (the
    # fleet scheduler's preemption contract for serving jobs).
    serve_drain_timeout: float = 5.0
    # r17 serving-throughput stack (serve/prefix_cache.py, serve/router.py):
    # prefix caching, chunked prefill + prefill/decode disaggregation, and
    # multi-replica prefix-affinity routing over one process's devices.
    serve_prefix_cache: bool = False
    serve_prefill_chunk: int = 0      # tokens/window; 0 = whole prompt
    serve_disaggregate: bool = False  # prefill-role + decode-role pair
    serve_replicas: int = 1
    serve_route: str = "affinity"     # affinity | least_loaded
    # Shared-prefix synthetic workload (Zipf-popular prompt templates).
    serve_templates: int = 0
    serve_zipf_a: float = 1.2
    serve_prefix_len: str = "16:32"   # template length range, "min:max"
    # r19 speculative decoding (serve/spec_decode.py): "off" | "ngram"
    # (self-drafting prompt lookup) | "draft" (separate small draft model
    # named by serve_draft_model, params-only restored from an optional
    # "name@ckpt_dir" suffix). Greedy output stays bit-identical to the
    # unsped engine; draft_len bounds the per-step speculation window.
    serve_spec_decode: str = "off"
    serve_draft_len: int = 4
    serve_draft_model: str = ""
    # r20 serving SLO observability (serve/slo.py): per-request span
    # tracing (reqtrace.<replica>.a<A>.json, merged by trace_merge.py)
    # plus a sliding-window TTFT/ITL quantile tracker flushed to
    # slo.jsonl, which the fleet scheduler folds into serve-job
    # placement weights. Targets of 0 ms disable attainment/breach
    # accounting (quantiles still export).
    serve_slo: bool = False
    serve_slo_window: int = 256       # samples per replica/role window
    serve_slo_ttft_ms: float = 0.0    # TTFT target; 0 = no target
    serve_slo_itl_ms: float = 0.0     # per-token ITL target; 0 = no target
    serve_trace_events: int = 4096    # request-span ring capacity/replica

    def mesh_config(self) -> dict[str, int]:
        return dict(data=self.mesh_data, fsdp=self.mesh_fsdp, stage=self.mesh_stage,
                    expert=self.mesh_expert, context=self.mesh_context,
                    model=self.mesh_model)

    def replace(self, **kw) -> "Config":
        return dataclasses.replace(self, **kw)


#: The reference's workload matrix (BASELINE.json ``configs``), one preset each.
PRESETS: dict[str, dict[str, Any]] = {
    # configs[0]: ResNet-18 / CIFAR-10 — single-process, CPU-runnable dev config
    "resnet18_cifar10": dict(
        model="resnet18", dataset="cifar10", num_classes=10, image_size=32,
        epochs=30, global_batch_size=256, lr=0.1, warmup_epochs=2.0,
        weight_decay=5e-4, precision="fp32", strategy="dp",
    ),
    # configs[1]: ResNet-50 / ImageNet-1k — data-parallel (the driver metric)
    "resnet50_imagenet": dict(
        model="resnet50", dataset="imagenet", num_classes=1000, image_size=224,
        epochs=90, global_batch_size=1024, lr=0.4, warmup_epochs=5.0,
        weight_decay=1e-4, precision="bf16", strategy="dp",
    ),
    # configs[2]: ViT-B/16 / ImageNet-1k — DDP -> pjit data-parallel
    "vit_b16_imagenet": dict(
        model="vit_b16", dataset="imagenet", num_classes=1000, image_size=224,
        epochs=90, global_batch_size=1024, lr=3e-3, warmup_epochs=10.0,
        weight_decay=0.1, optimizer="adamw", label_smoothing=0.1,
        precision="bf16", strategy="dp", grad_clip=1.0,
    ),
    # configs[3]: GPT-2 124M LM — FSDP -> GSPMD param-shard
    "gpt2_124m": dict(
        model="gpt2", dataset="lm", seq_len=1024, epochs=1,
        global_batch_size=256, lr=6e-4, warmup_epochs=0.01,
        weight_decay=0.1, optimizer="adamw", precision="bf16",
        strategy="fsdp", mesh_data=1, mesh_fsdp=-1, grad_clip=1.0,
    ),
    # configs[4]: Llama-3 8B — FSDP + gradient checkpointing
    "llama3_8b": dict(
        model="llama3_8b", dataset="lm", seq_len=8192, epochs=1,
        global_batch_size=128, lr=3e-4, warmup_epochs=0.01,
        weight_decay=0.1, optimizer="adamw", precision="bf16",
        strategy="fsdp", mesh_data=1, mesh_fsdp=-1, remat=True, grad_clip=1.0,
    ),
}


def from_preset(name: str, **overrides) -> Config:
    if name not in PRESETS:
        raise ValueError(f"unknown preset {name!r}; have {sorted(PRESETS)}")
    return Config(**{**PRESETS[name], **overrides})
