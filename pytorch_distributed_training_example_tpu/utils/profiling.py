"""Profiling hooks — SURVEY.md §5 "tracing/profiling".

The reference has none beyond optional CUDA-event timing; here the TPU-native
mechanism is ``jax.profiler`` traces (viewable in TensorBoard/Perfetto, with
per-HLO timing from the xplane dump on TPU) plus named step phases.

Used by the trainer's ``--profile-steps a:b`` flag; also usable standalone::

    with profiling.trace("/tmp/trace"):
        step(state, batch)
"""

from __future__ import annotations

import contextlib
import time

import jax


@contextlib.contextmanager
def trace(log_dir: str, *, create_perfetto_link: bool = False):
    jax.profiler.start_trace(log_dir, create_perfetto_link=create_perfetto_link)
    try:
        yield log_dir
    finally:
        jax.profiler.stop_trace()


def annotate(name: str):
    """Named region that shows up on the TPU trace timeline."""
    return jax.profiler.TraceAnnotation(name)


class StepTimer:
    """Wall-clock step timing with device-sync on demand.

    Async dispatch means host timestamps around ``step()`` measure dispatch,
    not execution; call ``sync()`` (blocks on the metrics) at measurement
    boundaries only, the way bench.py does.
    """

    def __init__(self):
        self.times: list[float] = []
        self._t0: float | None = None

    def start(self, sync_on=None):
        if sync_on is not None:
            jax.tree.map(lambda x: x.block_until_ready(), sync_on)
        self._t0 = time.perf_counter()

    def stop(self, sync_on=None) -> float:
        if sync_on is not None:
            jax.tree.map(lambda x: x.block_until_ready(), sync_on)
        dt = time.perf_counter() - self._t0
        self.times.append(dt)
        return dt

    @property
    def mean(self) -> float:
        return sum(self.times) / max(len(self.times), 1)
