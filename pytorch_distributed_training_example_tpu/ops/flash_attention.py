"""Pallas TPU flash attention (blockwise online softmax in VMEM).

The hot attention kernel for long sequences: never materializes the
[Sq, Skv] score matrix in HBM. Grid is (batch, heads, q-blocks, kv-blocks)
with the kv dimension innermost — TPU grids execute sequentially over the
trailing dimension, so the online-softmax state (running max ``m``, denom
``l``, unnormalized accumulator) lives in VMEM scratch across kv steps and
the output block is written once on the last step.

Causal masking skips fully-masked kv blocks (predicated with ``pl.when``)
and applies an elementwise mask only on the diagonal block.

Backward currently recomputes attention with XLA inside a ``custom_vjp``
(correct everywhere, tested vs the oracle); a Pallas dq/dkv kernel pair is
the planned upgrade. Layout: [B, S, H, D] in, transposed to [B, H, S, D]
internally (head-major keeps the MXU's 128-lane dim on head_dim).
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from pytorch_distributed_training_example_tpu.ops import attention as attn_lib

NEG_INF = -1e30
DEFAULT_BLOCK_Q = 512
DEFAULT_BLOCK_KV = 512


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, m_ref, l_ref, acc_ref, *,
                sm_scale: float, causal: bool, block_q: int, block_kv: int):
    qi = pl.program_id(2)
    kvi = pl.program_id(3)
    n_kv = pl.num_programs(3)

    @pl.when(kvi == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    # Causal: kv block strictly above the diagonal contributes nothing.
    run = True
    if causal:
        run = kvi * block_kv <= (qi + 1) * block_q - 1

    @pl.when(run)
    def _compute():
        q = q_ref[0, 0].astype(jnp.float32)           # [bq, D]
        k = k_ref[0, 0].astype(jnp.float32)           # [bkv, D]
        v = v_ref[0, 0].astype(jnp.float32)           # [bkv, D]
        logits = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * sm_scale  # [bq, bkv]
        if causal:
            q_pos = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, logits.shape, 0)
            k_pos = kvi * block_kv + jax.lax.broadcasted_iota(
                jnp.int32, logits.shape, 1)
            logits = jnp.where(q_pos >= k_pos, logits, NEG_INF)

        m_prev = m_ref[:, :1]                         # [bq, 1] (lane-bcast)
        block_max = jnp.max(logits, axis=1, keepdims=True)
        m_new = jnp.maximum(m_prev, block_max)
        p = jnp.exp(logits - m_new)                   # [bq, bkv]
        correction = jnp.exp(m_prev - m_new)          # [bq, 1]
        l_new = l_ref[:, :1] * correction + jnp.sum(p, axis=1, keepdims=True)
        pv = jax.lax.dot_general(p, v, (((1,), (0,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        acc_ref[:] = acc_ref[:] * correction + pv
        m_ref[:] = jnp.broadcast_to(m_new, m_ref.shape)
        l_ref[:] = jnp.broadcast_to(l_new, l_ref.shape)

    @pl.when(kvi == n_kv - 1)
    def _finish():
        denom = jnp.maximum(l_ref[:, :1], 1e-30)
        o_ref[0, 0] = (acc_ref[:] / denom).astype(o_ref.dtype)


def _flash_fwd(q, k, v, *, causal: bool, block_q: int, block_kv: int):
    B, Sq, H, D = q.shape
    Skv = k.shape[1]
    k = attn_lib._repeat_kv(k, H)
    v = attn_lib._repeat_kv(v, H)
    # head-major layout for the kernel
    qt = jnp.transpose(q, (0, 2, 1, 3))
    kt = jnp.transpose(k, (0, 2, 1, 3))
    vt = jnp.transpose(v, (0, 2, 1, 3))
    block_q = min(block_q, Sq)
    block_kv = min(block_kv, Skv)
    assert Sq % block_q == 0 and Skv % block_kv == 0, (Sq, Skv, block_q, block_kv)
    grid = (B, H, Sq // block_q, Skv // block_kv)

    out = pl.pallas_call(
        functools.partial(_fwd_kernel, sm_scale=1.0 / math.sqrt(D),
                          causal=causal, block_q=block_q, block_kv=block_kv),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, 1, block_q, D), lambda b, h, i, j: (b, h, i, 0)),
            pl.BlockSpec((1, 1, block_kv, D), lambda b, h, i, j: (b, h, j, 0)),
            pl.BlockSpec((1, 1, block_kv, D), lambda b, h, i, j: (b, h, j, 0)),
        ],
        out_specs=pl.BlockSpec((1, 1, block_q, D),
                               lambda b, h, i, j: (b, h, i, 0)),
        out_shape=jax.ShapeDtypeStruct((B, H, Sq, D), q.dtype),
        scratch_shapes=[
            pltpu.VMEM((block_q, 128), jnp.float32),   # m
            pltpu.VMEM((block_q, 128), jnp.float32),   # l
            pltpu.VMEM((block_q, D), jnp.float32),     # acc
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "parallel", "arbitrary"),
        ),
    )(qt, kt, vt)
    return jnp.transpose(out, (0, 2, 1, 3))


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def flash_attention(q, k, v, causal: bool = False,
                    block_q: int = DEFAULT_BLOCK_Q,
                    block_kv: int = DEFAULT_BLOCK_KV):
    """Flash attention with the XLA oracle's exact semantics.

    [B, S, H, D] layout; fp32 softmax; GQA via fewer KV heads.
    """
    return _flash_fwd(q, k, v, causal=causal, block_q=block_q,
                      block_kv=block_kv)


def _vjp_fwd(q, k, v, causal, block_q, block_kv):
    out = _flash_fwd(q, k, v, causal=causal, block_q=block_q, block_kv=block_kv)
    return out, (q, k, v)


def _vjp_bwd(causal, block_q, block_kv, res, g):
    # Recompute-based backward (XLA): one extra forward's worth of FLOPs,
    # standard flash-attention practice; Pallas dq/dkv kernels are the
    # planned replacement for long-sequence memory.
    q, k, v = res

    def ref(q, k, v):
        return attn_lib.dot_product_attention(q, k, v, causal=causal)

    _, vjp = jax.vjp(ref, q, k, v)
    return vjp(g)


flash_attention.defvjp(_vjp_fwd, _vjp_bwd)
