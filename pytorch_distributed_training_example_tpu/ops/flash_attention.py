"""Pallas TPU flash attention (blockwise online softmax in VMEM).

The hot attention kernel for long sequences: never materializes the
[Sq, Skv] score matrix in HBM. Grid is (batch, heads, q-blocks, kv-blocks)
with the kv dimension innermost — TPU grids execute sequentially over the
trailing dimension, so the online-softmax state (running max ``m``, denom
``l``, unnormalized accumulator) lives in VMEM scratch across kv steps and
the output block is written once on the last step.

Causal masking skips fully-masked kv blocks (predicated with ``pl.when``)
and applies an elementwise mask only on the diagonal block.

Backward is a Pallas dq/dkv kernel pair under ``custom_vjp`` (see
``_dq_kernel``/``_dkv_kernel`` below): recompute-based, using the
saved forward LSE, with the same blockwise masking. Layout: [B, S, H, D] in,
transposed to [B, H, S, D] internally (head-major keeps the MXU's 128-lane
dim on head_dim).
"""

from __future__ import annotations

import functools
import math
import os

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from pytorch_distributed_training_example_tpu.ops import pallas_compat  # noqa: F401
from pytorch_distributed_training_example_tpu.ops import attention as attn_lib

NEG_INF = -1e30
# Online-kernel defaults (the one-shot kernels self-plan their tiling):
# 1024x1024 measured best e2e of the {256,512,1024}^2 grid — GPT-2 S=1024
# forced-online MFU 0.5475 vs 0.4888 at the old 512x512 (LM_SWEEP.json).
DEFAULT_BLOCK_Q = 1024
DEFAULT_BLOCK_KV = 1024
LSE_LANES = 8  # lse stored [B,H,S,8]: minor dims satisfy Mosaic tiling

# Measured per-shape block overrides for the ONLINE kernels, keyed
# (bwd, S, D) -> (block_q, block_kv). Consulted only when the caller left
# block_q/block_kv at the module defaults (an explicit caller choice always
# wins), so it is a tuning table, not an API change. Entries are added ONLY
# from on-chip sweeps (``benchmarks/flash_micro.py --block-sweep`` emits the
# grid); the r3 LM sweep that picked the 1024x1024 default ran at D=64 —
# D=128 long-S shapes get their own rows here as they are measured.
ONLINE_BLOCK_TABLE: dict[tuple[bool, int, int], tuple[int, int]] = {
    # D=128, S=4096 fwd: default 1024x1024 measured 1.371 ms = 0.509 of MXU
    # peak (BENCH_FLASH_MICRO.json r4) — the default IS the tuned choice.
    (False, 4096, 128): (1024, 1024),
}


def _online_blocks(bwd: bool, s: int, d: int, block_q: int, block_kv: int):
    """Resolve the online kernels' block sizes through ONLINE_BLOCK_TABLE."""
    if (block_q, block_kv) != (DEFAULT_BLOCK_Q, DEFAULT_BLOCK_KV):
        return block_q, block_kv
    return ONLINE_BLOCK_TABLE.get((bwd, s, d), (block_q, block_kv))


def _fit_block(s: int, requested: int) -> int:
    """Largest divisor of ``s`` that is <= ``requested``.

    DEFAULT_BLOCK_Q/KV are preferences, not contracts: ``_flash_eligible``
    admits any S % 512 == 0, so S=2560 under a 1024 default must tile at
    640 — flooring the grid instead (Sq // block) would silently drop the
    trailing rows (dq unwritten, dk/dv missing contributions). Every
    eligible S (% 512 == 0) lands on a block >= 512 (640, 704, 768...).
    No alignment guarantee beyond divisibility is claimed — block_q/kv sit
    on the second-minor (sublane) dim, where Mosaic handles any size and
    512-divisible S gives at least 8-alignment in the worst case; odd
    explicit S still gets an exact tiling (worst case 1).
    """
    b = min(requested, s)
    while s % b:
        b -= 1
    return b


def _mxu(x):
    """MXU operand dtype: bf16/fp32 as stored; fp16 upcast to fp32.

    fp16's 5-bit exponent overflows on scale-multiplied gradients (the
    GradScaler path multiplies do by up to 2^15+), and softmax probabilities
    below 2^-24 flush to zero — so the fp16 AMP policy keeps kernel math in
    fp32 while bf16 training uses native-dtype operands for MXU rate.
    """
    return x.astype(jnp.float32) if x.dtype == jnp.float16 else x


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, m_ref, l_ref, acc_ref, *,
                sm_scale: float, causal: bool, block_q: int, block_kv: int):
    qi = pl.program_id(2)
    kvi = pl.program_id(3)
    n_kv = pl.num_programs(3)

    @pl.when(kvi == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    # Causal: kv block strictly above the diagonal contributes nothing.
    run = True
    if causal:
        run = kvi * block_kv <= (qi + 1) * block_q - 1

    @pl.when(run)
    def _compute():
        # MXU-native operands: dots take q/k/v in their stored dtype (bf16 in
        # training) with fp32 accumulation via preferred_element_type — the
        # FlashAttention-2 scheme. Upcasting operands to fp32 here measured
        # ~20 TF/s on v5e (fp32 MXU rate); bf16 operands run ~2-3x faster.
        # All softmax state (m, l, acc) stays fp32.
        q = _mxu(q_ref[0, 0])                         # [bq, D]
        k = _mxu(k_ref[0, 0])                         # [bkv, D]
        v = _mxu(v_ref[0, 0])                         # [bkv, D]
        logits = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * sm_scale  # [bq, bkv]
        if causal:
            q_pos = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, logits.shape, 0)
            k_pos = kvi * block_kv + jax.lax.broadcasted_iota(
                jnp.int32, logits.shape, 1)
            logits = jnp.where(q_pos >= k_pos, logits, NEG_INF)

        m_prev = m_ref[:, :1]                         # [bq, 1] (lane-bcast)
        block_max = jnp.max(logits, axis=1, keepdims=True)
        m_new = jnp.maximum(m_prev, block_max)
        p = jnp.exp(logits - m_new)                   # [bq, bkv]
        correction = jnp.exp(m_prev - m_new)          # [bq, 1]
        l_new = l_ref[:, :1] * correction + jnp.sum(p, axis=1, keepdims=True)
        pv = jax.lax.dot_general(p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        acc_ref[:] = acc_ref[:] * correction + pv
        m_ref[:] = jnp.broadcast_to(m_new, m_ref.shape)
        l_ref[:] = jnp.broadcast_to(l_new, l_ref.shape)

    @pl.when(kvi == n_kv - 1)
    def _finish():
        denom = jnp.maximum(l_ref[:, :1], 1e-30)
        o_ref[0, 0] = (acc_ref[:] / denom).astype(o_ref.dtype)
        # lse rows broadcast over LSE_LANES (Mosaic tiling needs >= 2D tiles).
        lse_ref[0, 0] = (m_ref[:, :LSE_LANES]
                         + jnp.log(jnp.maximum(l_ref[:, :LSE_LANES], 1e-30)))


def _flash_fwd(q, k, v, *, causal: bool, block_q: int, block_kv: int):
    """Returns (out [B,S,H,D], lse [B,H,S]) with K/V already GQA-expanded."""
    B, Sq, H, D = q.shape
    Skv = k.shape[1]
    # head-major layout for the kernel
    qt = jnp.transpose(q, (0, 2, 1, 3))
    kt = jnp.transpose(k, (0, 2, 1, 3))
    vt = jnp.transpose(v, (0, 2, 1, 3))
    block_q = _fit_block(Sq, block_q)
    block_kv = _fit_block(Skv, block_kv)
    assert Sq % block_q == 0 and Skv % block_kv == 0, (Sq, Skv, block_q, block_kv)
    grid = (B, H, Sq // block_q, Skv // block_kv)

    out, lse = pl.pallas_call(
        functools.partial(_fwd_kernel, sm_scale=1.0 / math.sqrt(D),
                          causal=causal, block_q=block_q, block_kv=block_kv),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, 1, block_q, D), lambda b, h, i, j: (b, h, i, 0)),
            pl.BlockSpec((1, 1, block_kv, D), lambda b, h, i, j: (b, h, j, 0)),
            pl.BlockSpec((1, 1, block_kv, D), lambda b, h, i, j: (b, h, j, 0)),
        ],
        out_specs=(
            pl.BlockSpec((1, 1, block_q, D), lambda b, h, i, j: (b, h, i, 0)),
            pl.BlockSpec((1, 1, block_q, LSE_LANES),
                         lambda b, h, i, j: (b, h, i, 0)),
        ),
        out_shape=(
            jax.ShapeDtypeStruct((B, H, Sq, D), q.dtype),
            jax.ShapeDtypeStruct((B, H, Sq, LSE_LANES), jnp.float32),
        ),
        scratch_shapes=[
            pltpu.VMEM((block_q, 128), jnp.float32),   # m
            pltpu.VMEM((block_q, 128), jnp.float32),   # l
            pltpu.VMEM((block_q, D), jnp.float32),     # acc
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "parallel", "arbitrary"),
        ),
    )(qt, kt, vt)
    return jnp.transpose(out, (0, 2, 1, 3)), lse


# ---------------------------------------------------------------------------
# Backward kernels (FlashAttention-2 style): dq pass over kv blocks; dk/dv
# pass over q blocks. Residuals: q,k,v,o + the forward logsumexp rows.
# ---------------------------------------------------------------------------


def _dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref,
               acc_ref, *, sm_scale, causal, block_q, block_kv):
    qi = pl.program_id(2)
    kvi = pl.program_id(3)
    n_kv = pl.num_programs(3)

    @pl.when(kvi == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    run = True
    if causal:
        run = kvi * block_kv <= (qi + 1) * block_q - 1

    @pl.when(run)
    def _compute():
        # Native-dtype matmul operands, fp32 accumulation (see _fwd_kernel).
        q = _mxu(q_ref[0, 0])
        k = _mxu(k_ref[0, 0])
        v = _mxu(v_ref[0, 0])
        do = _mxu(do_ref[0, 0])
        lse = lse_ref[0, 0, :, :1]               # [bq, 1]
        delta = delta_ref[0, 0, :, :1]           # [bq, 1]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * sm_scale
        if causal:
            q_pos = qi * block_q + jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
            k_pos = kvi * block_kv + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
            s = jnp.where(q_pos >= k_pos, s, NEG_INF)
        p = jnp.exp(s - lse)                     # [bq, bkv]
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = (p * (dp - delta) * sm_scale).astype(k.dtype)
        acc_ref[:] += jax.lax.dot_general(ds, k, (((1,), (0,)), ((), ())),
                                          preferred_element_type=jnp.float32)

    @pl.when(kvi == n_kv - 1)
    def _finish():
        dq_ref[0, 0] = acc_ref[:].astype(dq_ref.dtype)


def _dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                dk_ref, dv_ref, dk_acc, dv_acc, *,
                sm_scale, causal, block_q, block_kv):
    kvi = pl.program_id(2)
    qi = pl.program_id(3)
    n_q = pl.num_programs(3)

    @pl.when(qi == 0)
    def _init():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    run = True
    if causal:
        run = (qi + 1) * block_q - 1 >= kvi * block_kv

    @pl.when(run)
    def _compute():
        # Native-dtype matmul operands, fp32 accumulation (see _fwd_kernel).
        q = _mxu(q_ref[0, 0])
        k = _mxu(k_ref[0, 0])
        v = _mxu(v_ref[0, 0])
        do = _mxu(do_ref[0, 0])
        lse = lse_ref[0, 0, :, :1]               # [bq, 1]
        delta = delta_ref[0, 0, :, :1]           # [bq, 1]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * sm_scale
        if causal:
            q_pos = qi * block_q + jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
            k_pos = kvi * block_kv + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
            s = jnp.where(q_pos >= k_pos, s, NEG_INF)
        p = jnp.exp(s - lse)                     # [bq, bkv]
        # dV += P^T dO
        dv_acc[:] += jax.lax.dot_general(p.astype(do.dtype), do,
                                         (((0,), (0,)), ((), ())),
                                         preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = (p * (dp - delta) * sm_scale).astype(q.dtype)
        # dK += dS^T Q
        dk_acc[:] += jax.lax.dot_general(ds, q, (((0,), (0,)), ((), ())),
                                         preferred_element_type=jnp.float32)

    @pl.when(qi == n_q - 1)
    def _finish():
        dk_ref[0, 0] = dk_acc[:].astype(dk_ref.dtype)
        dv_ref[0, 0] = dv_acc[:].astype(dv_ref.dtype)


def _flash_bwd(q, k, v, o, lse, g, *, causal, block_q, block_kv):
    """q,k,v,o,g: [B,S,H,D] (kv already GQA-expanded); lse: [B,H,Sq]."""
    B, Sq, H, D = q.shape
    Skv = k.shape[1]
    block_q = _fit_block(Sq, block_q)
    block_kv = _fit_block(Skv, block_kv)
    assert Sq % block_q == 0 and Skv % block_kv == 0, (Sq, Skv, block_q, block_kv)
    sm_scale = 1.0 / math.sqrt(D)
    # delta_i = rowsum(dO * O): cheap elementwise+reduce, fused by XLA;
    # broadcast over LSE_LANES to match the kernel's tile layout.
    delta = jnp.einsum("bshd,bshd->bhs", g.astype(jnp.float32),
                       o.astype(jnp.float32))
    delta = jnp.broadcast_to(delta[..., None], (*delta.shape, LSE_LANES))
    qt = jnp.transpose(q, (0, 2, 1, 3))
    kt = jnp.transpose(k, (0, 2, 1, 3))
    vt = jnp.transpose(v, (0, 2, 1, 3))
    dot = jnp.transpose(g, (0, 2, 1, 3))

    qspec = pl.BlockSpec((1, 1, block_q, D), lambda b, h, i, j: (b, h, i, 0))
    kspec = pl.BlockSpec((1, 1, block_kv, D), lambda b, h, i, j: (b, h, j, 0))
    lspec = pl.BlockSpec((1, 1, block_q, LSE_LANES),
                         lambda b, h, i, j: (b, h, i, 0))

    dq = pl.pallas_call(
        functools.partial(_dq_kernel, sm_scale=sm_scale, causal=causal,
                          block_q=block_q, block_kv=block_kv),
        grid=(B, H, Sq // block_q, Skv // block_kv),
        in_specs=[qspec, kspec, kspec, qspec, lspec, lspec],
        out_specs=qspec,
        out_shape=jax.ShapeDtypeStruct((B, H, Sq, D), q.dtype),
        scratch_shapes=[pltpu.VMEM((block_q, D), jnp.float32)],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "parallel", "arbitrary"),
        ),
    )(qt, kt, vt, dot, lse, delta)

    # dk/dv pass: kv blocks outer (parallel), q blocks inner (accumulated).
    qspec2 = pl.BlockSpec((1, 1, block_q, D), lambda b, h, j, i: (b, h, i, 0))
    kspec2 = pl.BlockSpec((1, 1, block_kv, D), lambda b, h, j, i: (b, h, j, 0))
    lspec2 = pl.BlockSpec((1, 1, block_q, LSE_LANES),
                          lambda b, h, j, i: (b, h, i, 0))
    dk, dv = pl.pallas_call(
        functools.partial(_dkv_kernel, sm_scale=sm_scale, causal=causal,
                          block_q=block_q, block_kv=block_kv),
        grid=(B, H, Skv // block_kv, Sq // block_q),
        in_specs=[qspec2, kspec2, kspec2, qspec2, lspec2, lspec2],
        out_specs=(kspec2, kspec2),
        out_shape=(jax.ShapeDtypeStruct((B, H, Skv, D), k.dtype),
                   jax.ShapeDtypeStruct((B, H, Skv, D), v.dtype)),
        scratch_shapes=[pltpu.VMEM((block_kv, D), jnp.float32),
                        pltpu.VMEM((block_kv, D), jnp.float32)],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "parallel", "arbitrary"),
        ),
    )(qt, kt, vt, dot, lse, delta)

    tr = lambda x: jnp.transpose(x, (0, 2, 1, 3))
    return tr(dq), tr(dk), tr(dv)


# ---------------------------------------------------------------------------
# One-shot kernels: short/medium sequences (the LM bench shapes).
#
# The online-softmax kernels above are grid-step bound at small head_dim:
# measured on v5e at B=16,H=12,S=1024,D=64, the (B,H,q,kv) grid runs ~8 us
# per step regardless of causality or FLOPs (6.2 ms fwd ~ 2% of MXU peak;
# XLA's attention and jax.experimental's reference Pallas kernel land in the
# same 6-9 ms band — see BENCH_FLASH_MICRO.json). When the whole KV fits in
# VMEM there is no reason to stream it: these kernels give each program a
# full [block_q, Skv] score tile and do plain fp32 softmax in registers —
# no scratch state, no revisiting, no per-kv-step DMA boundaries — and
# optionally batch G heads per program to amortize DMA latency. Backward
# computes dq/dk/dv in ONE pass (dk/dv accumulated across q blocks in VMEM).
#
# Tried and rejected (measured, same slope-timing as BENCH_FLASH_MICRO):
# splitting causal work into a low-kv half + full-kv half (two kernel
# variants, q_base mask offset) to skip the ~37% masked tile area — fwd
# improved 6% but fwd+bwd REGRESSED 6% (2.76 vs 2.61 ms at GPT-2 shapes):
# the dk/dv pad+add stitch, duplicate k/v reads, and extra launches cost
# more than the skipped FLOPs. Dense causal tiles are the keeper here.
# ---------------------------------------------------------------------------

# Live-bytes budgets for one-shot plans. r3 ran 10 MB ("16 MB VMEM minus
# operand buffers"); r4's plan sweep (see PROFILE_GPT2.md r4 addendum)
# measured that the 16.8 MB-modeled (G=2, bq=512) backward compiles and is
# the fastest fwd+bwd combo at GPT-2 shapes — the cost model overstates
# live bytes (softmax tiles reuse the score tile's registers). r4 raised
# the single budget to 17 MB, but that sits ABOVE the ~16 MB physical
# VMEM: any not-measured shape whose true live bytes exceed VMEM would
# hard-fail the Mosaic compile instead of falling back to online
# (ADVICE r4). r5 split the policy:
#   - general admission (impl="auto"): 13 MB modeled — margin under
#     physical VMEM (the model is known to over-count), chosen as the
#     smallest cap that preserves every plan choice the r4 benches
#     measured on-chip (Llama-400M bwd (G=1, bq=256) at S=2048/D=128 =
#     11.3 MB, BENCH_LLAMA.json r4_update; S=4096/D=128 non-causal fwd
#     (G=1, bq=256) = 12.5 MB, BENCH_FLASH_MICRO.json);
#   - plans above 13 MB are admitted under auto only via the explicit
#     measured allowlist below;
#   - forced impl="oneshot" keeps the 17 MB cap (an opt-in: the caller
#     asked for this kernel and gets the compile error if it won't fit).
ONESHOT_BUDGET = 13 * 1024 * 1024
ONESHOT_FORCED_BUDGET = 17 * 1024 * 1024
# (bwd, g, bq, Skv, D) plans above ONESHOT_BUDGET measured to compile and
# win on v5e (PROFILE_GPT2.md r4 plan sweep: fastest GPT-2 backward,
# 16.8 MB modeled).
ONESHOT_MEASURED_PLANS = {
    (True, 2, 512, 1024, 64),
}


def _oneshot_plan(H, Sq, Skv, D, *, bwd=False, forced=False):
    """Pick (heads_per_program G, q_rows_per_program bq), or None.

    Cost model (bytes live per program): fwd keeps s/p f32 + p bf16 tiles
    (~10 B per (g, q, kv) cell) + k/v blocks; bwd adds dp/ds tiles and the
    f32 dk/dv accumulators. None -> KV too long for a dense score tile;
    caller falls back to the online-softmax kernels.
    """
    cell = 14 if bwd else 10
    kvbytes = (16 if bwd else 4) * Skv * D
    # Under "auto", plans whose q tile is thinner than 256 rows are
    # rejected — they lose to the online kernels: measured at S=4096/D=128
    # the degenerate bq=16/128 one-shot plans run 2x slower than
    # online@1024-blocks (BENCH_FLASH_MICRO.json), while every bq>=256 plan
    # measured wins. Tiny sequences (Sq<256) are exempt — there the whole
    # problem fits one program. impl="oneshot" (forced) skips the
    # threshold so the kernel stays measurable at any feasible shape.
    min_bq = 1 if forced else min(256, Sq)
    budget = ONESHOT_FORCED_BUDGET if forced else ONESHOT_BUDGET
    best = None
    for g in range(min(H, 8), 0, -1):
        if H % g:
            continue
        for bq in (1024, 512, 256, 128, 64, 32, 16):
            if bq > Sq or Sq % bq or bq < min_bq:
                continue
            if (cell * g * bq * Skv + g * kvbytes <= budget
                    or (bwd, g, bq, Skv, D) in ONESHOT_MEASURED_PLANS):
                # Maximize work per program; on ties prefer MORE HEADS over
                # fatter q tiles — measured at B16·H12·S1024·D64 (r4 plan
                # sweep): (2,512) runs fwd+bwd 1.87 ms vs 2.49 ms for
                # (1,1024) at the identical program count, the extra heads
                # amortizing per-program DMA better than extra q rows.
                key = (g * bq, g)
                if best is None or key > best[0]:
                    best = (key, (g, bq))
                break  # smaller bq only shrinks work per program
    return best[1] if best else None


def _causal_mask(s, qi, block_q):
    q_pos = qi * block_q + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
    k_pos = jax.lax.broadcasted_iota(jnp.int32, s.shape, 2)
    return jnp.where(q_pos >= k_pos, s, NEG_INF)


def _kv_len_mask(s, kv_len):
    """Mask keys at positions >= kv_len (padded keys; see ``kv_len`` docs)."""
    k_pos = jax.lax.broadcasted_iota(jnp.int32, s.shape, 2)
    return jnp.where(k_pos < kv_len, s, NEG_INF)


def _causal_mask_chunk(s, qi, block_q, k_base):
    """Causal mask for a kv chunk whose global key offset is ``k_base``."""
    q_pos = qi * block_q + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
    k_pos = k_base + jax.lax.broadcasted_iota(jnp.int32, s.shape, 2)
    return jnp.where(q_pos >= k_pos, s, NEG_INF)


# Per-direction switches for the chunked causal-skip path, set from e2e
# GPT-2 A/B (3 reps each, PROFILE_GPT2.md r4 addendum): chunked BACKWARD
# wins 117.2 -> 114.6 ms/step (exact lse-based chunks, ~25-37% of dot/exp
# work skipped); chunked FORWARD loses ~5 ms (the online rescale chain +
# scratch round-trips cost more than the skipped work at these shapes), so
# the forward keeps the single dense-score formulation.
CHUNK_FWD = False
CHUNK_BWD = True


def _oneshot_num_chunks(causal, kv_len, Skv, bq, *, enabled=True) -> int:
    """kv chunks per program for the causal-skip path (1 = dense).

    Causal one-shot programs waste ~(nq-1)/(2nq) of their dot/exp work on
    fully-masked keys. r3 tried splitting into two kernel VARIANTS and the
    dk/dv stitch + duplicate K/V reads lost more than the skipped FLOPs
    (see "Tried and rejected" above). This splits WITHIN the program
    instead: a python-unrolled chunk loop whose invisible chunks are
    skipped via pl.when on the q-block index — no extra launches, no
    stitch, K/V DMA unchanged. Chunks of 512 keys keep the per-chunk dots
    MXU-sized; shapes that don't tile fall back to dense.
    """
    if not enabled or not causal or kv_len is not None:
        return 1
    for ck in (512, 256):
        if Skv % ck == 0 and Skv // ck > 1:
            return Skv // ck
    return 1


def _oneshot_fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, *,
                        sm_scale, causal, block_q, kv_len):
    qi = pl.program_id(2)
    q = _mxu(q_ref[0])                            # [G, bq, D]
    k = _mxu(k_ref[0])                            # [G, Skv, D]
    v = _mxu(v_ref[0])
    s = jax.lax.dot_general(q, k, (((2,), (2,)), ((0,), (0,))),
                            preferred_element_type=jnp.float32) * sm_scale
    if causal:
        s = _causal_mask(s, qi, block_q)
    if kv_len is not None:
        s = _kv_len_mask(s, kv_len)
    m = jnp.max(s, axis=2, keepdims=True)         # [G, bq, 1]
    p = jnp.exp(s - m)
    l = jnp.sum(p, axis=2, keepdims=True)
    o = jax.lax.dot_general(p.astype(v.dtype), v, (((2,), (1,)), ((0,), (0,))),
                            preferred_element_type=jnp.float32)
    o_ref[0] = (o / jnp.maximum(l, 1e-30)).astype(o_ref.dtype)
    lse = m + jnp.log(jnp.maximum(l, 1e-30))
    lse_ref[0] = jnp.broadcast_to(lse, (*lse.shape[:2], LSE_LANES))


def _oneshot_fwd_kernel_chunked(q_ref, k_ref, v_ref, o_ref, lse_ref,
                                m_s, l_s, acc_s, *,
                                sm_scale, block_q, num_chunks):
    """Causal one-shot forward with in-program kv-chunk skipping: online
    softmax over unrolled chunks (state in VMEM scratch so it crosses
    pl.when region boundaries); chunks entirely above the diagonal are
    never computed."""
    qi = pl.program_id(2)
    G, Skv, D = k_ref.shape[1], k_ref.shape[2], k_ref.shape[3]
    ck = Skv // num_chunks
    q = _mxu(q_ref[0])                            # [G, bq, D]

    m_s[:] = jnp.full_like(m_s, NEG_INF)
    l_s[:] = jnp.zeros_like(l_s)
    acc_s[:] = jnp.zeros_like(acc_s)

    for c in range(num_chunks):
        @pl.when(c * ck < (qi + 1) * block_q)
        def _chunk(c=c):
            k_c = _mxu(k_ref[0, :, c * ck:(c + 1) * ck, :])
            v_c = _mxu(v_ref[0, :, c * ck:(c + 1) * ck, :])
            s = jax.lax.dot_general(q, k_c, (((2,), (2,)), ((0,), (0,))),
                                    preferred_element_type=jnp.float32)
            s = _causal_mask_chunk(s * sm_scale, qi, block_q, c * ck)
            m_prev = m_s[:, :, :1]                # [G, bq, 1]
            m_new = jnp.maximum(m_prev, jnp.max(s, axis=2, keepdims=True))
            p = jnp.exp(s - m_new)
            corr = jnp.exp(m_prev - m_new)
            l_s[:, :, :1] = l_s[:, :, :1] * corr + jnp.sum(p, axis=2,
                                                           keepdims=True)
            pv = jax.lax.dot_general(p.astype(v_c.dtype), v_c,
                                     (((2,), (1,)), ((0,), (0,))),
                                     preferred_element_type=jnp.float32)
            acc_s[:] = acc_s[:] * corr + pv
            m_s[:] = jnp.broadcast_to(m_new, m_s.shape)

    l = jnp.maximum(l_s[:, :, :1], 1e-30)
    o_ref[0] = (acc_s[:] / l).astype(o_ref.dtype)
    # Only lane 0 of l_s carries the denominator — broadcast the lane-0
    # lse over LSE_LANES rather than reading uninitialized lanes.
    lse = m_s[:, :, :1] + jnp.log(l)
    lse_ref[0] = jnp.broadcast_to(lse, (*lse.shape[:2], LSE_LANES))


def _oneshot_fwd(q, k, v, *, causal, plan, kv_len=None):
    B, Sq, H, D = q.shape
    Skv = k.shape[1]
    G, bq = plan
    qt = jnp.transpose(q, (0, 2, 1, 3))
    kt = jnp.transpose(k, (0, 2, 1, 3))
    vt = jnp.transpose(v, (0, 2, 1, 3))
    grid = (B, H // G, Sq // bq)
    nc = _oneshot_num_chunks(causal, kv_len, Skv, bq, enabled=CHUNK_FWD)
    if nc > 1:
        kernel = functools.partial(
            _oneshot_fwd_kernel_chunked, sm_scale=1.0 / math.sqrt(D),
            block_q=bq, num_chunks=nc)
        scratch = [pltpu.VMEM((G, bq, 128), jnp.float32),   # m
                   pltpu.VMEM((G, bq, 128), jnp.float32),   # l
                   pltpu.VMEM((G, bq, D), jnp.float32)]     # acc
    else:
        kernel = functools.partial(
            _oneshot_fwd_kernel, sm_scale=1.0 / math.sqrt(D),
            causal=causal, block_q=bq, kv_len=kv_len)
        scratch = []
    out, lse = pl.pallas_call(
        kernel,
        scratch_shapes=scratch,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, G, bq, D), lambda b, h, i: (b, h, i, 0)),
            pl.BlockSpec((1, G, Skv, D), lambda b, h, i: (b, h, 0, 0)),
            pl.BlockSpec((1, G, Skv, D), lambda b, h, i: (b, h, 0, 0)),
        ],
        out_specs=(
            pl.BlockSpec((1, G, bq, D), lambda b, h, i: (b, h, i, 0)),
            pl.BlockSpec((1, G, bq, LSE_LANES), lambda b, h, i: (b, h, i, 0)),
        ),
        out_shape=(
            jax.ShapeDtypeStruct((B, H, Sq, D), q.dtype),
            jax.ShapeDtypeStruct((B, H, Sq, LSE_LANES), jnp.float32),
        ),
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
    )(qt, kt, vt)
    return jnp.transpose(out, (0, 2, 1, 3)), lse


def _oneshot_bwd_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                        dq_ref, dk_ref, dv_ref, dk_acc, dv_acc, *,
                        sm_scale, causal, block_q, kv_len):
    qi = pl.program_id(2)
    n_q = pl.num_programs(2)

    @pl.when(qi == 0)
    def _init():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    q = _mxu(q_ref[0])                            # [G, bq, D]
    k = _mxu(k_ref[0])                            # [G, Skv, D]
    v = _mxu(v_ref[0])
    do = _mxu(do_ref[0])
    lse = lse_ref[0][..., :1]                     # [G, bq, 1]
    delta = delta_ref[0][..., :1]
    s = jax.lax.dot_general(q, k, (((2,), (2,)), ((0,), (0,))),
                            preferred_element_type=jnp.float32) * sm_scale
    if causal:
        s = _causal_mask(s, qi, block_q)
    if kv_len is not None:
        s = _kv_len_mask(s, kv_len)
    p = jnp.exp(s - lse)                          # [G, bq, Skv]
    dp = jax.lax.dot_general(do, v, (((2,), (2,)), ((0,), (0,))),
                             preferred_element_type=jnp.float32)
    ds = (p * (dp - delta) * sm_scale).astype(k.dtype)
    dq = jax.lax.dot_general(ds, k, (((2,), (1,)), ((0,), (0,))),
                             preferred_element_type=jnp.float32)
    dq_ref[0] = dq.astype(dq_ref.dtype)
    dv_acc[:] += jax.lax.dot_general(p.astype(do.dtype), do,
                                     (((1,), (1,)), ((0,), (0,))),
                                     preferred_element_type=jnp.float32)
    dk_acc[:] += jax.lax.dot_general(ds, q, (((1,), (1,)), ((0,), (0,))),
                                     preferred_element_type=jnp.float32)

    @pl.when(qi == n_q - 1)
    def _flush():
        dk_ref[0] = dk_acc[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_acc[:].astype(dv_ref.dtype)


def _oneshot_bwd_kernel_chunked(q_ref, k_ref, v_ref, do_ref, lse_ref,
                                delta_ref, dq_ref, dk_ref, dv_ref,
                                dk_acc, dv_acc, dq_acc, *,
                                sm_scale, block_q, num_chunks):
    """Causal one-shot backward with in-program kv-chunk skipping. Exact
    (probabilities recomputed from the saved forward lse, so no online
    state): invisible chunks contribute nothing to dq and nothing from
    these queries to dk/dv."""
    qi = pl.program_id(2)
    n_q = pl.num_programs(2)
    G, Skv, D = k_ref.shape[1], k_ref.shape[2], k_ref.shape[3]
    ck = Skv // num_chunks

    @pl.when(qi == 0)
    def _init():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    dq_acc[:] = jnp.zeros_like(dq_acc)
    q = _mxu(q_ref[0])                            # [G, bq, D]
    do = _mxu(do_ref[0])
    lse = lse_ref[0][..., :1]                     # [G, bq, 1]
    delta = delta_ref[0][..., :1]

    for c in range(num_chunks):
        @pl.when(c * ck < (qi + 1) * block_q)
        def _chunk(c=c):
            k_c = _mxu(k_ref[0, :, c * ck:(c + 1) * ck, :])
            v_c = _mxu(v_ref[0, :, c * ck:(c + 1) * ck, :])
            s = jax.lax.dot_general(q, k_c, (((2,), (2,)), ((0,), (0,))),
                                    preferred_element_type=jnp.float32)
            s = _causal_mask_chunk(s * sm_scale, qi, block_q, c * ck)
            p = jnp.exp(s - lse)                  # [G, bq, ck]
            dv_acc[:, c * ck:(c + 1) * ck, :] += jax.lax.dot_general(
                p.astype(do.dtype), do, (((1,), (1,)), ((0,), (0,))),
                preferred_element_type=jnp.float32)
            dp = jax.lax.dot_general(do, v_c, (((2,), (2,)), ((0,), (0,))),
                                     preferred_element_type=jnp.float32)
            ds = (p * (dp - delta) * sm_scale).astype(k_c.dtype)
            dq_acc[:] += jax.lax.dot_general(
                ds, k_c, (((2,), (1,)), ((0,), (0,))),
                preferred_element_type=jnp.float32)
            dk_acc[:, c * ck:(c + 1) * ck, :] += jax.lax.dot_general(
                ds, q, (((1,), (1,)), ((0,), (0,))),
                preferred_element_type=jnp.float32)

    dq_ref[0] = dq_acc[:].astype(dq_ref.dtype)

    @pl.when(qi == n_q - 1)
    def _flush():
        dk_ref[0] = dk_acc[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_acc[:].astype(dv_ref.dtype)


def _oneshot_bwd(q, k, v, o, lse, g, *, causal, plan, kv_len=None):
    B, Sq, H, D = q.shape
    Skv = k.shape[1]
    G, bq = plan
    delta = jnp.einsum("bshd,bshd->bhs", g.astype(jnp.float32),
                       o.astype(jnp.float32))
    delta = jnp.broadcast_to(delta[..., None], (*delta.shape, LSE_LANES))
    qt = jnp.transpose(q, (0, 2, 1, 3))
    kt = jnp.transpose(k, (0, 2, 1, 3))
    vt = jnp.transpose(v, (0, 2, 1, 3))
    dot = jnp.transpose(g, (0, 2, 1, 3))
    qspec = pl.BlockSpec((1, G, bq, D), lambda b, h, i: (b, h, i, 0))
    kspec = pl.BlockSpec((1, G, Skv, D), lambda b, h, i: (b, h, 0, 0))
    lspec = pl.BlockSpec((1, G, bq, LSE_LANES), lambda b, h, i: (b, h, i, 0))
    nc = _oneshot_num_chunks(causal, kv_len, Skv, bq, enabled=CHUNK_BWD)
    if nc > 1:
        kernel = functools.partial(
            _oneshot_bwd_kernel_chunked, sm_scale=1.0 / math.sqrt(D),
            block_q=bq, num_chunks=nc)
        scratch = [pltpu.VMEM((G, Skv, D), jnp.float32),   # dk
                   pltpu.VMEM((G, Skv, D), jnp.float32),   # dv
                   pltpu.VMEM((G, bq, D), jnp.float32)]    # dq
    else:
        kernel = functools.partial(
            _oneshot_bwd_kernel, sm_scale=1.0 / math.sqrt(D),
            causal=causal, block_q=bq, kv_len=kv_len)
        scratch = [pltpu.VMEM((G, Skv, D), jnp.float32),
                   pltpu.VMEM((G, Skv, D), jnp.float32)]
    dq, dk, dv = pl.pallas_call(
        kernel,
        grid=(B, H // G, Sq // bq),
        in_specs=[qspec, kspec, kspec, qspec, lspec, lspec],
        out_specs=(qspec, kspec, kspec),
        out_shape=(jax.ShapeDtypeStruct((B, H, Sq, D), q.dtype),
                   jax.ShapeDtypeStruct((B, H, Skv, D), k.dtype),
                   jax.ShapeDtypeStruct((B, H, Skv, D), v.dtype)),
        scratch_shapes=scratch,
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
    )(qt, kt, vt, dot, lse, delta)
    tr = lambda x: jnp.transpose(x, (0, 2, 1, 3))
    return tr(dq), tr(dk), tr(dv)


# ---------------------------------------------------------------------------
# Streaming one-shot backward: the D=128 long-context path (ISSUE r6).
#
# At S >= 4096 with D=128 the dense one-shot backward no longer fits VMEM
# (``_oneshot_plan(..., bwd=True)`` returns None) and dispatch fell back to
# the two-kernel online backward. That path recomputes the score matrix
# TWICE (dq pass + dkv pass): 7 S^2-scale matmuls and 2 full exp sweeps per
# backward. This kernel does the whole backward in ONE pass — 5 matmuls,
# 1 exp — by inverting the residency: each program pins one (batch,
# head-group)'s full-Sq q/do/lse/delta plus an fp32 dq accumulator in VMEM
# and STREAMS the kv axis on the innermost grid dimension. The kv dimension
# is "arbitrary", so the Pallas pipeline double-buffers the k/v chunk
# fetches against the compute of the previous chunk — the HBM->VMEM KV DMA
# overlap the online kernels get per kv block, kept, while the score tile
# is computed once. dk/dv for a chunk complete within its grid step (every
# q subtile contributes in the unrolled loop); dq accumulates across chunks
# and flushes on the last one. Causal chunk skipping is per q-subtile via
# pl.when, same scheme as the chunked one-shot kernels.
#
# Auto-dispatch is gated to D=128 (this round's target; the D=64 dispatch
# map is measured and unchanged) and can be widened or killed via
# PDTX_STREAM_BWD ("all" = any head dim, "0" = off) until the on-chip A/B
# lands.
# ---------------------------------------------------------------------------

STREAM_BWD = os.environ.get("PDTX_STREAM_BWD", "1")
STREAM_BWD_BUDGET = 13 * 1024 * 1024  # same general-admission cap as one-shot


def _stream_bwd_plan(H, Sq, Skv, D, *, mode=None):
    """Pick (heads_per_program G, q_subtile_rows bsub, kv_chunk ck), or None.

    Cost model (bytes live per program): resident q/do (bf16) + fp32 dq
    accumulator + lse/delta rows, plus the double-buffered k/v chunk pair,
    per-chunk dk/dv output blocks and fp32 accumulators, plus the transient
    s/p/dp/ds tiles (14 B per (g, bsub, ck) cell, as in the one-shot bwd
    model). None -> caller falls back to the online two-kernel backward.
    """
    mode = STREAM_BWD if mode is None else mode
    if mode in ("0", "off"):
        return None
    if D != 128 and mode != "all":
        return None
    best = None
    for g in range(min(H, 8), 0, -1):
        if H % g:
            continue
        for bsub in (512, 256):
            if bsub > Sq or Sq % bsub:
                continue
            ck = 512  # keeps per-chunk dots MXU-sized (see _oneshot_num_chunks)
            if Skv % ck or Skv // ck < 2:
                continue
            resident = g * (2 * Sq * D * 2          # q + do (bf16)
                            + Sq * D * 4            # dq accumulator (f32)
                            + 2 * Sq * LSE_LANES * 4)  # lse + delta rows
            chunk = g * ck * D * (2 * 2 * 2         # k/v, double-buffered
                                  + 2 * 2           # dk/dv output blocks
                                  + 2 * 4)          # dk/dv accumulators (f32)
            tiles = 14 * g * bsub * ck              # s/p/dp f32 + ds bf16
            if resident + chunk + tiles <= STREAM_BWD_BUDGET:
                key = (g, bsub)
                if best is None or key > best[0]:
                    best = (key, (g, bsub, ck))
    return best[1] if best else None


def _stream_bwd_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                       dq_ref, dk_ref, dv_ref, dq_acc, dk_acc, dv_acc, *,
                       sm_scale, causal, bsub, num_sub):
    c = pl.program_id(2)
    n_c = pl.num_programs(2)
    ck = k_ref.shape[2]

    @pl.when(c == 0)
    def _init():
        dq_acc[:] = jnp.zeros_like(dq_acc)

    # dk/dv complete within this grid step — reset every chunk.
    dk_acc[:] = jnp.zeros_like(dk_acc)
    dv_acc[:] = jnp.zeros_like(dv_acc)

    k_c = _mxu(k_ref[0])                          # [G, ck, D]
    v_c = _mxu(v_ref[0])
    for qs in range(num_sub):
        visible = True
        if causal:
            # Subtile qs sees chunk c iff any of its rows reach the chunk's
            # first key; fully-above-diagonal (subtile, chunk) pairs skip
            # the dots AND the exp entirely.
            visible = c * ck < (qs + 1) * bsub

        @pl.when(visible)
        def _sub(qs=qs):
            lo = qs * bsub
            q_s = _mxu(q_ref[0, :, lo:lo + bsub, :])      # [G, bsub, D]
            do_s = _mxu(do_ref[0, :, lo:lo + bsub, :])
            lse_s = lse_ref[0, :, lo:lo + bsub, :1]       # [G, bsub, 1]
            delta_s = delta_ref[0, :, lo:lo + bsub, :1]
            s = jax.lax.dot_general(q_s, k_c, (((2,), (2,)), ((0,), (0,))),
                                    preferred_element_type=jnp.float32)
            s = s * sm_scale
            if causal:
                s = _causal_mask_chunk(s, qs, bsub, c * ck)
            p = jnp.exp(s - lse_s)                        # [G, bsub, ck]
            dv_acc[:] += jax.lax.dot_general(
                p.astype(do_s.dtype), do_s, (((1,), (1,)), ((0,), (0,))),
                preferred_element_type=jnp.float32)
            dp = jax.lax.dot_general(do_s, v_c, (((2,), (2,)), ((0,), (0,))),
                                     preferred_element_type=jnp.float32)
            ds = (p * (dp - delta_s) * sm_scale).astype(k_c.dtype)
            dq_acc[:, lo:lo + bsub, :] += jax.lax.dot_general(
                ds, k_c, (((2,), (1,)), ((0,), (0,))),
                preferred_element_type=jnp.float32)
            dk_acc[:] += jax.lax.dot_general(
                ds, q_s, (((1,), (1,)), ((0,), (0,))),
                preferred_element_type=jnp.float32)

    dk_ref[0] = dk_acc[:].astype(dk_ref.dtype)
    dv_ref[0] = dv_acc[:].astype(dv_ref.dtype)

    @pl.when(c == n_c - 1)
    def _flush():
        dq_ref[0] = dq_acc[:].astype(dq_ref.dtype)


def _stream_bwd(q, k, v, o, lse, g, *, causal, plan):
    """q,k,v,o,g: [B,S,H,D] (kv already GQA-expanded); lse: [B,H,Sq,LANES]."""
    B, Sq, H, D = q.shape
    Skv = k.shape[1]
    G, bsub, ck = plan
    sm_scale = 1.0 / math.sqrt(D)
    delta = jnp.einsum("bshd,bshd->bhs", g.astype(jnp.float32),
                       o.astype(jnp.float32))
    delta = jnp.broadcast_to(delta[..., None], (*delta.shape, LSE_LANES))
    qt = jnp.transpose(q, (0, 2, 1, 3))
    kt = jnp.transpose(k, (0, 2, 1, 3))
    vt = jnp.transpose(v, (0, 2, 1, 3))
    dot = jnp.transpose(g, (0, 2, 1, 3))
    qspec = pl.BlockSpec((1, G, Sq, D), lambda b, h, c: (b, h, 0, 0))
    cspec = pl.BlockSpec((1, G, ck, D), lambda b, h, c: (b, h, c, 0))
    lspec = pl.BlockSpec((1, G, Sq, LSE_LANES), lambda b, h, c: (b, h, 0, 0))
    dq, dk, dv = pl.pallas_call(
        functools.partial(_stream_bwd_kernel, sm_scale=sm_scale,
                          causal=causal, bsub=bsub, num_sub=Sq // bsub),
        grid=(B, H // G, Skv // ck),
        in_specs=[qspec, cspec, cspec, qspec, lspec, lspec],
        out_specs=(qspec, cspec, cspec),
        out_shape=(jax.ShapeDtypeStruct((B, H, Sq, D), q.dtype),
                   jax.ShapeDtypeStruct((B, H, Skv, D), k.dtype),
                   jax.ShapeDtypeStruct((B, H, Skv, D), v.dtype)),
        scratch_shapes=[pltpu.VMEM((G, Sq, D), jnp.float32),   # dq
                        pltpu.VMEM((G, ck, D), jnp.float32),   # dk
                        pltpu.VMEM((G, ck, D), jnp.float32)],  # dv
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
    )(qt, kt, vt, dot, lse, delta)
    tr = lambda x: jnp.transpose(x, (0, 2, 1, 3))
    return tr(dq), tr(dk), tr(dv)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def flash_attention(q, k, v, causal: bool = False,
                    block_q: int = DEFAULT_BLOCK_Q,
                    block_kv: int = DEFAULT_BLOCK_KV,
                    impl: str = "auto",
                    kv_len: int | None = None):
    """Flash attention with the XLA oracle's exact semantics.

    [B, S, H, D] layout; fp32 softmax; GQA via fewer KV heads. Forward and
    backward are both Pallas kernels. ``impl``: "auto" picks the one-shot
    dense-score kernels when KV fits VMEM (short/medium S — see
    ``_oneshot_plan``) and the online-softmax streaming kernels otherwise
    (FlashAttention-2 recomputation scheme: residuals are q/k/v/o + per-row
    logsumexp, never the S x S matrix in HBM); "oneshot"/"online" force.

    ``kv_len`` (static): mask keys at positions >= kv_len. Used by the
    tile-padding path in :func:`attention.padded_flash_attention` that
    serves non-tile-aligned sequences (e.g. ViT's 197 tokens padded to
    256); one-shot kernels only.
    """
    k = attn_lib._repeat_kv(k, q.shape[2])
    v = attn_lib._repeat_kv(v, q.shape[2])
    out, _ = _fwd_dispatch(q, k, v, causal, block_q, block_kv, impl, kv_len)
    return out


def _fwd_dispatch(q, k, v, causal, block_q, block_kv, impl, kv_len):
    """Auto dispatch is per-direction, from the r4 measured shape map
    (BENCH_FLASH_MICRO.json):

    - CAUSAL forward: the streaming online kernel wins at every measured
      shape (0.54 vs 0.79 ms at B16·H12·S1024·D64; 0.72 vs 0.86 at
      S2048; 1.37 vs 1.99 at S4096/D128) — its grid skips fully-masked
      kv blocks and at default 1024-blocks the grid overhead that
      motivated the one-shot kernels has collapsed to one program per
      (batch, head, q-block).
    - Backward: the one-shot chunked kernel wins whenever its plan fits
      VMEM (2.37 vs 3.05 ms fwd+bwd at GPT-2 shapes); otherwise online.
    - Non-causal forward: one-shot when a plan exists (no masked blocks
      for the online grid to skip, so fewer/fatter programs win).

    The two kernels share the residual format (q,k,v,o + lse
    [B,H,S,LSE_LANES]), so mixing directions is free. The r3/r4-early
    all-or-nothing rule is superseded by these per-direction
    measurements; forced impl="oneshot"/"online" still pin both sides.
    """
    B, Sq, H, D = q.shape
    if kv_len is not None and impl == "online":
        raise ValueError("kv_len masking requires the one-shot kernels; "
                         "impl='online' cannot serve it")
    plan = None
    if impl == "oneshot" or kv_len is not None:
        plan = _oneshot_plan(H, Sq, k.shape[1], D, forced=impl == "oneshot")
    elif impl == "auto" and not causal:
        plan = _oneshot_plan(H, Sq, k.shape[1], D)
    if plan is None and (impl == "oneshot" or kv_len is not None):
        raise ValueError(f"oneshot flash attention cannot tile "
                         f"Sq={Sq}, Skv={k.shape[1]}, D={D} within VMEM"
                         + (" (kv_len masking requires the one-shot kernels)"
                            if kv_len is not None else ""))
    if plan is not None:
        return _oneshot_fwd(q, k, v, causal=causal, plan=plan, kv_len=kv_len)
    block_q, block_kv = _online_blocks(False, Sq, D, block_q, block_kv)
    return _flash_fwd(q, k, v, causal=causal, block_q=block_q,
                      block_kv=block_kv)


def _vjp_fwd(q, k, v, causal, block_q, block_kv, impl, kv_len):
    ke = attn_lib._repeat_kv(k, q.shape[2])
    ve = attn_lib._repeat_kv(v, q.shape[2])
    out, lse = _fwd_dispatch(q, ke, ve, causal, block_q, block_kv, impl,
                             kv_len)
    return out, (q, k, v, out, lse)


def _vjp_bwd(causal, block_q, block_kv, impl, kv_len, res, g):
    q, k, v, o, lse = res
    H, Hkv = q.shape[2], k.shape[2]
    ke = attn_lib._repeat_kv(k, H)
    ve = attn_lib._repeat_kv(v, H)
    if kv_len is not None and impl == "online":
        raise ValueError("kv_len masking requires the one-shot kernels; "
                         "impl='online' cannot serve it")
    plan = None
    if impl in ("oneshot", "auto") or kv_len is not None:
        # auto: one-shot backward whenever its plan fits (see
        # _fwd_dispatch's dispatch-map docstring).
        plan = _oneshot_plan(H, q.shape[1], ke.shape[1], q.shape[3], bwd=True,
                             forced=impl == "oneshot")
    if plan is None and (impl == "oneshot" or kv_len is not None):
        raise ValueError(
            f"oneshot flash attention backward cannot tile Sq={q.shape[1]}, "
            f"Skv={ke.shape[1]}, D={q.shape[3]} within VMEM (the backward "
            f"needs ~40% more live bytes than the forward"
            + ("; kv_len masking requires the one-shot kernels)"
               if kv_len is not None else "); use impl='auto' to fall back "
               "to the online kernels for such shapes"))
    if plan is not None:
        dq, dk, dv = _oneshot_bwd(q, ke, ve, o, lse, g, causal=causal,
                                  plan=plan, kv_len=kv_len)
    else:
        # Long-S fallback order: the streaming one-pass backward where its
        # plan fits (D=128 gate — see _stream_bwd_plan), else the online
        # two-kernel backward.
        splan = None
        if impl == "auto" and kv_len is None:
            splan = _stream_bwd_plan(H, q.shape[1], ke.shape[1], q.shape[3])
        if splan is not None:
            dq, dk, dv = _stream_bwd(q, ke, ve, o, lse, g, causal=causal,
                                     plan=splan)
        else:
            block_q, block_kv = _online_blocks(True, q.shape[1], q.shape[3],
                                               block_q, block_kv)
            dq, dk, dv = _flash_bwd(q, ke, ve, o, lse, g, causal=causal,
                                    block_q=block_q, block_kv=block_kv)
    if Hkv != H:
        # GQA: fold the repeated-head grads back onto the shared KV heads.
        B, S, _, D = dk.shape
        dk = dk.reshape(B, S, Hkv, H // Hkv, D).sum(3)
        dv = dv.reshape(B, S, Hkv, H // Hkv, D).sum(3)
    return dq, dk, dv


flash_attention.defvjp(_vjp_fwd, _vjp_bwd)


# ---------------------------------------------------------------------------
# Paged decode attention (serving): one query token per request, K/V read
# through a per-request page table into the preallocated page pool
# (serve/kv_cache.py). Forward-only — no vjp; decode never differentiates.
# ---------------------------------------------------------------------------


def _paged_decode_kernel(pt_ref, pos_ref, q_ref, k_ref, v_ref, o_ref,
                         m_ref, l_ref, acc_ref, *, sm_scale: float,
                         page_size: int, num_kv_heads: int):
    """Grid (B, max_pages), pages innermost ("arbitrary": online-softmax
    state persists in VMEM scratch across page steps, exactly the online
    kernels' scheme with the page table standing in for ONLINE_BLOCK_TABLE
    block indexing). ``pt_ref``/``pos_ref`` are the scalar-prefetched page
    table and query positions — the same values the in_specs' index_maps
    used to pick which physical page this step streams."""
    b = pl.program_id(0)
    p = pl.program_id(1)
    n_pages = pl.num_programs(1)
    pos = pos_ref[b]

    @pl.when(p == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    # A page whose first slot is past the query position is fully masked.
    @pl.when(p * page_size <= pos)
    def _compute():
        q = _mxu(q_ref[0])                       # [H, D]
        k = _mxu(k_ref[0])                       # [page_size, Hkv, D]
        v = _mxu(v_ref[0])
        H = q.shape[0]
        G = H // num_kv_heads
        # GQA without materializing repeated KV heads: per KV head, the G
        # grouped query heads share one [page_size, D] key tile.
        logits = jnp.concatenate([
            jax.lax.dot_general(
                q[h * G:(h + 1) * G], k[:, h, :], (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32)
            for h in range(num_kv_heads)], axis=0) * sm_scale  # [H, ps]
        k_pos = p * page_size + jax.lax.broadcasted_iota(
            jnp.int32, logits.shape, 1)
        logits = jnp.where(k_pos <= pos, logits, NEG_INF)

        m_prev = m_ref[:, :1]                    # [H, 1] (lane-bcast)
        m_new = jnp.maximum(m_prev, jnp.max(logits, axis=1, keepdims=True))
        prob = jnp.exp(logits - m_new)           # [H, ps]
        correction = jnp.exp(m_prev - m_new)
        l_ref[:] = jnp.broadcast_to(
            l_ref[:, :1] * correction + jnp.sum(prob, axis=1, keepdims=True),
            l_ref.shape)
        pv = jnp.concatenate([
            jax.lax.dot_general(
                prob[h * G:(h + 1) * G].astype(v.dtype), v[:, h, :],
                (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
            for h in range(num_kv_heads)], axis=0)  # [H, D]
        acc_ref[:] = acc_ref[:] * correction + pv
        m_ref[:] = jnp.broadcast_to(m_new, m_ref.shape)

    @pl.when(p == n_pages - 1)
    def _finish():
        denom = jnp.maximum(l_ref[:, :1], 1e-30)
        o_ref[0] = (acc_ref[:] / denom).astype(o_ref.dtype)


def _paged_decode_pallas(q, k_pages, v_pages, page_table, positions,
                         sm_scale):
    B, H, D = q.shape
    _, page_size, num_kv_heads, _ = k_pages.shape
    max_pages = page_table.shape[1]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(B, max_pages),
        in_specs=[
            pl.BlockSpec((1, H, D), lambda b, p, pt, pos: (b, 0, 0)),
            pl.BlockSpec((1, page_size, num_kv_heads, D),
                         lambda b, p, pt, pos: (pt[b, p], 0, 0, 0)),
            pl.BlockSpec((1, page_size, num_kv_heads, D),
                         lambda b, p, pt, pos: (pt[b, p], 0, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, H, D), lambda b, p, pt, pos: (b, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((H, 128), jnp.float32),   # m
            pltpu.VMEM((H, 128), jnp.float32),   # l
            pltpu.VMEM((H, D), jnp.float32),     # acc
        ],
    )
    return pl.pallas_call(
        functools.partial(_paged_decode_kernel, sm_scale=sm_scale,
                          page_size=page_size, num_kv_heads=num_kv_heads),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B, H, D), q.dtype),
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "arbitrary"),
        ),
        # Non-TPU backends run the identical kernel body interpreted — the
        # parity tests exercise this exact code path on CPU.
        interpret=jax.default_backend() != "tpu",
    )(page_table, positions, q, k_pages, v_pages)


def _paged_decode_xla(q, k_pages, v_pages, page_table, positions, sm_scale):
    """Gather-based reference/CPU path: materialize each request's logical
    KV view from the pool, then masked softmax in fp32 (same math as the
    ``attention.dot_product_attention`` oracle the training forward uses —
    the prefill/decode parity tests lean on that)."""
    B, H, D = q.shape
    _, page_size, num_kv_heads, _ = k_pages.shape
    S = page_table.shape[1] * page_size
    flat = page_table.reshape(-1)
    k = jnp.take(k_pages, flat, axis=0).reshape(B, S, num_kv_heads, D)
    v = jnp.take(v_pages, flat, axis=0).reshape(B, S, num_kv_heads, D)
    G = H // num_kv_heads
    qg = q.reshape(B, num_kv_heads, G, D)
    logits = jnp.einsum("bhgd,bshd->bhgs", _mxu(qg), _mxu(k),
                        preferred_element_type=jnp.float32) * sm_scale
    mask = jnp.arange(S)[None, :] <= positions[:, None]          # [B, S]
    logits = jnp.where(mask[:, None, None, :], logits, NEG_INF)
    prob = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
    out = jnp.einsum("bhgs,bshd->bhgd", prob, v.astype(jnp.float32))
    return out.reshape(B, H, D).astype(q.dtype)


def paged_decode_attention(q, k_pages, v_pages, page_table, positions,
                           impl: str = "auto"):
    """Decode-mode attention through a paged KV cache.

    q:          [B, H, D] — ONE query token per request (the decode step)
    k_pages:    [num_pages, page_size, Hkv, D] pool (one layer's K)
    v_pages:    same shape, the layer's V
    page_table: [B, max_pages] int32 physical page ids; entries past a
                request's length may be garbage (they are masked)
    positions:  [B] int32 position of the query token; keys at positions
                <= positions[b] are attended (the query's own K/V must
                already be appended — the model appends before attending)

    GQA is served natively: KV heads stay folded (H % Hkv == 0), queries
    are grouped per KV head. ``impl``: "auto" picks the Pallas page-table
    kernel on TPU and the gather-based XLA path elsewhere; "pallas"/"xla"
    force (the Pallas kernel runs interpreted off-TPU — that is the
    parity-test configuration).
    """
    B, H, D = q.shape
    num_kv_heads = k_pages.shape[2]
    if H % num_kv_heads:
        raise ValueError(f"H={H} not a multiple of Hkv={num_kv_heads}")
    if impl not in ("auto", "pallas", "xla"):
        raise ValueError(f"unknown paged decode impl {impl!r}")
    sm_scale = 1.0 / math.sqrt(D)
    page_table = page_table.astype(jnp.int32)
    positions = positions.astype(jnp.int32)
    if impl == "pallas" or (impl == "auto"
                            and jax.default_backend() == "tpu"):
        return _paged_decode_pallas(q, k_pages, v_pages, page_table,
                                    positions, sm_scale)
    return _paged_decode_xla(q, k_pages, v_pages, page_table, positions,
                             sm_scale)


def paged_prefill_attention(q, k_pages, v_pages, page_table, positions):
    """Prefill-window attention against a paged KV cache with history.

    q:          [B, S, H, D] — a window of query tokens starting mid-
                sequence (suffix prefill after a prefix-cache splice, or
                a later chunk of a chunked prefill)
    k_pages:    [num_pages, page_size, Hkv, D] pool (one layer's K)
    v_pages:    same shape, the layer's V
    page_table: [B, max_pages] int32 physical page ids
    positions:  [B, S] int32 logical position of each query token; keys
                at pool positions <= positions[b, s] are attended, which
                is causal masking that also covers the history before
                the window (those keys came from cached/earlier pages —
                the window's own K/V are appended before this runs).

    Plain-causal attention is wrong here: it would start every window at
    position 0. This is the gather-based XLA path (fp32 softmax, GQA
    grouped like ``_paged_decode_xla``); decode-bound serving keeps the
    Pallas budget on the decode kernel.
    """
    B, S, H, D = q.shape
    _, page_size, num_kv_heads, _ = k_pages.shape
    if H % num_kv_heads:
        raise ValueError(f"H={H} not a multiple of Hkv={num_kv_heads}")
    sm_scale = 1.0 / math.sqrt(D)
    page_table = page_table.astype(jnp.int32)
    positions = positions.astype(jnp.int32)
    T = page_table.shape[1] * page_size
    flat = page_table.reshape(-1)
    k = jnp.take(k_pages, flat, axis=0).reshape(B, T, num_kv_heads, D)
    v = jnp.take(v_pages, flat, axis=0).reshape(B, T, num_kv_heads, D)
    G = H // num_kv_heads
    qg = q.reshape(B, S, num_kv_heads, G, D)
    logits = jnp.einsum("bshgd,bthd->bhgst", _mxu(qg), _mxu(k),
                        preferred_element_type=jnp.float32) * sm_scale
    mask = jnp.arange(T)[None, None, :] <= positions[:, :, None]  # [B, S, T]
    logits = jnp.where(mask[:, None, None, :, :], logits, NEG_INF)
    prob = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
    out = jnp.einsum("bhgst,bthd->bshgd", prob, v.astype(jnp.float32))
    return out.reshape(B, S, H, D).astype(q.dtype)
