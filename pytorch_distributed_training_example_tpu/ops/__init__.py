"""TPU compute ops: attention family (XLA, Pallas flash, ring, Ulysses) and collectives."""
