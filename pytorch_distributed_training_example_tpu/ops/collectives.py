"""Thin axis-name wrappers over XLA collectives — SURVEY.md §2d.

The communication backend IS the XLA partitioner: there is no user-space
transport (the NCCL replacement is compiled ICI/DCN collectives). These
wrappers exist for ``shard_map`` code (ring attention, pipeline, manual
reductions) so call sites read like the c10d API the reference uses, and for
host-level reductions used by logging/eval.
"""

from __future__ import annotations

from typing import Sequence

import jax
import jax.numpy as jnp


def axis_size(axis: str) -> int:
    """Static mesh-axis size inside ``shard_map``, portable across jax
    versions (``jax.lax.axis_size`` only exists on newer jax; 0.4.x exposes
    the size through ``jax.core.axis_frame``)."""
    if hasattr(jax.lax, "axis_size"):
        return int(jax.lax.axis_size(axis))
    from jax import core

    frame = core.axis_frame(axis)
    return int(getattr(frame, "size", frame))


def all_reduce(x, axis: str | Sequence[str]):
    """Sum across a mesh axis (reference: ``dist.all_reduce``)."""
    return jax.lax.psum(x, axis)


def all_reduce_mean(x, axis: str | Sequence[str]):
    return jax.lax.pmean(x, axis)


def all_gather(x, axis: str, *, axis_index: int = 0, tiled: bool = True):
    """Concatenate shards along ``axis_index`` (reference: ``all_gather``)."""
    return jax.lax.all_gather(x, axis, axis=axis_index, tiled=tiled)


def reduce_scatter(x, axis: str, *, axis_index: int = 0):
    """Sum then scatter along ``axis_index`` (the ZeRO grad primitive)."""
    return jax.lax.psum_scatter(x, axis, scatter_dimension=axis_index,
                                tiled=True)


def ring_shift(x, axis: str, *, reverse: bool = False):
    """Send to the next ring neighbor over ICI (ppermute convenience)."""
    n = axis_size(axis)
    if reverse:
        perm = [(i, (i - 1) % n) for i in range(n)]
    else:
        perm = [(i, (i + 1) % n) for i in range(n)]
    return jax.lax.ppermute(x, axis, perm)


def all_to_all(x, axis: str, *, split_axis: int, concat_axis: int):
    """Transpose sharding between two array dims (Ulysses/MoE primitive)."""
    return jax.lax.all_to_all(x, axis, split_axis=split_axis,
                              concat_axis=concat_axis, tiled=True)


def all_to_all_blocks(x, axis: str, *, impl: str = "native"):
    """Block all-to-all: ``x[q]`` goes to device q, returns ``out[s]`` from s.

    ``x`` is ``[n, ...]`` with one leading block per destination on the
    ``axis`` mesh axis (size n); the result has the same shape with block
    ``s`` holding what source device s addressed to this device. This is
    the MoE expert-dispatch primitive (GShard's token all-to-all).

    ``impl``:

    - ``"native"`` — ``lax.all_to_all``. Verified to compute correctly
      under the gloo CPU cross-process backend (r12 gangs), so it is the
      default everywhere including host-mesh dryruns.
    - ``"ppermute"`` — decomposed into n-1 ``ppermute`` hops (each shift k
      sends block ``(i+k) mod n`` to peer ``i+k``). Kept as a
      gloo/older-jaxlib safety hatch and as a directly testable oracle for
      the native path (tests/test_moe_dropless.py); byte volume is
      identical, latency is n-1 serialized hops instead of one fused op.

    Must be called inside ``shard_map`` (manual axis context).
    """
    n = axis_size(axis)
    if impl == "native":
        return jax.lax.all_to_all(x, axis, split_axis=0, concat_axis=0,
                                  tiled=True)
    if impl != "ppermute":
        raise ValueError(
            f"unknown all_to_all_blocks impl {impl!r}; have ['native', "
            "'ppermute']")
    idx = jax.lax.axis_index(axis)
    # out[idx] = my own block addressed to myself (no hop).
    out = jnp.zeros_like(x)
    out = jax.lax.dynamic_update_slice_in_dim(
        out, jax.lax.dynamic_slice_in_dim(x, idx, 1, axis=0), idx, axis=0)
    for k in range(1, n):
        # Shift k: device i sends its block for peer (i+k) mod n; the block
        # device i receives on this hop therefore came from (i-k) mod n.
        perm = [(i, (i + k) % n) for i in range(n)]
        sent = jax.lax.dynamic_slice_in_dim(x, (idx + k) % n, 1, axis=0)
        recv = jax.lax.ppermute(sent, axis, perm)
        out = jax.lax.dynamic_update_slice_in_dim(
            out, recv, (idx - k) % n, axis=0)
    return out


def broadcast_one_to_all(x, axis: str, *, src: int = 0):
    """Replicate ``src``'s value across the axis (reference: ``broadcast``)."""
    idx = jax.lax.axis_index(axis)
    masked = jnp.where(idx == src, x, jnp.zeros_like(x))
    return jax.lax.psum(masked, axis)


# Host-level (cross-process, outside jit) ----------------------------------


def host_all_reduce_sum(x):
    """Sum a small host value across processes (logging/eval convenience)."""
    from jax.experimental import multihost_utils

    return multihost_utils.process_allgather(jnp.asarray(x)).sum(0)
