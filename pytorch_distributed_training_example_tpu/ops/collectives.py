"""Thin axis-name wrappers over XLA collectives — SURVEY.md §2d.

The communication backend IS the XLA partitioner: there is no user-space
transport (the NCCL replacement is compiled ICI/DCN collectives). These
wrappers exist for ``shard_map`` code (ring attention, pipeline, manual
reductions) so call sites read like the c10d API the reference uses, and for
host-level reductions used by logging/eval.
"""

from __future__ import annotations

from typing import Sequence

import jax
import jax.numpy as jnp


def all_reduce(x, axis: str | Sequence[str]):
    """Sum across a mesh axis (reference: ``dist.all_reduce``)."""
    return jax.lax.psum(x, axis)


def all_reduce_mean(x, axis: str | Sequence[str]):
    return jax.lax.pmean(x, axis)


def all_gather(x, axis: str, *, axis_index: int = 0, tiled: bool = True):
    """Concatenate shards along ``axis_index`` (reference: ``all_gather``)."""
    return jax.lax.all_gather(x, axis, axis=axis_index, tiled=tiled)


def reduce_scatter(x, axis: str, *, axis_index: int = 0):
    """Sum then scatter along ``axis_index`` (the ZeRO grad primitive)."""
    return jax.lax.psum_scatter(x, axis, scatter_dimension=axis_index,
                                tiled=True)


def ring_shift(x, axis: str, *, reverse: bool = False):
    """Send to the next ring neighbor over ICI (ppermute convenience)."""
    n = jax.lax.axis_size(axis)
    if reverse:
        perm = [(i, (i - 1) % n) for i in range(n)]
    else:
        perm = [(i, (i + 1) % n) for i in range(n)]
    return jax.lax.ppermute(x, axis, perm)


def all_to_all(x, axis: str, *, split_axis: int, concat_axis: int):
    """Transpose sharding between two array dims (Ulysses/MoE primitive)."""
    return jax.lax.all_to_all(x, axis, split_axis=split_axis,
                              concat_axis=concat_axis, tiled=True)


def broadcast_one_to_all(x, axis: str, *, src: int = 0):
    """Replicate ``src``'s value across the axis (reference: ``broadcast``)."""
    idx = jax.lax.axis_index(axis)
    masked = jnp.where(idx == src, x, jnp.zeros_like(x))
    return jax.lax.psum(masked, axis)


# Host-level (cross-process, outside jit) ----------------------------------


def host_all_reduce_sum(x):
    """Sum a small host value across processes (logging/eval convenience)."""
    from jax.experimental import multihost_utils

    return multihost_utils.process_allgather(jnp.asarray(x)).sum(0)
