"""Fused (BN-apply + ReLU) -> 1x1-conv matmul -> BN-statistics Pallas kernel.

The ResNet-50 profile (PROFILE_RN50.md) pins 46% of the v5e step on
BatchNorm-statistics reductions and another 22% on the elementwise
BN-apply/ReLU passes — both pure HBM traffic over activation tensors that
the convolutions already stream through VMEM. A 1x1 convolution in NHWC is
exactly a matmul ``[B*H*W, Cin] @ [Cin, Cout]`` (most of ResNet-50's convs:
the bottleneck reduce/expand pair), so this kernel fuses, in ONE pass over
the activation:

- prologue: per-channel affine (the *previous* BN's fold: ``x*scale+bias``)
  + ReLU, applied to the block while it sits in VMEM;
- body: the MXU matmul;
- epilogue: per-channel ``sum(y)`` and ``sum(y^2)`` of the conv *output*
  accumulated across row-blocks — the statistics the *next* BN needs,
  computed without ever re-reading ``y`` from HBM.

Relative to XLA's schedule (separate BN-apply pass + conv + separate
``convert_reduce_fusion`` stats pass) this removes an elementwise
read+write of the input tensor and a full re-read of the output tensor:
for the canonical ``[128*56*56, 256] @ [256, 64]`` bottleneck conv that is
~720 MB -> ~260 MB of logical HBM traffic (2.8x) for the segment.

Grid: 1-D over row blocks (the full ``[Cin, Cout]`` weight tile stays
resident in VMEM — 1x1-conv weights are <=1 MB). The stats output block
maps every grid step to the same ``[8, Cout]`` tile; TPU grids execute
sequentially, so read-modify-write accumulation across steps is sound
(same revisiting pattern as the flash-attention kernel's accumulators).

``fused_stats_matmul`` is the raw kernel; ``bn_stats_matmul`` wraps it
with channel padding to the 128-lane boundary and returns
``(y, mean, var)`` — a drop-in for ``relu(x*s+b) @ w`` + ``moments(y)``.
Microbenchmark + parity artifact: benchmarks/fused_bn_bench.py ->
BENCH_FUSED_BN.json (VERDICT r2 #1).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

STATS_ROWS = 8  # f32 sublane tile height; row 0 = sum, row 1 = sum of squares


def _kernel(x_ref, w_ref, scale_ref, bias_ref, y_ref, stats_ref, *,
            relu: bool, affine: bool):
    i = pl.program_id(0)
    x = x_ref[:]
    if affine:
        x = x * scale_ref[:] + bias_ref[:]
    if relu:
        x = jnp.maximum(x, 0.0)
    y = jnp.dot(x.astype(w_ref.dtype), w_ref[:],
                preferred_element_type=jnp.float32)
    y_ref[:] = y.astype(y_ref.dtype)

    @pl.when(i == 0)
    def _init():
        stats_ref[:] = jnp.zeros_like(stats_ref)

    zeros = jnp.zeros((STATS_ROWS - 2, y.shape[1]), jnp.float32)
    block = jnp.concatenate(
        [jnp.sum(y, 0)[None], jnp.sum(y * y, 0)[None], zeros], 0)
    stats_ref[:] += block


def fused_stats_matmul(x, w, scale=None, bias=None, *, relu: bool = True,
                       block_n: int = 1024, out_dtype=None,
                       interpret: bool = False):
    """``y = maybe_relu(x*scale+bias) @ w`` plus per-column sum/sumsq of y.

    x: [N, K] (N % block_n == 0), w: [K, C] with C a multiple of 128.
    scale/bias: [1, K] per-channel affine on x (None = skip).
    Returns (y [N, C], stats [STATS_ROWS, C] f32) with stats[0]=sum(y),
    stats[1]=sum(y^2) over rows.
    """
    N, K = x.shape
    K2, C = w.shape
    assert K == K2, (x.shape, w.shape)
    block_n = min(block_n, N)
    assert N % block_n == 0, (N, block_n)
    assert C % 128 == 0, f"pad Cout to the 128-lane boundary (got {C})"
    affine = scale is not None or bias is not None
    if scale is None:
        scale = jnp.ones((1, K), x.dtype)
    if bias is None:
        bias = jnp.zeros((1, K), x.dtype)
    out_dtype = out_dtype or x.dtype
    grid = (N // block_n,)
    y, stats = pl.pallas_call(
        functools.partial(_kernel, relu=relu, affine=affine),
        grid=grid,
        in_specs=[
            pl.BlockSpec((block_n, K), lambda i: (i, 0)),
            pl.BlockSpec((K, C), lambda i: (0, 0)),
            pl.BlockSpec((1, K), lambda i: (0, 0)),
            pl.BlockSpec((1, K), lambda i: (0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((block_n, C), lambda i: (i, 0)),
            pl.BlockSpec((STATS_ROWS, C), lambda i: (0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((N, C), out_dtype),
            jax.ShapeDtypeStruct((STATS_ROWS, C), jnp.float32),
        ],
        interpret=interpret,
    )(x, w, scale, bias)
    return y, stats


def bn_stats_matmul(x, w, scale=None, bias=None, *, relu: bool = True,
                    block_n: int = 1024, interpret: bool = False):
    """Channel-padding wrapper returning ``(y, mean, var)`` of the output.

    Pads Cout up to 128 lanes (zero columns produce zero stats and are
    sliced away), so it accepts the raw ResNet channel counts (64, ...).
    """
    N, K = x.shape
    C = w.shape[1]
    Cp = max(128, -(-C // 128) * 128)
    if Cp != C:
        w = jnp.pad(w, ((0, 0), (0, Cp - C)))
    y, stats = fused_stats_matmul(x, w, scale, bias, relu=relu,
                                  block_n=block_n, interpret=interpret)
    mean = stats[0, :C] / N
    var = stats[1, :C] / N - mean * mean
    return y[:, :C], mean, var
