"""Version compat for the jax/Pallas API surface the kernels use.

The code targets the current API (``pltpu.CompilerParams``,
``pltpu.force_tpu_interpret_mode``, ``jax.shard_map``). jax 0.4.x spells
these ``TPUCompilerParams``, nothing at all, and
``jax.experimental.shard_map.shard_map(check_rep=...)`` — which made every
kernel call site *and* every interpret-mode CPU test fail on 0.4.x hosts.
Importing this module (ops.flash_attention, ops.attention,
parallel.pipeline and tests/conftest all do) patches the names in place,
so call sites stay written against the modern API:

- ``pltpu.CompilerParams``: aliased to ``TPUCompilerParams`` when missing.
- ``pltpu.force_tpu_interpret_mode``: emulated by wrapping
  ``pl.pallas_call`` with ``interpret=True`` for the duration of the
  context. Like the real thing, it takes effect at trace time, so
  ``jit``/``grad`` regions traced inside the context run the kernels in
  interpret mode.
- ``jax.shard_map``: forwarded to ``jax.experimental.shard_map.shard_map``
  with ``check_vma`` translated to the old ``check_rep``.

No-op on jax versions that already provide the modern names.
"""

from __future__ import annotations

import contextlib

import jax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

if not hasattr(jax, "shard_map"):
    from jax.experimental.shard_map import shard_map as _shard_map

    def _shard_map_compat(f, *, mesh, in_specs, out_specs, check_vma=True,
                          **kwargs):
        return _shard_map(f, mesh=mesh, in_specs=in_specs,
                          out_specs=out_specs, check_rep=check_vma, **kwargs)

    jax.shard_map = _shard_map_compat

if not hasattr(pltpu, "CompilerParams") and hasattr(pltpu, "TPUCompilerParams"):
    pltpu.CompilerParams = pltpu.TPUCompilerParams

if not hasattr(pltpu, "force_tpu_interpret_mode"):

    @contextlib.contextmanager
    def force_tpu_interpret_mode():
        orig = pl.pallas_call

        def _interpreted(*args, **kwargs):
            kwargs.setdefault("interpret", True)
            return orig(*args, **kwargs)

        pl.pallas_call = _interpreted
        try:
            yield
        finally:
            pl.pallas_call = orig

    pltpu.force_tpu_interpret_mode = force_tpu_interpret_mode
