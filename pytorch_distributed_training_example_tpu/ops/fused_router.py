"""Fused single-pass MoE router kernel (Pallas TPU).

The reference router chain (parallel/moe.py, ``router_impl="reference"``)
computes softmax -> ``lax.top_k`` -> gate renormalization -> logsumexp ->
``probs.mean(0)`` as separate XLA ops, each re-reading the fp32 ``[T, E]``
logits/probs from HBM. This kernel makes ONE VMEM-resident pass over a
``[block_tokens, E]`` logits tile and emits everything the MoE block needs
downstream:

- ``gate_vals`` ``[T, k]`` — renormalized top-k gate weights,
- ``expert_idx`` ``[T, k]`` int32 — chosen experts, ``lax.top_k`` order
  (ties broken toward the lower expert index, matching XLA),
- ``lse`` ``[T]`` — logsumexp of the logits (the z-loss input),
- ``probs_mean`` ``[E]`` — mean router probability per expert (the aux-loss
  ``me`` term), accumulated across the sequential grid.

The top-k is k rounds of first-occurrence argmax (max, then min-index among
maxima, then mask) — identical selection and tie order to ``lax.top_k``.

Backward is a plain-XLA ``custom_vjp`` that recomputes the softmax from the
saved logits and composes the gate-renormalization, top-k scatter,
``probs_mean``, logsumexp, and softmax VJPs in one expression — exactly the
cotangent the reference chain's AD produces (equivalence-tested in
tests/test_moe_router.py). A Pallas backward is a chip-A/B follow-up; the
[T, E] recompute is tiny next to the expert FFNs.

On non-TPU backends the kernel runs in interpret mode (numerically the same
program), so CPU tests/dryruns validate the real kernel body — the same
``pallas_compat`` route ``_stream_bwd`` took. Output layouts are kept at
their logical shapes (``[T, k]``, ``[T, 1]``); lane-padding them for Mosaic
is part of the chip A/B, not correctness.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from pytorch_distributed_training_example_tpu.ops import pallas_compat  # noqa: F401


def _block_tokens(n_tokens: int) -> int:
    """Largest nice power-of-two row block; ragged sizes pad the last block."""
    for bt in (512, 256, 128, 64, 32, 16, 8):
        if n_tokens % bt == 0:
            return bt
    return min(n_tokens, 512)


def _router_kernel(logits_ref, gate_ref, idx_ref, lse_ref, pm_ref, *,
                   top_k: int, n_tokens: int, block_tokens: int,
                   num_experts: int):
    i = pl.program_id(0)
    x = logits_ref[...].astype(jnp.float32)                  # [bt, E]
    m = jnp.max(x, axis=-1, keepdims=True)
    ex = jnp.exp(x - m)
    se = jnp.sum(ex, axis=-1, keepdims=True)
    probs = ex / se
    lse_ref[...] = m + jnp.log(se)

    # k rounds of first-occurrence argmax == lax.top_k incl. tie order.
    eidx = jax.lax.broadcasted_iota(jnp.int32, probs.shape, 1)
    avail = probs
    gates, idxs = [], []
    for _ in range(top_k):
        mj = jnp.max(avail, axis=-1, keepdims=True)
        aj = jnp.min(jnp.where(avail == mj, eidx, num_experts),
                     axis=-1, keepdims=True)
        gates.append(mj)
        idxs.append(aj)
        avail = jnp.where(eidx == aj, -jnp.inf, avail)
    g = jnp.concatenate(gates, axis=-1)                      # [bt, k]
    gate_ref[...] = g / jnp.maximum(jnp.sum(g, -1, keepdims=True), 1e-9)
    idx_ref[...] = jnp.concatenate(idxs, axis=-1)

    # probs.mean(0) accumulated across the (sequential) grid; padded rows
    # of a ragged final block are masked out of the sum.
    row = (i * block_tokens
           + jax.lax.broadcasted_iota(jnp.int32, (probs.shape[0], 1), 0))
    contrib = jnp.sum(jnp.where(row < n_tokens, probs, 0.0),
                      axis=0, keepdims=True) / n_tokens

    @pl.when(i == 0)
    def _init():
        pm_ref[...] = jnp.zeros_like(pm_ref)

    pm_ref[...] += contrib


def _fused_router_call(logits, top_k: int):
    T, E = logits.shape
    bt = _block_tokens(T)
    Tp = -(-T // bt) * bt
    logits_p = logits if Tp == T else jnp.zeros(
        (Tp, E), logits.dtype).at[:T].set(logits)
    kernel = functools.partial(_router_kernel, top_k=top_k, n_tokens=T,
                               block_tokens=bt, num_experts=E)
    gate, idx, lse, pm = pl.pallas_call(
        kernel,
        grid=(Tp // bt,),
        in_specs=[pl.BlockSpec((bt, E), lambda i: (i, 0))],
        out_specs=[
            pl.BlockSpec((bt, top_k), lambda i: (i, 0)),
            pl.BlockSpec((bt, top_k), lambda i: (i, 0)),
            pl.BlockSpec((bt, 1), lambda i: (i, 0)),
            pl.BlockSpec((1, E), lambda i: (0, 0)),   # revisited accumulator
        ],
        out_shape=[
            jax.ShapeDtypeStruct((Tp, top_k), jnp.float32),
            jax.ShapeDtypeStruct((Tp, top_k), jnp.int32),
            jax.ShapeDtypeStruct((Tp, 1), jnp.float32),
            jax.ShapeDtypeStruct((1, E), jnp.float32),
        ],
        # The pm accumulator needs the grid walked in order.
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("arbitrary",)),
        # Non-TPU backends run the identical kernel body interpreted — the
        # CPU-validation route (pallas_compat) the flash kernels use.
        interpret=jax.default_backend() != "tpu",
    )(logits_p)
    return gate[:T], idx[:T], lse[:T, 0], pm[0]


@functools.partial(jax.custom_vjp, nondiff_argnums=(1,))
def fused_router(logits, top_k: int):
    """Single-pass router: (gate_vals, expert_idx, lse, probs_mean).

    ``logits``: [T, E] fp32 router logits. Differentiable in ``gate_vals``,
    ``lse`` and ``probs_mean``; ``expert_idx`` is integral.
    """
    return _fused_router_call(logits, top_k)


def _fused_router_fwd(logits, top_k: int):
    out = _fused_router_call(logits, top_k)
    return out, (logits, out[1])


def _fused_router_bwd(top_k: int, res, cts):
    logits, idx = res
    dg, _didx, dlse, dpm = cts
    probs = jax.nn.softmax(logits, axis=-1)                  # [T, E]
    T = logits.shape[0]
    # Gate renormalization VJP: v_j = raw_j / G, G = sum(raw) (the 1e-9
    # clamp is inactive for softmax outputs — top-1 prob >= 1/E).
    raw = jnp.take_along_axis(probs, idx, axis=1)            # [T, k]
    denom = jnp.maximum(raw.sum(-1, keepdims=True), 1e-9)
    v = raw / denom
    draw = (dg - jnp.sum(dg * v, -1, keepdims=True)) / denom
    # top-k selection VJP: scatter the raw-gate cotangents (expert indices
    # are distinct per token, so no collisions)...
    dprobs = jnp.zeros_like(probs).at[
        jnp.arange(T)[:, None], idx].add(draw)
    # ...plus the probs_mean term, then one softmax VJP over the sum.
    dprobs = dprobs + dpm[None, :] / T
    dlogits = probs * (dprobs - jnp.sum(dprobs * probs, -1, keepdims=True))
    # logsumexp VJP: d lse / d logits = probs.
    dlogits = dlogits + probs * dlse[:, None]
    return (dlogits,)


fused_router.defvjp(_fused_router_fwd, _fused_router_bwd)
