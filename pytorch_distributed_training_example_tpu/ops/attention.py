"""Attention family: XLA reference, ring (context-parallel) and Ulysses.

The reference has no attention of its own (its models come from torchvision /
minimal GPT-2; long-context parallelism is absent, SURVEY.md §5) — but the
framework treats sequence/context parallelism as first-class (§2c):

- :func:`dot_product_attention` — the single-device oracle. Plain XLA ops:
  on TPU, XLA fuses QK^T -> softmax -> PV into an MXU-friendly pipeline; the
  Pallas flash kernel (ops/flash_attention.py) replaces it when profitable.
- :func:`ring_attention` — context-parallel attention: Q stays put, K/V
  blocks rotate around the ``context`` mesh axis via ``ppermute`` (ICI
  neighbors on the torus), with blockwise online-softmax accumulation, so
  sequence length scales with the number of chips while memory per chip
  stays O(S/c * S/c).
- :func:`ulysses_attention` — all-to-all alternative: swap sequence-sharding
  for head-sharding around the attention core (preferable when
  heads >= context shards and full-sequence attention per head is cheap).

Shapes follow the TPU-native convention ``[batch, seq, heads, head_dim]``
(BSHD; heads before head_dim keeps the trailing 128-lane dim dense for the
MXU). GQA is supported by passing fewer K/V heads than Q heads.
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from pytorch_distributed_training_example_tpu.ops import pallas_compat  # noqa: F401

NEG_INF = -1e30


def _repeat_kv(k: jax.Array, num_q_heads: int) -> jax.Array:
    """Broadcast GQA KV heads up to the Q head count."""
    num_kv = k.shape[2]
    if num_kv == num_q_heads:
        return k
    assert num_q_heads % num_kv == 0, (num_q_heads, num_kv)
    return jnp.repeat(k, num_q_heads // num_kv, axis=2)


def _causal_masked(logits, q_offset):
    q_pos = jax.lax.broadcasted_iota(jnp.int32, logits.shape, 2) + q_offset
    k_pos = jax.lax.broadcasted_iota(jnp.int32, logits.shape, 3)
    return jnp.where(q_pos >= k_pos, logits, NEG_INF)


@functools.partial(jax.custom_vjp, nondiff_argnums=(1, 2, 3))
def _softmax_lowp_residual(logits, out_dtype, causal, q_offset):
    """(mask +) f32 softmax whose ONLY autodiff residual is the
    low-precision probs.

    Plain ``softmax(logits).astype(bf16)`` saves the f32 probs for the
    softmax VJP *and* the bf16 copy for the downstream PV matmul VJP —
    at ViT-B/16 shapes that f32 residual is 119 MB/layer of pure HBM
    traffic (PROFILE_VIT.md). Backward here recomputes the softmax VJP
    from the bf16 probs instead: dlogits = p * (g - <g, p>). The causal
    mask lives INSIDE this op (static ``q_offset`` only) because masked
    rows have p = 0, so the backward needs no mask residual either; a
    ``jnp.where`` outside would pin an extra [B,H,S,S] f32 + bool pair.
    Precision cost is one bf16 rounding of p inside an expression that is
    already evaluated in the model's bf16 compute dtype;
    exactness-sensitive callers keep the default exact path.
    """
    if causal:
        logits = _causal_masked(logits, q_offset)
    return jax.nn.softmax(logits, axis=-1).astype(out_dtype)


def _softmax_lowp_fwd(logits, out_dtype, causal, q_offset):
    p = _softmax_lowp_residual(logits, out_dtype, causal, q_offset)
    return p, p


def _softmax_lowp_bwd(out_dtype, causal, q_offset, p_lowp, g):
    p = p_lowp.astype(jnp.float32)
    g = g.astype(jnp.float32)
    d = p * (g - jnp.sum(g * p, axis=-1, keepdims=True))
    return (d,)


_softmax_lowp_residual.defvjp(_softmax_lowp_fwd, _softmax_lowp_bwd)


def dot_product_attention(
    q: jax.Array,           # [B, Sq, H, D]
    k: jax.Array,           # [B, Skv, Hkv, D]
    v: jax.Array,           # [B, Skv, Hkv, D]
    *,
    causal: bool = False,
    bias: jax.Array | None = None,
    q_offset: int | jax.Array = 0,
    lowp_residual: bool = False,
) -> jax.Array:
    """Reference attention in pure XLA; fp32 softmax, inputs' dtype out.

    ``q_offset`` positions the query block within the global sequence for
    causal masking (used by the ring schedule where K/V blocks come from
    other context shards).

    ``lowp_residual=True`` stores the attention probabilities for backward
    in the compute dtype instead of f32 (see
    :func:`_softmax_lowp_residual`) — the dispatcher enables it for
    low-precision training, where it removes half the dominant residual
    traffic at short-sequence shapes the flash kernels don't serve.
    """
    orig_dtype = q.dtype
    depth = q.shape[-1]
    k = _repeat_kv(k, q.shape[2])
    v = _repeat_kv(v, q.shape[2])
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                        preferred_element_type=jnp.float32)
    logits = logits * (1.0 / math.sqrt(depth))
    if bias is not None:
        logits = logits + bias.astype(jnp.float32)
    # The low-precision-residual path also wants the causal mask inside
    # its custom VJP (see _softmax_lowp_residual); it needs a STATIC
    # q_offset — ring schedules pass traced offsets and use the exact path.
    if (lowp_residual and v.dtype != jnp.float32
            and isinstance(q_offset, int)):
        probs = _softmax_lowp_residual(logits, v.dtype, causal, q_offset)
    else:
        if causal:
            logits = _causal_masked(logits, q_offset)
        probs = jax.nn.softmax(logits, axis=-1).astype(v.dtype)
    out = jnp.einsum("bhqk,bkhd->bqhd", probs, v,
                     preferred_element_type=jnp.float32)
    return out.astype(orig_dtype)


# ---------------------------------------------------------------------------
# Ring attention (context parallelism) — SURVEY.md §2c "Ring attention"
# ---------------------------------------------------------------------------


def _online_block(q, k, v, *, causal, q_offset, k_offset, m, l, acc,
                  kv_len=None):
    """One ring step: attend q against a K/V block, updating the online
    softmax state (m: running max, l: running denom, acc: unnormalized out).

    ``kv_len`` bounds the VALID global key positions: keys at
    ``k_offset + j >= kv_len`` are padding (the torn-last-block case, where
    the sequence was padded up to a ring-degree multiple) and are masked
    out exactly like causally-future keys.
    """
    depth = q.shape[-1]
    k = _repeat_kv(k, q.shape[2])
    v = _repeat_kv(v, q.shape[2])
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                        preferred_element_type=jnp.float32)
    logits = logits * (1.0 / math.sqrt(depth))
    if causal or kv_len is not None:
        q_pos = jax.lax.broadcasted_iota(jnp.int32, logits.shape, 2) + q_offset
        k_pos = jax.lax.broadcasted_iota(jnp.int32, logits.shape, 3) + k_offset
        valid = jnp.ones(logits.shape, bool)
        if causal:
            valid &= q_pos >= k_pos
        if kv_len is not None:
            valid &= k_pos < kv_len
        logits = jnp.where(valid, logits, NEG_INF)
    block_max = jnp.max(logits, axis=-1)               # [B,H,Q]
    new_m = jnp.maximum(m, block_max)
    correction = jnp.exp(m - new_m)
    p = jnp.exp(logits - new_m[..., None])             # [B,H,Q,K]
    new_l = l * correction + jnp.sum(p, axis=-1)
    pv = jnp.einsum("bhqk,bkhd->bhqd", p.astype(jnp.float32),
                    v.astype(jnp.float32), preferred_element_type=jnp.float32)
    new_acc = acc * correction[..., None] + pv
    return new_m, new_l, new_acc


def ring_attention(
    q: jax.Array, k: jax.Array, v: jax.Array,
    *,
    mesh: Mesh,
    axis: str = "context",
    causal: bool = False,
    batch_axes=("data", "fsdp"),
    head_axis: str = "model",
    ring_impl: str = "ppermute",
) -> jax.Array:
    """Context-parallel attention over the ``axis`` mesh dimension.

    Inputs are globally-shaped ``[B, S, H, D]`` arrays whose sequence dim is
    sharded over ``axis``; inside ``shard_map`` each device holds its local
    ``S/c`` block, and K/V blocks rotate around the ring with ``ppermute``
    (one ICI hop per step — neighbor exchange rides the torus,
    ``ops.collectives.ring_shift``). The online softmax keeps the result
    exactly equal to full attention (tested against
    :func:`dot_product_attention` on a fake 8-device mesh).

    ``S`` need not divide the ring degree: a torn last block is handled by
    padding the sequence up to the next multiple of ``c`` — padded keys are
    masked out of every block's softmax (``kv_len``) and the padded query
    rows are sliced off (their cotangents are zero, so gradients are exact).

    The head dim stays sharded on ``head_axis`` (tensor parallelism composes
    with the ring: each TP shard rings its own head slice). With
    ``causal=True``, blocks that are entirely in a query shard's future are
    skipped with ``lax.cond`` (they still circulate — the ring must stay in
    lockstep — but their QK/PV FLOPs are elided; their contribution is
    identically zero either way).

    ``ring_impl``:

    - ``"ppermute"`` — the rotating-block schedule above (default; K/V
      memory stays O(S/c) per device and each hop overlaps with compute).
    - ``"allgather"`` — gather the full K/V along the ring axis once and
      run one masked local attention. Keeps activation memory for Q/out at
      O(S/c) but materializes full K/V per device; the fallback for
      backends where ppermute-in-a-loop doesn't lower or overlap (and a
      directly testable oracle for the rotating schedule).
    """
    c = mesh.shape[axis]
    if c == 1:
        return dot_product_attention(q, k, v, causal=causal)
    if ring_impl not in ("ppermute", "allgather"):
        raise ValueError(
            f"unknown ring_impl {ring_impl!r}; have ['ppermute', 'allgather']")
    if k.shape[1] != q.shape[1]:
        raise ValueError(
            f"ring attention is self-attention over one sharded sequence; "
            f"got Sq={q.shape[1]}, Skv={k.shape[1]}")
    from pytorch_distributed_training_example_tpu.ops import collectives

    # Torn last block: pad S up to a ring-degree multiple; padded keys are
    # masked via kv_len, padded query rows are sliced off below.
    S = q.shape[1]
    kv_len = None
    if S % c:
        Sp = -(-S // c) * c
        pad = ((0, 0), (0, Sp - S), (0, 0), (0, 0))
        q, k, v = (jnp.pad(t, pad) for t in (q, k, v))
        kv_len = S
    # Keep heads TP-sharded only when BOTH q and kv head counts divide by the
    # TP degree — otherwise local GQA head-group pairing would be wrong, so
    # fall back to replicated heads inside the ring.
    tp = mesh.shape.get(head_axis, 1)
    h_ax = head_axis if (tp > 1 and q.shape[2] % tp == 0
                         and k.shape[2] % tp == 0) else None

    def local_fn(q, k, v):
        idx = jax.lax.axis_index(axis)
        s_local = q.shape[1]
        q_offset = idx * s_local

        if ring_impl == "allgather":
            # One gather, one masked block. The named scope is load-bearing:
            # graftlint GL105 sanctions attention-issued collectives in the
            # lowered step by scope tag (attn_ring_allgather).
            with jax.named_scope("attn_ring_allgather"):
                kg = collectives.all_gather(k, axis, axis_index=1)
                vg = collectives.all_gather(v, axis, axis_index=1)
            bias = None
            if kv_len is not None:
                k_pos = jnp.arange(kg.shape[1])
                bias = jnp.where(k_pos < kv_len, 0.0, NEG_INF)[
                    None, None, None, :]
            return dot_product_attention(q, kg, vg, causal=causal, bias=bias,
                                         q_offset=q_offset)

        B, _, H, D = q.shape
        m = jnp.full((B, H, s_local), NEG_INF, jnp.float32)
        l = jnp.zeros((B, H, s_local), jnp.float32)
        acc = jnp.zeros((B, H, s_local, D), jnp.float32)

        def compute(step, m, l, acc, kb, vb):
            # K/V block currently held came from shard (idx - step) mod c.
            src = (idx - step) % c

            def do(ops):
                m, l, acc, kb, vb = ops
                return _online_block(q, kb, vb, causal=causal,
                                     q_offset=q_offset,
                                     k_offset=src * s_local,
                                     m=m, l=l, acc=acc, kv_len=kv_len)

            if not causal:
                return do((m, l, acc, kb, vb))
            # Causal: a block from a strictly-later shard is entirely in
            # this shard's future — skip its QK/PV work (contribution is
            # identically zero; the block still circulates in lockstep).
            return jax.lax.cond(src <= idx, do,
                                lambda ops: (ops[0], ops[1], ops[2]),
                                (m, l, acc, kb, vb))

        def body(step, carry):
            m, l, acc, kb, vb = carry
            m, l, acc = compute(step, m, l, acc, kb, vb)
            # Rotate: send our block to the next shard, receive previous.
            # Scope sanctions the collective-permute for graftlint GL105.
            with jax.named_scope("attn_ring_ppermute"):
                kb = collectives.ring_shift(kb, axis)
                vb = collectives.ring_shift(vb, axis)
            return m, l, acc, kb, vb

        # Final step outside the loop: its rotation would be discarded, and
        # 1/c of the schedule's ICI traffic with it.
        m, l, acc, kb, vb = jax.lax.fori_loop(0, c - 1, body, (m, l, acc, k, v))
        m, l, acc = compute(c - 1, m, l, acc, kb, vb)
        out = acc / jnp.maximum(l, 1e-30)[..., None]   # [B,H,Q,D]
        return jnp.transpose(out, (0, 2, 1, 3)).astype(q.dtype)

    spec = P(batch_axes, axis, h_ax, None)
    out = jax.shard_map(local_fn, mesh=mesh, in_specs=(spec, spec, spec),
                        out_specs=spec, check_vma=False)(q, k, v)
    return out[:, :S] if kv_len is not None else out


def zigzag_ring_attention(
    q: jax.Array, k: jax.Array, v: jax.Array,
    *,
    mesh: Mesh,
    axis: str = "context",
    causal: bool = True,
    batch_axes=("data", "fsdp"),
    head_axis: str = "model",
) -> jax.Array:
    """Load-balanced causal ring attention (zigzag chunk placement).

    A contiguous ring under a causal mask is imbalanced: shard 0's queries
    only ever attend to 1/c of the KV while shard c-1 attends to all of it,
    and because the ring rotates in lockstep every tick runs at the slowest
    shard's pace. Zigzag placement splits the sequence into ``2c`` chunks
    and gives shard ``i`` the pair ``(i, 2c-1-i)`` — one early + one late
    chunk — so every shard does ~the same causal work on every tick
    (the Llama-3 context-parallel schedule).

    Chunks are re-laid out with two static ``ppermute``s (one per local
    half), rung for ``c`` steps over the paired KV halves with 4 sub-block
    online-softmax updates per tick (fully-masked sub-blocks are skipped
    with ``lax.cond``), then outputs are permuted back to the contiguous
    layout. Exactly equals full attention (oracle-tested, incl. grads).
    """
    c = mesh.shape[axis]
    if c == 1:
        return dot_product_attention(q, k, v, causal=causal)
    if not causal or q.shape[1] % (2 * c) != 0:
        # Balance only matters under a causal mask; odd half-chunks fall
        # back to the contiguous schedule.
        return ring_attention(q, k, v, mesh=mesh, axis=axis, causal=causal,
                              batch_axes=batch_axes, head_axis=head_axis)
    tp = mesh.shape.get(head_axis, 1)
    h_ax = head_axis if (tp > 1 and q.shape[2] % tp == 0
                         and k.shape[2] % tp == 0) else None

    # Static chunk routing. Contiguous shard i holds chunks (2i, 2i+1);
    # zigzag shard j holds {j, 2c-1-j}: slot A gets chunk j for even j else
    # 2c-1-j, slot B the other one (parity falls out of the permutation).
    def dest_first(i):
        return 2 * i if 2 * i < c else 2 * c - 1 - 2 * i

    def dest_second(i):
        return 2 * i + 1 if 2 * i + 1 < c else 2 * c - 2 - 2 * i

    perm_a = [(i, dest_first(i)) for i in range(c)]
    perm_b = [(i, dest_second(i)) for i in range(c)]
    inv_a = [(d, s) for s, d in perm_a]
    inv_b = [(d, s) for s, d in perm_b]

    from pytorch_distributed_training_example_tpu.ops import collectives

    def local_fn(q, k, v):
        idx = jax.lax.axis_index(axis)
        L = q.shape[1]
        h = L // 2
        B, _, H, D = q.shape

        def scatter(x):
            # Scoped for graftlint GL105 (sanctioned attention collectives).
            with jax.named_scope("attn_ring_ppermute"):
                xa = jax.lax.ppermute(x[:, :h], axis, perm_a)
                xb = jax.lax.ppermute(x[:, h:], axis, perm_b)
            return xa, xb

        (qa, qb), (ka, kb), (va, vb) = scatter(q), scatter(k), scatter(v)

        def chunk_ids(j):
            a = jnp.where(j % 2 == 0, j, 2 * c - 1 - j)
            return a, (2 * c - 1 - j) - a + j  # the partner chunk

        my_a, my_b = chunk_ids(idx)
        Hq = q.shape[2]
        state = [
            (jnp.full((B, Hq, h), NEG_INF, jnp.float32),
             jnp.zeros((B, Hq, h), jnp.float32),
             jnp.zeros((B, Hq, h, D), jnp.float32))
            for _ in range(2)
        ]

        def compute(step, sa, sb, ka, kb, va, vb):
            src = (idx - step) % c
            src_a, src_b = chunk_ids(src)

            def update(s, q_half, q_chunk, k_half, v_half, k_chunk):
                m, l, acc = s
                active = k_chunk <= q_chunk  # causal: skip all-future chunks

                def do(ops):
                    m, l, acc, kh, vh = ops
                    return _online_block(
                        q_half, kh, vh, causal=True,
                        q_offset=q_chunk * h, k_offset=k_chunk * h,
                        m=m, l=l, acc=acc)

                return jax.lax.cond(active, do,
                                    lambda ops: (ops[0], ops[1], ops[2]),
                                    (m, l, acc, k_half, v_half))

            for k_half, v_half, k_chunk in ((ka, va, src_a), (kb, vb, src_b)):
                sa = update(sa, qa, my_a, k_half, v_half, k_chunk)
                sb = update(sb, qb, my_b, k_half, v_half, k_chunk)
            return sa, sb

        def body(step, carry):
            sa, sb, ka, kb, va, vb = carry
            sa, sb = compute(step, sa, sb, ka, kb, va, vb)
            with jax.named_scope("attn_ring_ppermute"):
                ka = collectives.ring_shift(ka, axis)
                kb = collectives.ring_shift(kb, axis)
                va = collectives.ring_shift(va, axis)
                vb = collectives.ring_shift(vb, axis)
            return sa, sb, ka, kb, va, vb

        # Last step hoisted out of the loop (its rotation would be waste).
        sa, sb, ka, kb, va, vb = jax.lax.fori_loop(
            0, c - 1, body, (state[0], state[1], ka, kb, va, vb))
        sa, sb = compute(c - 1, sa, sb, ka, kb, va, vb)

        def finish(s):
            m, l, acc = s
            out = acc / jnp.maximum(l, 1e-30)[..., None]
            return jnp.transpose(out, (0, 2, 1, 3)).astype(q.dtype)

        # Send each output half back to its contiguous home.
        with jax.named_scope("attn_ring_ppermute"):
            oa = jax.lax.ppermute(finish(sa), axis, inv_a)
            ob = jax.lax.ppermute(finish(sb), axis, inv_b)
        return jnp.concatenate([oa, ob], axis=1)

    spec = P(batch_axes, axis, h_ax, None)
    return jax.shard_map(local_fn, mesh=mesh, in_specs=(spec, spec, spec),
                         out_specs=spec, check_vma=False)(q, k, v)


# ---------------------------------------------------------------------------
# Ulysses (all-to-all sequence<->head) — SURVEY.md §2c "Ulysses"
# ---------------------------------------------------------------------------


def ulysses_attention(
    q: jax.Array, k: jax.Array, v: jax.Array,
    *,
    mesh: Mesh,
    axis: str = "context",
    causal: bool = False,
    batch_axes=("data", "fsdp"),
    head_axis: str = "model",
) -> jax.Array:
    """All-to-all context parallelism: trade sequence-sharding for
    head-sharding, run full-sequence attention per (local) head, trade back.

    Requires the per-TP-shard head count to divide by the context shards
    (GQA KV heads are broadcast up first when smaller than the shard count).
    """
    c = mesh.shape[axis]
    if c == 1:
        return dot_product_attention(q, k, v, causal=causal)
    tp = mesh.shape.get(head_axis, 1)
    h_ax = head_axis if (tp > 1 and q.shape[2] % tp == 0
                         and k.shape[2] % tp == 0) else None
    local_heads = q.shape[2] // (tp if h_ax else 1)
    if local_heads % c:
        # Head-pad so each TP shard's heads divide the context shards
        # (r3 hard-errored here; README "Known limits"). Zero heads attend
        # uniformly, their outputs are sliced off, and the slice's vjp
        # drops their gradient contributions — exactness is tested. Cost:
        # the padded heads do full attention compute (pad/H overhead).
        # The pad target is a multiple of tp*c regardless of whether H
        # divided tp before: this both keeps heads TP-sharded after the
        # pad (h_ax=None would replicate all heads across the model axis)
        # and guarantees the recursive call pads no further.
        H = q.shape[2]
        group = tp * c
        h_pad = -(-H // group) * group
        import logging

        logging.getLogger(__name__).warning(
            "ulysses_attention: %d heads not divisible by %s=%d%s; "
            "zero-padding to %d heads (+%.0f%% attention compute). Ring "
            "attention has no head constraint if this overhead matters.",
            H, axis, c, f" x {head_axis}={tp}" if tp > 1 else "", h_pad,
            100.0 * (h_pad - H) / H)
        k = _repeat_kv(k, H)
        v = _repeat_kv(v, H)
        pad = ((0, 0), (0, 0), (0, h_pad - H), (0, 0))
        out = ulysses_attention(
            jnp.pad(q, pad), jnp.pad(k, pad), jnp.pad(v, pad), mesh=mesh,
            axis=axis, causal=causal, batch_axes=batch_axes,
            head_axis=head_axis)
        return out[:, :, :H]

    def local_fn(q, k, v):
        # The named scope is load-bearing: graftlint GL105 sanctions
        # all-to-all ops in the lowered step by scope tag (moe_* or
        # attn_ulysses_a2a) — an untagged a2a is flagged as unattributable.
        # [B, S/c, H', D] -> all_to_all -> [B, S, H'/c, D]
        def seq_to_heads(x):
            if x.shape[2] % c:   # GQA KV with fewer heads than shards
                x = _repeat_kv(x, c)
            with jax.named_scope("attn_ulysses_a2a"):
                return jax.lax.all_to_all(x, axis, split_axis=2,
                                          concat_axis=1, tiled=True)

        qh, kh, vh = seq_to_heads(q), seq_to_heads(k), seq_to_heads(v)
        out = dot_product_attention(qh, kh, vh, causal=causal)
        # [B, S, H'/c, D] -> back to [B, S/c, H', D]
        with jax.named_scope("attn_ulysses_a2a"):
            return jax.lax.all_to_all(out, axis, split_axis=1, concat_axis=2,
                                      tiled=True)

    spec = P(batch_axes, axis, h_ax, None)
    return jax.shard_map(local_fn, mesh=mesh, in_specs=(spec, spec, spec),
                         out_specs=spec, check_vma=False)(q, k, v)


def attention(
    q, k, v, *, causal=False, impl: str = "auto",
    mesh: Mesh | None = None, context_axis: str = "context",
    batch_axes=("data", "fsdp"),
):
    """Dispatcher used by the models.

    impl: 'auto' | 'xla' | 'flash' | 'ring' | 'ring_zigzag' |
    'ring_allgather' | 'ulysses'. 'auto' picks ring when the ambient mesh
    has a context axis > 1, the Pallas flash kernel on TPU for long
    sequences, else plain XLA. Causal rings use the load-balanced zigzag
    schedule when the sequence divides into 2*ctx chunks (see
    :func:`zigzag_ring_attention`). 'ring_allgather' is the all-gather-KV
    fallback for backends where the ppermute ring doesn't lower or overlap
    (see :func:`ring_attention` ``ring_impl``).
    """
    from pytorch_distributed_training_example_tpu.core import mesh as mesh_lib

    mesh = mesh or mesh_lib.current_mesh()
    ctx = mesh.shape.get(context_axis, 1) if mesh is not None else 1
    if impl == "auto":
        if ctx > 1:
            impl = "ring_zigzag" if causal else "ring"
        elif _flash_eligible(q, k):
            impl = "flash"
        elif _padded_flash_eligible(q, k, explicit=False):
            return padded_flash_attention(q, k, v, causal=causal)
        else:
            impl = "xla"
    elif impl in ("ring", "ring_zigzag", "ring_allgather",
                  "ulysses") and ctx == 1:
        # No context axis to parallelize over (includes init-time tracing
        # outside use_mesh): all collapse to plain attention.
        impl = "xla"
    if impl == "ring_zigzag":
        # Self-falls-back to contiguous when non-causal or indivisible.
        return zigzag_ring_attention(q, k, v, mesh=mesh, axis=context_axis,
                                     causal=causal, batch_axes=batch_axes)
    if impl == "ring":
        # Explicit 'ring' = the contiguous schedule (so the two can be
        # benchmarked against each other); only 'auto' upgrades causal runs.
        return ring_attention(q, k, v, mesh=mesh, axis=context_axis,
                              causal=causal, batch_axes=batch_axes)
    if impl == "ring_allgather":
        return ring_attention(q, k, v, mesh=mesh, axis=context_axis,
                              causal=causal, batch_axes=batch_axes,
                              ring_impl="allgather")
    if impl == "ulysses":
        return ulysses_attention(q, k, v, mesh=mesh, axis=context_axis,
                                 causal=causal, batch_axes=batch_axes)
    if impl == "flash":
        if not _flash_eligible(q, k, explicit=True):
            if _padded_flash_eligible(q, k):
                return padded_flash_attention(q, k, v, causal=causal)
            import logging

            logging.getLogger(__name__).warning(
                "attn_impl='flash' not eligible for shape q=%s k=%s on %s "
                "(needs seq %% 512 == 0 or a VMEM-fitting padded one-shot "
                "plan, head_dim in {64,128,256}, TPU); falling back to XLA "
                "attention", q.shape, k.shape, jax.default_backend())
            return dot_product_attention(q, k, v, causal=causal,
                                         lowp_residual=_lowp(q))
        from pytorch_distributed_training_example_tpu.ops import flash_attention

        return flash_attention.flash_attention(q, k, v, causal=causal)
    return dot_product_attention(q, k, v, causal=causal,
                                 lowp_residual=_lowp(q))


def _lowp(q) -> bool:
    """Model-path policy for the low-precision probs residual: OFF by
    default — a measured NEGATIVE result on v5e (r5, paired A/B at
    ViT-B/16: 70.4 ms/step vs 67.6 exact; PROFILE_VIT.md r5 addendum).
    Halving the f32 probs residual's bytes loses to what XLA gives up
    around the opaque custom-vjp boundary (the softmax-VJP chain no
    longer fuses into the PV-matmul backward). PDTX_LOWP_RESIDUAL=1
    enables it for low-precision dtypes — kept because the balance may
    flip on bandwidth-poorer chips or bigger S where the residual
    dominates harder."""
    import os

    if not os.environ.get("PDTX_LOWP_RESIDUAL"):
        return False
    return q.dtype in (jnp.bfloat16, jnp.float16)


PAD_MULTIPLE = 64  # tile granularity shared by pad + eligibility below


def _round_up(n: int, multiple: int) -> int:
    return -(-n // multiple) * multiple


def padded_flash_attention(q, k, v, *, causal=False,
                           multiple: int = PAD_MULTIPLE):
    """Flash attention for non-tile-aligned S via padding + key masking.

    ViT-B/16's 197 tokens (and any sequence the block kernels can't tile)
    are zero-padded up to the next ``multiple``; the one-shot kernel masks
    padded keys with ``kv_len`` so softmax never attends to them, and the
    padded query rows are sliced away (their cotangents are zero, so the
    extra rows contribute nothing to gradients). Pays (Sp/S)^2 extra
    attention FLOPs — at ViT's 197->256 that is +69% on a term that is
    ~4% of model FLOPs, far cheaper than XLA attention's unfused softmax
    passes at these shapes (BENCH_FLASH_MICRO.json: one-shot 2.8x XLA).
    """
    from pytorch_distributed_training_example_tpu.ops import flash_attention

    S = q.shape[1]
    if k.shape[1] != S:
        raise ValueError(
            f"padded_flash_attention needs Sq == Skv (kv_len masking is "
            f"derived from q's length); got Sq={S}, Skv={k.shape[1]}")
    Sp = _round_up(S, multiple)
    if Sp != S:
        pad = ((0, 0), (0, Sp - S), (0, 0), (0, 0))
        q, k, v = (jnp.pad(t, pad) for t in (q, k, v))
    out = flash_attention.flash_attention(
        q, k, v, causal, flash_attention.DEFAULT_BLOCK_Q,
        flash_attention.DEFAULT_BLOCK_KV, "auto", S if Sp != S else None)
    return out[:, :S] if Sp != S else out


def _padded_flash_eligible(q, k, multiple: int = PAD_MULTIPLE,
                           explicit: bool = True) -> bool:
    from pytorch_distributed_training_example_tpu.ops import flash_attention

    if jax.default_backend() in ("cpu",) or q.shape[-1] not in (64, 128, 256):
        return False
    if q.shape[1] != k.shape[1]:  # cross-shard ring chunks: keep simple
        return False
    Sp = _round_up(q.shape[1], multiple)
    if not explicit and Sp < 1024:
        # Same threshold as _flash_eligible's auto mode, re-validated for
        # the padded path: ViT-B/16 (197->256) measured 690 img/s padded
        # one-shot vs 730 img/s XLA — below ~1024 tokens XLA's fused
        # attention wins and padding FLOPs only add to that.
        return False
    H, D = q.shape[2], q.shape[3]
    return (flash_attention._oneshot_plan(H, Sp, Sp, D) is not None
            and flash_attention._oneshot_plan(H, Sp, Sp, D, bwd=True)
            is not None)


def _flash_eligible(q, k, explicit: bool = False) -> bool:
    """Whether the Pallas kernel can (explicit) / should (auto) run.

    ``auto`` additionally requires seq >= 1024 — below that the XLA fusion
    is already fast and kernel launch overhead dominates; an explicit
    ``impl='flash'`` only needs the kernel's hard shape constraints.
    """
    on_tpu = jax.default_backend() not in ("cpu",)
    seq_ok = q.shape[1] % 512 == 0 and k.shape[1] % 512 == 0
    if not explicit:
        seq_ok = seq_ok and q.shape[1] >= 1024
    return on_tpu and seq_ok and q.shape[-1] in (64, 128, 256)
