"""Ragged grouped expert matmul (Pallas TPU): the dropless-MoE kernel.

``gmm(x [Tk, d], w [E, d, f], group_starts [E], group_counts [E]) -> [Tk, f]``
computes ``out[r] = x[r] @ w[e]`` for every row ``r`` of expert ``e``'s
contiguous segment ``[starts[e], starts[e] + counts[e])`` of the sorted
token layout that ``routing_stats()``'s stable argsort already produces
(parallel/moe.py). This is the MegaBlocks reformulation of the expert
FFN: no ``[E, C, d]`` capacity buffer is ever materialized and no token
is dropped — the kernel tiles the token dimension and a scalar-prefetched
per-tile expert index steers each tile's ``[d, bf]`` weight block straight
out of the stacked ``[E, d, f]`` weights (the BlockSpec index_map reads
the prefetched tile->expert table, so weight traffic is one block per
tile, reused across a segment's consecutive tiles).

Raggedness is handled by a tile-aligned relayout with STATIC shapes:
each expert's segment is padded up to a whole number of ``bt``-row tiles
(empty experts keep one all-padding tile so every expert's backward
weight block is visited and zero-initialized). The padded row count is
bounded by ``ceil(Tk/bt)*bt + E*bt`` independent of any capacity factor,
so the relayout is two O(Tk·d) gathers (in, out) against int32 index
vectors built from the segment offsets — the same compact-index
machinery the sort dispatch uses, never an ``[E, C]`` slot table.

Backward is a ``custom_vjp``:

- ``dx = gmm(dout, w^T)`` over the identical padded layout (the ISSUE's
  "gmm against transposed weights" — the swap of the weight's last two
  axes is left to XLA),
- ``dw[e] = sum over expert e's segment of x_r^T dout_r`` via a second
  kernel whose ``[1, d, bf]`` output block is a revisited accumulator:
  the grid walks token tiles innermost in segment order (sequential
  ``"arbitrary"`` dimension semantics), a prefetched first-tile flag
  zero-initializes each expert's block, and every tile of that expert
  accumulates into it before the block index moves on — segment-wise
  accumulation with no atomics and no ``[E, Tk]`` masks.

On non-TPU backends both kernels run in interpret mode (numerically the
same program), so CPU tests and dryruns validate the real kernel bodies —
the same ``pallas_compat`` route the flash and fused-router kernels take.
fp32 accumulation everywhere (``preferred_element_type``); outputs are
cast to the input dtype, gradients to the primal dtypes. Tile sizes are
powers of two down to 8 rows — Mosaic-friendly at bench shapes; lane-dim
(128) padding of small test shapes is interpret-mode territory and part
of the chip A/B, not correctness (PROFILE_MOE.md r14 hooks).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from pytorch_distributed_training_example_tpu.ops import pallas_compat  # noqa: F401


def _block_rows(n_rows: int, num_experts: int) -> int:
    """Power-of-two token-tile height balancing grid length against the
    worst-case padding ``E * bt`` (every expert rounds up at most one
    partial tile): the tile is capped so padding stays within ~1/8 of the
    real rows. Tiny test shapes bottom out at 8-row tiles (mostly-padding
    layouts are interpret-mode territory); the llama_moe bench shape
    (kT=16384, E=8) gets 256-row tiles — 12.5% worst-case padding instead
    of the 25% a 512-row tile costs, at twice the grid length. 512 stays
    the hard ceiling (MXU-friendly multiples of 128 beyond that buy no
    reuse: the weight block is already resident across a segment's tiles).
    """
    E = max(num_experts, 1)
    target = max(n_rows // (8 * E), 8)
    bt = 8
    while bt * 2 <= min(target, 512):
        bt *= 2
    return bt


def _block_cols(n: int) -> int:
    """Largest nice power-of-two column block; odd widths get one block."""
    for bc in (512, 256, 128, 64, 32, 16, 8):
        if n % bc == 0:
            return bc
    return n


def _padded_layout(group_starts, group_counts, n_rows: int,
                   num_experts: int, bt: int):
    """Tile-aligned relayout of the ragged segments, static shapes.

    Returns ``(tile_expert [G], tile_first [G], src [G*bt], dst [n_rows])``
    (all int32): padded row ``r`` reads input row ``src[r]`` (``n_rows`` =
    the appended zero row), tile ``g`` multiplies expert ``tile_expert[g]``'s
    weights (``tile_first[g]`` marks the expert's first tile — the backward
    accumulator init), and logical output row ``j`` reads padded row
    ``dst[j]``. ``G = ceil(n_rows/bt) + num_experts`` is a static bound on
    ``sum(max(ceil(counts/bt), 1))`` — every expert rounds up at most one
    partial tile and empty experts keep one tile each.
    """
    E = num_experts
    G = -(-n_rows // bt) + E
    tiles_per_e = jnp.maximum(-(-group_counts // bt), 1)          # [E]
    tile_starts = jnp.concatenate(
        [jnp.zeros((1,), jnp.int32),
         jnp.cumsum(tiles_per_e)[:-1].astype(jnp.int32)])         # [E]
    tile_ids = jnp.arange(G, dtype=jnp.int32)
    tile_expert = (jnp.searchsorted(tile_starts, tile_ids, side="right")
                   .astype(jnp.int32) - 1)                        # [G]
    tile_first = (tile_ids == tile_starts[tile_expert]).astype(jnp.int32)

    padded_starts = tile_starts * bt                              # [E]
    r = jnp.arange(G * bt, dtype=jnp.int32)
    e_r = tile_expert[r // bt]
    off = r - padded_starts[e_r]
    src = jnp.where(off < group_counts[e_r], group_starts[e_r] + off,
                    n_rows).astype(jnp.int32)

    j = jnp.arange(n_rows, dtype=jnp.int32)
    # Owner of logical row j: highest expert with start <= j. Duplicate
    # starts (empty experts) resolve to the non-empty owner because empty
    # segments have zero width.
    e_j = (jnp.searchsorted(group_starts, j, side="right")
           .astype(jnp.int32) - 1)
    dst = (padded_starts[e_j] + (j - group_starts[e_j])).astype(jnp.int32)
    return tile_expert, tile_first, src, dst


def _gmm_kernel(te_ref, x_ref, w_ref, out_ref):
    del te_ref  # consumed by the index_maps
    out_ref[...] = jax.lax.dot_general(
        x_ref[...], w_ref[0],
        (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32).astype(out_ref.dtype)


def _gmm_call(x_pad, w, tile_expert, bt: int, out_dtype):
    Tp, d = x_pad.shape
    E, _, f = w.shape
    bf = _block_cols(f)
    return pl.pallas_call(
        _gmm_kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(f // bf, Tp // bt),
            in_specs=[
                pl.BlockSpec((bt, d), lambda jc, g, te: (g, 0)),
                pl.BlockSpec((1, d, bf), lambda jc, g, te: (te[g], 0, jc)),
            ],
            out_specs=pl.BlockSpec((bt, bf), lambda jc, g, te: (g, jc)),
        ),
        out_shape=jax.ShapeDtypeStruct((Tp, f), out_dtype),
        # Sequential grid: consecutive same-expert tiles keep the weight
        # block resident instead of re-fetching it.
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("arbitrary", "arbitrary")),
        # Non-TPU backends run the identical kernel body interpreted — the
        # CPU-validation route (pallas_compat) the flash kernels use.
        interpret=jax.default_backend() != "tpu",
    )(tile_expert, x_pad, w)


def _gmm_dw_kernel(te_ref, tf_ref, x_ref, g_ref, dw_ref):
    del te_ref
    g_idx = pl.program_id(1)

    # First tile of this expert's segment (per column block): the [1, d, bf]
    # output block is revisited by every later tile of the segment, so
    # zero it exactly once before accumulating.
    @pl.when(tf_ref[g_idx] == 1)
    def _init():
        dw_ref[...] = jnp.zeros_like(dw_ref)

    dw_ref[...] += jax.lax.dot_general(
        x_ref[...], g_ref[...],
        (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)[None].astype(dw_ref.dtype)


def _gmm_dw_call(x_pad, g_pad, tile_expert, tile_first, num_experts: int,
                 bt: int):
    Tp, d = x_pad.shape
    f = g_pad.shape[1]
    bf = _block_cols(f)
    return pl.pallas_call(
        _gmm_dw_kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            # Token tiles are the INNER grid dim: for each column block the
            # tiles of one expert are visited consecutively (the padded
            # layout is segment-sorted), which is what makes the revisited
            # dw block a valid accumulator under sequential semantics.
            grid=(f // bf, Tp // bt),
            in_specs=[
                pl.BlockSpec((bt, d), lambda jc, g, te, tf: (g, 0)),
                pl.BlockSpec((bt, bf), lambda jc, g, te, tf: (g, jc)),
            ],
            out_specs=pl.BlockSpec(
                (1, d, bf), lambda jc, g, te, tf: (te[g], 0, jc)),
        ),
        out_shape=jax.ShapeDtypeStruct((num_experts, d, f), jnp.float32),
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("arbitrary", "arbitrary")),
        interpret=jax.default_backend() != "tpu",
    )(tile_expert, tile_first, x_pad, g_pad)


def _pad_rows(x, src):
    """Gather rows into the tile-aligned layout; index n_rows reads zeros."""
    return jnp.concatenate([x, jnp.zeros((1, x.shape[1]), x.dtype)])[src]


@jax.custom_vjp
def _gmm_padded(x_pad, w, tile_expert, tile_first):
    """Kernel entry over the PADDED layout: [Tp, d] -> [Tp, f] (no relayout).

    The tile height is implied by the shapes (``bt = Tp // G``). Padded rows
    are zero on the way in and garbage-free on the way out (zero rows times
    weights are zero), so callers can chain padded-space ops — the grouped
    FFN runs up-proj -> gelu -> down-proj entirely in this layout and pays
    for ONE relayout round trip instead of one per matmul.
    """
    bt = x_pad.shape[0] // tile_expert.shape[0]
    return _gmm_call(x_pad, w, tile_expert, bt, x_pad.dtype)


def _gmm_padded_fwd(x_pad, w, tile_expert, tile_first):
    return _gmm_padded(x_pad, w, tile_expert, tile_first), (
        x_pad, w, tile_expert, tile_first)


def _gmm_padded_bwd(res, dout_pad):
    x_pad, w, tile_expert, tile_first = res
    bt = x_pad.shape[0] // tile_expert.shape[0]
    dx_pad = _gmm_call(dout_pad, jnp.swapaxes(w, 1, 2), tile_expert, bt,
                       x_pad.dtype)
    dw = _gmm_dw_call(x_pad, dout_pad, tile_expert, tile_first,
                      w.shape[0], bt).astype(w.dtype)
    zeros = functools.partial(np.zeros, dtype=jax.dtypes.float0)
    return dx_pad, dw, zeros(tile_expert.shape), zeros(tile_first.shape)


_gmm_padded.defvjp(_gmm_padded_fwd, _gmm_padded_bwd)


def grouped_ffn(x, w_up, w_down, group_starts, group_counts):
    """Full grouped expert MLP: gelu(x @ w_up[e]) @ w_down[e] per segment.

    Composition of two ``gmm``s that stays in the tile-padded layout across
    the activation, so the mid-FFN unpad/re-pad gathers (and their
    transposes in the backward) vanish — the relayout is paid once per FFN
    instead of once per matmul. Same math as ``ExpertFFN``'s einsums: fp32
    accumulation, gelu in the compute dtype (gelu keeps the padding rows at
    exactly zero). The boundary gathers differentiate through standard AD;
    the kernels through ``_gmm_padded``'s custom_vjp.
    """
    Tk = x.shape[0]
    E = w_up.shape[0]
    bt = _block_rows(Tk, E)
    tile_expert, tile_first, src, dst = _padded_layout(
        group_starts, group_counts, Tk, E, bt)
    x_pad = _pad_rows(x, src)
    h_pad = _gmm_padded(x_pad, w_up, tile_expert, tile_first)
    h_pad = jax.nn.gelu(h_pad)
    out_pad = _gmm_padded(h_pad, w_down, tile_expert, tile_first)
    return out_pad[dst]


def _gmm_impl(x, w, group_starts, group_counts):
    Tk, d = x.shape
    E = w.shape[0]
    bt = _block_rows(Tk, E)  # static (shape-derived) — recomputed in bwd
    tile_expert, tile_first, src, dst = _padded_layout(
        group_starts, group_counts, Tk, E, bt)
    out_pad = _gmm_call(_pad_rows(x, src), w, tile_expert, bt, x.dtype)
    return out_pad[dst], (tile_expert, tile_first, src, dst)


@jax.custom_vjp
def gmm(x, w, group_starts, group_counts):
    """Grouped/ragged expert matmul over contiguous per-expert segments.

    ``out[r] = x[r] @ w[e]`` for rows ``r`` in segment
    ``[group_starts[e], group_starts[e] + group_counts[e])``; segments must
    tile ``[0, Tk)`` in expert order (``group_starts`` = exclusive cumsum of
    ``group_counts``, ``sum == Tk``) — exactly what ``routing_stats()``
    hands out. fp32 accumulation, output in ``x.dtype``. Differentiable in
    ``x`` and ``w``; the integer segment offsets get float0 cotangents.
    """
    out, _ = _gmm_impl(x, w, group_starts, group_counts)
    return out


def _gmm_fwd(x, w, group_starts, group_counts):
    out, layout = _gmm_impl(x, w, group_starts, group_counts)
    return out, (x, w, group_starts, group_counts, layout)


def _gmm_bwd(res, dout):
    x, w, group_starts, group_counts, layout = res
    tile_expert, tile_first, src, dst = layout
    bt = _block_rows(x.shape[0], w.shape[0])
    dout_pad = _pad_rows(dout, src)
    # dx: the same grouped matmul against the transposed weight blocks,
    # reusing the tile layout (dout rows live in the same segments as x).
    dx_pad = _gmm_call(dout_pad, jnp.swapaxes(w, 1, 2), tile_expert, bt,
                       x.dtype)
    dx = dx_pad[dst]
    # dw: segment-wise accumulation — padded rows are zero on both sides,
    # so they contribute nothing; empty experts' single all-padding tile
    # zero-initializes their block.
    dw = _gmm_dw_call(_pad_rows(x, src), dout_pad, tile_expert, tile_first,
                      w.shape[0], bt).astype(w.dtype)
    zeros = functools.partial(np.zeros, dtype=jax.dtypes.float0)
    return dx, dw, zeros(group_starts.shape), zeros(group_counts.shape)


gmm.defvjp(_gmm_fwd, _gmm_bwd)
