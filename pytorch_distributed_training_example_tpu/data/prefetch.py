"""Host->HBM prefetch: build globally-sharded batches ahead of the step.

Reference parity (SURVEY.md §2b N5/N7): torch overlaps H2D with compute via
pinned memory + CUDA streams. On TPU, ``jax.device_put`` is asynchronous and
the step itself is dispatched ahead, so a small look-ahead window (putting
the next batch while the current step runs) gives the same overlap. Each host
contributes its local slice; ``jax.make_array_from_process_local_data``
assembles the logical global batch across hosts.
"""

from __future__ import annotations

import collections
from typing import Iterable, Iterator

import jax
import numpy as np
from jax.sharding import NamedSharding


def pad_batch(batch: dict, target: int) -> dict:
    """Pad a short final batch up to ``target`` rows and attach a 0/1 ``mask``.

    Keeps every batch the same (static) shape — one compiled program, no
    per-remainder recompiles — while eval metrics stay exact via the mask.
    """
    n = next(iter(batch.values())).shape[0]
    mask = batch.get("mask", np.ones(n, np.float32))
    if n == target:
        return {**batch, "mask": mask}
    if n > target:
        raise ValueError(f"batch of {n} exceeds target {target}")
    pad = target - n

    def pad_rows(x):
        reps = np.repeat(x[:1], pad, axis=0)
        return np.concatenate([x, reps], axis=0)

    out = {k: pad_rows(np.asarray(v)) for k, v in batch.items() if k != "mask"}
    out["mask"] = np.concatenate([mask, np.zeros(pad, np.float32)])
    return out


def shard_batch(batch: dict, sharding: NamedSharding) -> dict:
    """Turn a per-host numpy batch into a globally-sharded jax.Array batch."""

    def put(x):
        nd_sharding = sharding
        if x.ndim != len(sharding.spec):
            from jax.sharding import PartitionSpec as P

            spec = list(sharding.spec) + [None] * (x.ndim - len(sharding.spec))
            nd_sharding = NamedSharding(sharding.mesh, P(*spec[: max(x.ndim, 1)]))
        if jax.process_count() == 1:
            return jax.device_put(x, nd_sharding)
        return jax.make_array_from_process_local_data(nd_sharding, x)

    return {k: put(v) for k, v in batch.items()}


def device_prefetch(
    it: Iterable[dict], sharding: NamedSharding, lookahead: int = 2
) -> Iterator[dict]:
    """Yield sharded device batches, keeping ``lookahead`` in flight."""
    it = iter(it)
    buf: collections.deque = collections.deque()
    try:
        for _ in range(lookahead):
            buf.append(shard_batch(next(it), sharding))
    except StopIteration:
        pass
    while buf:
        out = buf.popleft()
        try:
            buf.append(shard_batch(next(it), sharding))
        except StopIteration:
            pass
        yield out
