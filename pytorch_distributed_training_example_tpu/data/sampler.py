"""Deterministic sharded index sampling — ``DistributedSampler`` equivalence.

Reference parity (SURVEY.md §2a #3): ``torch.utils.data.DistributedSampler``
gives each rank a disjoint, equally-sized slice of an epoch-seeded global
permutation, padding by wrap-around so all ranks take the same number of
steps, and reshuffles when the user calls ``set_epoch(e)``.

This implements exactly those semantics (property-tested in
``tests/test_sampler.py``: every index covered exactly once per epoch across
shards modulo padding; permutation changes with epoch; identical across
processes given the seed). On TPU the "rank" is a *host process*; chips below
a host receive their slice via the batch's ``NamedSharding``.
"""

from __future__ import annotations

import numpy as np


class ShardedSampler:
    def __init__(
        self,
        num_examples: int,
        num_shards: int = 1,
        shard_id: int = 0,
        shuffle: bool = True,
        seed: int = 0,
        drop_last: bool = False,
    ):
        if not 0 <= shard_id < num_shards:
            raise ValueError(f"shard_id {shard_id} out of range for {num_shards} shards")
        self.num_examples = num_examples
        self.num_shards = num_shards
        self.shard_id = shard_id
        self.shuffle = shuffle
        self.seed = seed
        self.drop_last = drop_last
        self.epoch = 0
        if drop_last:
            self.num_samples = num_examples // num_shards
        else:
            self.num_samples = -(-num_examples // num_shards)  # ceil
        self.total_size = self.num_samples * num_shards

    def set_epoch(self, epoch: int) -> None:
        """Reseed the permutation (reference: ``sampler.set_epoch(e)``)."""
        self.epoch = epoch

    def global_indices(self) -> np.ndarray:
        if self.shuffle:
            rng = np.random.default_rng((self.seed, self.epoch))
            order = rng.permutation(self.num_examples)
        else:
            order = np.arange(self.num_examples)
        if self.drop_last:
            return order[: self.total_size]
        if self.total_size > self.num_examples:  # pad by wrap-around
            order = np.concatenate([order, order[: self.total_size - self.num_examples]])
        return order

    def local_indices(self) -> np.ndarray:
        """This shard's slice: strided like the reference (rank::num_shards)."""
        return self.global_indices()[self.shard_id :: self.num_shards]

    def __iter__(self):
        return iter(self.local_indices().tolist())

    def __len__(self) -> int:
        return self.num_samples
