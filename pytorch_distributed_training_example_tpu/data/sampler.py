"""Deterministic sharded index sampling — ``DistributedSampler`` equivalence.

Reference parity (SURVEY.md §2a #3): ``torch.utils.data.DistributedSampler``
gives each rank a disjoint, equally-sized slice of an epoch-seeded global
permutation, padding by wrap-around so all ranks take the same number of
steps, and reshuffles when the user calls ``set_epoch(e)``.

This implements exactly those semantics (property-tested in
``tests/test_sampler.py``: every index covered exactly once per epoch across
shards modulo padding; permutation changes with epoch; identical across
processes given the seed). On TPU the "rank" is a *host process*; chips below
a host receive their slice via the batch's ``NamedSharding``.
"""

from __future__ import annotations

import numpy as np


class ShardedSampler:
    def __init__(
        self,
        num_examples: int,
        num_shards: int = 1,
        shard_id: int = 0,
        shuffle: bool = True,
        seed: int = 0,
        drop_last: bool = False,
    ):
        if not 0 <= shard_id < num_shards:
            raise ValueError(f"shard_id {shard_id} out of range for {num_shards} shards")
        self.num_examples = num_examples
        self.num_shards = num_shards
        self.shard_id = shard_id
        self.shuffle = shuffle
        self.seed = seed
        self.drop_last = drop_last
        self.epoch = 0
        if drop_last:
            self.num_samples = num_examples // num_shards
        else:
            self.num_samples = -(-num_examples // num_shards)  # ceil
        self.total_size = self.num_samples * num_shards

    def set_epoch(self, epoch: int) -> None:
        """Reseed the permutation (reference: ``sampler.set_epoch(e)``)."""
        self.epoch = epoch

    def global_indices(self) -> np.ndarray:
        if self.shuffle:
            rng = np.random.default_rng((self.seed, self.epoch))
            order = rng.permutation(self.num_examples)
        else:
            order = np.arange(self.num_examples)
        if self.drop_last:
            return order[: self.total_size]
        if self.total_size > self.num_examples:  # pad by wrap-around
            order = np.concatenate([order, order[: self.total_size - self.num_examples]])
        return order

    def local_indices(self) -> np.ndarray:
        """This shard's slice: strided like the reference (rank::num_shards)."""
        return self.global_indices()[self.shard_id :: self.num_shards]

    def __iter__(self):
        return iter(self.local_indices().tolist())

    def __len__(self) -> int:
        return self.num_samples


# ---------------------------------------------------------------------------
# World-size invariance helpers (elastic resume, utils/elastic.py).
#
# With drop_last=True and any shard count W dividing the global batch G,
# global batch b — the union over shards of each shard's batch b — is the
# contiguous slice perm[b*G:(b+1)*G] of the epoch permutation *as a set*,
# and the number of full global batches is floor(N/G) for every such W
# (proof in utils/elastic.py's module docstring). These helpers materialize
# the streams so the elastic remap can be asserted sample-exact.
# ---------------------------------------------------------------------------


def shard_batch_stream(num_examples: int, global_batch: int, num_shards: int,
                       shard_id: int, *, seed: int = 0, epoch: int = 0,
                       shuffle: bool = True) -> list[np.ndarray]:
    """The exact per-batch index stream ``DataLoader`` yields for one shard:
    the shard's strided slice, cut into per-shard batches, drop_last."""
    if global_batch % num_shards:
        raise ValueError(
            f"global_batch {global_batch} not divisible by {num_shards} shards")
    s = ShardedSampler(num_examples, num_shards, shard_id, shuffle=shuffle,
                       seed=seed, drop_last=True)
    s.set_epoch(epoch)
    idx = s.local_indices()
    per_shard = global_batch // num_shards
    n_full = len(idx) // per_shard
    return [idx[b * per_shard:(b + 1) * per_shard] for b in range(n_full)]


def global_sample_stream(num_examples: int, global_batch: int,
                         num_shards: int = 1, *, seed: int = 0,
                         epoch: int = 0, shuffle: bool = True) -> np.ndarray:
    """The epoch's flat consumed-sample stream: global batches concatenated
    in step order, each batch's members in canonical (sorted) order so the
    result is identical for every world size ``num_shards | global_batch``."""
    streams = [shard_batch_stream(num_examples, global_batch, num_shards, r,
                                  seed=seed, epoch=epoch, shuffle=shuffle)
               for r in range(num_shards)]
    n_batches = min(len(st) for st in streams)
    if not n_batches:
        return np.empty((0,), dtype=np.int64)
    return np.concatenate([
        np.sort(np.concatenate([st[b] for st in streams]))
        for b in range(n_batches)])
