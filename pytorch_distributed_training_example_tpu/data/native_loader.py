"""ctypes bindings for the C++ batch engine (native/batch_engine.cc).

The native path replaces the Python hot loop for memory-resident datasets:
sample gather + augmentation + normalization run on C++ threads with the GIL
released, double-buffered ahead of the train loop. Python keeps orchestration
(index order from :class:`ShardedSampler`) so determinism semantics are
identical to the pure-Python loader — tested against it bit-for-bit in
gather mode (augmentation RNG differs by design).

Falls back silently (``available() == False``) when no compiler is present;
the pure-Python loader is always the reference implementation.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading

import numpy as np

from pytorch_distributed_training_example_tpu.data import loader as loader_lib

_NATIVE_DIR = os.path.join(os.path.dirname(__file__), "..", "..", "native")
_LIB_PATH = os.path.abspath(os.path.join(_NATIVE_DIR, "libbatch_engine.so"))

_lib = None
_lib_lock = threading.Lock()


def _load() -> ctypes.CDLL | None:
    global _lib
    with _lib_lock:
        if _lib is not None:
            return _lib
        # ALWAYS invoke make (incremental: a no-op when the .so is newer than
        # batch_engine.cc). The library is untracked, so a checkout can leave
        # a stale binary with an old C ABI next to newer sources — loading it
        # would mis-stride gathers instead of erroring. An flock serializes
        # concurrent ranks (launch.py spawns N processes that would otherwise
        # race the compiler on the same output file).
        try:
            import fcntl

            with open(os.path.join(_NATIVE_DIR, ".build.lock"), "w") as lk:
                fcntl.flock(lk, fcntl.LOCK_EX)
                subprocess.run(["make", "-C", os.path.abspath(_NATIVE_DIR)],
                               check=True, capture_output=True, timeout=120)
        except Exception:
            if not os.path.exists(_LIB_PATH):
                return None  # no toolchain and no prebuilt library
        try:
            lib = ctypes.CDLL(_LIB_PATH)
        except OSError:
            return None
        try:
            lib.be_abi_version.restype = ctypes.c_int64
            if lib.be_abi_version() != 2:
                return None
        except AttributeError:  # pre-versioning binary
            return None
        lib.be_create_image.restype = ctypes.c_void_p
        lib.be_create_image.argtypes = [
            ctypes.c_void_p, ctypes.c_int64, ctypes.c_int64, ctypes.c_int64,
            ctypes.c_int64, ctypes.POINTER(ctypes.c_float),
            ctypes.POINTER(ctypes.c_float), ctypes.c_int, ctypes.c_int]
        lib.be_create_gather.restype = ctypes.c_void_p
        lib.be_create_gather.argtypes = [ctypes.c_void_p, ctypes.c_int64,
                                         ctypes.c_int64, ctypes.c_int,
                                         ctypes.c_int64]
        lib.be_create_jpeg.restype = ctypes.c_void_p
        lib.be_create_jpeg.argtypes = [
            ctypes.c_char_p, ctypes.POINTER(ctypes.c_int64), ctypes.c_int64,
            ctypes.c_int64, ctypes.POINTER(ctypes.c_float),
            ctypes.POINTER(ctypes.c_float), ctypes.c_int, ctypes.c_int]
        lib.be_decode_errors.restype = ctypes.c_int64
        lib.be_decode_errors.argtypes = [ctypes.c_void_p]
        lib.be_submit.argtypes = [ctypes.c_void_p, ctypes.c_int64,
                                  ctypes.POINTER(ctypes.c_int64),
                                  ctypes.c_int64, ctypes.c_void_p,
                                  ctypes.c_uint64]
        lib.be_wait.restype = ctypes.c_int
        lib.be_wait.argtypes = [ctypes.c_void_p, ctypes.c_int64, ctypes.c_int64]
        lib.be_destroy.argtypes = [ctypes.c_void_p]
        _lib = lib
        return _lib


def available() -> bool:
    return _load() is not None


class NativeBatchEngine:
    """Thin RAII wrapper; one engine per (dataset, mode)."""

    def __init__(self, handle, lib, sample_shape, out_dtype,
                 num_threads: int = 1, chunked: bool = False):
        self._handle = handle
        self._lib = lib
        self.sample_shape = sample_shape
        self.out_dtype = out_dtype
        self.num_threads = num_threads
        # One engine job runs on ONE worker thread; expensive per-sample work
        # (JPEG decode) must be submitted in per-thread chunks or parallelism
        # caps at the number of in-flight jobs instead of num_threads.
        self.chunked = chunked
        self._keepalive = []  # buffers the C++ side reads from

    @classmethod
    def image(cls, data_u8: np.ndarray, mean, std, augment: bool,
              num_threads: int = 2) -> "NativeBatchEngine":
        lib = _load()
        assert lib is not None
        data_u8 = np.ascontiguousarray(data_u8, np.uint8)
        n, h, w, c = data_u8.shape
        mean_arr = (ctypes.c_float * c)(*[float(m) for m in mean])
        std_arr = (ctypes.c_float * c)(*[float(s) for s in std])
        handle = lib.be_create_image(
            data_u8.ctypes.data_as(ctypes.c_void_p), n, h, w, c,
            mean_arr, std_arr, int(augment), num_threads)
        eng = cls(handle, lib, (h, w, c), np.float32, num_threads=num_threads)
        eng._keepalive.append(data_u8)
        return eng

    @classmethod
    def jpeg(cls, paths: list, image_size: int, mean, std, augment: bool,
             num_threads: int = 2) -> "NativeBatchEngine":
        """File-decode engine (native/batch_engine.cc jpeg mode).

        Raises RuntimeError when the library was built without libjpeg.
        """
        lib = _load()
        assert lib is not None
        encoded = [p.encode("utf-8") for p in paths]
        offsets = np.zeros(len(encoded) + 1, np.int64)
        np.cumsum([len(p) for p in encoded], out=offsets[1:])
        blob = b"".join(encoded)
        mean_arr = (ctypes.c_float * 3)(*[float(m) for m in mean])
        std_arr = (ctypes.c_float * 3)(*[float(s) for s in std])
        handle = lib.be_create_jpeg(
            blob, offsets.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            len(encoded), image_size, mean_arr, std_arr, int(augment),
            num_threads)
        if not handle:
            raise RuntimeError("batch engine built without libjpeg support")
        return cls(handle, lib, (image_size, image_size, 3), np.float32,
                   num_threads=num_threads, chunked=True)

    def decode_errors(self) -> int:
        return int(self._lib.be_decode_errors(self._handle))

    @classmethod
    def gather(cls, data: np.ndarray, num_threads: int = 2) -> "NativeBatchEngine":
        lib = _load()
        assert lib is not None
        data = np.ascontiguousarray(data)
        n = data.shape[0]
        sample_bytes = int(data.nbytes // n)
        handle = lib.be_create_gather(
            data.ctypes.data_as(ctypes.c_void_p), n, sample_bytes, num_threads,
            0)
        eng = cls(handle, lib, data.shape[1:], data.dtype,
                  num_threads=num_threads)
        eng._keepalive.append(data)
        return eng

    @classmethod
    def gather_windows(cls, flat: np.ndarray, num_samples: int,
                       window: int, stride: int,
                       num_threads: int = 2) -> "NativeBatchEngine":
        """Overlapping-window gather over a flat 1-D array (LM token files):
        sample i = flat[i*stride : i*stride + window]."""
        lib = _load()
        assert lib is not None
        assert flat.ndim == 1 and flat.flags["C_CONTIGUOUS"]
        item = flat.dtype.itemsize
        handle = lib.be_create_gather(
            flat.ctypes.data_as(ctypes.c_void_p), num_samples, window * item,
            num_threads, stride * item)
        eng = cls(handle, lib, (window,), flat.dtype, num_threads=num_threads)
        eng._keepalive.append(flat)
        return eng

    def submit(self, batch_id: int, indices: np.ndarray, out: np.ndarray,
               seed: int = 0):
        idx = np.ascontiguousarray(indices, np.int64)
        self._keepalive_batch = idx  # released after wait
        self._lib.be_submit(
            self._handle, batch_id,
            idx.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)), len(idx),
            out.ctypes.data_as(ctypes.c_void_p), seed & 0xFFFFFFFFFFFFFFFF)

    def wait(self, batch_id: int, timeout_ms: int = 60000):
        rc = self._lib.be_wait(self._handle, batch_id, timeout_ms)
        if rc != 0:
            raise TimeoutError(f"native batch {batch_id} not ready in {timeout_ms}ms")

    def close(self):
        if self._handle:
            self._lib.be_destroy(self._handle)
            self._handle = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


class NativeDataLoader:
    """DataLoader-compatible iterator backed by the C++ engine.

    Works for array-backed datasets exposing ``.images``/``.labels`` (CIFAR)
    or ``.tokens`` memmaps; double-buffers ``prefetch`` batches ahead.
    """

    def __init__(self, images_u8, labels, sampler, batch_size: int,
                 mean, std, augment: bool, num_threads: int = 2,
                 prefetch: int = 4, drop_last: bool = True, engine=None):
        if not drop_last:
            # The engine writes into fixed-size buffers; a short final batch
            # would leave stale tail rows. Use the Python loader for that.
            raise ValueError("NativeDataLoader requires drop_last=True")
        self.engine = engine if engine is not None else NativeBatchEngine.image(
            images_u8, mean, std, augment, num_threads)
        self.labels = np.asarray(labels)
        self.sampler = sampler
        self.batch_size = batch_size
        self.prefetch = prefetch
        self.epoch = 0
        # Mid-epoch resume: first batch of the epoch to produce (same
        # contract as loader.DataLoader.start_batch — skipped batches are
        # never submitted to the engine).
        self.start_batch = 0
        self._next_id = 0  # globally monotonic: ids never reused across epochs

    @classmethod
    def jpeg(cls, paths: list, labels, sampler, batch_size: int,
             image_size: int, mean, std, augment: bool, num_threads: int = 2,
             prefetch: int = 4) -> "NativeDataLoader":
        """Loader over a FolderDataset's files via the native decode engine."""
        engine = NativeBatchEngine.jpeg(paths, image_size, mean, std, augment,
                                        num_threads)
        return cls(None, labels, sampler, batch_size, None, None, augment,
                   num_threads, prefetch, engine=engine)

    @classmethod
    def tokens(cls, tokens_flat: np.ndarray, seq_len: int, sampler,
               batch_size: int, num_threads: int = 2,
               prefetch: int = 4) -> "NativeTokenDataLoader":
        """Loader over a flat token file via the native window-gather engine."""
        num_samples = (len(tokens_flat) - 1) // seq_len
        engine = NativeBatchEngine.gather_windows(
            np.ascontiguousarray(tokens_flat), num_samples, seq_len + 1,
            seq_len, num_threads)
        return NativeTokenDataLoader(
            None, None, sampler, batch_size, None, None, False,
            num_threads, prefetch, engine=engine)

    def set_epoch(self, epoch: int):
        self.epoch = epoch
        self.sampler.set_epoch(epoch)

    def __len__(self):
        return len(self.sampler) // self.batch_size

    def _emit(self, buf: np.ndarray, bi: np.ndarray) -> dict:
        """Turn a filled engine buffer + its sample indices into a batch."""
        return {"image": buf.copy(),
                "label": self.labels[bi].astype(np.int32)}

    def __iter__(self):
        idx = self.sampler.local_indices()
        nb = len(self)
        bufs = [np.empty((self.batch_size, *self.engine.sample_shape),
                         self.engine.out_dtype)
                for _ in range(self.prefetch)]
        pending: dict[int, tuple[list[int], np.ndarray]] = {}  # b -> (ids, indices)

        # Expensive per-sample engines (JPEG decode) get the batch split
        # into one job per worker thread — a single job runs on a single
        # thread, so batch-granular submission would cap parallelism at the
        # prefetch depth instead of num_threads.
        n_chunks = max(self.engine.num_threads, 1) if self.engine.chunked else 1

        def submit(b):
            lo = b * self.batch_size
            bi = np.ascontiguousarray(idx[lo:lo + self.batch_size], np.int64)
            buf = bufs[b % self.prefetch]
            per = -(-len(bi) // min(n_chunks, len(bi)))
            ids = []
            for j in range(0, len(bi), per):
                cid = self._next_id
                self._next_id += 1
                # Epoch-only seed: the engine keys per-sample RNG on the
                # DATASET index, so augmentation is reproducible across
                # --workers / chunking / batch-size choices.
                self.engine.submit(cid, np.ascontiguousarray(bi[j:j + per]),
                                   buf[j:], seed=self.epoch)
                ids.append(cid)
            pending[b] = (ids, bi)

        start = min(self.start_batch, nb)
        inflight = min(self.prefetch, nb - start)
        for b in range(start, start + inflight):
            submit(b)
        try:
            for b in range(start, nb):
                ids, bi = pending[b]
                for cid in ids:
                    self.engine.wait(cid)
                del pending[b]
                batch = self._emit(bufs[b % self.prefetch], bi)
                if b + inflight < nb:
                    submit(b + inflight)
                loader_lib._log_indices(self.epoch, b, bi)
                yield loader_lib._apply_batch_hook(self.epoch, b, batch)
        finally:
            # Drain in-flight jobs before `bufs` can be garbage-collected:
            # abandoned C++ jobs hold raw pointers into them (use-after-free
            # otherwise when the consumer stops early).
            for ids, _ in pending.values():
                for cid in ids:
                    try:
                        self.engine.wait(cid)
                    except TimeoutError:
                        pass


class NativeTokenDataLoader(NativeDataLoader):
    """Token-file loader on the C++ gather engine (overlapping LM windows).

    Produces the same ``{"tokens", "targets"}`` int32 batches as iterating a
    :class:`~...datasets.TokenFileDataset` through the Python loader — tested
    bit-for-bit — but the window gather runs on engine threads with the GIL
    released, straight off the memmapped file. Construct via
    :meth:`NativeDataLoader.tokens`; all buffering/drain behavior is
    inherited — only batch emission differs.
    """

    def _emit(self, buf: np.ndarray, bi: np.ndarray) -> dict:
        chunk = buf.astype(np.int32)
        return {"tokens": chunk[:, :-1], "targets": chunk[:, 1:]}
