"""Threaded batch loader — the ``DataLoader(num_workers=...)`` equivalent.

Reference parity (SURVEY.md §2b N7): torch's loader forks worker *processes*
because Python-side decode is GIL-bound. Here batch assembly is numpy slicing
/ light augmentation, so a thread pool (optionally backed by the C++ prefetch
runtime in ``native/``) suffices: worker threads materialize batches ahead of
the training loop into a bounded queue, and the device prefetcher
(:mod:`prefetch`) overlaps host->HBM transfer with the running step.
"""

from __future__ import annotations

import json
import os
import queue
import threading
from typing import Iterator

import numpy as np

from pytorch_distributed_training_example_tpu.data.sampler import ShardedSampler

# Debug/verification hook: when this env var names a file, every loader
# appends one JSON line per YIELDED batch ({"epoch", "batch", "indices"}).
# Used by the mid-epoch-resume test to assert sample-exact continuation
# (no replay, no skip). In multi-process runs every rank would otherwise
# interleave appends into one file, so the path is suffixed ".rankN" when
# jax reports more than one process.
INDEX_LOG_ENV = "PDTX_INDEX_LOG"

def dp_shard(nproc: int, dp: int, process_index: int) -> tuple[int, int]:
    """Loader (shards, rank) for a host in a gang with non-data axes in the
    mesh — the DistributedSampler coordinate contract.

    A process must feed rows for its **data-parallel coordinate**, not its
    process index: with seq/pp/ep/tp axes in the mesh the batch dim
    replicates across some or all processes, and
    ``make_array_from_process_local_data`` assumes every process in a
    replica group supplies IDENTICAL rows. Device order is dp-major, so the
    ``nproc / dp`` processes holding one dp coordinate form a contiguous
    run of process indices — e.g. a 2-process dp1 x seq2 gang maps both
    ranks to coordinate 0 and they read the SAME sample stream.

    ``nproc <= dp`` is the plain multi-host data-parallel case (each host
    feeds its own slice); otherwise ``nproc`` must be a multiple of ``dp``
    so every host maps to exactly one dp replica group.
    """
    if nproc <= dp:
        return nproc, process_index
    if nproc % dp:
        raise ValueError(
            f"process count {nproc} must be a multiple of the data-parallel "
            f"degree {dp} (mesh data x fsdp) so every host maps to one dp "
            "replica group")
    return dp, process_index * dp // nproc


# Process-wide yield-time hook: ``hook(epoch, batch_idx, batch) -> batch``,
# applied by every loader (python and native paths) right after index
# logging. The chaos harness (utils/chaos.py) uses it to poison or stall
# specific batches deterministically — keyed on the batch INDEX, so prefetch
# lookahead does not shift which batch gets hit.
_batch_hook = None


def set_batch_hook(fn) -> None:
    global _batch_hook
    _batch_hook = fn


def _apply_batch_hook(epoch: int, batch: int, item):
    return _batch_hook(epoch, batch, item) if _batch_hook is not None else item


def _log_indices(epoch: int, batch: int, indices) -> None:
    path = os.environ.get(INDEX_LOG_ENV)
    if not path:
        return
    try:  # lazy: the loader is importable (and testable) without jax init
        import jax

        if jax.process_count() > 1:
            path = f"{path}.rank{jax.process_index()}"
    except ImportError:
        pass
    with open(path, "a") as fh:
        fh.write(json.dumps({"epoch": int(epoch), "batch": int(batch),
                             "indices": [int(i) for i in indices]}) + "\n")


class _WorkerError:
    """Wraps a worker-thread exception for re-raise in the consumer
    (torch DataLoader's ExceptionWrapper behavior)."""

    def __init__(self, exc: BaseException):
        self.exc = exc


def collate(samples: list[dict]) -> dict[str, np.ndarray]:
    out = {}
    for key in samples[0]:
        vals = [s[key] for s in samples]
        out[key] = np.stack(vals) if np.ndim(vals[0]) else np.asarray(vals)
    return out


def build_image_loader(dataset, sampler, batch_size: int, workers: int = 0,
                       native: bool = True):
    """Pick the fastest available train loader for a dataset.

    One decision point shared by the trainer and the benchmarks: the native
    C++ engine serves in-memory uint8 arrays (``images_u8``, CIFAR),
    all-JPEG directory trees (``jpeg_paths``, ImageNet), and memmapped token
    files (``tokens`` + ``seq_len``, LM); everything else — including trees
    with non-JPEG files, which the native decoder would zero-fill — falls
    back to the Python :class:`DataLoader`.
    """
    from pytorch_distributed_training_example_tpu.data import native_loader

    augment = bool(getattr(dataset, "augment", False))
    if native and native_loader.available():
        if hasattr(dataset, "images_u8"):
            return native_loader.NativeDataLoader(
                dataset.images_u8, dataset.labels, sampler, batch_size,
                dataset.mean, dataset.std, augment=augment,
                num_threads=max(workers, 1))
        paths = getattr(dataset, "jpeg_paths", None)
        if paths and all(p.lower().endswith((".jpg", ".jpeg")) for p in paths):
            try:
                return native_loader.NativeDataLoader.jpeg(
                    paths, dataset.labels, sampler, batch_size,
                    dataset.image_size, dataset.mean, dataset.std,
                    augment=augment, num_threads=max(workers, 1))
            except RuntimeError:  # engine built without libjpeg
                pass
        if hasattr(dataset, "tokens") and hasattr(dataset, "seq_len"):
            return native_loader.NativeDataLoader.tokens(
                dataset.tokens, dataset.seq_len, sampler, batch_size,
                num_threads=max(workers, 1))
    return DataLoader(dataset, batch_size, sampler, num_workers=workers)


class DataLoader:
    """Iterates per-host batches of stacked numpy arrays.

    ``batch_size`` is the *per-host* batch (global batch / process count);
    the sampler hands this host its index shard, mirroring the reference's
    per-rank ``DistributedSampler`` slice.
    """

    def __init__(
        self,
        dataset,
        batch_size: int,
        sampler: ShardedSampler | None = None,
        num_workers: int = 0,
        drop_last: bool = True,
        prefetch_batches: int = 4,
    ):
        self.dataset = dataset
        self.batch_size = batch_size
        self.sampler = sampler or ShardedSampler(len(dataset), shuffle=False)
        self.num_workers = num_workers
        self.drop_last = drop_last
        self.prefetch_batches = prefetch_batches
        # Mid-epoch resume: skip this many leading batches of the epoch's
        # index stream (never decoded, not just dropped). The trainer sets
        # it for the resumed epoch and resets it to 0 for later epochs.
        self.start_batch = 0

    def set_epoch(self, epoch: int) -> None:
        self.sampler.set_epoch(epoch)
        if hasattr(self.dataset, "epoch"):
            self.dataset.epoch = epoch  # augmentations reseed per epoch

    def _batches_of_indices(self, start: int = 0):
        idx = self.sampler.local_indices()
        n_full = len(idx) // self.batch_size
        for b in range(start, n_full):
            yield idx[b * self.batch_size : (b + 1) * self.batch_size]
        rem = len(idx) - n_full * self.batch_size
        if rem and not self.drop_last and start <= n_full:
            yield idx[n_full * self.batch_size :]

    def __len__(self) -> int:
        n = len(self.sampler)
        return n // self.batch_size if self.drop_last else -(-n // self.batch_size)

    def _make_batch(self, indices) -> dict[str, np.ndarray]:
        return collate([self.dataset[int(i)] for i in indices])

    def __iter__(self) -> Iterator[dict[str, np.ndarray]]:
        start = self.start_batch
        if self.num_workers <= 0:
            for b, indices in enumerate(self._batches_of_indices(start), start):
                _log_indices(self.sampler.epoch, b, indices)
                yield _apply_batch_hook(self.sampler.epoch, b,
                                        self._make_batch(indices))
            return
        yield from self._threaded_iter(start)

    def _threaded_iter(self, start: int = 0):
        # Ordered hand-off: each worker owns batch b where b % W == worker_id,
        # writing into a per-batch slot so batch order is deterministic.
        index_batches = list(self._batches_of_indices(start))
        out_q: list[queue.Queue] = [queue.Queue(maxsize=1) for _ in index_batches]
        budget = threading.Semaphore(max(self.prefetch_batches, self.num_workers))
        stop = threading.Event()

        def worker(wid: int):
            for b in range(wid, len(index_batches), self.num_workers):
                budget.acquire()
                if stop.is_set():
                    return
                try:
                    out_q[b].put(self._make_batch(index_batches[b]))
                except BaseException as e:  # re-raised in the consumer
                    out_q[b].put(_WorkerError(e))
                    return

        threads = [
            threading.Thread(target=worker, args=(w,), daemon=True)
            for w in range(self.num_workers)
        ]
        for t in threads:
            t.start()
        try:
            for b in range(len(index_batches)):
                item = out_q[b].get()
                if isinstance(item, _WorkerError):
                    raise RuntimeError(
                        f"DataLoader worker failed on batch {b}") from item.exc
                _log_indices(self.sampler.epoch, start + b, index_batches[b])
                yield _apply_batch_hook(self.sampler.epoch, start + b, item)
                budget.release()
        finally:
            stop.set()
            # Unblock any workers parked on the budget semaphore.
            for _ in threads:
                budget.release()
