"""Datasets for the five reference configs (BASELINE.json).

A dataset is anything with ``__len__`` and ``__getitem__(i) -> dict[str,
np.ndarray]`` (batches are dicts; the train step consumes ``image``/``label``
or ``tokens``). Real data:

- CIFAR-10 from the standard ``cifar-10-batches-py`` pickle layout.
- ImageNet-style class-per-directory trees via :class:`FolderDataset`
  (JPEG decode through PIL/libjpeg-turbo, or the native C++ engine's libjpeg
  path — data/native_loader.py); the synthetic variants below stand in when
  no dataset is on disk (benchmarking uses them — input pipeline excluded
  from the MFU measurement the same way the reference's synthetic-data mode
  would; ``bench.py --include-input`` measures the full pipeline).
"""

from __future__ import annotations

import os
import pickle
from typing import Sequence

import numpy as np

IMAGENET_MEAN = np.array([0.485, 0.456, 0.406], np.float32)
IMAGENET_STD = np.array([0.229, 0.224, 0.225], np.float32)
CIFAR_MEAN = np.array([0.4914, 0.4822, 0.4465], np.float32)
CIFAR_STD = np.array([0.2470, 0.2435, 0.2616], np.float32)


class SyntheticImageDataset:
    """Deterministic fake images+labels; shaped/normalized like the real thing.

    Each image is noise plus a fixed per-class pattern, so classes are
    separable — few-epoch convergence tests measure real learning rather
    than memorization of pure noise.
    """

    def __init__(self, num_examples: int = 51200, image_size: int = 224,
                 num_classes: int = 1000, seed: int = 0,
                 noise_seed: int | None = None, augment: bool = False):
        self.num_examples = num_examples
        self.image_size = image_size
        self.num_classes = num_classes
        self.seed = seed
        # Per-sample noise stream. Class PATTERNS are keyed on `seed` so
        # train and eval share the learnable signal, but a split built with
        # a different `noise_seed` draws DISJOINT samples — a genuinely
        # held-out set (the r4 artifact's eval indices reused the train
        # noise stream, so "held-out" partially scored seen images).
        self.noise_seed = seed if noise_seed is None else noise_seed
        self.augment = augment
        self.epoch = 0
        pat_rng = np.random.default_rng(seed + 12345)
        # Low-res patterns upsampled at access: O(classes * 8*8*3) memory.
        self._pat_res = min(8, image_size)
        self._patterns = pat_rng.standard_normal(
            (min(num_classes, 1024), self._pat_res, self._pat_res, 3)
        ).astype(np.float32)

    def __len__(self):
        return self.num_examples

    def __getitem__(self, i: int):
        rng = np.random.default_rng((self.noise_seed, i))
        label = np.int32(i % self.num_classes)
        img = rng.standard_normal(
            (self.image_size, self.image_size, 3), np.float32)
        pat = self._patterns[label % len(self._patterns)]
        rep = self.image_size // self._pat_res
        if rep > 1:
            pat = np.repeat(np.repeat(pat, rep, 0), rep, 1)
        img = 0.7 * img[: pat.shape[0], : pat.shape[1]] + 0.7 * pat
        if img.shape[0] != self.image_size:  # image_size not divisible by 8
            full = rng.standard_normal(
                (self.image_size, self.image_size, 3)).astype(np.float32)
            full[: img.shape[0], : img.shape[1]] = img
            img = full
        img = img.astype(np.float32)
        if self.augment:
            # CIFAR-style train transform (reflect-pad-4 crop + flip),
            # reseeded per epoch like CIFAR10/FolderDataset.
            arng = np.random.default_rng((self.noise_seed, self.epoch, i))
            padded = np.pad(img, ((4, 4), (4, 4), (0, 0)), mode="reflect")
            y, x = arng.integers(0, 9, size=2)
            img = padded[y: y + self.image_size, x: x + self.image_size]
            if arng.integers(0, 2):
                img = img[:, ::-1]
            img = np.ascontiguousarray(img)
        return {"image": img, "label": label}


class CIFAR10:
    """CIFAR-10 from the canonical python pickle batches (NHWC float32, normalized).

    The reference's CPU-runnable dev config (BASELINE.json configs[0]).
    Train-time augmentation: random crop with 4px pad + horizontal flip.
    """

    mean = CIFAR_MEAN
    std = CIFAR_STD

    def __init__(self, root: str, train: bool = True, augment: bool | None = None,
                 seed: int = 0):
        base = os.path.join(root, "cifar-10-batches-py")
        files = [f"data_batch_{i}" for i in range(1, 6)] if train else ["test_batch"]
        images, labels = [], []
        for f in files:
            with open(os.path.join(base, f), "rb") as fh:
                d = pickle.load(fh, encoding="bytes")
            images.append(d[b"data"])
            labels.extend(d[b"labels"])
        data = np.concatenate(images).reshape(-1, 3, 32, 32).transpose(0, 2, 3, 1)
        # Kept uint8: 4x less host RAM, and the native C++ engine reads it
        # directly; normalization happens at access time (affine ops commute
        # with crop/flip, so results match normalizing first).
        self.images_u8 = np.ascontiguousarray(data)
        self.labels = np.asarray(labels, np.int32)
        self.augment = train if augment is None else augment
        self.seed = seed
        self.epoch = 0

    def __len__(self):
        return len(self.labels)

    def __getitem__(self, i: int):
        img = self.images_u8[i]
        if self.augment:
            rng = np.random.default_rng((self.seed, self.epoch, i))
            padded = np.pad(img, ((4, 4), (4, 4), (0, 0)), mode="reflect")
            y, x = rng.integers(0, 9, size=2)
            img = padded[y : y + 32, x : x + 32]
            if rng.random() < 0.5:
                img = img[:, ::-1]
        out = img.astype(np.float32) / 255.0
        out = (out - CIFAR_MEAN) / CIFAR_STD
        return {"image": out, "label": self.labels[i]}


def random_resized_crop_params(rng, width: int, height: int,
                               scale=(0.08, 1.0), ratio=(3 / 4, 4 / 3)):
    """Sample an (x, y, w, h) crop box — torchvision RandomResizedCrop semantics.

    10 rejection-sampling tries over (area-scale, log-aspect), then the
    ratio-clamped center-crop fallback. Coordinates are in original pixels.
    """
    area = width * height
    log_ratio = (np.log(ratio[0]), np.log(ratio[1]))
    for _ in range(10):
        target_area = area * rng.uniform(*scale)
        aspect = np.exp(rng.uniform(*log_ratio))
        w = int(round(np.sqrt(target_area * aspect)))
        h = int(round(np.sqrt(target_area / aspect)))
        if 0 < w <= width and 0 < h <= height:
            x = int(rng.integers(0, width - w + 1))
            y = int(rng.integers(0, height - h + 1))
            return x, y, w, h
    in_ratio = width / height
    if in_ratio < ratio[0]:
        w = width
        h = int(round(w / ratio[0]))
    elif in_ratio > ratio[1]:
        h = height
        w = int(round(h * ratio[1]))
    else:
        w, h = width, height
    return (width - w) // 2, (height - h) // 2, w, h


def center_crop_box(width: int, height: int, image_size: int,
                    resize_short: int | None = None):
    """Eval crop box in ORIGINAL pixel coords.

    Equivalent to resize-short-side-to-``resize_short`` (default
    ``image_size * 256 // 224``, the standard ImageNet eval recipe) followed
    by an ``image_size`` center crop: a centered square of side
    ``short * image_size / resize_short``.
    """
    if resize_short is None:
        resize_short = image_size * 256 // 224
    short = min(width, height)
    side = max(1, int(round(short * image_size / resize_short)))
    return (width - side) // 2, (height - side) // 2, side, side


class FolderDataset:
    """ImageFolder-equivalent dataset over a ``root/<class>/<image>`` tree.

    Reference parity (SURVEY.md §2a #3, §7 hard part (a)): the reference's
    ImageNet path is ``torchvision.datasets.ImageFolder`` + RandomResizedCrop/
    flip (train) or Resize(256)/CenterCrop(224) (eval). Class names are the
    sorted subdirectory names; labels are their indices.

    Decode path: PIL with JPEG ``draft`` mode — libjpeg's DCT-space 1/2, 1/4,
    1/8 downscale — so a 224px crop from a large JPEG decodes at roughly crop
    resolution instead of full resolution, then one fused crop+bilinear-resize
    (``Image.resize(box=...)``). The C++ engine implements the same pipeline
    natively (native/batch_engine.cc jpeg mode) for GIL-free threaded decode;
    ``jpeg_paths``/``labels`` expose what it needs.
    """

    IMG_EXTS = (".jpg", ".jpeg", ".png", ".bmp", ".webp")
    mean = IMAGENET_MEAN
    std = IMAGENET_STD

    def __init__(self, root: str, train: bool = True, image_size: int = 224,
                 augment: bool | None = None, seed: int = 0):
        self.root = root
        self.image_size = image_size
        self.augment = train if augment is None else augment
        self.seed = seed
        self.epoch = 0
        self.classes = sorted(
            d for d in os.listdir(root)
            if os.path.isdir(os.path.join(root, d)) and not d.startswith("."))
        if not self.classes:
            raise FileNotFoundError(f"no class directories under {root!r}")
        self.class_to_idx = {c: i for i, c in enumerate(self.classes)}
        paths, labels = [], []
        for c in self.classes:
            cdir = os.path.join(root, c)
            for f in sorted(os.listdir(cdir)):
                if f.lower().endswith(self.IMG_EXTS):
                    paths.append(os.path.join(cdir, f))
                    labels.append(self.class_to_idx[c])
        if not paths:
            raise FileNotFoundError(f"no images under {root!r}")
        self.jpeg_paths = paths
        self.labels = np.asarray(labels, np.int32)

    def __len__(self):
        return len(self.jpeg_paths)

    def _crop_box(self, i: int, width: int, height: int):
        if self.augment:
            rng = np.random.default_rng((self.seed, self.epoch, i))
            x, y, w, h = random_resized_crop_params(rng, width, height)
            flip = bool(rng.random() < 0.5)
        else:
            x, y, w, h = center_crop_box(width, height, self.image_size)
            flip = False
        return x, y, w, h, flip

    def __getitem__(self, i: int):
        from PIL import Image

        s = self.image_size
        with Image.open(self.jpeg_paths[i]) as img:
            w0, h0 = img.size
            x, y, w, h, flip = self._crop_box(i, w0, h0)
            # DCT-scaled decode: ask for a size where the crop is >= s px.
            img.draft("RGB", (max(1, -(-w0 * s // w)), max(1, -(-h0 * s // h))))
            wd, hd = img.size
            if img.mode != "RGB":
                img = img.convert("RGB")
            sx, sy = wd / w0, hd / h0
            box = (x * sx, y * sy, (x + w) * sx, (y + h) * sy)
            img = img.resize((s, s), Image.BILINEAR, box=box)
            arr = np.asarray(img, np.uint8)
        if flip:
            arr = arr[:, ::-1]
        out = arr.astype(np.float32) / 255.0
        out = (out - IMAGENET_MEAN) / IMAGENET_STD
        return {"image": out, "label": self.labels[i]}


class SyntheticTokenDataset:
    """Fake LM sequences for GPT-2 / Llama configs: next-token prediction."""

    def __init__(self, num_examples: int = 8192, seq_len: int = 1024,
                 vocab_size: int = 50257, seed: int = 0):
        self.num_examples = num_examples
        self.seq_len = seq_len
        self.vocab_size = vocab_size
        self.seed = seed

    def __len__(self):
        return self.num_examples

    def __getitem__(self, i: int):
        rng = np.random.default_rng((self.seed, i))
        toks = rng.integers(0, self.vocab_size, self.seq_len + 1, dtype=np.int32)
        return {"tokens": toks[:-1], "targets": toks[1:]}


class TokenFileDataset:
    """LM dataset over a flat binary token file (uint16/uint32 memmap, GPT-2 style)."""

    def __init__(self, path: str, seq_len: int = 1024, dtype=np.uint16):
        self.tokens = np.memmap(path, dtype=dtype, mode="r")
        self.seq_len = seq_len

    def __len__(self):
        return (len(self.tokens) - 1) // self.seq_len

    def __getitem__(self, i: int):
        s = i * self.seq_len
        chunk = np.asarray(self.tokens[s : s + self.seq_len + 1], np.int32)
        return {"tokens": chunk[:-1], "targets": chunk[1:]}


def build_dataset(name: str, data_path: str | None, train: bool, *,
                  image_size: int = 224, seq_len: int = 1024, seed: int = 0,
                  vocab_size: int = 50257, require_split: bool = False):
    """Dataset factory used by main.py; falls back to synthetic when no data dir.

    ``require_split=True`` (eval-only mode) refuses the train-images fallback
    when ``val/`` is missing — scoring the training set must never be
    reported as "the evaluation metric" silently (ADVICE r2).
    """
    name = name.lower()
    if name == "cifar10":
        if data_path and os.path.isdir(os.path.join(data_path, "cifar-10-batches-py")):
            return CIFAR10(data_path, train=train, seed=seed)
        # Train split augments (CIFAR10-class parity); eval draws a
        # DISJOINT noise stream — genuinely held-out samples of the same
        # pattern distribution (see SyntheticImageDataset.noise_seed).
        if train:
            return SyntheticImageDataset(51200, 32, 10, seed, augment=True)
        return SyntheticImageDataset(10000, 32, 10, seed,
                                     noise_seed=seed + 777)
    if name in ("imagenet", "imagenet1k"):
        if data_path:
            split = os.path.join(data_path, "train" if train else "val")
            if os.path.isdir(split):
                root = split
            elif os.path.isdir(data_path):
                # Flat tree (class dirs at the root) or a missing val/
                # split: fall back to the usable train images — loudly,
                # because for eval that means scoring on training data.
                train_split = os.path.join(data_path, "train")
                root = (train_split
                        if not train and os.path.isdir(train_split)
                        else data_path)
                if not train and require_split and root == train_split:
                    # Only the TRAIN-IMAGES fallback is refused; a flat tree
                    # (class dirs at the root, e.g. --data-path .../val
                    # pointing straight at the eval split) stays valid.
                    raise FileNotFoundError(
                        f"--evaluate: no val/ split under {data_path!r} — "
                        "refusing to score the training images as the "
                        "evaluation metric")
                if not train:
                    import logging

                    logging.getLogger(__name__).warning(
                        "no val/ split under %r; evaluation will run on "
                        "the SAME images as training", data_path)
            else:
                raise FileNotFoundError(
                    f"--data-path {data_path!r} does not exist")
            return FolderDataset(root, train=train, image_size=image_size,
                                 seed=seed)
        # perf vehicle (no augment), but eval still gets a disjoint
        # noise stream so synthetic "val" never scores seen samples
        return SyntheticImageDataset(
            1281167 if train else 50000, image_size, 1000, seed,
            noise_seed=seed if train else seed + 777)
    if name in ("lm", "synthetic_lm", "openwebtext"):
        if data_path and os.path.isfile(data_path):
            return TokenFileDataset(data_path, seq_len=seq_len)
        return SyntheticTokenDataset(seq_len=seq_len, seed=seed,
                                     vocab_size=vocab_size)
    raise ValueError(f"unknown dataset {name!r}")
