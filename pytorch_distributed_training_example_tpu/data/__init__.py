"""Input pipeline: sharded sampling, datasets, loading, and device prefetch.

The reference's ``DistributedSampler``/``DataLoader`` pair (SURVEY.md §2a #3)
maps to: per-host index sharding (:mod:`sampler`), a threaded loader
(:mod:`loader`), and a double-buffered host->HBM prefetcher
(:mod:`prefetch`) that assembles globally-sharded ``jax.Array`` batches.
"""

from pytorch_distributed_training_example_tpu.data.sampler import ShardedSampler  # noqa: F401
from pytorch_distributed_training_example_tpu.data.loader import DataLoader  # noqa: F401
