"""End-to-end pipeline-parallel language-model training (strategy "pp").

Wires the generic GPipe schedule (parallel/pipeline.py) into the Llama
family: a ``scan_layers`` Llama owns ONE stacked block parameter tree
``[num_layers, ...]``; for PP we shard that leading dim over the ``stage``
mesh axis (each chip holds a contiguous slice of layers) and run the
embed -> pipeline(blocks) -> norm -> head forward with microbatched
activations hopping stage-to-stage via ``ppermute``.

The wrapper quacks like a flax module (``init``/``apply``) so the standard
train step, checkpointing, and Trainer work unchanged; its params ARE the
scan-Llama params (checkpoint-compatible with the non-PP model).
"""

from __future__ import annotations

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from pytorch_distributed_training_example_tpu.models import llama as llama_lib
from pytorch_distributed_training_example_tpu.parallel import pipeline as pp

#: Parameter rules for strategy "pp": the stacked block tree shards its
#: leading (layer) dim over 'stage'; embeddings/head replicate (they run
#: outside the pipeline on every chip) with auto-FSDP composition available.
PP_RULES = (
    (r"blocks/block/", P("stage")),
    (r".*", "AUTO_FSDP"),
)


class PipelinedLlama:
    """Flax-compatible facade over Llama(scan_layers=True) + GPipe."""

    def __init__(self, module: llama_lib.Llama, mesh: Mesh,
                 num_microbatches: int = 8):
        if not module.scan_layers:
            module = module.clone(scan_layers=True)
        self.module = module
        self.mesh = mesh
        self.num_microbatches = num_microbatches
        self.num_stages = mesh.shape["stage"]
        if module.num_layers % self.num_stages:
            raise ValueError(
                f"num_layers {module.num_layers} must divide by stage "
                f"{self.num_stages}")

    # -- flax-like surface ------------------------------------------------

    def init(self, rngs, tokens, train=False):
        return self.module.init(rngs, tokens, train=train)

    def apply(self, variables, tokens, train=True, rngs=None, mutable=()):
        logits = self._forward(variables["params"], tokens, train)
        if mutable:
            return logits, {}
        return logits

    # -- forward ----------------------------------------------------------

    def _forward(self, params, tokens, train):
        m = self.module
        x = nn.Embed(m.vocab_size, m.d_model, dtype=m.dtype,
                     param_dtype=m.param_dtype).apply(
            {"params": params["embed"]}, tokens)

        block = llama_lib.LlamaBlock(
            num_heads=m.num_heads, num_kv_heads=m.num_kv_heads,
            head_dim=m.head_dim, ffn_dim=m.ffn_dim, rope_theta=m.rope_theta,
            dtype=m.dtype, param_dtype=m.param_dtype, attn_impl="xla",
            num_experts=m.num_experts)
        if m.remat:
            block_apply = jax.checkpoint(
                lambda p, x: block.apply({"params": p}, x, train),
                policy=jax.checkpoint_policies.nothing_saveable,
                prevent_cse=False)
        else:
            block_apply = lambda p, x: block.apply({"params": p}, x, train)

        S = self.num_stages
        stacked = params["blocks"]["block"]          # leaves [L, ...]
        stage_params = jax.tree.map(
            lambda p: p.reshape(S, p.shape[0] // S, *p.shape[1:]), stacked)

        def stage_fn(p_stage, x):
            def body(x, p_layer):
                return block_apply(p_layer, x), None
            x, _ = jax.lax.scan(body, x, p_stage)
            return x

        # remat_stages stays off: with m.remat the per-block checkpoint above
        # already bounds saved residuals to layer inputs (stage-level remat on
        # top would only re-recompute the scan).
        x = pp.pipeline_apply(stage_fn, stage_params, x, mesh=self.mesh,
                              num_microbatches=self.num_microbatches)

        x = llama_lib.RMSNorm(dtype=m.dtype, param_dtype=m.param_dtype).apply(
            {"params": params["final_norm"]}, x)
        logits = nn.Dense(m.vocab_size, use_bias=False, dtype=m.dtype,
                          param_dtype=m.param_dtype).apply(
            {"params": params["lm_head"]}, x)
        return logits.astype(m.logits_dtype)
