"""Mixture-of-Experts with expert parallelism over the ``expert`` mesh axis.

SURVEY.md §2c "EP": Switch/GShard-style token routing, built the GSPMD way —
dispatch/combine are einsums against a capacity-bucketed one-hot mask, with
expert-stacked FFN weights sharded on ``expert``; XLA partitions the einsums
and inserts the token all-to-all automatically (no hand-written routing
transport).

Top-k gating (k=1 Switch, k=2 GShard defaults), capacity factor with token
dropping, and the standard load-balancing auxiliary loss (mean(gates)*
fraction-routed per expert, scaled by E), surfaced via the flax ``sow``
mechanism under the ``"losses"`` collection as ``moe_aux_loss``.
"""

from __future__ import annotations

from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from pytorch_distributed_training_example_tpu.core import mesh as mesh_lib

BATCH = mesh_lib.BATCH_AXES


class ExpertFFN(nn.Module):
    """Stacked expert MLPs applied to dispatched tokens [E, C, d]."""

    num_experts: int
    ffn_dim: int
    dtype: Any
    param_dtype: Any

    @nn.compact
    def __call__(self, x):  # [E, C, d]
        d = x.shape[-1]
        w_up = self.param("w_up", nn.initializers.lecun_normal(),
                          (self.num_experts, d, self.ffn_dim), self.param_dtype)
        w_down = self.param("w_down", nn.initializers.lecun_normal(),
                            (self.num_experts, self.ffn_dim, d), self.param_dtype)
        h = jnp.einsum("ecd,edf->ecf", x, w_up.astype(self.dtype),
                       preferred_element_type=jnp.float32).astype(self.dtype)
        h = nn.gelu(h)
        out = jnp.einsum("ecf,efd->ecd", h, w_down.astype(self.dtype),
                         preferred_element_type=jnp.float32).astype(self.dtype)
        return out


class MoEBlock(nn.Module):
    """Router + expert FFNs; drop-in replacement for a dense MLP block.

    Two dispatch implementations, equivalence-tested against each other:

    - ``"gather"`` (default): scatter token ids into an ``[E*C]`` slot table,
      gather token vectors into ``[E, C, d]``, gather expert outputs back by
      slot. Memory O(E*C*d + T*k) — scales to real token counts.
    - ``"einsum"``: the GShard/Switch formulation with an explicit
      ``[T, E, C]`` dispatch/combine mask. O(T*E*C) memory; kept because its
      einsums partition very predictably under GSPMD (useful oracle and
      fallback).
    """

    num_experts: int
    ffn_dim: int
    top_k: int = 2
    capacity_factor: float = 1.25
    aux_loss_weight: float = 0.01
    z_loss_weight: float = 1e-3
    dispatch_impl: str = "gather"  # "gather" | "einsum"
    dtype: Any = jnp.float32
    param_dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = True):  # x: [B, S, d]
        B, S, d = x.shape
        E = self.num_experts
        tokens = x.reshape(B * S, d)
        T = B * S
        capacity = max(int(self.capacity_factor * T * self.top_k / E), 1)

        # Router in fp32 (standard for stability).
        router_logits = nn.Dense(E, use_bias=False, dtype=jnp.float32,
                                 param_dtype=jnp.float32,
                                 name="router")(tokens.astype(jnp.float32))
        probs = jax.nn.softmax(router_logits, axis=-1)          # [T, E]

        # Top-k expert choice per token.
        gate_vals, expert_idx = jax.lax.top_k(probs, self.top_k)  # [T, k]
        gate_vals = gate_vals / jnp.maximum(
            gate_vals.sum(-1, keepdims=True), 1e-9)

        # Capacity bucketing: position of each token within its expert queue.
        onehot = jax.nn.one_hot(expert_idx, E, dtype=jnp.float32)  # [T, k, E]
        # priority: earlier tokens first, k=0 choices before k=1
        flat = onehot.transpose(1, 0, 2).reshape(self.top_k * T, E)
        pos_in_expert = jnp.cumsum(flat, axis=0) - flat            # [kT, E]
        pos = (pos_in_expert.reshape(self.top_k, T, E)
               .transpose(1, 0, 2) * onehot).sum(-1)               # [T, k]
        within_cap = pos < capacity
        gate_vals = gate_vals * within_cap

        if self.dispatch_impl == "einsum":
            out = self._einsum_route(tokens, onehot, pos, within_cap,
                                     gate_vals, capacity)
        else:
            out = self._gather_route(tokens, expert_idx, pos, within_cap,
                                     gate_vals, capacity)

        # Load-balancing aux loss (Switch eq. 4): E * sum_e f_e * P_e.
        me = probs.mean(0)                                # mean router prob
        ce = onehot[:, 0].mean(0)                         # top-1 routed frac
        aux = E * jnp.sum(me * ce)
        self.sow("losses", "moe_aux_loss", self.aux_loss_weight * aux)
        # Router z-loss (ST-MoE): keeps logits from drifting to magnitudes
        # where fp32 softmax saturates.
        z = jnp.mean(jax.scipy.special.logsumexp(router_logits, axis=-1) ** 2)
        self.sow("losses", "moe_z_loss", self.z_loss_weight * z)

        return out.reshape(B, S, d).astype(self.dtype)

    def _experts(self, dispatched):
        dispatched = mesh_lib.constrain(dispatched, P("expert", None, None))
        expert_out = ExpertFFN(self.num_experts, self.ffn_dim, self.dtype,
                               self.param_dtype, name="experts")(dispatched)
        return mesh_lib.constrain(expert_out, P("expert", None, None))

    def _gather_route(self, tokens, expert_idx, pos, within_cap, gate_vals,
                      capacity):
        T, d = tokens.shape
        E = self.num_experts
        n_slots = E * capacity
        # Each kept (token, choice) owns one slot; the trash row (index
        # n_slots) absorbs dropped tokens. Slots are unique per expert queue
        # position, so the scatter has no collisions.
        slot = jnp.where(within_cap,
                         expert_idx * capacity + pos.astype(jnp.int32),
                         n_slots)                                   # [T, k]
        tok_ids = jnp.broadcast_to(
            jnp.arange(T, dtype=jnp.int32)[:, None], slot.shape)
        token_for_slot = jnp.full((n_slots + 1,), T, jnp.int32)
        token_for_slot = token_for_slot.at[slot.reshape(-1)].set(
            tok_ids.reshape(-1))
        tokens_pad = jnp.concatenate(
            [tokens, jnp.zeros((1, d), tokens.dtype)])              # row T = 0
        dispatched = tokens_pad[token_for_slot[:n_slots]].reshape(
            E, capacity, d).astype(self.dtype)
        expert_out = self._experts(dispatched)
        out_pad = jnp.concatenate(
            [expert_out.reshape(n_slots, d).astype(jnp.float32),
             jnp.zeros((1, d), jnp.float32)])                       # trash row
        y = out_pad[slot]                                           # [T, k, d]
        return jnp.einsum("tk,tkd->td", gate_vals, y)

    def _einsum_route(self, tokens, onehot, pos, within_cap, gate_vals,
                      capacity):
        # Dispatch mask [T, k, E, C] -> combined [T, E, C].
        cap_onehot = jax.nn.one_hot(pos.astype(jnp.int32), capacity,
                                    dtype=jnp.float32)  # [T,k,C]
        dispatch = jnp.einsum("tke,tkc->tec", onehot,
                              cap_onehot * within_cap[..., None])
        combine = jnp.einsum("tke,tkc,tk->tec", onehot, cap_onehot,
                             gate_vals)
        dispatched = jnp.einsum("tec,td->ecd", dispatch,
                                tokens.astype(jnp.float32)).astype(self.dtype)
        expert_out = self._experts(dispatched)
        return jnp.einsum("tec,ecd->td", combine,
                          expert_out.astype(jnp.float32))


#: Expert-parallel rules: stacked expert weights shard on the 'expert' axis
#: (composes with fsdp on the remaining dims via AUTO composition).
EP_RULES = (
    (r"experts/w_(up|down)", P("expert", None, None)),
    (r"router/kernel", P()),
)
