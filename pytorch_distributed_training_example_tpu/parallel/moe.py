"""Mixture-of-Experts with expert parallelism over the ``expert`` mesh axis.

SURVEY.md §2c "EP": Switch/GShard-style token routing, built the GSPMD way —
dispatch/combine are einsums against a capacity-bucketed one-hot mask, with
expert-stacked FFN weights sharded on ``expert``; XLA partitions the einsums
and inserts the token all-to-all automatically (no hand-written routing
transport).

Top-k gating (k=1 Switch, k=2 GShard defaults), capacity factor with token
dropping, and the standard load-balancing auxiliary loss (mean(gates)*
fraction-routed per expert, scaled by E), surfaced via the flax ``sow``
mechanism under the ``"losses"`` collection as ``moe_aux_loss``.
"""

from __future__ import annotations

from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from pytorch_distributed_training_example_tpu.core import mesh as mesh_lib

BATCH = mesh_lib.BATCH_AXES


class ExpertFFN(nn.Module):
    """Stacked expert MLPs applied to dispatched tokens [E, C, d]."""

    num_experts: int
    ffn_dim: int
    dtype: Any
    param_dtype: Any

    @nn.compact
    def __call__(self, x):  # [E, C, d]
        d = x.shape[-1]
        w_up = self.param("w_up", nn.initializers.lecun_normal(),
                          (self.num_experts, d, self.ffn_dim), self.param_dtype)
        w_down = self.param("w_down", nn.initializers.lecun_normal(),
                            (self.num_experts, self.ffn_dim, d), self.param_dtype)
        h = jnp.einsum("ecd,edf->ecf", x, w_up.astype(self.dtype),
                       preferred_element_type=jnp.float32).astype(self.dtype)
        h = nn.gelu(h)
        out = jnp.einsum("ecf,efd->ecd", h, w_down.astype(self.dtype),
                         preferred_element_type=jnp.float32).astype(self.dtype)
        return out


class MoEBlock(nn.Module):
    """Router + expert FFNs; drop-in replacement for a dense MLP block."""

    num_experts: int
    ffn_dim: int
    top_k: int = 2
    capacity_factor: float = 1.25
    aux_loss_weight: float = 0.01
    dtype: Any = jnp.float32
    param_dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = True):  # x: [B, S, d]
        B, S, d = x.shape
        E = self.num_experts
        tokens = x.reshape(B * S, d)
        T = B * S
        capacity = max(int(self.capacity_factor * T * self.top_k / E), 1)

        # Router in fp32 (standard for stability).
        router_logits = nn.Dense(E, use_bias=False, dtype=jnp.float32,
                                 param_dtype=jnp.float32,
                                 name="router")(tokens.astype(jnp.float32))
        probs = jax.nn.softmax(router_logits, axis=-1)          # [T, E]

        # Top-k expert choice per token.
        gate_vals, expert_idx = jax.lax.top_k(probs, self.top_k)  # [T, k]
        gate_vals = gate_vals / jnp.maximum(
            gate_vals.sum(-1, keepdims=True), 1e-9)

        # Capacity bucketing: position of each token within its expert queue.
        onehot = jax.nn.one_hot(expert_idx, E, dtype=jnp.float32)  # [T, k, E]
        # priority: earlier tokens first, k=0 choices before k=1
        flat = onehot.transpose(1, 0, 2).reshape(self.top_k * T, E)
        pos_in_expert = jnp.cumsum(flat, axis=0) - flat            # [kT, E]
        pos = (pos_in_expert.reshape(self.top_k, T, E)
               .transpose(1, 0, 2) * onehot).sum(-1)               # [T, k]
        within_cap = pos < capacity
        gate_vals = gate_vals * within_cap

        # Dispatch mask [T, k, E, C] -> combined [T, E, C].
        cap_onehot = jax.nn.one_hot(pos.astype(jnp.int32), capacity,
                                    dtype=jnp.float32)  # [T,k,C]
        dispatch = jnp.einsum("tke,tkc->tec", onehot,
                              cap_onehot * within_cap[..., None])
        combine = jnp.einsum("tke,tkc,tk->tec", onehot, cap_onehot,
                             gate_vals)

        # Route -> experts (expert dim sharded on 'expert'; XLA inserts the
        # all-to-all), compute, route back.
        dispatched = jnp.einsum("tec,td->ecd", dispatch,
                                tokens.astype(jnp.float32)).astype(self.dtype)
        dispatched = mesh_lib.constrain(dispatched, P("expert", None, None))
        expert_out = ExpertFFN(E, self.ffn_dim, self.dtype, self.param_dtype,
                               name="experts")(dispatched)
        expert_out = mesh_lib.constrain(expert_out, P("expert", None, None))
        out = jnp.einsum("tec,ecd->td", combine,
                         expert_out.astype(jnp.float32))

        # Load-balancing aux loss (Switch eq. 4): E * sum_e f_e * P_e.
        me = probs.mean(0)                                # mean router prob
        ce = onehot[:, 0].mean(0)                         # top-1 routed frac
        aux = E * jnp.sum(me * ce)
        self.sow("losses", "moe_aux_loss", self.aux_loss_weight * aux)

        return out.reshape(B, S, d).astype(self.dtype)


#: Expert-parallel rules: stacked expert weights shard on the 'expert' axis
#: (composes with fsdp on the remaining dims via AUTO composition).
EP_RULES = (
    (r"experts/w_(up|down)", P("expert", None, None)),
    (r"router/kernel", P()),
)
