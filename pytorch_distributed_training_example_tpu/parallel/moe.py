"""Mixture-of-Experts with expert parallelism over the ``expert`` mesh axis.

SURVEY.md §2c "EP": Switch/GShard-style token routing, built the GSPMD way —
expert-stacked FFN weights sharded on ``expert``; XLA partitions the expert
einsums and inserts the token all-to-all automatically (no hand-written
routing transport).

Top-k gating (k=1 Switch, k=2 GShard defaults), capacity factor with token
dropping, and the standard load-balancing auxiliary loss (mean(gates)*
fraction-routed per expert, scaled by E), surfaced via the flax ``sow``
mechanism under the ``"losses"`` collection as ``moe_aux_loss``.

Routing bookkeeping is compact-index (MegaBlocks' lesson, Gale et al. 2023):
one stable argsort + bincount over ``expert_idx`` (``routing_stats``) yields
the per-expert counts, segment starts, and within-queue positions that the
dispatch, the Switch aux loss, the z-loss, and the telemetry sows all share.
No fp32 ``[T, E]``/``[T, k, E]`` one-hot is materialized outside the einsum
dispatch impl (whose explicit masks are its definition); the shared stats
are ``[E]``/``[k·T]``-shaped int32. The routing *decision* (fp32 softmax +
``lax.top_k``) is unchanged — the compact path is equivalence-tested
against the one-hot reference in tests/test_moe_router.py.

Three capacity-dropped dispatch implementations share identical
routing/drop semantics (the priority order is: earlier tokens first, k=0
choices before k=1) and are equivalence-tested against each other — see
``dispatch_impl`` on ``MoEBlock``. A fourth, ``"dropless"``, retires the
capacity machinery entirely (MegaBlocks): the ragged per-expert segments
the stats' argsort produces feed a Pallas grouped matmul
(ops/grouped_matmul.py) directly — no ``[E, C, d]`` buffer, no dropped
tokens, capacity factor irrelevant; it is equivalence-tested against the
einsum path at a capacity factor high enough to never drop. The step
regions are tagged with ``jax.named_scope`` (``moe_router`` /
``moe_dispatch`` / ``moe_experts`` / ``moe_combine`` / ``moe_aux``, plus
``moe_experts_gmm`` inside the dropless kernel) so
``benchmarks/profile_step.py`` can attribute device time per region from
an xplane trace (PROFILE_MOE.md).

The dropless path additionally supports **expert-parallel sharded
execution** (``ep_dispatch``, r17): instead of replicated-pinning the sorted
tokens and all-gathering the expert weights every step, the contiguous
per-expert segments are all-to-all'd to the devices that own the experts
(weights stay sharded ``P('expert', None, None)`` per EP_RULES) and
``gmm()`` runs against LOCAL weights only, with a device-local tile table
derived from the local segment counts. ``"a2a_overlap"`` splits the token
dim into double-buffered chunks so the next chunk's all-to-all is issued
before the current chunk's grouped matmul — program order XLA's async
scheduler can overlap on a chip. Both variants are bitwise-identical to the
replicated path (same rows, same weights, same single-dot full-``d``
contraction per row; tested in tests/test_moe_dropless.py). This is what
makes E ≫ devices representable: per-device expert memory is ``E/ep``
weight blocks instead of all ``E``.
"""

from __future__ import annotations

import functools
import json
import os
import warnings
from typing import Any, NamedTuple

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from pytorch_distributed_training_example_tpu.core import mesh as mesh_lib

BATCH = mesh_lib.BATCH_AXES

#: Valid values for ``MoEBlock.ep_dispatch`` (dropless only).
EP_DISPATCH_IMPLS = ("replicated", "a2a", "a2a_overlap")

#: jsonl path: trace-time a2a chunk geometry (static shapes only, so two
#: same-seed runs produce byte-identical logs — asserted by dryrun leg 17).
A2A_CHUNK_LOG_ENV = "PDTX_A2A_CHUNK_LOG"

#: "native" (lax.all_to_all; default — verified correct under the gloo CPU
#: cross-process backend) or "ppermute" (decomposed fallback hatch).
EP_A2A_IMPL_ENV = "PDTX_EP_A2A_IMPL"

_capacity_clamp_warned = False
_ep_fallback_warned = False


def _warn_ep_fallback(ep_dispatch, num_experts, n_rows, ep):
    """One-time trace-time warning when a requested sharded EP dispatch
    falls back to replicated because the shape doesn't tile the EP axis."""
    global _ep_fallback_warned
    if _ep_fallback_warned:
        return
    _ep_fallback_warned = True
    warnings.warn(
        f"MoE ep_dispatch={ep_dispatch!r} requested but E={num_experts} or "
        f"sorted rows kT={n_rows} does not divide the expert mesh axis "
        f"(size {ep}); falling back to the replicated dropless path. "
        f"(warned once per process)", RuntimeWarning, stacklevel=3)


def _ep_degree(ep_dispatch: str, num_experts: int, n_rows: int) -> int:
    """Static EP fan-out for the dropless dispatch: the expert mesh axis
    size when the sharded path can run, else 1 (replicated execution).

    All inputs are trace-time static; init-time tracing outside
    ``use_mesh`` (mesh None) collapses to 1 like the attention dispatcher
    does, so param structure is identical across paths.
    """
    if ep_dispatch not in EP_DISPATCH_IMPLS:
        raise ValueError(f"unknown ep_dispatch {ep_dispatch!r}; "
                         f"have {list(EP_DISPATCH_IMPLS)}")
    if ep_dispatch == "replicated":
        return 1
    mesh = mesh_lib.current_mesh()
    ep = mesh.shape.get("expert", 1) if mesh is not None else 1
    if ep <= 1:
        return 1
    if num_experts % ep or n_rows % ep:
        _warn_ep_fallback(ep_dispatch, num_experts, n_rows, ep)
        return 1
    return ep


def _log_a2a_chunks(scope: str, mode: str, *, ep: int, rows_per_device: int,
                    d_model: int, chunk_rows, dtype, impl: str) -> None:
    """Append the static a2a geometry to ``A2A_CHUNK_LOG_ENV`` (trace time).

    Everything here is compile-time static (no data, no clocks), so the log
    is byte-identical across same-seed runs — the dryrun leg's determinism
    contract for the sharded dispatch.
    """
    path = os.environ.get(A2A_CHUNK_LOG_ENV)
    if not path:
        return
    itemsize = jnp.dtype(dtype).itemsize
    row = {"scope": scope, "mode": mode, "ep": ep,
           "rows_per_device": int(rows_per_device), "d_model": int(d_model),
           "n_chunks": len(chunk_rows),
           "chunk_rows": [int(w) for w in chunk_rows],
           "send_bytes_per_chunk": [int(ep * w * d_model * itemsize)
                                    for w in chunk_rows],
           "dtype": str(jnp.dtype(dtype).name), "impl": impl}
    with open(path, "a") as fh:
        fh.write(json.dumps(row, sort_keys=True) + "\n")


def _warn_capacity_clamp(capacity_factor, T, top_k, num_experts):
    """Loud one-time warning when ``int(cf*T*k/E)`` lands at 0 and the
    capacity is silently clamped to 1 slot per expert — tiny T·k/E shapes
    (small batches, many experts) drop almost every token in that regime.
    Trace-time only (static shapes): no host sync in the compiled step.
    """
    global _capacity_clamp_warned
    if _capacity_clamp_warned:
        return
    _capacity_clamp_warned = True
    warnings.warn(
        f"MoE expert capacity clamped to 1: int(capacity_factor * T * k / E)"
        f" = int({capacity_factor} * {T} * {top_k} / {num_experts}) = 0. "
        f"With one slot per expert most (token, choice) assignments will be "
        f"DROPPED. Raise capacity_factor / batch size, or switch to "
        f"dispatch_impl='dropless' (no capacity, no drops). "
        f"(warned once per process)", RuntimeWarning, stacklevel=3)


def _ep_sharded_ffn(x_loc, w_up, w_down, starts, counts, *, ep, a2a_impl):
    """shard_map body (manual over 'expert'): a2a dispatch + LOCAL gmm.

    ``x_loc`` is this device's contiguous ``[R, d]`` slice of the globally
    expert-sorted ``[kT, d]`` array (R = kT/ep); ``w_up``/``w_down`` are the
    local ``[E/ep, ...]`` expert shards; ``starts``/``counts`` the GLOBAL
    ``[E]`` segment table (replicated — O(E) ints).

    Two contiguity invariants carry the whole formulation:

    1. a contiguous slice of the sorted array splits into ≤ ep contiguous
       destination chunks with boundaries ``clip(starts[q·E/ep] − p·R, 0,
       R)`` — so the send buffer is ep static windows, no scatter;
    2. source-major concatenation of the valid received rows IS the global
       sorted order restricted to this device's experts — so ONE compaction
       gather yields an expert-sorted local array and the unchanged
       ``grouped_ffn`` kernel runs against it with the device-local tile
       table built from ``counts[p·E/ep : (p+1)·E/ep]``.

    The local row buffer is padded to the static worst case kT (all tokens
    routed here); padding rows are zero, steered into the last local
    expert's segment (zero rows contribute zero to outputs and to dw), and
    never scattered back. Per-row outputs are bitwise-identical to the
    replicated path: same rows, same weights, and the kernel contracts the
    full ``d`` dim in one fp32-accumulated dot regardless of tile layout.
    """
    from pytorch_distributed_training_example_tpu.ops import (
        collectives, grouped_matmul as gmm_lib)

    p = jax.lax.axis_index("expert")
    R = x_loc.shape[0]
    E_l = w_up.shape[0]
    Tk = R * ep
    st_ext = jnp.concatenate([starts, jnp.array([Tk], starts.dtype)])
    ar = jnp.arange(R)
    with jax.named_scope("moe_dispatch"):
        # Invariant 1: my rows' destination-chunk boundaries.
        bounds = jnp.clip(st_ext[::E_l][:ep + 1] - p * R, 0, R)   # [ep+1]
        pos = bounds[:-1, None] + ar[None, :]
        valid = pos < bounds[1:, None]
        send = jnp.where(valid[..., None],
                         x_loc[jnp.clip(pos, 0, R - 1)], 0)       # [ep, R, d]
        recv = collectives.all_to_all_blocks(send, "expert", impl=a2a_impl)
        # Source-side geometry: source s sent me its rows [lo_s, hi_s).
        s_ar = jnp.arange(ep)
        lo = jnp.clip(st_ext[p * E_l] - s_ar * R, 0, R)
        hi = jnp.clip(st_ext[(p + 1) * E_l] - s_ar * R, 0, R)
        seg = hi - lo
        off = jnp.concatenate([jnp.zeros((1,), seg.dtype), jnp.cumsum(seg)])
        T_l = off[-1]                       # my valid token count (traced)
        # Invariant 2: compaction gather -> expert-sorted local rows.
        j = jnp.arange(Tk)
        sj = jnp.clip(jnp.searchsorted(off, j, side="right") - 1, 0, ep - 1)
        flat = recv.reshape(Tk, -1)
        gidx = jnp.clip(sj * R + (j - off[sj]), 0, Tk - 1)
        x_l = jnp.where((j < T_l)[:, None], flat[gidx], 0)        # [kT, d]
        # Device-local tile table: local counts, last segment inflated to
        # absorb the zero padding so the segments tile [0, kT) exactly.
        ct_l = jax.lax.dynamic_slice(counts, (p * E_l,), (E_l,))
        ct_l = ct_l.at[-1].add((Tk - T_l).astype(ct_l.dtype))
        st_l = jnp.concatenate(
            [jnp.zeros((1,), jnp.int32),
             jnp.cumsum(ct_l)[:-1].astype(jnp.int32)])
    with jax.named_scope("moe_experts_gmm"):
        y_l = gmm_lib.grouped_ffn(x_l, w_up, w_down, st_l, ct_l)
    with jax.named_scope("moe_dispatch"):
        # Inverse transport: return chunk for source s = rows [off_s,
        # off_s + seg_s) of the local result, then reassemble my slice.
        bidx = jnp.clip(off[:-1, None] + ar[None, :], 0, Tk - 1)
        bvalid = ar[None, :] < seg[:, None]
        back = jnp.where(bvalid[..., None], y_l[bidx], 0)         # [ep, R, d]
        rb = collectives.all_to_all_blocks(back, "expert", impl=a2a_impl)
        qr = jnp.clip(jnp.searchsorted(bounds, ar, side="right") - 1,
                      0, ep - 1)
        return rb.reshape(Tk, -1)[qr * R + (ar - bounds[qr])]     # [R, d]


def _ep_overlap_ffn(x_loc, w_up, w_down, starts, counts, *, ep, chunk_rows,
                    a2a_impl):
    """shard_map body: double-buffered chunked a2a/gmm overlap variant.

    Same transport geometry as :func:`_ep_sharded_ffn`, but the
    per-destination ``R`` rows are split into ``chunk_rows`` windows (the
    last may be torn) and the loop is unrolled so chunk ``c+1``'s dispatch
    all-to-all is issued BEFORE chunk ``c``'s grouped matmul — independent
    ops in program order that XLA's async scheduler can overlap on a chip
    (a2a-start / gmm / a2a-done). Each received chunk is locally re-sorted
    by expert (ids derived from the static geometry, no extra metadata on
    the wire) and fed to ``gmm`` with chunk-local counts; per-chunk dw
    contributions sum under autodiff.
    """
    from pytorch_distributed_training_example_tpu.ops import (
        collectives, grouped_matmul as gmm_lib)

    p = jax.lax.axis_index("expert")
    R = x_loc.shape[0]
    E_l = w_up.shape[0]
    Tk = R * ep
    Rc = chunk_rows[0] if chunk_rows else R
    st_ext = jnp.concatenate([starts, jnp.array([Tk], starts.dtype)])
    bounds = jnp.clip(st_ext[::E_l][:ep + 1] - p * R, 0, R)
    s_ar = jnp.arange(ep)
    lo = jnp.clip(st_ext[p * E_l] - s_ar * R, 0, R)
    hi = jnp.clip(st_ext[(p + 1) * E_l] - s_ar * R, 0, R)
    seg = hi - lo

    def make_send(c, w):
        jr = jnp.arange(w)
        pos = bounds[:-1, None] + c * Rc + jr[None, :]
        valid = pos < bounds[1:, None]
        return jnp.where(valid[..., None],
                         x_loc[jnp.clip(pos, 0, R - 1)], 0)       # [ep, w, d]

    def expert_chunk(c, recv):
        """Local FFN on one received chunk: geometry-derived expert ids,
        chunk-local stable sort, gmm with chunk-local counts, inverse."""
        w = recv.shape[1]
        jr = jnp.arange(w)
        o = lo[:, None] + c * Rc + jr[None, :]     # source-slice offsets
        valid = (c * Rc + jr[None, :]) < seg[:, None]
        g = s_ar[:, None] * R + o                  # global sorted index
        eid = jnp.searchsorted(st_ext[1:], g, side="right")
        eid_l = jnp.clip(eid - p * E_l, 0, E_l - 1)
        # Invalid (padding) rows are zeroed and steered into the last
        # local expert's segment: zero rows through any expert are zero.
        eid_l = jnp.where(valid, eid_l, E_l - 1)
        xs_c = jnp.where(valid[..., None], recv, 0).reshape(ep * w, -1)
        keys = eid_l.reshape(-1).astype(jnp.int32)
        perm = jnp.argsort(keys, stable=True)
        ct_c = jnp.bincount(keys, length=E_l).astype(jnp.int32)
        st_c = jnp.concatenate(
            [jnp.zeros((1,), jnp.int32),
             jnp.cumsum(ct_c)[:-1].astype(jnp.int32)])
        with jax.named_scope("moe_experts_gmm"):
            y_sorted = gmm_lib.grouped_ffn(xs_c[perm], w_up, w_down,
                                           st_c, ct_c)
        y_c = jnp.zeros_like(y_sorted).at[perm].set(y_sorted)
        return jnp.where(valid.reshape(-1)[:, None], y_c,
                         0).reshape(ep, w, -1)

    a2a = functools.partial(collectives.all_to_all_blocks, axis="expert",
                            impl=a2a_impl)
    n_chunks = len(chunk_rows)
    with jax.named_scope("moe_dispatch"):
        sends = [make_send(c, w) for c, w in enumerate(chunk_rows)]
        recv = [None] * n_chunks
        recv[0] = a2a(sends[0])
    y_slice = jnp.zeros((R + 1, x_loc.shape[1]), x_loc.dtype)
    ar = jnp.arange(R)
    for c, w in enumerate(chunk_rows):
        if c + 1 < n_chunks:
            # Double buffering: next chunk's a2a precedes this chunk's gmm
            # in program order (the overlap the HLO test inspects).
            with jax.named_scope("moe_dispatch"):
                recv[c + 1] = a2a(sends[c + 1])
        y_c = expert_chunk(c, recv[c])
        with jax.named_scope("moe_dispatch"):
            rb = a2a(y_c)                          # [ep, w, d] back to me
            jr = jnp.arange(w)
            pos = bounds[:-1, None] + c * Rc + jr[None, :]
            valid = pos < bounds[1:, None]
            tgt = jnp.where(valid, pos, R)         # row R = trash
            y_slice = y_slice.at[tgt.reshape(-1)].set(rb.reshape(ep * w, -1))
    return y_slice[:R]


class ExpertFFN(nn.Module):
    """Stacked expert MLPs applied to dispatched tokens [E, C, d]."""

    num_experts: int
    ffn_dim: int
    dtype: Any
    param_dtype: Any

    @nn.compact
    def __call__(self, x):  # [E, C, d]
        d = x.shape[-1]
        w_up = self.param("w_up", nn.initializers.lecun_normal(),
                          (self.num_experts, d, self.ffn_dim), self.param_dtype)
        w_down = self.param("w_down", nn.initializers.lecun_normal(),
                            (self.num_experts, self.ffn_dim, d), self.param_dtype)
        h = jnp.einsum("ecd,edf->ecf", x, w_up.astype(self.dtype),
                       preferred_element_type=jnp.float32).astype(self.dtype)
        h = nn.gelu(h)
        out = jnp.einsum("ecf,efd->ecd", h, w_down.astype(self.dtype),
                         preferred_element_type=jnp.float32).astype(self.dtype)
        return out


class GroupedExpertFFN(nn.Module):
    """Expert MLPs over the SORTED ragged token layout ``[kT, d]`` (dropless).

    Same math as ``ExpertFFN`` but computed by the Pallas grouped matmul
    (ops/grouped_matmul.py) over contiguous per-expert segments instead of
    a padded ``[E, C, d]`` einsum. Param names/shapes/init are identical to
    ``ExpertFFN`` (``w_up`` ``[E, d, f]``, ``w_down`` ``[E, f, d]``,
    lecun_normal, ``param_dtype``), so checkpoints and the
    ``experts/w_(up|down)`` sharding rules (EP_RULES, llama TP_RULES) are
    unchanged when flipping ``dispatch_impl`` to ``"dropless"``.

    ``ep_dispatch`` selects the execution layout (see the module
    docstring): ``"replicated"`` runs the r14 single-program kernel on the
    replicated sorted array; ``"a2a"`` shard_maps over the ``expert`` mesh
    axis — the weight in_specs match EP_RULES exactly, so no resharding —
    and ``"a2a_overlap"`` additionally splits the transport into
    ``ep_overlap_chunks`` double-buffered windows. Sharded paths fall back
    to replicated when the mesh has no expert axis > 1 or the shape does
    not tile it (one-time warning), keeping init-time tracing and
    single-device runs on the identical param structure.
    """

    num_experts: int
    ffn_dim: int
    dtype: Any
    param_dtype: Any
    ep_dispatch: str = "replicated"  # "replicated" | "a2a" | "a2a_overlap"
    ep_overlap_chunks: int = 2       # a2a_overlap double-buffer windows

    @nn.compact
    def __call__(self, x_sorted, starts, counts):  # [kT, d], [E], [E]
        from pytorch_distributed_training_example_tpu.ops import (
            grouped_matmul as gmm_lib)

        d = x_sorted.shape[-1]
        w_up = self.param("w_up", nn.initializers.lecun_normal(),
                          (self.num_experts, d, self.ffn_dim), self.param_dtype)
        w_down = self.param("w_down", nn.initializers.lecun_normal(),
                            (self.num_experts, self.ffn_dim, d), self.param_dtype)
        ep = _ep_degree(self.ep_dispatch, self.num_experts, x_sorted.shape[0])
        if ep == 1:
            with jax.named_scope("moe_experts_gmm"):
                return gmm_lib.grouped_ffn(x_sorted, w_up.astype(self.dtype),
                                           w_down.astype(self.dtype), starts,
                                           counts)
        # Sharded EP execution: manual over 'expert' only; the other mesh
        # axes are unmentioned (the sorted array is replicated over the
        # batch axes exactly like the r14 path — shard_map's transpose
        # handles the unmentioned-axis cotangents, grads oracle-tested).
        from pytorch_distributed_training_example_tpu.ops import (
            pallas_compat as _compat)  # noqa: F401  jax.shard_map shim
        mesh = mesh_lib.current_mesh()
        a2a_impl = os.environ.get(EP_A2A_IMPL_ENV, "native")
        R = x_sorted.shape[0] // ep
        if self.ep_dispatch == "a2a_overlap":
            n = max(1, min(int(self.ep_overlap_chunks), R))
            rc = -(-R // n)
            chunk_rows = tuple(min(rc, R - c * rc) for c in range(n)
                               if R - c * rc > 0)  # torn last chunk
            body = functools.partial(_ep_overlap_ffn, ep=ep,
                                     chunk_rows=chunk_rows, a2a_impl=a2a_impl)
        else:
            chunk_rows = (R,)
            body = functools.partial(_ep_sharded_ffn, ep=ep,
                                     a2a_impl=a2a_impl)
        try:
            scope = "/".join(self.scope.path)
        except Exception:
            scope = str(self.name)
        _log_a2a_chunks(scope, self.ep_dispatch, ep=ep, rows_per_device=R,
                        d_model=d, chunk_rows=chunk_rows, dtype=self.dtype,
                        impl=a2a_impl)
        fn = jax.shard_map(
            body, mesh=mesh,
            in_specs=(P("expert", None), P("expert", None, None),
                      P("expert", None, None), P(None), P(None)),
            out_specs=P("expert", None), check_vma=False)
        return fn(x_sorted, w_up.astype(self.dtype),
                  w_down.astype(self.dtype), starts, counts)


class RouterDense(nn.Module):
    """Router logits in fp32 WITHOUT an fp32 copy of the [T, d] token block.

    ``nn.Dense(dtype=f32)`` promotes bf16 activations before the dot, which
    materializes an fp32 [T, d] array in the forward and an fp32 [T, d]
    cotangent + downcast chain in the backward — pure residual-stream
    bandwidth charged to the router region. A mixed-precision
    ``lax.dot_general`` with ``preferred_element_type=f32`` produces
    bit-identical logits (bf16 values are exactly representable in fp32, so
    promoting per-element inside the MXU pass changes nothing) with no
    promoted operand in the program.

    ``compute_dtype`` None/fp32 keeps that exact contract (ST-MoE fp32
    router). bf16 casts BOTH operands to bf16 — halved logits-matmul read
    traffic, still fp32 accumulation via ``preferred_element_type`` — and is
    the opt-in ``router_dtype`` A/B; softmax/top-k stay fp32 downstream
    either way.

    Param path/init match ``nn.Dense(name="router")`` exactly ("kernel",
    lecun_normal, fp32), so checkpoints and the ``router/kernel`` sharding
    rules are unaffected.
    """

    features: int
    compute_dtype: Any = None  # None/f32 -> exact mixed dot; bf16 -> bf16 dot

    @nn.compact
    def __call__(self, x):
        kernel = self.param("kernel", nn.initializers.lecun_normal(),
                            (x.shape[-1], self.features), jnp.float32)
        cdt = self.compute_dtype
        if cdt is not None and cdt != jnp.float32:
            x = x.astype(cdt)
            kernel = kernel.astype(cdt)
        return jax.lax.dot_general(
            x, kernel, (((x.ndim - 1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)


class RoutingStats(NamedTuple):
    """Compact-index routing bookkeeping shared by dispatch/aux/telemetry.

    Everything is int32/bool and [E]- or [k·T]-shaped — the fp32 one-hot
    position chain, the aux-loss top-1 fraction, and the load-entropy
    telemetry all derive from these instead of materializing [T, E] masks.
    """

    counts: jax.Array      # [E] assignments per expert (pre-capacity)
    starts: jax.Array      # [E] exclusive-cumsum segment starts
    order: jax.Array       # [k·T] stable argsort of (choice, token) by expert
    pos: jax.Array         # [T, k] position within the expert's queue
    within_cap: jax.Array  # [T, k] bool, pos < capacity


def routing_stats(expert_idx, num_experts: int, capacity: int) -> RoutingStats:
    """One stable argsort + bincount over ``expert_idx`` -> shared stats.

    Flattens the (choice, token) pairs in the priority order (index
    j = k_idx*T + t: all k=0 choices for tokens 0..T-1, then k=1) and
    stable-argsorts by expert id; the within-queue position — rank in
    sorted order minus the expert's segment start — equals the legacy
    [k·T, E] one-hot-cumsum position exactly, drop for drop (stable sort
    preserves the priority order within each expert's run).
    """
    T, k = expert_idx.shape
    e_flat = expert_idx.T.reshape(-1).astype(jnp.int32)         # [kT]
    order = jnp.argsort(e_flat, stable=True)                    # [kT]
    sorted_e = e_flat[order]
    counts = jnp.bincount(e_flat, length=num_experts).astype(jnp.int32)
    starts = jnp.concatenate(
        [jnp.zeros((1,), jnp.int32), jnp.cumsum(counts)[:-1]])
    # Routing index vectors are O(E) and O(k·T) ints — pin them replicated
    # so sharding propagation (backward from the expert-sharded dispatch)
    # can never turn `starts[sorted_e]` into a sharded-operand gather
    # (miscompiled by the jax 0.4.x SPMD partitioner; see MoEBlock._combine).
    counts = mesh_lib.constrain(counts, P(None))
    starts = mesh_lib.constrain(starts, P(None))
    pos_sorted = (jnp.arange(k * T, dtype=jnp.int32) - starts[sorted_e])
    # Invert the permutation to per-(token, choice) positions.
    pos_flat = jnp.zeros((k * T,), jnp.int32).at[order].set(
        pos_sorted, unique_indices=True)
    pos = pos_flat.reshape(k, T).T                              # [T, k]
    within_cap = pos < capacity
    return RoutingStats(counts, starts, order, pos, within_cap)


class MoEBlock(nn.Module):
    """Router + expert FFNs; drop-in replacement for a dense MLP block.

    Dispatch implementations, equivalence-tested against each other (all
    three consume the shared ``routing_stats`` positions):

    - ``"sort"`` (recommended; MegaBlocks-style reformulation): read
      per-expert queues as contiguous runs of the stats' stable-argsort
      order and take the first ``capacity`` entries of each run as the
      ``[E, C, d]`` dispatch. Index work is the shared O(T·k log T·k) sort +
      O(T·k) segment arithmetic — no ``E·C``-slot scatter.
    - ``"gather"``: scatter token ids into an ``[E*C]`` slot table, gather
      token vectors into ``[E, C, d]``, gather expert outputs back by slot.
      Memory O(E*C*d + T*k).
    - ``"einsum"``: the GShard/Switch formulation with an explicit
      ``[T, E, C]`` dispatch/combine mask. O(T*E*C) memory; kept because its
      einsums partition very predictably under GSPMD (useful oracle and
      fallback).
    - ``"dropless"`` (MegaBlocks-style): NO capacity and NO dropped tokens —
      ``capacity_factor`` is irrelevant. Tokens are gathered once into the
      stats' sorted layout and the expert FFNs run as ragged grouped Pallas
      matmuls over the contiguous per-expert segments
      (ops/grouped_matmul.py); combine is the inverse-permutation gather.
      ``moe_drop_fraction`` sows an exact constant 0.0. Matches the einsum
      oracle at a never-drop capacity factor (tests/test_moe_dropless.py);
      the kernel runs interpret-mode off-TPU. ``ep_dispatch`` selects the
      execution layout: ``"replicated"`` (r14 default — single-program
      kernel on the replicated sorted array), ``"a2a"`` (sorted segments
      all-to-all'd to per-device expert shards, gmm against LOCAL weights
      only), or ``"a2a_overlap"`` (chunked double-buffered a2a so expert
      compute hides interconnect latency). All three are bitwise-identical
      per row; see the module docstring and PROFILE_MOE.md r17 addendum.

    ``router_dtype`` sets the logits-matmul precision (``RouterDense``):
    None/fp32 is the exact ST-MoE contract and the default; bf16 halves the
    matmul's read traffic with fp32 accumulation, parity-bounded in
    tests/test_moe_router.py. Softmax/top-k/logsumexp are always fp32.

    ``router_impl`` selects the softmax+top-k+gates computation:
    ``"reference"`` (default; plain XLA fp32 chain) or ``"fused"`` (the
    single-pass Pallas kernel in ops/fused_router.py — one VMEM-resident
    pass over the [T, E] logits, interpret-mode validated on CPU). Both
    produce identical routing decisions; ``fused`` stays opt-in until a
    chip A/B (PROFILE_MOE.md hooks).

    ``combine_dtype`` sets the precision of the output combine (the
    slot-gather of expert outputs + the ``tk,tkd->td`` gate einsum). It
    defaults to fp32 — the historical behavior and the equivalence oracle.
    The combine is pure bandwidth (its FLOPs are negligible; the gather of
    ``[T, k, d]`` expert outputs dominates), so running it in bf16 halves
    its HBM traffic; accumulation stays fp32 via
    ``preferred_element_type``.
    """

    num_experts: int
    ffn_dim: int
    top_k: int = 2
    capacity_factor: float = 1.25
    aux_loss_weight: float = 0.01
    z_loss_weight: float = 1e-3
    dispatch_impl: str = "gather"  # "sort" | "gather" | "einsum" | "dropless"
    dtype: Any = jnp.float32
    param_dtype: Any = jnp.float32
    combine_dtype: Any = None  # None -> fp32 (exact); bf16 halves combine BW
    router_dtype: Any = None   # None -> fp32 logits matmul (exact); bf16 A/B
    router_impl: str = "reference"  # "reference" | "fused" (Pallas)
    # Dropless-only EP execution layout (module docstring; r17):
    # "replicated" = r14 single-program kernel; "a2a" = sharded segments to
    # per-device expert shards; "a2a_overlap" = chunked double-buffered a2a.
    ep_dispatch: str = "replicated"
    ep_overlap_chunks: int = 2

    @nn.compact
    def __call__(self, x, train: bool = True,
                 decode: bool = False):  # x: [B, S, d]
        B, S, d = x.shape
        E = self.num_experts
        tokens = x.reshape(B * S, d)
        T = B * S
        # Serving decode (models/llama.py threads ``decode_ctx`` down as
        # ``decode=True``) always routes DROPLESS, whatever dispatch_impl
        # the checkpoint trained with: capacity-dropped dispatch is
        # non-causal — a token's drop depends on capacity competition from
        # tokens AFTER it and on capacity = f(T) itself — so it has no
        # exact incremental equivalent, while dropless routing is
        # per-token-independent (bitwise row-invariant, r14/r17 contract)
        # and therefore identical between the [T_train] training forward
        # and [B*S] batch-decode shapes. Params are shared across impls
        # (``experts/w_up``/``w_down``), so this is a pure routing switch.
        dropless = self.dispatch_impl == "dropless" or decode
        if self.ep_dispatch != "replicated" and not dropless:
            raise ValueError(
                f"ep_dispatch={self.ep_dispatch!r} only applies to "
                f"dispatch_impl='dropless' (got {self.dispatch_impl!r}); "
                "the capacity-dropped impls shard through GSPMD alone")
        if dropless:
            # No capacity in the dropless formulation; a never-drop value
            # keeps stats.within_cap trivially all-true (and DCE'd — nothing
            # downstream reads it).
            capacity = T * self.top_k
        else:
            raw_capacity = int(self.capacity_factor * T * self.top_k / E)
            if raw_capacity < 1:
                _warn_capacity_clamp(self.capacity_factor, T, self.top_k, E)
            capacity = max(raw_capacity, 1)

        # Router logits in fp32 accumulation (standard for stability); the
        # softmax/top-k decision chain is always fp32.
        with jax.named_scope("moe_router"):
            router_logits = RouterDense(
                E, self.router_dtype, name="router")(tokens)        # [T, E]
            if self.router_impl == "fused":
                from pytorch_distributed_training_example_tpu.ops import (
                    fused_router as fused_router_lib)

                gate_vals, expert_idx, router_lse, router_me = (
                    fused_router_lib.fused_router(router_logits, self.top_k))
                probs = None
            elif self.router_impl == "reference":
                probs = jax.nn.softmax(router_logits, axis=-1)      # [T, E]
                # Top-k expert choice per token.
                gate_vals, expert_idx = jax.lax.top_k(
                    probs, self.top_k)                              # [T, k]
                gate_vals = gate_vals / jnp.maximum(
                    gate_vals.sum(-1, keepdims=True), 1e-9)
                router_lse = router_me = None
            else:
                raise ValueError(
                    f"unknown router_impl {self.router_impl!r}; "
                    "have ['reference', 'fused']")

        with jax.named_scope("moe_dispatch"):
            stats = routing_stats(expert_idx, E, capacity)
            if dropless:
                # Every (token, choice) is kept by construction: sow the
                # exact constant 0.0 instead of the within_cap reductions so
                # XLA DCEs the mask work rather than computing an
                # identically-zero value.
                self.sow("telemetry", "moe_drop_fraction",
                         jnp.zeros((), jnp.float32))
            else:
                gate_vals = gate_vals * stats.within_cap
                # Telemetry (ST-MoE router diagnostics): fraction of
                # (token, choice) assignments beyond expert capacity — exact
                # from the shared [E] counts, no mask re-materialized. sow
                # is a no-op unless the step runs with the "telemetry"
                # collection mutable (utils/telemetry health pack), and XLA
                # DCEs the unused reduction in that case.
                kept = jnp.sum(jnp.minimum(stats.counts, capacity))
                self.sow("telemetry", "moe_drop_fraction",
                         1.0 - kept.astype(jnp.float32) / (T * self.top_k))

        if dropless:
            out = self._dropless_route(tokens, expert_idx, stats, gate_vals)
        elif self.dispatch_impl == "sort":
            out = self._sort_route(tokens, expert_idx, stats, gate_vals,
                                   capacity)
        elif self.dispatch_impl == "einsum":
            out = self._einsum_route(tokens, expert_idx, stats, gate_vals,
                                     capacity)
        else:
            out = self._gather_route(tokens, expert_idx, stats, gate_vals,
                                     capacity)

        with jax.named_scope("moe_aux"):
            # Load-balancing aux loss (Switch eq. 4): E * sum_e f_e * P_e.
            # The gradient flows only through me (counts are int-derived),
            # so the compact ce is exactly gradient-equivalent to the
            # one-hot mean it replaces.
            me = router_me if router_me is not None else probs.mean(0)
            top1 = jnp.bincount(expert_idx[:, 0].astype(jnp.int32), length=E)
            top1 = mesh_lib.constrain(top1, P(None))
            ce = top1.astype(jnp.float32) / T           # top-1 routed frac
            aux = E * jnp.sum(me * ce)
            self.sow("losses", "moe_aux_loss", self.aux_loss_weight * aux)
            # Router z-loss (ST-MoE): keeps logits from drifting to
            # magnitudes where fp32 softmax saturates.
            lse = (router_lse if router_lse is not None else
                   jax.scipy.special.logsumexp(router_logits, axis=-1))
            z = jnp.mean(lse ** 2)
            self.sow("losses", "moe_z_loss", self.z_loss_weight * z)
            # Telemetry: entropy of the routed-load distribution over all k
            # choices (pre-capacity), normalized by ln(E) so 1.0 = perfectly
            # balanced, 0.0 = collapsed onto one expert. Shares the [E]
            # counts with dispatch — zero extra router-region traffic.
            load = stats.counts.astype(jnp.float32) / (T * self.top_k)
            ent = -jnp.sum(load * jnp.log(load + 1e-9)) / jnp.log(float(E))
            self.sow("telemetry", "router_load_entropy", ent)

        return out.reshape(B, S, d).astype(self.dtype)

    def _experts(self, dispatched):
        with jax.named_scope("moe_experts"):
            dispatched = mesh_lib.constrain(dispatched, P("expert", None, None))
            expert_out = ExpertFFN(self.num_experts, self.ffn_dim, self.dtype,
                                   self.param_dtype, name="experts")(dispatched)
            return mesh_lib.constrain(expert_out, P("expert", None, None))

    def _combine(self, expert_out, slot, gate_vals, n_slots):
        """Gather expert outputs back by slot and mix by gate weight.

        [E, C, d] expert outputs -> [T, k, d] gather by slot (the trash row
        n_slots reads zeros for dropped tokens) -> gate-weighted sum over k.
        Runs in ``combine_dtype`` (fp32 default); the einsum accumulates in
        fp32 either way via preferred_element_type.
        """
        with jax.named_scope("moe_combine"):
            d = expert_out.shape[-1]
            cdt = self.combine_dtype or jnp.float32
            out_pad = jnp.concatenate(
                [expert_out.reshape(n_slots, d).astype(cdt),
                 jnp.zeros((1, d), cdt)])                       # trash row
            # Replicate the slot table before the combine gather. Every
            # token needs rows from every expert, so GSPMD must all-gather
            # the [E·C, d] outputs over 'expert' here regardless; making it
            # explicit also sidesteps a jax 0.4.x SPMD partitioner
            # miscompile for gathers with sharded operands (wrong values,
            # reproduced in tests/test_moe_sort_dispatch.py's EP suite).
            out_pad = mesh_lib.constrain(out_pad, P(None, None))
            y = out_pad[slot]                                   # [T, k, d]
            return jnp.einsum("tk,tkd->td", gate_vals.astype(cdt), y,
                              preferred_element_type=jnp.float32)

    def _dropless_route(self, tokens, expert_idx, stats, gate_vals):
        """Dropless dispatch (MegaBlocks): ragged grouped matmul, no capacity.

        The shared stats' stable argsort already lays the (token, choice)
        pairs out as contiguous per-expert segments, so dispatch is ONE
        ``[kT, d]`` gather into sorted order and the expert FFNs consume the
        ragged layout directly via the Pallas gmm kernel with the ``[E]``
        segment starts/counts — no ``[E, C, d]`` buffer exists in the
        program. Combine is the scatter-add back through the sort
        permutation, read-side: the permutation is a bijection (nothing
        dropped, no trash row), so each (t, k)'s output row sits at
        ``slot = starts[expert] + pos`` and a gather + gate einsum is exact.
        """
        T, d = tokens.shape
        ep = _ep_degree(self.ep_dispatch, self.num_experts,
                        stats.order.shape[0])
        with jax.named_scope("moe_dispatch"):
            tok_flat = (stats.order % T).astype(jnp.int32)
            x_sorted = tokens[tok_flat].astype(self.dtype)       # [kT, d]
            # Pin the sorted layout: replicated for the single-program
            # kernel (pallas_call does not partition under GSPMD, and the
            # pin also sidesteps the jax 0.4.x sharded-operand gather
            # miscompile — see _combine); expert-sliced for the sharded EP
            # paths, matching the shard_map in_specs so GSPMD feeds the
            # manual region without a reshard.
            x_sorted = mesh_lib.constrain(
                x_sorted, P("expert", None) if ep > 1 else P(None, None))
        with jax.named_scope("moe_experts"):
            y_sorted = GroupedExpertFFN(
                self.num_experts, self.ffn_dim, self.dtype, self.param_dtype,
                ep_dispatch=self.ep_dispatch,
                ep_overlap_chunks=self.ep_overlap_chunks,
                name="experts")(x_sorted, stats.starts, stats.counts)
        with jax.named_scope("moe_combine"):
            cdt = self.combine_dtype or jnp.float32
            slot = stats.starts[expert_idx] + stats.pos          # [T, k]
            y_sorted = mesh_lib.constrain(y_sorted.astype(cdt), P(None, None))
            y = y_sorted[slot]                                   # [T, k, d]
            return jnp.einsum("tk,tkd->td", gate_vals.astype(cdt), y,
                              preferred_element_type=jnp.float32)

    def _sort_route(self, tokens, expert_idx, stats, gate_vals, capacity):
        """Sort-based dispatch (MegaBlocks-style, capacity-dropped).

        Expert e's queue = sorted entries [starts[e], starts[e]+C) of the
        shared stats order: one [E, C] take of token rows — no E*C scatter,
        no [T, k, E] mask. Overflow entries (c >= counts[e]) read the zero
        row T.
        """
        T, d = tokens.shape
        E = self.num_experts
        k = self.top_k
        n_slots = E * capacity
        with jax.named_scope("moe_dispatch"):
            tok_flat = (stats.order % T).astype(jnp.int32)
            take = stats.starts[:, None] + jnp.arange(
                capacity, dtype=jnp.int32)[None, :]
            valid = (jnp.arange(capacity)[None, :]
                     < stats.counts[:, None])                    # [E, C]
            tok_for_slot = jnp.where(
                valid, tok_flat[jnp.minimum(take, k * T - 1)], T)
            tokens_pad = jnp.concatenate(
                [tokens, jnp.zeros((1, d), tokens.dtype)])       # row T = 0
            dispatched = tokens_pad[tok_for_slot].astype(self.dtype)
        expert_out = self._experts(dispatched)
        slot = jnp.where(stats.within_cap,
                         expert_idx * capacity + stats.pos, n_slots)  # [T, k]
        return self._combine(expert_out, slot, gate_vals, n_slots)

    def _gather_route(self, tokens, expert_idx, stats, gate_vals, capacity):
        T, d = tokens.shape
        E = self.num_experts
        n_slots = E * capacity
        with jax.named_scope("moe_dispatch"):
            # Each kept (token, choice) owns one slot; the trash row (index
            # n_slots) absorbs dropped tokens. Slots are unique per expert
            # queue position, so the scatter has no collisions.
            slot = jnp.where(stats.within_cap,
                             expert_idx * capacity + stats.pos,
                             n_slots)                               # [T, k]
            tok_ids = jnp.broadcast_to(
                jnp.arange(T, dtype=jnp.int32)[:, None], slot.shape)
            token_for_slot = jnp.full((n_slots + 1,), T, jnp.int32)
            token_for_slot = token_for_slot.at[slot.reshape(-1)].set(
                tok_ids.reshape(-1))
            tokens_pad = jnp.concatenate(
                [tokens, jnp.zeros((1, d), tokens.dtype)])          # row T = 0
            dispatched = tokens_pad[token_for_slot[:n_slots]].reshape(
                E, capacity, d).astype(self.dtype)
        expert_out = self._experts(dispatched)
        return self._combine(expert_out, slot, gate_vals, n_slots)

    def _einsum_route(self, tokens, expert_idx, stats, gate_vals, capacity):
        E = self.num_experts
        with jax.named_scope("moe_dispatch"):
            # The explicit-mask formulation IS this impl's definition: the
            # one-hots here are its dispatch/combine operands, built from
            # the shared stats positions (not a second position chain).
            onehot = jax.nn.one_hot(expert_idx, E,
                                    dtype=jnp.float32)              # [T,k,E]
            cap_onehot = jax.nn.one_hot(stats.pos, capacity,
                                        dtype=jnp.float32)          # [T,k,C]
            dispatch = jnp.einsum(
                "tke,tkc->tec", onehot,
                cap_onehot * stats.within_cap[..., None])
            combine = jnp.einsum("tke,tkc,tk->tec", onehot, cap_onehot,
                                 gate_vals)
            dispatched = jnp.einsum(
                "tec,td->ecd", dispatch,
                tokens.astype(jnp.float32)).astype(self.dtype)
        expert_out = self._experts(dispatched)
        with jax.named_scope("moe_combine"):
            return jnp.einsum("tec,ecd->td", combine,
                              expert_out.astype(jnp.float32))


#: Expert-parallel rules: stacked expert weights shard on the 'expert' axis
#: (composes with fsdp on the remaining dims via AUTO composition).
EP_RULES = (
    (r"experts/w_(up|down)", P("expert", None, None)),
    (r"router/kernel", P()),
)
