"""Mixture-of-Experts with expert parallelism over the ``expert`` mesh axis.

SURVEY.md §2c "EP": Switch/GShard-style token routing, built the GSPMD way —
expert-stacked FFN weights sharded on ``expert``; XLA partitions the expert
einsums and inserts the token all-to-all automatically (no hand-written
routing transport).

Top-k gating (k=1 Switch, k=2 GShard defaults), capacity factor with token
dropping, and the standard load-balancing auxiliary loss (mean(gates)*
fraction-routed per expert, scaled by E), surfaced via the flax ``sow``
mechanism under the ``"losses"`` collection as ``moe_aux_loss``.

Three dispatch implementations share identical routing/drop semantics (the
priority order is: earlier tokens first, k=0 choices before k=1) and are
equivalence-tested against each other — see ``dispatch_impl`` on
``MoEBlock``. The step regions are tagged with ``jax.named_scope`` (
``moe_router`` / ``moe_dispatch`` / ``moe_experts`` / ``moe_combine`` /
``moe_aux``) so ``benchmarks/profile_step.py`` can attribute device time
per region from an xplane trace (PROFILE_MOE.md).
"""

from __future__ import annotations

from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from pytorch_distributed_training_example_tpu.core import mesh as mesh_lib

BATCH = mesh_lib.BATCH_AXES


class ExpertFFN(nn.Module):
    """Stacked expert MLPs applied to dispatched tokens [E, C, d]."""

    num_experts: int
    ffn_dim: int
    dtype: Any
    param_dtype: Any

    @nn.compact
    def __call__(self, x):  # [E, C, d]
        d = x.shape[-1]
        w_up = self.param("w_up", nn.initializers.lecun_normal(),
                          (self.num_experts, d, self.ffn_dim), self.param_dtype)
        w_down = self.param("w_down", nn.initializers.lecun_normal(),
                            (self.num_experts, self.ffn_dim, d), self.param_dtype)
        h = jnp.einsum("ecd,edf->ecf", x, w_up.astype(self.dtype),
                       preferred_element_type=jnp.float32).astype(self.dtype)
        h = nn.gelu(h)
        out = jnp.einsum("ecf,efd->ecd", h, w_down.astype(self.dtype),
                         preferred_element_type=jnp.float32).astype(self.dtype)
        return out


class MoEBlock(nn.Module):
    """Router + expert FFNs; drop-in replacement for a dense MLP block.

    Dispatch implementations, equivalence-tested against each other:

    - ``"sort"`` (recommended; MegaBlocks-style reformulation): stable-argsort
      the (token, choice) pairs by expert id, recover per-expert segment
      offsets from the sorted order, and take the first ``capacity`` entries
      of each expert's contiguous run as the ``[E, C, d]`` dispatch. Index
      work is O(T·k log T·k) sort + O(T·k) segment arithmetic — no
      ``[T, k, E]`` one-hot mask, no ``k·T × E`` cumsum, no ``E·C``-slot
      scatter. Same capacity-overflow drop semantics (stable sort preserves
      the priority order within each expert queue).
    - ``"gather"``: scatter token ids into an ``[E*C]`` slot table, gather
      token vectors into ``[E, C, d]``, gather expert outputs back by slot.
      Computes queue positions via a ``[k·T, E]`` one-hot cumsum. Memory
      O(E*C*d + T*k); index work O(T·k·E).
    - ``"einsum"``: the GShard/Switch formulation with an explicit
      ``[T, E, C]`` dispatch/combine mask. O(T*E*C) memory; kept because its
      einsums partition very predictably under GSPMD (useful oracle and
      fallback).

    ``combine_dtype`` sets the precision of the output combine (the
    slot-gather of expert outputs + the ``tk,tkd->td`` gate einsum). It
    defaults to fp32 — the historical behavior and the equivalence oracle.
    The combine is pure bandwidth (its FLOPs are negligible; the gather of
    ``[T, k, d]`` expert outputs dominates), so running it in bf16 halves
    its HBM traffic; accumulation stays fp32 via
    ``preferred_element_type``. Router logits/softmax/top-k are always fp32.
    """

    num_experts: int
    ffn_dim: int
    top_k: int = 2
    capacity_factor: float = 1.25
    aux_loss_weight: float = 0.01
    z_loss_weight: float = 1e-3
    dispatch_impl: str = "gather"  # "sort" | "gather" | "einsum"
    dtype: Any = jnp.float32
    param_dtype: Any = jnp.float32
    combine_dtype: Any = None  # None -> fp32 (exact); bf16 halves combine BW

    @nn.compact
    def __call__(self, x, train: bool = True):  # x: [B, S, d]
        B, S, d = x.shape
        E = self.num_experts
        tokens = x.reshape(B * S, d)
        T = B * S
        capacity = max(int(self.capacity_factor * T * self.top_k / E), 1)

        # Router in fp32 (standard for stability).
        with jax.named_scope("moe_router"):
            router_logits = nn.Dense(E, use_bias=False, dtype=jnp.float32,
                                     param_dtype=jnp.float32,
                                     name="router")(tokens.astype(jnp.float32))
            probs = jax.nn.softmax(router_logits, axis=-1)          # [T, E]

            # Top-k expert choice per token.
            gate_vals, expert_idx = jax.lax.top_k(probs, self.top_k)  # [T, k]
            gate_vals = gate_vals / jnp.maximum(
                gate_vals.sum(-1, keepdims=True), 1e-9)

        if self.dispatch_impl == "sort":
            out = self._sort_route(tokens, expert_idx, gate_vals, capacity)
        else:
            with jax.named_scope("moe_dispatch"):
                # Capacity bucketing: position of each token within its
                # expert queue, via the [k·T, E] one-hot cumsum.
                onehot = jax.nn.one_hot(expert_idx, E,
                                        dtype=jnp.float32)  # [T, k, E]
                # priority: earlier tokens first, k=0 choices before k=1
                flat = onehot.transpose(1, 0, 2).reshape(self.top_k * T, E)
                pos_in_expert = jnp.cumsum(flat, axis=0) - flat     # [kT, E]
                pos = (pos_in_expert.reshape(self.top_k, T, E)
                       .transpose(1, 0, 2) * onehot).sum(-1)        # [T, k]
                within_cap = pos < capacity
                gate_vals = gate_vals * within_cap
                # Telemetry (ST-MoE router diagnostics): fraction of
                # (token, choice) assignments beyond expert capacity. sow is
                # a no-op unless the step runs with the "telemetry"
                # collection mutable (utils/telemetry health pack), and XLA
                # DCEs the unused mean in that case.
                self.sow("telemetry", "moe_drop_fraction",
                         1.0 - jnp.mean(within_cap.astype(jnp.float32)))

            if self.dispatch_impl == "einsum":
                out = self._einsum_route(tokens, onehot, pos, within_cap,
                                         gate_vals, capacity)
            else:
                out = self._gather_route(tokens, expert_idx, pos, within_cap,
                                         gate_vals, capacity)

        with jax.named_scope("moe_aux"):
            # Load-balancing aux loss (Switch eq. 4): E * sum_e f_e * P_e.
            me = probs.mean(0)                            # mean router prob
            ce = jax.nn.one_hot(expert_idx[:, 0], E,
                                dtype=jnp.float32).mean(0)  # top-1 routed frac
            aux = E * jnp.sum(me * ce)
            self.sow("losses", "moe_aux_loss", self.aux_loss_weight * aux)
            # Router z-loss (ST-MoE): keeps logits from drifting to
            # magnitudes where fp32 softmax saturates.
            z = jnp.mean(
                jax.scipy.special.logsumexp(router_logits, axis=-1) ** 2)
            self.sow("losses", "moe_z_loss", self.z_loss_weight * z)
            # Telemetry: entropy of the routed-load distribution over all k
            # choices (pre-capacity), normalized by ln(E) so 1.0 = perfectly
            # balanced, 0.0 = collapsed onto one expert. Sown under the
            # "telemetry" collection — free unless the health pack is on.
            load = jax.nn.one_hot(expert_idx, E,
                                  dtype=jnp.float32).mean((0, 1))  # [E]
            ent = -jnp.sum(load * jnp.log(load + 1e-9)) / jnp.log(float(E))
            self.sow("telemetry", "router_load_entropy", ent)

        return out.reshape(B, S, d).astype(self.dtype)

    def _experts(self, dispatched):
        with jax.named_scope("moe_experts"):
            dispatched = mesh_lib.constrain(dispatched, P("expert", None, None))
            expert_out = ExpertFFN(self.num_experts, self.ffn_dim, self.dtype,
                                   self.param_dtype, name="experts")(dispatched)
            return mesh_lib.constrain(expert_out, P("expert", None, None))

    def _combine(self, expert_out, slot, gate_vals, n_slots):
        """Gather expert outputs back by slot and mix by gate weight.

        [E, C, d] expert outputs -> [T, k, d] gather by slot (the trash row
        n_slots reads zeros for dropped tokens) -> gate-weighted sum over k.
        Runs in ``combine_dtype`` (fp32 default); the einsum accumulates in
        fp32 either way via preferred_element_type.
        """
        with jax.named_scope("moe_combine"):
            d = expert_out.shape[-1]
            cdt = self.combine_dtype or jnp.float32
            out_pad = jnp.concatenate(
                [expert_out.reshape(n_slots, d).astype(cdt),
                 jnp.zeros((1, d), cdt)])                       # trash row
            # Replicate the slot table before the combine gather. Every
            # token needs rows from every expert, so GSPMD must all-gather
            # the [E·C, d] outputs over 'expert' here regardless; making it
            # explicit also sidesteps a jax 0.4.x SPMD partitioner
            # miscompile for gathers with sharded operands (wrong values,
            # reproduced in tests/test_moe_sort_dispatch.py's EP suite).
            out_pad = mesh_lib.constrain(out_pad, P(None, None))
            y = out_pad[slot]                                   # [T, k, d]
            return jnp.einsum("tk,tkd->td", gate_vals.astype(cdt), y,
                              preferred_element_type=jnp.float32)

    def _sort_route(self, tokens, expert_idx, gate_vals, capacity):
        """Sort-based dispatch (MegaBlocks-style, capacity-dropped).

        Flattens the (choice, token) pairs in the legacy priority order
        (index j = k_idx*T + t: all k=0 choices for tokens 0..T-1, then
        k=1), stable-argsorts by expert id, and reads per-expert queues as
        contiguous runs of the sorted order. Stable sort preserves the
        priority order within each expert, so the within-queue position —
        rank in sorted order minus the expert's segment start — equals the
        one-hot-cumsum position of the gather/einsum paths exactly, drop
        for drop.
        """
        T, d = tokens.shape
        E = self.num_experts
        k = self.top_k
        n_slots = E * capacity
        with jax.named_scope("moe_dispatch"):
            e_flat = expert_idx.T.reshape(-1).astype(jnp.int32)     # [kT]
            order = jnp.argsort(e_flat, stable=True)                # [kT]
            sorted_e = e_flat[order]
            counts = jnp.bincount(e_flat, length=E).astype(jnp.int32)
            starts = jnp.concatenate(
                [jnp.zeros((1,), jnp.int32), jnp.cumsum(counts)[:-1]])
            # Routing index vectors are O(E) and O(k·T) ints — pin them
            # replicated so sharding propagation (backward from the
            # expert-sharded dispatch) can never turn `starts[sorted_e]`
            # into a sharded-operand gather (miscompiled by the jax 0.4.x
            # SPMD partitioner; see _combine).
            counts = mesh_lib.constrain(counts, P(None))
            starts = mesh_lib.constrain(starts, P(None))
            pos_sorted = (jnp.arange(k * T, dtype=jnp.int32)
                          - starts[sorted_e])
            # Invert the permutation to per-(token, choice) positions.
            pos_flat = jnp.zeros((k * T,), jnp.int32).at[order].set(
                pos_sorted, unique_indices=True)
            pos = pos_flat.reshape(k, T).T                          # [T, k]
            within_cap = pos < capacity
            gate_vals = gate_vals * within_cap
            # Same telemetry scalar as the gather/einsum path (positions are
            # drop-for-drop identical across dispatch impls).
            self.sow("telemetry", "moe_drop_fraction",
                     1.0 - jnp.mean(within_cap.astype(jnp.float32)))

            # Expert e's queue = sorted entries [starts[e], starts[e]+C):
            # one [E, C] take of token rows — no E*C scatter, no [T,k,E]
            # mask. Overflow entries (c >= counts[e]) read the zero row T.
            tok_flat = (order % T).astype(jnp.int32)
            take = starts[:, None] + jnp.arange(capacity,
                                                dtype=jnp.int32)[None, :]
            valid = jnp.arange(capacity)[None, :] < counts[:, None]  # [E, C]
            tok_for_slot = jnp.where(
                valid, tok_flat[jnp.minimum(take, k * T - 1)], T)
            tokens_pad = jnp.concatenate(
                [tokens, jnp.zeros((1, d), tokens.dtype)])          # row T = 0
            dispatched = tokens_pad[tok_for_slot].astype(self.dtype)
        expert_out = self._experts(dispatched)
        slot = jnp.where(within_cap,
                         expert_idx * capacity + pos, n_slots)      # [T, k]
        return self._combine(expert_out, slot, gate_vals, n_slots)

    def _gather_route(self, tokens, expert_idx, pos, within_cap, gate_vals,
                      capacity):
        T, d = tokens.shape
        E = self.num_experts
        n_slots = E * capacity
        with jax.named_scope("moe_dispatch"):
            # Each kept (token, choice) owns one slot; the trash row (index
            # n_slots) absorbs dropped tokens. Slots are unique per expert
            # queue position, so the scatter has no collisions.
            slot = jnp.where(within_cap,
                             expert_idx * capacity + pos.astype(jnp.int32),
                             n_slots)                               # [T, k]
            tok_ids = jnp.broadcast_to(
                jnp.arange(T, dtype=jnp.int32)[:, None], slot.shape)
            token_for_slot = jnp.full((n_slots + 1,), T, jnp.int32)
            token_for_slot = token_for_slot.at[slot.reshape(-1)].set(
                tok_ids.reshape(-1))
            tokens_pad = jnp.concatenate(
                [tokens, jnp.zeros((1, d), tokens.dtype)])          # row T = 0
            dispatched = tokens_pad[token_for_slot[:n_slots]].reshape(
                E, capacity, d).astype(self.dtype)
        expert_out = self._experts(dispatched)
        return self._combine(expert_out, slot, gate_vals, n_slots)

    def _einsum_route(self, tokens, onehot, pos, within_cap, gate_vals,
                      capacity):
        with jax.named_scope("moe_dispatch"):
            # Dispatch mask [T, k, E, C] -> combined [T, E, C].
            cap_onehot = jax.nn.one_hot(pos.astype(jnp.int32), capacity,
                                        dtype=jnp.float32)  # [T,k,C]
            dispatch = jnp.einsum("tke,tkc->tec", onehot,
                                  cap_onehot * within_cap[..., None])
            combine = jnp.einsum("tke,tkc,tk->tec", onehot, cap_onehot,
                                 gate_vals)
            dispatched = jnp.einsum(
                "tec,td->ecd", dispatch,
                tokens.astype(jnp.float32)).astype(self.dtype)
        expert_out = self._experts(dispatched)
        with jax.named_scope("moe_combine"):
            return jnp.einsum("tec,ecd->td", combine,
                              expert_out.astype(jnp.float32))


#: Expert-parallel rules: stacked expert weights shard on the 'expert' axis
#: (composes with fsdp on the remaining dims via AUTO composition).
EP_RULES = (
    (r"experts/w_(up|down)", P("expert", None, None)),
    (r"router/kernel", P()),
)
