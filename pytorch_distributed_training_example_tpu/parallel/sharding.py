"""Parallelism as data: parameter partition rules over the named mesh.

Reference parity (SURVEY.md §2c): the reference's only strategy object is the
``DistributedDataParallel`` wrapper (replicate params, all-reduce grads); its
config matrix additionally names FSDP and gradient checkpointing. Here every
strategy — DP, FSDP/ZeRO-3, TP, and their compositions — is a *table of
rules* mapping parameter path patterns to :class:`PartitionSpec`s. Changing
strategy changes the table, not the model or the train step: XLA's GSPMD
partitioner reads the resulting ``NamedSharding``s and inserts the
all-gathers / reduce-scatters / psums that DDP's C++ reducer and FSDP's
wrapper perform by hand on GPU.

Rule syntax: ``(regex, PartitionSpec)`` matched (``re.search``) against the
``'/'``-joined parameter path, first match wins. The special sentinel
:data:`AUTO_FSDP` shards the largest divisible dimension along the ``fsdp``
axis — the generic ZeRO-3 fallback that needs no per-model table.
"""

from __future__ import annotations

import math
import re
from typing import Any, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from pytorch_distributed_training_example_tpu.core import mesh as mesh_lib

#: Sentinel: shard the largest dim divisible by the fsdp axis size.
AUTO_FSDP = "AUTO_FSDP"

Rule = tuple[str, Any]  # (path regex, PartitionSpec | AUTO_FSDP)


def param_path(keypath) -> str:
    """Render a jax tree key-path as 'a/b/c'."""
    parts = []
    for k in keypath:
        if hasattr(k, "key"):
            parts.append(str(k.key))
        elif hasattr(k, "idx"):
            parts.append(str(k.idx))
        elif hasattr(k, "name"):
            parts.append(str(k.name))
        else:
            parts.append(str(k))
    return "/".join(parts)


#: Params smaller than this many elements stay replicated under AUTO_FSDP
#: (norm scales, biases): sharding tiny tensors costs more in collective
#: latency than it saves in HBM — torch FSDP's min-wrap-size analog.
MIN_SHARD_ELEMENTS = 16384


def _auto_fsdp_spec(shape: Sequence[int], fsdp_size: int, extra: P | None = None) -> P:
    """Shard the largest dimension divisible by ``fsdp_size``; replicate if none.

    ``extra`` (a PartitionSpec of same rank, e.g. a TP spec) marks dims that
    are already taken; the fsdp axis composes with it on a free dim.
    """
    if fsdp_size <= 1 or (math.prod(shape) < MIN_SHARD_ELEMENTS if shape else True):
        return extra if extra is not None else P()
    taken = list(extra) if extra is not None else [None] * len(shape)
    taken += [None] * (len(shape) - len(taken))
    best, best_dim = -1, None
    for d, s in enumerate(shape):
        if taken[d] is None and s % fsdp_size == 0 and s > best:
            best, best_dim = s, d
    if best_dim is None:
        return P(*taken) if extra is not None else P()
    taken[best_dim] = "fsdp"
    return P(*taken)


def _drop_indivisible(spec: P, shape: Sequence[int], mesh: Mesh) -> P:
    """Replicate any dim whose size isn't divisible by its assigned axes.

    The standard GQA case: KV-head kernels with fewer heads than the tensor-
    parallel degree stay replicated across 'model' (each TP shard holds all
    KV heads) instead of erroring out.
    """
    out = []
    for d, entry in enumerate(spec):
        if entry is None or d >= len(shape):
            out.append(entry)
            continue
        axes = entry if isinstance(entry, (tuple, list)) else (entry,)
        size = math.prod(mesh.shape.get(a, 1) for a in axes)
        out.append(entry if shape[d] % size == 0 else None)
    return P(*out)


def spec_for(path: str, shape: Sequence[int], rules: Sequence[Rule], mesh: Mesh) -> P:
    fsdp_size = mesh.shape.get("fsdp", 1)
    for pattern, spec in rules:
        if re.search(pattern, path):
            if isinstance(spec, str) and spec == AUTO_FSDP:
                return _auto_fsdp_spec(shape, fsdp_size)
            # nn.scan-stacked layers add exactly one leading 'layers' dim;
            # rule tables are written for the unstacked rank, so shift the
            # spec right by one (leading dim replicated).
            if len(shape) == len(spec) + 1:
                spec = P(None, *spec)
            # Compose explicit (e.g. TP) specs with auto-fsdp on a free dim.
            spec = mesh_lib._prune_spec(spec, mesh)
            spec = _drop_indivisible(spec, shape, mesh)
            return _auto_fsdp_spec(shape, fsdp_size, extra=spec) if fsdp_size > 1 else spec
    return _auto_fsdp_spec(shape, fsdp_size)


def infer_specs(params, rules: Sequence[Rule], mesh: Mesh):
    """Pytree of PartitionSpec matching ``params``' structure."""

    def one(keypath, x):
        shape = np.shape(x)
        return spec_for(param_path(keypath), shape, rules, mesh)

    return jax.tree_util.tree_map_with_path(one, params)


def make_shardings(params_or_specs, mesh: Mesh, rules: Sequence[Rule] = ()):
    """Pytree of NamedSharding for ``params`` (or an already-inferred spec tree)."""
    leaves = jax.tree.leaves(params_or_specs)
    if leaves and isinstance(leaves[0], P):
        specs = params_or_specs
    else:
        specs = infer_specs(params_or_specs, rules, mesh)
    return jax.tree.map(lambda s: NamedSharding(mesh, s), specs,
                        is_leaf=lambda x: isinstance(x, P))


def shard_params(params, mesh: Mesh, rules: Sequence[Rule] = ()):
    """Place (or re-place) a param pytree according to the rules."""
    shardings = make_shardings(params, mesh, rules)
    return jax.tree.map(jax.device_put, params, shardings)


# ---------------------------------------------------------------------------
# Sequence/context-axis activation rules
# ---------------------------------------------------------------------------

#: SNIPPETS.md [3]'s ``"seq": None  # TODO`` entry, filled: the sequence
#: dimension of every activation shards over the ``context`` mesh axis, so a
#: layer sees ``[B, S/seq, d]``. Norms, FFN and the MoE router are
#: position-wise — they run purely local on the seq shard; only attention
#: communicates across it (ring ppermute / Ulysses a2a in ops/attention.py).


def seq_rules(sp: bool = False) -> dict[str, P]:
    """Activation rule table for the sequence/context axis.

    Keys are the logical activation names the model constrain sites use;
    values carry the sequence dim on ``'context'``. ``sp`` additionally folds
    the TP (``'model'``) axis into the sequence dim between matmul regions
    (Megatron sequence parallelism, arXiv:2205.05198) — inside matmul regions
    the hidden/head dim holds ``'model'`` instead, so those entries keep the
    sequence dim on ``'context'`` alone.
    """
    seq = ("context", "model") if sp else "context"
    b = mesh_lib.BATCH_AXES
    return {
        "residual": P(b, seq, None),             # [B, S/seq, d]
        "qkv": P(b, "context", "model", None),   # [B, S/seq, H/tp, Dh]
        "ffn_hidden": P(b, "context", "model"),  # [B, S/seq, ffn/tp]
        "logits": P(b, seq, None),               # [B, S/seq, vocab]
    }


# ---------------------------------------------------------------------------
# Strategy tables
# ---------------------------------------------------------------------------

#: Pure DP — replicate everything (the reference's DDP semantics).
DP_RULES: tuple[Rule, ...] = ((".*", P()),)

#: ZeRO-3 / FSDP — shard every param's largest divisible dim on 'fsdp'.
FSDP_RULES: tuple[Rule, ...] = ((".*", AUTO_FSDP),)


def strategy_rules(strategy: str, model_rules: dict[str, Sequence[Rule]] | None = None):
    """Resolve a strategy name to its rule table.

    ``model_rules`` lets a model family contribute TP tables (e.g. Megatron
    column/row splits for attention and MLP); generic strategies need none.
    A ``_sp`` suffix (Megatron sequence parallelism) and a ``pp`` strategy
    reuse the family's TP table — SP changes activation constraints and PP
    changes the step schedule, not the parameter sharding.
    """
    model_rules = model_rules or {}
    base = strategy.removesuffix("_sp")
    if base in model_rules:
        return tuple(model_rules[base])
    if base in ("dp", "ddp", "none"):
        return DP_RULES
    if base in ("fsdp", "zero3", "pp"):
        return FSDP_RULES
    raise ValueError(f"unknown strategy {strategy!r} (model provides {sorted(model_rules)})")
