"""Parallelism strategies expressed as sharding rules over the named mesh (SURVEY.md §2c)."""
