"""Pipeline parallelism: GPipe microbatch schedule over the ``stage`` mesh axis.

SURVEY.md §2c "PP": the reference has none (DDP example); the TPU-native
design is stage-sliced parameters + a microbatch schedule where activations
hop between neighboring stages with ``ppermute`` (one ICI hop — stages map
to adjacent chips on the torus).

Design: ``shard_map`` over the ``stage`` axis. Parameters are stacked with a
leading ``[num_stages, ...]`` dim sharded on ``stage`` (each chip holds one
stage's weights). The schedule is the classic GPipe fill/steady/drain loop:
at tick ``t``, stage ``s`` processes microbatch ``t - s`` (when valid), then
passes its activation to stage ``s+1``. Total ticks = M + S - 1; bubble
fraction (S-1)/(M+S-1) — choose microbatches >= 4x stages. Backward is just
``jax.grad`` through the loop: ``ppermute`` transposes to the reverse
permutation, giving the symmetric backward pipeline automatically.

Inactive fill/drain ticks skip the stage computation via ``lax.cond`` (a
real XLA conditional, not a discarded ``where``), so the bubble costs idle
time but no FLOPs. ``remat_stages=True`` recomputes each stage in backward,
bounding saved activations to the stage *inputs* per microbatch.

Schedule decision — GPipe + remat_stages over 1F1B (VERDICT r2 #7):
1F1B does NOT shrink the bubble — both schedules idle (S-1) fill + (S-1)
drain ticks, bubble fraction (S-1)/(M+S-1): at the recommended operating
point M=32, S=4 that is 3/35 = **8.6%** of ticks (M=32, S=8: 7/39 = 18%;
the fix at larger S is more microbatches, M=64/S=8: 7/71 = 9.9%). What
1F1B buys is *memory*: it caps live activation sets at S per stage instead
of GPipe's M. Here ``remat_stages=True`` already caps live state at M
*stage-inputs* (one microbatch activation each — for a transformer stage
of L layers that is ~1/(20·L) of the full per-layer activation set that
1F1B would hold S of), so GPipe+remat strictly dominates 1F1B on memory
at these M while matching its bubble, at the price of one extra forward
recompute (~33% more stage FLOPs — the same price per-block remat already
pays in the fsdp+remat configs). An *interleaved* 1F1B (multiple
nonadjacent layer chunks per chip, bubble/(v·S)) is the only schedule that
actually shrinks the bubble; it multiplies ppermute traffic by the
interleave factor v and is not worth it below S≈16 stages — far beyond
the v5p-32 target topology (BASELINE.json configs[4]).

The stage function must be shape-preserving (activation in == activation
out), which transformer blocks satisfy.
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from pytorch_distributed_training_example_tpu.ops import pallas_compat  # noqa: F401

from pytorch_distributed_training_example_tpu.core import mesh as mesh_lib


def stack_stage_params(per_stage_params: list) -> Any:
    """Stack a list of per-stage param pytrees along a new leading dim."""
    return jax.tree.map(lambda *xs: jnp.stack(xs), *per_stage_params)


def pipeline_apply(
    stage_fn: Callable[[Any, jax.Array], jax.Array],
    stage_params: Any,
    x: jax.Array,
    *,
    mesh: Mesh,
    num_microbatches: int,
    axis: str = "stage",
    batch_axes=mesh_lib.BATCH_AXES,
    remat_stages: bool = False,
) -> jax.Array:
    """Run ``stage_fn`` as an S-stage pipeline over microbatches of ``x``.

    Args:
        stage_fn: ``(params_for_one_stage, x_mb) -> y_mb``, shape-preserving.
        stage_params: pytree whose leaves have leading dim ``num_stages``
            (see :func:`stack_stage_params`), sharded on ``axis``.
        x: ``[batch, ...]`` global input; batch must divide by
            ``num_microbatches`` (and the data axes).
    Returns:
        ``[batch, ...]`` output, equal to applying all stages sequentially.
    """
    S = mesh.shape[axis]
    M = num_microbatches
    if S == 1:
        def seq_fn(params, x):
            for i in range(params_leading_dim(stage_params)):
                x = stage_fn(jax.tree.map(lambda p: p[i], stage_params), x)
            return x
        return seq_fn(stage_params, x)
    B = x.shape[0]
    assert B % M == 0, (B, M)
    mb = B // M
    x_mb = x.reshape(M, mb, *x.shape[1:])

    def per_stage(params_local, x_mb):
        # Stage fns may run model code containing global sharding
        # constraints; inside shard_map those don't apply.
        with mesh_lib.no_constrain():
            return _per_stage_body(params_local, x_mb)

    def _per_stage_body(params_local, x_mb):
        # shard_map gives the local stage slice with leading dim 1: drop it.
        params = jax.tree.map(lambda p: jnp.squeeze(p, 0), params_local)
        stage = jax.lax.axis_index(axis)
        act_shape = x_mb.shape[1:]
        buf = jnp.zeros(act_shape, x_mb.dtype)        # activation entering this stage
        outs = jnp.zeros_like(x_mb)                   # collected on the last stage

        fwd_perm = [(i, i + 1) for i in range(S - 1)]
        run_stage = (jax.checkpoint(stage_fn, prevent_cse=False)
                     if remat_stages else stage_fn)

        def tick(t, carry):
            buf, outs = carry
            mb_idx = t - stage
            # Stage 0 reads microbatch t from the input; others read buf.
            src = jnp.where(stage == 0,
                            jax.lax.dynamic_index_in_dim(
                                x_mb, jnp.clip(t, 0, M - 1), keepdims=False),
                            buf)
            active = (mb_idx >= 0) & (mb_idx < M)
            # Fill/drain ticks skip the stage compute entirely (the ring
            # still rotates, keeping every device in lockstep).
            y = jax.lax.cond(active, lambda p, s: run_stage(p, s),
                             lambda p, s: jnp.zeros_like(s), params, src)
            # Last stage stores its (valid) result.
            is_last = stage == S - 1
            outs = jnp.where(
                (active & is_last),
                jax.lax.dynamic_update_index_in_dim(
                    outs, y, jnp.clip(mb_idx, 0, M - 1), axis=0),
                outs)
            # Scoped so the stage-hop traffic is attributable in the AOT
            # comms census and sanctioned by graftlint GL105.
            with jax.named_scope("pp_stage_shift"):
                buf = jax.lax.ppermute(y, axis, fwd_perm)
            return buf, outs

        _, outs = jax.lax.fori_loop(0, M + S - 1, tick, (buf, outs))
        # Replicate the last stage's outputs across the stage axis so the
        # result is stage-replicated (out_spec has no stage entry).
        outs = jax.lax.psum(
            jnp.where(stage == S - 1, outs, jnp.zeros_like(outs)), axis)
        return outs

    batch_spec = P(None, batch_axes, *([None] * (x.ndim - 1)))
    param_specs = jax.tree.map(lambda _: P(axis), stage_params)
    out = jax.shard_map(
        per_stage, mesh=mesh,
        in_specs=(param_specs, batch_spec),
        out_specs=batch_spec,
        check_vma=False,
    )(stage_params, x_mb)
    return out.reshape(B, *x.shape[1:])


def params_leading_dim(tree) -> int:
    return jax.tree.leaves(tree)[0].shape[0]


def sequential_apply(stage_fn, stage_params, x):
    """The single-device oracle: all stages applied in order."""
    S = params_leading_dim(stage_params)
    for i in range(S):
        x = stage_fn(jax.tree.map(lambda p: p[i], stage_params), x)
    return x
