"""Serving subsystem: continuous-batching decode over a paged GQA KV cache.

Layout mirrors the training stack it reuses:

- ``kv_cache``  — page pool + page tables (the vLLM-style memory layer)
- ``engine``    — bucketed AOT prefill/decode steps + continuous batching
- ``loadgen``   — seeded open-loop Poisson request generator
- ``aot``       — chipless AOT byte/FLOP model of the decode step
"""

from pytorch_distributed_training_example_tpu.serve.kv_cache import (  # noqa: F401
    CacheSpec, PagePool, append_pages, gather_pages, init_cache,
    pages_for_tokens)
