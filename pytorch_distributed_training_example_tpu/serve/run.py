"""CLI serving session behind ``main.py --serve``.

Builds the (decode-capable) model from the training Config, restores
parameters only — ``Checkpointer.restore_params``, skipping the optimizer
state that dominates checkpoint bytes — and drains a seeded synthetic
request stream through the continuous-batching engine, printing a JSON
summary. The same Config fields that describe the training run (model,
precision, seq_len, seed, metrics_port) describe the serving one, so a
checkpoint trained by ``main.py`` serves with the identical flags plus
``--serve --resume``.
"""

from __future__ import annotations

import json
import time

from pytorch_distributed_training_example_tpu.utils import resilience


def serve_loop(driver, eng, drain_timeout_s: float = 5.0,
               tick=None) -> dict:
    """Drive the open-loop stream until drained — or gracefully shut down.

    When a SIGTERM lands (``resilience.preempted()``, handler installed by
    :func:`main`), the loop stops pumping new requests and *drains*: active
    slots keep decoding to completion via ``eng.step(admit=False)``, bounded
    by ``drain_timeout_s``, instead of dying mid-decode-step. This is the
    serving counterpart of the trainer's checkpoint-and-yield path — finish
    the in-flight work, then exit ``PREEMPTED_EXIT_CODE`` — which is what
    makes serving jobs preemptible by the fleet scheduler
    (``launch.py --fleet``) with nothing worse than truncated tail latency.

    ``tick``, when given, runs every 128 iterations — the SLO observability
    hook (flush slo.jsonl, push gauges, rotate request-trace rings). It is
    host-side bookkeeping only; it must never touch device state.
    """
    t0 = time.perf_counter()
    drain_deadline = None
    it = 0
    while driver.remaining or eng.has_work:
        it += 1
        if tick is not None and it % 128 == 0:
            tick()
        if drain_deadline is None and resilience.preempted():
            drain_deadline = time.perf_counter() + drain_timeout_s
        if drain_deadline is not None:
            if eng.num_active == 0 or time.perf_counter() >= drain_deadline:
                break
            eng.step(admit=False)
            continue
        driver.pump(eng, time.perf_counter() - t0)
        if eng.has_work:
            eng.step()
        else:
            time.sleep(0.0005)
    return {"wall_s": time.perf_counter() - t0,
            "preempted": drain_deadline is not None,
            "drained": eng.num_active == 0}


def main(cfg) -> dict:
    import jax
    import jax.numpy as jnp
    import numpy as np

    from pytorch_distributed_training_example_tpu.core import (
        checkpoint as ckpt_lib)
    from pytorch_distributed_training_example_tpu.models import registry
    from pytorch_distributed_training_example_tpu.serve import (
        engine as engine_lib, loadgen)

    dtype = jnp.float32 if cfg.precision == "fp32" else jnp.bfloat16
    bundle = registry.create_model(cfg.model, seq_len=cfg.seq_len,
                                   dtype=dtype, param_dtype=dtype)
    module = bundle.module
    if not hasattr(module, "num_kv_heads"):
        raise SystemExit(f"--serve needs a decode-capable LM, "
                         f"got --model {cfg.model}")

    params = module.init(jax.random.PRNGKey(cfg.seed),
                         jnp.zeros((1, 8), jnp.int32), train=False)["params"]
    restored_step = None
    if cfg.resume:
        directory = cfg.checkpoint_dir if cfg.resume == "auto" else cfg.resume
        if not directory:
            raise SystemExit("--serve --resume auto needs --checkpoint-dir")
        ck = ckpt_lib.Checkpointer(directory)
        params, _ = ck.restore_params(params)
        restored_step = ck.last_restored_step

    metrics = None
    if cfg.metrics_port is not None:
        from pytorch_distributed_training_example_tpu.utils import fleetobs

        metrics = fleetobs.MetricsServer(port=cfg.metrics_port).start()

    # r20 SLO observability: one SLOTracker for the session, one
    # RequestTrace ring per replica (a disaggregated pair shares its
    # replica's tracer — role lanes keep prefill/decode apart). The
    # run id is deterministic (seed-derived fallback) so same-seed runs
    # produce byte-identical slo.jsonl headers.
    slo_tracker = None
    tracers: dict[str, object] = {}
    run_id = ""
    flightrec = None
    if cfg.serve_slo:
        from pytorch_distributed_training_example_tpu.serve import (
            slo as slo_lib)
        from pytorch_distributed_training_example_tpu.utils import fleetobs

        run_id = fleetobs.ensure_run_id(cfg.checkpoint_dir or "",
                                        f"serve_s{cfg.seed}")
        slo_tracker = slo_lib.SLOTracker(
            window=cfg.serve_slo_window,
            ttft_target_ms=cfg.serve_slo_ttft_ms,
            itl_target_ms=cfg.serve_slo_itl_ms)
        if cfg.checkpoint_dir:
            flightrec = fleetobs.FlightRecorder()
            fleetobs.set_active(flightrec, cfg.checkpoint_dir,
                                meta={"mode": "serve", "run_id": run_id})

    spec = engine_lib.spec_for_module(module, num_pages=cfg.serve_num_pages,
                                      page_size=cfg.serve_page_size)
    buckets = lambda s: tuple(int(t) for t in s.split(",") if t)

    def build_proposer():
        """Speculative-decode proposer per replica (r19). "ngram" is a
        string the engine resolves itself; "draft" builds a separate
        small model, params-only restored when the flag names a
        checkpoint as "name@dir" (mirroring the target's restore)."""
        mode = cfg.serve_spec_decode
        if mode in ("", "off"):
            return None
        if mode == "ngram":
            return "ngram"
        if mode != "draft":
            raise SystemExit(f"unknown --serve-spec-decode {mode!r}")
        if not cfg.serve_draft_model:
            raise SystemExit("--serve-spec-decode draft needs "
                             "--serve-draft-model")
        from pytorch_distributed_training_example_tpu.serve import (
            spec_decode as spec_decode_lib)

        name, _, draft_dir = cfg.serve_draft_model.partition("@")
        draft = registry.create_model(name, seq_len=cfg.seq_len,
                                      dtype=dtype, param_dtype=dtype)
        dparams = draft.module.init(
            jax.random.PRNGKey(cfg.seed),
            jnp.zeros((1, 8), jnp.int32), train=False)["params"]
        if draft_dir:
            dparams, _ = ckpt_lib.Checkpointer(draft_dir).restore_params(
                dparams)
        return spec_decode_lib.DraftModelProposer(
            draft.module, dparams, draft_len=cfg.serve_draft_len)

    def build_replica(name: str = "replica0"):
        """One serve replica: a single engine, or a prefill/decode pair
        under --serve-disaggregate. All replicas share module + params
        (one process, one set of weights) but own separate page pools."""
        kw = dict(decode_buckets=buckets(cfg.serve_decode_buckets),
                  prompt_buckets=buckets(cfg.serve_prompt_buckets),
                  max_model_len=cfg.serve_max_model_len or None,
                  metrics=metrics)
        if slo_tracker is not None:
            from pytorch_distributed_training_example_tpu.serve import (
                slo as slo_lib)

            rt = slo_lib.RequestTrace(name, run_id=run_id,
                                      capacity=cfg.serve_trace_events)
            tracers[name] = rt
            kw.update(reqtrace=rt, slo=slo_tracker)
        spec_kw = dict(spec_decode=build_proposer(),
                       draft_len=cfg.serve_draft_len)
        if cfg.serve_disaggregate:
            return engine_lib.DisaggregatedServe(
                engine_lib.ContinuousBatchingEngine(
                    module, params, spec, role="prefill",
                    prefix_cache=cfg.serve_prefix_cache,
                    prefill_chunk=cfg.serve_prefill_chunk, **kw),
                engine_lib.ContinuousBatchingEngine(
                    module, params, spec, role="decode", **spec_kw, **kw))
        return engine_lib.ContinuousBatchingEngine(
            module, params, spec, prefix_cache=cfg.serve_prefix_cache,
            prefill_chunk=cfg.serve_prefill_chunk, **spec_kw, **kw)

    if cfg.serve_replicas > 1:
        from pytorch_distributed_training_example_tpu.serve import (
            router as router_lib)

        replicas = {f"replica{i}": build_replica(f"replica{i}")
                    for i in range(cfg.serve_replicas)}
        for rep in replicas.values():
            rep.warmup()
        eng = router_lib.PrefixAffinityRouter(
            replicas, page_size=cfg.serve_page_size, policy=cfg.serve_route)
    else:
        eng = build_replica()
        eng.warmup()

    # The synthetic stream must fit what the engine was warmed for: prompts
    # no longer than the largest prompt bucket, prompt + new tokens within
    # the model-length budget.
    plen_cap = max(buckets(cfg.serve_prompt_buckets))
    len_budget = (cfg.serve_max_model_len or module.max_seq_len) - plen_cap
    defaults = loadgen.LoadSpec()
    # Template prefix + random suffix together must fit the prompt cap.
    pfx_min_s, _, pfx_max_s = cfg.serve_prefix_len.partition(":")
    pfx_max = min(int(pfx_max_s or pfx_min_s),
                  plen_cap - defaults.prompt_len_min)
    pfx_min = min(int(pfx_min_s), pfx_max)
    suffix_cap = plen_cap - (pfx_max if cfg.serve_templates else 0)
    requests = loadgen.generate_requests(loadgen.LoadSpec(
        num_requests=cfg.serve_requests, rate=cfg.serve_rate,
        prompt_len_min=min(defaults.prompt_len_min, suffix_cap),
        prompt_len_max=max(1, min(defaults.prompt_len_max, suffix_cap)),
        max_new_min=max(1, min(defaults.max_new_min, len_budget)),
        max_new_max=max(1, min(defaults.max_new_max, len_budget)),
        vocab_size=int(module.vocab_size), seed=cfg.seed,
        num_templates=cfg.serve_templates, zipf_a=cfg.serve_zipf_a,
        prefix_len_min=pfx_min, prefix_len_max=pfx_max))
    # SIGTERM becomes a bounded drain + exit 75 instead of a mid-step death
    # (the scheduler's preemption contract). Install is idempotent and a
    # no-op off the main thread (in-process tests drive serve_loop directly).
    resilience.install()
    driver = loadgen.OpenLoopDriver(requests)

    slo_tick = None
    if slo_tracker is not None:
        tick_count = [0]

        def slo_tick():
            """Periodic host-side SLO bookkeeping (serve_loop, every 128
            iterations): flush the window file, push live gauges, rotate
            rings nearing capacity, dump the flight recorder on a fresh
            breach episode. Never touches device state."""
            tick_count[0] += 1
            dropped = sum(rt.dropped_spans for rt in tracers.values())
            if cfg.checkpoint_dir:
                slo_tracker.flush(cfg.checkpoint_dir, run_id,
                                  dropped_spans=dropped)
                for rt in tracers.values():
                    if rt.pending >= (rt.capacity * 3) // 4:
                        rt.rotate(cfg.checkpoint_dir)
            if metrics is not None:
                metrics.update(**slo_tracker.gauges(extra_dropped=dropped))
                metrics.update_histograms(**slo_tracker.histograms())
            if flightrec is not None:
                flightrec.record_timing(
                    tick_count[0],
                    attainment=round(slo_tracker.overall_attainment(), 4),
                    breaches=slo_tracker.breaches, dropped_spans=dropped)
            breach = slo_tracker.breach()
            if breach is not None:
                fleetobs.dump_active(
                    f"slo_breach:{breach}",
                    attainment=slo_tracker.overall_attainment())

    outcome = serve_loop(driver, eng,
                         drain_timeout_s=cfg.serve_drain_timeout,
                         tick=slo_tick)
    wall = outcome["wall_s"]

    completed = eng.completed
    if cfg.serve_replicas > 1:
        fleet = eng.fleet_stats()
        stats = {}
        for rep in fleet["replicas"].values():
            for k, v in rep["stats"].items():
                stats[k] = stats.get(k, 0) + v
    else:
        fleet = None
        stats = dict(eng.stats)
    ttfts = sorted(r.ttft_s for r in completed if r.ttft_s is not None)
    result = {
        "mode": "serve",
        "model": cfg.model,
        "restored_step": restored_step,
        "requests_completed": len(completed),
        "tokens_generated": stats["tokens_generated"],
        "tokens_per_s": round(stats["tokens_generated"]
                              / max(wall, 1e-9), 2),
        "ttft_p50_ms": (round(1e3 * float(np.percentile(ttfts, 50)), 3)
                        if ttfts else None),
        "compiles": stats["compiles"],
        "decode_steps": stats["decode_steps"],
        "evictions": stats["evictions"],
        "metrics_port": metrics.port if metrics is not None else None,
        "preempted": outcome["preempted"],
        "drained": outcome["drained"],
    }
    if cfg.serve_prefix_cache:
        result["prefix_cache"] = {
            "hit_rate": round(stats["cached_tokens"]
                              / max(stats["prompt_tokens"], 1), 4),
            "cached_tokens": stats["cached_tokens"],
            "cow_copies": stats["cow_copies"],
        }
    if cfg.serve_spec_decode not in ("", "off"):
        drafted = stats.get("draft_tokens", 0)
        result["spec_decode"] = {
            "mode": cfg.serve_spec_decode,
            "spec_steps": stats.get("spec_steps", 0),
            "draft_tokens": drafted,
            "accepted_tokens": stats.get("accepted_tokens", 0),
            "accept_rate": round(stats.get("accepted_tokens", 0)
                                 / max(drafted, 1), 4),
            "accepted_len_hist": {
                n: stats.get(f"spec_accept_{n}", 0)
                for n in range(cfg.serve_draft_len + 1)},
        }
    if slo_tracker is not None:
        # Final breach check + artifact flush: slo.jsonl (atomic) plus one
        # request-trace snapshot per replica, all under the checkpoint dir
        # where trace_merge.py and the fleet scheduler look for them.
        breach = slo_tracker.breach()
        if breach is not None:
            fleetobs.dump_active(
                f"slo_breach:{breach}",
                attainment=slo_tracker.overall_attainment())
        dropped = sum(rt.dropped_spans for rt in tracers.values())
        if cfg.checkpoint_dir:
            slo_tracker.flush(cfg.checkpoint_dir, run_id,
                              dropped_spans=dropped)
            for rt in tracers.values():
                rt.write(cfg.checkpoint_dir)
        if metrics is not None:
            metrics.update(**slo_tracker.gauges(extra_dropped=dropped))
            metrics.update_histograms(**slo_tracker.histograms())
        result["slo"] = {
            "run_id": run_id,
            "attainment": round(slo_tracker.overall_attainment(), 4),
            "breaches": slo_tracker.breaches,
            "dropped_spans": dropped,
            "windows": slo_tracker.snapshot(),
        }
    if cfg.serve_disaggregate:
        result["handoffs"] = stats["handoffs_out"]
    if fleet is not None:
        result["router"] = {k: v for k, v in fleet.items()
                            if k != "replicas"}
        result["router"]["per_replica_completed"] = {
            name: rep["completed"]
            for name, rep in fleet["replicas"].items()}
    if metrics is not None:
        metrics.stop()
    print(json.dumps(result, indent=2))
    if outcome["preempted"]:
        raise resilience.PreemptedExit()
    return result
