"""Front-end router: prefix-affinity placement across serve replicas.

Prefix caches are PER-REPLICA (each engine owns its own ``PagePool``),
so fleet-level hit rate depends on placement: two requests sharing a
system prompt only share KV if they land on the same replica. The
router keys each request by the rolling hash chain of its full
``page_size``-token chunks and remembers which replica last served each
chain link; a new request goes to the replica owning its LONGEST hashed
prefix (that replica's tree has those pages), falling back to the
least-loaded replica (active + waiting, lowest index on ties —
deterministic, GL005). ``policy="least_loaded"`` disables affinity for
A/B runs.

Failure handling rides the existing drain-on-SIGTERM semantics:

- ``drain(name)`` — the replica stops taking new work; its queued
  (waiting) requests re-route immediately, its in-flight requests finish
  locally via ``step(admit=False)`` and the replica leaves the rotation
  once idle. Zero drops.
- ``kill(name)`` — hard loss: everything incomplete on the replica
  (queued AND in-flight) re-routes with runtime state reset, so greedy
  recompute regenerates the identical token stream elsewhere. Zero
  drops, at recompute cost.

Replicas are any engine-shaped object (``ContinuousBatchingEngine`` or
``DisaggregatedServe``): submit/step/has_work/num_active/waiting/
completed. The router itself exposes the same protocol, so
``serve_loop`` and the open-loop driver run unchanged against it.
"""

from __future__ import annotations

import dataclasses

from pytorch_distributed_training_example_tpu.serve.engine import Request

_HASH_MASK = (1 << 61) - 1


def chunk_keys(prompt: list[int], page_size: int) -> list[int]:
    """Rolling hash chain over the prompt's full page-size chunks: key i
    summarizes tokens [0, (i+1)*page_size). Process-stable (no ``hash``)
    so router decisions replay across runs and machines."""
    keys = []
    h = 0
    for i in range(len(prompt) // page_size):
        for tok in prompt[i * page_size:(i + 1) * page_size]:
            h = (h * 1000003 + tok + 1) & _HASH_MASK
        keys.append(h)
    return keys


@dataclasses.dataclass
class _ReplicaState:
    engine: object
    alive: bool = True      # taking new placements
    draining: bool = False  # finishing in-flight work before leaving


class PrefixAffinityRouter:
    """Spread an open-loop stream over replicas, prefix-affinity first."""

    def __init__(self, replicas: dict[str, object], page_size: int,
                 policy: str = "affinity"):
        if policy not in ("affinity", "least_loaded"):
            raise ValueError(f"unknown routing policy {policy!r}")
        if not replicas:
            raise ValueError("router needs at least one replica")
        self.policy = policy
        self.page_size = page_size
        self._replicas = {name: _ReplicaState(eng)
                          for name, eng in replicas.items()}
        self._owner: dict[int, str] = {}       # chunk key -> replica name
        self._placed: dict[str, str] = {}      # request id -> replica name
        self.stats = {"routed": 0, "affinity_hits": 0, "rerouted": 0,
                      "drained": 0, "killed": 0}

    # ------------------------------------------------------------- placement

    def _alive(self) -> list[str]:
        return [n for n, s in self._replicas.items() if s.alive]

    def _load(self, name: str) -> int:
        eng = self._replicas[name].engine
        return eng.num_active + len(eng.waiting)

    def route(self, req: Request) -> str:
        """Pick a replica: deepest owned chunk-chain link wins, else
        least-loaded; record ownership of the request's whole chain."""
        alive = self._alive()
        if not alive:
            raise RuntimeError("no live replicas")
        keys = chunk_keys(req.prompt, self.page_size)
        choice = None
        if self.policy == "affinity":
            for key in reversed(keys):
                owner = self._owner.get(key)
                if owner is not None and self._replicas[owner].alive:
                    choice = owner
                    self.stats["affinity_hits"] += 1
                    break
        if choice is None:
            choice = min(alive, key=lambda n: (self._load(n), n))
        for key in keys:
            self._owner[key] = choice
        return choice

    def submit(self, req: Request) -> None:
        hits_before = self.stats["affinity_hits"]
        name = self.route(req)
        self._placed[req.request_id] = name
        self._replicas[name].engine.submit(req)
        self.stats["routed"] += 1
        self._trace(name, "router_admit", req,
                    affinity_hit=self.stats["affinity_hits"] > hits_before)

    def _trace(self, name: str, event: str, req: Request, **args) -> None:
        """Stamp a routing decision onto the CHOSEN replica's request
        trace (serve/slo.py), if that replica records one. Host-side
        bookkeeping only — the router never touches device state."""
        rt = getattr(self._replicas[name].engine, "reqtrace", None)
        if rt is not None:
            rt.instant(event, role="router", request_id=req.request_id,
                       replica=name, **args)

    # ------------------------------------------------------------- lifecycle

    def _reroute(self, req: Request) -> None:
        """Re-place a request displaced from a lost replica, with runtime
        state reset so greedy recompute reproduces its exact stream."""
        req.generated.clear()
        req.token_times.clear()
        req.first_token_t = None
        req.admit_t = None
        req.evictions += 1
        name = self.route(req)
        self._placed[req.request_id] = name
        self._replicas[name].engine.submit(req)
        self.stats["rerouted"] += 1
        self._trace(name, "router_reroute", req, evictions=req.evictions)

    def drain(self, name: str) -> int:
        """SIGTERM semantics: stop placements, re-route the queue, let
        in-flight requests finish locally. Returns requests re-routed."""
        state = self._replicas[name]
        if not state.alive:
            return 0
        state.alive = False
        state.draining = True
        self.stats["drained"] += 1
        moved = 0
        while state.engine.waiting:
            self._reroute(state.engine.waiting.popleft())
            moved += 1
        return moved

    def kill(self, name: str) -> int:
        """Hard replica loss: everything incomplete re-routes (in-flight
        requests lose their pages and recompute elsewhere)."""
        state = self._replicas[name]
        was_alive = state.alive
        state.alive = False
        state.draining = False
        self.stats["killed"] += was_alive
        moved = 0
        while state.engine.waiting:
            self._reroute(state.engine.waiting.popleft())
            moved += 1
        for req in list(getattr(state.engine, "slots", [])):
            if req is not None:
                self._reroute(req)
                moved += 1
        # A DisaggregatedServe replica holds in-flight work in both
        # engines plus the handoff queues.
        for attr in ("prefill_engine", "decode_engine"):
            sub = getattr(state.engine, attr, None)
            if sub is None:
                continue
            while sub.waiting:
                self._reroute(sub.waiting.popleft())
                moved += 1
            for req in sub.slots:
                if req is not None:
                    self._reroute(req)
                    moved += 1
            for h in sub.take_handoffs():
                self._reroute(h.req)
                moved += 1
            while sub._inbox:
                self._reroute(sub._inbox.popleft().req)
                moved += 1
        return moved

    # ---------------------------------------------------------- engine shape

    @property
    def num_active(self) -> int:
        return sum(s.engine.num_active for s in self._replicas.values()
                   if s.alive or s.draining)

    @property
    def waiting(self) -> list[Request]:
        out = []
        for state in self._replicas.values():
            out.extend(state.engine.waiting)
        return out

    @property
    def has_work(self) -> bool:
        return any(s.engine.has_work for s in self._replicas.values()
                   if s.alive or s.draining)

    @property
    def completed(self) -> list[Request]:
        out = []
        for state in self._replicas.values():
            out.extend(state.engine.completed)
        return out

    def step(self, admit: bool = True) -> int:
        """One iteration across the fleet (deterministic replica order).
        Draining replicas run admit-free until their last in-flight
        request completes, then leave the rotation."""
        produced = 0
        for state in self._replicas.values():
            if state.alive:
                produced += state.engine.step(admit=admit)
            elif state.draining:
                produced += state.engine.step(admit=False)
                if not state.engine.has_work:
                    state.draining = False
        return produced

    def run(self, max_steps: int = 100000) -> list[Request]:
        steps = 0
        while self.has_work:
            self.step()
            steps += 1
            if steps > max_steps:
                raise RuntimeError(
                    f"router did not drain in {max_steps} steps")
        return self.completed

    def fleet_stats(self) -> dict:
        """Router counters plus per-replica engine stats and hit rates."""
        per = {}
        for name, state in self._replicas.items():
            eng = state.engine
            per[name] = {
                "completed": len(eng.completed),
                "alive": state.alive,
                "stats": dict(eng.stats),
                "prefix_hit_rate": (eng.prefix_hit_rate()
                                    if hasattr(eng, "prefix_hit_rate")
                                    else 0.0),
            }
        return {**self.stats, "replicas": per}
