"""Prefix cache: a hash-keyed tree over token chunks mapping to pool pages.

N concurrent users of one system prompt should pay its prefill once.
The tree stores one node per ``page_size``-token chunk of previously
prefilled prompts; each node pins one physical page in the ``PagePool``
(under the cache's own owner id), and ``_admit`` splices matched pages
into a new request's page table instead of recomputing their KV. This is
SGLang's RadixAttention idea restricted to page granularity, which is
what our vLLM-style ``PagePool`` supports natively (PAPERS.md).

Two node flavors:

- FULL nodes hold exactly ``page_size`` tokens. Their pages are safe to
  share zero-copy: decode appends only ever land past a sequence's
  current length, so a full page that entered the cache is never written
  through any follower's table — unless the follower's *last prompt
  token* falls inside it (the fully-cached-prompt clamp), in which case
  the engine copy-on-writes that single page before prefilling it.
- PARTIAL nodes hold a sub-page tail chunk (< page_size tokens). They
  match on longest common prefix and their pages are shared
  copy-on-write: the first divergent write (a follower's differing
  prompt tail, or the publishing request's own next decode token)
  triggers a page copy in the engine. Stale tokens past the matched
  length are masked by position, exactly like pool garbage.

Correctness rests on KV determinism: the KV vector at position ``p`` is
a pure function of tokens ``[0, p]`` (causal attention, RoPE applied at
absolute positions), so cached pages are valid under any continuation.

The cache NEVER touches device memory. It is a host-side index: the
engine owns the compiled COW/prefill programs; this module only decides
which page ids to splice, pin, and evict. Eviction is LRU over
unreferenced leaves (``refs == 0``), aged by a monotonic counter — no
wall clock, so same-seed runs evict identically (GL005).
"""

from __future__ import annotations

import dataclasses
from typing import Optional

from .kv_cache import PagePool

CACHE_OWNER = "__prefix_cache__"  # PagePool owner id for pinned pages


class _Node:
    """One cached chunk: ``chunk`` tokens living in physical ``page``."""

    __slots__ = ("chunk", "page", "parent", "children", "partials", "refs",
                 "last_use")

    def __init__(self, chunk: tuple, page: int, parent: "_Node | None"):
        self.chunk = chunk
        self.page = page
        self.parent = parent
        self.children: dict[tuple, _Node] = {}   # full chunks, keyed by tokens
        self.partials: dict[tuple, _Node] = {}   # sub-page tails
        self.refs = 0          # requests currently pinning this node
        self.last_use = 0      # monotonic tick, for LRU

    @property
    def is_leaf(self) -> bool:
        return not self.children and not self.partials


def _common_prefix(a: tuple, b: tuple) -> int:
    n = min(len(a), len(b))
    for i in range(n):
        if a[i] != b[i]:
            return i
    return n


@dataclasses.dataclass
class PrefixMatch:
    """Result of a tree walk: pages to splice and how many tokens they
    cover after the first-token clamp (the engine must still prefill at
    least the final prompt token to get logits)."""

    nodes: list
    tokens: int

    @property
    def pages(self) -> list[int]:
        return [n.page for n in self.nodes]


class PrefixCache:
    """Host-side prefix tree pinning pages in a shared ``PagePool``."""

    def __init__(self, pool: PagePool, page_size: int):
        self.pool = pool
        self.page_size = page_size
        self._root = _Node((), -1, None)
        self._tick = 0
        self._nodes = 0
        self.stats = {"inserted_pages": 0, "evicted_pages": 0}

    @property
    def cached_pages(self) -> int:
        return self._nodes

    def _touch(self, node: _Node) -> None:
        self._tick += 1
        node.last_use = self._tick

    # ------------------------------------------------------------------
    # lookup / pin / unpin

    def match(self, prompt: list[int], max_tokens: int) -> PrefixMatch:
        """Longest cached prefix of ``prompt``, capped at ``max_tokens``
        usable tokens (callers pass ``len(prompt) - 1`` so the final
        prompt token is always prefilled for its logits)."""
        ps = self.page_size
        prompt_t = tuple(prompt)
        nodes: list[_Node] = []
        node = self._root
        pos = 0
        if max_tokens <= 0:
            return PrefixMatch([], 0)
        while pos + ps <= len(prompt_t):
            child = node.children.get(prompt_t[pos:pos + ps])
            if child is None:
                break
            nodes.append(child)
            node = child
            pos += ps
            if pos >= max_tokens:
                # Last full node covers the clamp point; writes into it
                # go through the engine's COW path.
                return PrefixMatch(nodes, max_tokens)
        # Best partial tail under the deepest full node: longest common
        # prefix wins, insertion order (dict order) breaks ties.
        remainder = prompt_t[pos:]
        best: Optional[_Node] = None
        best_n = 0
        if remainder:
            for part in node.partials.values():
                n = _common_prefix(part.chunk, remainder)
                if n > best_n:
                    best, best_n = part, n
        if best is not None:
            nodes.append(best)
            pos += best_n
        return PrefixMatch(nodes, min(pos, max_tokens))

    def acquire(self, match: PrefixMatch, request_id: str) -> None:
        """Pin matched nodes for ``request_id``: bumps node refs and adds
        the request as a pool owner of every matched page."""
        for node in match.nodes:
            node.refs += 1
            self._touch(node)
        self.pool.share(request_id, match.pages)

    def release(self, nodes: list) -> None:
        """Unpin nodes (pool refs are released by the engine via
        ``pool.free``/``pool.drop`` — this only drops the tree pins that
        guard against eviction)."""
        for node in nodes:
            if node.refs <= 0:
                raise ValueError("prefix-cache node ref underflow")
            node.refs -= 1

    # ------------------------------------------------------------------
    # insert / evict

    def insert(self, prompt: list[int], pages: list[int]) -> int:
        """Register a freshly prefilled prompt's pages.

        ``pages[i]`` holds tokens ``[i*ps, (i+1)*ps)``. Chunks already in
        the tree are skipped (the request's duplicate page simply stays
        private); new full chunks and a sub-page tail, if any, become
        nodes pinning their page under ``CACHE_OWNER``. Returns the
        number of pages newly pinned.
        """
        ps = self.page_size
        prompt_t = tuple(prompt)
        node = self._root
        added = 0
        pos = 0
        while pos + ps <= len(prompt_t):
            chunk = prompt_t[pos:pos + ps]
            child = node.children.get(chunk)
            if child is None:
                child = _Node(chunk, pages[pos // ps], node)
                node.children[chunk] = child
                self.pool.share(CACHE_OWNER, [child.page])
                self._nodes += 1
                added += 1
            self._touch(child)
            node = child
            pos += ps
        tail = prompt_t[pos:]
        if tail and tail not in node.partials:
            part = _Node(tail, pages[pos // ps], node)
            node.partials[tail] = part
            self.pool.share(CACHE_OWNER, [part.page])
            self._nodes += 1
            added += 1
            self._touch(part)
        self.stats["inserted_pages"] += added
        return added

    def evict(self, n: int) -> int:
        """Drop up to ``n`` unreferenced LEAF nodes, oldest first,
        releasing the cache's pool pin on each (the page only returns to
        the free list once every other owner releases it too). Interior
        nodes become evictable once their subtrees go; one sweep per
        call keeps the cost bounded and deterministic."""
        evicted = 0
        while evicted < n:
            victim: Optional[_Node] = None
            stack = [self._root]
            while stack:
                node = stack.pop()
                for group in (node.children, node.partials):
                    for child in group.values():
                        if child.is_leaf and child.refs == 0:
                            if victim is None or child.last_use < victim.last_use:
                                victim = child
                        else:
                            stack.append(child)
            if victim is None:
                break
            parent = victim.parent
            if victim.chunk in parent.children and \
                    parent.children[victim.chunk] is victim:
                del parent.children[victim.chunk]
            else:
                del parent.partials[victim.chunk]
            self.pool.drop(CACHE_OWNER, victim.page)
            self._nodes -= 1
            evicted += 1
        self.stats["evicted_pages"] += evicted
        return evicted
