"""Continuous-batching decode engine over the paged KV cache.

The serving loop that turns the trainer's forward pass into a token
stream: requests are admitted and retired BETWEEN decode steps (Orca-style
iteration-level scheduling), so a long generation never holds the batch
hostage and a finished request's pages return to the pool immediately.

Shape discipline is the whole design: every compiled program runs at one
of a small set of padded BATCH BUCKETS (and, for prefill, prompt-length
buckets), all AOT-compiled at warmup through the same
``jit(...).lower(abstract).compile()`` front-end the r13 profile/lint
stack uses — steady-state continuous batching therefore NEVER recompiles
(``stats["compiles"]`` is flat after warmup; asserted in tests). Inactive
rows in a bucket carry token 0, position 0 and a page table full of the
reserved scratch page, so their lanes compute garbage that is never read.

Host/device split per step: exactly ONE device->host sync (the batched
next-token fetch that stop conditions need); admission, page allocation
and eviction are pure host bookkeeping on the ``PagePool`` free list.

Eviction: a slot that cannot get a page (pool exhausted) evicts the
YOUNGEST active request — its pages free immediately and the request
re-queues at the head of the waiting line, to be recomputed when pressure
drops (recompute-on-readmit, the classic vLLM preemption policy).
"""

from __future__ import annotations

import collections
import contextlib
import dataclasses
import time
from typing import Any, Callable

import jax
import jax.numpy as jnp
import numpy as np

from pytorch_distributed_training_example_tpu.serve import kv_cache
from pytorch_distributed_training_example_tpu.serve.kv_cache import (
    CacheSpec, PagePool, pages_for_tokens)


@dataclasses.dataclass
class Request:
    """One generation request. The engine fills the runtime fields."""

    request_id: str
    prompt: list[int]
    max_new_tokens: int
    eos_id: int | None = None
    arrival_time: float = 0.0
    # --- runtime (engine-owned) ---
    generated: list[int] = dataclasses.field(default_factory=list)
    submit_t: float | None = None
    first_token_t: float | None = None
    token_times: list[float] = dataclasses.field(default_factory=list)
    evictions: int = 0

    @property
    def ttft_s(self) -> float | None:
        if self.submit_t is None or self.first_token_t is None:
            return None
        return self.first_token_t - self.submit_t

    def inter_token_s(self) -> list[float]:
        return [b - a for a, b in zip(self.token_times, self.token_times[1:])]

    def finished(self, max_len: int) -> bool:
        if self.eos_id is not None and self.generated \
                and self.generated[-1] == self.eos_id:
            return True
        if len(self.generated) >= self.max_new_tokens:
            return True
        return len(self.prompt) + len(self.generated) >= max_len


def spec_for_module(module, *, num_pages: int, page_size: int) -> CacheSpec:
    """Cache geometry from a decode-capable model's own attributes, so the
    pools always match the flax ``cache`` variables the model declares."""
    return CacheSpec(num_layers=module.num_layers, num_pages=num_pages,
                     page_size=page_size, num_kv_heads=module.num_kv_heads,
                     head_dim=module.head_dim, dtype=module.dtype)


def _bucket(n: int, buckets: tuple[int, ...]) -> int:
    for b in buckets:
        if n <= b:
            return b
    raise ValueError(f"{n} exceeds largest bucket {buckets[-1]}")


class ContinuousBatchingEngine:
    """Greedy decode with iteration-level scheduling.

    ``module`` is the flax model (decode-capable: ``decode_ctx`` kwarg),
    ``params`` its restored parameters. ``telemetry`` (a
    ``SpanRecorder``) and ``metrics`` (a fleetobs ``MetricsServer``) are
    optional; when present the engine records prefill/step goodput spans
    and exports ``pdtx_serve_*`` gauges.
    """

    def __init__(self, module, params, spec: CacheSpec, *,
                 decode_buckets: tuple[int, ...] = (1, 2, 4, 8),
                 prompt_buckets: tuple[int, ...] = (16, 32, 64),
                 max_model_len: int | None = None,
                 attn_impl: str = "auto",
                 telemetry=None, metrics=None,
                 clock: Callable[[], float] = time.perf_counter):
        self.module = module
        self.params = params
        self.spec = spec
        self.decode_buckets = tuple(sorted(decode_buckets))
        self.prompt_buckets = tuple(sorted(prompt_buckets))
        model_cap = getattr(module, "max_seq_len", None) or spec.max_len
        self.max_model_len = min(max_model_len or model_cap, model_cap,
                                 spec.max_len)
        if self.prompt_buckets[-1] > self.max_model_len:
            raise ValueError(
                f"largest prompt bucket {self.prompt_buckets[-1]} exceeds "
                f"max_model_len {self.max_model_len}")
        self.attn_impl = attn_impl
        self.telemetry = telemetry
        self.metrics = metrics
        self._clock = clock
        self.table_width = pages_for_tokens(self.max_model_len,
                                            spec.page_size)

        self.pool = PagePool(spec.num_pages)
        self.cache = kv_cache.init_cache(spec)
        self.waiting: collections.deque[Request] = collections.deque()
        max_b = self.decode_buckets[-1]
        self.slots: list[Request | None] = [None] * max_b
        # Host mirrors of per-slot device state.
        self._tables = np.zeros((max_b, self.table_width), np.int32)
        self._lens = np.zeros(max_b, np.int32)
        self._next_tok = np.zeros(max_b, np.int32)
        self.completed: list[Request] = []
        self.stats = {"compiles": 0, "prefills": 0, "decode_steps": 0,
                      "tokens_generated": 0, "evictions": 0, "admitted": 0}
        self._compiled: dict[tuple, Any] = {}
        self._t0 = self._clock()

    # ---------------------------------------------------------------- steps

    def _decode_fn(self):
        spec = self.spec

        def run(params, cache, tokens, positions, page_table, last_index):
            logits, vs = self.module.apply(
                {"params": params, "cache": cache}, tokens, train=False,
                decode_ctx=dict(positions=positions, page_table=page_table,
                                cache_spec=(spec.num_pages, spec.page_size),
                                last_index=last_index,
                                attn_impl=self.attn_impl),
                mutable=["cache"])
            return (jnp.argmax(logits, axis=-1).astype(jnp.int32),
                    vs["cache"])

        return run

    def _abstract(self, tree):
        return jax.tree.map(
            lambda x: jax.ShapeDtypeStruct(jnp.shape(x), jnp.asarray(x).dtype),
            tree)

    def _get_step(self, kind: str, batch: int, seq: int):
        """AOT-compiled executable for one (kind, batch, seq) shape. Every
        compile goes through here so ``stats["compiles"]`` is the single
        source of truth the no-recompile test asserts on."""
        key = (kind, batch, seq)
        if key not in self._compiled:
            fn = jax.jit(self._decode_fn(), donate_argnums=1)
            args = (
                self._abstract(self.params), self._abstract(self.cache),
                jax.ShapeDtypeStruct((batch, seq), jnp.int32),
                jax.ShapeDtypeStruct((batch, seq), jnp.int32),
                jax.ShapeDtypeStruct((batch, self.table_width), jnp.int32),
                jax.ShapeDtypeStruct((batch,), jnp.int32),
            )
            self._compiled[key] = fn.lower(*args).compile()
            self.stats["compiles"] += 1
        return self._compiled[key]

    def warmup(self) -> int:
        """Precompile every decode bucket and every batch-1 prefill bucket;
        returns the number of executables. After this, steady-state
        continuous batching runs entirely out of ``_compiled``."""
        for b in self.decode_buckets:
            self._get_step("decode", b, 1)
        for sp in self.prompt_buckets:
            self._get_step("prefill", 1, sp)
        return len(self._compiled)

    # ------------------------------------------------------------ scheduling

    @property
    def num_active(self) -> int:
        return sum(1 for r in self.slots if r is not None)

    @property
    def has_work(self) -> bool:
        return bool(self.waiting) or self.num_active > 0

    def submit(self, req: Request) -> None:
        if len(req.prompt) > self.prompt_buckets[-1]:
            raise ValueError(
                f"prompt of {len(req.prompt)} tokens exceeds largest "
                f"prompt bucket {self.prompt_buckets[-1]}")
        req.submit_t = self._clock()
        self.waiting.append(req)

    def _free_slot(self) -> int | None:
        for i, r in enumerate(self.slots):
            if r is None:
                return i
        return None

    def _admit(self) -> list[int]:
        """Move waiting requests into free slots while pages last; prefill
        each (batch-1, prompt-bucket shape). Returns admitted slot ids."""
        admitted = []
        while self.waiting:
            slot = self._free_slot()
            if slot is None:
                break
            req = self.waiting[0]
            need = pages_for_tokens(len(req.prompt) + 1, self.spec.page_size)
            if not self.pool.can_alloc(need):
                break
            self.waiting.popleft()
            pages = self.pool.alloc(req.request_id, need)
            self.slots[slot] = req
            self._tables[slot] = 0
            self._tables[slot, :need] = pages
            self._lens[slot] = len(req.prompt)
            self.stats["admitted"] += 1
            self._prefill(slot, req)
            admitted.append(slot)
        return admitted

    def _prefill(self, slot: int, req: Request) -> None:
        plen = len(req.prompt)
        sp = _bucket(plen, self.prompt_buckets)
        step = self._get_step("prefill", 1, sp)
        tokens = np.zeros((1, sp), np.int32)
        tokens[0, :plen] = req.prompt
        positions = np.arange(sp, dtype=np.int32)[None]
        table = self._tables[slot:slot + 1]
        last = np.asarray([plen - 1], np.int32)
        with self._span("prefill"):
            tok, self.cache = step(self.params, self.cache,
                                   jnp.asarray(tokens), jnp.asarray(positions),
                                   jnp.asarray(table), jnp.asarray(last))
            first = int(np.asarray(tok)[0])
        now = self._clock()
        req.generated.append(first)
        req.first_token_t = now
        req.token_times.append(now)
        self._next_tok[slot] = first
        self.stats["prefills"] += 1
        self.stats["tokens_generated"] += 1
        self._retire(slot)

    def _ensure_pages(self) -> None:
        """Every active slot must own the page its NEXT append lands in;
        allocate incrementally, evicting the youngest request on OOM."""
        while True:
            need_slot = None
            for i, req in enumerate(self.slots):
                if req is None:
                    continue
                pos = int(self._lens[i])  # next token's position
                page_idx = pos // self.spec.page_size
                owned = len(self.pool.owned(req.request_id))
                if page_idx >= owned:
                    need_slot = i
                    break
            if need_slot is None:
                return
            req = self.slots[need_slot]
            if self.pool.can_alloc(1):
                (page,) = self.pool.alloc(req.request_id, 1)
                owned = len(self.pool.owned(req.request_id))
                self._tables[need_slot, owned - 1] = page
                continue
            self._evict()

    def _evict(self) -> None:
        """Free the youngest active request and requeue it (recompute on
        readmission). Raises if nothing is evictable — the pool is too
        small for even one request, a configuration error."""
        youngest, slot = None, None
        for i, req in enumerate(self.slots):
            if req is None:
                continue
            if youngest is None or req.submit_t > youngest.submit_t:
                youngest, slot = req, i
        if youngest is None:
            raise MemoryError("page pool exhausted with no active request "
                              "to evict — num_pages is too small")
        self.pool.free(youngest.request_id)
        self.slots[slot] = None
        self._lens[slot] = 0
        self._tables[slot] = 0
        youngest.generated.clear()
        youngest.token_times.clear()
        youngest.first_token_t = None
        youngest.evictions += 1
        self.stats["evictions"] += 1
        self.waiting.appendleft(youngest)

    def _retire(self, slot: int) -> None:
        req = self.slots[slot]
        if req is not None and req.finished(self.max_model_len):
            self.pool.free(req.request_id)
            self.slots[slot] = None
            self._lens[slot] = 0
            self._tables[slot] = 0
            self.completed.append(req)

    def _span(self, name: str):
        if self.telemetry is not None:
            return self.telemetry.span(name)
        return contextlib.nullcontext()

    # ---------------------------------------------------------------- step

    def step(self, admit: bool = True) -> int:
        """One scheduling iteration: admit+prefill, then one decode step
        over the active slots (padded to a batch bucket). Returns tokens
        generated this iteration. ``admit=False`` is the drain mode a
        graceful shutdown uses: in-flight sequences keep decoding to
        completion but nothing moves from the waiting queue into a slot."""
        if admit:
            self._admit()
        active = [i for i, r in enumerate(self.slots) if r is not None]
        produced = 0
        if active:
            self._ensure_pages()
            active = [i for i, r in enumerate(self.slots) if r is not None]
        if active:
            bucket = _bucket(len(active), self.decode_buckets)
            rows = active + [i for i in range(len(self.slots))
                             if i not in active][:bucket - len(active)]
            rows = rows[:bucket]
            tokens = self._next_tok[rows][:, None].copy()
            positions = self._lens[rows][:, None].copy()
            table = self._tables[rows].copy()
            # Inactive filler rows: scratch page table, position 0, token 0.
            for j, i in enumerate(rows):
                if self.slots[i] is None:
                    tokens[j] = 0
                    positions[j] = 0
                    table[j] = 0
            step = self._get_step("decode", bucket, 1)
            with self._span("step"):
                tok, self.cache = step(
                    self.params, self.cache, jnp.asarray(tokens),
                    jnp.asarray(positions), jnp.asarray(table),
                    np.zeros(bucket, np.int32))
                out = np.asarray(tok)
            now = self._clock()
            self.stats["decode_steps"] += 1
            for j, i in enumerate(rows):
                req = self.slots[i]
                if req is None:
                    continue
                req.generated.append(int(out[j]))
                req.token_times.append(now)
                self._lens[i] += 1
                self._next_tok[i] = int(out[j])
                produced += 1
                self._retire(i)
            self.stats["tokens_generated"] += produced
        self._export_metrics()
        return produced

    def run(self, max_steps: int = 100000) -> list[Request]:
        """Drain every submitted request; returns the completed list."""
        steps = 0
        while self.has_work:
            self.step()
            steps += 1
            if steps > max_steps:
                raise RuntimeError(f"engine did not drain in {max_steps} "
                                   "steps (stop conditions broken?)")
        return self.completed

    def _export_metrics(self) -> None:
        if self.metrics is None:
            return
        elapsed = max(self._clock() - self._t0, 1e-9)
        self.metrics.update(
            serve_active=self.num_active,
            serve_waiting=len(self.waiting),
            serve_completed=len(self.completed),
            serve_tokens_total=self.stats["tokens_generated"],
            serve_tokens_per_s=self.stats["tokens_generated"] / elapsed,
            serve_pages_free=self.pool.num_free,
            serve_evictions=self.stats["evictions"],
            serve_compiles=self.stats["compiles"],
            serve_decode_steps=self.stats["decode_steps"],
        )
