"""Continuous-batching decode engine over the paged KV cache.

The serving loop that turns the trainer's forward pass into a token
stream: requests are admitted and retired BETWEEN decode steps (Orca-style
iteration-level scheduling), so a long generation never holds the batch
hostage and a finished request's pages return to the pool immediately.

Shape discipline is the whole design: every compiled program runs at one
of a small set of padded BATCH BUCKETS (and, for prefill, prompt-length
buckets), all AOT-compiled at warmup through the same
``jit(...).lower(abstract).compile()`` front-end the r13 profile/lint
stack uses — steady-state continuous batching therefore NEVER recompiles
(``stats["compiles"]`` is flat after warmup; asserted in tests). Inactive
rows in a bucket carry token 0, position 0 and a page table full of the
reserved scratch page, so their lanes compute garbage that is never read.

Host/device split per step: exactly ONE device->host sync (the batched
next-token fetch that stop conditions need); admission, page allocation
and eviction are pure host bookkeeping on the ``PagePool`` free list.

Eviction: a slot that cannot get a page (pool exhausted) evicts the
YOUNGEST active request — its pages free immediately and the request
re-queues at the head of the waiting line, to be recomputed when pressure
drops (recompute-on-readmit, the classic vLLM preemption policy).

r17 grows three serving-throughput layers on the same skeleton:

- PREFIX CACHING (``prefix_cache=True``): ``_admit`` consults a
  ``PrefixCache`` tree and splices matched pages into the request's page
  table instead of prefilling them; only the un-cached suffix runs
  through a (history-flavored) prefill program. Writes that would land
  in a page with pool refcount > 1 copy-on-write through one compiled
  ``copy_page`` program.
- CHUNKED PREFILL (``prefill_chunk=N``): prefill runs as a sequence of
  at-most-N-token windows. Under ``role="prefill"`` each slot advances
  ONE window per step, so a long prompt never monopolizes an iteration.
- DISAGGREGATION (``role="prefill"`` / ``role="decode"``): a prefill-only
  engine hands finished prompts to a decode-only engine as ``Handoff``
  blocks — the KV pages extracted through one fixed-width compiled
  program and inserted into the decode pool through another, so the
  decode batch never shares a step with a prefill. ``DisaggregatedServe``
  drives such a pair behind the single-engine interface.

r19 adds SPECULATIVE DECODING (``spec_decode=``): a draft proposer
(serve/spec_decode.py — self-drafting n-gram lookup by default, or a
separate small draft model) guesses up to K tokens per slot, ONE batched
verify forward (the history-attention program with ``all_logits``)
scores all K+1 positions, and exact greedy acceptance (Leviathan et al.
2023) keeps the longest draft prefix matching the model's own argmax
plus one bonus token — so the emitted stream is bit-identical to the
unsped engine while each accepted token skips a decode step. The paged
cache rolls back over rejected positions for free (attention masks on
position; stale entries are overwritten by later appends) and overshoot
PAGES are dropped refcount-safely. Draft lengths are bucketed like
batch/prompt buckets, so verify programs precompile at warmup and the
steady state still never recompiles; the per-step host sync stays at
exactly one — the verify fetch carries scores AND echoed draft tokens
in a single stacked array.
"""

from __future__ import annotations

import collections
import contextlib
import dataclasses
import time
from typing import Any, Callable

import jax
import jax.numpy as jnp
import numpy as np

from pytorch_distributed_training_example_tpu.serve import kv_cache
from pytorch_distributed_training_example_tpu.serve.kv_cache import (
    CacheSpec, PagePool, pages_for_tokens)
from pytorch_distributed_training_example_tpu.serve.prefix_cache import (
    PrefixCache)


@dataclasses.dataclass
class Request:
    """One generation request. The engine fills the runtime fields."""

    request_id: str
    prompt: list[int]
    max_new_tokens: int
    eos_id: int | None = None
    arrival_time: float = 0.0
    # --- runtime (engine-owned) ---
    generated: list[int] = dataclasses.field(default_factory=list)
    submit_t: float | None = None
    admit_t: float | None = None
    first_token_t: float | None = None
    token_times: list[float] = dataclasses.field(default_factory=list)
    evictions: int = 0

    @property
    def ttft_s(self) -> float | None:
        if self.submit_t is None or self.first_token_t is None:
            return None
        return self.first_token_t - self.submit_t

    def inter_token_s(self) -> list[float]:
        return [b - a for a, b in zip(self.token_times, self.token_times[1:])]

    def finished(self, max_len: int) -> bool:
        if self.eos_id is not None and self.generated \
                and self.generated[-1] == self.eos_id:
            return True
        if len(self.generated) >= self.max_new_tokens:
            return True
        return len(self.prompt) + len(self.generated) >= max_len


@dataclasses.dataclass
class Handoff:
    """A prefilled request crossing the prefill→decode boundary: its KV
    pages (extracted as a fixed-width device block), how many of the
    block's rows are real, and the decode resume state."""

    req: Request
    block: Any              # pytree of [W, page_size, Hkv, D] per layer/KV
    n_pages: int
    length: int             # prompt length == next append position
    next_token: int         # the prefill's argmax, decode's first input


def spec_for_module(module, *, num_pages: int, page_size: int) -> CacheSpec:
    """Cache geometry from a decode-capable model's own attributes, so the
    pools always match the flax ``cache`` variables the model declares."""
    return CacheSpec(num_layers=module.num_layers, num_pages=num_pages,
                     page_size=page_size, num_kv_heads=module.num_kv_heads,
                     head_dim=module.head_dim, dtype=module.dtype)


def _bucket(n: int, buckets: tuple[int, ...]) -> int:
    for b in buckets:
        if n <= b:
            return b
    raise ValueError(f"{n} exceeds largest bucket {buckets[-1]}")


class ContinuousBatchingEngine:
    """Greedy decode with iteration-level scheduling.

    ``module`` is the flax model (decode-capable: ``decode_ctx`` kwarg),
    ``params`` its restored parameters. ``telemetry`` (a
    ``SpanRecorder``) and ``metrics`` (a fleetobs ``MetricsServer``) are
    optional; when present the engine records per-role goodput spans
    (``prefill`` / ``step`` / ``decode``) and exports ``pdtx_serve_*``
    gauges. ``role`` is ``"both"`` (the r15 single-engine path),
    ``"prefill"`` (admit + prefill only, finished prompts queue in
    ``handoffs``) or ``"decode"`` (drains ``ingest``-ed handoffs only).
    """

    def __init__(self, module, params, spec: CacheSpec, *,
                 decode_buckets: tuple[int, ...] = (1, 2, 4, 8),
                 prompt_buckets: tuple[int, ...] = (16, 32, 64),
                 max_model_len: int | None = None,
                 attn_impl: str = "auto",
                 prefix_cache: bool = False,
                 prefill_chunk: int = 0,
                 role: str = "both",
                 spec_decode: Any = None,
                 draft_len: int = 4,
                 telemetry=None, metrics=None,
                 reqtrace=None, slo=None,
                 clock: Callable[[], float] = time.perf_counter):
        if role not in ("both", "prefill", "decode"):
            raise ValueError(f"unknown engine role {role!r}")
        self.module = module
        self.params = params
        self.spec = spec
        self.role = role
        self.decode_buckets = tuple(sorted(decode_buckets))
        self.prompt_buckets = tuple(sorted(prompt_buckets))
        model_cap = getattr(module, "max_seq_len", None) or spec.max_len
        self.max_model_len = min(max_model_len or model_cap, model_cap,
                                 spec.max_len)
        if self.prompt_buckets[-1] > self.max_model_len:
            raise ValueError(
                f"largest prompt bucket {self.prompt_buckets[-1]} exceeds "
                f"max_model_len {self.max_model_len}")
        if prefill_chunk and prefill_chunk % spec.page_size:
            raise ValueError(
                f"prefill_chunk={prefill_chunk} must be a multiple of "
                f"page_size={spec.page_size} (windows must not split a "
                f"page between programs)")
        self.attn_impl = attn_impl
        self.prefill_chunk = int(prefill_chunk)
        self.telemetry = telemetry
        self.metrics = metrics
        # Request-level observability (serve/slo.py): ``reqtrace`` records
        # lifecycle span events, ``slo`` accumulates TTFT/ITL windows. Both
        # ride timestamps this engine already takes (or cheap extra reads
        # of the same injected host clock) — never a device sync, so
        # tokens and compile counts are identical with tracing on or off.
        self.reqtrace = reqtrace
        self.slo = slo
        self._slo_key = (reqtrace.replica if reqtrace is not None
                         else "engine", role)
        self._clock = clock
        self.table_width = pages_for_tokens(self.max_model_len,
                                            spec.page_size)

        self.pool = PagePool(spec.num_pages)
        self.prefix_cache = (PrefixCache(self.pool, spec.page_size)
                             if prefix_cache else None)
        self.cache = self._init_cache()
        self.waiting: collections.deque[Request] = collections.deque()
        max_b = self.decode_buckets[-1]
        self.slots: list[Request | None] = [None] * max_b
        # Host mirrors of per-slot device state. ``_pages`` is the
        # engine's own ordered page list per slot — COW swaps individual
        # entries, so ``pool.owned`` order can no longer be trusted.
        self._tables = np.zeros((max_b, self.table_width), np.int32)
        self._lens = np.zeros(max_b, np.int32)
        self._next_tok = np.zeros(max_b, np.int32)
        self._pages: list[list[int]] = [[] for _ in range(max_b)]
        self._nodes: dict[str, list] = {}      # rid -> pinned cache nodes
        self._prefill_pos: dict[int, int] = {}  # slot -> next window start
        self._inbox: collections.deque[Handoff] = collections.deque()
        self.handoffs: list[Handoff] = []
        self.requeued: list[Request] = []
        self.completed: list[Request] = []
        self.stats = {"compiles": 0, "prefills": 0, "decode_steps": 0,
                      "tokens_generated": 0, "evictions": 0, "admitted": 0,
                      "prompt_tokens": 0, "cached_tokens": 0,
                      "cow_copies": 0, "handoffs_out": 0, "handoffs_in": 0,
                      "spec_steps": 0, "draft_tokens": 0,
                      "accepted_tokens": 0}
        self._compiled: dict[tuple, Any] = {}
        # Speculative decoding: ``spec_decode`` is None/"off", the string
        # "ngram" (build the default self-drafting proposer), or a
        # proposer object (serve/spec_decode.py protocol: attach/warmup/
        # begin/release/propose). A prefill-role engine never decodes, so
        # it never speculates. Draft lengths bucket like batch buckets:
        # verify programs compile once per (decode bucket, draft bucket)
        # at warmup and the compile count stays flat afterwards.
        self.draft_len = int(draft_len)
        if self.draft_len < 1:
            raise ValueError(f"draft_len={draft_len} must be >= 1")
        self.draft_buckets = tuple(
            b for b in (1, 2, 4, 8, 16) if b < self.draft_len
        ) + (self.draft_len,)
        if spec_decode in (None, False, "", "off") or role == "prefill":
            self.proposer = None
        elif spec_decode == "ngram":
            from pytorch_distributed_training_example_tpu.serve import (
                spec_decode as spec_decode_lib)
            self.proposer = spec_decode_lib.NGramProposer(self.draft_len)
        elif isinstance(spec_decode, str):
            raise ValueError(
                f"unknown spec_decode mode {spec_decode!r}: expected 'off', "
                "'ngram', or a proposer object (e.g. DraftModelProposer)")
        else:
            self.proposer = spec_decode
        if self.proposer is not None:
            # Accepted-length histogram rides the stats dict as plain int
            # keys so DisaggregatedServe / router stat merges stay trivial.
            for n in range(self.draft_len + 1):
                self.stats[f"spec_accept_{n}"] = 0
            self.proposer.attach(self)
        self._t0 = self._clock()

    def _init_cache(self):
        """Zeroed pools matching the cache pytree the MODEL declares —
        per-block for unrolled models, one stacked [L, ...] carry under
        ``scan_layers`` (kv_cache.init_model_cache)."""
        return kv_cache.init_model_cache(self.module, self.spec,
                                         self.table_width, self.attn_impl)

    # ---------------------------------------------------------------- steps

    def _decode_fn(self, history: bool = False, all_logits: bool = False):
        spec = self.spec

        def run(params, cache, tokens, positions, page_table, last_index):
            logits, vs = self.module.apply(
                {"params": params, "cache": cache}, tokens, train=False,
                decode_ctx=dict(positions=positions, page_table=page_table,
                                cache_spec=(spec.num_pages, spec.page_size),
                                last_index=last_index, history=history,
                                all_logits=all_logits,
                                attn_impl=self.attn_impl),
                mutable=["cache"])
            out = jnp.argmax(logits, axis=-1).astype(jnp.int32)
            if all_logits:
                # Verify step: stack the per-position argmax with the ECHOED
                # input tokens, so the host acceptance loop reads drafts and
                # scores out of one fetched array — device-side proposers
                # (draft model) never force a second device->host sync.
                out = jnp.stack([out, tokens.astype(jnp.int32)], axis=1)
            return out, vs["cache"]

        return run

    def _abstract(self, tree):
        return jax.tree.map(
            lambda x: jax.ShapeDtypeStruct(jnp.shape(x), jnp.asarray(x).dtype),
            tree)

    def _get_step(self, kind: str, batch: int, seq: int):
        """AOT-compiled executable for one (kind, batch, seq) shape. Every
        compile goes through here so ``stats["compiles"]`` is the single
        source of truth the no-recompile test asserts on."""
        key = (kind, batch, seq)
        if key not in self._compiled:
            fn = jax.jit(
                self._decode_fn(history=kind in ("prefill_hist", "verify"),
                                all_logits=kind == "verify"),
                donate_argnums=1)
            args = (
                self._abstract(self.params), self._abstract(self.cache),
                jax.ShapeDtypeStruct((batch, seq), jnp.int32),
                jax.ShapeDtypeStruct((batch, seq), jnp.int32),
                jax.ShapeDtypeStruct((batch, self.table_width), jnp.int32),
                jax.ShapeDtypeStruct((batch,), jnp.int32),
            )
            self._compiled[key] = fn.lower(*args).compile()
            self.stats["compiles"] += 1
        return self._compiled[key]

    def _get_aux(self, kind: str):
        """The non-forward compiled programs: ``cow`` (clone one page),
        ``export``/``import`` (fixed-width handoff block out of / into
        this pool). One shape each, compiled once, counted in
        ``stats["compiles"]`` like every other program."""
        key = (kind, 0, 0)
        if key not in self._compiled:
            cache_abs = self._abstract(self.cache)
            ids_abs = jax.ShapeDtypeStruct((self.table_width,), jnp.int32)
            if kind == "cow":
                fn = jax.jit(kv_cache.copy_page, donate_argnums=0)
                scalar = jax.ShapeDtypeStruct((), jnp.int32)
                lowered = fn.lower(cache_abs, scalar, scalar)
            elif kind == "export":
                fn = jax.jit(kv_cache.extract_pages)
                lowered = fn.lower(cache_abs, ids_abs)
            elif kind == "import":
                fn = jax.jit(kv_cache.insert_pages, donate_argnums=0)
                # Page axis is ndim-4 on every pool leaf (scanned stacks
                # carry a leading layer dim) — the handoff block swaps it
                # for the fixed table width.
                block_abs = jax.tree.map(
                    lambda s: jax.ShapeDtypeStruct(
                        s.shape[:-4] + (self.table_width,) + s.shape[-3:],
                        s.dtype),
                    cache_abs)
                lowered = fn.lower(cache_abs, block_abs, ids_abs)
            else:
                raise ValueError(f"unknown aux program {kind!r}")
            self._compiled[key] = lowered.compile()
            self.stats["compiles"] += 1
        return self._compiled[key]

    def warmup(self) -> int:
        """Precompile every program this role can reach; returns the
        executable count. After this, steady-state serving runs entirely
        out of ``_compiled`` — ``stats["compiles"]`` must stay flat."""
        if self.role in ("both", "decode"):
            for b in self.decode_buckets:
                self._get_step("decode", b, 1)
            if self.proposer is not None:
                for b in self.decode_buckets:
                    for w in self.draft_buckets:
                        self._get_step("verify", b, w + 1)
        if self.role in ("both", "prefill"):
            for sp in self.prompt_buckets:
                self._get_step("prefill", 1, sp)
            if self.prefix_cache is not None or self.prefill_chunk:
                for sp in self.prompt_buckets:
                    self._get_step("prefill_hist", 1, sp)
        if self.prefix_cache is not None:
            self._get_aux("cow")
        if self.role == "prefill":
            self._get_aux("export")
        if self.role == "decode":
            self._get_aux("import")
        n = len(self._compiled)
        if self.proposer is not None:
            n += self.proposer.warmup(self)
        return n

    # ------------------------------------------------------------ scheduling

    @property
    def num_active(self) -> int:
        return sum(1 for r in self.slots if r is not None)

    @property
    def has_work(self) -> bool:
        return bool(self.waiting) or bool(self._inbox) or self.num_active > 0

    def submit(self, req: Request) -> None:
        if self.role == "decode":
            raise ValueError("decode-role engine takes Handoffs via "
                             "ingest(), not fresh requests")
        if len(req.prompt) > self.prompt_buckets[-1]:
            raise ValueError(
                f"prompt of {len(req.prompt)} tokens exceeds largest "
                f"prompt bucket {self.prompt_buckets[-1]}")
        req.submit_t = self._clock()
        self.waiting.append(req)

    def ingest(self, handoff: Handoff) -> None:
        """Decode role: queue a prefilled request for placement at the
        next step (placement needs a slot and pages, so it happens in
        step order like any other admission)."""
        if self.role != "decode":
            raise ValueError("only decode-role engines ingest handoffs")
        self._inbox.append(handoff)

    def take_handoffs(self) -> list[Handoff]:
        out, self.handoffs = self.handoffs, []
        return out

    def take_requeued(self) -> list[Request]:
        out, self.requeued = self.requeued, []
        return out

    def _free_slot(self) -> int | None:
        for i, r in enumerate(self.slots):
            if r is None:
                return i
        return None

    def _reserve(self, n: int) -> bool:
        """Can ``n`` pages be allocated, evicting unreferenced prefix-cache
        pages (LRU) first if the free list is short?"""
        if self.pool.can_alloc(n):
            return True
        if self.prefix_cache is not None:
            self.prefix_cache.evict(n - self.pool.num_free)
        return self.pool.can_alloc(n)

    def _admit(self) -> list[int]:
        """Move waiting requests into free slots while pages last. A
        prefix-cache hit splices the matched pages into the page table
        and only the suffix is prefilled; the page containing the first
        prefilled position is copy-on-written up front if it is shared.
        Role "both" prefills to completion inline (r15 semantics); role
        "prefill" queues windows that ``step`` advances one at a time."""
        admitted = []
        while self.waiting:
            slot = self._free_slot()
            if slot is None:
                break
            req = self.waiting[0]
            plen = len(req.prompt)
            ps = self.spec.page_size
            match = None
            shared: list[int] = []
            if self.prefix_cache is not None:
                match = self.prefix_cache.match(req.prompt,
                                                max_tokens=plen - 1)
                shared = match.pages
            start = match.tokens if match else 0
            cow_idx = start // ps if shared and start // ps < len(shared) \
                else None
            need_new = pages_for_tokens(plen + 1, ps) - len(shared)
            if shared:
                # Pin BEFORE reserving: _reserve may LRU-evict exactly the
                # unreferenced cache pages this match is about to splice.
                self.prefix_cache.acquire(match, req.request_id)
            if not self._reserve(need_new + (1 if cow_idx is not None else 0)):
                if shared:
                    self.prefix_cache.release(match.nodes)
                    self.pool.free(req.request_id)
                break
            self.waiting.popleft()
            if shared:
                self._nodes[req.request_id] = list(match.nodes)
                self.stats["cached_tokens"] += start
            pages = shared + (self.pool.alloc(req.request_id, need_new)
                              if need_new else [])
            self.slots[slot] = req
            self._pages[slot] = pages
            self._tables[slot] = 0
            self._tables[slot, :len(pages)] = pages
            self._lens[slot] = plen
            self.stats["admitted"] += 1
            self.stats["prompt_tokens"] += plen
            if self.reqtrace is not None:
                now = self._clock()
                req.admit_t = now
                if req.submit_t is not None:
                    self.reqtrace.span("queue_wait", req.submit_t, now,
                                       role=self.role,
                                       request_id=req.request_id)
                self.reqtrace.instant("admit", now, role=self.role,
                                      request_id=req.request_id,
                                      cached_tokens=start,
                                      recompute=req.evictions > 0)
            if cow_idx is not None:
                self._cow(slot, cow_idx)
            if self.role == "prefill":
                self._prefill_pos[slot] = start
            else:
                self._prefill(slot, req, start)
            admitted.append(slot)
        return admitted

    def _cow(self, slot: int, idx: int) -> None:
        """Copy-on-write page ``idx`` of ``slot``: clone it into a fresh
        private page, swap the table entry, release this request's share
        of the old page (and its cache pin, if that is where the share
        came from). Callers reserve the page beforehand."""
        req = self.slots[slot]
        old = self._pages[slot][idx]
        (new,) = self.pool.alloc(req.request_id, 1)
        step = self._get_aux("cow")
        self.cache = step(self.cache, jnp.asarray(old, jnp.int32),
                          jnp.asarray(new, jnp.int32))
        self._pages[slot][idx] = new
        self._tables[slot, idx] = new
        self.pool.drop(req.request_id, old)
        nodes = self._nodes.get(req.request_id)
        if nodes is not None:
            for node in nodes:
                if node.page == old:
                    self.prefix_cache.release([node])
                    nodes.remove(node)
                    break
        self.stats["cow_copies"] += 1
        if self.reqtrace is not None:
            self.reqtrace.instant("cow", role=self.role,
                                  request_id=req.request_id, page=int(new))

    def _window_cap(self) -> int:
        return self.prefill_chunk or self.prompt_buckets[-1]

    def _prefill(self, slot: int, req: Request, start: int = 0) -> None:
        """Prefill ``req`` from position ``start`` (cached tokens before it
        are already in spliced pages) to completion, one window per
        compiled program, then finish (first token + retire/handoff)."""
        plen = len(req.prompt)
        pos = start
        first = 0
        while pos < plen:
            n = min(plen - pos, self._window_cap())
            first = self._prefill_window(slot, req, pos, n)
            pos += n
        self._finish_prefill(slot, req, first)

    def _prefill_window(self, slot: int, req: Request, pos: int,
                        n: int) -> int:
        """One prefill window: tokens [pos, pos+n) at their true
        positions. ``pos == 0`` is the plain causal program; ``pos > 0``
        runs the history flavor, which reads the earlier positions back
        through the page table. Returns the argmax after the window's
        last token (only the final window's matters)."""
        sp = _bucket(n, self.prompt_buckets)
        kind = "prefill_hist" if pos > 0 else "prefill"
        step = self._get_step(kind, 1, sp)
        tokens = np.zeros((1, sp), np.int32)
        tokens[0, :n] = req.prompt[pos:pos + n]
        # Padded tail positions are clipped into table range; they write
        # garbage into not-yet-used (or scratch) slots that later real
        # appends overwrite and position masking hides meanwhile.
        positions = np.minimum(pos + np.arange(sp, dtype=np.int32),
                               self.table_width * self.spec.page_size - 1)
        table = self._tables[slot:slot + 1]
        last = np.asarray([n - 1], np.int32)
        t0 = self._clock() if self.reqtrace is not None else 0.0
        with self._span("prefill"):
            tok, self.cache = step(self.params, self.cache,
                                   jnp.asarray(tokens),
                                   jnp.asarray(positions[None]),
                                   jnp.asarray(table), jnp.asarray(last))
            first = int(np.asarray(tok)[0])
        if self.reqtrace is not None:
            self.reqtrace.span("prefill_chunk", t0, self._clock(),
                               role=self.role, request_id=req.request_id,
                               pos=pos, n=n)
        return first

    def _finish_prefill(self, slot: int, req: Request, first: int) -> None:
        """Prefill done: record the first token, publish the prompt's
        pages to the prefix cache, then either retire (role "both", or
        already finished) or queue the KV handoff (role "prefill")."""
        now = self._clock()
        req.generated.append(first)
        req.first_token_t = now
        req.token_times.append(now)
        self._next_tok[slot] = first
        self.stats["prefills"] += 1
        self.stats["tokens_generated"] += 1
        if self.slo is not None and req.ttft_s is not None:
            self.slo.observe_ttft(*self._slo_key, req.ttft_s)
        if self.reqtrace is not None:
            self.reqtrace.span("prefill", req.admit_t or now, now,
                               role=self.role, request_id=req.request_id,
                               tokens=len(req.prompt))
        if self.prefix_cache is not None:
            self.prefix_cache.insert(req.prompt, self._pages[slot])
        if self.role == "prefill" and not req.finished(self.max_model_len):
            self._handoff(slot, req, first)
        else:
            if self.proposer is not None \
                    and not req.finished(self.max_model_len):
                self.proposer.begin(self, slot, req)
            self._retire(slot)

    def _handoff(self, slot: int, req: Request, first: int) -> None:
        """Extract the slot's pages as a fixed-width block and queue it
        for the decode engine; this engine's copies release immediately
        (the prefix cache keeps its own pins on published pages)."""
        pages = self._pages[slot]
        ids = np.zeros(self.table_width, np.int32)
        ids[:len(pages)] = pages
        step = self._get_aux("export")
        block = step(self.cache, jnp.asarray(ids))
        self.handoffs.append(Handoff(req=req, block=block,
                                     n_pages=len(pages),
                                     length=len(req.prompt),
                                     next_token=first))
        self.stats["handoffs_out"] += 1
        if self.reqtrace is not None:
            self.reqtrace.instant("kv_handoff", role=self.role,
                                  request_id=req.request_id,
                                  pages=len(pages))
        self._release_slot(slot)

    def _place(self, handoff: Handoff, slot: int) -> None:
        """Decode role: import a handoff block into freshly-allocated
        pages and resume the request mid-sequence."""
        req = handoff.req
        pages = self.pool.alloc(req.request_id, handoff.n_pages)
        ids = np.zeros(self.table_width, np.int32)
        ids[:len(pages)] = pages
        step = self._get_aux("import")
        self.cache = step(self.cache, handoff.block, jnp.asarray(ids))
        self.slots[slot] = req
        self._pages[slot] = pages
        self._tables[slot] = 0
        self._tables[slot, :len(pages)] = pages
        self._lens[slot] = handoff.length
        self._next_tok[slot] = handoff.next_token
        self.stats["handoffs_in"] += 1
        self.stats["admitted"] += 1
        if self.reqtrace is not None:
            self.reqtrace.instant("kv_place", role=self.role,
                                  request_id=req.request_id,
                                  pages=handoff.n_pages)
        if self.proposer is not None:
            self.proposer.begin(self, slot, req)

    def _drain_inbox(self) -> None:
        while self._inbox:
            slot = self._free_slot()
            if slot is None or not self._reserve(self._inbox[0].n_pages):
                break
            self._place(self._inbox.popleft(), slot)

    def _ensure_pages(self, extra: dict[int, int] | None = None) -> None:
        """Every active slot must be able to take its NEXT append: the
        target page must exist (allocate incrementally) and be private
        (copy-on-write if its pool refcount exceeds one — someone else,
        possibly the prefix cache, still reads the original bytes).
        ``extra[slot]`` widens the write window for a speculative verify
        step — positions ``len .. len+extra`` all land this step, so
        every page in that range must exist and be private up front.
        Evicts the youngest request on OOM."""
        while True:
            pending = None
            for i, req in enumerate(self.slots):
                if req is None:
                    continue
                ps = self.spec.page_size
                lo = int(self._lens[i]) // ps
                hi = (int(self._lens[i]) + (extra.get(i, 0) if extra else 0)
                      ) // ps
                for idx in range(lo, hi + 1):
                    if idx >= len(self._pages[i]):
                        pending = (i, "grow", idx)
                        break
                    if self.pool.refcount(self._pages[i][idx]) > 1:
                        pending = (i, "cow", idx)
                        break
                if pending is not None:
                    break
            if pending is None:
                return
            i, what, idx = pending
            if self._reserve(1):
                if what == "grow":
                    req = self.slots[i]
                    (page,) = self.pool.alloc(req.request_id, 1)
                    self._pages[i].append(page)
                    self._tables[i, len(self._pages[i]) - 1] = page
                else:
                    self._cow(i, idx)
                continue
            self._evict()

    def _release_slot(self, slot: int) -> None:
        req = self.slots[slot]
        if self.proposer is not None:
            self.proposer.release(slot)
        self.pool.free(req.request_id)
        nodes = self._nodes.pop(req.request_id, None)
        if nodes and self.prefix_cache is not None:
            self.prefix_cache.release(nodes)
        self.slots[slot] = None
        self._lens[slot] = 0
        self._tables[slot] = 0
        self._pages[slot] = []
        self._prefill_pos.pop(slot, None)

    def _evict(self) -> None:
        """Free the youngest active request and requeue it (recompute on
        readmission). A decode-role engine cannot re-prefill, so its
        victims land in ``requeued`` for the pair driver to send back to
        the prefill engine. Raises if nothing is evictable — the pool is
        too small for even one request, a configuration error."""
        youngest, slot = None, None
        for i, req in enumerate(self.slots):
            if req is None:
                continue
            if youngest is None or req.submit_t > youngest.submit_t:
                youngest, slot = req, i
        if youngest is None:
            raise MemoryError("page pool exhausted with no active request "
                              "to evict — num_pages is too small")
        self._release_slot(slot)
        youngest.generated.clear()
        youngest.token_times.clear()
        youngest.first_token_t = None
        youngest.admit_t = None
        youngest.evictions += 1
        self.stats["evictions"] += 1
        if self.reqtrace is not None:
            self.reqtrace.instant("evict", role=self.role,
                                  request_id=youngest.request_id,
                                  evictions=youngest.evictions)
        if self.role == "decode":
            self.requeued.append(youngest)
        else:
            self.waiting.appendleft(youngest)

    def _retire(self, slot: int) -> None:
        req = self.slots[slot]
        if req is not None and req.finished(self.max_model_len):
            self._release_slot(slot)
            self.completed.append(req)
            if self.reqtrace is not None and req.submit_t is not None:
                end = (req.token_times[-1] if req.token_times
                       else req.submit_t)
                self.reqtrace.span("request", req.submit_t, end,
                                   role=self.role,
                                   request_id=req.request_id,
                                   tokens=len(req.generated),
                                   evictions=req.evictions)

    def _span(self, name: str):
        if self.telemetry is not None:
            return self.telemetry.span(name)
        return contextlib.nullcontext()

    # ---------------------------------------------------------------- step

    def _advance_prefills(self) -> int:
        """Prefill role: one window per in-flight slot per step, so long
        prompts interleave instead of monopolizing. Returns first tokens
        produced (prefills that completed this step)."""
        produced = 0
        for slot, req in enumerate(self.slots):
            if req is None or slot not in self._prefill_pos:
                continue
            pos = self._prefill_pos[slot]
            plen = len(req.prompt)
            n = min(plen - pos, self._window_cap())
            first = self._prefill_window(slot, req, pos, n)
            pos += n
            if pos >= plen:
                del self._prefill_pos[slot]
                self._finish_prefill(slot, req, first)
                produced += 1
            else:
                self._prefill_pos[slot] = pos
        return produced

    def step(self, admit: bool = True) -> int:
        """One scheduling iteration. Role "both": admit+prefill, then one
        decode step over the active slots (padded to a batch bucket).
        Role "prefill": admit, then advance each in-flight prefill one
        window. Role "decode": place queued handoffs, then decode.
        Returns tokens generated this iteration. ``admit=False`` is the
        drain mode a graceful shutdown uses: in-flight sequences keep
        decoding to completion but nothing new enters a slot."""
        if self.role == "decode":
            if admit:
                self._drain_inbox()
        elif admit:
            self._admit()
        if self.role == "prefill":
            produced = self._advance_prefills()
            self._export_metrics()
            return produced
        produced = 0
        if self.num_active:
            if self.proposer is not None:
                produced = self._spec_step()
            else:
                self._ensure_pages()
                produced = self._decode_step()
        self._export_metrics()
        return produced

    def _batch_rows(self, active: list[int]) -> tuple[int, list[int]]:
        bucket = _bucket(len(active), self.decode_buckets)
        rows = active + [i for i in range(len(self.slots))
                         if i not in active][:bucket - len(active)]
        return bucket, rows[:bucket]

    def _decode_step(self) -> int:
        """One plain (non-speculative) decode step over the active slots,
        padded to a batch bucket. Callers run ``_ensure_pages`` first."""
        active = [i for i, r in enumerate(self.slots) if r is not None]
        if not active:
            return 0
        bucket, rows = self._batch_rows(active)
        tokens = self._next_tok[rows][:, None].copy()
        positions = self._lens[rows][:, None].copy()
        table = self._tables[rows].copy()
        # Inactive filler rows: scratch page table, position 0, token 0.
        for j, i in enumerate(rows):
            if self.slots[i] is None:
                tokens[j] = 0
                positions[j] = 0
                table[j] = 0
        step = self._get_step("decode", bucket, 1)
        t0 = self._clock() if self.reqtrace is not None else 0.0
        with self._span("decode" if self.role == "decode" else "step"):
            tok, self.cache = step(
                self.params, self.cache, jnp.asarray(tokens),
                jnp.asarray(positions), jnp.asarray(table),
                np.zeros(bucket, np.int32))
            out = np.asarray(tok)    # the step's ONE host sync
        # All per-request bookkeeping below (ITL samples, span events)
        # rides this single clock read — tracing adds no syncs.
        now = self._clock()
        self.stats["decode_steps"] += 1
        produced = 0
        for j, i in enumerate(rows):
            req = self.slots[i]
            if req is None:
                continue
            if self.slo is not None and req.token_times:
                self.slo.observe_itl(*self._slo_key,
                                     now - req.token_times[-1])
            req.generated.append(int(out[j]))
            req.token_times.append(now)
            self._lens[i] += 1
            self._next_tok[i] = int(out[j])
            produced += 1
            self._retire(i)
        if self.reqtrace is not None:
            self.reqtrace.span("decode_step", t0, now, role=self.role,
                               batch=len(active), produced=produced)
        self.stats["tokens_generated"] += produced
        return produced

    def _spec_step(self) -> int:
        """One speculative iteration: propose up to K drafts per slot,
        score all K+1 positions in ONE batched verify forward, accept the
        longest draft prefix that matches the model's own greedy argmax
        plus one bonus token (exact — emitted tokens are bit-identical to
        the unsped engine's), then roll the cache back over the overshoot.

        The verify program is the history-attention flavor at
        ``all_logits``: position ``len+m`` scores input m, and its output
        row echoes the input tokens so the single ``np.asarray`` fetch
        carries drafts and scores together (one host sync per step)."""
        ps = self.spec.page_size
        cap = self.table_width * ps - 1
        active = [i for i, r in enumerate(self.slots) if r is not None]
        # Per-slot draft budget: speculation must not run past a stop
        # condition the unsped engine would hit — at most remaining-1
        # drafts (so accepted+bonus <= tokens left) and never a write
        # position beyond the model length.
        budgets = {}
        for i in active:
            req = self.slots[i]
            remaining = req.max_new_tokens - len(req.generated)
            budgets[i] = max(0, min(self.draft_len, remaining - 1,
                                    self.max_model_len - 1
                                    - int(self._lens[i])))
        counts, values = self.proposer.propose(self, active, budgets)
        n_draft = {i: int(counts.get(i, 0)) for i in active}
        d_max = max(n_draft.values(), default=0)
        self._ensure_pages(extra=n_draft if d_max else None)
        survivors = [i for i, r in enumerate(self.slots) if r is not None]
        if d_max == 0 or survivors != active:
            # Nothing proposed (or an eviction invalidated the proposal
            # batch): fall back to a plain decode step this iteration.
            return self._decode_step()
        width = _bucket(d_max, self.draft_buckets) + 1
        bucket, rows = self._batch_rows(active)
        tokens = np.zeros((bucket, width), np.int32)
        positions = np.zeros((bucket, width), np.int32)
        table = np.zeros((bucket, self.table_width), np.int32)
        for j, i in enumerate(rows):
            if self.slots[i] is None:
                continue
            tokens[j, 0] = self._next_tok[i]
            if isinstance(values, dict):
                d = values.get(i, ())
                tokens[j, 1:1 + len(d)] = d
            positions[j] = np.minimum(
                int(self._lens[i]) + np.arange(width, dtype=np.int32), cap)
            table[j] = self._tables[i]
        tok_dev = jnp.asarray(tokens)
        if not isinstance(values, dict):
            # Device-resident drafts (draft-model proposer): scatter them
            # in without ever fetching them — the verify echo returns them.
            tok_dev = tok_dev.at[:len(active), 1:1 + values.shape[1]].set(
                values.astype(jnp.int32))
        step = self._get_step("verify", bucket, width)
        t0 = self._clock() if self.reqtrace is not None else 0.0
        with self._span("decode" if self.role == "decode" else "step"):
            out, self.cache = step(
                self.params, self.cache, tok_dev,
                jnp.asarray(positions), jnp.asarray(table),
                np.zeros(bucket, np.int32))
            fetched = np.asarray(out)    # [bucket, 2, width]: scores, echo
        # One host sync per verify step, same as plain decode; all span/SLO
        # bookkeeping below reads the fetched array + this one clock value.
        now = self._clock()
        self.stats["decode_steps"] += 1
        self.stats["spec_steps"] += 1
        produced = 0
        step_drafted = step_accepted = 0
        for j, i in enumerate(rows):
            req = self.slots[i]
            if req is None:
                continue
            scored, echoed = fetched[j, 0], fetched[j, 1]
            k = n_draft[i]
            n_acc = 0
            while n_acc < k and int(echoed[n_acc + 1]) == int(scored[n_acc]):
                n_acc += 1
            # Emit accepted drafts + the bonus token one at a time, exactly
            # like the unsped loop would — an eos mid-acceptance truncates.
            prev_t = req.token_times[-1] if req.token_times else None
            emitted = 0
            for t in [int(x) for x in echoed[1:1 + n_acc]] \
                    + [int(scored[n_acc])]:
                req.generated.append(t)
                req.token_times.append(now)
                self._lens[i] += 1
                produced += 1
                emitted += 1
                if req.finished(self.max_model_len):
                    break
            if self.slo is not None and prev_t is not None and emitted:
                # A verify step emits a burst sharing one timestamp; the
                # honest per-token latency is the step gap amortized over
                # the burst (one sample per request per step).
                self.slo.observe_itl(*self._slo_key,
                                     (now - prev_t) / emitted)
            self._next_tok[i] = req.generated[-1]
            self.stats["draft_tokens"] += k
            self.stats["accepted_tokens"] += n_acc
            self.stats[f"spec_accept_{n_acc}"] += 1
            step_drafted += k
            step_accepted += n_acc
            self._rollback(i)
            self._retire(i)
        if self.reqtrace is not None:
            self.reqtrace.span("spec_verify", t0, now, role=self.role,
                               batch=len(active), drafted=step_drafted,
                               accepted=step_accepted, produced=produced)
        self.stats["tokens_generated"] += produced
        return produced

    def _rollback(self, slot: int) -> None:
        """Drop the OVERSHOOT pages a verify step grew past the accepted
        length. Stale cache entries within kept pages need no cleanup —
        attention masks on position and later appends overwrite them —
        but whole pages beyond the next write target go back to the pool
        (refcount-safe: prompt pages shared with the prefix cache always
        precede the accepted length, so only private growth is dropped)."""
        req = self.slots[slot]
        if req is None:
            return
        keep = min(len(self._pages[slot]),
                   int(self._lens[slot]) // self.spec.page_size + 1)
        if keep >= len(self._pages[slot]):
            return
        for page in self._pages[slot][keep:]:
            self.pool.drop(req.request_id, page)
        self._tables[slot, keep:len(self._pages[slot])] = 0
        del self._pages[slot][keep:]

    def run(self, max_steps: int = 100000) -> list[Request]:
        """Drain every submitted request; returns the completed list."""
        steps = 0
        while self.has_work:
            self.step()
            steps += 1
            if steps > max_steps:
                raise RuntimeError(f"engine did not drain in {max_steps} "
                                   "steps (stop conditions broken?)")
        return self.completed

    def prefix_hit_rate(self) -> float:
        return self.stats["cached_tokens"] / max(self.stats["prompt_tokens"],
                                                 1)

    def _export_metrics(self) -> None:
        if self.metrics is None:
            return
        elapsed = max(self._clock() - self._t0, 1e-9)
        extra = {}
        if self.proposer is not None:
            extra.update(
                serve_spec_steps=self.stats["spec_steps"],
                serve_draft_tokens=self.stats["draft_tokens"],
                serve_accepted_tokens=self.stats["accepted_tokens"],
                serve_accept_rate=self.stats["accepted_tokens"]
                / max(self.stats["draft_tokens"], 1),
            )
        if self.prefix_cache is not None:
            extra.update(
                serve_prefix_hit_rate=self.prefix_hit_rate(),
                serve_cached_pages=self.prefix_cache.cached_pages,
                serve_cow_copies=self.stats["cow_copies"],
                serve_cache_evicted_pages=self.prefix_cache.stats[
                    "evicted_pages"],
            )
        self.metrics.update(
            serve_role=self.role,
            serve_active=self.num_active,
            serve_waiting=len(self.waiting),
            serve_completed=len(self.completed),
            serve_tokens_total=self.stats["tokens_generated"],
            serve_tokens_per_s=self.stats["tokens_generated"] / elapsed,
            serve_pages_free=self.pool.num_free,
            serve_evictions=self.stats["evictions"],
            serve_compiles=self.stats["compiles"],
            serve_decode_steps=self.stats["decode_steps"],
            **extra,
        )


class DisaggregatedServe:
    """A prefill-role + decode-role engine pair behind the single-engine
    interface (submit/step/has_work/completed), with the explicit KV
    handoff ferried between their pools each step. Pages cross the
    boundary as fixed-width device blocks, so both engines keep their
    one-compile-per-shape discipline."""

    def __init__(self, prefill_engine: ContinuousBatchingEngine,
                 decode_engine: ContinuousBatchingEngine):
        if prefill_engine.role != "prefill" or decode_engine.role != "decode":
            raise ValueError("DisaggregatedServe takes (prefill-role, "
                             "decode-role) engines in that order")
        if prefill_engine.table_width != decode_engine.table_width or \
                prefill_engine.spec.page_size != decode_engine.spec.page_size:
            raise ValueError("prefill/decode cache geometry mismatch: "
                             "handoff blocks must agree on page size and "
                             "table width")
        if prefill_engine.max_model_len != decode_engine.max_model_len:
            raise ValueError("prefill/decode max_model_len mismatch")
        self.prefill_engine = prefill_engine
        self.decode_engine = decode_engine

    def warmup(self) -> int:
        return self.prefill_engine.warmup() + self.decode_engine.warmup()

    def submit(self, req: Request) -> None:
        self.prefill_engine.submit(req)

    @property
    def waiting(self):
        return self.prefill_engine.waiting

    @property
    def num_active(self) -> int:
        return (self.prefill_engine.num_active
                + self.decode_engine.num_active
                + len(self.prefill_engine.handoffs)
                + len(self.decode_engine._inbox))

    @property
    def has_work(self) -> bool:
        return (self.prefill_engine.has_work or self.decode_engine.has_work
                or bool(self.prefill_engine.handoffs))

    @property
    def completed(self) -> list[Request]:
        return self.prefill_engine.completed + self.decode_engine.completed

    @property
    def max_model_len(self) -> int:
        return self.prefill_engine.max_model_len

    @property
    def prefix_cache(self):
        return self.prefill_engine.prefix_cache

    @property
    def reqtrace(self):
        """The pair shares one RequestTrace (built per replica); role tids
        keep the prefill and decode lanes apart inside it."""
        return self.prefill_engine.reqtrace

    def prefix_hit_rate(self) -> float:
        return self.prefill_engine.prefix_hit_rate()

    @property
    def stats(self) -> dict:
        merged = dict(self.prefill_engine.stats)
        for k, v in self.decode_engine.stats.items():
            merged[k] = merged.get(k, 0) + v
        return merged

    def step(self, admit: bool = True) -> int:
        produced = self.prefill_engine.step(admit=admit)
        for handoff in self.prefill_engine.take_handoffs():
            self.decode_engine.ingest(handoff)
        for req in self.decode_engine.take_requeued():
            self.prefill_engine.waiting.appendleft(req)
        produced += self.decode_engine.step()
        return produced

    def run(self, max_steps: int = 100000) -> list[Request]:
        steps = 0
        while self.has_work:
            self.step()
            steps += 1
            if steps > max_steps:
                raise RuntimeError(f"disaggregated pair did not drain in "
                                   f"{max_steps} steps")
        return self.completed
