"""Open-loop Poisson load generator for the serving engine.

OPEN loop: arrival times are drawn up front from a seeded exponential
inter-arrival process and never react to engine backpressure — the
generator keeps "sending" on schedule even while the engine is saturated,
which is what makes saturation-mode p99s honest (a closed loop would
self-throttle and hide the queueing delay).

Everything is seeded through one ``np.random.default_rng(seed)`` (this
module sits under the GL005 lint scope): same seed, same request stream,
same page-table evolution — serve runs diff bit-for-bit.
"""

from __future__ import annotations

import dataclasses

import numpy as np

from pytorch_distributed_training_example_tpu.serve.engine import Request


@dataclasses.dataclass(frozen=True)
class LoadSpec:
    """Shape of the synthetic request stream."""

    num_requests: int = 32
    rate: float = 0.0            # requests/s; <= 0 means all arrive at t=0
    prompt_len_min: int = 4
    prompt_len_max: int = 24
    max_new_min: int = 4
    max_new_max: int = 24
    vocab_size: int = 512
    eos_id: int | None = None    # None: length-bounded generation only
    seed: int = 0
    # Skewed shared-prefix workload (the realistic serving distribution:
    # a handful of system prompts / few-shot templates dominate traffic).
    # num_templates > 0 prepends a template prefix to every prompt, with
    # template popularity Zipf-distributed: p(rank k) ∝ 1 / k**zipf_a.
    num_templates: int = 0
    zipf_a: float = 1.2
    prefix_len_min: int = 16
    prefix_len_max: int = 32


def generate_requests(spec: LoadSpec) -> list[Request]:
    """The full request stream, arrival-time sorted. ``rate <= 0`` is the
    saturation configuration: every request is available immediately."""
    rng = np.random.default_rng(spec.seed)
    if spec.rate > 0:
        arrivals = np.cumsum(rng.exponential(1.0 / spec.rate,
                                             spec.num_requests))
    else:
        arrivals = np.zeros(spec.num_requests)
    templates: list[list[int]] = []
    weights = None
    if spec.num_templates > 0:
        for _ in range(spec.num_templates):
            tlen = int(rng.integers(spec.prefix_len_min,
                                    spec.prefix_len_max + 1))
            templates.append(rng.integers(1, spec.vocab_size, tlen).tolist())
        # Explicit ranked-probability Zipf (``rng.zipf`` is unbounded).
        ranks = np.arange(1, spec.num_templates + 1, dtype=np.float64)
        weights = ranks ** -spec.zipf_a
        weights /= weights.sum()
    out = []
    for i in range(spec.num_requests):
        plen = int(rng.integers(spec.prompt_len_min, spec.prompt_len_max + 1))
        prompt = rng.integers(1, spec.vocab_size, plen).tolist()
        if templates:
            t = int(rng.choice(spec.num_templates, p=weights))
            prompt = templates[t] + prompt
        max_new = int(rng.integers(spec.max_new_min, spec.max_new_max + 1))
        out.append(Request(request_id=f"req{i:04d}", prompt=prompt,
                           max_new_tokens=max_new, eos_id=spec.eos_id,
                           arrival_time=float(arrivals[i])))
    return out


class OpenLoopDriver:
    """Feed a request stream into an engine on its arrival schedule.

    The caller owns the clock (pass elapsed seconds since the run began)
    so tests can drive virtual time; ``pump`` submits everything whose
    arrival time has passed and returns how many were submitted.
    """

    def __init__(self, requests: list[Request]):
        self._pending = sorted(requests, key=lambda r: r.arrival_time)
        self._cursor = 0

    @property
    def remaining(self) -> int:
        return len(self._pending) - self._cursor

    def pump(self, engine, now: float) -> int:
        sent = 0
        while (self._cursor < len(self._pending)
               and self._pending[self._cursor].arrival_time <= now):
            engine.submit(self._pending[self._cursor])
            self._cursor += 1
            sent += 1
        return sent
