"""Paged KV cache: fixed-size pages in a preallocated pool.

The cache for one attention layer is a pair of pools shaped
``[num_pages, page_size, num_kv_heads, head_dim]``. A request owns a
*page table* — a row of physical page ids, one per ``page_size`` logical
tokens — so its K/V live scattered across the pool and the pool never
fragments: any free page serves any request (SURVEY.md's serving gap,
ROADMAP item 2; the layout is vLLM's PagedAttention applied to the r6
``ONLINE_BLOCK_TABLE`` block-indexing machinery). GQA keeps only
``num_kv_heads`` KV heads per page (4:1 on the bench trunk), which cuts
cache bytes by the same ratio versus MHA.

Split of responsibilities:

- ``PagePool`` is the HOST-side allocator (plain python free list). It
  never touches device memory — it hands out integer page ids that the
  engine writes into page-table rows between decode steps.
- ``append_pages`` / ``gather_pages`` are the DEVICE-side functional ops
  traced into the prefill/decode steps. They are pure (functional
  update; the engine donates the pools so XLA updates in place).

Page 0 is RESERVED as a scratch page: padded (inactive) batch rows point
their entire page table at it, so their appends land somewhere harmless
and their reads are masked by position anyway.
"""

from __future__ import annotations

import dataclasses
from typing import Any

import jax
import jax.numpy as jnp

RESERVED_PAGES = 1  # page 0: scratch target for padded batch rows


@dataclasses.dataclass(frozen=True)
class CacheSpec:
    """Static geometry of one model's paged cache."""

    num_layers: int
    num_pages: int          # pool size, INCLUDING the reserved scratch page
    page_size: int          # tokens per page
    num_kv_heads: int
    head_dim: int
    dtype: Any = jnp.float32

    @property
    def max_len(self) -> int:
        """Upper bound on any single sequence (pool capacity aside)."""
        return (self.num_pages - RESERVED_PAGES) * self.page_size

    @property
    def bytes_per_page(self) -> int:
        itemsize = jnp.dtype(self.dtype).itemsize
        # K and V pools, every layer.
        return (2 * self.num_layers * self.page_size * self.num_kv_heads
                * self.head_dim * itemsize)

    def layer_shape(self) -> tuple[int, int, int, int]:
        return (self.num_pages, self.page_size, self.num_kv_heads,
                self.head_dim)


def pages_for_tokens(num_tokens: int, page_size: int) -> int:
    """Pages needed to hold ``num_tokens`` logical positions."""
    return -(-max(num_tokens, 1) // page_size)


class PagePool:
    """Host-side refcounted free list over page ids ``[RESERVED, num_pages)``.

    LIFO reuse keeps recently-freed pages hot; determinism matters more
    than locality here — same admission order, same page tables, so
    same-seed serve runs are bit-reproducible.

    Refcounts make prefix sharing safe: ``alloc`` hands out private pages
    (refcount 1), ``share`` adds an owner to an existing page, and a page
    only returns to the free list once every owner has released it. The
    engine's copy-on-write trigger is exactly ``refcount(page) > 1`` at
    the moment a write would land in it. Invariants are enforced loudly:
    the scratch page is never allocated or shared, a refcount can never
    go negative, and releasing a page twice through the same owner raises.
    """

    def __init__(self, num_pages: int):
        if num_pages <= RESERVED_PAGES:
            raise ValueError(
                f"num_pages={num_pages} leaves no allocatable pages "
                f"({RESERVED_PAGES} reserved)")
        self.num_pages = num_pages
        self._free: list[int] = list(range(num_pages - 1, RESERVED_PAGES - 1,
                                           -1))
        self._owned: dict[str, list[int]] = {}
        self._refs: dict[int, int] = {}

    @property
    def num_free(self) -> int:
        return len(self._free)

    def can_alloc(self, n: int) -> bool:
        return n <= len(self._free)

    def refcount(self, page: int) -> int:
        """Owners currently holding ``page`` (0 = free)."""
        return self._refs.get(page, 0)

    def alloc(self, request_id: str, n: int) -> list[int]:
        """Take ``n`` private pages for ``request_id``; raises if short
        (callers check ``can_alloc`` first — admission control, not
        exceptions, decides who runs)."""
        if n > len(self._free):
            raise MemoryError(
                f"page pool exhausted: want {n}, have {len(self._free)}")
        pages = [self._free.pop() for _ in range(n)]
        self._owned.setdefault(request_id, []).extend(pages)
        for p in pages:
            self._refs[p] = 1
        return pages

    def share(self, request_id: str, pages: list[int]) -> None:
        """Add ``request_id`` as an owner of already-allocated ``pages``."""
        for p in pages:
            if p < RESERVED_PAGES:
                raise ValueError(f"page {p} is reserved scratch")
            if self._refs.get(p, 0) <= 0:
                raise ValueError(f"page {p} is free; cannot share")
        self._owned.setdefault(request_id, []).extend(pages)
        for p in pages:
            self._refs[p] += 1

    def drop(self, request_id: str, page: int) -> None:
        """Release ONE reference ``request_id`` holds on ``page``."""
        owned = self._owned.get(request_id)
        if owned is None or page not in owned:
            raise ValueError(
                f"double free: {request_id!r} does not own page {page}")
        owned.remove(page)
        if not owned:
            del self._owned[request_id]
        self._unref(page)

    def free(self, request_id: str) -> int:
        """Release every reference held by ``request_id``; idempotent."""
        pages = self._owned.pop(request_id, [])
        for p in reversed(pages):
            self._unref(p)
        return len(pages)

    def _unref(self, page: int) -> None:
        rc = self._refs.get(page, 0)
        if rc <= 0:
            raise ValueError(f"refcount underflow on page {page}")
        rc -= 1
        if rc == 0:
            del self._refs[page]
            self._free.append(page)
        else:
            self._refs[page] = rc

    def owned(self, request_id: str) -> list[int]:
        return list(self._owned.get(request_id, ()))


def init_cache(spec: CacheSpec) -> dict:
    """Zeroed K/V pools for every layer, keyed like the flax ``cache``
    collection an UNROLLED model's decode path declares (``block_i/attn``).
    Prefer ``init_model_cache`` — it derives the pytree from the model
    itself and therefore also covers ``scan_layers`` stacked pools."""
    shape = spec.layer_shape()
    return {
        f"block_{i}": {"attn": {
            "k_pages": jnp.zeros(shape, spec.dtype),
            "v_pages": jnp.zeros(shape, spec.dtype),
        }}
        for i in range(spec.num_layers)
    }


def init_model_cache(module, spec: CacheSpec, table_width: int,
                     attn_impl: str = "auto") -> dict:
    """Zeroed K/V pools matching the cache structure ``module`` itself
    declares, derived via ``jax.eval_shape`` over ``module.init`` — so
    unrolled blocks (per-block [P, page_size, Hkv, D] pools) and
    ``scan_layers`` models (one stacked [L, P, page_size, Hkv, D] carry)
    both get the right pytree without callers hardcoding either layout.
    Shape-only: no parameters are materialized and nothing runs."""

    def init_fn():
        return module.init(
            jax.random.PRNGKey(0), jnp.zeros((1, 1), jnp.int32),
            train=False,
            decode_ctx=dict(
                positions=jnp.zeros((1, 1), jnp.int32),
                page_table=jnp.zeros((1, table_width), jnp.int32),
                cache_spec=(spec.num_pages, spec.page_size),
                last_index=jnp.zeros((1,), jnp.int32),
                history=False, attn_impl=attn_impl))

    shapes = jax.eval_shape(init_fn)["cache"]
    return jax.tree.map(lambda s: jnp.zeros(s.shape, s.dtype), shapes)


def append_pages(pages: jax.Array, new: jax.Array, page_table: jax.Array,
                 positions: jax.Array) -> jax.Array:
    """Scatter ``new`` K or V rows into the pool through the page table.

    pages:      [P, page_size, Hkv, D] pool (donated by the engine step)
    new:        [B, S, Hkv, D] freshly-projected K or V
    page_table: [B, max_pages] int32 physical page per logical block
    positions:  [B, S] int32 logical position of each new token

    Token (b, s) lands in page ``page_table[b, positions // page_size]``
    at slot ``positions % page_size``. Padded rows carry page tables full
    of the scratch page, so their writes collide harmlessly on page 0.
    """
    B, S, Hkv, D = new.shape
    page_size = pages.shape[1]
    page_ids = jnp.take_along_axis(page_table,
                                   positions // page_size, axis=1)  # [B, S]
    slots = positions % page_size
    flat_new = new.reshape(B * S, Hkv, D).astype(pages.dtype)
    return pages.at[page_ids.reshape(-1), slots.reshape(-1)].set(
        flat_new, mode="drop")


def copy_page(cache: dict, src: jax.Array, dst: jax.Array) -> dict:
    """Copy-on-write: clone physical page ``src`` into ``dst`` across every
    layer's K and V pools.

    ``src``/``dst`` are scalar int32 page ids, so one compiled program
    serves every COW event — the engine traces this once and replays it
    whenever a write would land in a page whose refcount exceeds one.

    Pool leaves are [P, page_size, Hkv, D] for unrolled blocks, or
    [L, P, page_size, Hkv, D] when ``scan_layers`` stacks every block's
    pool into one scanned carry — the page axis is ``ndim - 4`` either
    way, so each op rank-dispatches on the leaf.
    """
    def _cp(pages: jax.Array) -> jax.Array:
        if pages.ndim == 5:  # scanned stack: page axis 1
            return pages.at[:, dst].set(pages[:, src])
        return pages.at[dst].set(pages[src])

    return jax.tree.map(_cp, cache)


def extract_pages(cache: dict, page_ids: jax.Array) -> dict:
    """Gather a fixed-width block of physical pages from every pool.

    ``page_ids`` is a [W] int32 vector padded with the scratch page, so
    one compiled program covers every prefill→decode handoff regardless
    of how many pages the sequence actually owns. Returns a pytree of
    [W, page_size, Hkv, D] blocks ([L, W, ...] for scanned stacks).
    """
    def _ex(pages: jax.Array) -> jax.Array:
        if pages.ndim == 5:
            return pages[:, page_ids]
        return pages[page_ids]

    return jax.tree.map(_ex, cache)


def insert_pages(cache: dict, block: dict, page_ids: jax.Array) -> dict:
    """Scatter an extracted block into this pool's pages at ``page_ids``.

    Padded rows target the scratch page, so their stale contents collide
    harmlessly on page 0 — the decode-side half of the KV handoff.
    """
    def _ins(pages: jax.Array, b: jax.Array) -> jax.Array:
        if pages.ndim == 5:
            return pages.at[:, page_ids].set(b.astype(pages.dtype))
        return pages.at[page_ids].set(b.astype(pages.dtype))

    return jax.tree.map(_ins, cache, block)


def gather_pages(pages: jax.Array, page_table: jax.Array) -> jax.Array:
    """Materialize each request's logical K/V view from the pool.

    Returns [B, max_pages * page_size, Hkv, D]; positions past a
    request's length hold stale pool contents and MUST be masked by the
    caller (attention masks on position). This is the XLA decode path —
    the Pallas kernel reads pages in place instead.
    """
    B, max_pages = page_table.shape
    _, page_size, Hkv, D = pages.shape
    gathered = jnp.take(pages, page_table.reshape(-1), axis=0)
    return gathered.reshape(B, max_pages * page_size, Hkv, D)
