"""Paged KV cache: fixed-size pages in a preallocated pool.

The cache for one attention layer is a pair of pools shaped
``[num_pages, page_size, num_kv_heads, head_dim]``. A request owns a
*page table* — a row of physical page ids, one per ``page_size`` logical
tokens — so its K/V live scattered across the pool and the pool never
fragments: any free page serves any request (SURVEY.md's serving gap,
ROADMAP item 2; the layout is vLLM's PagedAttention applied to the r6
``ONLINE_BLOCK_TABLE`` block-indexing machinery). GQA keeps only
``num_kv_heads`` KV heads per page (4:1 on the bench trunk), which cuts
cache bytes by the same ratio versus MHA.

Split of responsibilities:

- ``PagePool`` is the HOST-side allocator (plain python free list). It
  never touches device memory — it hands out integer page ids that the
  engine writes into page-table rows between decode steps.
- ``append_pages`` / ``gather_pages`` are the DEVICE-side functional ops
  traced into the prefill/decode steps. They are pure (functional
  update; the engine donates the pools so XLA updates in place).

Page 0 is RESERVED as a scratch page: padded (inactive) batch rows point
their entire page table at it, so their appends land somewhere harmless
and their reads are masked by position anyway.
"""

from __future__ import annotations

import dataclasses
from typing import Any

import jax
import jax.numpy as jnp

RESERVED_PAGES = 1  # page 0: scratch target for padded batch rows


@dataclasses.dataclass(frozen=True)
class CacheSpec:
    """Static geometry of one model's paged cache."""

    num_layers: int
    num_pages: int          # pool size, INCLUDING the reserved scratch page
    page_size: int          # tokens per page
    num_kv_heads: int
    head_dim: int
    dtype: Any = jnp.float32

    @property
    def max_len(self) -> int:
        """Upper bound on any single sequence (pool capacity aside)."""
        return (self.num_pages - RESERVED_PAGES) * self.page_size

    @property
    def bytes_per_page(self) -> int:
        itemsize = jnp.dtype(self.dtype).itemsize
        # K and V pools, every layer.
        return (2 * self.num_layers * self.page_size * self.num_kv_heads
                * self.head_dim * itemsize)

    def layer_shape(self) -> tuple[int, int, int, int]:
        return (self.num_pages, self.page_size, self.num_kv_heads,
                self.head_dim)


def pages_for_tokens(num_tokens: int, page_size: int) -> int:
    """Pages needed to hold ``num_tokens`` logical positions."""
    return -(-max(num_tokens, 1) // page_size)


class PagePool:
    """Host-side free list over physical page ids ``[RESERVED, num_pages)``.

    LIFO reuse keeps recently-freed pages hot; determinism matters more
    than locality here — same admission order, same page tables, so
    same-seed serve runs are bit-reproducible.
    """

    def __init__(self, num_pages: int):
        if num_pages <= RESERVED_PAGES:
            raise ValueError(
                f"num_pages={num_pages} leaves no allocatable pages "
                f"({RESERVED_PAGES} reserved)")
        self.num_pages = num_pages
        self._free: list[int] = list(range(num_pages - 1, RESERVED_PAGES - 1,
                                           -1))
        self._owned: dict[str, list[int]] = {}

    @property
    def num_free(self) -> int:
        return len(self._free)

    def can_alloc(self, n: int) -> bool:
        return n <= len(self._free)

    def alloc(self, request_id: str, n: int) -> list[int]:
        """Take ``n`` pages for ``request_id``; raises if short (callers
        check ``can_alloc`` first — admission control, not exceptions,
        decides who runs)."""
        if n > len(self._free):
            raise MemoryError(
                f"page pool exhausted: want {n}, have {len(self._free)}")
        pages = [self._free.pop() for _ in range(n)]
        self._owned.setdefault(request_id, []).extend(pages)
        return pages

    def free(self, request_id: str) -> int:
        """Return every page owned by ``request_id``; idempotent."""
        pages = self._owned.pop(request_id, [])
        self._free.extend(reversed(pages))
        return len(pages)

    def owned(self, request_id: str) -> list[int]:
        return list(self._owned.get(request_id, ()))


def init_cache(spec: CacheSpec) -> dict:
    """Zeroed K/V pools for every layer, keyed like the flax ``cache``
    collection the model's decode path declares (``block_i/attn``)."""
    shape = spec.layer_shape()
    return {
        f"block_{i}": {"attn": {
            "k_pages": jnp.zeros(shape, spec.dtype),
            "v_pages": jnp.zeros(shape, spec.dtype),
        }}
        for i in range(spec.num_layers)
    }


def append_pages(pages: jax.Array, new: jax.Array, page_table: jax.Array,
                 positions: jax.Array) -> jax.Array:
    """Scatter ``new`` K or V rows into the pool through the page table.

    pages:      [P, page_size, Hkv, D] pool (donated by the engine step)
    new:        [B, S, Hkv, D] freshly-projected K or V
    page_table: [B, max_pages] int32 physical page per logical block
    positions:  [B, S] int32 logical position of each new token

    Token (b, s) lands in page ``page_table[b, positions // page_size]``
    at slot ``positions % page_size``. Padded rows carry page tables full
    of the scratch page, so their writes collide harmlessly on page 0.
    """
    B, S, Hkv, D = new.shape
    page_size = pages.shape[1]
    page_ids = jnp.take_along_axis(page_table,
                                   positions // page_size, axis=1)  # [B, S]
    slots = positions % page_size
    flat_new = new.reshape(B * S, Hkv, D).astype(pages.dtype)
    return pages.at[page_ids.reshape(-1), slots.reshape(-1)].set(
        flat_new, mode="drop")


def gather_pages(pages: jax.Array, page_table: jax.Array) -> jax.Array:
    """Materialize each request's logical K/V view from the pool.

    Returns [B, max_pages * page_size, Hkv, D]; positions past a
    request's length hold stale pool contents and MUST be masked by the
    caller (attention masks on position). This is the XLA decode path —
    the Pallas kernel reads pages in place instead.
    """
    B, max_pages = page_table.shape
    _, page_size, Hkv, D = pages.shape
    gathered = jnp.take(pages, page_table.reshape(-1), axis=0)
    return gathered.reshape(B, max_pages * page_size, Hkv, D)
