"""Request-level serving observability: span traces + sliding-window SLOs.

Two host-side recorders, both zero-intrusion by construction — they only
consume timestamps the engine already takes (one injected-clock read per
decode step, one per admission) plus the token ids that come back through
the engine's single ``np.asarray`` fetch. Nothing here touches the device,
so compile counts and emitted tokens are identical with tracing on or off
(asserted in tests/test_slo.py and dryrun leg 20).

- :class:`RequestTrace`: a bounded ring of request-lifecycle span events
  (router admit, queue wait, prefill, KV handoff, per-step decode, spec
  verify, COW/eviction) emitted as a Perfetto-compatible per-replica trace
  file ``reqtrace.<replica>.a<attempt>.json`` that ``benchmarks/
  trace_merge.py`` aligns next to the training-rank tracks. The ring plus
  generation rotation (``rotate``) bounds artifact growth on long open-loop
  runs; wrapping is LOUD — ``dropped_spans`` counts every evicted event and
  is stamped into the file header and the ``/metrics`` gauges.
- :class:`SLOTracker`: sliding-window p50/p99 TTFT and inter-token latency
  per (replica, role), clock-injected like ``utils/scheduler.py`` so tests
  are deterministic. Snapshots export as gauges + cumulative Prometheus
  histograms on the existing ``MetricsServer`` and flush atomically to
  ``slo.jsonl`` — the file ``FleetScheduler.plan`` reads to fold SLO
  attainment into a serve job's placement weight, and the file
  ``check_regression.py --slo`` gates in CI.

Quantiles use the same linear interpolation as ``numpy.percentile``'s
default so the tests can diff against a numpy reference exactly.
"""

from __future__ import annotations

import collections
import json
import logging
import math
import os
import time
from typing import Callable

from pytorch_distributed_training_example_tpu.utils import fleetobs

log = logging.getLogger("pdtx")

#: slo.jsonl lives in the serve job's checkpoint directory — the same place
#: the scheduler already reads goodput.json from — so the placement loop
#: needs no new plumbing to find it. The name (and the attainment reader)
#: live in stdlib fleetobs so the jax-free scheduler/launcher never import
#: the serve package.
SLO_FILE = fleetobs.SLO_FILE

#: Cumulative histogram bucket upper bounds, milliseconds (Prometheus
#: ``le`` convention; ``+Inf`` is implicit as the final bucket).
HIST_BUCKETS_MS = (1.0, 2.5, 5.0, 10.0, 25.0, 50.0, 100.0, 250.0, 500.0,
                   1000.0, 2500.0)


def quantile(samples, q: float) -> float | None:
    """q-th percentile (0..100) with numpy's default linear interpolation.

    Pure stdlib so the SLO path needs no numpy at import; the test suite
    asserts exact agreement with ``np.percentile(samples, q)``.
    """
    xs = sorted(samples)
    if not xs:
        return None
    if len(xs) == 1:
        return float(xs[0])
    pos = (len(xs) - 1) * (float(q) / 100.0)
    lo = math.floor(pos)
    hi = min(math.ceil(pos), len(xs) - 1)
    frac = pos - lo
    return float(xs[lo]) * (1.0 - frac) + float(xs[hi]) * frac


# ---------------------------------------------------------------------------
# RequestTrace: bounded per-replica span ring -> Perfetto trace files
# ---------------------------------------------------------------------------

#: Stable thread ids per engine role so a replica's prefill and decode
#: lanes render as separate named tracks under one process group.
ROLE_TIDS = {"both": 0, "prefill": 1, "decode": 2, "router": 3}


class RequestTrace:
    """Bounded ring of request-lifecycle events for ONE serve replica.

    Events carry timestamps from the caller's injected monotonic clock (the
    engine hands in the ``now`` it already took after its decode fetch); the
    wall/monotonic anchor captured at construction lets the merge CLI align
    this replica's track with every other host's, exactly like
    ``SpanRecorder``. When the ring is full the OLDEST event is dropped and
    ``dropped_spans`` increments — silently growing files on long open-loop
    runs is the failure mode this replaces, so the drop is by design loud:
    warned once, stamped in the file header, exported as a gauge.
    """

    def __init__(self, replica: str, *, role: str = "both", run_id: str = "",
                 capacity: int = 4096, max_generations: int = 4,
                 clock: Callable[[], float] = time.perf_counter,
                 wall_clock: Callable[[], float] = time.time):
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        self.replica = str(replica)
        self.role = role
        self.run_id = run_id
        self.capacity = int(capacity)
        self.max_generations = int(max_generations)
        self._clock = clock
        self._anchor_mono = clock()
        self._anchor_wall = wall_clock()
        self._events: collections.deque = collections.deque(maxlen=capacity)
        self.dropped_spans = 0
        self._generation = 0
        self._warned = False

    # ------------------------------------------------------------ recording

    @property
    def pending(self) -> int:
        return len(self._events)

    def event(self, name: str, t0: float, dur_s: float = 0.0, *,
              role: str | None = None, **args) -> None:
        """One span (``dur_s > 0``) or instant (``dur_s == 0``) event at
        injected-clock time ``t0``. Never blocks, never syncs."""
        if len(self._events) == self.capacity:
            self.dropped_spans += 1
            if not self._warned:
                self._warned = True
                log.warning(
                    "reqtrace[%s]: span ring full (capacity=%d) — dropping "
                    "oldest events; rotate() more often or raise "
                    "--serve-trace-events", self.replica, self.capacity)
        self._events.append((name, role or self.role, t0, dur_s, args))

    def instant(self, name: str, t: float | None = None, *,
                role: str | None = None, **args) -> None:
        self.event(name, self._clock() if t is None else t, 0.0,
                   role=role, **args)

    def span(self, name: str, t0: float, t1: float, *,
             role: str | None = None, **args) -> None:
        self.event(name, t0, max(t1 - t0, 0.0), role=role, **args)

    # -------------------------------------------------------------- emitting

    def trace_events(self) -> dict:
        """Perfetto/Chrome trace doc, ``otherData`` first (same torn-write
        salvage contract as ``SpanRecorder.trace_events``)."""
        events = []
        for name, role, t0, dur_s, args in self._events:
            ev = {"name": name,
                  "ph": "X" if dur_s > 0 else "i",
                  "cat": "serve",
                  "ts": int((t0 - self._anchor_mono) * 1e6),
                  "pid": 0,
                  "tid": ROLE_TIDS.get(role, 7)}
            if dur_s > 0:
                ev["dur"] = int(dur_s * 1e6)
            else:
                ev["s"] = "t"
            if args:
                ev["args"] = {k: v for k, v in args.items()}
            events.append(ev)
        return fleetobs.trace_doc(
            run_id=self.run_id,
            anchor_wall=self._anchor_wall, anchor_mono=self._anchor_mono,
            events=events,
            meta={"replica": self.replica, "role": self.role,
                  "host": fleetobs.host_identity(),
                  "dropped_spans": self.dropped_spans,
                  "generation": self._generation,
                  "roles": {str(v): k for k, v in ROLE_TIDS.items()}})

    def _path(self, directory: str, attempt: int, gen: int | None) -> str:
        g = "" if gen is None else f".g{gen}"
        return os.path.join(directory,
                            f"reqtrace.{self.replica}.a{attempt}{g}.json")

    def write(self, directory: str, attempt: int = 1) -> str:
        """Final snapshot (ring is kept): ``reqtrace.<replica>.a<N>.json``."""
        os.makedirs(directory, exist_ok=True)
        path = self._path(directory, attempt, None)
        fleetobs.write_json_atomic(path, self.trace_events())
        return path

    def rotate(self, directory: str, attempt: int = 1) -> str:
        """Flush the ring to the next generation file and clear it, keeping
        at most ``max_generations`` on disk — the cap that bounds artifact
        growth on long open-loop runs (satellite of r20)."""
        os.makedirs(directory, exist_ok=True)
        path = self._path(directory, attempt, self._generation)
        fleetobs.write_json_atomic(path, self.trace_events())
        self._events.clear()
        stale = self._generation - self.max_generations
        self._generation += 1
        if stale >= 0:
            try:
                os.unlink(self._path(directory, attempt, stale))
            except OSError:
                pass
        return path


# ---------------------------------------------------------------------------
# SLOTracker: sliding-window TTFT/ITL quantiles + attainment
# ---------------------------------------------------------------------------


class _Window:
    __slots__ = ("ttft", "itl")

    def __init__(self, window: int):
        self.ttft: collections.deque = collections.deque(maxlen=window)
        self.itl: collections.deque = collections.deque(maxlen=window)


class _Hist:
    """Cumulative (never-evicted) histogram in Prometheus bucket form."""

    __slots__ = ("counts", "total", "count")

    def __init__(self):
        self.counts = [0] * (len(HIST_BUCKETS_MS) + 1)
        self.total = 0.0
        self.count = 0

    def add(self, ms: float) -> None:
        for i, le in enumerate(HIST_BUCKETS_MS):
            if ms <= le:
                self.counts[i] += 1
                break
        else:
            self.counts[-1] += 1
        self.total += ms
        self.count += 1

    def render(self) -> dict:
        cum, out = 0, []
        for le, c in zip(HIST_BUCKETS_MS, self.counts):
            cum += c
            out.append((le, cum))
        out.append(("+Inf", self.count))
        return {"buckets": out, "sum": round(self.total, 3),
                "count": self.count}


class SLOTracker:
    """Sliding-window p50/p99 TTFT + ITL per (replica, role).

    Windows are sample-count sliding (``deque(maxlen=window)``) — eviction
    keeps the quantiles responsive to the CURRENT load regime instead of
    averaging over the whole run. Targets of 0 disable attainment/breach
    accounting (attainment reports 1.0). The clock is injected and only
    used for breach-episode bookkeeping, never for sample values — callers
    pass in latencies they measured themselves, which is what keeps this
    module out of the engine's host-sync budget.
    """

    def __init__(self, *, window: int = 256, ttft_target_ms: float = 0.0,
                 itl_target_ms: float = 0.0, min_breach_samples: int = 8,
                 clock: Callable[[], float] = time.perf_counter):
        if window < 1:
            raise ValueError(f"window must be >= 1, got {window}")
        self.window = int(window)
        self.ttft_target_ms = float(ttft_target_ms)
        self.itl_target_ms = float(itl_target_ms)
        self.min_breach_samples = int(min_breach_samples)
        self._clock = clock
        self._windows: dict[tuple[str, str], _Window] = {}
        self._hists: dict[tuple[str, str, str], _Hist] = {}
        self._in_breach = False
        self.breaches = 0

    # ------------------------------------------------------------ observing

    def _win(self, replica: str, role: str) -> _Window:
        key = (str(replica), str(role))
        if key not in self._windows:
            self._windows[key] = _Window(self.window)
        return self._windows[key]

    def observe_ttft(self, replica: str, role: str, seconds: float) -> None:
        ms = float(seconds) * 1e3
        self._win(replica, role).ttft.append(ms)
        self._hists.setdefault((replica, role, "ttft"), _Hist()).add(ms)

    def observe_itl(self, replica: str, role: str, seconds: float) -> None:
        ms = float(seconds) * 1e3
        self._win(replica, role).itl.append(ms)
        self._hists.setdefault((replica, role, "itl"), _Hist()).add(ms)

    # ------------------------------------------------------------ reporting

    @staticmethod
    def _ok(samples, target_ms: float) -> tuple[int, int]:
        if target_ms <= 0 or not samples:
            return len(samples), len(samples)
        return sum(1 for s in samples if s <= target_ms), len(samples)

    def snapshot(self) -> dict:
        """Per-(replica, role) window stats keyed ``"replica/role"``."""
        out = {}
        for (replica, role), w in sorted(self._windows.items()):
            ok_t, n_t = self._ok(w.ttft, self.ttft_target_ms)
            ok_i, n_i = self._ok(w.itl, self.itl_target_ms)
            total = n_t + n_i
            out[f"{replica}/{role}"] = {
                "replica": replica, "role": role,
                "ttft_count": n_t, "itl_count": n_i,
                "ttft_p50_ms": quantile(w.ttft, 50),
                "ttft_p99_ms": quantile(w.ttft, 99),
                "itl_p50_ms": quantile(w.itl, 50),
                "itl_p99_ms": quantile(w.itl, 99),
                "attainment": (ok_t + ok_i) / total if total else 1.0,
            }
        return out

    def overall_attainment(self) -> float:
        """Pooled in-target fraction across every window — the scalar the
        fleet scheduler quantizes into a serve job's placement weight."""
        ok = n = 0
        for w in self._windows.values():
            ok_t, n_t = self._ok(w.ttft, self.ttft_target_ms)
            ok_i, n_i = self._ok(w.itl, self.itl_target_ms)
            ok += ok_t + ok_i
            n += n_t + n_i
        return ok / n if n else 1.0

    def breach(self) -> str | None:
        """Episode-gated breach check: returns a reason string on the FIRST
        check where some window's p99 exceeds its target (with at least
        ``min_breach_samples`` samples), then stays quiet until every
        window has recovered — the same episode semantics as
        ``telemetry.AnomalyGuard`` so one bad stretch produces one
        FlightRecorder dump, not one per step."""
        bad = []
        for (replica, role), w in sorted(self._windows.items()):
            for metric, samples, target in (
                    ("ttft", w.ttft, self.ttft_target_ms),
                    ("itl", w.itl, self.itl_target_ms)):
                if target <= 0 or len(samples) < self.min_breach_samples:
                    continue
                p99 = quantile(samples, 99)
                if p99 is not None and p99 > target:
                    bad.append(f"{replica}/{role}:{metric}_p99="
                               f"{p99:.1f}ms>{target:g}ms")
        if not bad:
            self._in_breach = False
            return None
        if self._in_breach:
            return None
        self._in_breach = True
        self.breaches += 1
        return "slo_breach:" + ",".join(bad)

    def gauges(self, extra_dropped: int = 0) -> dict:
        """Flat gauge dict for ``MetricsServer.update`` (names are
        sanitized by the server; ``/`` becomes ``_``)."""
        out = {"serve_slo_attainment": round(self.overall_attainment(), 4),
               "serve_slo_breaches": self.breaches,
               "serve_slo_dropped_spans": extra_dropped}
        for key, snap in self.snapshot().items():
            for metric in ("ttft_p50_ms", "ttft_p99_ms",
                           "itl_p50_ms", "itl_p99_ms"):
                v = snap[metric]
                if v is not None:
                    out[f"serve_slo_{metric}_{key}"] = round(v, 3)
        return out

    def histograms(self) -> dict:
        """Cumulative histograms for ``MetricsServer.update_histograms``."""
        return {f"serve_slo_{metric}_ms_{replica}_{role}": h.render()
                for (replica, role, metric), h in sorted(self._hists.items())}

    # -------------------------------------------------------------- slo.jsonl

    def rows(self, run_id: str, dropped_spans: int = 0) -> list[dict]:
        """Header + per-window + summary rows (the ``check_regression
        --slo`` contract: one run_id, finite quantiles, window coverage)."""
        rows = [{"schema_version": fleetobs.SCHEMA_VERSION,
                 "kind": "slo_header", "run_id": run_id,
                 "window": self.window,
                 "ttft_target_ms": self.ttft_target_ms,
                 "itl_target_ms": self.itl_target_ms}]
        for snap in self.snapshot().values():
            if snap["ttft_count"] + snap["itl_count"] == 0:
                continue
            row = {"kind": "slo_window", "run_id": run_id}
            row.update({k: (round(v, 4) if isinstance(v, float) else v)
                        for k, v in snap.items() if v is not None})
            rows.append(row)
        rows.append({"kind": "slo_summary", "run_id": run_id,
                     "attainment": round(self.overall_attainment(), 4),
                     "windows": len(self._windows),
                     "breaches": self.breaches,
                     "dropped_spans": dropped_spans})
        return rows

    def flush(self, directory: str, run_id: str,
              dropped_spans: int = 0) -> str:
        """Atomically (re)write ``slo.jsonl`` — tmp + ``os.replace``, same
        torn-write discipline as ``fleetobs.write_json_atomic``, so the
        scheduler never reads a half-written window row."""
        os.makedirs(directory, exist_ok=True)
        path = os.path.join(directory, SLO_FILE)
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w") as fh:
            for row in self.rows(run_id, dropped_spans):
                fh.write(json.dumps(row, default=float) + "\n")
        os.replace(tmp, path)
        return path


#: Reader lives in fleetobs (stdlib) so the scheduler/launcher can consume
#: slo.jsonl without importing the serve package; re-exported here for the
#: serving-side callers that already import this module.
read_attainment = fleetobs.read_slo_attainment
