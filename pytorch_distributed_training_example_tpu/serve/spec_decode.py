"""Draft proposers for speculative decoding (serve/engine.py r19).

Speculative decoding (Leviathan et al. 2023, arXiv:2211.17192) splits a
decode step into a cheap GUESS and one batched CHECK: a proposer drafts
up to K candidate tokens per slot, the target model scores all K+1
positions in a single verify forward (the engine's history-attention
program with ``all_logits``), and exact greedy acceptance keeps the
longest draft prefix that matches the target's own argmax plus one bonus
token. Output is bit-identical to the unsped engine — the proposer only
moves WHERE the FLOPs are spent, never what is emitted — so draft
quality is purely a throughput knob: mean accepted length sets the
tokens-per-verify multiplier.

Two proposers, one protocol (``attach``/``warmup``/``begin``/``release``/
``propose``):

- ``NGramProposer`` (default): self-drafting prompt lookup — match the
  most recent n-gram of the context against its own earlier tokens and
  propose the continuation that followed last time. Pure host
  bookkeeping: zero device work, zero params, deterministic. Strong on
  repetitive continuations (code, extraction, templated text), useless
  on novel text — which costs only the draft bookkeeping, since a
  0-length draft falls back to a plain decode step.
- ``DraftModelProposer``: a separate small decode-capable model (params
  restored params-only, same as the target) autoregressively drafts K
  tokens against its OWN paged cache pool. Every program is bucketed and
  AOT-warmed like the target's (compiles counted in the engine's
  ``stats["compiles"]``), and the drafted tokens STAY ON DEVICE — the
  engine scatters them into the verify batch and reads them back through
  the verify fetch's echoed row, keeping the one-host-sync-per-step
  contract.

The draft cache needs no rollback machinery: each proposal round
re-appends the last two real context tokens (positions L-1, L) through
the catch-up program before drafting, so positions a rejected draft left
stale are overwritten sequentially before any later query reads them —
the same masks-on-position argument the target cache relies on.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

from pytorch_distributed_training_example_tpu.serve import kv_cache
from pytorch_distributed_training_example_tpu.serve.kv_cache import (
    CacheSpec, PagePool, pages_for_tokens)


class NGramProposer:
    """Self-drafting prompt-lookup: propose the continuation that followed
    the most recent earlier occurrence of the context's trailing n-gram
    (longest n first). Host-only and deterministic."""

    def __init__(self, draft_len: int = 4, max_ngram: int = 3,
                 min_ngram: int = 1):
        if min_ngram < 1 or max_ngram < min_ngram:
            raise ValueError(
                f"need 1 <= min_ngram <= max_ngram, got "
                f"[{min_ngram}, {max_ngram}]")
        self.draft_len = int(draft_len)
        self.max_ngram = int(max_ngram)
        self.min_ngram = int(min_ngram)

    def attach(self, engine) -> None:
        pass

    def warmup(self, engine) -> int:
        return 0

    def begin(self, engine, slot: int, req) -> None:
        pass

    def release(self, slot: int) -> None:
        pass

    def propose(self, engine, active: list[int],
                budgets: dict[int, int]) -> tuple[dict[int, int], dict]:
        rt = engine.reqtrace
        t0 = engine._clock() if rt is not None else 0.0
        counts: dict[int, int] = {}
        values: dict[int, list[int]] = {}
        for i in active:
            req = engine.slots[i]
            d = self._match(req.prompt + req.generated,
                            min(budgets[i], self.draft_len))
            counts[i] = len(d)
            values[i] = d
        if rt is not None:
            rt.span("draft_propose", t0, engine._clock(), role=engine.role,
                    proposer="ngram", slots=len(active),
                    drafted=sum(counts.values()))
        return counts, values

    def _match(self, ctx: list[int], k: int) -> list[int]:
        if k <= 0:
            return []
        for n in range(self.max_ngram, self.min_ngram - 1, -1):
            if len(ctx) <= n:
                continue
            tail = ctx[-n:]
            # Most recent earlier occurrence whose continuation is
            # non-empty (s + n <= len(ctx) - 1).
            for s in range(len(ctx) - n - 1, -1, -1):
                if ctx[s:s + n] == tail:
                    return ctx[s + n:s + n + k]
        return []


class DraftModelProposer:
    """Small-model drafting against a private paged cache.

    Per proposal round and batch bucket: one width-2 catch-up forward
    (re-appends the last two accepted context tokens at positions
    [L-1, L] and returns the draft's argmax after L — re-appending an
    already-cached position rewrites the same K/V, so no separate
    catch-up state is tracked), then K-1 single-token decode steps, each
    feeding the previous argmax back WITHOUT leaving the device. The
    drafted [B, K] block is handed to the engine as a device array.

    The draft pool mirrors the target's geometry (same page size / table
    width so position arithmetic is shared) but is wholly private: no
    prefix cache, no COW, no handoffs. ``begin`` prefills the prompt
    through bucketed windows when a slot is (re)admitted; ``release``
    frees the slot's pages. Pool sizing defaults to the target's
    ``num_pages`` plus one page per slot of draft overshoot.
    """

    def __init__(self, module, params, *, num_pages: int | None = None,
                 draft_len: int = 4):
        self.module = module
        self.params = params
        self.draft_len = int(draft_len)
        self._num_pages = num_pages
        self.engine = None
        self._compiled: dict[tuple, Any] = {}

    # ------------------------------------------------------------ lifecycle

    def attach(self, engine) -> None:
        self.engine = engine
        ps = engine.spec.page_size
        num_pages = self._num_pages or (engine.spec.num_pages
                                        + len(engine.slots))
        self.spec = CacheSpec(
            num_layers=self.module.num_layers, num_pages=num_pages,
            page_size=ps, num_kv_heads=self.module.num_kv_heads,
            head_dim=self.module.head_dim, dtype=self.module.dtype)
        self.table_width = engine.table_width
        self.pool = PagePool(num_pages)
        self.cache = kv_cache.init_model_cache(
            self.module, self.spec, self.table_width, engine.attn_impl)
        max_b = len(engine.slots)
        self._tables = np.zeros((max_b, self.table_width), np.int32)
        self._pages: list[list[int]] = [[] for _ in range(max_b)]

    def warmup(self, engine) -> int:
        for b in engine.decode_buckets:
            self._get_step("draft_decode", b, 1)
            self._get_step("draft_catchup", b, 2)
        for sp in engine.prompt_buckets:
            self._get_step("draft_prefill", 1, sp)
            self._get_step("draft_prefill_hist", 1, sp)
        return len(self._compiled)

    def begin(self, engine, slot: int, req) -> None:
        """(Re)admission: prefill the PROMPT into the draft cache — the
        generated tokens stream in through later catch-ups."""
        self.release(slot)
        plen = len(req.prompt)
        need = pages_for_tokens(plen + self.draft_len + 1,
                                self.spec.page_size)
        self._grow(slot, need)
        cap = self._window_cap(engine)
        pos = 0
        while pos < plen:
            n = min(plen - pos, cap)
            self._prefill_window(engine, slot, req, pos, n)
            pos += n

    def release(self, slot: int) -> None:
        self.pool.free(f"slot-{slot}")
        self._pages[slot] = []
        self._tables[slot] = 0

    # ------------------------------------------------------------- programs

    def _decode_fn(self, history: bool):
        spec = self.spec

        def run(params, cache, tokens, positions, page_table, last_index):
            logits, vs = self.module.apply(
                {"params": params, "cache": cache}, tokens, train=False,
                decode_ctx=dict(positions=positions, page_table=page_table,
                                cache_spec=(spec.num_pages, spec.page_size),
                                last_index=last_index, history=history,
                                attn_impl=self.engine.attn_impl),
                mutable=["cache"])
            return (jnp.argmax(logits, axis=-1).astype(jnp.int32),
                    vs["cache"])

        return run

    def _get_step(self, kind: str, batch: int, seq: int):
        """AOT-compiled draft program; compiles count toward the ENGINE's
        ``stats["compiles"]`` so the no-steady-state-recompile assertion
        covers the draft model too."""
        key = (kind, batch, seq)
        if key not in self._compiled:
            hist = kind in ("draft_catchup", "draft_prefill_hist")
            fn = jax.jit(self._decode_fn(history=hist), donate_argnums=1)
            abstract = jax.tree.map(
                lambda x: jax.ShapeDtypeStruct(jnp.shape(x),
                                               jnp.asarray(x).dtype),
                (self.params, self.cache))
            args = abstract + (
                jax.ShapeDtypeStruct((batch, seq), jnp.int32),
                jax.ShapeDtypeStruct((batch, seq), jnp.int32),
                jax.ShapeDtypeStruct((batch, self.table_width), jnp.int32),
                jax.ShapeDtypeStruct((batch,), jnp.int32),
            )
            self._compiled[key] = fn.lower(*args).compile()
            self.engine.stats["compiles"] += 1
        return self._compiled[key]

    # ------------------------------------------------------------- internal

    def _window_cap(self, engine) -> int:
        return engine.prompt_buckets[-1]

    def _grow(self, slot: int, need: int) -> None:
        have = len(self._pages[slot])
        if need <= have:
            return
        if not self.pool.can_alloc(need - have):
            raise MemoryError(
                f"draft page pool exhausted (want {need - have}, have "
                f"{self.pool.num_free}): size DraftModelProposer num_pages "
                "at least like the target pool")
        pages = self.pool.alloc(f"slot-{slot}", need - have)
        self._pages[slot].extend(pages)
        self._tables[slot, have:have + len(pages)] = pages

    def _prefill_window(self, engine, slot: int, req, pos: int,
                        n: int) -> None:
        """One draft prefill window; the output argmax is DISCARDED (the
        first proposal round re-derives it through catch-up), so prefill
        costs zero host syncs."""
        sp = _bucket(n, engine.prompt_buckets)
        kind = "draft_prefill_hist" if pos > 0 else "draft_prefill"
        step = self._get_step(kind, 1, sp)
        tokens = np.zeros((1, sp), np.int32)
        tokens[0, :n] = req.prompt[pos:pos + n]
        positions = np.minimum(pos + np.arange(sp, dtype=np.int32),
                               self.table_width * self.spec.page_size - 1)
        _, self.cache = step(self.params, self.cache, jnp.asarray(tokens),
                             jnp.asarray(positions[None]),
                             jnp.asarray(self._tables[slot:slot + 1]),
                             np.asarray([n - 1], np.int32))

    def propose(self, engine, active: list[int],
                budgets: dict[int, int]) -> tuple[dict[int, int], Any]:
        rt = engine.reqtrace
        t0 = engine._clock() if rt is not None else 0.0
        counts = {i: min(int(budgets[i]), self.draft_len) for i in active}
        k_max = max(counts.values(), default=0)
        if k_max == 0:
            return counts, {}
        ps = self.spec.page_size
        cap = self.table_width * ps - 1
        bucket = _bucket(len(active), engine.decode_buckets)
        # Draft writes land at positions [L-1 .. L+k_max-1]; grow each
        # slot's private pages to cover them (budget capping keeps real
        # positions inside the table; padded rows clip onto scratch).
        for i in active:
            self._grow(i, pages_for_tokens(
                int(engine._lens[i]) + k_max, ps))
        tokens = np.zeros((bucket, 2), np.int32)
        positions = np.zeros((bucket, 2), np.int32)
        table = np.zeros((bucket, self.table_width), np.int32)
        last = np.zeros(bucket, np.int32)
        lens = np.zeros(bucket, np.int32)
        for j, i in enumerate(active):
            req = engine.slots[i]
            ctx = req.prompt + req.generated
            L = int(engine._lens[i])         # == len(ctx) - 1, >= 1
            tokens[j] = (ctx[L - 1], ctx[L])
            positions[j] = np.minimum((L - 1, L), cap)
            table[j] = self._tables[i]
            last[j] = 1
            lens[j] = L
        table_dev = jnp.asarray(table)
        step = self._get_step("draft_catchup", bucket, 2)
        cur, self.cache = step(self.params, self.cache, jnp.asarray(tokens),
                               jnp.asarray(positions), table_dev,
                               jnp.asarray(last))
        cur = cur[:, None]                   # [bucket, 1] device, = d1
        drafts = [cur]
        dstep = self._get_step("draft_decode", bucket, 1)
        for m in range(1, k_max):
            pos_m = np.minimum(lens + m, cap)[:, None]
            cur, self.cache = dstep(self.params, self.cache, cur,
                                    jnp.asarray(pos_m), table_dev,
                                    np.zeros(bucket, np.int32))
            cur = cur[:, None]
            drafts.append(cur)
        values = jnp.concatenate(drafts, axis=1)[:len(active)]
        if rt is not None:
            # Drafts stay on device — this span times the HOST-side
            # dispatch of the draft chain, not a fetch (no sync added).
            rt.span("draft_propose", t0, engine._clock(), role=engine.role,
                    proposer="draft_model", slots=len(active),
                    drafted=sum(counts.values()))
        return counts, values


def _bucket(n: int, buckets: tuple[int, ...]) -> int:
    for b in buckets:
        if n <= b:
            return b
    raise ValueError(f"{n} exceeds largest bucket {buckets[-1]}")
