"""Prefix cache: refcounted pool sharing, COW, tree index, engine identity.

The load-bearing claim stays TOKEN IDENTITY: a cache hit splices already-
computed KV pages into a new request's table, and the request must still
produce the exact greedy continuation a cold engine (or the full training
forward) produces — including when a shared page is copy-on-written at the
divergence point. The pool's refcount invariants are what make the sharing
sound, so they are tested loudly and first.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from pytorch_distributed_training_example_tpu.models import registry
from pytorch_distributed_training_example_tpu.serve import (
    engine as engine_lib, kv_cache)
from pytorch_distributed_training_example_tpu.serve.kv_cache import (
    CacheSpec, PagePool, RESERVED_PAGES)
from pytorch_distributed_training_example_tpu.serve.prefix_cache import (
    CACHE_OWNER, PrefixCache)


# ---------------------------------------------------------------------------
# PagePool refcount invariants
# ---------------------------------------------------------------------------


def test_pool_share_and_drop_refcounts():
    pool = PagePool(8)
    (p,) = pool.alloc("a", 1)
    assert pool.refcount(p) == 1
    pool.share("b", [p])
    pool.share("c", [p])
    assert pool.refcount(p) == 3
    pool.free("a")
    assert pool.refcount(p) == 2 and p not in pool._free
    pool.drop("b", p)
    assert pool.refcount(p) == 1
    pool.free("c")
    assert pool.refcount(p) == 0 and pool.num_free == 7


def test_pool_double_free_raises():
    pool = PagePool(8)
    (p,) = pool.alloc("a", 1)
    pool.drop("a", p)
    with pytest.raises(ValueError, match="double free"):
        pool.drop("a", p)
    # free() stays idempotent (retire + evict racing is a no-op)...
    pool.free("a")
    # ...but a stale owner re-releasing a freed page would underflow, and
    # share() of a free page is refused before it can corrupt the list.
    with pytest.raises(ValueError, match="free"):
        pool.share("b", [p])


def test_pool_refcount_never_negative():
    pool = PagePool(8)
    (p,) = pool.alloc("a", 1)
    pool.share("b", [p])
    pool.free("a")
    pool.free("b")
    with pytest.raises(ValueError, match="underflow"):
        pool._unref(p)
    assert pool.refcount(p) == 0


def test_pool_scratch_page_is_never_shared_or_allocated():
    pool = PagePool(4)
    pages = pool.alloc("a", 3)  # drains the whole pool
    assert 0 not in pages
    with pytest.raises(ValueError, match="reserved"):
        pool.share("b", [0])


def test_pool_alloc_after_free_reuse_is_deterministic():
    """LIFO free list: two same-seed runs that free and re-allocate in the
    same order get bit-identical page tables."""
    def trace():
        pool = PagePool(16)
        a = pool.alloc("a", 3)
        b = pool.alloc("b", 4)
        pool.share("c", b[:2])
        pool.free("a")
        pool.free("b")          # shared pages survive under "c"
        c = pool.alloc("d", 5)
        pool.free("c")
        return a, b, c, pool.alloc("e", 2)

    assert trace() == trace()


# ---------------------------------------------------------------------------
# COW device op: mutating one stream's copy leaves the original bytes intact
# ---------------------------------------------------------------------------


def test_copy_page_isolates_writer_from_sharer():
    spec = CacheSpec(num_layers=2, num_pages=8, page_size=4, num_kv_heads=2,
                     head_dim=4)
    cache = kv_cache.init_cache(spec)
    rng = np.random.default_rng(0)
    # Request A prefills page 3 with real KV.
    table_a = jnp.asarray([[3]], jnp.int32)
    kv = {}
    for pos in range(4):
        positions = jnp.full((1, 1), pos, jnp.int32)
        for layer in cache.values():
            for name in ("k_pages", "v_pages"):
                new = rng.standard_normal((1, 1, 2, 4)).astype(np.float32)
                kv.setdefault(id(layer["attn"]), {}).setdefault(
                    name, []).append(new)
                layer["attn"][name] = kv_cache.append_pages(
                    layer["attn"][name], jnp.asarray(new), table_a, positions)
    before = jax.tree.map(lambda x: np.asarray(x[3]).copy(), cache)
    # Request B shares page 3, then copy-on-writes it into page 5 and
    # scribbles over its copy.
    cache = kv_cache.copy_page(cache, jnp.int32(3), jnp.int32(5))
    after_copy = jax.tree.map(lambda x: np.asarray(x[5]), cache)
    jax.tree.map(np.testing.assert_array_equal, after_copy, before)
    table_b = jnp.asarray([[5]], jnp.int32)
    for pos in range(2, 4):  # divergent rewrite of the tail slots
        positions = jnp.full((1, 1), pos, jnp.int32)
        garbage = jnp.full((1, 1, 2, 4), 99.0)
        for layer in cache.values():
            for name in ("k_pages", "v_pages"):
                layer["attn"][name] = kv_cache.append_pages(
                    layer["attn"][name], garbage, table_b, positions)
    after = jax.tree.map(lambda x: np.asarray(x[3]), cache)
    jax.tree.map(np.testing.assert_array_equal, after, before)


def test_extract_insert_round_trip():
    spec = CacheSpec(num_layers=1, num_pages=8, page_size=4, num_kv_heads=2,
                     head_dim=4)
    rng = np.random.default_rng(3)
    src = {"block_0": {"attn": {
        "k_pages": jnp.asarray(rng.standard_normal(spec.layer_shape()),
                               jnp.float32),
        "v_pages": jnp.asarray(rng.standard_normal(spec.layer_shape()),
                               jnp.float32)}}}
    dst = kv_cache.init_cache(spec)
    # Width-3 handoff of 2 real pages; the pad row targets scratch page 0.
    ids_out = jnp.asarray([6, 2, 0], jnp.int32)
    block = kv_cache.extract_pages(src, ids_out)
    ids_in = jnp.asarray([1, 5, 0], jnp.int32)
    dst = kv_cache.insert_pages(dst, block, ids_in)
    for name in ("k_pages", "v_pages"):
        s = np.asarray(src["block_0"]["attn"][name])
        d = np.asarray(dst["block_0"]["attn"][name])
        np.testing.assert_array_equal(d[1], s[6])
        np.testing.assert_array_equal(d[5], s[2])


# ---------------------------------------------------------------------------
# PrefixCache tree: match / insert / evict
# ---------------------------------------------------------------------------


def _cache(num_pages=32, ps=4):
    pool = PagePool(num_pages)
    return PrefixCache(pool, ps), pool


def test_tree_match_full_and_partial_chunks():
    cache, pool = _cache()
    prompt = list(range(100, 110))  # 2 full pages + 2-token tail at ps=4
    pages = pool.alloc("seed", 3)
    assert cache.insert(prompt, pages) == 3
    assert cache.cached_pages == 3
    pool.free("seed")  # cache pins survive the publisher retiring
    assert all(pool.refcount(p) == 1 for p in pages)

    # Exact re-match, clamped so the last prompt token stays prefillable.
    m = cache.match(prompt, max_tokens=len(prompt) - 1)
    assert m.pages == pages and m.tokens == 9
    # Divergent tail: full chunks match, partial matches its common prefix.
    m2 = cache.match(prompt[:9] + [999, 999], max_tokens=10)
    assert m2.pages == pages and m2.tokens == 9
    # Divergence inside the first chunk: no usable full node, no partial.
    m3 = cache.match([999] + prompt[1:], max_tokens=9)
    assert m3.pages == [] and m3.tokens == 0
    # max_tokens <= 0 (single-token prompt) can never hit.
    assert cache.match(prompt, max_tokens=0).pages == []


def test_tree_insert_dedupes_shared_chunks():
    cache, pool = _cache()
    a = pool.alloc("a", 3)
    cache.insert([1, 2, 3, 4, 5, 6, 7, 8, 9], a)
    b = pool.alloc("b", 3)
    # Same first two chunks, different tail: only the tail node is new and
    # b's duplicate pages stay private (un-pinned by the cache).
    assert cache.insert([1, 2, 3, 4, 5, 6, 7, 8, 42], b) == 1
    assert cache.cached_pages == 4
    assert pool.refcount(b[0]) == 1 and pool.refcount(b[2]) == 2


def test_tree_evicts_lru_unreferenced_leaves_only():
    cache, pool = _cache()
    a = pool.alloc("a", 2)      # chunk X + tail (touched first -> oldest)
    cache.insert([1, 2, 3, 4, 5, 6], a)
    b = pool.alloc("b", 2)      # chunk Y + tail (younger)
    cache.insert([9, 9, 9, 9, 7, 7], b)
    pool.free("a")
    pool.free("b")
    m = cache.match([1, 2, 3, 4, 5, 6], max_tokens=5)
    cache.acquire(m, "reader")  # pins a's nodes

    assert cache.evict(10) == 2  # only b's tail leaf + then b's chunk go
    assert cache.cached_pages == 2
    assert pool.refcount(b[0]) == 0 and pool.refcount(a[0]) > 0
    # Release the pin: a's subtree becomes evictable, tail leaf first.
    cache.release(m.nodes)
    pool.free("reader")
    assert cache.evict(10) == 2
    assert cache.cached_pages == 0 and pool.num_free == pool.num_pages - 1
    with pytest.raises(ValueError, match="underflow"):
        cache.release(m.nodes)


def test_tree_eviction_order_is_lru():
    cache, pool = _cache()
    old = pool.alloc("old", 1)
    cache.insert([1, 2, 3, 4], old)
    young = pool.alloc("young", 1)
    cache.insert([5, 6, 7, 8], young)
    pool.free("old")
    pool.free("young")
    # Touch the old node via a match+acquire/release cycle -> now youngest.
    m = cache.match([1, 2, 3, 4], max_tokens=3)
    cache.acquire(m, "toucher")
    cache.release(m.nodes)
    pool.free("toucher")
    cache.evict(1)
    assert cache.match([5, 6, 7, 8], max_tokens=3).pages == []
    assert cache.match([1, 2, 3, 4], max_tokens=3).pages == old


# ---------------------------------------------------------------------------
# engine: cached == uncached greedy tokens, COW divergence, LRU pressure
# ---------------------------------------------------------------------------


def _tiny(seq_len=128):
    bundle = registry.create_model("llama_tiny", seq_len=seq_len,
                                   dtype=jnp.float32, param_dtype=jnp.float32)
    module = bundle.module
    params = module.init(jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32),
                         train=False)["params"]
    return module, params


def _run_staggered(eng, reqs):
    """Submit sequentially, draining between submissions, so later requests
    actually see the pages earlier ones published."""
    done = []
    for r in reqs:
        eng.submit(r)
        done += eng.run()
    return {r.request_id: r for r in done}


def test_cached_tokens_identical_incl_cow(devices):
    module, params = _tiny()
    spec = engine_lib.spec_for_module(module, num_pages=64, page_size=8)

    rng = np.random.default_rng(21)
    shared = rng.integers(1, 512, 16).tolist()  # two full pages
    reqs = []
    # Page-boundary prompt lengths: 16 (exact), 17 (1-token tail), 24
    # (boundary again), plus a mid-page divergence that forces COW of a
    # shared partial page.
    for i, tail_len in enumerate([0, 1, 8, 3]):
        tail = rng.integers(1, 512, tail_len).tolist()
        reqs.append(engine_lib.Request(
            request_id=f"c{i}", prompt=shared + tail, max_new_tokens=6))
    reqs.append(engine_lib.Request(  # exact duplicate of c0: full-prompt hit
        request_id="dup", prompt=list(reqs[0].prompt), max_new_tokens=6))

    cold = engine_lib.ContinuousBatchingEngine(
        module, params, spec, decode_buckets=(1, 2), prompt_buckets=(16, 32),
        max_model_len=48)
    ref = {r.request_id: r.generated
           for r in _run_staggered(
               cold, [engine_lib.Request(r.request_id, list(r.prompt),
                                         r.max_new_tokens)
                      for r in reqs]).values()}

    warm = engine_lib.ContinuousBatchingEngine(
        module, params, spec, decode_buckets=(1, 2), prompt_buckets=(16, 32),
        max_model_len=48, prefix_cache=True)
    n = warm.warmup()
    done = _run_staggered(warm, reqs)
    assert len(done) == 5
    for rid, toks in ref.items():
        assert done[rid].generated == toks, rid
    assert warm.stats["cached_tokens"] > 0
    assert warm.stats["cow_copies"] > 0  # the divergent tails exercised COW
    assert warm.prefix_hit_rate() > 0.3
    assert warm.stats["compiles"] == n  # splicing never minted a new shape


def test_cache_eviction_under_pressure_keeps_tokens(devices):
    module, params = _tiny()
    # 11 usable pages of 8 tokens; each 17-token prompt takes 3 pages and
    # the cache pins them after retire -> the fourth admission must evict.
    spec = engine_lib.spec_for_module(module, num_pages=12, page_size=8)
    eng = engine_lib.ContinuousBatchingEngine(
        module, params, spec, decode_buckets=(1,), prompt_buckets=(32,),
        max_model_len=32, prefix_cache=True)
    rng = np.random.default_rng(5)
    reqs = [engine_lib.Request(request_id=f"p{i}",
                               prompt=rng.integers(1, 512, 17).tolist(),
                               max_new_tokens=4)
            for i in range(4)]
    done = _run_staggered(eng, reqs)
    assert len(done) == 4
    assert eng.prefix_cache.stats["evicted_pages"] > 0
    for r in reqs:
        # Every request decoded correctly despite cache pages being
        # reclaimed out from under the tree.
        logits = module.apply({"params": params},
                              jnp.asarray([list(r.prompt)], jnp.int32),
                              train=False)
        first = int(jnp.argmax(logits[0, len(r.prompt) - 1]))
        assert done[r.request_id].generated[0] == first, r.request_id
