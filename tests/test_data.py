import numpy as np

from pytorch_distributed_training_example_tpu.data import prefetch
from pytorch_distributed_training_example_tpu.data.datasets import (
    SyntheticImageDataset, SyntheticTokenDataset, build_dataset)
from pytorch_distributed_training_example_tpu.data.loader import DataLoader
from pytorch_distributed_training_example_tpu.data.sampler import ShardedSampler
from pytorch_distributed_training_example_tpu.core import mesh as mesh_lib


def test_loader_shapes_and_count():
    ds = SyntheticImageDataset(100, 16, 10)
    dl = DataLoader(ds, batch_size=8, drop_last=True)
    batches = list(dl)
    assert len(batches) == len(dl) == 12
    assert batches[0]["image"].shape == (8, 16, 16, 3)
    assert batches[0]["label"].shape == (8,)


def test_threaded_loader_matches_serial():
    ds = SyntheticImageDataset(64, 8, 10)
    sampler = ShardedSampler(64, 2, 1, shuffle=True, seed=1)
    serial = list(DataLoader(ds, 4, sampler, num_workers=0))
    threaded = list(DataLoader(ds, 4, sampler, num_workers=3))
    assert len(serial) == len(threaded)
    for a, b in zip(serial, threaded):
        np.testing.assert_array_equal(a["image"], b["image"])
        np.testing.assert_array_equal(a["label"], b["label"])


def test_token_dataset_targets_shifted():
    ds = SyntheticTokenDataset(4, seq_len=16, vocab_size=100)
    s = ds[0]
    assert s["tokens"].shape == (16,)
    np.testing.assert_array_equal(s["tokens"][1:], s["targets"][:-1])


def test_device_prefetch_shards_batch(devices):
    mesh = mesh_lib.build_mesh({"data": 8})
    ds = SyntheticImageDataset(64, 8, 10)
    dl = DataLoader(ds, batch_size=16)
    out = list(prefetch.device_prefetch(dl, mesh_lib.batch_sharding(mesh)))
    assert len(out) == 4
    x = out[0]["image"]
    assert x.shape == (16, 8, 8, 3)
    assert len(x.addressable_shards) == 8


def test_build_dataset_synthetic_fallback():
    ds = build_dataset("cifar10", None, train=True)
    assert ds[0]["image"].shape == (32, 32, 3)
    lm = build_dataset("lm", None, train=True, seq_len=64)
    assert lm[0]["tokens"].shape == (64,)


def test_pad_batch_mask():
    b = {"image": np.ones((5, 4, 4, 3), np.float32), "label": np.arange(5)}
    out = prefetch.pad_batch(b, 8)
    assert out["image"].shape == (8, 4, 4, 3)
    np.testing.assert_array_equal(out["mask"], [1, 1, 1, 1, 1, 0, 0, 0])
    full = prefetch.pad_batch({"label": np.arange(8)}, 8)
    np.testing.assert_array_equal(full["mask"], np.ones(8))
