import numpy as np

from pytorch_distributed_training_example_tpu.data import prefetch
from pytorch_distributed_training_example_tpu.data.datasets import (
    SyntheticImageDataset, SyntheticTokenDataset, build_dataset)
from pytorch_distributed_training_example_tpu.data.loader import DataLoader
from pytorch_distributed_training_example_tpu.data.sampler import ShardedSampler
from pytorch_distributed_training_example_tpu.core import mesh as mesh_lib


def test_loader_shapes_and_count():
    ds = SyntheticImageDataset(100, 16, 10)
    dl = DataLoader(ds, batch_size=8, drop_last=True)
    batches = list(dl)
    assert len(batches) == len(dl) == 12
    assert batches[0]["image"].shape == (8, 16, 16, 3)
    assert batches[0]["label"].shape == (8,)


def test_threaded_loader_matches_serial():
    ds = SyntheticImageDataset(64, 8, 10)
    sampler = ShardedSampler(64, 2, 1, shuffle=True, seed=1)
    serial = list(DataLoader(ds, 4, sampler, num_workers=0))
    threaded = list(DataLoader(ds, 4, sampler, num_workers=3))
    assert len(serial) == len(threaded)
    for a, b in zip(serial, threaded):
        np.testing.assert_array_equal(a["image"], b["image"])
        np.testing.assert_array_equal(a["label"], b["label"])


def test_token_dataset_targets_shifted():
    ds = SyntheticTokenDataset(4, seq_len=16, vocab_size=100)
    s = ds[0]
    assert s["tokens"].shape == (16,)
    np.testing.assert_array_equal(s["tokens"][1:], s["targets"][:-1])


def test_device_prefetch_shards_batch(devices):
    mesh = mesh_lib.build_mesh({"data": 8})
    ds = SyntheticImageDataset(64, 8, 10)
    dl = DataLoader(ds, batch_size=16)
    out = list(prefetch.device_prefetch(dl, mesh_lib.batch_sharding(mesh)))
    assert len(out) == 4
    x = out[0]["image"]
    assert x.shape == (16, 8, 8, 3)
    assert len(x.addressable_shards) == 8


def test_build_dataset_synthetic_fallback():
    ds = build_dataset("cifar10", None, train=True)
    assert ds[0]["image"].shape == (32, 32, 3)
    lm = build_dataset("lm", None, train=True, seq_len=64)
    assert lm[0]["tokens"].shape == (64,)


def test_loader_start_batch_skips_exact_prefix():
    """Mid-epoch resume contract: start_batch=k yields exactly the suffix
    of the epoch's deterministic batch stream, bit-for-bit, in both the
    serial and threaded paths."""
    ds = SyntheticImageDataset(96, 8, 10)
    sampler = ShardedSampler(96, 1, 0, shuffle=True, seed=3)
    full = list(DataLoader(ds, 8, sampler, num_workers=0))
    for workers in (0, 2):
        dl = DataLoader(ds, 8, ShardedSampler(96, 1, 0, shuffle=True, seed=3),
                        num_workers=workers)
        dl.start_batch = 5
        tail = list(dl)
        assert len(tail) == len(full) - 5
        for a, b in zip(full[5:], tail):
            np.testing.assert_array_equal(a["image"], b["image"])
            np.testing.assert_array_equal(a["label"], b["label"])


def test_loader_index_log_records_absolute_batches(tmp_path, monkeypatch):
    """PDTX_INDEX_LOG writes one line per yielded batch with the ABSOLUTE
    batch number, so resumed runs can be compared against the full epoch
    stream for the no-replay/no-skip assertion."""
    import json

    from pytorch_distributed_training_example_tpu.data import loader as loader_lib

    log = tmp_path / "idx.jsonl"
    monkeypatch.setenv(loader_lib.INDEX_LOG_ENV, str(log))
    ds = SyntheticImageDataset(64, 8, 10)
    sampler = ShardedSampler(64, 1, 0, shuffle=True, seed=7)
    dl = DataLoader(ds, 8, sampler)
    dl.set_epoch(2)
    dl.start_batch = 3
    list(dl)
    rows = [json.loads(l) for l in log.read_text().splitlines()]
    assert [r["batch"] for r in rows] == [3, 4, 5, 6, 7]
    assert all(r["epoch"] == 2 for r in rows)
    want = sampler.local_indices()[3 * 8:]
    got = [i for r in rows for i in r["indices"]]
    np.testing.assert_array_equal(got, want)


def test_pad_batch_mask():
    b = {"image": np.ones((5, 4, 4, 3), np.float32), "label": np.arange(5)}
    out = prefetch.pad_batch(b, 8)
    assert out["image"].shape == (8, 4, 4, 3)
    np.testing.assert_array_equal(out["mask"], [1, 1, 1, 1, 1, 0, 0, 0])
    full = prefetch.pad_batch({"label": np.arange(8)}, 8)
    np.testing.assert_array_equal(full["mask"], np.ones(8))


def test_dp_shard_coordinate_mapping():
    """Loader sharding keys on the dp COORDINATE, not the process index:
    hosts holding only seq/pp/ep/tp shards of one replica read the same
    sample stream (ISSUE 20 satellite: nproc % dp == 0 generalization)."""
    from pytorch_distributed_training_example_tpu.data import loader as loader_lib

    # Plain multi-host data parallel: each host its own slice.
    assert loader_lib.dp_shard(2, 4, 1) == (2, 1)
    assert loader_lib.dp_shard(4, 4, 3) == (4, 3)
    # dp1 x seq2 gang: both ranks -> coordinate 0, identical rows.
    assert loader_lib.dp_shard(2, 1, 0) == (1, 0)
    assert loader_lib.dp_shard(2, 1, 1) == (1, 0)
    # dp2 x (seq or pp)2 over 4 processes: contiguous pairs share a stream.
    assert [loader_lib.dp_shard(4, 2, p)[1] for p in range(4)] == [0, 0, 1, 1]
    # Indivisible gangs fail loudly.
    import pytest
    with pytest.raises(ValueError, match="multiple of"):
        loader_lib.dp_shard(3, 2, 0)
