"""Sort-based MoE dispatch vs the gather and einsum oracles.

``dispatch_impl="sort"`` (argsort by expert id + segment offsets,
MegaBlocks-style) replaces the one-hot/scatter formulations on perf grounds
only, so it must reproduce them EXACTLY: same routing decisions, same
capacity-overflow drops (priority: k=0 choices before k=1, earlier tokens
first), same outputs and gradients. The EP suite at the bottom also guards
the jax 0.4.x SPMD gather miscompile worked around in parallel/moe.py
(_combine/_sort_route pin gather operands replicated — without that, the
partitioner silently produces wrong VALUES for gathers with sharded
operands).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pytorch_distributed_training_example_tpu.core import mesh as mesh_lib
from pytorch_distributed_training_example_tpu.parallel import moe as moe_lib
from pytorch_distributed_training_example_tpu.parallel import sharding as sharding_lib

D = 16


def _blocks(E, k, cf, **kw):
    def mk(impl):
        return moe_lib.MoEBlock(num_experts=E, ffn_dim=32, top_k=k,
                                capacity_factor=cf, dispatch_impl=impl, **kw)
    return mk("sort"), mk("gather"), mk("einsum")


def _x(seed=7, b=2, t=32):
    return jnp.asarray(np.random.RandomState(seed).randn(b, t, D), jnp.float32)


@pytest.mark.parametrize("E,k,cf", [
    (4, 2, 2.0),    # no overflow: every routed token fits
    (4, 2, 0.5),    # heavy overflow: the drop priority is exercised
    (4, 1, 1.0),    # top-1 (Switch) regime
    (8, 2, 0.25),   # many experts, tiny capacity
])
def test_sort_matches_gather_and_einsum(E, k, cf):
    """Forward + param/input grads agree across all three formulations."""
    s, g, e = _blocks(E, k, cf)
    x = _x()
    variables = {"params": g.init(jax.random.PRNGKey(0), x)["params"]}

    outs, grads = {}, {}
    for name, block in (("sort", s), ("gather", g), ("einsum", e)):
        outs[name] = block.apply(variables, x)

        def loss(p, xx, block=block):
            return jnp.sum(block.apply({"params": p}, xx) ** 2)

        grads[name] = jax.grad(loss, argnums=(0, 1))(variables["params"], x)
    for other in ("gather", "einsum"):
        np.testing.assert_allclose(np.asarray(outs["sort"]),
                                   np.asarray(outs[other]),
                                   rtol=1e-5, atol=1e-6)
        for a, b in zip(jax.tree.leaves(grads["sort"]),
                        jax.tree.leaves(grads[other])):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-4, atol=1e-5)


def test_sort_overflow_drops_same_tokens():
    """Under overflow the sort path drops the SAME tokens as the legacy
    paths (zero output rows match positionally), and some are dropped."""
    s, g, _ = _blocks(E=2, k=1, cf=0.25)
    x = _x(seed=0, b=2, t=16)
    variables = {"params": g.init(jax.random.PRNGKey(0), x)["params"]}
    zero_s = np.abs(np.asarray(s.apply(variables, x))).max(-1) == 0.0
    zero_g = np.abs(np.asarray(g.apply(variables, x))).max(-1) == 0.0
    assert zero_s.sum() > 0
    np.testing.assert_array_equal(zero_s, zero_g)


def test_bf16_combine_parity():
    """combine_dtype=bf16 changes only the combine einsum's precision: the
    output must track the fp32-combine result to bf16 resolution."""
    ref = moe_lib.MoEBlock(num_experts=4, ffn_dim=32, top_k=2,
                           capacity_factor=2.0, dispatch_impl="sort")
    b16 = moe_lib.MoEBlock(num_experts=4, ffn_dim=32, top_k=2,
                           capacity_factor=2.0, dispatch_impl="sort",
                           combine_dtype=jnp.bfloat16)
    x = _x(seed=11)
    variables = {"params": ref.init(jax.random.PRNGKey(0), x)["params"]}
    a = np.asarray(ref.apply(variables, x))
    b = np.asarray(b16.apply(variables, x))
    # bf16 eps = 2^-8; the combine is a k=2 weighted sum, so a few ULP
    np.testing.assert_allclose(a, b, rtol=3e-2, atol=3e-2)


def test_sort_expert_parallel_matches_replicated(devices):
    """Sort dispatch under an expert×data mesh == unsharded oracle, forward
    AND grads. This is the regression guard for the jax 0.4.x sharded-
    operand gather miscompile (see module docstring)."""
    block = moe_lib.MoEBlock(num_experts=4, ffn_dim=32, top_k=2,
                             capacity_factor=2.0, dispatch_impl="sort")
    x = _x(seed=0, b=4, t=8)
    variables = {"params": block.init(jax.random.PRNGKey(0), x)["params"]}
    ref = block.apply(variables, x)

    def loss(p, xx):
        return jnp.sum(block.apply({"params": p}, xx) ** 2)

    g_ref = jax.grad(loss)(variables["params"], x)

    mesh = mesh_lib.build_mesh({"expert": 4, "data": 2})
    shardings = sharding_lib.make_shardings(variables["params"], mesh,
                                            moe_lib.EP_RULES)
    params_sharded = jax.tree.map(jax.device_put, variables["params"],
                                  shardings)
    assert "expert" in str(params_sharded["experts"]["w_up"].sharding.spec)
    with mesh_lib.use_mesh(mesh):
        out = jax.jit(lambda p, xx: block.apply({"params": p}, xx))(
            params_sharded, x)
        g_out = jax.jit(jax.grad(loss))(params_sharded, x)
    np.testing.assert_allclose(np.asarray(ref), np.asarray(out),
                               rtol=1e-4, atol=1e-5)
    for a, b in zip(jax.tree.leaves(g_ref), jax.tree.leaves(g_out)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-5)


def test_sort_dispatch_llama_gqa_fsdp_ep(devices):
    """Full MoE-Llama (GQA trunk) one train step under fsdp×ep: the sort
    and gather programs produce the same loss and the same updated params
    through the registry -> config plumbing."""
    from pytorch_distributed_training_example_tpu.core import optim, train_loop
    from pytorch_distributed_training_example_tpu.data import prefetch
    from pytorch_distributed_training_example_tpu.models import registry
    from pytorch_distributed_training_example_tpu.utils.config import Config

    mesh = mesh_lib.build_mesh({"data": 2, "fsdp": 2, "expert": 2})
    r = np.random.RandomState(0)
    toks = r.randint(0, 512, (8, 33)).astype(np.int32)
    results = {}
    for impl in ("gather", "sort"):
        bundle = registry.create_model("llama_moe_tiny", seq_len=32,
                                       dtype=jnp.float32,
                                       param_dtype=jnp.float32,
                                       moe_dispatch_impl=impl)
        tx, _ = optim.build_optimizer(
            Config(lr=1e-2, warmup_epochs=0.0, optimizer="sgd",
                   weight_decay=0.0), steps_per_epoch=10)
        rules = sharding_lib.strategy_rules("fsdp_tp", bundle.rules)
        state = train_loop.create_train_state(bundle.module, tx,
                                              bundle.input_template, mesh,
                                              rules, seed=0)
        step = jax.jit(train_loop.make_train_step(train_loop.get_task("lm")),
                       donate_argnums=0)
        with mesh_lib.use_mesh(mesh):
            b = prefetch.shard_batch(
                {"tokens": toks[:, :-1], "targets": toks[:, 1:]},
                mesh_lib.batch_sharding(mesh))
            state, m = step(state, b)
        results[impl] = (float(m["loss"]),
                         np.asarray(state.params["block_0"]["moe"]["experts"]
                                    ["w_up"]))
    assert np.isfinite(results["sort"][0])
    np.testing.assert_allclose(results["sort"][0], results["gather"][0],
                               rtol=1e-5)
    np.testing.assert_allclose(results["sort"][1], results["gather"][1],
                               rtol=1e-4, atol=1e-5)
