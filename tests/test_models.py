import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pytorch_distributed_training_example_tpu.models import registry


@pytest.mark.parametrize("name,size,classes", [
    ("resnet18", 32, 10),
    ("resnet50", 64, 100),
])
def test_resnet_forward_shapes(name, size, classes):
    bundle = registry.create_model(name, num_classes=classes, image_size=size,
                                   dtype=jnp.float32, param_dtype=jnp.float32)
    x = jnp.zeros((4, size, size, 3))
    variables = bundle.module.init(jax.random.PRNGKey(0), x, train=False)
    logits = bundle.module.apply(variables, x, train=False)
    assert logits.shape == (4, classes)
    assert logits.dtype == jnp.float32
    # train mode mutates batch_stats
    logits2, mutated = bundle.module.apply(
        variables, x, train=True, mutable=["batch_stats"],
        rngs={"dropout": jax.random.PRNGKey(1)})
    assert "batch_stats" in mutated


def test_vit_dropout_plumbed_and_defaults_off():
    """Reference parity: torchvision vit_b_16 defaults to dropout=0.0; the
    r3 registry hardcoded 0.1 and paid ~25% of the step for it
    (PROFILE_VIT.md). The rate must flow from create_model to the module."""
    off = registry.create_model("vit_b16", num_classes=10)
    assert off.module.dropout == 0.0
    on = registry.create_model("vit_b16", num_classes=10, dropout=0.1)
    assert on.module.dropout == 0.1


def test_dropout_rejected_for_families_without_it():
    """ADVICE r4: builders that have no dropout knob (Llama, ResNet —
    matching their reference factories) must fail loudly on a nonzero
    --dropout instead of silently swallowing it; GPT-2 implements it and
    must plumb it through."""
    for name in ("llama_tiny", "resnet18"):
        with pytest.raises(ValueError, match="dropout"):
            registry.create_model(name, seq_len=64, dropout=0.1)
    on = registry.create_model("gpt2_tiny", seq_len=64, dropout=0.1)
    assert on.module.dropout == 0.1


@pytest.mark.parametrize("name,expected_m", [
    ("resnet34", 21.80), ("resnet101", 44.55), ("resnet152", 60.19),
    ("vit_l16", 304.33),
])
def test_param_counts_extended_zoo(name, expected_m):
    """New zoo entries match the torchvision factories' published param
    counts (resnet34/101/152, vit_l_16) within 1%."""
    bundle = registry.create_model(name, num_classes=1000, image_size=224)
    variables = jax.eval_shape(
        lambda: bundle.module.init(jax.random.PRNGKey(0),
                                   jnp.zeros((1, 224, 224, 3)), train=False))
    n = sum(int(np.prod(p.shape)) for p in jax.tree.leaves(variables["params"]))
    assert abs(n / 1e6 - expected_m) / expected_m < 0.01, n


def test_llama_moe_param_accounting():
    """The MoE zoo entry's closed-form totals match real init, and the MFU
    basis counts only ACTIVE (top-2) experts — an 8-expert MoE must not
    claim the full expert stack as compute."""
    from pytorch_distributed_training_example_tpu.models import llama

    bundle = registry.create_model("llama_moe", seq_len=64)
    variables = jax.eval_shape(
        lambda: bundle.module.init(jax.random.PRNGKey(0),
                                   jnp.zeros((1, 64), jnp.int32)))
    n = sum(int(np.prod(p.shape)) for p in jax.tree.leaves(variables["params"]))
    cfg = bundle.module
    assert n == llama.num_params(cfg)
    # Independent structural check: count the REAL expert-stack leaves
    # (params under .../moe/experts) from the initialized tree; active =
    # trunk + top_k/E of the expert stack must match the closed form.
    flat = jax.tree_util.tree_flatten_with_path(variables["params"])[0]
    expert = sum(
        int(np.prod(leaf.shape)) for path, leaf in flat
        if any(getattr(p, "key", None) == "experts" for p in path))
    assert expert > 0.5 * n  # the stack dominates an 8-expert MoE
    want_active = (n - expert) + expert * 2 // cfg.num_experts
    assert llama.num_params_active(cfg) == want_active, (
        llama.num_params_active(cfg), want_active)


def test_param_count_resnet18():
    bundle = registry.create_model("resnet18", num_classes=1000, image_size=224,
                                   dtype=jnp.float32, param_dtype=jnp.float32)
    variables = jax.eval_shape(
        lambda: bundle.module.init(jax.random.PRNGKey(0),
                                   jnp.zeros((1, 224, 224, 3)), train=False))
    n = sum(int(np.prod(p.shape)) for p in jax.tree.leaves(variables["params"]))
    # torchvision resnet18 has 11.69M params
    assert 11.4e6 < n < 12.0e6, n


def test_bf16_compute_fp32_params():
    bundle = registry.create_model("resnet18", num_classes=10, image_size=32,
                                   dtype=jnp.bfloat16, param_dtype=jnp.float32)
    x = jnp.zeros((2, 32, 32, 3))
    variables = bundle.module.init(jax.random.PRNGKey(0), x, train=False)
    for p in jax.tree.leaves(variables["params"]):
        assert p.dtype == jnp.float32
    logits = bundle.module.apply(variables, x, train=False)
    assert logits.dtype == jnp.float32  # outputs cast back up
