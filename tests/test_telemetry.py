"""Unified telemetry layer (utils/telemetry.py): on-device health pack,
span timeline / goodput accounting, anomaly guard — plus the logging and
watchdog satellites that ride with it."""

import json
import os
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pytorch_distributed_training_example_tpu.core import (
    mesh as mesh_lib, optim, train_loop)
from pytorch_distributed_training_example_tpu.data import prefetch
from pytorch_distributed_training_example_tpu.models import registry
from pytorch_distributed_training_example_tpu.parallel import moe as moe_lib
from pytorch_distributed_training_example_tpu.parallel import (
    sharding as sharding_lib)
from pytorch_distributed_training_example_tpu.utils import (
    logging as logging_lib, metrics as metrics_lib,
    telemetry as telemetry_lib, watchdog as watchdog_lib)
from pytorch_distributed_training_example_tpu.utils.config import Config

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _lm_batch(n, seq, vocab=512, seed=0):
    r = np.random.RandomState(seed)
    toks = r.randint(0, vocab, (n, seq + 1)).astype(np.int32)
    return {"tokens": toks[:, :-1], "targets": toks[:, 1:]}


def _np_norm(tree) -> float:
    return float(np.sqrt(sum(
        float(np.sum(np.asarray(x, np.float64) ** 2))
        for x in jax.tree.leaves(tree))))


# ---------------------------------------------------------------------------
# Health pack (device side)
# ---------------------------------------------------------------------------


def test_health_pack_matches_reference_norms(devices):
    """grad/update/param norms from the compiled step equal host-side
    recomputation (optax.global_norm on jax.grad / numpy on fetched params)."""
    import optax

    mesh = mesh_lib.single_device_mesh()
    bundle = registry.create_model("llama_tiny", seq_len=16,
                                   dtype=jnp.float32, param_dtype=jnp.float32)
    tx, _ = optim.build_optimizer(Config(lr=0.01, warmup_epochs=0.0),
                                  steps_per_epoch=10)
    rules = sharding_lib.strategy_rules("dp", bundle.rules)
    state = train_loop.create_train_state(
        bundle.module, tx, bundle.input_template, mesh, rules, seed=0)
    step = jax.jit(train_loop.make_train_step(
        train_loop.get_task("lm"), health=True))  # no donation: state reused
    batch = _lm_batch(4, 16)

    old_params = jax.device_get(state.params)
    with mesh_lib.use_mesh(mesh):
        b = prefetch.shard_batch(batch, mesh_lib.batch_sharding(mesh))
        new_state, metrics = step(state, b)
    m = {k: float(v) for k, v in jax.device_get(metrics).items()}
    new_params = jax.device_get(new_state.params)

    # Reference gradient: same forward the step traces (llama_tiny has no
    # aux losses and dropout 0.0, so the loss is plain cross-entropy).
    step_rng = jax.random.fold_in(state.rng, state.step)

    def loss_fn(params):
        logits, _ = state.apply_fn(
            {"params": params}, jnp.asarray(batch["tokens"]), train=True,
            rngs={"dropout": step_rng}, mutable=["losses"])
        return metrics_lib.cross_entropy(logits, jnp.asarray(batch["targets"]))

    grads = jax.grad(loss_fn)(state.params)
    ref_grad_norm = float(optax.global_norm(grads))

    assert np.isclose(m["grad_norm"], ref_grad_norm, rtol=1e-4)
    update = jax.tree.map(lambda n, o: np.asarray(n) - np.asarray(o),
                          new_params, old_params)
    assert np.isclose(m["update_norm"], _np_norm(update), rtol=1e-4)
    assert np.isclose(m["param_norm"], _np_norm(new_params), rtol=1e-4)
    assert m["loss_finite"] == 1.0
    assert m["grads_finite_all"] == 1.0


def test_train_step_moe_telemetry_with_grad_accum(devices):
    """MoE router scalars survive the grad-accum scan carry and land in the
    metrics dict alongside the health pack."""
    mesh = mesh_lib.single_device_mesh()
    bundle = registry.create_model(
        "llama_moe_tiny", seq_len=16, dtype=jnp.float32,
        param_dtype=jnp.float32, moe_capacity_factor=1.0, moe_top_k=2,
        moe_dispatch_impl="gather")
    tx, _ = optim.build_optimizer(Config(lr=0.01, warmup_epochs=0.0),
                                  steps_per_epoch=10)
    rules = sharding_lib.strategy_rules("fsdp", bundle.rules)
    state = train_loop.create_train_state(
        bundle.module, tx, bundle.input_template, mesh, rules, seed=0)
    step = jax.jit(train_loop.make_train_step(
        train_loop.get_task("lm"), grad_accum=2, health=True),
        donate_argnums=0)
    with mesh_lib.use_mesh(mesh):
        b = prefetch.shard_batch(_lm_batch(4, 16),
                                 mesh_lib.batch_sharding(mesh))
        state, metrics = step(state, b)
    m = {k: float(v) for k, v in jax.device_get(metrics).items()}
    for key in ("router_load_entropy", "moe_drop_fraction", "update_norm",
                "param_norm", "loss_finite", "grads_finite_all"):
        assert key in m and np.isfinite(m[key]), (key, m)
    assert 0.0 <= m["router_load_entropy"] <= 1.0 + 1e-6
    assert 0.0 <= m["moe_drop_fraction"] <= 1.0


@pytest.mark.parametrize("impl", ["sort", "gather", "einsum"])
def test_moe_router_scalars_match_numpy(devices, impl):
    """router_load_entropy / moe_drop_fraction from the sow collection equal
    a from-scratch numpy recomputation of the routing math — identically
    across all three dispatch implementations."""
    E, k, cf = 4, 2, 0.5  # cf=0.5 forces real capacity drops
    B, S, d = 2, 8, 16
    T = B * S
    capacity = max(int(cf * T * k / E), 1)
    moe = moe_lib.MoEBlock(num_experts=E, ffn_dim=32, top_k=k,
                           capacity_factor=cf, dispatch_impl=impl,
                           dtype=jnp.float32, param_dtype=jnp.float32)
    rng = np.random.RandomState(0)
    x = rng.randn(B, S, d).astype(np.float32)
    variables = moe.init(jax.random.PRNGKey(0), jnp.asarray(x))
    _, new_vars = moe.apply({"params": variables["params"]}, jnp.asarray(x),
                            mutable=["losses", "telemetry"])
    tele = {kk: float(v) for kk, v in
            telemetry_lib.collect_sowed(new_vars["telemetry"]).items()}

    # numpy reference: router softmax -> top-k -> load entropy; priority-
    # order capacity cumsum -> drop fraction.
    W = np.asarray(variables["params"]["router"]["kernel"], np.float32)
    logits = x.reshape(T, d) @ W
    z = logits - logits.max(-1, keepdims=True)
    probs = np.exp(z) / np.exp(z).sum(-1, keepdims=True)
    expert_idx = np.argsort(-probs, axis=-1, kind="stable")[:, :k]  # [T, k]
    onehot = np.eye(E, dtype=np.float32)[expert_idx]                # [T, k, E]
    load = onehot.mean((0, 1))
    ref_entropy = float(-np.sum(load * np.log(load + 1e-9)) / np.log(E))
    flat = onehot.transpose(1, 0, 2).reshape(k * T, E)
    pos_in_expert = np.cumsum(flat, axis=0) - flat
    pos = (pos_in_expert.reshape(k, T, E).transpose(1, 0, 2) * onehot).sum(-1)
    within_cap = pos < capacity
    ref_drop = float(1.0 - within_cap.mean())

    assert np.isclose(tele["router_load_entropy"], ref_entropy, atol=1e-5)
    assert np.isclose(tele["moe_drop_fraction"], ref_drop, atol=1e-6)
    assert ref_drop > 0.0  # the capacity factor actually bit


# ---------------------------------------------------------------------------
# Span recorder + goodput (host side)
# ---------------------------------------------------------------------------


def test_span_recorder_perfetto_and_goodput(tmp_path):
    rec = telemetry_lib.SpanRecorder(run_id="r1")
    with rec.span("init"):
        with rec.span("checkpoint_restore"):  # nested: timeline only
            time.sleep(0.01)
        time.sleep(0.01)
    for _ in range(3):
        with rec.span("step"):
            time.sleep(0.01)
    rec.write(str(tmp_path))

    trace = json.load(open(tmp_path / "trace_events.json"))
    events = trace["traceEvents"]
    assert {e["name"] for e in events} == {"init", "checkpoint_restore",
                                          "step"}
    for e in events:  # Perfetto complete-event shape
        assert e["ph"] == "X"
        assert isinstance(e["ts"], int) and e["ts"] >= 0
        assert isinstance(e["dur"], int) and e["dur"] > 0
        assert "pid" in e and "tid" in e

    g = json.load(open(tmp_path / "goodput.json"))
    # Only OUTERMOST spans accrue: the nested restore is on the timeline
    # but never double-counts wall time.
    assert g["counts"] == {"init": 1, "step": 3}
    assert 0.0 < g["goodput_fraction"] <= 1.0
    assert sum(g["fractions"].values()) <= 1.0 + 1e-9
    # goodput/badput/coverage are each rounded to 4 decimals independently,
    # so the identity only holds to that rounding.
    assert np.isclose(g["coverage"],
                      g["goodput_fraction"] + g["badput_fraction"], atol=2e-4)
    assert g["run_id"] == "r1"


# ---------------------------------------------------------------------------
# Anomaly guard
# ---------------------------------------------------------------------------


def test_anomaly_guard_abort_dumps_bundle(tmp_path):
    guard = telemetry_lib.AnomalyGuard(str(tmp_path), action="abort",
                                       config=Config(), run_id="rid")
    assert guard.check(0, {"loss": 1.0, "grad_norm": 2.0}) is False
    with pytest.raises(telemetry_lib.AnomalyError):
        guard.check(1, {"loss": float("nan"), "grad_norm": 1.0})
    bundles = sorted(tmp_path.glob("anomaly_step*.json"))
    assert len(bundles) == 1
    b = json.load(open(bundles[0]))
    assert b["trigger_keys"] == ["loss"]
    assert b["step"] == 1
    assert len(b["history"]) == 2  # last-K rows, including the trigger
    assert b["config"]["model"] == "resnet18"
    assert b["run_id"] == "rid"


def test_anomaly_guard_continue_and_scaler_skip(tmp_path):
    guard = telemetry_lib.AnomalyGuard(str(tmp_path), action="continue",
                                       allow_scaler_skips=True)
    # fp16 overflow-skip row: inf grad norm with grads_finite==0 is the
    # scaler's HANDLED branch, not an anomaly.
    assert guard.check(0, {"loss": 2.0, "grad_norm": float("inf"),
                           "grads_finite": 0.0}) is False
    assert not guard.tripped
    # A real non-finite loss trips, dumps, and continues (no raise).
    assert guard.check(1, {"loss": float("inf"), "grads_finite": 1.0}) is True
    assert guard.tripped
    assert (tmp_path / "anomaly_step00000001.json").exists()
    with pytest.raises(ValueError):
        telemetry_lib.AnomalyGuard(str(tmp_path), action="explode")


def test_anomaly_guard_flight_dump_once_per_episode(tmp_path):
    guard = telemetry_lib.AnomalyGuard(str(tmp_path), action="continue")
    dumps = []
    guard.flight_dump_fn = lambda reason, **kw: dumps.append(
        (reason, kw["step"]))
    # A NaN that sticks in the params flags every subsequent check — the
    # bundle is per-step, but the flight ring dumps once per episode.
    for s in (4, 5, 6):
        assert guard.check(s, {"loss": float("nan")}) is True
    assert dumps == [("anomaly", 4)]
    assert (tmp_path / "anomaly_step00000006.json").exists()
    # A clean row closes the episode; the next trip dumps again.
    assert guard.check(7, {"loss": 1.0}) is False
    assert guard.check(8, {"loss": float("inf")}) is True
    assert dumps == [("anomaly", 4), ("anomaly", 8)]


def test_telemetry_facade_observe_snapshot_emit(tmp_path):
    tele = telemetry_lib.Telemetry(str(tmp_path), run_id="rid",
                                   anomaly_action="continue")
    with tele.span("step"):
        time.sleep(0.005)
    assert tele.observe(3, {"loss": 1.5}) is False
    snap = tele.snapshot()
    assert snap["last_step"] == 3
    assert snap["last_health"]["loss"] == 1.5
    assert "goodput" in snap
    g = tele.emit("test")
    assert g["run_id"] == "rid"
    assert (tmp_path / "trace_events.json").exists()
    assert (tmp_path / "goodput.json").exists()


# ---------------------------------------------------------------------------
# Trainer end-to-end: health rows, timeline artifacts, injected-NaN bundle
# ---------------------------------------------------------------------------


def test_trainer_telemetry_end_to_end_with_nan_injection(tmp_path, devices):
    """A NaN learning rate makes the very first applied update non-finite,
    so the first health fetch must trip the guard (action=continue), dump a
    diagnostic bundle, and the run must still produce the full telemetry
    surface: health rows in metrics.jsonl, trace_events.json, goodput.json."""
    from pytorch_distributed_training_example_tpu.core.trainer import Trainer

    ckdir = tmp_path / "ck"
    cfg = Config(model="llama_tiny", dataset="lm", seq_len=16, epochs=1,
                 global_batch_size=8, lr=float("nan"), warmup_epochs=0.0,
                 optimizer="sgd", precision="fp32", workers=0,
                 steps_per_epoch=3, log_every=1, telemetry=True,
                 health_every=1, anomaly_action="continue",
                 checkpoint_dir=str(ckdir), checkpoint_every_epochs=100,
                 eval_every_epochs=100)
    Trainer(cfg).train()

    rows = [json.loads(line) for line in open(ckdir / "metrics.jsonl")]
    train_rows = [r for r in rows if r.get("kind") == "train"]
    assert train_rows and all("update_norm" in r for r in train_rows)
    assert any(r.get("kind") == "goodput" for r in rows)
    assert all("run_id" in r for r in rows)

    bundles = sorted(ckdir.glob("anomaly_step*.json"))
    assert bundles, "injected NaN never produced a diagnostic bundle"
    b = json.load(open(bundles[0]))
    assert any(k in b["trigger_keys"] for k in ("update_norm", "param_norm",
                                                "loss", "grad_norm"))
    assert b["config"]["anomaly_action"] == "continue"

    trace = json.load(open(ckdir / "trace_events.json"))
    names = {e["name"] for e in trace["traceEvents"]}
    assert {"init", "compile", "input_wait"} <= names
    good = json.load(open(ckdir / "goodput.json"))
    assert sum(good["fractions"].values()) <= 1.0 + 1e-9
    assert good["counts"].get("compile") == 1


# ---------------------------------------------------------------------------
# Satellites: watchdog context, logger run_id, AverageMeter fmt, health scan
# ---------------------------------------------------------------------------


def test_watchdog_calls_context_fn_on_timeout():
    calls = []

    def ctx():
        calls.append(1)
        return {"last_step": 7}

    wd = watchdog_lib.Watchdog(timeout_s=0.2, context_fn=ctx).start()
    try:
        deadline = time.monotonic() + 10.0
        while not calls and time.monotonic() < deadline:
            time.sleep(0.05)
    finally:
        wd.stop()
    assert calls, "watchdog never fired its context hook"


def test_metric_logger_stamps_run_id(tmp_path):
    path = tmp_path / "m.jsonl"
    ml = logging_lib.MetricLogger(str(path))
    ml.write(kind="train", step=0, loss=1.0)
    ml.write(kind="health", step=1, loss=0.9)
    ml.close()
    rows = [json.loads(line) for line in open(path)]
    assert len(rows) == 2
    assert all(r["run_id"] == ml.run_id for r in rows)
    assert len(ml.run_id) == 12


def test_average_meter_fmt_with_and_without_colon():
    m1 = logging_lib.AverageMeter("loss", ":.2f")
    m2 = logging_lib.AverageMeter("loss", ".2f")
    m1.update(1.234)
    m2.update(1.234)
    assert str(m1) == str(m2) == "loss 1.23 (1.23)"


def test_check_regression_flags_nonfinite_health(tmp_path):
    sys.path.insert(0, os.path.join(REPO, "benchmarks"))
    import check_regression as cr

    p = tmp_path / "metrics.jsonl"
    rows = [{"kind": "train", "step": 0, "loss": 1.0, "update_norm": 0.5}]
    p.write_text("\n".join(json.dumps(r, default=float) for r in rows) + "\n")
    failures, _ = cr.check_health(str(p))
    assert not failures

    rows.append({"kind": "health", "step": 1, "loss": 2.0,
                 "update_norm": float("nan")})
    p.write_text("\n".join(json.dumps(r, default=float) for r in rows) + "\n")
    failures, report = cr.check_health(str(p))
    assert failures and "update_norm" in failures[0]
    assert any(line.startswith("NON-FINITE") for line in report)


def test_span_recorder_ttfs_and_restart_breakdown(tmp_path):
    rec = telemetry_lib.SpanRecorder(run_id="r1")
    with rec.span("compile"):
        time.sleep(0.01)
    with rec.span("step"):
        time.sleep(0.005)
    rec.mark_first_step("cold")
    rec.mark_first_step("warm")  # later calls are no-ops: TTFS is ONE number
    g1 = rec.goodput()
    assert g1["ttfs_mode"] == "cold"
    assert g1["time_to_first_step_s"] >= 0.01
    assert g1["ttfs_history"] == [{"attempt": 1, "mode": "cold",
                                   "ttfs_s": g1["time_to_first_step_s"]}]
    assert "restart_breakdown" not in g1  # no restart gap yet

    # Attempt 2 carries attempt 1's goodput: history accumulates and the
    # restart gap is decomposed into the three costs r21 exists to shrink.
    time.sleep(0.02)  # a measurable supervisor gap past ended_at's rounding
    rec2 = telemetry_lib.SpanRecorder(run_id="r1", carry=g1)
    with rec2.span("checkpoint_restore"):
        time.sleep(0.005)
    with rec2.span("step"):
        pass
    rec2.mark_first_step("warm")
    g2 = rec2.goodput()
    assert g2["attempts"] == 2
    assert [h["mode"] for h in g2["ttfs_history"]] == ["cold", "warm"]
    assert g2["ttfs_history"][1]["attempt"] == 2
    bd = g2["restart_breakdown"]
    assert bd["gap_s"] > 0.0  # the supervisor gap between the attempts
    assert bd["restore_s"] >= 0.005
    assert set(bd) == {"gap_s", "compile_s", "restore_s"}
