"""Guard-rail and observability utilities (SURVEY.md §5): watchdog, timeout
blocking, metric logging — small pieces the trainer leans on every step."""

import json
import logging
import time

import jax.numpy as jnp
import numpy as np
import pytest

from pytorch_distributed_training_example_tpu.utils import (
    logging as log_lib, metrics as metrics_lib, watchdog as wd)


class _Capture(logging.Handler):
    """Handler attached straight to the 'pdtx' logger: trainer tests run
    setup_logging() which sets propagate=False, so caplog's root-logger
    handler misses watchdog records inside the full suite."""

    def __init__(self):
        super().__init__(level=logging.ERROR)
        self.records = []

    def emit(self, record):
        self.records.append(record)


def test_watchdog_fires_and_recovers():
    logger = logging.getLogger("pdtx")
    cap = _Capture()
    logger.addHandler(cap)
    old_level = logger.level
    logger.setLevel(logging.ERROR)
    try:
        # Generous windows + deadline polling: the suite runs on a
        # contended single-core box where thread scheduling can lag.
        w = wd.Watchdog(timeout_s=0.5).start()
        try:
            deadline = time.monotonic() + 15.0
            while (not any("watchdog" in r.getMessage() for r in cap.records)
                   and time.monotonic() < deadline):
                time.sleep(0.05)  # no beats -> must fire eventually
            assert any("watchdog" in r.getMessage() for r in cap.records)
        finally:
            w.stop()

        # Heartbeats keep it silent over a window long enough for the idle
        # check (every timeout/4 = 0.5s) to run at least once; the 2s
        # timeout tolerates scheduler stalls without re-flaking.
        w2 = wd.Watchdog(timeout_s=2.0).start()
        try:
            cap.records.clear()
            deadline = time.monotonic() + 1.2
            while time.monotonic() < deadline:
                w2.beat()
                time.sleep(0.02)
            assert not cap.records
        finally:
            w2.stop()
    finally:
        logger.removeHandler(cap)
        logger.setLevel(old_level)


def test_block_with_timeout_passes_and_raises():
    x = jnp.ones((4,)) * 2
    wd.block_until_ready_with_timeout(x, timeout_s=30)

    class Never:
        # The hung-dispatch contract is polled via is_ready() (r9: the old
        # helper-thread-in-block_until_ready version leaked the thread).
        def is_ready(self):
            return False

        def block_until_ready(self):
            time.sleep(60)

    with pytest.raises(TimeoutError, match="not ready"):
        wd.block_until_ready_with_timeout(Never(), timeout_s=0.3)


def test_metric_logger_jsonl_roundtrip(tmp_path):
    path = tmp_path / "m" / "metrics.jsonl"
    ml = log_lib.MetricLogger(str(path))
    ml.write(kind="train", step=1, loss=2.5)
    ml.write(kind="eval", loss=np.float32(1.25))  # numpy scalars serialize
    ml.close()
    rows = [json.loads(l) for l in path.read_text().splitlines()]
    assert rows[0]["kind"] == "train" and rows[0]["loss"] == 2.5
    assert rows[1]["loss"] == 1.25 and "time" in rows[1]


def test_average_meter_and_throughput():
    m = log_lib.AverageMeter("loss")
    m.update(2.0)
    m.update(4.0, n=3)
    assert m.avg == pytest.approx(3.5)
    t = log_lib.Throughput(warmup_steps=1)
    t.update(10)          # warmup step sets t0
    time.sleep(0.05)
    t.update(10)
    assert 0 < t.rate < 10_000


def test_mfu_accounting():
    # 1000 img/s at 4.09 GFLOP fwd => 3x fwd+bwd = 12.27 TF/s achieved.
    class FakeDev:
        device_kind = "TPU v5 lite"

    mfu = metrics_lib.mfu(1000.0, 4.09e9, device=FakeDev())
    assert mfu == pytest.approx(3 * 4.09e12 / 197e12)
    assert metrics_lib.peak_hbm_gbps(FakeDev()) == 819.0


def test_metric_logger_tensorboard_export(tmp_path):
    """SURVEY.md §5 optional TensorBoard scalars: numeric metrics land as
    event-file scalars tagged kind/name at the given step; non-numerics
    are skipped; JSONL keeps working alongside."""
    pytest.importorskip("tensorboard")
    from pytorch_distributed_training_example_tpu.utils.logging import MetricLogger

    tb = tmp_path / "tb"
    ml = MetricLogger(jsonl_path=str(tmp_path / "m.jsonl"),
                      tensorboard_dir=str(tb))
    ml.write(kind="train", step=3, loss=1.5, acc_top1=0.25, note="skip-me")
    ml.write(kind="eval", epoch=1, loss=2.0)
    ml.close()

    from tensorboard.backend.event_processing.event_accumulator import (
        EventAccumulator)

    acc = EventAccumulator(str(tb))
    acc.Reload()
    tags = set(acc.Tags()["scalars"])
    assert {"train/loss", "train/acc_top1", "eval/loss"} <= tags, tags
    ev = acc.Scalars("train/loss")[0]
    assert ev.step == 3 and abs(ev.value - 1.5) < 1e-6
    assert "train/note" not in tags
    assert (tmp_path / "m.jsonl").read_text().count("\n") == 2


def test_metric_logger_tensorboard_step_axes(tmp_path):
    """Eval rows (epoch-keyed) land on the global-step axis when the
    trainer provides steps_per_epoch, so train/eval scalars are
    comparable; per-kind counters never move backwards (ADVICE r4)."""
    pytest.importorskip("tensorboard")
    from pytorch_distributed_training_example_tpu.utils.logging import MetricLogger

    tb = tmp_path / "tb"
    ml = MetricLogger(tensorboard_dir=str(tb))
    ml.steps_per_epoch = 100
    ml.write(kind="train", epoch=0, step=99, loss=1.0)
    ml.write(kind="eval", epoch=0, loss=2.0)    # -> global step 99
    ml.write(kind="train", epoch=1, step=199, loss=0.5)
    ml.write(kind="eval", epoch=1, loss=1.5)    # -> global step 199
    ml.close()

    from tensorboard.backend.event_processing.event_accumulator import (
        EventAccumulator)

    acc = EventAccumulator(str(tb))
    acc.Reload()
    assert [e.step for e in acc.Scalars("eval/loss")] == [99, 199]
    assert [e.step for e in acc.Scalars("train/loss")] == [99, 199]


def test_lr_schedules_reference_recipes():
    """Schedule parity: 'step' reproduces the reference ImageNet StepLR
    (lr * gamma^(epoch // 30)); cosine + warmup keeps its r4 shape
    (linear to peak at warmup end, cosine to 0 at the horizon);
    'constant' is flat after warmup."""
    from pytorch_distributed_training_example_tpu.core import optim
    from pytorch_distributed_training_example_tpu.utils.config import Config

    spe = 100
    step = optim.build_schedule(
        Config(lr=0.1, warmup_epochs=0.0, lr_schedule="step",
               lr_step_epochs=30, lr_gamma=0.1, epochs=90), spe)
    assert float(step(0)) == pytest.approx(0.1)
    assert float(step(29 * spe + 99)) == pytest.approx(0.1)
    assert float(step(30 * spe)) == pytest.approx(0.01)
    assert float(step(60 * spe)) == pytest.approx(0.001)

    # ...and the decay epochs stay on the GLOBAL grid under warmup: the
    # reference recipe decays at epochs 30/60 regardless of warmup.
    stepw = optim.build_schedule(
        Config(lr=0.1, warmup_epochs=5.0, lr_schedule="step",
               lr_step_epochs=30, lr_gamma=0.1, epochs=90), spe)
    assert float(stepw(5 * spe // 2)) == pytest.approx(0.05)  # mid-warmup
    assert float(stepw(29 * spe + 99)) == pytest.approx(0.1)
    assert float(stepw(30 * spe)) == pytest.approx(0.01)
    assert float(stepw(60 * spe)) == pytest.approx(0.001)

    cos = optim.build_schedule(
        Config(lr=0.4, warmup_epochs=1.0, lr_schedule="cosine", epochs=10),
        spe)
    assert float(cos(0)) == pytest.approx(0.0)
    assert float(cos(spe)) == pytest.approx(0.4)       # peak at warmup end
    assert float(cos(10 * spe)) == pytest.approx(0.0, abs=1e-6)
    # halfway through the cosine phase = half the peak
    assert float(cos(spe + (9 * spe) // 2)) == pytest.approx(0.2, rel=0.01)

    const = optim.build_schedule(
        Config(lr=0.05, warmup_epochs=0.0, lr_schedule="constant",
               epochs=5), spe)
    assert float(const(0)) == float(const(499)) == pytest.approx(0.05)

    with pytest.raises(ValueError, match="lr_schedule"):
        optim.build_schedule(Config(lr_schedule="nope"), spe)
