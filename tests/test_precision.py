"""fp16 GradScaler parity path (SURVEY.md §2a #6 / §2b N6).

bf16 is the TPU-native AMP replacement (no scaler); fp16 keeps exact
``torch.cuda.amp.GradScaler`` semantics — scale, unscale, skip-on-overflow,
backoff/growth — inside the compiled step. These were implemented in round 1
but never test-covered.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pytorch_distributed_training_example_tpu.core import (
    mesh as mesh_lib, optim, precision as precision_lib, train_loop)
from pytorch_distributed_training_example_tpu.data import prefetch
from pytorch_distributed_training_example_tpu.models import registry
from pytorch_distributed_training_example_tpu.parallel import (
    sharding as sharding_lib)
from pytorch_distributed_training_example_tpu.utils.config import Config


def test_policy_table():
    assert precision_lib.needs_loss_scaling(precision_lib.get_policy("fp16"))
    for name in ("fp32", "bf16", "pure_bf16"):
        assert not precision_lib.needs_loss_scaling(
            precision_lib.get_policy(name))
    with pytest.raises(ValueError, match="unknown precision"):
        precision_lib.get_policy("fp8")


def test_scaler_backoff_and_growth():
    s = precision_lib.ScalerState.create(init_scale=1024.0,
                                         growth_interval=2)
    s = s.update(jnp.asarray(False))            # overflow -> halve
    assert float(s.scale) == 512.0 and int(s.growth_tracker) == 0
    s = s.update(jnp.asarray(True))
    s = s.update(jnp.asarray(True))             # 2 finite steps -> double
    assert float(s.scale) == 1024.0 and int(s.growth_tracker) == 0


def _fp16_state_and_step(grad_accum=1, lr=1e-3):
    mesh = mesh_lib.build_mesh({"data": 8})
    policy = precision_lib.get_policy("fp16")
    bundle = registry.create_model("llama_tiny", seq_len=32,
                                   dtype=policy.compute_dtype,
                                   param_dtype=policy.param_dtype)
    cfg = Config(lr=lr, warmup_epochs=0.0, optimizer="sgd", grad_clip=0.0,
                 weight_decay=0.0)
    tx, _ = optim.build_optimizer(cfg, steps_per_epoch=100)
    rules = sharding_lib.strategy_rules("dp", bundle.rules)
    state = train_loop.create_train_state(
        bundle.module, tx, bundle.input_template, mesh, rules, seed=0,
        scaler=precision_lib.ScalerState.create())
    step = jax.jit(train_loop.make_train_step(
        train_loop.get_task("lm"), grad_accum), donate_argnums=0)
    return mesh, state, step


def _lm_batch(n=16, seed=0):
    r = np.random.RandomState(seed)
    toks = r.randint(0, 512, (n, 33)).astype(np.int32)
    return {"tokens": toks[:, :-1], "targets": toks[:, 1:]}


@pytest.mark.parametrize("grad_accum", [1, 4])
def test_fp16_trains_finite_with_scaler(devices, grad_accum):
    mesh, state, step = _fp16_state_and_step(grad_accum)
    with mesh_lib.use_mesh(mesh):
        sh = mesh_lib.batch_sharding(mesh)
        for i in range(3):
            state, m = step(state, prefetch.shard_batch(_lm_batch(seed=i), sh))
        m = {k: float(v) for k, v in jax.device_get(m).items()}
    assert np.isfinite(m["loss"])
    assert m["grads_finite"] == 1.0
    assert m["loss_scale"] == 2.0**15  # untouched while finite


def test_fp16_overflow_skips_update_and_backs_off(devices):
    """GradScaler.step parity: on overflow params AND opt state hold, the
    scale halves, and the step counter still advances."""
    mesh, state, step = _fp16_state_and_step()
    # A scaled loss at 2^15 over fp16's max (~65504) overflows the backward.
    huge = jax.tree.map(
        lambda p: (p * 1e4).astype(p.dtype)
        if jnp.issubdtype(p.dtype, jnp.floating) else p, state.params)
    state = state.replace(params=huge)
    params_before = jax.device_get(state.params)
    with mesh_lib.use_mesh(mesh):
        sh = mesh_lib.batch_sharding(mesh)
        state, m = step(state, prefetch.shard_batch(_lm_batch(), sh))
    m = {k: float(v) for k, v in jax.device_get(m).items()}
    assert m["grads_finite"] == 0.0
    assert m["loss_scale"] == 2.0**14  # backed off
    for a, b in zip(jax.tree.leaves(params_before),
                    jax.tree.leaves(jax.device_get(state.params))):
        np.testing.assert_array_equal(a, b)  # update skipped
    assert int(jax.device_get(state.step)) == 1  # schedule still advances


def test_bf16_logits_storage_matches_f32():
    """bf16 logits_dtype (the bf16 policy's LM setting) only re-rounds what
    the bf16 vocab matmul already rounded: the CE loss must match the
    f32-stored-logits run closely, and the policy must request it."""
    assert precision_lib.get_policy("bf16").logits_dtype == jnp.bfloat16
    assert precision_lib.get_policy("fp16").logits_dtype == jnp.float32

    mesh = mesh_lib.single_device_mesh()
    losses = {}
    for ld in (jnp.float32, jnp.bfloat16):
        bundle = registry.create_model("gpt2_tiny", seq_len=32,
                                       dtype=jnp.bfloat16,
                                       param_dtype=jnp.float32,
                                       logits_dtype=ld)
        cfg = Config(lr=1e-3, warmup_epochs=0.0, optimizer="sgd")
        tx, _ = optim.build_optimizer(cfg, steps_per_epoch=100)
        state = train_loop.create_train_state(
            bundle.module, tx, bundle.input_template, mesh, (), seed=0)
        step = jax.jit(train_loop.make_train_step(train_loop.get_task("lm")))
        with mesh_lib.use_mesh(mesh):
            _, m = step(state, prefetch.shard_batch(
                _lm_batch(), mesh_lib.batch_sharding(mesh)))
        losses[str(ld.__name__)] = float(m["loss"])
    assert np.isclose(losses["float32"], losses["bfloat16"], rtol=2e-3), losses
