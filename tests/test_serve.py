"""Serving subsystem: paged KV cache, paged decode attention, engine.

The load-bearing claim is TOKEN IDENTITY: greedy decode through the paged
cache (prefill + one-token steps, pages scattered arbitrarily by the pool's
LIFO allocator) must reproduce the exact argmax sequence of the full
training forward on the same weights. Everything else — bucketing, paging,
eviction, AOT warmup — is only allowed to change performance, never tokens.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from pytorch_distributed_training_example_tpu.models import registry
from pytorch_distributed_training_example_tpu.ops import flash_attention as flash_lib
from pytorch_distributed_training_example_tpu.serve import (
    engine as engine_lib, kv_cache, loadgen)
from pytorch_distributed_training_example_tpu.serve.kv_cache import (
    CacheSpec, PagePool, pages_for_tokens)


# ---------------------------------------------------------------------------
# kv_cache: pool bookkeeping + append/gather round trip
# ---------------------------------------------------------------------------


def test_pages_for_tokens():
    assert pages_for_tokens(1, 16) == 1
    assert pages_for_tokens(16, 16) == 1
    assert pages_for_tokens(17, 16) == 2
    assert pages_for_tokens(0, 16) == 1  # a request always owns page one


def test_page_pool_alloc_free_idempotent():
    pool = PagePool(8)  # page 0 reserved -> 7 allocatable
    assert pool.num_free == 7
    a = pool.alloc("a", 3)
    assert len(a) == 3 and 0 not in a
    assert pool.owned("a") == a
    assert not pool.can_alloc(5) and pool.can_alloc(4)
    with pytest.raises(MemoryError):
        pool.alloc("b", 5)
    pool.free("a")
    pool.free("a")  # idempotent (retire + evict racing is a no-op)
    assert pool.num_free == 7 and pool.owned("a") == []


def test_append_gather_round_trip():
    spec = CacheSpec(num_layers=1, num_pages=8, page_size=4, num_kv_heads=2,
                     head_dim=4)
    pages = jnp.zeros(spec.layer_shape())
    rng = np.random.default_rng(0)
    # Two requests with deliberately interleaved, non-contiguous pages.
    table = jnp.asarray([[3, 5, 0], [6, 2, 7]], jnp.int32)
    ref = np.zeros((2, 12, 2, 4), np.float32)
    for pos in range(9):
        new = rng.standard_normal((2, 1, 2, 4)).astype(np.float32)
        positions = jnp.full((2, 1), pos, jnp.int32)
        pages = kv_cache.append_pages(pages, jnp.asarray(new), table,
                                      positions)
        ref[:, pos] = new[:, 0]
    got = np.asarray(kv_cache.gather_pages(pages, table))
    np.testing.assert_array_equal(got[:, :9], ref[:, :9])


# ---------------------------------------------------------------------------
# paged decode attention: xla vs oracle vs pallas(interpret), GQA shapes
# ---------------------------------------------------------------------------


def _paged_setup(B, H, Hkv, D, page_size, num_pages, lens, seed=0):
    rng = np.random.default_rng(seed)
    S = max(lens) + 1
    q = rng.standard_normal((B, H, D)).astype(np.float32)
    k_full = rng.standard_normal((B, S, Hkv, D)).astype(np.float32)
    v_full = rng.standard_normal((B, S, Hkv, D)).astype(np.float32)
    max_pages = pages_for_tokens(S, page_size)
    pool = PagePool(num_pages)
    k_pages = jnp.zeros((num_pages, page_size, Hkv, D))
    v_pages = jnp.zeros((num_pages, page_size, Hkv, D))
    table = np.zeros((B, max_pages), np.int32)
    for b in range(B):
        table[b] = pool.alloc(f"r{b}", max_pages)
    table = jnp.asarray(table)
    for pos in range(S):
        positions = jnp.full((B, 1), pos, jnp.int32)
        k_pages = kv_cache.append_pages(k_pages, jnp.asarray(k_full[:, pos:pos + 1]),
                                        table, positions)
        v_pages = kv_cache.append_pages(v_pages, jnp.asarray(v_full[:, pos:pos + 1]),
                                        table, positions)
    return q, k_full, v_full, k_pages, v_pages, table


def _oracle(q, k_full, v_full, lens):
    """Dense masked attention over the UNPAGED buffers (fp32 softmax)."""
    B, H, D = q.shape
    Hkv = k_full.shape[2]
    G = H // Hkv
    out = np.zeros_like(q)
    for b in range(B):
        L = lens[b] + 1  # position p attends to k[0..p] inclusive
        for h in range(H):
            kh = k_full[b, :L, h // G]
            logits = (q[b, h] @ kh.T) / np.sqrt(D)
            w = np.exp(logits - logits.max())
            w /= w.sum()
            out[b, h] = w @ v_full[b, :L, h // G]
    return out


@pytest.mark.parametrize("H,Hkv", [(4, 2), (8, 2), (4, 4)])
def test_paged_decode_attention_matches_oracle(H, Hkv):
    lens = [0, 5, 16, 30]  # page boundaries at 16: first/mid/edge/crossing
    q, k_full, v_full, k_pages, v_pages, table = _paged_setup(
        4, H, Hkv, 8, page_size=16, num_pages=16, lens=lens)
    positions = jnp.asarray(lens, jnp.int32)
    ref = _oracle(q, k_full, v_full, lens)
    got = np.asarray(flash_lib.paged_decode_attention(
        jnp.asarray(q), k_pages, v_pages, table, positions, impl="xla"))
    np.testing.assert_allclose(got, ref, atol=2e-5)


@pytest.mark.parametrize("H,Hkv", [(4, 2), (8, 2)])
def test_paged_decode_pallas_matches_xla(H, Hkv):
    lens = [3, 15, 16, 40]
    q, k_full, v_full, k_pages, v_pages, table = _paged_setup(
        4, H, Hkv, 8, page_size=16, num_pages=16, lens=lens, seed=3)
    positions = jnp.asarray(lens, jnp.int32)
    a = np.asarray(flash_lib.paged_decode_attention(
        jnp.asarray(q), k_pages, v_pages, table, positions, impl="xla"))
    b = np.asarray(flash_lib.paged_decode_attention(
        jnp.asarray(q), k_pages, v_pages, table, positions, impl="pallas"))
    np.testing.assert_allclose(b, a, atol=2e-5)


def test_paged_decode_rejects_bad_gqa():
    with pytest.raises(ValueError, match="not a multiple"):
        flash_lib.paged_decode_attention(
            jnp.zeros((1, 3, 8)), jnp.zeros((4, 16, 2, 8)),
            jnp.zeros((4, 16, 2, 8)), jnp.zeros((1, 2), jnp.int32),
            jnp.zeros((1,), jnp.int32))


# ---------------------------------------------------------------------------
# greedy parity: engine through the paged cache == full training forward
# ---------------------------------------------------------------------------


def _tiny(seq_len=128):
    bundle = registry.create_model("llama_tiny", seq_len=seq_len,
                                   dtype=jnp.float32, param_dtype=jnp.float32)
    module = bundle.module
    params = module.init(jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32),
                         train=False)["params"]
    return module, params


def _reference_greedy(module, params, prompt, steps):
    """Greedy continuation via the FULL training forward (no cache): at each
    step re-run the whole sequence and take argmax at the last position."""
    toks = list(prompt)
    out = []
    for _ in range(steps):
        logits = module.apply({"params": params},
                              jnp.asarray([toks], jnp.int32), train=False)
        out.append(int(jnp.argmax(logits[0, len(toks) - 1])))
        toks.append(out[-1])
    return out


def test_engine_greedy_parity_with_page_crossings(devices):
    module, params = _tiny()
    spec = engine_lib.spec_for_module(module, num_pages=32, page_size=8)
    eng = engine_lib.ContinuousBatchingEngine(
        module, params, spec, decode_buckets=(1, 2, 4),
        prompt_buckets=(16, 32), max_model_len=64)
    rng = np.random.default_rng(7)
    # Prompt lengths straddle the 8-token page boundary; max_new pushes every
    # request across at least one page crossing mid-generation.
    reqs = [engine_lib.Request(request_id=f"r{i}",
                               prompt=rng.integers(1, 512, plen).tolist(),
                               max_new_tokens=12)
            for i, plen in enumerate([3, 8, 9, 23])]
    for r in reqs:
        eng.submit(r)
    done = {r.request_id: r for r in eng.run()}
    assert len(done) == 4
    for r in reqs:
        ref = _reference_greedy(module, params, r.prompt, r.max_new_tokens)
        assert done[r.request_id].generated == ref, r.request_id


def test_engine_no_steady_state_recompile(devices):
    module, params = _tiny()
    spec = engine_lib.spec_for_module(module, num_pages=64, page_size=8)
    eng = engine_lib.ContinuousBatchingEngine(
        module, params, spec, decode_buckets=(1, 2, 4), prompt_buckets=(16,),
        max_model_len=48)
    n = eng.warmup()
    assert eng.stats["compiles"] == n == 4  # 3 decode buckets + 1 prefill
    reqs = loadgen.generate_requests(loadgen.LoadSpec(
        num_requests=9, prompt_len_max=15, max_new_max=10, seed=1))
    for r in reqs:
        eng.submit(r)
    eng.run()
    assert len(eng.completed) == 9
    # Continuous batching swept batch sizes 1..4 and several prompt lengths;
    # every shape hit a warmed executable.
    assert eng.stats["compiles"] == n


def test_engine_eviction_recompute_preserves_tokens(devices):
    module, params = _tiny()
    # 11 usable pages of 8 tokens: two concurrent 40-token requests cannot
    # both fit -> guaranteed eviction traffic under a 4-wide batch.
    spec = engine_lib.spec_for_module(module, num_pages=12, page_size=8)
    eng = engine_lib.ContinuousBatchingEngine(
        module, params, spec, decode_buckets=(1, 2, 4), prompt_buckets=(16,),
        max_model_len=48)
    rng = np.random.default_rng(11)
    reqs = [engine_lib.Request(request_id=f"r{i}",
                               prompt=rng.integers(1, 512, 12).tolist(),
                               max_new_tokens=28)
            for i in range(4)]
    for r in reqs:
        eng.submit(r)
    done = {r.request_id: r for r in eng.run()}
    assert len(done) == 4
    assert eng.stats["evictions"] > 0  # the pressure actually materialized
    for r in reqs:
        ref = _reference_greedy(module, params, r.prompt, r.max_new_tokens)
        assert done[r.request_id].generated == ref, \
            f"{r.request_id} diverged after {done[r.request_id].evictions} evictions"


# ---------------------------------------------------------------------------
# loadgen: determinism + open-loop schedule
# ---------------------------------------------------------------------------


def test_loadgen_deterministic_and_open_loop():
    spec = loadgen.LoadSpec(num_requests=16, rate=100.0, seed=5)
    a = loadgen.generate_requests(spec)
    b = loadgen.generate_requests(spec)
    assert [r.prompt for r in a] == [r.prompt for r in b]
    assert [r.arrival_time for r in a] == [r.arrival_time for r in b]
    assert all(t >= 0 for t in (r.arrival_time for r in a))

    class _Sink:
        def __init__(self):
            self.got = []

        def submit(self, r):
            self.got.append(r.request_id)

    drv = loadgen.OpenLoopDriver(a)
    sink = _Sink()
    drv.pump(sink, now=-1.0)
    assert sink.got == []  # nothing has arrived yet
    drv.pump(sink, now=1e9)
    assert len(sink.got) == 16 and drv.remaining == 0


# ---------------------------------------------------------------------------
# checkpoint: params-only restore for serving
# ---------------------------------------------------------------------------


def test_restore_params_for_inference(tmp_path, devices):
    from pytorch_distributed_training_example_tpu.core import (
        checkpoint as ckpt_lib, mesh as mesh_lib, optim, train_loop)
    from pytorch_distributed_training_example_tpu.parallel import (
        sharding as sharding_lib)
    from pytorch_distributed_training_example_tpu.utils.config import Config

    mesh = mesh_lib.build_mesh({"data": 8})
    bundle = registry.create_model("resnet_micro", num_classes=10,
                                   image_size=32, dtype=jnp.float32,
                                   param_dtype=jnp.float32)
    tx, _ = optim.build_optimizer(Config(), steps_per_epoch=10)
    rules = sharding_lib.strategy_rules("dp", bundle.rules)
    state = train_loop.create_train_state(bundle.module, tx,
                                          bundle.input_template, mesh, rules,
                                          seed=0)
    ck = ckpt_lib.Checkpointer(str(tmp_path))
    ck.save(state, 3, extra={"epoch": 1}, block=True)

    template = jax.tree.map(lambda x: np.zeros(x.shape, x.dtype),
                            state.params)
    params, extra = ck.restore_params(template)
    assert extra == {"epoch": 1}
    assert ck.last_restored_step == 3
    for a, b in zip(jax.tree.leaves(state.params), jax.tree.leaves(params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    # All-or-nothing: a template whose shapes don't match must refuse.
    bad = jax.tree.map(lambda x: np.zeros((x.shape[0] + 1,) + x.shape[1:],
                                          x.dtype), template)
    with pytest.raises(ValueError, match="does not match this model"):
        ck.restore_params(bad)


# ---------------------------------------------------------------------------
# SIGTERM drain (fleet preemption contract for serving jobs)
# ---------------------------------------------------------------------------


def test_engine_step_admit_false_freezes_waiting_queue(devices):
    from pytorch_distributed_training_example_tpu.serve import run as serve_run
    from pytorch_distributed_training_example_tpu.utils import resilience

    module, params = _tiny()
    spec = engine_lib.spec_for_module(module, num_pages=32, page_size=8)
    eng = engine_lib.ContinuousBatchingEngine(
        module, params, spec, decode_buckets=(1, 2), prompt_buckets=(16,),
        max_model_len=32)
    rng = np.random.default_rng(3)
    for i in range(3):
        eng.submit(engine_lib.Request(
            request_id=f"r{i}", prompt=rng.integers(1, 512, 4).tolist(),
            max_new_tokens=3))
    eng.step()  # admits up to the 2 decode slots; r2 stays waiting
    assert eng.num_active == 2 and len(eng.waiting) == 1
    # Drain mode: active slots decode to completion, nothing new is admitted.
    resilience.reset()
    resilience.trip()
    try:
        assert resilience.preempted()
        outcome = serve_run.serve_loop(
            loadgen.OpenLoopDriver([]), eng, drain_timeout_s=30.0)
    finally:
        resilience.reset()
    assert outcome["preempted"] is True and outcome["drained"] is True
    assert eng.num_active == 0
    assert len(eng.waiting) == 1  # the un-admitted request was NOT started
    assert {r.request_id for r in eng.completed} == {"r0", "r1"}


def test_serve_loop_drain_timeout_bounds_shutdown(devices):
    from pytorch_distributed_training_example_tpu.serve import run as serve_run
    from pytorch_distributed_training_example_tpu.utils import resilience

    module, params = _tiny()
    spec = engine_lib.spec_for_module(module, num_pages=32, page_size=8)
    eng = engine_lib.ContinuousBatchingEngine(
        module, params, spec, decode_buckets=(1,), prompt_buckets=(16,),
        max_model_len=32)
    eng.submit(engine_lib.Request(request_id="slow", prompt=[5, 6, 7],
                                  max_new_tokens=20))
    eng.step()
    assert eng.num_active == 1
    resilience.reset()
    resilience.trip()
    try:
        # Zero budget: the loop must exit immediately, reporting the
        # sequence it had to abandon rather than hanging on it.
        outcome = serve_run.serve_loop(
            loadgen.OpenLoopDriver([]), eng, drain_timeout_s=0.0)
    finally:
        resilience.reset()
    assert outcome["preempted"] is True
    assert outcome["drained"] is False
    assert eng.num_active == 1


def test_serve_loop_without_preemption_reports_clean_exit(devices):
    from pytorch_distributed_training_example_tpu.serve import run as serve_run

    module, params = _tiny()
    spec = engine_lib.spec_for_module(module, num_pages=32, page_size=8)
    eng = engine_lib.ContinuousBatchingEngine(
        module, params, spec, decode_buckets=(1, 2), prompt_buckets=(16,),
        max_model_len=32)
    reqs = [engine_lib.Request(request_id=f"r{i}", prompt=[1 + i, 2, 3],
                               max_new_tokens=2, arrival_time=0.0)
            for i in range(3)]
    outcome = serve_run.serve_loop(loadgen.OpenLoopDriver(reqs), eng,
                                   drain_timeout_s=5.0)
    assert outcome["preempted"] is False and outcome["drained"] is True
    assert len(eng.completed) == 3
