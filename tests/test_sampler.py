"""DistributedSampler-equivalence properties (SURVEY.md §4.1)."""

import numpy as np
import pytest

from pytorch_distributed_training_example_tpu.data.sampler import ShardedSampler


@pytest.mark.parametrize("n,shards", [(100, 4), (101, 4), (8, 8), (1000, 7)])
def test_full_coverage_once_per_epoch(n, shards):
    seen = []
    lengths = set()
    for r in range(shards):
        s = ShardedSampler(n, shards, r, shuffle=True, seed=3)
        idx = s.local_indices()
        lengths.add(len(idx))
        seen.append(idx)
    assert len(lengths) == 1  # equal steps per shard
    allidx = np.concatenate(seen)
    # padded by wrap-around: every example appears at least once, at most twice
    counts = np.bincount(allidx, minlength=n)
    assert counts.min() >= 1
    assert (counts >= 1).all() and counts.sum() == len(allidx)
    extra = len(allidx) - n
    assert (counts == 2).sum() == extra


def test_drop_last():
    total = 0
    for r in range(4):
        s = ShardedSampler(103, 4, r, shuffle=False, drop_last=True)
        total += len(s.local_indices())
    assert total == 100  # 103 -> 25 per shard


def test_epoch_reshuffle_and_determinism():
    a = ShardedSampler(50, 2, 0, seed=7)
    b = ShardedSampler(50, 2, 0, seed=7)
    assert (a.local_indices() == b.local_indices()).all()
    a.set_epoch(1)
    assert not (a.local_indices() == b.local_indices()).all()
    b.set_epoch(1)
    assert (a.local_indices() == b.local_indices()).all()


def test_no_shuffle_is_strided():
    s = ShardedSampler(10, 2, 1, shuffle=False)
    assert s.local_indices().tolist() == [1, 3, 5, 7, 9]


def test_shard_id_validation():
    with pytest.raises(ValueError):
        ShardedSampler(10, 2, 2)
