"""Speculative decoding (r19): token identity is the whole contract.

A speculative engine may only change WHEN tokens are computed (K drafts
scored in one batched verify forward), never WHICH tokens come out: greedy
output with speculation on must be bit-identical to the unsped engine —
across page-boundary crossings, eviction/recompute, prefix-cache hits and
the prefill/decode disaggregation handoff. The same bar applies to the two
decode paths this PR opens: MoE blocks served via forced-dropless routing
and scan_layers checkpoints served with a stacked cache carry must match
the training forward's greedy argmax.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from pytorch_distributed_training_example_tpu.models import registry
from pytorch_distributed_training_example_tpu.serve import (
    engine as engine_lib, kv_cache, spec_decode)


def _model(name="llama_tiny", seq_len=128, **kw):
    bundle = registry.create_model(name, seq_len=seq_len,
                                   dtype=jnp.float32,
                                   param_dtype=jnp.float32, **kw)
    module = bundle.module
    params = module.init(jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32),
                         train=False)["params"]
    return module, params


def _requests(module, n, seed, plen_lo=5, plen_hi=30, new_lo=8, new_hi=40):
    rng = np.random.default_rng(seed)
    reqs = []
    for rid in range(n):
        plen = int(rng.integers(plen_lo, plen_hi))
        prompt = rng.integers(0, module.vocab_size, size=plen).tolist()
        reqs.append(engine_lib.Request(
            request_id=f"r{rid}", prompt=prompt,
            max_new_tokens=int(rng.integers(new_lo, new_hi))))
    return reqs


def _drain(eng):
    while eng.has_work:
        eng.step()
    return {r.request_id: r.generated for r in eng.completed}


def _run_engine(module, params, spec, *, spec_decode_=None, draft_len=4,
                n_req=6, seed=0, plen_lo=5, plen_hi=30, new_lo=8, new_hi=40,
                **kw):
    eng = engine_lib.ContinuousBatchingEngine(
        module, params, spec, spec_decode=spec_decode_, draft_len=draft_len,
        decode_buckets=(1, 2, 4), prompt_buckets=(16, 32),
        max_model_len=96, **kw)
    warm = eng.warmup()
    for req in _requests(module, n_req, seed, plen_lo, plen_hi,
                         new_lo, new_hi):
        eng.submit(req)
    out = _drain(eng)
    return eng, warm, out


def _jit_greedy(module, params, prompt, steps):
    """Greedy continuation via the COMPILED training forward. The oracle
    must be jitted like the engine's programs: eager op-by-op execution
    materializes bf16/fp32 intermediates XLA would fuse, and that sub-ulp
    skew can flip argmax at near-ties — a harness artifact, not an engine
    difference."""
    fwd = jax.jit(lambda t: module.apply({"params": params}, t, train=False))
    toks = list(prompt)
    out = []
    for _ in range(steps):
        logits = fwd(jnp.asarray([toks], jnp.int32))
        out.append(int(jnp.argmax(logits[0, len(toks) - 1])))
        toks.append(out[-1])
    return out


# ---------------------------------------------------------------------------
# NGramProposer unit behavior
# ---------------------------------------------------------------------------


def test_ngram_proposer_matches_repeats_and_respects_budget():
    prop = spec_decode.NGramProposer(draft_len=4)
    # trailing 3-gram [7, 8, 9] occurred earlier, followed by [1, 2, 3, 4]
    ctx = [7, 8, 9, 1, 2, 3, 4, 5, 7, 8, 9]
    assert prop._match(ctx, 4) == [1, 2, 3, 4]
    assert prop._match(ctx, 2) == [1, 2]       # budget clamps the copy
    assert prop._match(ctx, 0) == []
    assert prop._match([1, 2, 3], 4) == []     # no earlier occurrence
    # most RECENT earlier occurrence wins over an older one
    ctx2 = [5, 6, 1, 5, 6, 2, 5, 6]
    assert prop._match(ctx2, 1) == [2]


def test_ngram_proposer_rejects_bad_config():
    with pytest.raises(ValueError):
        spec_decode.NGramProposer(draft_len=4, max_ngram=1, min_ngram=2)


# ---------------------------------------------------------------------------
# token identity: speculation on == speculation off, bit for bit
# ---------------------------------------------------------------------------


def test_spec_ngram_token_identity_with_page_crossings(devices):
    module, params = _model()
    spec = engine_lib.spec_for_module(module, num_pages=64, page_size=8)
    _, _, base = _run_engine(module, params, spec)
    eng, _, sped = _run_engine(module, params, spec, spec_decode_="ngram")
    assert sped == base
    st = eng.stats
    assert st["spec_steps"] > 0
    assert 0 <= st["accepted_tokens"] <= st["draft_tokens"]
    hist = sum(st[f"spec_accept_{n}"] for n in range(5))
    assert hist > 0 and st["accepted_tokens"] == sum(
        n * st[f"spec_accept_{n}"] for n in range(5))


def test_spec_draft_model_token_identity_and_self_draft_acceptance(devices):
    module, params = _model()
    spec = engine_lib.spec_for_module(module, num_pages=64, page_size=8)
    _, _, base = _run_engine(module, params, spec)
    # Self-drafting with the TARGET model: every draft is the target's own
    # argmax, so the verify must accept all of them — any rejection would
    # mean the draft catch-up programs diverge from the target decode.
    prop = spec_decode.DraftModelProposer(module, params, draft_len=4)
    eng, _, sped = _run_engine(module, params, spec, spec_decode_=prop)
    assert sped == base
    st = eng.stats
    assert st["draft_tokens"] > 0
    assert st["accepted_tokens"] == st["draft_tokens"]


def test_spec_token_identity_under_eviction(devices):
    module, params = _model()
    # Starve the pool so decode-time page growth forces evictions.
    spec = engine_lib.spec_for_module(module, num_pages=20, page_size=8)
    kw = dict(n_req=5, seed=3, plen_lo=20, plen_hi=30, new_lo=30, new_hi=50)
    a, _, base = _run_engine(module, params, spec, **kw)
    b, _, sped = _run_engine(module, params, spec, spec_decode_="ngram", **kw)
    assert b.stats["evictions"] > 0
    assert sped == base


def test_spec_token_identity_with_prefix_cache(devices):
    module, params = _model()
    spec = engine_lib.spec_for_module(module, num_pages=96, page_size=8)
    rng = np.random.default_rng(11)
    shared = rng.integers(0, module.vocab_size, size=16).tolist()

    def submit_all(eng):
        eng.warmup()
        for rid in range(5):
            tail = rng.integers(0, module.vocab_size,
                                size=int(rng.integers(4, 12))).tolist()
            eng.submit(engine_lib.Request(
                request_id=f"r{rid}", prompt=shared + tail,
                max_new_tokens=int(rng.integers(10, 30))))
        return _drain(eng)

    kw = dict(decode_buckets=(1, 2, 4), prompt_buckets=(16, 32),
              max_model_len=96, prefix_cache=True)
    rng = np.random.default_rng(11)
    shared = rng.integers(0, module.vocab_size, size=16).tolist()
    base = submit_all(engine_lib.ContinuousBatchingEngine(
        module, params, spec, **kw))
    rng = np.random.default_rng(11)
    shared = rng.integers(0, module.vocab_size, size=16).tolist()
    eng = engine_lib.ContinuousBatchingEngine(
        module, params, spec, spec_decode="ngram", **kw)
    sped = submit_all(eng)
    assert eng.stats["cached_tokens"] > 0  # the prefix cache actually hit
    assert sped == base


def test_spec_token_identity_through_disagg_handoff(devices):
    module, params = _model()

    def pair(spec_decode_):
        kw = dict(decode_buckets=(1, 2, 4), prompt_buckets=(16, 32),
                  max_model_len=96)
        spec_p = engine_lib.spec_for_module(module, num_pages=48, page_size=8)
        spec_d = engine_lib.spec_for_module(module, num_pages=48, page_size=8)
        return engine_lib.DisaggregatedServe(
            engine_lib.ContinuousBatchingEngine(
                module, params, spec_p, role="prefill", **kw),
            engine_lib.ContinuousBatchingEngine(
                module, params, spec_d, role="decode",
                spec_decode=spec_decode_, **kw))

    base = pair(None)
    base.warmup()
    for req in _requests(module, 5, 4):
        base.submit(req)
    base_out = {r.request_id: r.generated for r in base.run()}

    sped = pair("ngram")
    sped.warmup()
    for req in _requests(module, 5, 4):
        sped.submit(req)
    sped_out = {r.request_id: r.generated for r in sped.run()}
    assert sped.stats["handoffs_out"] > 0
    assert sped.stats["spec_steps"] > 0
    assert sped_out == base_out


def test_prefill_role_engine_never_speculates(devices):
    module, params = _model()
    spec = engine_lib.spec_for_module(module, num_pages=32, page_size=8)
    eng = engine_lib.ContinuousBatchingEngine(
        module, params, spec, role="prefill", spec_decode="ngram",
        decode_buckets=(1, 2), prompt_buckets=(16, 32), max_model_len=96)
    assert eng.proposer is None


def test_spec_rejects_unknown_mode(devices):
    module, params = _model()
    spec = engine_lib.spec_for_module(module, num_pages=32, page_size=8)
    with pytest.raises(ValueError):
        engine_lib.ContinuousBatchingEngine(
            module, params, spec, spec_decode="nope",
            decode_buckets=(1, 2), prompt_buckets=(16, 32))
    with pytest.raises(ValueError):
        engine_lib.ContinuousBatchingEngine(
            module, params, spec, spec_decode="ngram", draft_len=0,
            decode_buckets=(1, 2), prompt_buckets=(16, 32))


# ---------------------------------------------------------------------------
# compile discipline: verify programs are warmed, steady state stays flat
# ---------------------------------------------------------------------------


def test_spec_no_steady_state_recompile(devices):
    module, params = _model()
    spec = engine_lib.spec_for_module(module, num_pages=64, page_size=8)
    eng = engine_lib.ContinuousBatchingEngine(
        module, params, spec, spec_decode="ngram", draft_len=4,
        decode_buckets=(1, 2, 4), prompt_buckets=(16, 32), max_model_len=96)
    n = eng.warmup()
    # decode(3) + prefill(2) + verify(3 batch buckets x 3 draft buckets)
    assert n == 3 + 2 + 9
    assert eng.stats["compiles"] == n
    for req in _requests(module, 6, 0):
        eng.submit(req)
    _drain(eng)
    assert eng.stats["compiles"] == n, "speculation recompiled in steady state"


def test_spec_draft_model_no_steady_state_recompile(devices):
    module, params = _model()
    spec = engine_lib.spec_for_module(module, num_pages=64, page_size=8)
    prop = spec_decode.DraftModelProposer(module, params, draft_len=4)
    eng = engine_lib.ContinuousBatchingEngine(
        module, params, spec, spec_decode=prop, draft_len=4,
        decode_buckets=(1, 2, 4), prompt_buckets=(16, 32), max_model_len=96)
    n = eng.warmup()
    assert eng.stats["compiles"] == n
    for req in _requests(module, 6, 0):
        eng.submit(req)
    _drain(eng)
    assert eng.stats["compiles"] == n, "draft proposer recompiled mid-run"


def test_spec_rollback_returns_overshoot_pages(devices):
    module, params = _model()
    spec = engine_lib.spec_for_module(module, num_pages=64, page_size=8)
    eng, _, _ = _run_engine(module, params, spec, spec_decode_="ngram")
    # Every request retired; every page (minus the reserved scratch page)
    # must be back in the pool — rollback may not leak overshoot pages.
    assert eng.pool.num_free == spec.num_pages - kv_cache.RESERVED_PAGES


# ---------------------------------------------------------------------------
# MoE decode: forced-dropless serving == dropless training forward
# ---------------------------------------------------------------------------


def test_moe_decode_parity_with_dropless_training_forward(devices):
    module, params = _model("llama_moe_tiny")
    spec = engine_lib.spec_for_module(module, num_pages=64, page_size=8)
    eng, _, out = _run_engine(module, params, spec, n_req=3, seed=2)
    # Decode forces dropless routing whatever the checkpoint trained with
    # (capacity-dropped dispatch is non-causal), so the oracle is the same
    # weights applied through the dropless training path.
    oracle = module.copy(moe_dispatch_impl="dropless")
    for r in eng.completed:
        ref = _jit_greedy(oracle, params, r.prompt, len(r.generated))
        assert r.generated == ref, r.request_id


def test_moe_spec_decode_token_identity(devices):
    module, params = _model("llama_moe_tiny")
    spec = engine_lib.spec_for_module(module, num_pages=64, page_size=8)
    _, _, base = _run_engine(module, params, spec, n_req=4, seed=1)
    eng, _, sped = _run_engine(module, params, spec, spec_decode_="ngram",
                               n_req=4, seed=1)
    assert sped == base
    assert eng.stats["spec_steps"] > 0


# ---------------------------------------------------------------------------
# scan_layers decode: stacked cache carry == unrolled == training forward
# ---------------------------------------------------------------------------


def test_scan_layers_decode_parity_and_stacked_cache(devices):
    module, params = _model()
    scanned = module.copy(scan_layers=True)
    # Scanned params are stacked [L, ...]; restack the unrolled init so both
    # engines serve identical weights.
    stacked = {"blocks": {"block": jax.tree.map(
        lambda *xs: jnp.stack(xs),
        *(params[f"block_{i}"] for i in range(module.num_layers)))}}
    sparams = {**{k: v for k, v in params.items()
                  if not k.startswith("block_")}, **stacked}
    spec = engine_lib.spec_for_module(scanned, num_pages=64, page_size=8)
    eng = engine_lib.ContinuousBatchingEngine(
        scanned, sparams, spec, decode_buckets=(1, 2), prompt_buckets=(16,),
        max_model_len=64)
    # The cache pytree is ONE stacked [L, P, page_size, Hkv, D] carry per
    # K/V pool, not per-layer leaves.
    leaves = jax.tree.leaves(eng.cache)
    assert len(leaves) == 2
    assert all(leaf.shape[0] == module.num_layers and leaf.ndim == 5
               for leaf in leaves)
    eng.warmup()
    for req in _requests(scanned, 3, 5, plen_hi=14, new_hi=20):
        eng.submit(req)
    _drain(eng)
    for r in eng.completed:
        ref = _jit_greedy(scanned, sparams, r.prompt, len(r.generated))
        assert r.generated == ref, r.request_id


def test_scan_layers_spec_decode_token_identity(devices):
    module, params = _model()
    scanned = module.copy(scan_layers=True)
    stacked = {"blocks": {"block": jax.tree.map(
        lambda *xs: jnp.stack(xs),
        *(params[f"block_{i}"] for i in range(module.num_layers)))}}
    sparams = {**{k: v for k, v in params.items()
                  if not k.startswith("block_")}, **stacked}
    spec = engine_lib.spec_for_module(scanned, num_pages=64, page_size=8)
    _, _, base = _run_engine(scanned, sparams, spec, n_req=4, seed=6)
    eng, _, sped = _run_engine(scanned, sparams, spec, spec_decode_="ngram",
                               n_req=4, seed=6)
    assert sped == base
    assert eng.stats["spec_steps"] > 0
