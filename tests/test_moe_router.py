"""Compact-index routing stats, router precision policy, fused router.

The r8 router round replaced the fp32 one-hot bookkeeping in
parallel/moe.py with shared compact-index stats (``routing_stats``) and
added two opt-in knobs (``router_dtype=bf16``, ``router_impl="fused"``).
The routing DECISION is contractually unchanged, so every test here pins
the new paths to the legacy formulations: the one-hot cumsum position
chain (bit-for-bit), the one-hot aux/z/telemetry reductions (exact), the
plain-XLA softmax/top-k chain (fused kernel, including tie order), and
fp32 numerics (bf16 router, tolerance-bounded like combine_dtype).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pytorch_distributed_training_example_tpu.ops import fused_router as fr
from pytorch_distributed_training_example_tpu.parallel import moe as moe_lib

D = 16


def _x(seed=7, b=2, t=32):
    return jnp.asarray(np.random.RandomState(seed).randn(b, t, D), jnp.float32)


def _block(impl="gather", E=4, k=2, cf=2.0, **kw):
    return moe_lib.MoEBlock(num_experts=E, ffn_dim=32, top_k=k,
                            capacity_factor=cf, dispatch_impl=impl, **kw)


def _onehot_positions(expert_idx, E, capacity):
    """The legacy fp32 one-hot cumsum position chain (the r7 formulation
    routing_stats replaced): flatten (choice, token) in priority order,
    cumulative count per expert = position in that expert's queue."""
    T, k = expert_idx.shape
    e_flat = expert_idx.T.reshape(-1)                         # [kT], k-major
    oh = jax.nn.one_hot(e_flat, E, dtype=jnp.float32)         # [kT, E]
    pos_flat = (jnp.cumsum(oh, axis=0) - oh)[
        jnp.arange(e_flat.shape[0]), e_flat]                  # [kT]
    pos = pos_flat.astype(jnp.int32).reshape(k, T).T          # [T, k]
    return pos, pos < capacity


@pytest.mark.parametrize("E,k,capacity", [
    (4, 2, 9),     # mild overflow
    (4, 2, 1000),  # no overflow
    (4, 1, 5),     # Switch top-1
    (8, 2, 3),     # tiny capacity, many experts
])
def test_routing_stats_matches_onehot_cumsum(E, k, capacity):
    """stats.pos / stats.within_cap are bit-identical to the one-hot cumsum
    chain, drop for drop — including the priority order (earlier tokens
    first, k=0 choices before k=1)."""
    T = 37
    idx = jnp.asarray(np.random.RandomState(0).randint(0, E, (T, k)),
                      jnp.int32)
    stats = moe_lib.routing_stats(idx, E, capacity)
    ref_pos, ref_within = _onehot_positions(idx, E, capacity)
    np.testing.assert_array_equal(np.asarray(stats.pos), np.asarray(ref_pos))
    np.testing.assert_array_equal(np.asarray(stats.within_cap),
                                  np.asarray(ref_within))
    counts_ref = np.bincount(np.asarray(idx).reshape(-1), minlength=E)
    np.testing.assert_array_equal(np.asarray(stats.counts), counts_ref)


@pytest.mark.parametrize("impl", ["gather", "sort", "einsum"])
@pytest.mark.parametrize("k,cf", [(1, 1.0), (2, 2.0), (2, 0.5)])
def test_block_losses_match_onehot_reference(impl, k, cf):
    """aux loss, z-loss, drop fraction and load entropy from the compact
    stats == the legacy one-hot reductions recomputed here from the same
    routing decision."""
    E = 4
    block = _block(impl, E=E, k=k, cf=cf)
    x = _x(seed=3)
    variables = {"params": block.init(jax.random.PRNGKey(0), x)["params"]}
    out, coll = block.apply(variables, x,
                            mutable=["losses", "telemetry"])
    assert np.isfinite(np.asarray(out)).all()
    sown = {name: float(v[0]) for name, v in
            {**coll["losses"], **coll["telemetry"]}.items()}

    # Recompute the routing decision + legacy one-hot bookkeeping.
    tokens = x.reshape(-1, D)
    T = tokens.shape[0]
    logits = tokens @ variables["params"]["router"]["kernel"]
    probs = jax.nn.softmax(logits, axis=-1)
    _, expert_idx = jax.lax.top_k(probs, k)
    capacity = max(int(cf * T * k / E), 1)
    _, within = _onehot_positions(expert_idx, E, capacity)

    me = probs.mean(0)
    ce = jax.nn.one_hot(expert_idx[:, 0], E, dtype=jnp.float32).mean(0)
    aux_ref = float(E * jnp.sum(me * ce)) * block.aux_loss_weight
    z_ref = float(jnp.mean(
        jax.scipy.special.logsumexp(logits, axis=-1) ** 2)
    ) * block.z_loss_weight
    drop_ref = 1.0 - float(jnp.sum(within)) / (T * k)
    load = jax.nn.one_hot(expert_idx, E, dtype=jnp.float32).sum((0, 1))
    load = load / (T * k)
    ent_ref = float(-jnp.sum(load * jnp.log(load + 1e-9)) / np.log(E))

    np.testing.assert_allclose(sown["moe_aux_loss"], aux_ref, rtol=1e-6)
    np.testing.assert_allclose(sown["moe_z_loss"], z_ref, rtol=1e-6)
    np.testing.assert_allclose(sown["moe_drop_fraction"], drop_ref,
                               rtol=0, atol=1e-7)
    np.testing.assert_allclose(sown["router_load_entropy"], ent_ref,
                               rtol=1e-5)


def test_losses_identical_across_dispatch_impls():
    """The sown losses/telemetry come from the shared stats, so they are
    the same numbers under all three dispatch formulations."""
    x = _x(seed=5)
    ref = None
    for impl in ("gather", "sort", "einsum"):
        block = _block(impl, cf=0.75)
        variables = {"params": block.init(jax.random.PRNGKey(0), x)["params"]}
        _, coll = block.apply(variables, x, mutable=["losses", "telemetry"])
        vals = jax.tree.map(float, {**coll["losses"], **coll["telemetry"]})
        if ref is None:
            ref = vals
        else:
            assert vals == ref, f"{impl} diverged: {vals} vs {ref}"


@pytest.mark.parametrize("T,E,k", [(64, 8, 2), (37, 4, 1), (100, 16, 2)])
def test_fused_router_matches_reference_chain(T, E, k):
    """fused_router (interpret mode on CPU) == the plain-XLA fp32 chain:
    identical expert indices, matching gates/logsumexp/mean-probs."""
    logits = jnp.asarray(np.random.RandomState(1).randn(T, E) * 3.0,
                         jnp.float32)
    gate, idx, lse, me = fr.fused_router(logits, k)
    probs = jax.nn.softmax(logits, axis=-1)
    g_ref, i_ref = jax.lax.top_k(probs, k)
    g_ref = g_ref / jnp.maximum(g_ref.sum(-1, keepdims=True), 1e-9)
    np.testing.assert_array_equal(np.asarray(idx), np.asarray(i_ref))
    np.testing.assert_allclose(np.asarray(gate), np.asarray(g_ref),
                               rtol=1e-6, atol=1e-7)
    np.testing.assert_allclose(
        np.asarray(lse),
        np.asarray(jax.scipy.special.logsumexp(logits, axis=-1)),
        rtol=1e-6, atol=1e-6)
    np.testing.assert_allclose(np.asarray(me), np.asarray(probs.mean(0)),
                               rtol=1e-6, atol=1e-7)


def test_fused_router_tie_breaking():
    """Exact ties (duplicated logit columns) must resolve to the SAME
    expert ids as lax.top_k (first occurrence wins) — otherwise fused vs
    reference route different tokens and the A/B is meaningless."""
    base = jnp.asarray(np.random.RandomState(2).randn(32, 3), jnp.float32)
    logits = jnp.concatenate([base, base[:, :2], base[:, :1]], axis=-1)
    _, idx, _, _ = fr.fused_router(logits, 2)
    _, i_ref = jax.lax.top_k(jax.nn.softmax(logits, -1), 2)
    np.testing.assert_array_equal(np.asarray(idx), np.asarray(i_ref))


def test_fused_block_matches_reference_block():
    """MoEBlock(router_impl='fused') == reference block: outputs, grads,
    and sown losses, through the custom_vjp backward."""
    x = _x(seed=9)
    ref = _block("gather", cf=1.0)
    fus = _block("gather", cf=1.0, router_impl="fused")
    variables = {"params": ref.init(jax.random.PRNGKey(0), x)["params"]}

    out_r, c_r = ref.apply(variables, x, mutable=["losses", "telemetry"])
    out_f, c_f = fus.apply(variables, x, mutable=["losses", "telemetry"])
    np.testing.assert_allclose(np.asarray(out_r), np.asarray(out_f),
                               rtol=1e-6, atol=1e-7)
    for (n, a), (_, b) in zip(
            sorted({**c_r["losses"], **c_r["telemetry"]}.items()),
            sorted({**c_f["losses"], **c_f["telemetry"]}.items())):
        np.testing.assert_allclose(float(a[0]), float(b[0]), rtol=1e-6,
                                   err_msg=n)

    def loss(block, p, xx):
        out, coll = block.apply({"params": p}, xx,
                                mutable=["losses", "telemetry"])
        return (jnp.sum(out ** 2)
                + sum(v[0] for v in coll["losses"].values()))

    g_r = jax.grad(lambda p: loss(ref, p, x))(variables["params"])
    g_f = jax.grad(lambda p: loss(fus, p, x))(variables["params"])
    for a, b in zip(jax.tree.leaves(g_r), jax.tree.leaves(g_f)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-5, atol=1e-6)


def test_bf16_router_parity():
    """router_dtype=bf16 changes ONLY the logits-matmul operand precision
    (fp32 accumulation + fp32 softmax/top-k stay): with an unchanged
    routing decision the output tracks fp32 to bf16 resolution, like the
    combine_dtype contract."""
    x = _x(seed=12)
    ref = _block("sort", cf=2.0)
    b16 = _block("sort", cf=2.0, router_dtype=jnp.bfloat16)
    variables = {"params": ref.init(jax.random.PRNGKey(0), x)["params"]}

    # Guard the premise: this seed's routing decisions are precision-stable
    # (no top-k flip between fp32 and bf16 logits), so the comparison
    # below measures precision, not routing churn.
    tokens = x.reshape(-1, D)
    kernel = variables["params"]["router"]["kernel"]
    lg32 = tokens @ kernel
    lg16 = jax.lax.dot_general(
        tokens.astype(jnp.bfloat16), kernel.astype(jnp.bfloat16),
        (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32)
    _, i32 = jax.lax.top_k(lg32, 2)
    _, i16 = jax.lax.top_k(lg16, 2)
    np.testing.assert_array_equal(np.asarray(i32), np.asarray(i16))

    a = np.asarray(ref.apply(variables, x))
    b = np.asarray(b16.apply(variables, x))
    np.testing.assert_allclose(a, b, rtol=3e-2, atol=3e-2)

    def loss(block, p):
        return jnp.sum(block.apply({"params": p}, x) ** 2)

    g_ref = jax.grad(lambda p: loss(ref, p))(variables["params"])
    g_b16 = jax.grad(lambda p: loss(b16, p))(variables["params"])
    for ga, gb in zip(jax.tree.leaves(g_ref), jax.tree.leaves(g_b16)):
        np.testing.assert_allclose(np.asarray(ga), np.asarray(gb),
                                   rtol=5e-2, atol=5e-2)


def test_router_defaults_are_exact_contract():
    """Defaults unchanged until the chip A/B: fp32 router, reference impl,
    and the registry maps the string knobs onto them."""
    from pytorch_distributed_training_example_tpu.models import registry

    assert moe_lib.MoEBlock.router_dtype is None
    assert moe_lib.MoEBlock.router_impl == "reference"
    bundle = registry.create_model("llama_moe_tiny", seq_len=32,
                                   dtype=jnp.float32,
                                   param_dtype=jnp.float32)
    assert bundle.module.moe_router_dtype is None
    assert bundle.module.moe_router_impl == "reference"
    b2 = registry.create_model("llama_moe_tiny", seq_len=32,
                               dtype=jnp.float32, param_dtype=jnp.float32,
                               moe_router_dtype="bf16",
                               moe_router_impl="fused")
    assert b2.module.moe_router_dtype == jnp.bfloat16
    assert b2.module.moe_router_impl == "fused"
    with pytest.raises(ValueError):
        registry.create_model("llama_moe_tiny", seq_len=32,
                              dtype=jnp.float32, param_dtype=jnp.float32,
                              moe_router_impl="bogus")
