"""Elastic resume (ISSUE r11): batch policies, stream remap, supervisor shrink.

The pure layer (utils/elastic.py, the sampler stream helpers) is tested
directly at world sizes 1/2/4; the supervisor tests run the real launch.py
restart loop against a jax-free fake job, in the style of the
test_resilience.py supervisor tests — the elastic drill with the *real*
trainer lives in the dryrun gauntlet (__graft_entry__.py leg 11).
"""

import importlib.util
import json
import os
import socket
import subprocess
import sys

import numpy as np
import pytest

from pytorch_distributed_training_example_tpu.data import sampler as sampler_lib
from pytorch_distributed_training_example_tpu.utils import elastic

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _launch_module():
    spec = importlib.util.spec_from_file_location(
        "launch_under_test", os.path.join(REPO, "launch.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


# ---------------------------------------------------------------------------
# rescale: both policies across world sizes 1/2/4
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("old,new,accum,want_accum", [
    (2, 1, 1, 2), (4, 2, 1, 2), (4, 1, 1, 4),
    (2, 4, 2, 1), (1, 2, 2, 1), (4, 4, 2, 2),
])
def test_keep_global_batch_scales_accum(old, new, accum, want_accum):
    plan = elastic.rescale(elastic.KEEP_GLOBAL_BATCH, old_world=old,
                           new_world=new, global_batch=16, grad_accum=accum)
    assert plan.global_batch_size == 16  # the defining property
    assert plan.grad_accum_steps == want_accum
    assert plan.lr_scale == 1.0
    # Total microbatch work per update is conserved (or rounded up).
    assert plan.grad_accum_steps * new >= accum * old
    assert 16 % (new * plan.grad_accum_steps) == 0
    assert "elastic [keep_global_batch]" in plan.describe()


def test_keep_global_batch_non_integral_ratio_rounds_up():
    plan = elastic.rescale(elastic.KEEP_GLOBAL_BATCH, old_world=3,
                           new_world=2, global_batch=12)
    assert plan.global_batch_size == 12
    assert plan.grad_accum_steps == 2  # ceil(3/2), and 12 % (2*2) == 0
    assert "rounded up" in plan.note


@pytest.mark.parametrize("old,new,want_gb,want_lr", [
    (2, 1, 8, 0.5), (4, 2, 8, 0.5), (4, 1, 4, 0.25),
    (1, 2, 32, 2.0), (2, 4, 32, 2.0),
])
def test_scale_lr_linear_scaling(old, new, want_gb, want_lr):
    plan = elastic.rescale(elastic.SCALE_LR, old_world=old, new_world=new,
                           global_batch=16)
    assert plan.global_batch_size == want_gb
    assert plan.grad_accum_steps == 1
    assert plan.lr_scale == want_lr
    # Per-device batch is preserved exactly.
    assert want_gb // new == 16 // old


def test_rescale_rejects_bad_inputs():
    with pytest.raises(ValueError, match="unknown elastic policy"):
        elastic.rescale("frobnicate", old_world=2, new_world=1,
                        global_batch=16)
    with pytest.raises(ValueError, match="world sizes"):
        elastic.rescale(elastic.SCALE_LR, old_world=0, new_world=1,
                        global_batch=16)
    with pytest.raises(ValueError, match="not divisible"):
        elastic.rescale(elastic.KEEP_GLOBAL_BATCH, old_world=4, new_world=2,
                        global_batch=10)
    with pytest.raises(ValueError, match="not divisible"):
        elastic.rescale(elastic.SCALE_LR, old_world=3, new_world=2,
                        global_batch=16)


# ---------------------------------------------------------------------------
# step-offset / step-count remap: exact sample positions only
# ---------------------------------------------------------------------------


def test_remap_step_offset_preserves_sample_position():
    assert elastic.remap_step_offset(6, 16, 8) == 12
    assert elastic.remap_step_offset(6, 16, 32) == 3
    assert elastic.remap_step_offset(0, 16, 8) == 0
    assert elastic.remap_step_count(8, 16, 4) == 32


def test_remap_step_offset_rejects_partial_batches():
    with pytest.raises(ValueError, match="sample-exact"):
        elastic.remap_step_offset(3, 16, 32)


# ---------------------------------------------------------------------------
# sampler stream invariance: the property that makes resume sample-exact
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("num_examples", [64, 70])
def test_global_sample_stream_world_size_invariant(num_examples):
    ref = sampler_lib.global_sample_stream(num_examples, 16, 1, seed=3)
    for shards in (2, 4):
        got = sampler_lib.global_sample_stream(num_examples, 16, shards,
                                               seed=3)
        np.testing.assert_array_equal(got, ref)
    # Same number of full batches for every world size (drop_last math).
    assert len(ref) == (num_examples // 16) * 16


def test_global_sample_stream_epochs_differ():
    a = sampler_lib.global_sample_stream(64, 16, 1, seed=3, epoch=0)
    b = sampler_lib.global_sample_stream(64, 16, 1, seed=3, epoch=1)
    assert not np.array_equal(a, b)


def test_shard_batch_stream_partitions_each_global_batch():
    per_shard = sampler_lib.shard_batch_stream(64, 16, 2, 0, seed=3)
    other = sampler_lib.shard_batch_stream(64, 16, 2, 1, seed=3)
    flat = sampler_lib.global_sample_stream(64, 16, 1, seed=3)
    assert len(per_shard) == len(other) == 4
    for b, (mine, theirs) in enumerate(zip(per_shard, other)):
        assert len(mine) == len(theirs) == 8
        union = np.sort(np.concatenate([mine, theirs]))
        np.testing.assert_array_equal(union, np.sort(flat[b * 16:(b + 1) * 16]))


# ---------------------------------------------------------------------------
# recorded geometry -> plan
# ---------------------------------------------------------------------------


def test_plan_from_record_builds_plan_on_world_change():
    recorded = {"mesh_shape": {"data": 2, "fsdp": 1}, "global_batch_size": 16,
                "grad_accum": 1}
    plan = elastic.plan_from_record(recorded,
                                    policy=elastic.KEEP_GLOBAL_BATCH,
                                    new_world=1, fallback_global_batch=999)
    assert plan is not None
    assert (plan.old_world, plan.new_world) == (2, 1)
    assert plan.global_batch_size == 16 and plan.grad_accum_steps == 2


def test_plan_from_record_none_when_unchanged_or_unrecorded():
    recorded = {"mesh_shape": {"data": 2, "fsdp": 2}}
    assert elastic.plan_from_record(recorded, policy=elastic.SCALE_LR,
                                    new_world=4,
                                    fallback_global_batch=16) is None
    assert elastic.plan_from_record({}, policy=elastic.SCALE_LR, new_world=2,
                                    fallback_global_batch=16) is None


def test_recorded_world_reads_mesh_shape_and_fallback():
    assert elastic.recorded_world({"mesh_shape": {"data": 2, "fsdp": 2,
                                                  "model": 2}}) == 4
    assert elastic.recorded_world({"world": 3}) == 3
    assert elastic.recorded_world({}) is None


# ---------------------------------------------------------------------------
# dead-host protocol: append-only jsonl, corruption-tolerant reads
# ---------------------------------------------------------------------------


def test_dead_hosts_round_trip_tolerates_corruption(tmp_path):
    assert elastic.read_dead_hosts(str(tmp_path)) == set()
    elastic.record_dead_host(str(tmp_path), 1, world=2, step=5, reason="test")
    elastic.record_dead_host(str(tmp_path), 0, world=1)
    path = os.path.join(str(tmp_path), elastic.DEAD_HOSTS_FILE)
    with open(path, "a") as fh:
        fh.write('{"host": trunc')  # a host died mid-write
    assert elastic.read_dead_hosts(str(tmp_path)) == {0, 1}
    rows = [json.loads(line) for line
            in open(path).read().splitlines()[:2]]
    assert rows[0] == {"host": 1, "world": 2, "step": 5, "reason": "test"}


def test_returned_hosts_cancel_dead_records(tmp_path):
    d = str(tmp_path)
    assert elastic.effective_dead_hosts(d) == set()
    elastic.record_dead_host(d, 1, world=2, reason="kill")
    elastic.record_dead_host(d, 3, world=2, reason="kill")
    assert elastic.effective_dead_hosts(d) == {1, 3}
    elastic.record_host_return(d, 1, reason="repaired")
    assert elastic.read_returned_hosts(d) == {1}
    assert elastic.effective_dead_hosts(d) == {3}
    # Count-based, not set difference: die -> return -> die again is dead.
    elastic.record_dead_host(d, 1, world=2, reason="kill again")
    assert elastic.effective_dead_hosts(d) == {1, 3}
    # read_dead_hosts keeps its historical "ever died" semantics.
    assert elastic.read_dead_hosts(d) == {1, 3}


def test_returned_hosts_tolerate_torn_tail_and_read_errors(tmp_path):
    """The grow-side ledger gets the same degradation contract as the dead
    side: a torn tail (host died mid-append) skips the bad line, and an
    OSError on open (ESTALE/EIO, not just a missing file) degrades to "no
    records seen" — never a crash in the supervisor's planning path."""
    d = str(tmp_path)
    elastic.record_host_return(d, 1, reason="repaired")
    elastic.record_host_return(d, 4, reason="repaired")
    path = os.path.join(d, elastic.RETURNED_HOSTS_FILE)
    with open(path, "a") as fh:
        fh.write('{"host": 9, "reas')  # torn tail: no newline, no close brace
    assert elastic.read_returned_hosts(d) == {1, 4}
    # Torn records must not cancel dead ones they never finished recording.
    elastic.record_dead_host(d, 9, reason="kill")
    assert elastic.effective_dead_hosts(d) == {9}
    # Non-ENOENT OSError (IsADirectoryError here) degrades to empty, same
    # as the dead-host reader.
    bad = tmp_path / "bad"
    bad.mkdir()
    (bad / elastic.RETURNED_HOSTS_FILE).mkdir()
    assert elastic.read_returned_hosts(str(bad)) == set()


# ---------------------------------------------------------------------------
# mesh: elastic_resolve degrades pinned axes instead of refusing
# ---------------------------------------------------------------------------


def test_elastic_resolve_degrades_fixed_axes(caplog):
    mesh_lib = pytest.importorskip(
        "pytorch_distributed_training_example_tpu.core.mesh")
    cfg = mesh_lib.MeshConfig(fsdp=4)
    with pytest.raises(ValueError):
        cfg.resolve(2)
    with caplog.at_level("WARNING", logger="pdtx"):
        shape = cfg.elastic_resolve(2)
    assert shape == (1, 2, 1, 1, 1, 1)
    assert any("degraded axes" in r.message for r in caplog.records)
    # When the strict resolve works, elastic_resolve is a pass-through.
    assert mesh_lib.MeshConfig().elastic_resolve(4) == (4, 1, 1, 1, 1, 1)
    assert cfg.elastic_resolve(8) == (2, 4, 1, 1, 1, 1)


# ---------------------------------------------------------------------------
# launch.py helpers (imported from the file, not via subprocess)
# ---------------------------------------------------------------------------


def test_parse_elastic_and_find_flag():
    launch = _launch_module()
    assert launch.parse_elastic("2") == (2, 1 << 30)
    assert launch.parse_elastic("1:4") == (1, 4)
    for junk in ("0", "4:2", "0:3"):
        with pytest.raises(ValueError):
            launch.parse_elastic(junk)
    cmd = ["main.py", "--checkpoint-dir", "/a", "--checkpoint-dir", "/b"]
    assert launch.find_flag(cmd, "--checkpoint-dir") == "/b"
    assert launch.find_flag(cmd, "--nope") is None


def test_coordinator_port_falls_back_when_held(capsys):
    launch = _launch_module()
    with socket.socket() as held:
        held.bind(("", 0))
        held.listen(1)
        taken = held.getsockname()[1]
        assert not launch.probe_port(taken)
        port = launch.coordinator_port(taken)
        assert port != taken
        assert launch.probe_port(port)
    assert "not bindable" in capsys.readouterr().err


# ---------------------------------------------------------------------------
# launch.py supervisor: elastic shrink loop (jax-free fake job)
# ---------------------------------------------------------------------------


def _write_elastic_script(tmp_path):
    """Fake gang member: on the first attempt the highest rank records itself
    dead and dies abruptly with the host-loss code; the relaunched attempt
    writes what world it came back at."""
    script = tmp_path / "fake_elastic_job.py"
    script.write_text(
        "import json, os, sys, time\n"
        "args = sys.argv[1:]\n"
        "ckdir = args[args.index('--checkpoint-dir') + 1]\n"
        "os.makedirs(ckdir, exist_ok=True)\n"
        "if '--resume' in args:\n"
        "    with open(os.path.join(ckdir, 'resumed.txt'), 'w') as fh:\n"
        "        fh.write(os.environ.get('NUM_PROCESSES', '?') + '|'\n"
        "                 + ' '.join(args))\n"
        "    sys.exit(0)\n"
        "rank = int(os.environ.get('PROCESS_ID', '0'))\n"
        "world = int(os.environ.get('NUM_PROCESSES', '1'))\n"
        "if rank == world - 1 and world > 1:\n"
        "    with open(os.path.join(ckdir, 'dead_hosts.jsonl'), 'a') as fh:\n"
        "        fh.write(json.dumps({'host': rank, 'world': world}) + '\\n')\n"
        "    os._exit(76)\n"
        "time.sleep(30)\n"  # survivor blocks 'in a collective' until torn down
        "sys.exit(1)\n")
    return script


def _run_launch(tmp_path, script, *launch_flags):
    ckdir = tmp_path / "ck"
    res = subprocess.run(
        [sys.executable, "launch.py", "--nprocs", "2",
         "--restart-policy", "on-failure", "--max-restarts", "2",
         "--restart-backoff", "0.05", "--log-dir", str(tmp_path),
         *launch_flags, "--", str(script), "--checkpoint-dir", str(ckdir)],
        cwd=REPO, capture_output=True, text=True, timeout=120)
    return res, ckdir


def test_supervisor_shrinks_world_after_host_loss(tmp_path):
    script = _write_elastic_script(tmp_path)
    res, ckdir = _run_launch(tmp_path, script, "--elastic", "1")
    assert res.returncode == 0, res.stderr
    assert "elastic — host(s) [1] lost, relaunching at world size 1" \
        in res.stderr, res.stderr
    world, argv = (ckdir / "resumed.txt").read_text().split("|", 1)
    assert world == "1"  # relaunched one host smaller
    assert "--resume auto" in argv


def _write_grow_script(tmp_path):
    """Fake gang member for the shrink-then-grow drill. Attempt 1 (world 2):
    the highest rank records itself dead and dies abruptly. Attempt 2 (world
    1): the survivor records the host's RETURN and exits preempted. Attempt
    3 must therefore come back at world 2; rank 0 writes the final marker."""
    script = tmp_path / "fake_grow_job.py"
    script.write_text(
        "import json, os, sys, time\n"
        "args = sys.argv[1:]\n"
        "ckdir = args[args.index('--checkpoint-dir') + 1]\n"
        "os.makedirs(ckdir, exist_ok=True)\n"
        "rank = int(os.environ.get('PROCESS_ID', '0'))\n"
        "world = int(os.environ.get('NUM_PROCESSES', '1'))\n"
        "returned = os.path.exists(os.path.join(ckdir, 'returned.txt'))\n"
        "if world > 1 and not returned:\n"  # attempt 1: lose the last host
        "    if rank == world - 1:\n"
        "        with open(os.path.join(ckdir, 'dead_hosts.jsonl'), 'a') as fh:\n"
        "            fh.write(json.dumps({'host': rank, 'world': world}) + '\\n')\n"
        "        os._exit(76)\n"
        "    time.sleep(30)\n"
        "    sys.exit(1)\n"
        "if world == 1:\n"  # attempt 2: the lost host came back repaired
        "    with open(os.path.join(ckdir, 'returned.txt'), 'w') as fh:\n"
        "        fh.write('1')\n"
        "    with open(os.path.join(ckdir, 'returned_hosts.jsonl'), 'a') as fh:\n"
        "        fh.write(json.dumps({'host': 1, 'reason': 'repaired'}) + '\\n')\n"
        "    sys.exit(75)\n"
        "with open(os.path.join(ckdir, f'final.r{rank}.txt'), 'w') as fh:\n"
        "    fh.write(str(world) + '|' + ' '.join(args))\n"
        "sys.exit(0)\n")
    return script


def test_supervisor_grows_world_on_host_return(tmp_path):
    script = _write_grow_script(tmp_path)
    res, ckdir = _run_launch(tmp_path, script, "--elastic", "1")
    assert res.returncode == 0, res.stderr
    assert "elastic — host(s) [1] lost, relaunching at world size 1" \
        in res.stderr, res.stderr
    assert "elastic — host(s) [1] returned, relaunching at world size 2" \
        in res.stderr, res.stderr
    world, argv = (ckdir / "final.r0.txt").read_text().split("|", 1)
    assert world == "2"  # grew back to the launch-time size
    assert "--resume auto" in argv
    assert (ckdir / "final.r1.txt").exists()  # the returned host ran again


def test_supervisor_gives_up_below_elastic_min(tmp_path):
    script = _write_elastic_script(tmp_path)
    res, ckdir = _run_launch(tmp_path, script, "--elastic", "2")
    assert res.returncode == 76, res.stderr
    assert "elastic give-up" in res.stderr, res.stderr
    assert not (ckdir / "resumed.txt").exists()


def test_elastic_requires_restart_policy(tmp_path):
    res = subprocess.run(
        [sys.executable, "launch.py", "--nprocs", "1", "--elastic", "1",
         "--", "whatever.py"],
        cwd=REPO, capture_output=True, text=True, timeout=60)
    assert res.returncode == 2  # argparse error
    assert "--elastic needs a restart policy" in res.stderr


def test_supervisor_coordinator_port_probe(tmp_path):
    script = tmp_path / "port_echo.py"
    script.write_text(
        "import os, sys\n"
        "open(sys.argv[1], 'w').write(os.environ['MASTER_PORT'])\n"
        "sys.exit(0)\n")
    marker = tmp_path / "port.txt"
    with socket.socket() as held:
        held.bind(("", 0))
        held.listen(1)
        taken = held.getsockname()[1]
        res = subprocess.run(
            [sys.executable, "launch.py", "--nprocs", "1",
             "--coordinator-port", str(taken), "--log-dir", str(tmp_path),
             "--", str(script), str(marker)],
            cwd=REPO, capture_output=True, text=True, timeout=60)
    assert res.returncode == 0, res.stderr
    assert f"coordinator port {taken} is not bindable" in res.stderr
    assert marker.read_text() != str(taken)


def _write_preempt_script(tmp_path):
    """Fake gang member that is preempted on every attempt — exercises the
    supervisor's backoff/budget ledger with no elastic machinery in play."""
    script = tmp_path / "fake_preempt_job.py"
    script.write_text("import sys\nsys.exit(75)\n")
    return script


def test_supervisor_backoff_doubles_until_budget_exhausted(tmp_path):
    script = _write_preempt_script(tmp_path)
    res, _ = _run_launch(tmp_path, script, "--restart-policy", "on-preempt",
                         "--restart-backoff", "0.2")
    assert res.returncode == 75, res.stderr
    err = res.stderr
    assert "restart 1/2 with --resume auto in 0.2s" in err, err
    assert "restart 2/2 with --resume auto in 0.4s" in err, err  # doubled
    assert "restart budget exhausted (2); last exit code 75" in err, err
    assert err.count("-> restart") == 2  # budget, not one-more-than-budget


def _write_repeat_kill_script(tmp_path):
    """The SAME host dies abruptly on every attempt — a genuinely bad node,
    not a transient preemption. The supervisor must shrink exactly once
    (absolute dead-host accounting: the second record of host 1 is not a
    NEW loss) and then burn the restart budget with doubling backoff,
    rather than shrinking again or restarting forever."""
    script = tmp_path / "fake_repeat_kill_job.py"
    script.write_text(
        "import json, os, sys\n"
        "args = sys.argv[1:]\n"
        "ckdir = args[args.index('--checkpoint-dir') + 1]\n"
        "os.makedirs(ckdir, exist_ok=True)\n"
        "rank = int(os.environ.get('PROCESS_ID', '0'))\n"
        "world = int(os.environ.get('NUM_PROCESSES', '1'))\n"
        "if rank == 0:\n"
        "    with open(os.path.join(ckdir, 'dead_hosts.jsonl'), 'a') as fh:\n"
        "        fh.write(json.dumps({'host': 1, 'world': world}) + '\\n')\n"
        "os._exit(76)\n")
    return script


def test_supervisor_repeated_same_host_loss_exhausts_budget(tmp_path):
    script = _write_repeat_kill_script(tmp_path)
    res, ckdir = _run_launch(tmp_path, script, "--elastic", "1",
                             "--restart-backoff", "0.2")
    assert res.returncode == 76, res.stderr
    err = res.stderr
    # One shrink for the first loss; re-recording the same host is not news.
    assert err.count("relaunching at world size 1") == 1, err
    assert "host(s) [1] lost" in err, err
    assert "restart 1/2 with --resume auto in 0.2s" in err, err
    assert "restart 2/2 with --resume auto in 0.4s" in err, err
    assert "restart budget exhausted (2); last exit code 76" in err, err
    # Every attempt recorded the host: the ledger holds three records but
    # only ever one effectively-dead host.
    recs = [json.loads(line) for line in
            (ckdir / "dead_hosts.jsonl").read_text().splitlines()]
    assert len(recs) == 3 and {r["host"] for r in recs} == {1}
    assert elastic.effective_dead_hosts(str(ckdir)) == {1}


# ---------------------------------------------------------------------------
# goodput coverage gate (benchmarks/check_regression.py --goodput)
# ---------------------------------------------------------------------------


def _check_regression(*argv):
    spec = importlib.util.spec_from_file_location(
        "check_regression_under_test",
        os.path.join(REPO, "benchmarks", "check_regression.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod.main(list(argv))


def test_goodput_gate_accepts_merged_multi_attempt(tmp_path, capsys):
    path = tmp_path / "goodput.json"
    path.write_text(json.dumps({
        "coverage": 0.97, "wall_s": 12.0, "attempts": 2,
        "categories_s": {"step": 10.0, "restart": 1.5}}))
    assert _check_regression("--goodput", str(path)) == 0
    out = capsys.readouterr().out
    assert "OK goodput" in out and "2 attempt(s)" in out


def test_goodput_gate_fails_below_coverage_floor(tmp_path, capsys):
    path = tmp_path / "goodput.json"
    path.write_text(json.dumps({"coverage": 0.5, "wall_s": 12.0}))
    assert _check_regression("--goodput", str(path)) == 1
    assert "REGRESSION goodput" in capsys.readouterr().out
    path.write_text("{not json")
    assert _check_regression("--goodput", str(path)) == 1


def test_clear_stale_run_id_removes_torn_keeps_healthy(tmp_path, capsys):
    launch = _launch_module()
    d = str(tmp_path)
    path = os.path.join(d, "run_id.json")

    launch.clear_stale_run_id(None)  # no checkpoint dir: no-op
    launch.clear_stale_run_id(d)  # no file yet: no-op

    # A healthy survivor is the shared identity — never cleared.
    with open(path, "w") as fh:
        json.dump({"run_id": "r-abc", "host": "h0"}, fh)
    launch.clear_stale_run_id(d)
    assert json.load(open(path))["run_id"] == "r-abc"
    assert capsys.readouterr().err == ""

    # A torn file (attempt killed mid-write) is cleared LOUDLY, so the
    # relaunch's rank 0 re-establishes identity instead of poll-reading
    # its own wreck to the deadline on every restart.
    with open(path, "w") as fh:
        fh.write('{"run_id": "r-kil')
    launch.clear_stale_run_id(d)
    assert not os.path.exists(path)
    assert "torn" in capsys.readouterr().err

    # Valid JSON missing the key is just as unusable.
    with open(path, "w") as fh:
        json.dump({"host": "h0"}, fh)
    launch.clear_stale_run_id(d)
    assert not os.path.exists(path)
