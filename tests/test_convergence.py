"""Convergence artifact (VERDICT r3 missing #1; SURVEY.md §4.4).

The reference's implicit acceptance test is "ResNet converges to known
accuracy". Two layers here:

- a fast test validating the committed CONVERGENCE.json artifact (produced
  by ``benchmarks/convergence.py``, re-runnable anywhere) so the claim is
  load-bearing in CI;
- a marked-slow test that actually re-trains to the threshold on the
  deterministic synthetic task (the CIFAR-10 preset's fallback dataset),
  catching optimizer/model/data regressions end to end.
"""

import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
ARTIFACT = os.path.join(REPO, "CONVERGENCE.json")


def test_convergence_artifact_meets_threshold():
    """r5 hardened contract (VERDICT r4 weak #4): >=5 curve points over a
    full horizon, augmented training, a genuinely-disjoint held-out split,
    and a bounded train/eval generalization gap — all stated a-priori in
    the artifact and asserted here."""
    with open(ARTIFACT) as f:
        d = json.load(f)
    assert d["ok"] is True
    assert d["threshold"] >= 0.9
    assert d["final_acc_top1"] >= d["threshold"], d["curve"]
    assert d["reached_at_epoch"] is not None
    assert len(d["curve"]) >= 5, "curve must cover a real horizon"
    assert "augmented train" in d["task"] and "DISJOINT" in d["task"]
    assert abs(d["generalization_gap"]) <= d["max_gap"] <= 0.10, d
    accs = [r["acc_top1"] for r in d["curve"]]
    assert accs[-1] == max(accs) or accs[-1] >= d["threshold"], (
        "accuracy curve should end converged", accs)
    assert d["curve"][-1]["loss"] < d["curve"][0]["loss"]
    assert all("gap" in r for r in d["curve"])


@pytest.mark.slow
def test_convergence_rerun_reaches_threshold(tmp_path):
    """Re-train from scratch to >=85% held-out accuracy under augmentation
    with a disjoint eval stream (ResNet-18, the reference dev config's
    synthetic task) in a CI-budget horizon — catches optimizer/model/data
    regressions end to end. The full-horizon artifact (threshold 0.9,
    10 epochs) is produced by benchmarks/convergence.py defaults."""
    out = tmp_path / "conv.json"
    res = subprocess.run(
        [sys.executable, os.path.join(REPO, "benchmarks", "convergence.py"),
         "--epochs", "5", "--steps-per-epoch", "25", "--batch-size", "128",
         "--lr", "0.05", "--threshold", "0.85", "--max-gap", "0.15",
         "--out", str(out)],
        capture_output=True, text=True, timeout=3600, cwd=REPO)
    assert res.returncode == 0, res.stderr[-2000:]
    d = json.loads(out.read_text())
    assert d["ok"] and d["final_acc_top1"] >= 0.85, d["curve"]
