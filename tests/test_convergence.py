"""Convergence artifact (VERDICT r3 missing #1; SURVEY.md §4.4).

The reference's implicit acceptance test is "ResNet converges to known
accuracy". Two layers here:

- a fast test validating the committed CONVERGENCE.json artifact (produced
  by ``benchmarks/convergence.py``, re-runnable anywhere) so the claim is
  load-bearing in CI;
- a marked-slow test that actually re-trains to the threshold on the
  deterministic synthetic task (the CIFAR-10 preset's fallback dataset),
  catching optimizer/model/data regressions end to end.
"""

import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
ARTIFACT = os.path.join(REPO, "CONVERGENCE.json")
ARTIFACT_LM = os.path.join(REPO, "CONVERGENCE_LM.json")


def test_convergence_artifact_meets_threshold():
    """r5 hardened contract (VERDICT r4 weak #4): >=5 curve points over a
    full horizon, augmented training, a genuinely-disjoint held-out split,
    and a bounded train/eval generalization gap — all stated a-priori in
    the artifact and asserted here."""
    with open(ARTIFACT) as f:
        d = json.load(f)
    assert d["ok"] is True
    assert d["threshold"] >= 0.9
    assert d["final_acc_top1"] >= d["threshold"], d["curve"]
    assert d["reached_at_epoch"] is not None
    assert len(d["curve"]) >= 5, "curve must cover a real horizon"
    assert "augmented train" in d["task"] and "DISJOINT" in d["task"]
    assert abs(d["generalization_gap"]) <= d["max_gap"] <= 0.10, d
    accs = [r["acc_top1"] for r in d["curve"]]
    assert accs[-1] == max(accs) or accs[-1] >= d["threshold"], (
        "accuracy curve should end converged", accs)
    assert d["curve"][-1]["loss"] < d["curve"][0]["loss"]
    assert all("gap" in r for r in d["curve"])


def test_lm_convergence_artifact_sits_on_entropy_floor():
    """r17 LM leg: the synthetic token stream is i.i.d. uniform, so the
    optimal loss is exactly ln(vocab) — the artifact's final eval loss
    must land inside [floor - eps, floor + margin]. The LOWER bound is
    the interesting half: loss below the floor on uniform data is only
    possible via target leakage (broken causal mask / shifted targets),
    the bug class the EP token reshuffle could reintroduce."""
    import math
    with open(ARTIFACT_LM) as f:
        d = json.load(f)
    assert d["ok"] is True
    floor = math.log(d["vocab_size"])
    assert abs(d["entropy_floor_nats"] - floor) < 1e-3
    assert d["floor_eps"] <= 0.01 and d["floor_margin"] <= 0.10, (
        "gate bounds must stay tight", d)
    assert floor - d["floor_eps"] <= d["final_loss"] <= \
        floor + d["floor_margin"], d["curve"]
    assert len(d["curve"]) >= 3, "curve must cover a real horizon"
    losses = [r["loss"] for r in d["curve"]]
    assert losses[-1] <= losses[0] + 1e-3, ("loss must not diverge", losses)
    assert all(l >= floor - d["floor_eps"] for l in losses), (
        "no epoch may dip below the entropy floor", losses)
    assert "leakage" in d["task"] and "uniform" in d["task"]


@pytest.mark.slow
def test_lm_convergence_rerun_holds_entropy_floor(tmp_path):
    """Re-train llama_tiny on the uniform token stream with a reduced
    budget and assert the two-sided floor gate end to end."""
    out = tmp_path / "conv_lm.json"
    res = subprocess.run(
        [sys.executable, os.path.join(REPO, "benchmarks", "convergence.py"),
         "--task", "lm", "--epochs", "3", "--steps-per-epoch", "30",
         "--floor-margin", "0.10", "--out", str(out)],
        capture_output=True, text=True, timeout=1800, cwd=REPO)
    assert res.returncode == 0, res.stderr[-2000:]
    d = json.loads(out.read_text())
    assert d["ok"], d["curve"]


@pytest.mark.slow
def test_convergence_rerun_reaches_threshold(tmp_path):
    """Re-train from scratch to >=85% held-out accuracy under augmentation
    with a disjoint eval stream (ResNet-18, the reference dev config's
    synthetic task) in a CI-budget horizon — catches optimizer/model/data
    regressions end to end. The full-horizon artifact (threshold 0.9,
    10 epochs) is produced by benchmarks/convergence.py defaults."""
    out = tmp_path / "conv.json"
    res = subprocess.run(
        [sys.executable, os.path.join(REPO, "benchmarks", "convergence.py"),
         "--epochs", "5", "--steps-per-epoch", "25", "--batch-size", "128",
         "--lr", "0.05", "--threshold", "0.85", "--max-gap", "0.15",
         "--out", str(out)],
        capture_output=True, text=True, timeout=3600, cwd=REPO)
    assert res.returncode == 0, res.stderr[-2000:]
    d = json.loads(out.read_text())
    assert d["ok"] and d["final_acc_top1"] >= 0.85, d["curve"]
