"""Convergence artifact (VERDICT r3 missing #1; SURVEY.md §4.4).

The reference's implicit acceptance test is "ResNet converges to known
accuracy". Two layers here:

- a fast test validating the committed CONVERGENCE.json artifact (produced
  by ``benchmarks/convergence.py``, re-runnable anywhere) so the claim is
  load-bearing in CI;
- a marked-slow test that actually re-trains to the threshold on the
  deterministic synthetic task (the CIFAR-10 preset's fallback dataset),
  catching optimizer/model/data regressions end to end.
"""

import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
ARTIFACT = os.path.join(REPO, "CONVERGENCE.json")


def test_convergence_artifact_meets_threshold():
    with open(ARTIFACT) as f:
        d = json.load(f)
    assert d["ok"] is True
    assert d["threshold"] >= 0.9
    assert d["final_acc_top1"] >= d["threshold"], d["curve"]
    assert d["reached_at_epoch"] is not None
    accs = [r["acc_top1"] for r in d["curve"]]
    assert accs == sorted(accs) or accs[-1] == max(accs), (
        "accuracy curve should end at its max for a converged run", accs)
    assert d["curve"][-1]["loss"] < d["curve"][0]["loss"]


@pytest.mark.slow
def test_convergence_rerun_reaches_threshold(tmp_path):
    """Re-train from scratch to >=90% held-out accuracy (ResNet-18, the
    reference dev config, on the deterministic synthetic 10-class task).
    ~10-15 min on the CI host — the longest-horizon training test."""
    out = tmp_path / "conv.json"
    res = subprocess.run(
        [sys.executable, os.path.join(REPO, "benchmarks", "convergence.py"),
         "--epochs", "4", "--steps-per-epoch", "25", "--batch-size", "128",
         "--lr", "0.05", "--threshold", "0.9", "--out", str(out)],
        capture_output=True, text=True, timeout=3000, cwd=REPO)
    assert res.returncode == 0, res.stderr[-2000:]
    d = json.loads(out.read_text())
    assert d["ok"] and d["final_acc_top1"] >= 0.9, d["curve"]
