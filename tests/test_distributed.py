"""Multi-process tests without a cluster (SURVEY.md §4.3) + fault injection.

These spawn real OS processes through launch.py: the actual
``jax.distributed.initialize`` rendezvous, per-host data sharding, and the
launcher's failure propagation — the behaviors fake-device tests can't see.
"""

import os
import subprocess
import sys
import textwrap
import time

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run_launch(nprocs, script_args, timeout=240, cpu_devices=2):
    cmd = [sys.executable, os.path.join(REPO, "launch.py"),
           "--nprocs", str(nprocs), "--cpu-devices", str(cpu_devices),
           "--", *script_args]
    return subprocess.run(cmd, capture_output=True, text=True, timeout=timeout,
                          cwd=REPO)


@pytest.mark.slow
def test_two_process_training_world(tmp_path):
    """2 procs x 2 fake devices -> one 4-device world; trains + checkpoints."""
    res = _run_launch(2, [
        "main.py", "--distributed", "--config", "resnet18_cifar10",
        "--epochs", "1", "--steps-per-epoch", "2", "--batch-size", "16",
        "--workers", "0", "--log-every", "2",
        "--checkpoint-dir", str(tmp_path / "ck"),
    ])
    assert res.returncode == 0, res.stdout[-2000:] + res.stderr[-2000:]
    assert "epoch 0" in res.stdout
    # the world really formed: per-chip rate must be rate/4, printed as such
    committed = [d for d in os.listdir(tmp_path / "ck") if d.startswith("step_")]
    assert committed, "no checkpoint written by the 2-process run"


def test_failed_rank_tears_down_launcher(tmp_path):
    """A dead rank must fail the whole job quickly (no hang) — the
    torchrun-style contract; recovery is restart-from-checkpoint."""
    script = tmp_path / "failing_rank.py"
    script.write_text(textwrap.dedent("""
        import os, sys, time
        if os.environ.get("PROCESS_ID") == "1":
            sys.exit(3)
        time.sleep(120)
    """))
    t0 = time.time()
    res = _run_launch(2, [str(script)], timeout=60)
    assert res.returncode == 3
    assert time.time() - t0 < 30, "launcher did not tear down promptly"


@pytest.mark.slow
def test_restart_and_resume_after_rank_kill(tmp_path):
    """The full TPU recovery story (SURVEY.md §5): a host process dies
    mid-epoch -> the gang-scheduled job fails fast -> a relaunch with
    ``--resume auto`` continues from the last committed checkpoint with no
    epoch replay."""
    common = [
        "main.py", "--distributed", "--config", "resnet18_cifar10",
        "--model", "resnet_micro",
        "--epochs", "2", "--steps-per-epoch", "3", "--batch-size", "16",
        "--workers", "0", "--log-every", "1",
        "--checkpoint-dir", str(tmp_path / "ck"),
    ]
    # Rank 1 is hard-killed (os._exit) at global step 4 — one step into
    # epoch 1, after epoch 0's checkpoint (step 3) committed.
    t0 = time.time()
    res = _run_launch(2, common + ["--fault-inject", "1:4"], timeout=240)
    assert res.returncode == 57, res.stdout[-2000:] + res.stderr[-2000:]
    assert time.time() - t0 < 180, "job did not fail fast after rank death"
    committed = [d for d in os.listdir(tmp_path / "ck")
                 if d.startswith("step_")
                 and os.path.exists(tmp_path / "ck" / d / "COMMIT")]
    assert committed == ["step_00000003"], committed

    # Relaunch with --resume auto: must continue at epoch 1 (no replay of
    # epoch 0) and finish the remaining steps.
    res2 = _run_launch(2, common + ["--resume", "auto"], timeout=240)
    assert res2.returncode == 0, res2.stdout[-2000:] + res2.stderr[-2000:]
    assert "resumed from step 3 (epoch 1)" in res2.stdout
    assert "epoch 0 step" not in res2.stdout  # no epoch replay
    assert "epoch 1 step 3/3" in res2.stdout
    steps = [d for d in os.listdir(tmp_path / "ck") if d.startswith("step_")
             and os.path.exists(tmp_path / "ck" / d / "COMMIT")]
    assert "step_00000006" in steps  # epoch 1's checkpoint committed


def test_launcher_requires_command():
    res = subprocess.run([sys.executable, os.path.join(REPO, "launch.py"),
                          "--nprocs", "2"], capture_output=True, text=True,
                         cwd=REPO, timeout=60)
    assert res.returncode != 0
    assert "no command" in res.stderr
